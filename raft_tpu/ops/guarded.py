"""Guarded kernel dispatch: Pallas engines fall back to their XLA-path
equivalents on compile/execution failure — and probe their way back.

Every custom-kernel engine in this library has an exact composed-XLA
equivalent (that is what the parity tests assert; gated sites today:
``select_k`` KPASS, the ivf_flat/ivf_pq scans, ``brute_force.fused``,
``cagra.graph_expand`` → the XLA gather hop, ``cagra.fused_search`` →
the per-hop edge/gather chain, ``cagra.nn_descent`` → the
exact/ivf_pq graph builders, and the sharded merge's
``sharded.ring_topk`` → the allgather + ``knn_merge_parts`` program),
so a Pallas failure — a Mosaic lowering bug on a new chip generation, a
scoped-VMEM compile-OOM on an unrehearsed shape, a driver hiccup —
should cost one log line and a slower call, never the request or the
process. The reference hard-fails on kernel errors (RAFT_CUDA_TRY); a
serving stack cannot.

``guarded_call(site, primary, fallback)`` is the single chokepoint.
Since ISSUE 10 each site is a **circuit breaker**, not a sticky
demotion — a transient driver fault must not cost the kernel path for
the life of the process (docs/robustness.md):

* **closed** (healthy): fault probes fire first, then the kernel path
  runs; a real failure transitions to *open*.
* **open** (contained): every call serves the fallback. After the
  probation window (``RAFT_TPU_GUARD_PROBE_AFTER_S``, default 30 s;
  ``<= 0`` restores the pre-ISSUE-10 sticky demotion) the breaker
  half-opens.
* **half-open**: exactly ONE call is let through the kernel path as a
  probe (concurrent callers keep the fallback). Probe success →
  *closed* (the demotion verdict is forgotten, in-process and on disk);
  probe failure → *open* again with the backoff doubled, capped at
  ``RAFT_TPU_GUARD_MAX_BACKOFF_S`` (default 600 s).

Fault-injection semantics (:mod:`raft_tpu.core.faults`):

* ``kernel_compile`` keeps the PR 1 per-call contract: the fallback
  serves THIS call only and the breaker does not move — a simulation
  must not change later dispatch decisions.
* ``kernel_fault`` simulates a *persistent* kernel failure: it drives
  the breaker (open → probe → re-open while armed, re-close once
  cleared) so the whole recovery arc is deterministically drillable.
  Injected opens are never persisted to the cross-process autotune
  cache, and the probe machinery guarantees they never outlive the
  armed fault — an injected fault can never open a breaker permanently.
* a probe call treats ANY injected fault as a probe failure (the probe
  asks "does the kernel path work *now*", and an armed simulation says
  no).

A real failure logs once per site, records the demotion in the autotune
cache (in-process always; persisted to the cross-process cache only
when ``RAFT_TPU_GUARD_PERSIST=1``, so a transient failure cannot poison
future processes by default — a persisted entry seeds the next
process's breaker *open*, so it too probes and recovers), and serves
the fallback. Transitions are flight-recorded (``breaker_open`` /
``breaker_probe`` / ``breaker_close``; the site's first open this
process also keeps the PR 6 ``guarded_demotion`` event) and gauged
per site (``guarded.breaker.<site>``: 0 closed / 1 half-open / 2 open).

All breaker state lives behind one lock: serving threads mutate it
mid-dispatch while background ``SnapshotWriter`` threads read
:func:`breaker_snapshot` — the bare-module-dict race the PR 8 SLOEngine
fix already covered for SLO state.

Trace caveat: when the guarded call happens inside an outer ``jit``
trace, the kernel's own compilation may be deferred to the outer
executable's compile, outside this try block — the guard then catches
trace-time failures and armed faults, not late compile errors. Eager
dispatch (the serving pattern) is fully covered.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Dict, Optional

import jax

from ..core import faults, logging as rlog
from ..core.deadline import DeadlineExceeded
from ..core.interruptible import InterruptedException
from ..utils import env_float

__all__ = ["guarded_call", "demoted_sites", "breaker_snapshot", "reset",
           "BreakerPolicy", "POLICIES", "DEFAULT_POLICY"]

# breaker state -> reported gauge value (guarded.breaker.<site>)
_STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


@dataclasses.dataclass(frozen=True)
class BreakerPolicy:
    """Per-site recovery policy. ``None`` fields defer to the env knobs
    (``RAFT_TPU_GUARD_PROBE_AFTER_S`` / ``RAFT_TPU_GUARD_MAX_BACKOFF_S``)
    so one operator knob retunes the whole fleet while a site that needs
    a different cadence can pin its own."""

    probe_after_s: Optional[float] = None
    max_backoff_s: Optional[float] = None


DEFAULT_POLICY = BreakerPolicy()

# every guarded_call site ships a breaker policy; the drift-guard test
# (tests/test_quality.py) fails the suite when a new site is added
# without one — a gated kernel without a rehearsed demote→probe→recover
# arc is exactly the untested failure path this module exists to close
POLICIES: Dict[str, BreakerPolicy] = {
    "select_k.kpass": DEFAULT_POLICY,
    "ivf_flat.scan": DEFAULT_POLICY,
    "ivf_pq.scan": DEFAULT_POLICY,
    "brute_force.fused": DEFAULT_POLICY,
    "cagra.graph_expand": DEFAULT_POLICY,
    # the PQ edge-store rung's expand (in-kernel LUT decode) — a
    # separate program from the dense expand, so its breaker must not
    # couple the two rungs' demotions
    "cagra.pq_expand": DEFAULT_POLICY,
    # host-streamed cold IVF lists (neighbors/host_stream.py): falls
    # back to XLA scoring of the same streamed block — correctness never
    # depends on the scan kernel accepting a streamed chunk
    "ivf.host_stream": DEFAULT_POLICY,
    # the one-dispatch traversal megakernel (ops/cagra_fused.py): falls
    # back to the per-hop edge engine, which carries its own breaker
    # (cagra.graph_expand) onto the XLA gather path
    "cagra.fused_search": DEFAULT_POLICY,
    "cagra.nn_descent": DEFAULT_POLICY,
    # the ring merge compiles per mesh shape; probing it re-runs a whole
    # shard_map program, so keep the default (not a tighter) cadence
    "sharded.ring_topk": DEFAULT_POLICY,
    # the mutable-tier background merge (neighbors/mutable.py): not a
    # kernel site — the breaker keeps a repeatedly-failing merge from
    # hot-looping the maintenance tick, and a probe retries one merge
    "mutable.merge": DEFAULT_POLICY,
    # the soak harness's hot-tenant serving wrapper (soak/harness.py):
    # primary and fallback are the same exact search, so a kernel_fault
    # drill exercises the full breaker arc (and the heal.mttr verdict)
    # with zero recall impact
    "soak.serve": DEFAULT_POLICY,
    # the selectivity crossover (ops/filter_policy.py): exact brute
    # force over the compacted filter survivors; falls back to the
    # family's own widened-scan search (bit-safe — same contract, more
    # HBM traffic), so a gather/rebuild failure costs latency only
    "filter.survivor_brute": DEFAULT_POLICY,
}


@dataclasses.dataclass
class _Breaker:
    """One site's circuit-breaker state (mutated only under _lock)."""

    state: str = "closed"           # closed | open | half_open
    reason: str = ""
    opened_at: float = 0.0
    backoff_s: float = 0.0
    next_probe_at: float = 0.0
    opens: int = 0                  # open transitions this process
    probes: int = 0                 # probe attempts this process
    closes: int = 0                 # probe successes this process
    injected: bool = False          # last open caused by an injected fault
    probing: bool = False           # a probe call is in flight


_lock = threading.Lock()
_BREAKERS: Dict[str, _Breaker] = {}
_LOGGED: set = set()                # sites whose first open was logged

# injectable for deterministic recovery drills (tests monkeypatch)
_clock = time.monotonic


def _probe_after_s(site: str) -> float:
    p = POLICIES.get(site, DEFAULT_POLICY)
    if p.probe_after_s is not None:
        return float(p.probe_after_s)
    return env_float("RAFT_TPU_GUARD_PROBE_AFTER_S", 30.0)


def _max_backoff_s(site: str) -> float:
    p = POLICIES.get(site, DEFAULT_POLICY)
    if p.max_backoff_s is not None:
        return float(p.max_backoff_s)
    return env_float("RAFT_TPU_GUARD_MAX_BACKOFF_S", 600.0)


def _guard_key(site: str) -> str:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform).replace(" ", "_")
    return f"{dev.platform}:{kind}:guard:{site}"


def _set_state_gauge(site: str, state: str) -> None:
    try:
        from ..serve import metrics as serve_metrics

        serve_metrics.gauge(f"guarded.breaker.{site}").set(
            _STATE_VALUE[state])
    except Exception:  # noqa: BLE001 - telemetry must not break containment
        pass


def _emit(kind: str, site: str, **details) -> None:
    try:
        from ..core import events as core_events

        core_events.record(kind, site, **details)
    except Exception:  # noqa: BLE001 - telemetry must not break containment
        pass


def _admit(site: str):
    """Dispatch decision for one call: ``"kernel"`` (closed — run the
    kernel path), ``"fallback"`` (open/another probe in flight), or
    ``"probe"`` (this call IS the half-open probe). Seeds a breaker in
    the *open* state from a persisted ``guard:`` autotune verdict, so a
    prior process's demotion still probes and recovers here."""
    from . import autotune

    probe_info = None
    with _lock:
        b = _BREAKERS.get(site)
    if b is None:
        # the persisted-verdict lookup can hit the disk cache on first
        # use — keep it OUTSIDE the lock so one cold lookup cannot stall
        # every concurrent guarded dispatch on every site
        if autotune.lookup(_guard_key(site)) != "fallback":
            return "kernel"
        backoff = _probe_after_s(site)
    with _lock:
        if b is None:
            b = _BREAKERS.get(site)   # re-check: another thread may have
            if b is None:             # seeded or opened it meanwhile
                now = _clock()
                b = _Breaker(state="open",
                             reason="persisted demotion (autotune cache)",
                             opened_at=now, backoff_s=backoff,
                             next_probe_at=now + backoff, opens=1)
                _BREAKERS[site] = b
        if b.state == "closed":
            return "kernel"
        probe_after = _probe_after_s(site)
        now = _clock()
        if (b.state == "open" and probe_after > 0 and not b.probing
                and now >= b.next_probe_at):
            b.state = "half_open"
            b.probing = True
            b.probes += 1
            probe_info = {"attempt": b.probes,
                          "open_for_s": round(now - b.opened_at, 3)}
        elif b.state == "half_open" and not b.probing:
            # defensive: a half-open breaker with no probe in flight
            # re-arms as open rather than stranding half-open
            b.state = "open"
    if probe_info is None:
        return "fallback"
    _set_state_gauge(site, "half_open")
    _emit("breaker_probe", site, **probe_info)
    try:
        from ..serve import metrics as serve_metrics

        serve_metrics.counter(f"guarded.breaker.probes.{site}").inc()
    except Exception:  # noqa: BLE001
        pass
    return "probe"


def _on_failure(site: str, err: Exception, injected: bool) -> None:
    """closed → open, or half_open → open with the backoff doubled."""
    from . import autotune

    with _lock:
        b = _BREAKERS.setdefault(site, _Breaker())
        now = _clock()
        was_closed = b.state == "closed"
        if b.state == "half_open":
            b.backoff_s = min(b.backoff_s * 2.0, _max_backoff_s(site))
        else:
            b.backoff_s = _probe_after_s(site)
        b.state = "open"
        b.probing = False
        b.reason = f"{type(err).__name__}: {err}"
        b.opened_at = now
        b.next_probe_at = now + b.backoff_s
        b.opens += 1
        # the injected label tracks the breaker's ORIGINAL open cause: a
        # probe of a real-failure-opened breaker failing on an armed
        # simulation must neither relabel the outage as injected nor
        # (below) drop the real demotion's persisted verdict — while a
        # REAL failure always claims the label (and persistence)
        b.injected = injected if (was_closed or not injected) else b.injected
        injected = b.injected
        reason, backoff, opens = b.reason, b.backoff_s, b.opens
        first = site not in _LOGGED
        _LOGGED.add(site)
    if first:
        rlog.log_warn(
            "guarded %s: kernel path failed (%s); breaker OPEN — serving "
            "the XLA fallback, probing the kernel path again in %.0fs",
            site, reason, backoff)
    _set_state_gauge(site, "open")
    try:
        from ..serve import metrics as serve_metrics

        # demotion counters keep their PR 2/8 names: the SLO engine's
        # demotion-rate target and the drift guard read them
        serve_metrics.counter("guarded.demotions").inc()
        serve_metrics.counter(f"guarded.demotions.{site}").inc()
    except Exception:  # noqa: BLE001 - telemetry must not break containment
        pass
    _emit("breaker_open", site, error=reason, backoff_s=round(backoff, 3),
          opens=opens, injected=injected)
    if first:
        # PR 6 contract: the site's first demotion this process is a
        # guarded_demotion event (dashboards and the drift guard key on it)
        _emit("guarded_demotion", site, error=reason)
    # in-process record always (trace-time lookups see the demotion);
    # cross-process persistence only for REAL failures under the opt-in —
    # an injected fault must never poison another process's dispatch
    autotune.record(
        _guard_key(site), "fallback",
        persist=(not injected)
        and os.environ.get("RAFT_TPU_GUARD_PERSIST") == "1")


def _on_probe_success(site: str) -> None:
    """half_open → closed: the kernel path is healthy again."""
    from . import autotune

    with _lock:
        b = _BREAKERS.get(site)
        if b is None:
            return
        down_s = round(_clock() - b.opened_at, 3)
        probes = b.probes
        b.state = "closed"
        b.probing = False
        b.reason = ""
        b.injected = False
        b.backoff_s = 0.0
        b.closes += 1
    autotune.forget(_guard_key(site))
    _set_state_gauge(site, "closed")
    try:
        from ..serve import metrics as serve_metrics

        serve_metrics.counter(f"guarded.breaker.closes.{site}").inc()
        # MTTR verdict (docs/soak.md): open → close wall, in recovery
        # buckets (probation alone is 30s; latency buckets top at 10s)
        serve_metrics.histogram(
            f"heal.mttr.{site}",
            serve_metrics.MTTR_BUCKETS_S).observe(down_s)
    except Exception:  # noqa: BLE001
        pass
    _emit("breaker_close", site, down_s=down_s, probes=probes)
    rlog.log_warn(
        "guarded %s: probe succeeded after %.1fs; breaker CLOSED — kernel "
        "path restored", site, down_s)


def _abort_probe(site: str) -> None:
    """A probe interrupted by control flow (cancellation, deadline) is
    neither success nor failure: back to open, eligible to re-probe
    immediately."""
    with _lock:
        b = _BREAKERS.get(site)
        if b is not None and b.probing:
            b.state = "open"
            b.probing = False
    _set_state_gauge(site, "open")


def guarded_call(site: str, primary: Callable[[], object],
                 fallback: Callable[[], object]):
    """Run ``primary`` (the kernel engine) with ``fallback`` (its exact
    XLA equivalent) as the containment path, through the site's circuit
    breaker. See module docstring for the state machine and injection
    contract. Cancellation and deadline exceptions pass through — they
    are control flow, not engine failures."""
    action = _admit(site)
    if action == "fallback":
        return fallback()
    probing = action == "probe"
    try:
        faults.check("kernel_compile", site)
        faults.check("kernel_fault", site)
        faults.sleep_if(site)
        out = primary()
    except faults.InjectedFault as e:
        if probing or e.kind == "kernel_fault":
            # kernel_fault simulates a PERSISTENT failure (drives the
            # breaker); any injected fault fails a probe — but injected
            # opens are never persisted cross-process
            _on_failure(site, e, injected=True)
        # kernel_compile outside a probe: PR 1 per-call simulation —
        # serve the fallback for THIS call only, breaker untouched
        return fallback()
    except (KeyboardInterrupt, SystemExit, InterruptedException,
            DeadlineExceeded):
        if probing:
            _abort_probe(site)
        raise
    except Exception as e:  # noqa: BLE001 - any engine failure = contain
        _on_failure(site, e, injected=False)
        return fallback()
    except BaseException:   # noqa: BLE001 - e.g. CancelledError: control
        # flow, not an engine failure — but a probe must never exit with
        # the probing flag stranded (that would disable every future
        # probe: the one-way demotion this module exists to close)
        if probing:
            _abort_probe(site)
        raise
    if probing:
        _on_probe_success(site)
    return out


def demoted_sites() -> Dict[str, str]:
    """Sites currently serving the fallback (breaker open or half-open)
    and why (diagnostics). A recovered breaker no longer reports."""
    with _lock:
        return {site: b.reason or "open"
                for site, b in _BREAKERS.items() if b.state != "closed"}


def breaker_snapshot() -> Dict[str, dict]:
    """JSON-safe per-site breaker state for the ops surface
    (serve/debugz ``breakers`` section): state, open-since, probe count,
    next-probe ETA."""
    now = _clock()
    out: Dict[str, dict] = {}
    with _lock:
        for site, b in _BREAKERS.items():
            ent = {"state": b.state, "opens": b.opens, "probes": b.probes,
                   "closes": b.closes}
            if b.state != "closed":
                ent.update({
                    "reason": b.reason,
                    "injected": b.injected,
                    "open_for_s": round(max(0.0, now - b.opened_at), 3),
                    "backoff_s": round(b.backoff_s, 3),
                    "next_probe_in_s": (
                        None if _probe_after_s(site) <= 0
                        else round(max(0.0, b.next_probe_at - now), 3)),
                })
            out[site] = ent
    return out


def reset(sites=None) -> None:
    """Clear breaker state (tests / operator re-arm after a fix).

    With no argument, everything resets. With an iterable of site
    names, only those breakers re-close — the soak harness uses this to
    re-arm exactly the sites it drills without clobbering breakers the
    rest of the process may legitimately hold open."""
    from . import autotune

    with _lock:
        if sites is None:
            cleared = list(_BREAKERS)
            _BREAKERS.clear()
            _LOGGED.clear()
        else:
            cleared = [s for s in sites if s in _BREAKERS]
            for s in cleared:
                del _BREAKERS[s]
                _LOGGED.discard(s)
    for site in cleared:
        autotune.forget(_guard_key(site))
        _set_state_gauge(site, "closed")
