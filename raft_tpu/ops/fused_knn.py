"""Fused pairwise-distance + running top-k Pallas kernel.

The TPU analog of RAFT's fused brute-force path: the tiled distance GEMM
(detail/knn_brute_force.cuh:61) with the per-tile select and cross-tile
merge (matrix/detail/select_warpsort.cuh:35) collapsed into one kernel.
The distance block for each (query-tile, dataset-tile) pair is computed on
the MXU; a running k-best (value, index) buffer lives in VMEM scratch and
is updated in-place as the kernel walks the dataset tiles, so no
(m, n) distance matrix — and no full per-tile sort — ever exists.

Selection is TWO-LEVEL (the extraction economics of select_radix.cuh's
candidate-pruning pass, fused against the GEMM tile while it is still in
VMEM):

* level 1 — a VPU block-min partial reduce collapses the (tm, tn)
  distance tile to ``nc`` group minima per query row (``nc`` ≈ 2k,
  lane-aligned): one bandwidth-bound pass, O(tm·tn), instead of the
  former k-pass min-extraction's O(k·tm·tn);
* level 2 — only group minima that beat the running k-th value (the
  threshold filter) are merged into the k-best scratch, a k-pass extract
  over a (kp + nc)-wide row — O(k·(kp+nc)), independent of tile width.

A group can hold more than one of the tile's true top-k, so the reduce +
merge repeats for a bounded number of rounds (each round retires every
group's current minimum); a final exact fallback — the full-width k-pass
over whatever still beats the threshold — makes the kernel exact on any
input, including all-tied rows. Every round and the fallback are gated on
``any(remaining <= running k-th)``: in steady state (corpus scan past the
first few tiles) the gates collapse and a tile costs its GEMM plus one
block-min pass, nothing else.

Extraction breaks ties by (value, smallest global column) — exactly
``lax.top_k``'s order — so the fused engine is bit-identical in both
index set and order to the GEMM+top_k reference engine.

The corpus stays RESIDENT in HBM in its storage dtype — f32, bf16 (half
the stream traffic) or int8/uint8 (quarter traffic; int8 carries per-row
dequant scales folded into the dot) — and tiles stream HBM→VMEM through
the Pallas grid pipeline, which double-buffers the async tile copies
against the MXU work. At 1M×128 bf16 that is ~256 MB of corpus reads per
query batch: bandwidth-bound at the measured ~650 GB/s stream rate, with
the former compute+spill select cost gone from the steady state.

Masking (bitset sample filters, padded rows, shard validity) is folded
into an additive penalty row: +inf for excluded dataset rows, 0 otherwise
— one broadcast add, no per-metric special cases (all four expanded
metrics ride the same kernel; sqrt-L2 post-processes outside).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import round_up_to

__all__ = ["fused_knn"]

_INT_BIG = 2**30  # sentinel column id, larger than any real lane index

# extra block-min/merge rounds before the exact fallback: round 1 seeds
# the buffer from the group minima, round 2 catches groups that held two
# of the tile's top-k; anything rarer is the fallback's job
_ROUNDS = 2


def _compiler_params(dimension_semantics):
    """Version-compat TPU compiler params (resilience: API skew must
    degrade to the equivalent spelling, not crash the kernel path).
    Newer jax spells it ``pltpu.CompilerParams`` with a
    ``GridDimensionSemantics`` enum; 0.4.x uses ``TPUCompilerParams``
    with plain 'parallel'/'arbitrary' strings."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is not None:
        sem = getattr(pltpu, "GridDimensionSemantics", None)
        dims = (tuple(getattr(sem, s.upper()) for s in dimension_semantics)
                if sem is not None and hasattr(sem, "PARALLEL") else None)
        return cls(dimension_semantics=dims)
    return pltpu.TPUCompilerParams(
        dimension_semantics=tuple(dimension_semantics))


def _pick_tiles(dim_p: int, k: int, itemsize: int = 4) -> Tuple[int, int]:
    """(query-tile, dataset-tile) sizes under a ~12 MB VMEM working set.

    Defaults target v5e-class VMEM; override with
    ``RAFT_TPU_FUSED_TILES=tm,tn`` when sweeping other generations.
    Engine-level dispatch is where measurement lives: ops.autotune times
    this whole kernel against the matmul/scan engines per shape class
    (brute_force.tune_search), so a tile config only matters on hardware
    where the fused kernel wins that race. Shrink with dim so the
    (tm, tn) distance block plus tiles stay inside VMEM, and with k since
    the merge working set grows with kp. Byte-dtype corpora (``itemsize``
    < 4) stream wider dataset tiles: the double-buffered tile pair costs
    2·tn·dim_p·itemsize, so halving the element size funds a wider tn
    (fewer grid revisits per corpus pass) at the same VMEM budget.
    """
    import os

    env = os.environ.get("RAFT_TPU_FUSED_TILES")
    if env:
        parts = env.split(",")
        if len(parts) != 2:
            raise ValueError(
                f"RAFT_TPU_FUSED_TILES must be 'tm,tn', got {env!r}")
        tm, tn = (int(v) for v in parts)
        # snap to TPU tiling multiples (sublane 8 / lane 128)
        tm = max(8, (tm // 8) * 8)
        tn = max(128, (tn // 128) * 128)
        return tm, tn
    if dim_p <= 256:
        tm, tn = 512, 1024
    elif dim_p <= 512:
        tm, tn = 512, 512
    else:
        tm, tn = 256, 512
    if itemsize <= 2 and dim_p <= 512:
        tn *= 2
    if k > 64:
        tm = max(tm // 2, 128)
    return tm, tn


def _extract_smallest(c, ci, k: int, kp: int):
    """k smallest of rows of ``c`` with global ids ``ci`` → (tm, kp) val/id.

    Iterative min-extraction with the tie-break on (value, smallest id) —
    not smallest *position* — so the result order matches ``lax.top_k``
    over the globally-indexed row regardless of how candidates were
    concatenated. Exactly one id is retired per pass (ids are unique
    except the -1 sentinel, which only accompanies +inf slots).
    """
    tm = c.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tm, kp), 1)

    def extract(t, state):
        c, nv, ni = state
        best = jnp.min(c, axis=1, keepdims=True)
        at_min = c <= best
        bid = jnp.min(jnp.where(at_min, ci, _INT_BIG), axis=1, keepdims=True)
        at = at_min & (ci == bid)
        # rows with no remaining finite candidate: emit the -1 sentinel,
        # not a (real, excluded/duplicate) id
        bid = jnp.where(jnp.isfinite(best), bid, -1)
        nv = jnp.where(lane == t, best, nv)
        ni = jnp.where(lane == t, bid, ni)
        return jnp.where(at, jnp.inf, c), nv, ni

    state = (c, jnp.full((tm, kp), jnp.inf, jnp.float32),
             jnp.full((tm, kp), -1, jnp.int32))
    if k <= 16:
        for t in range(k):
            state = extract(t, state)
    else:
        state = jax.lax.fori_loop(0, k, extract, state)
    return state[1], state[2]


def _kernel(q_ref, d_ref, dn_ref, pen_ref, *rest, k: int, kp: int, tn: int,
            nc: int, metric: str, n_dtiles: int, precision: str,
            with_scales: bool, int4: bool = False):
    if with_scales:
        sc_ref, ov_ref, oi_ref, sv_ref, si_ref = rest
    else:
        sc_ref = None
        ov_ref, oi_ref, sv_ref, si_ref = rest
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        sv_ref[:] = jnp.full_like(sv_ref, jnp.inf)
        si_ref[:] = jnp.full_like(si_ref, -1)

    q = q_ref[:]                                   # (tm, dim_p) f32
    d = d_ref[:]                                   # (tn, dim_p) stored dtype
    tm = q.shape[0]
    if int4:
        # nibble-packed corpus (ops/quant.py split-half layout): byte j
        # holds components j (low nibble) and j+half (high). Unpacking
        # is a lane-axis shift+mask — never a minor-axis reshape — and
        # the dot splits into two half-width GEMMs against the query's
        # (low, high) column halves. HBM stream traffic: 1/8 of f32.
        from .quant import int4_nibbles

        half = d.shape[1]
        low, high = int4_nibbles(d.astype(jnp.int32))
        kw = dict(preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision(precision))
        dot = (jax.lax.dot_general(q[:, :half], low,
                                   (((1,), (1,)), ((), ())), **kw)
               + jax.lax.dot_general(q[:, half:], high,
                                     (((1,), (1,)), ((), ())), **kw))
    elif d.dtype == jnp.bfloat16:
        # bf16 corpus mode: rows stream from HBM at half the f32 traffic;
        # the product accumulates in f32 (precision knob is moot — the
        # stored operand is already bf16)
        dot = jax.lax.dot_general(q.astype(jnp.bfloat16), d,
                                  (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    elif d.dtype in (jnp.int8, jnp.uint8):
        # byte corpus mode: quarter HBM traffic; the f32 convert happens
        # in VMEM after the stream, and the math matches the GEMM
        # engine's fused-convert path bit for bit
        dot = jax.lax.dot_general(
            q, d.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision(precision))
    else:
        dot = jax.lax.dot_general(
            q, d, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision(precision))  # (tm, tn)
    if sc_ref is not None:
        dot = dot * sc_ref[:]          # int8 per-row scales: q·(s·v)=s·(q·v)
    if metric == "l2":
        qn = jnp.sum(q * q, axis=1, keepdims=True)
        dist = jnp.maximum(qn + dn_ref[:] - 2.0 * dot, 0.0)
    elif metric == "cos":                          # dn holds sqrt row norms
        qn = jnp.sqrt(jnp.sum(q * q, axis=1, keepdims=True))
        dist = 1.0 - dot / jnp.maximum(qn * dn_ref[:], 1e-30)
    else:                                          # "ip": min-select on -dot
        dist = -dot
    dist = dist + pen_ref[:]                       # +inf on masked/padded rows

    col = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 1) + j * tn

    def merge(cv, ci):
        nv, ni = _extract_smallest(
            jnp.concatenate([sv_ref[:], cv], axis=1),
            jnp.concatenate([si_ref[:], ci], axis=1), k, kp)
        sv_ref[:] = nv
        si_ref[:] = ni

    # ``<=`` (not ``<``) everywhere a threshold gates work: an element
    # EQUAL to the running k-th but with a smaller column must still
    # displace it for exact lax.top_k tie order
    thresh = sv_ref[:, k - 1 : k]                  # (tm, 1)
    tile_min = jnp.min(dist, axis=1, keepdims=True)

    @pl.when(jnp.any(tile_min <= thresh))
    def _():
        if nc >= tn:
            # tile no wider than the candidate budget: merge it directly
            merge(dist, col)
            return

        # STRIDED groups — group g holds columns {g, g+nc, g+2nc, ...} —
        # so the reduce runs over the middle axis and the lane axis stays
        # nc (≥128) wide, the layout Mosaic reduces at full VPU rate
        bw = tn // nc                              # chunks per group
        tcol = jax.lax.broadcasted_iota(jnp.int32, (tm, bw, nc), 1)
        gcol = jax.lax.broadcasted_iota(jnp.int32, (tm, nc), 1) + j * tn

        def round_body(dmask):
            """One block-min reduce + gated merge; retires each group's
            current minimum so the next round sees fresh candidates."""
            th = sv_ref[:, k - 1 : k]
            d3 = dmask.reshape(tm, bw, nc)
            gmin = jnp.min(d3, axis=1)                         # (tm, nc)
            # chunk attaining the min; smallest chunk index on ties ==
            # smallest global column within the group
            gchunk = jnp.min(
                jnp.where(d3 <= gmin[:, None, :], tcol, _INT_BIG),
                axis=1)                                        # (tm, nc)
            keep = gmin <= th

            @pl.when(jnp.any(keep))
            def _():
                merge(jnp.where(keep, gmin, jnp.inf), gchunk * nc + gcol)

            retired = (tcol == gchunk[:, None, :]) & keep[:, None, :]
            return jnp.where(retired, jnp.inf, d3).reshape(tm, tn)

        dmask = dist
        for _r in range(min(_ROUNDS, k)):
            dmask = round_body(dmask)

        # exact fallback: rows where >_ROUNDS of the tile's top-k shared a
        # group (or heavy value ties) still have pending candidates — the
        # full-width k-pass retires them. Steady state never reaches here.
        @pl.when(jnp.any(jnp.min(dmask, axis=1, keepdims=True)
                         <= sv_ref[:, k - 1 : k]))
        def _():
            tv, ti = _extract_smallest(dmask, col, k, kp)
            merge(tv, ti)

    @pl.when(j == n_dtiles - 1)
    def _():
        ov_ref[:] = sv_ref[:]
        oi_ref[:] = si_ref[:]


@functools.partial(jax.jit,
                   static_argnames=("k", "metric", "interpret", "precision",
                                    "tiles", "int4"))
def _fused_knn_padded(q, d, dn, pen, sc, k: int, metric: str,
                      interpret: bool, precision: str,
                      tiles: Tuple[int, int], int4: bool = False):
    m_pad, dim_p = q.shape
    n_pad = d.shape[0]
    d_w = d.shape[1]               # packed byte width (= dim_p/2 for int4)
    tm, tn = tiles
    tm = min(tm, m_pad)
    tn = min(tn, n_pad)
    kp = round_up_to(k, 128)
    # candidate budget per row after the level-1 reduce: ≥2k, lane-aligned,
    # and a divisor of tn so groups tile the row exactly
    nc = min(tn, max(128, round_up_to(2 * k, 128)))
    while tn % nc:
        nc += 128
    grid = (m_pad // tm, n_pad // tn)

    kern = functools.partial(_kernel, k=k, kp=kp, tn=tn, nc=nc,
                             metric=metric, n_dtiles=grid[1],
                             precision=precision, with_scales=sc is not None,
                             int4=int4)
    flops = 2 * m_pad * n_pad * dim_p
    row_spec = pl.BlockSpec((1, tn), lambda i, j: (0, j),
                            memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((tm, dim_p), lambda i, j: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((tn, d_w), lambda i, j: (j, 0),
                     memory_space=pltpu.VMEM),
        row_spec,
        row_spec,
    ]
    args = [q, d, dn, pen]
    if sc is not None:
        in_specs.append(row_spec)
        args.append(sc)
    vals, idxs = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, kp), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, kp), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, kp), jnp.float32),
            pltpu.VMEM((tm, kp), jnp.int32),
        ],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=int(q.size * 4 + d.size * d.dtype.itemsize
                               + dn.size * 4),
            transcendentals=0,
        ),
        interpret=interpret,
    )(*args)
    return vals[:, :k], idxs[:, :k]


def fused_knn(
    queries: jax.Array,
    dataset: jax.Array,
    k: int,
    metric: str = "l2",
    data_norms: Optional[jax.Array] = None,
    penalty: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
    precision: str = "highest",
    scales: Optional[jax.Array] = None,
    int4_dim: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """k nearest rows of ``dataset`` for each query, fused on-TPU.

    metric: "l2" (squared L2), "cos" (1 - cosine, using precomputed or
    derived row norms), "ip" (inner product; returns min-ordered -dot,
    caller negates). ``dataset`` may be stored f32, bf16 (half the HBM
    stream traffic), or int8/uint8 (quarter traffic; int8 requires
    ``scales``, the per-row dequant factors). ``data_norms``: optional
    (n,) squared L2 row norms of the *dequantized* rows (reused from the
    index for "l2"/"cos"; derived here when absent).
    ``penalty``: optional (n,) f32 additive row penalty (+inf to exclude).
    ``precision``: MXU precision for the distance GEMM — "highest"
    (3-pass bf16, ~f32-accurate; the exact-search default) or "default"
    (single-pass bf16 multiplies, ~3x the MXU throughput, distance error
    ~1e-3 relative — fine as an ANN candidate generator).
    ``int4_dim``: when set, ``dataset`` is a nibble-packed int4 corpus
    (``(n, half_p)`` int8, ops/quant.py split-half layout) for a logical
    row width of ``int4_dim``; unpacking happens in-kernel (lane-axis
    shift+mask) so the HBM stream is 1/8 of f32. ``scales`` required.
    Pre-aligned inputs (rows a tile multiple, dim a 128 multiple — see
    ``brute_force.prepare_fused``) pass through without the trace-time
    pad copy, keeping the corpus genuinely HBM-resident across calls.
    Returns (values (m, k), indices (m, k)) sorted best-first; excluded /
    out-of-range slots have value +inf and index -1.
    """
    from ..core.errors import expects

    q = jnp.asarray(queries, jnp.float32)
    d = jnp.asarray(dataset)
    int4 = int4_dim is not None
    if not int4 and d.dtype not in (jnp.bfloat16, jnp.int8, jnp.uint8):
        d = d.astype(jnp.float32)   # low-precision modes stay as stored
    if (int4 or d.dtype == jnp.int8) and scales is None:
        # without the per-row dequant factors the raw quantized dot mixes
        # value spaces with the dequantized norms — plausibly-shaped,
        # silently wrong neighbors; fail the contract loudly instead
        expects(False, "int8/int4 datasets require per-row dequant scales "
                       "(see ops.quant.quantize_rows)")
    m, dim = q.shape
    n = d.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if int4:
        # the packed corpus fixes the geometry: the query widens to the
        # (low, high) double-half width the split dot contracts against
        expects(d.shape[1] * 2 >= dim,
                "int4 corpus width %d cannot hold dim %d", d.shape[1], dim)
        dim_p = 2 * d.shape[1]
        tm, tn = _pick_tiles(dim_p, k, 1)
    else:
        dim_p = round_up_to(dim, 128)
        tm, tn = _pick_tiles(dim_p, k, d.dtype.itemsize)
    m_pad = round_up_to(m, min(tm, round_up_to(m, 8)))
    n_pad = round_up_to(n, min(tn, round_up_to(n, 128)))
    if (m_pad, dim_p) != (m, dim):
        q = jnp.pad(q, ((0, m_pad - m), (0, dim_p - dim)))
    # the dataset pad keys on the DATASET's own shape (a prepare_fused
    # corpus arrives already (n_pad, dim_p) while queries are unpadded —
    # comparing against the query dim would re-pad it every call)
    d_w = d.shape[1] if int4 else dim_p
    if (n_pad, d_w) != d.shape:
        d = jnp.pad(d, ((0, n_pad - n), (0, d_w - d.shape[1])))

    if metric in ("l2", "cos"):
        if data_norms is None:
            if int4:
                from .quant import int4_nibbles

                low, high = int4_nibbles(d.astype(jnp.int32))
                dn = jnp.sum(low * low + high * high, axis=1)
            else:
                dn = jnp.sum(d.astype(jnp.float32) ** 2, axis=1)
            if scales is not None:
                dn = dn * jnp.pad(jnp.asarray(scales, jnp.float32),
                                  (0, n_pad - n)) ** 2
        else:
            dn = jnp.pad(jnp.asarray(data_norms, jnp.float32),
                         (0, n_pad - n))
        if metric == "cos":   # kernel divides by the norm, not its square
            dn = jnp.sqrt(dn)
    else:
        dn = jnp.zeros((n_pad,), jnp.float32)

    pen = jnp.zeros((n,), jnp.float32) if penalty is None else (
        jnp.asarray(penalty, jnp.float32))
    pen = jnp.pad(pen, (0, n_pad - n), constant_values=jnp.inf)

    sc = None
    if scales is not None:
        sc = jnp.pad(jnp.asarray(scales, jnp.float32),
                     (0, n_pad - n)).reshape(1, -1)

    vals, idxs = _fused_knn_padded(q, d, dn.reshape(1, -1),
                                   pen.reshape(1, -1), sc, k, metric,
                                   interpret, precision, (tm, tn),
                                   int4=int4)
    return vals[:m], idxs[:m]
