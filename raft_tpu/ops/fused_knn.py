"""Fused pairwise-distance + running top-k Pallas kernel.

The TPU analog of RAFT's fused brute-force path: the tiled distance GEMM
(detail/knn_brute_force.cuh:61) with the per-tile select and cross-tile
merge (matrix/detail/select_warpsort.cuh:35) collapsed into one kernel.
The distance block for each (query-tile, dataset-tile) pair is computed on
the MXU; a running k-best (value, index) buffer lives in VMEM scratch and
is updated in-place as the kernel walks the dataset tiles, so no
(m, n) distance matrix — and no full per-tile sort — ever exists.

Selection is an iterative min-extraction: k passes over the concatenated
[running-buffer | tile] row, each extracting the row minimum with a
deterministic smallest-column tie-break. For the k regimes ANN search
uses (k <= 128, tile width ~1k) this is a few VPU reductions per
extracted element, far below the O(n log^2 n) sort the XLA `top_k`
lowering performs per tile.

Masking (bitset sample filters, padded rows, shard validity) is folded
into an additive penalty row: +inf for excluded dataset rows, 0 otherwise
— one broadcast add, no per-metric special cases.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import round_up_to

__all__ = ["fused_knn"]

_INT_BIG = 2**30  # sentinel column id, larger than any real lane index


def _compiler_params(dimension_semantics):
    """Version-compat TPU compiler params (resilience: API skew must
    degrade to the equivalent spelling, not crash the kernel path).
    Newer jax spells it ``pltpu.CompilerParams`` with a
    ``GridDimensionSemantics`` enum; 0.4.x uses ``TPUCompilerParams``
    with plain 'parallel'/'arbitrary' strings."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is not None:
        sem = getattr(pltpu, "GridDimensionSemantics", None)
        dims = (tuple(getattr(sem, s.upper()) for s in dimension_semantics)
                if sem is not None and hasattr(sem, "PARALLEL") else None)
        return cls(dimension_semantics=dims)
    return pltpu.TPUCompilerParams(
        dimension_semantics=tuple(dimension_semantics))


def _pick_tiles(dim_p: int, k: int) -> Tuple[int, int]:
    """(query-tile, dataset-tile) sizes under a ~12 MB VMEM working set.

    Defaults target v5e-class VMEM; override with
    ``RAFT_TPU_FUSED_TILES=tm,tn`` when sweeping other generations.
    Engine-level dispatch is where measurement lives: ops.autotune times
    this whole kernel against the matmul/scan engines per shape class
    (brute_force.tune_search), so a tile config only matters on hardware
    where the fused kernel wins that race. Shrink with dim so the
    (tm, tn) distance block plus tiles stay inside VMEM, and with k since
    the merge working set grows with kp.
    """
    import os

    env = os.environ.get("RAFT_TPU_FUSED_TILES")
    if env:
        parts = env.split(",")
        if len(parts) != 2:
            raise ValueError(
                f"RAFT_TPU_FUSED_TILES must be 'tm,tn', got {env!r}")
        tm, tn = (int(v) for v in parts)
        # snap to TPU tiling multiples (sublane 8 / lane 128)
        tm = max(8, (tm // 8) * 8)
        tn = max(128, (tn // 128) * 128)
        return tm, tn
    if dim_p <= 256:
        tm, tn = 512, 1024
    elif dim_p <= 512:
        tm, tn = 512, 512
    else:
        tm, tn = 256, 512
    if k > 64:
        tm = max(tm // 2, 128)
    return tm, tn


def _kernel(q_ref, d_ref, dn_ref, pen_ref, ov_ref, oi_ref, sv_ref, si_ref,
            *, k: int, kp: int, tn: int, metric: str, n_dtiles: int,
            precision: str):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        sv_ref[:] = jnp.full_like(sv_ref, jnp.inf)
        si_ref[:] = jnp.full_like(si_ref, -1)

    q = q_ref[:]                                   # (tm, dim_p) f32
    d = d_ref[:]                                   # (tn, dim_p) f32|bf16
    tm = q.shape[0]
    if d.dtype == jnp.bfloat16:
        # bf16 dataset mode: rows stream from HBM at half the f32 traffic;
        # the product accumulates in f32 (precision knob is moot — the
        # stored operand is already bf16)
        dot = jax.lax.dot_general(q.astype(jnp.bfloat16), d,
                                  (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    else:
        dot = jax.lax.dot_general(
            q, d, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision(precision))  # (tm, tn)
    if metric == "l2":
        qn = jnp.sum(q * q, axis=1, keepdims=True)
        dist = jnp.maximum(qn + dn_ref[:] - 2.0 * dot, 0.0)
    elif metric == "cos":                          # dn holds sqrt row norms
        qn = jnp.sqrt(jnp.sum(q * q, axis=1, keepdims=True))
        dist = 1.0 - dot / jnp.maximum(qn * dn_ref[:], 1e-30)
    else:                                          # "ip": min-select on -dot
        dist = -dot
    dist = dist + pen_ref[:]                       # +inf on masked/padded rows

    lane = jax.lax.broadcasted_iota(jnp.int32, (tm, kp), 1)

    def topk_of(c, ci, k):
        """k smallest of rows of ``c`` with ids ``ci`` → ((tm, kp) val/id).

        Iterative min-extraction: ties broken toward the smallest column, so
        exactly one element is retired per pass.
        """
        w = c.shape[1]
        ccol = jax.lax.broadcasted_iota(jnp.int32, (tm, w), 1)

        def extract(t, state):
            c, nv, ni = state
            best = jnp.min(c, axis=1, keepdims=True)
            pos = jnp.min(jnp.where(c <= best, ccol, _INT_BIG), axis=1,
                          keepdims=True)
            at = ccol == pos
            bid = jnp.max(jnp.where(at, ci, -1), axis=1, keepdims=True)
            # rows with no remaining finite candidate: the inf tie-scan
            # lands on an already-retired column — emit the -1 sentinel,
            # not that column's (real, duplicate) id
            bid = jnp.where(jnp.isfinite(best), bid, -1)
            nv = jnp.where(lane == t, best, nv)
            ni = jnp.where(lane == t, bid, ni)
            return jnp.where(at, jnp.inf, c), nv, ni

        state = (c, jnp.full((tm, kp), jnp.inf, jnp.float32),
                 jnp.full((tm, kp), -1, jnp.int32))
        if k <= 16:
            for t in range(k):
                state = extract(t, state)
        else:
            state = jax.lax.fori_loop(0, k, extract, state)
        return state[1], state[2]

    # merge only when some row improves on its current k-th best
    thresh = sv_ref[:, k - 1 : k]                  # (tm, 1)
    tile_min = jnp.min(dist, axis=1, keepdims=True)

    @pl.when(jnp.any(tile_min < thresh))
    def _():
        # two-level: tile top-k first, then merge two k-lists — keeps the
        # VMEM peak at the (tm, tn) distance block instead of a wide concat
        col = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 1) + j * tn
        tv, ti = topk_of(dist, col, k)
        nv, ni = topk_of(jnp.concatenate([sv_ref[:], tv], axis=1),
                         jnp.concatenate([si_ref[:], ti], axis=1), k)
        sv_ref[:] = nv
        si_ref[:] = ni

    @pl.when(j == n_dtiles - 1)
    def _():
        ov_ref[:] = sv_ref[:]
        oi_ref[:] = si_ref[:]


@functools.partial(jax.jit,
                   static_argnames=("k", "metric", "interpret", "precision",
                                    "tiles"))
def _fused_knn_padded(q, d, dn, pen, k: int, metric: str, interpret: bool,
                      precision: str, tiles: Tuple[int, int]):
    m_pad, dim_p = q.shape
    n_pad = d.shape[0]
    tm, tn = tiles
    tm = min(tm, m_pad)
    tn = min(tn, n_pad)
    kp = round_up_to(k, 128)
    grid = (m_pad // tm, n_pad // tn)

    kern = functools.partial(_kernel, k=k, kp=kp, tn=tn, metric=metric,
                             n_dtiles=grid[1], precision=precision)
    flops = 2 * m_pad * n_pad * dim_p
    vals, idxs = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, dim_p), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, dim_p), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, kp), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, kp), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, kp), jnp.float32),
            pltpu.VMEM((tm, kp), jnp.int32),
        ],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=int(q.size + d.size + dn.size) * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, d, dn, pen)
    return vals[:, :k], idxs[:, :k]


def fused_knn(
    queries: jax.Array,
    dataset: jax.Array,
    k: int,
    metric: str = "l2",
    data_norms: Optional[jax.Array] = None,
    penalty: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array]:
    """k nearest rows of ``dataset`` for each query, fused on-TPU.

    metric: "l2" (squared L2), "cos" (1 - cosine, using precomputed or
    derived row norms), "ip" (inner product; returns min-ordered -dot,
    caller negates). ``data_norms``: optional (n,) squared L2 row norms
    (reused from the index for "l2"/"cos"; derived here when absent).
    ``penalty``: optional (n,) f32 additive row penalty (+inf to exclude).
    ``precision``: MXU precision for the distance GEMM — "highest"
    (3-pass bf16, ~f32-accurate; the exact-search default) or "default"
    (single-pass bf16 multiplies, ~3x the MXU throughput, distance error
    ~1e-3 relative — fine as an ANN candidate generator).
    Returns (values (m, k), indices (m, k)) sorted best-first; excluded /
    out-of-range slots have value +inf and index -1.
    """
    q = jnp.asarray(queries, jnp.float32)
    d = jnp.asarray(dataset)
    if d.dtype != jnp.bfloat16:    # bf16 stays bf16 (halved HBM traffic)
        d = d.astype(jnp.float32)
    m, dim = q.shape
    n = d.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    dim_p = round_up_to(dim, 128)
    tm, tn = _pick_tiles(dim_p, k)
    m_pad = round_up_to(m, min(tm, round_up_to(m, 8)))
    n_pad = round_up_to(n, min(tn, round_up_to(n, 128)))
    q = jnp.pad(q, ((0, m_pad - m), (0, dim_p - dim)))
    d = jnp.pad(d, ((0, n_pad - n), (0, dim_p - dim)))

    if metric in ("l2", "cos"):
        dn = (jnp.sum(d.astype(jnp.float32) ** 2, axis=1)
              if data_norms is None
              else jnp.pad(jnp.asarray(data_norms, jnp.float32),
                           (0, n_pad - n)))
        if metric == "cos":   # kernel divides by the norm, not its square
            dn = jnp.sqrt(dn)
    else:
        dn = jnp.zeros((n_pad,), jnp.float32)

    pen = jnp.zeros((n,), jnp.float32) if penalty is None else (
        jnp.asarray(penalty, jnp.float32))
    pen = jnp.pad(pen, (0, n_pad - n), constant_values=jnp.inf)

    vals, idxs = _fused_knn_padded(q, d, dn.reshape(1, -1),
                                   pen.reshape(1, -1), k, metric, interpret,
                                   precision, (tm, tn))
    return vals[:m], idxs[:m]
