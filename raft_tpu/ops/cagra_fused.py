"""One-dispatch CAGRA traversal: the multi-hop frontier megakernel.

``cagra._search_jit``'s hop loop is a ``lax.while_loop`` whose body
launches a fresh kernel per hop (the Pallas frontier expansion of
``ops/graph_expand.py``) and round-trips the itopk buffer through HBM
between launches. At serving batch sizes the per-launch fixed cost —
BENCH_r05 records ``dispatch_us ≈ 106,397`` on the tunneled backend —
bounds p99, not the kernel math. The reference CAGRA (Ootomo et al.,
2023; RAFT's persistent single-launch search mode) wins precisely by
keeping the whole traversal resident on-device in one launch.

This module is that launch, TPU form: ONE ``pallas_call`` whose grid is
``(query_blocks, max_iter)`` — the hop dimension is a *grid axis*, not a
host loop. The frontier (itopk distances/ids/explored flags) lives in
VMEM scratch and persists across the sequential hop steps; each step

* picks the top ``search_width`` unexplored parents with the same
  masked-min extraction ``select_k`` ties imply (lowest column first),
* DMAs each parent's contiguous edge tile + aux row + graph row (and
  the bitset-penalty row when filtering) from the HBM edge store — the
  ``graph_expand`` scalar-addressed streamed-tile machinery, with all
  per-parent copies in flight together,
* scores tiles with ``graph_expand``'s exact arithmetic (bit-identical
  values), extracts each parent's top-``k'`` in (value, edge-position)
  order, dedups against the buffer and earlier candidates,
* and folds candidates into the itopk buffer with the in-VMEM
  (value, position)-lexicographic k-pass fold from ``ops/ring_topk.py``
  (``_vmem_fold``), explored flags riding as a fold payload.

Every step mirrors the ``engine="edge"`` hop's math and tie order, so
the traversal is BIT-IDENTICAL to the edge engine (the total order
(distance, concat position) makes the sequential per-parent fold equal
to the one-shot ``select_k`` over the full concatenation — the ring
merge's associativity argument). tests/test_cagra_fused.py pins it in
interpret mode; on hardware the ``cagra.fused_search`` breaker demotes
to the edge/gather path on any kernel failure.

Parent ids are data-dependent (read from the VMEM frontier), so the
per-parent DMA addresses come from in-kernel scalar extraction rather
than scalar prefetch — the one structural difference from
``graph_expand``. Like the ring kernel, this kernel has only been
shape-traced and interpret-tested off-TPU; first hardware session:
``pytest tests/test_cagra_fused.py`` on the pod before trusting the
race.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import round_up_to
from .graph_expand import _pick_pq

__all__ = ["fused_traverse", "fused_capable", "one_dispatch_stats",
           "FUSED_SITE"]

# the breaker site every fused dispatch runs under (ops/guarded.py):
# a megakernel failure demotes to the edge engine (itself guarded onto
# the XLA gather path) — one log line, never the request
FUSED_SITE = "cagra.fused_search"

_INT_BIG = 2**30
# conservative VMEM ceiling for the resident working set (v5e has
# ~16 MB/core; leave headroom for the fold temporaries Mosaic keeps live)
_VMEM_CAP_BYTES = 8 << 20


def _kernel(q_ref, bd0_ref, bi0_ref, vecs_hbm, aux_hbm, gph_hbm, *rest,
            P_q: int, width: int, deg_p: int, degree: int, itopk: int,
            itopk_p: int, kprime: int, kp: int, n_hops: int, n: int,
            metric: str, with_pen: bool, mode: str):
    from .ring_topk import _vmem_fold

    if with_pen:
        pen_hbm, obd_ref, obi_ref, bufd, bufi, bufe, vtile, atile, \
            gtile, ptile, sem = rest
    else:
        pen_hbm = ptile = None
        obd_ref, obi_ref, bufd, bufi, bufe, vtile, atile, gtile, sem = rest
    P = P_q * width
    h = pl.program_id(1)

    @pl.when(h == 0)
    def _init():
        bufd[:] = bd0_ref[:]
        bufi[:] = bi0_ref[:]
        bufe[:] = jnp.zeros((P_q, itopk_p), jnp.int32)

    lane_it = jax.lax.broadcasted_iota(jnp.int32, (P_q, itopk_p), 1)
    bd = bufd[:]
    bi = bufi[:]
    be = bufe[:]

    # ---- pick the top `width` unexplored parents (pickup_next_parents):
    # sequential masked-min extraction == select_k's lowest-column tie
    # order, marking each pick explored as the XLA body does
    vald = jnp.where(be == 1, jnp.inf, bd)
    pids, poks = [], []
    for _w in range(width):
        best = jnp.min(vald, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(vald <= best, lane_it, _INT_BIG), axis=1,
                      keepdims=True)
        at = lane_it == pos
        pids.append(jnp.min(jnp.where(at, bi, _INT_BIG), axis=1,
                            keepdims=True))
        poks.append(jnp.isfinite(best))
        be = jnp.where(at, 1, be)
        vald = jnp.where(at, jnp.inf, vald)
    bufe[:] = be

    # ---- per-parent streamed DMAs, all in flight together (the
    # graph_expand pattern; addresses are in-kernel scalars here).
    # Tile row j = w*P_q + q is query q's w-th parent — width-major, so
    # each w-block of P_q tiles aligns 1:1 with the query rows and the
    # scoring below needs no routing matmul.
    copies = []
    for j in range(P):
        w, qr = j // P_q, j % P_q
        pid = jnp.where(poks[w][qr, 0], pids[w][qr, 0], 0)
        pid = jnp.clip(pid, 0, n - 1)
        for src, dst, s in ((vecs_hbm, vtile, 0), (aux_hbm, atile, 1),
                            (gph_hbm, gtile, 2)):
            c = pltpu.make_async_copy(src.at[pid], dst.at[j], sem.at[s, j])
            c.start()
            copies.append(c)
        if with_pen:
            c = pltpu.make_async_copy(pen_hbm.at[pid], ptile.at[j],
                                      sem.at[3, j])
            c.start()
            copies.append(c)

    q = q_ref[:]                                     # (P_q, dim_p) f32
    qn = jnp.sum(q * q, axis=1, keepdims=True)       # (P_q, 1)
    for c in copies:
        c.wait()

    col = jax.lax.broadcasted_iota(jnp.int32, (P_q, deg_p), 1)
    rank = jax.lax.broadcasted_iota(jnp.int32, (P_q, kp), 1)

    # ---- score + per-parent top-k' per width slot (graph_expand's
    # arithmetic and extraction verbatim, so values/ties are
    # bit-identical to the edge engine's kernel)
    cvals, cids, coks = [], [], []
    for w in range(width):
        V = vtile[w * P_q:(w + 1) * P_q]             # (P_q, deg_p, W)
        A = atile[w * P_q:(w + 1) * P_q]             # (P_q, 2, deg_p)
        scales = A[:, 0, :]
        vnorm = A[:, 1, :]
        # storage-rung widen + scoring SHARED with graph_expand (the
        # bit-parity contract: both engines evaluate the identical
        # expression — int4's split nibble reduce included)
        from .graph_expand import edge_tile_widen

        cross = edge_tile_widen(V, q, mode)           # (P_q, deg_p)
        cross = cross * scales
        if metric == "l2":
            dist = jnp.maximum(qn + vnorm - 2.0 * cross, 0.0)
        else:                                         # "ip": min-space -dot
            dist = -cross
        if with_pen:
            dist = dist + ptile[w * P_q:(w + 1) * P_q].reshape(P_q, deg_p)
        dist = jnp.where(col < degree, dist, jnp.inf)
        gids = gtile[w * P_q:(w + 1) * P_q].reshape(P_q, deg_p)

        def extract(t, state):
            c, nv, ni = state
            best = jnp.min(c, axis=1, keepdims=True)
            pos = jnp.min(jnp.where(c <= best, col, _INT_BIG), axis=1,
                          keepdims=True)
            at = col == pos
            gid = jnp.min(jnp.where(at, gids, _INT_BIG), axis=1,
                          keepdims=True)
            gid = jnp.where(jnp.isfinite(best), gid, -1)
            nv = jnp.where(rank == t, best, nv)
            ni = jnp.where(rank == t, gid, ni)
            return jnp.where(at, jnp.inf, c), nv, ni

        state = (dist, jnp.full((P_q, kp), jnp.inf, jnp.float32),
                 jnp.full((P_q, kp), -1, jnp.int32))
        if kprime <= 16:
            for t in range(kprime):
                state = extract(t, state)
        else:
            state = jax.lax.fori_loop(0, kprime, extract, state)
        cvals.append(state[1])
        cids.append(state[2])
        # an empty slot (inf value) mirrors pepos<0; parent gating is
        # applied after dedup exactly as the host-side edge path does
        coks.append(poks[w] & jnp.isfinite(state[1]))

    # ---- dedup (the _dup_mask semantics): a candidate equal to any
    # buffer entry or to an EARLIER candidate in (parent, rank) concat
    # order is masked to +inf — ids kept as-is; masked entries can never
    # be selected (every buffer entry outranks them by position)
    t_a = jax.lax.broadcasted_iota(jnp.int32, (P_q, kp, kp), 1)
    t_b = jax.lax.broadcasted_iota(jnp.int32, (P_q, kp, kp), 2)
    for w in range(width):
        dup = jnp.any(cids[w][:, :, None] == bi[:, None, :], axis=2)
        for wp in range(w):
            dup = dup | jnp.any(cids[w][:, :, None] == cids[wp][:, None, :],
                                axis=2)
        dup = dup | jnp.any(
            (cids[w][:, :, None] == cids[w][:, None, :]) & (t_b < t_a),
            axis=2)
        cvals[w] = jnp.where(coks[w] & ~dup, cvals[w], jnp.inf)

    # ---- merge: sequential per-parent folds with ORIGINAL concat
    # positions as the tie key == one select_k over the full (buffer ++
    # candidates) concatenation (total-order top-k is associative); the
    # explored plane rides as a fold payload
    run_d = bd
    run_p = jnp.where(lane_it < itopk, lane_it, _INT_BIG)
    run_g = bi
    run_e = bufe[:]
    zeros_e = jnp.zeros((P_q, kp), jnp.int32)
    for w in range(width):
        blk_p = jnp.where(rank < kprime, itopk + w * kprime + rank,
                          _INT_BIG)
        run_d, run_p, run_g, run_e = _vmem_fold(
            jnp.concatenate([run_d, cvals[w]], axis=1),
            jnp.concatenate([run_p, blk_p], axis=1),
            jnp.concatenate([run_g, cids[w]], axis=1),
            itopk, itopk_p,
            extra=(jnp.concatenate([run_e, zeros_e], axis=1),))
    bufd[:] = run_d
    bufi[:] = run_g
    bufe[:] = run_e

    @pl.when(h == n_hops - 1)
    def _out():
        obd_ref[:] = bufd[:]
        obi_ref[:] = bufi[:]


@functools.partial(
    jax.jit,
    static_argnames=("itopk", "width", "max_iter", "kprime", "degree",
                     "metric", "P_q", "interpret", "with_pen", "mode"))
def _fused_padded(q, bd0, bi0, vecs, aux, gph, pen, itopk: int, width: int,
                  max_iter: int, kprime: int, degree: int, metric: str,
                  P_q: int, interpret: bool, with_pen: bool,
                  mode: str = "dense"):
    m_pad, dim_p = q.shape
    n, deg_p, store_w = vecs.shape
    P = P_q * width
    itopk_p = round_up_to(itopk, 128)
    kp = round_up_to(kprime, 128)
    grid = (m_pad // P_q, max_iter)

    kern = functools.partial(_kernel, P_q=P_q, width=width, deg_p=deg_p,
                             degree=degree, itopk=itopk, itopk_p=itopk_p,
                             kprime=kprime, kp=kp, n_hops=max_iter, n=n,
                             metric=metric, with_pen=with_pen, mode=mode)
    blk = lambda shape: pl.BlockSpec(shape, lambda i, h: (i, 0),
                                     memory_space=pltpu.VMEM)
    in_specs = [
        blk((P_q, dim_p)),                       # queries
        blk((P_q, itopk_p)),                     # seed-initialized buf_d
        blk((P_q, itopk_p)),                     # seed-initialized buf_i
        pl.BlockSpec(memory_space=pl.ANY),       # edge store stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),       # aux (scales, norms)
        pl.BlockSpec(memory_space=pl.ANY),       # graph rows (n, 1, deg_p)
    ]
    args = [q, bd0, bi0, vecs, aux, gph]
    if with_pen:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        args.append(pen)
    scratch = [
        pltpu.VMEM((P_q, itopk_p), jnp.float32),   # frontier: distances
        pltpu.VMEM((P_q, itopk_p), jnp.int32),     # frontier: ids
        pltpu.VMEM((P_q, itopk_p), jnp.int32),     # frontier: explored
        pltpu.VMEM((P, deg_p, store_w), vecs.dtype),
        pltpu.VMEM((P, 2, deg_p), jnp.float32),
        pltpu.VMEM((P, 1, deg_p), jnp.int32),
    ]
    if with_pen:
        scratch.append(pltpu.VMEM((P, 1, deg_p), jnp.float32))
    scratch.append(pltpu.SemaphoreType.DMA((4, P)))

    out_d, out_i = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[blk((P_q, itopk_p)), blk((P_q, itopk_p))],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, itopk_p), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, itopk_p), jnp.int32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    return out_d, out_i


def fused_traverse(
    queries: jax.Array,          # (m, dim) f32
    buf_d: jax.Array,            # (m, itopk) f32 seed-initialized buffer
    buf_i: jax.Array,            # (m, itopk) int32 seed-initialized ids
    vecs: jax.Array,             # (n, deg_p, dim_p) int8 | bf16 edge store
    aux: jax.Array,              # (n, 2, deg_p) f32 [scales, dequant norms]
    gph: jax.Array,              # (n, deg_p) int32 padded graph rows
    pen: Optional[jax.Array] = None,   # (n, deg_p) f32 edge penalties
    *,
    itopk: int,
    width: int,
    max_iter: int,
    kprime: int,
    degree: int,
    metric: str = "l2",
    interpret: Optional[bool] = None,
    mode: str = "dense",
) -> Tuple[jax.Array, jax.Array]:
    """Run the whole multi-hop traversal in one kernel launch.

    Takes the seed-initialized itopk buffer (``cagra._search_jit``'s
    shared seeding preamble) and returns the converged ``(buf_d, buf_i)``
    — bit-identical to ``max_iter`` iterations of the edge-engine hop
    body (the fixed grid runs every hop; a converged frontier yields no
    finite parents, so extra hops are exact no-ops on the buffer, which
    is also why early exit costs nothing but the idle steps). ``mode``:
    the edge store's rung — "dense" (int8/bf16 rows) or "int4"
    (nibble-packed; the shared ``graph_expand.edge_tile_widen`` keeps
    both engines' arithmetic identical). PQ stores serve the edge
    engine — the megakernel carries no in-kernel LUT decode."""
    from .graph_expand import score_dim

    m = queries.shape[0]
    n, deg_p, _ = vecs.shape
    dim_p = score_dim(vecs, mode)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    P_q = _pick_pq(width)
    m_pad = round_up_to(m, P_q)
    itopk_p = round_up_to(itopk, 128)
    q = jnp.asarray(queries, jnp.float32)
    q = jnp.pad(q, ((0, m_pad - m), (0, dim_p - q.shape[1])))
    bd = jnp.pad(buf_d.astype(jnp.float32),
                 ((0, m_pad - m), (0, itopk_p - itopk)),
                 constant_values=jnp.inf)
    bi = jnp.pad(buf_i.astype(jnp.int32),
                 ((0, m_pad - m), (0, itopk_p - itopk)),
                 constant_values=-1)
    gph3 = gph.reshape(n, 1, deg_p)
    pen3 = pen.reshape(n, 1, deg_p) if pen is not None else None
    od, oi = _fused_padded(q, bd, bi, vecs, aux, gph3, pen3, itopk, width,
                           int(max_iter), kprime, degree, metric, P_q,
                           bool(interpret), pen is not None, mode)
    return od[:m, :itopk], oi[:m, :itopk]


def fused_capable(itopk: int, width: int, deg_p: int, dim_p: int,
                  store_dtype, max_iter: int) -> bool:
    """Whether the megakernel's resident working set fits the VMEM
    budget: edge tiles for P parents + the frontier planes + the fold's
    live concat temporaries (docs/perf.md has the itopk×width×dim
    math). Shapes past the cap should serve the edge engine instead —
    tune_search skips the fused lane for them."""
    if max_iter < 1:
        return False
    P_q = _pick_pq(width)
    P = P_q * width
    itopk_p = round_up_to(itopk, 128)
    kp = round_up_to(min(deg_p, max(itopk, 1)), 128)
    esize = jnp.dtype(store_dtype).itemsize
    tiles = P * deg_p * dim_p * esize + P * 3 * deg_p * 4
    frontier = 3 * P_q * itopk_p * 4
    # fold temporaries: ~4 planes of the (itopk_p + kp)-wide concat plus
    # the (P_q, kp, itopk_p) dedup compare, live at once
    fold = 4 * P_q * (itopk_p + kp) * 4 + P_q * kp * itopk_p
    return tiles + frontier + fold <= _VMEM_CAP_BYTES


def one_dispatch_stats(fn, *args) -> dict:
    """Trace ``fn(*args)`` and report its device-loop / kernel-launch
    structure: ``while_loops`` counts device-side loops OUTSIDE Pallas
    kernel bodies (each iteration of one is a separate kernel-launch
    round trip on device), ``pallas_calls`` counts kernel launch sites.
    ``one_dispatch`` is True when no such loop remains — the whole
    search then lowers to one straight-line XLA executable, dispatched
    once per call (the bench serving lane and the one-dispatch test
    read this).

    Since ISSUE 14 this is the thin public alias of the generalized
    serving audit (:func:`raft_tpu.analysis.hotpath_audit.jaxpr_stats`),
    which additionally reports host-callback primitives — one walker,
    one definition of "a dispatch"."""
    from ..analysis.hotpath_audit import jaxpr_stats

    return jaxpr_stats(fn, *args)
