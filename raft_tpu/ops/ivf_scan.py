"""Query-grouped IVF-Flat list scan — the fused interleaved-scan analog.

Reference role: neighbors/detail/ivf_flat_interleaved_scan-inl.cuh:1085
(fused per-list scan + top-k) — on GPU each CTA walks one (query, probe)
pair's list. A TPU grid step wants a dense MXU tile instead, so the
mapping is inverted: (query, probe) pairs are sorted by list id and
packed into fixed-size *query groups per list*; each grid step DMAs one
list's contiguous row range (the cluster-sorted layout makes every probe
a dense slice — no per-row gathers) and scores a (group × list) block on
the MXU, extracting the per-pair top-k in VMEM. A final XLA select_k
merges each query's probe results.

The pair grouping itself is all XLA sorts/cumsums on device; nothing
host-side touches per-query data. List offsets are arbitrary: the DMA
start is rounded down to the sublane multiple and the window masked.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import cdiv, round_up_to

__all__ = ["ivf_flat_scan"]

_QG = 128            # queries per group (MXU-height tile)
_INT_BIG = 2**30


def pack_pairs(probed: jax.Array, n_lists: int):
    """Pack the (query, probe) pairs into per-list groups of _QG queries.

    → (qtable (G, QG) query ids, glist (G,) list per group, galive (G,),
    flat (mp,) output slot per sorted pair, order (mp,) pair sort, G).
    Shared by the IVF-Flat and IVF-PQ scan kernels.

    SCATTER-FREE (r5): the original formulation built qtable/glist/galive
    with four ``.at[]`` scatters over the m·p pairs; TPU scatters
    serialize, and the grouping chain dominated the whole search wall
    (scratch/exp_grouping_r5.json: 110.9 → 14.8 ms at m=10k, p=20,
    L=1024). This version keeps ONE argsort and derives everything else
    from vectorized bisections over the n_lists boundaries, affine index
    math, and contiguous 128-wide window slices of the sorted pair
    array. ``glist`` of dead (gated) groups is unspecified.
    """
    m, p = probed.shape
    mp = m * p
    lids = probed.reshape(-1)                       # (mp,)
    order = jnp.argsort(lids, stable=True)
    slids = lids[order]
    sqids = (order // p).astype(jnp.int32)          # query of sorted pair
    lrange = jnp.arange(n_lists, dtype=jnp.int32)
    starts = jnp.searchsorted(slids, lrange, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(slids, lrange, side="right").astype(jnp.int32)
    counts = ends - starts
    gcounts = -(-counts // _QG)                     # cdiv per list
    gbase = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                             jnp.cumsum(gcounts)[:-1].astype(jnp.int32)])
    n_groups = cdiv(mp, _QG) + n_lists              # static bound
    gids = jnp.arange(n_groups, dtype=jnp.int32)
    glist = jnp.clip(jnp.searchsorted(gbase, gids, side="right") - 1,
                     0, n_lists - 1).astype(jnp.int32)
    within = gids - gbase[glist]                    # chunk index in list
    galive = within < gcounts[glist]
    row_start = starts[glist] + within * _QG
    sq_pad = jnp.concatenate(
        [sqids, jnp.zeros((n_groups * _QG,), jnp.int32)])
    qtable = jax.vmap(
        lambda s: jax.lax.dynamic_slice(sq_pad, (s,), (_QG,)))(row_start)
    lanes = jnp.arange(_QG, dtype=jnp.int32)[None, :]
    valid = (row_start[:, None] + lanes) < ends[glist][:, None]
    qtable = jnp.where(valid & galive[:, None], qtable, 0)
    pos = jnp.arange(mp, dtype=jnp.int32) - starts[slids]
    flat = (gbase[slids] + pos // _QG) * _QG + pos % _QG
    return qtable, glist, galive, flat, order, n_groups


def coarse_probe(q, centers, n_probes: int, metric: str = "l2",
                 center_norms=None, precision: str = "highest",
                 survivors=None):
    """Probe selection (ivf_flat_search-inl.cuh:38 role): one GEMM over
    the centers plus a rank-k select. Scores are RANKING-ONLY (per-query
    constants dropped — ||q||² never changes which lists win), and the
    select rides matrix.select_k's AUTO engine: at (m, n_lists=1024,
    k=20) the Pallas k-pass engine measured ~6x under lax.top_k
    (scratch/exp_select_slope_r5.json), which the old fused_knn coarse
    could not use.

    ``survivors``: optional (n_lists,) per-list filter-survivor counts
    (ops/filter_policy.py); lists with zero survivors score +inf so the
    probe budget is spent only where a candidate can actually come from
    (a pruned list would contribute nothing but sentinel rows)."""
    from ..matrix.select_k import select_k

    q = jnp.asarray(q, jnp.float32)
    cross = jax.lax.dot_general(
        q, jnp.asarray(centers, jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision(precision))     # (m, L)
    if center_norms is None:
        cn = jnp.sum(centers * centers, axis=1)
    else:
        cn = jnp.asarray(center_norms, jnp.float32)
    if metric == "ip":
        score = -cross
    elif metric == "cos":
        score = -cross / jnp.sqrt(jnp.maximum(cn, 1e-30))[None, :]
    else:                                           # "l2"
        score = cn[None, :] - 2.0 * cross
    if survivors is not None:
        score = jnp.where(survivors[None, :] > 0, score, jnp.inf)
    return select_k(score, n_probes, select_min=True)[1]


def merge_pairs(gv, gi, flat, order, m: int, p: int, k: int):
    """Per-pair (G, QG, kp) kernel outputs → per-query final top-k."""
    from ..matrix.select_k import select_k

    n_slots = gv.shape[0] * gv.shape[1]
    gv = gv[:, :, :k].reshape(n_slots, k)
    gi = gi[:, :, :k].reshape(n_slots, k)
    inv = jnp.argsort(order)
    pair_v = gv[flat][inv].reshape(m, p * k)
    pair_i = gi[flat][inv].reshape(m, p * k)
    out_v, sel = select_k(pair_v, k, select_min=True)
    out_i = jnp.take_along_axis(pair_i, sel, axis=1)
    return out_v, jnp.where(jnp.isfinite(out_v), out_i, -1)


def _kernel(offs_ref, sizes_ref, qb_ref, qn_ref, dn_ref, pen_ref, scl_ref,
            data_ref, ov_ref, oi_ref, rows_vmem, sem,
            *, k: int, kp: int, lmax: int, metric: str, precision: str,
            has_pen: bool, has_scales: bool):
    g = pl.program_id(0)
    off = offs_ref[g]
    size = sizes_ref[g]

    # DEAD-GROUP GATE: the static group bound adds up to n_lists dead
    # groups (pack_pairs); ungated they still DMA'd the full lmax window
    # each — measured 8.7 ms of the 15.8 ms kernel wall at 500k/np20
    # (scratch/exp_scan_decomp_r5.json: v0 15.77 -> gated 7.08)
    @pl.when(size <= 0)
    def _dead():
        ov_ref[0] = jnp.full((_QG, kp), jnp.inf, jnp.float32)
        oi_ref[0] = jnp.full((_QG, kp), -1, jnp.int32)

    @pl.when(size > 0)
    def _alive():
        _kernel_body(off, size, qb_ref, qn_ref, dn_ref, pen_ref,
                     scl_ref, data_ref, ov_ref, oi_ref, rows_vmem, sem,
                     k=k, kp=kp, lmax=lmax, metric=metric,
                     precision=precision, has_pen=has_pen,
                     has_scales=has_scales)


def _kernel_body(off, size, qb_ref, qn_ref, dn_ref, pen_ref,
                 scl_ref, data_ref, ov_ref, oi_ref, rows_vmem, sem,
                 *, k: int, kp: int, lmax: int, metric: str,
                 precision: str, has_pen: bool, has_scales: bool):
    # off/size arrive as values: pl.program_id cannot be called inside a
    # pl.when branch (the CPU interpreter has no lowering for it there)
    off_al = (off // 8) * 8
    extra = off - off_al

    # DMA this group's list rows: one contiguous, sublane-aligned range
    copy = pltpu.make_async_copy(
        data_ref.at[pl.ds(off_al, lmax), :], rows_vmem, sem)
    copy.start()
    q = qb_ref[0]                                   # (QG, dim_pad)
    qn = qn_ref[0]                                  # (QG, 1)
    copy.wait()
    rows = rows_vmem[:]                             # (lmax, dim_pad)

    if rows.dtype != jnp.float32:
        # reduced-precision dataset modes (per-dtype loadAndComputeDist
        # role): bf16 rows stream at half the f32 HBM traffic; int8/uint8
        # at a quarter, widened in-register — byte values in [-128, 255]
        # are exact in bf16 (8 significand bits), and int8 rows carry
        # per-row quantization scales applied to the dot below. All
        # accumulate f32. Mosaic has no direct byte→bf16 cast, so bytes
        # widen through int32/f32 (register-only; no extra HBM traffic).
        rows_b = rows
        if rows_b.dtype in (jnp.int8, jnp.uint8):
            rows_b = rows_b.astype(jnp.int32).astype(jnp.float32)
        dot = jax.lax.dot_general(q.astype(jnp.bfloat16),
                                  rows_b.astype(jnp.bfloat16),
                                  (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
    else:
        dot = jax.lax.dot_general(q, rows, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32,
                                  precision=jax.lax.Precision(precision))
    if has_scales:
        # int8 per-row scales: q . dequant(r) == (q . r_int8) * s_r
        dot = dot * scl_ref[0, 0]
    if metric == "l2":
        dist = jnp.maximum(qn + dn_ref[0, 0] - 2.0 * dot, 0.0)
    elif metric == "cos":
        dist = 1.0 - dot / jnp.maximum(qn * dn_ref[0, 0], 1e-30)
    else:                                           # "ip"
        dist = -dot
    if has_pen:
        # bitset sample filter folded in as an additive penalty row
        # (+inf on excluded rows) — the fused_knn penalty mechanism
        # applied to the list scan; role of the in-kernel filter at
        # detail/ivf_pq_search.cuh:795-797
        dist = dist + pen_ref[0, 0]
    col = jax.lax.broadcasted_iota(jnp.int32, (_QG, lmax), 1)
    dist = jnp.where((col >= extra) & (col < extra + size), dist, jnp.inf)

    lane = jax.lax.broadcasted_iota(jnp.int32, (_QG, kp), 1)

    def extract(t, state):
        c, nv, ni = state
        best = jnp.min(c, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(c <= best, col, _INT_BIG), axis=1,
                      keepdims=True)
        at = col == pos
        bid = jnp.where(jnp.isfinite(best), off_al + pos, -1)
        nv = jnp.where(lane == t, best, nv)
        ni = jnp.where(lane == t, bid, ni)
        return jnp.where(at, jnp.inf, c), nv, ni

    state = (dist, jnp.full((_QG, kp), jnp.inf, jnp.float32),
             jnp.full((_QG, kp), -1, jnp.int32))
    if k <= 16:
        for t in range(k):
            state = extract(t, state)
    else:
        state = jax.lax.fori_loop(0, k, extract, state)
    ov_ref[0] = state[1]
    oi_ref[0] = state[2]


@functools.partial(
    jax.jit,
    static_argnames=("k", "lmax", "n_groups", "metric", "interpret",
                     "precision", "has_pen", "has_scales"))
def _scan_groups(qblocks, qnorms, dnorm_slices, pen_slices, scale_slices,
                 data, goffs, gsizes, k: int, lmax: int, n_groups: int,
                 metric: str, interpret: bool, precision: str,
                 has_pen: bool, has_scales: bool):
    kp = round_up_to(k, 128)
    dim_pad = qblocks.shape[2]
    kern = functools.partial(_kernel, k=k, kp=kp, lmax=lmax,
                             metric=metric, precision=precision,
                             has_pen=has_pen, has_scales=has_scales)
    pen_map = (lambda g, o, s: (g, 0, 0)) if has_pen else (
        lambda g, o, s: (0, 0, 0))
    scl_map = (lambda g, o, s: (g, 0, 0)) if has_scales else (
        lambda g, o, s: (0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_groups,),
        in_specs=[
            pl.BlockSpec((1, _QG, dim_pad), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _QG, 1), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lmax), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lmax), pen_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, lmax), scl_map, memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # data stays in HBM
        ],
        out_specs=[
            pl.BlockSpec((1, _QG, kp), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _QG, kp), lambda g, o, s: (g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((lmax, dim_pad), data.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_groups, _QG, kp), jnp.float32),
            jax.ShapeDtypeStruct((n_groups, _QG, kp), jnp.int32),
        ],
        interpret=interpret,
    )(goffs, gsizes, qblocks, qnorms, dnorm_slices, pen_slices,
      scale_slices, data)


def ivf_flat_scan(
    data: jax.Array,            # (n, dim) cluster-sorted
    data_norms: jax.Array,      # (n,) squared L2 norms
    probed: jax.Array,          # (m, p) probed list ids
    offsets: jax.Array,         # (n_lists,) row offsets (arbitrary)
    sizes: jax.Array,           # (n_lists,) list sizes
    queries: jax.Array,         # (m, dim)
    k: int,
    lmax: int,                  # static bound: max list size (unaligned)
    metric: str = "l2",
    interpret: Optional[bool] = None,
    precision: str = "highest",
    penalty: Optional[jax.Array] = None,   # (n,) f32: +inf excludes a row
    scales: Optional[jax.Array] = None,    # (n,) f32: int8 per-row scales
) -> Tuple[jax.Array, jax.Array]:
    """Scan probed lists → per-query k best (values, ROW ids into ``data``'s
    sorted order, -1 when fewer than k candidates); caller maps row ids to
    source ids and applies metric postprocessing. ``penalty`` and
    ``scales`` are indexed in the same sorted row order as ``data``
    (sample filters / int8 dequantization in-kernel).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    data_p, norms_p, scales_p = pad_for_scan(data, data_norms, lmax, scales)
    pen_p = None
    if penalty is not None:
        pen_p = jnp.pad(jnp.asarray(penalty, jnp.float32),
                        (0, scan_window(lmax)))
    return _ivf_flat_scan_jit(data_p, norms_p, pen_p, scales_p, probed,
                              offsets, sizes, queries, k, lmax, metric,
                              interpret, precision)


def scan_window(lmax: int) -> int:
    """DMA window: max list + up-to-8 alignment slack, rounded to the
    128-lane tile so (1, window) norm blocks lower cleanly."""
    return round_up_to(lmax + 8, 128)


@functools.partial(jax.jit, static_argnames=("lmax",))
def pad_for_scan(data, data_norms, lmax: int, scales=None):
    """Row/col-pad the dataset for the scan kernel's aligned DMA windows.

    A full-dataset copy — call once per index (callers cache the result),
    not per search. bf16/int8/uint8 datasets keep their storage dtype (the
    kernel accumulates f32; int8 rides per-row ``scales``)."""
    lmax_pad = scan_window(lmax)
    dim_pad = round_up_to(data.shape[1], 128)
    data = jnp.asarray(data)
    if data.dtype not in (jnp.bfloat16, jnp.int8, jnp.uint8):
        data = data.astype(jnp.float32)
    data_p = jnp.pad(data, ((0, lmax_pad), (0, dim_pad - data.shape[1])))
    norms_p = jnp.pad(jnp.asarray(data_norms, jnp.float32), (0, lmax_pad))
    scales_p = (None if scales is None else
                jnp.pad(jnp.asarray(scales, jnp.float32), (0, lmax_pad)))
    return data_p, norms_p, scales_p


@functools.partial(
    jax.jit,
    static_argnames=("k", "lmax", "metric", "interpret", "precision"))
def _ivf_flat_scan_jit(data_p, norms_p, pen_p, scales_p, probed, offsets,
                       sizes, queries, k: int, lmax: int, metric: str,
                       interpret: bool, precision: str):
    # one jit over grouping + kernel + merge: the grouping chain is ~20
    # gather/sort ops over ~100 MB intermediates, far too hot to dispatch
    # eagerly per op
    m, p = probed.shape
    n_lists = offsets.shape[0]
    lmax_pad = scan_window(lmax)
    dim_pad = data_p.shape[1]
    dim = queries.shape[1]
    q = jnp.pad(jnp.asarray(queries, jnp.float32),
                ((0, 0), (0, dim_pad - dim)))

    qtable, glist, galive, flat, order, n_groups = pack_pairs(probed,
                                                              n_lists)

    qblocks = q[qtable]                             # (G, QG, dim_pad)
    sq = jnp.sum(qblocks * qblocks, axis=2, keepdims=True)
    qn = sq if metric == "l2" else jnp.sqrt(jnp.maximum(sq, 1e-30))
    goffs = offsets[glist]
    gsizes = jnp.where(galive, sizes[glist], 0)

    # per-group norm windows, matching the kernel's down-aligned DMA
    goffs_al = (goffs // 8) * 8
    dn = jax.vmap(lambda o: jax.lax.dynamic_slice(
        norms_p, (o,), (lmax_pad,)))(goffs_al)
    if metric == "cos":
        dn = jnp.sqrt(jnp.maximum(dn, 1e-30))
    dn = dn[:, None, :]                             # (G, 1, L): TPU block
                                                    # rule wants full minors
    if pen_p is None:
        pen = jnp.zeros((1, 1, lmax_pad), jnp.float32)
    else:
        pen = jax.vmap(lambda o: jax.lax.dynamic_slice(
            pen_p, (o,), (lmax_pad,)))(goffs_al)[:, None, :]
    if scales_p is None:
        scl = jnp.ones((1, 1, lmax_pad), jnp.float32)
    else:
        scl = jax.vmap(lambda o: jax.lax.dynamic_slice(
            scales_p, (o,), (lmax_pad,)))(goffs_al)[:, None, :]

    gv, gi = _scan_groups(qblocks, qn, dn, pen, scl, data_p, goffs, gsizes,
                          k, lmax_pad, int(n_groups), metric, interpret,
                          precision, pen_p is not None,
                          scales_p is not None)

    return merge_pairs(gv, gi, flat, order, m, p, k)
