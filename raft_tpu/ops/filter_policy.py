"""Selectivity-adaptive filtered-search policy (docs/perf.md "Filtered
search").

Every family threads a ``filter=`` bitset through its search path, but a
static policy wastes the information the filter carries: at 99%+
filtered-out a fixed ``n_probes``/``itopk`` collapses recall (the
survivors the probe set covers shrink with the selectivity), while the
kernels still scan every row only to penalize most of them. This module
turns one cheap measurement — the bitset's per-IVF-list survivor counts
(:meth:`raft_tpu.core.bitset.Bitset.count_by_segments`, a grouped
popcount) — into three decisions, all sharing one :class:`FilterDecision`:

* **prune**: lists with zero survivors are dropped from probe selection
  (their ``sizes`` zero out, so the scan kernel emits only sentinel rows
  with no DMA — ``allow_partial``/merge semantics untouched);
* **widen**: the probe set grows along a small ladder of levels
  (brownout-style ×1/×2/×4/×8) until the *survivor-weighted* probe mass
  reaches the unfiltered target, so recall holds without paying the
  widest setting on mild filters. Levels are the only shape knob — each
  lands on an existing compile bucket, so widening costs zero new
  compiles;
* **crossover**: when few enough rows survive
  (``RAFT_TPU_FILTER_BRUTE_MAX``, or a measured verdict under a
  selectivity-bucketed autotune key), gather the survivors and run the
  existing brute-force engine over the compacted set — exact by
  construction and, at extreme selectivity, orders of magnitude less HBM
  traffic than any widened scan. The compacted path is gated behind
  ``guarded_call("filter.survivor_brute")`` with the widened-scan search
  as the bit-safe fallback.

The decision points are eager-only (they read survivor counts onto the
host); a traced filtered search still gets the free device-side zero-
survivor prune via :func:`list_survivors`, just not the adaptive widen/
crossover. Host-streamed IVF indexes keep their own machinery and skip
the adaptive policy entirely, and internal shape-stable filters (the
mutable tier's tombstone masks) run under :func:`suspended`.
"""
from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import env_int

__all__ = ["FilterDecision", "LEVELS", "list_survivors", "decide_ivf",
           "decide_graph", "crossover", "crossover_key",
           "selectivity_bucket", "survivor_ids", "survivor_brute_ivf",
           "survivor_brute_dense", "tune_crossover", "suspended",
           "adaptive_off"]

# widen ladder: each level multiplies the probe budget (n_probes / itopk)
# and lands on its own compile bucket — four buckets total, never one per
# filter. RAFT_TPU_FILTER_WIDEN_MAX caps the ladder (default: full).
LEVELS: Tuple[int, ...] = (1, 2, 4, 8)

_local = threading.local()   # re-entry guard: the crossover's widened-scan
# fallback re-enters the family search, which must not crossover again


@contextlib.contextmanager
def suspended():
    """Disable the adaptive policy (widen + crossover) on this thread;
    the free zero-survivor prune stays. For INTERNAL filters whose
    caller owns a shape-stability contract: the mutable tier masks
    tombstones through the family filter slot, and its views are
    deliberately capacity-padded so repeated searches hit the same
    executables — a crossover there would re-gather the survivors into
    a new shape after every delete (one recompile per mutation, the
    exact storm the soak's steady-state invariant exists to catch)."""
    prev = getattr(_local, "off", False)
    _local.off = True
    try:
        yield
    finally:
        _local.off = prev


def adaptive_off() -> bool:
    """True while inside :func:`suspended` on this thread."""
    return getattr(_local, "off", False)


@dataclasses.dataclass(frozen=True)
class FilterDecision:
    """One filtered call's measured selectivity + the policy verdict."""

    selectivity: float            # surviving fraction (1.0 = no filtering)
    survivors: int                # total surviving rows
    level: int                    # widen multiplier chosen from LEVELS
    n_probes: int                 # widened probe count (IVF families)
    lists_pruned: int             # zero-survivor lists dropped (IVF)
    use_brute: bool               # route to the compacted brute crossover
    surv_dev: Optional[jax.Array] = None   # per-list survivor counts


def _widen_max() -> int:
    return max(1, env_int("RAFT_TPU_FILTER_WIDEN_MAX", LEVELS[-1]))


def _brute_max() -> int:
    return env_int("RAFT_TPU_FILTER_BRUTE_MAX", 8192)


def _set_gauge(selectivity: float) -> None:
    try:
        from ..serve import metrics as serve_metrics

        serve_metrics.gauge("serve.filter.selectivity").set(selectivity)
    except Exception:  # noqa: BLE001 - telemetry must not break search
        pass


def _list_labels(index) -> jax.Array:
    """(total_rows,) int32 list label of each storage row, from the host
    ``list_offsets`` spans. Cached on the index (concrete array built
    from host metadata, so caching is trace-safe)."""
    total = int(index.list_offsets[-1])
    cache = getattr(index, "_filter_list_labels", None)
    if cache is None or cache.shape[0] != total:
        spans = np.diff(np.asarray(index.list_offsets, np.int64))
        lab = np.repeat(np.arange(index.n_lists, dtype=np.int32), spans)
        cache = jnp.asarray(lab)
        index._filter_list_labels = cache
    return cache


def list_survivors(index, filter) -> jax.Array:  # noqa: A002
    """(n_lists,) int32 survivor count per IVF list — one O(total_rows)
    pass (grouped popcount over storage order). Capacity-slack rows carry
    source id -1 and never count. jit-safe; this is the half of the
    policy a traced search still gets (zero-survivor lists zero their
    scan size, so the kernel skips their DMA entirely)."""
    return filter.count_by_segments(index.source_ids, _list_labels(index),
                                    int(index.n_lists))


def selectivity_bucket(selectivity: float) -> str:
    """Coarse categorical tag for autotune keys: decades of surviving
    fraction ("e0" ≈ unfiltered … "e4" ≈ 1-in-10k survives, "none" =
    nothing survives). Crossover verdicts move with the decade, not the
    exact fraction — one race steers the whole bucket."""
    if selectivity <= 0.0:
        return "none"
    return f"e{min(6, max(0, int(-math.log10(min(selectivity, 1.0)))))}"


def crossover_key(family: str, n: int, d: int, k: int,
                  selectivity: float) -> str:
    """Selectivity-bucketed autotune key for the brute-vs-scan race."""
    from . import autotune

    return autotune.shape_bucket("filter_brute", fam=family, n=int(n),
                                 d=int(d), k=int(k),
                                 sel=selectivity_bucket(selectivity))


def _want_brute(family: str, n: int, d: int, k: int, survivors: int,
                selectivity: float) -> bool:
    """Crossover verdict: a measured race winner under the bucketed key
    when one exists, else the env threshold. The widened-fallback
    re-entry guard always wins."""
    if getattr(_local, "skip", False):
        return False
    from . import autotune

    verdict = autotune.lookup(crossover_key(family, n, d, k, selectivity))
    if verdict == "brute":
        return survivors > 0
    if verdict == "scan":
        return False
    return 0 < survivors <= _brute_max()


def decide_ivf(index, filter, n_probes: int, k: int,  # noqa: A002
               family: str) -> FilterDecision:
    """Measure + decide for an IVF family (eager-only: reads the per-list
    survivor counts onto the host).

    Widening math: the unfiltered probe set covers up to T = Σ of the
    ``n_probes`` largest list sizes candidate rows; under the filter the
    same probes cover only their survivors. Pick the smallest ladder
    level whose top-(n_probes·level) *survivor* mass reaches
    min(T, total survivors) — i.e. restore the unfiltered candidate mass
    where possible, and never widen past what survives."""
    surv_dev = list_survivors(index, filter)
    surv = np.asarray(surv_dev, np.int64)
    total = int(surv.sum())
    selectivity = total / max(int(filter.n_bits), 1)
    _set_gauge(selectivity)

    sizes = np.asarray(index.list_sizes, np.int64)
    n_lists = int(index.n_lists)
    target = int(np.sort(sizes)[::-1][:n_probes].sum())
    target = min(target, total)
    cum = np.cumsum(np.sort(surv)[::-1])
    lists_pruned = int((surv == 0).sum())

    widen_max = _widen_max()
    level = max(lv for lv in LEVELS if lv <= widen_max)
    for lv in LEVELS:
        if lv > widen_max:
            break
        p = min(n_probes * lv, n_lists)
        if total == 0 or cum[p - 1] >= target:
            level = lv
            break
    eff = min(n_probes * level, n_lists)

    use_brute = _want_brute(family, index.size, index.dim, k, total,
                            selectivity)
    return FilterDecision(selectivity, total, level, eff, lists_pruned,
                          use_brute, surv_dev)


def decide_graph(filter, n: int, d: int, k: int,  # noqa: A002
                 family: str = "cagra") -> FilterDecision:
    """Measure + decide for a graph/dense family (eager-only). No lists
    to prune — the verdict is a widen level for the traversal's
    ``itopk`` (the survivor-reachability analog of probe mass: keep the
    frontier wide enough that survivor hits are not crowded out) plus
    the same crossover decision as the IVF path."""
    total = int(filter.count())
    selectivity = total / max(int(filter.n_bits), 1)
    _set_gauge(selectivity)

    widen_max = _widen_max()
    if selectivity >= 0.5:
        level = 1
    elif selectivity >= 0.1:
        level = 2
    elif selectivity >= 0.01:
        level = 4
    else:
        level = LEVELS[-1]
    level = min(level, max(lv for lv in LEVELS if lv <= widen_max))

    use_brute = _want_brute(family, n, d, k, total, selectivity)
    return FilterDecision(selectivity, total, level, 0, 0, use_brute)


def crossover(fd: FilterDecision, family: str, brute_fn: Callable[[], object],
              widened_fn: Callable[[], object]):
    """Run the compacted survivor-brute path behind its breaker, with the
    family's own widened scan as the bit-safe fallback. ``widened_fn``
    re-enters the family search; the thread-local skip flag keeps the
    re-entry from deciding crossover again (infinite recursion)."""
    try:
        from ..core import events as core_events

        core_events.record("filter_crossover", f"filter.{family}",
                           family=family, survivors=fd.survivors,
                           selectivity=round(fd.selectivity, 6))
    except Exception:  # noqa: BLE001 - telemetry must not break search
        pass

    def _widened():
        _local.skip = True
        try:
            return widened_fn()
        finally:
            _local.skip = False

    from .guarded import guarded_call

    return guarded_call("filter.survivor_brute", brute_fn, _widened)


def survivor_ids(filter) -> np.ndarray:  # noqa: A002
    """Host int64 array of surviving sample ids (set-bit positions),
    cached on the bitset object — bitset ops are functional (every
    mutation returns a new object), so identity-keyed caching is safe."""
    cached = getattr(filter, "_survivor_ids_cache", None)
    if cached is None:
        cached = np.nonzero(np.asarray(filter.to_mask()))[0].astype(np.int64)
        filter._survivor_ids_cache = cached
    return cached


def _physical_rows(index, src: np.ndarray) -> np.ndarray:
    """Map surviving source ids → physical storage rows via the cached
    inverse of ``index.source_ids`` (slack rows carry -1 and never
    enter the inverse)."""
    inv = getattr(index, "_source_inverse", None)
    if inv is None:
        sid = np.asarray(index.source_ids, np.int64)
        inv = np.full(int(index.size), -1, np.int64)
        pos = np.nonzero((sid >= 0) & (sid < index.size))[0]
        inv[sid[pos]] = pos
        index._source_inverse = inv
    return inv[src]


def _pad_to_k(d, i, k: int, bad):
    kk = d.shape[1]
    if kk < k:
        d = jnp.pad(d, ((0, 0), (0, k - kk)), constant_values=bad)
        i = jnp.pad(i, ((0, 0), (0, k - kk)), constant_values=-1)
    return d, i


def _brute_over(vecs, metric, queries, k: int, src: np.ndarray,
                metric_arg: float = 2.0):
    """Exact brute-force top-k over the compacted survivor rows, mapped
    back to original sample ids and padded to ``k`` with the family
    sentinel ((+inf, -1) min-close / (-inf, -1) inner-product)."""
    from ..distance.distance_types import DistanceType
    from ..neighbors import brute_force

    bad = (-jnp.inf if metric is DistanceType.InnerProduct else jnp.inf)
    m = queries.shape[0]
    n_surv = int(vecs.shape[0]) if vecs is not None else 0
    if n_surv == 0:
        return (jnp.full((m, k), bad, jnp.float32),
                jnp.full((m, k), -1, jnp.int32))
    sub = brute_force.build(vecs, metric, metric_arg)
    kk = min(k, n_surv)
    d, i = brute_force.search(sub, queries, kk)
    src_j = jnp.asarray(src, jnp.int32)
    i = jnp.where(i >= 0, src_j[jnp.maximum(i, 0)], -1)
    return _pad_to_k(d, i, k, bad)


def survivor_brute_ivf(index, reconstruct_fn, queries, k: int,
                       filter):  # noqa: A002
    """Compacted crossover for IVF families: gather the survivors'
    stored rows (``reconstruct_fn``: physical rows → f32 vectors — exact
    for ivf_flat, decode+back-rotate for ivf_pq) and brute-force the
    compacted set. Survivor bits with no stored row (never-added ids)
    are skipped — they could never be returned by any path."""
    src = survivor_ids(filter)
    src = src[src < int(index.size)]
    rows = _physical_rows(index, src)
    keep = rows >= 0
    src, rows = src[keep], rows[keep]
    vecs = (reconstruct_fn(index, jnp.asarray(rows, jnp.int32))
            if rows.size else None)
    return _brute_over(vecs, index.metric, queries, k, src)


def survivor_brute_dense(dataset, metric, queries, k: int,
                         filter, scales=None,  # noqa: A002
                         metric_arg: float = 2.0):
    """Compacted crossover for dense-storage families (cagra /
    brute_force): row id IS the sample id, so the gather needs no
    inverse map. ``scales`` dequantizes int8/bf16 stores on the fly."""
    from .quant import dequantize_rows

    src = survivor_ids(filter)
    src = src[src < dataset.shape[0]]
    if src.size == 0:
        vecs = None
    else:
        rows = jnp.asarray(src, jnp.int32)
        vecs = dequantize_rows(dataset[rows],
                               None if scales is None else scales[rows])
    return _brute_over(vecs, metric, queries, k, src, metric_arg)


def tune_crossover(family: str, n: int, d: int, k: int, selectivity: float,
                   scan_fn: Callable, brute_fn: Callable, *args,
                   reps: int = 3):
    """Race the widened scan vs the compacted brute under the
    selectivity-bucketed key (both closures must take ``*args`` and
    return device arrays); the recorded winner steers every later
    filtered call in the same bucket. Called from ``tune_search``-style
    warmup and the bench sweep lane — never from the hot path."""
    from . import autotune

    key = crossover_key(family, n, d, k, selectivity)
    winner, timings = autotune.tune_best(
        key, {"scan": scan_fn, "brute": brute_fn}, *args,
        reps=reps, force=True, value_read=True)
    return key, winner, timings
