"""Sparse primitives: analog of ``raft/sparse/``.

Reference inventory (SURVEY §2.8): COO/CSR containers + conversions
(sparse/convert/), linalg (degree/norm/spmm/sddmm/symmetrize/transpose),
ops (filter/reduce/row_op/slice/sort), sparse pairwise distances
(sparse/distance/distance.cuh:38), sparse brute-force kNN + kNN-graph
(sparse/neighbors/), Boruvka MST (sparse/solver/mst_solver.cuh) and
Lanczos (sparse/solver/lanczos.cuh).

TPU design: storage rides `jax.experimental.sparse.BCOO` (XLA's native
batched-COO, with TPU lowerings for dense@sparse) wrapped in RAFT-shaped
COO/CSR views; compute paths densify row *tiles* so the MXU does the
work — a sparse lane-by-lane scan is exactly what the MXU is bad at.
MST and dendrogram-building run host-side (pointer-chasing), like the
reference's host orchestration around its kernels.
"""
from .coo import COO
from .csr import CSR
from .linalg import degree, row_norm, sddmm, spmm, symmetrize, transpose
from .distance import pairwise_distance as sparse_pairwise_distance
from .neighbors import brute_force_knn as sparse_brute_force_knn
from .neighbors import cross_component_nn, knn_graph
from .op import coalesce, filter_entries, remove_zeros, row_op, sort_coo
from .solver import lanczos_smallest, mst

__all__ = [
    "COO", "CSR", "degree", "row_norm", "spmm", "sddmm", "symmetrize",
    "transpose", "sparse_pairwise_distance", "sparse_brute_force_knn",
    "knn_graph", "cross_component_nn", "mst", "lanczos_smallest",
    "filter_entries", "remove_zeros", "coalesce", "row_op", "sort_coo",
]
