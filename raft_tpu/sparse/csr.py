"""CSR container (raft/core/csr_matrix.hpp + sparse/convert/csr.cuh)."""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["CSR"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CSR:
    """Compressed-sparse-row matrix (indptr, indices, vals) + shape."""

    indptr: jax.Array    # (n_rows+1,) i32
    indices: jax.Array   # (nnz,) i32
    vals: jax.Array      # (nnz,) f32
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return self.vals.shape[0]

    def tree_flatten(self):
        return (self.indptr, self.indices, self.vals), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, aux[0])

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_dense(cls, dense) -> "CSR":
        from .coo import COO

        return COO.from_dense(dense).to_csr()

    @classmethod
    def from_scipy(cls, m) -> "CSR":
        m = m.tocsr()
        return cls(jnp.asarray(m.indptr, jnp.int32),
                   jnp.asarray(m.indices, jnp.int32),
                   jnp.asarray(m.data, jnp.float32), m.shape)

    # -- conversions -------------------------------------------------------
    def row_ids(self) -> jax.Array:
        """(nnz,) row of each stored element (csr_to_coo row expansion)."""
        ptr = np.asarray(self.indptr)
        return jnp.asarray(np.repeat(np.arange(self.shape[0]),
                                     np.diff(ptr)), jnp.int32)

    def to_coo(self):
        from .coo import COO

        return COO(self.row_ids(), self.indices, self.vals, self.shape)

    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.shape, self.vals.dtype)
        return out.at[self.row_ids(), self.indices].add(self.vals)

    def to_bcsr(self):
        from jax.experimental import sparse as jsparse

        return jsparse.BCSR((self.vals, self.indices, self.indptr),
                            shape=self.shape)

    def slice_rows(self, start: int, stop: int) -> "CSR":
        """Row-range slice (sparse/op/slice.cuh)."""
        ptr = np.asarray(self.indptr)
        lo, hi = int(ptr[start]), int(ptr[stop])
        return CSR(jnp.asarray(ptr[start : stop + 1] - lo, jnp.int32),
                   self.indices[lo:hi], self.vals[lo:hi],
                   (stop - start, self.shape[1]))
