"""Sparse linalg ops (raft/sparse/linalg/: degree, norm, spmm, sddmm,
symmetrize, transpose)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import hdot
from .coo import COO
from .csr import CSR

__all__ = ["degree", "row_norm", "spmm", "sddmm", "symmetrize", "transpose"]


def _as_coo(m) -> COO:
    return m.to_coo() if isinstance(m, CSR) else m


def degree(m) -> jax.Array:
    """Per-row stored-element count (sparse/linalg/degree.cuh)."""
    coo = _as_coo(m)
    return jnp.zeros((coo.shape[0],), jnp.int32).at[coo.rows].add(1)


def row_norm(m, norm: str = "l2") -> jax.Array:
    """Per-row norm over stored values (sparse/linalg/norm.cuh)."""
    coo = _as_coo(m)
    if norm == "l1":
        contrib = jnp.abs(coo.vals)
    elif norm == "l2":
        contrib = coo.vals * coo.vals
    elif norm == "linf":
        out = jnp.zeros((coo.shape[0],), coo.vals.dtype)
        return out.at[coo.rows].max(jnp.abs(coo.vals))
    else:
        raise ValueError(f"unknown norm {norm!r}")
    return jnp.zeros((coo.shape[0],), coo.vals.dtype).at[coo.rows].add(contrib)


def spmm(m, dense) -> jax.Array:
    """sparse (n, k) @ dense (k, d) → dense (n, d)
    (sparse/linalg/spmm.hpp). Scatter-add formulation: one gather of the
    dense rows + one segment add — XLA fuses both."""
    coo = _as_coo(m)
    dense = jnp.asarray(dense, jnp.float32)
    contrib = coo.vals[:, None] * dense[coo.cols]      # (nnz, d)
    out = jnp.zeros((coo.shape[0], dense.shape[1]), jnp.float32)
    return out.at[coo.rows].add(contrib)


def sddmm(a, b, mask) -> COO:
    """Sampled dense-dense matmul: (A @ B)[i,j] at stored positions of
    ``mask`` (sparse/linalg/sddmm.hpp)."""
    coo = _as_coo(mask)
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    vals = jnp.sum(a[coo.rows] * b.T[coo.cols], axis=1)
    return COO(coo.rows, coo.cols, vals, coo.shape)


def transpose(m) -> COO:
    """COO/CSR transpose (sparse/linalg/transpose.hpp)."""
    coo = _as_coo(m)
    return COO(coo.cols, coo.rows, coo.vals,
               (coo.shape[1], coo.shape[0])).sorted_by_row()


def symmetrize(m, op: str = "max") -> COO:
    """Symmetrize an adjacency: combine (i,j) and (j,i) stored values with
    ``op`` (sparse/linalg/symmetrize.cuh — the kNN-graph → undirected-graph
    step for single-linkage/UMAP-style pipelines)."""
    coo = _as_coo(m)
    n = max(coo.shape)
    # duplicate every edge in both directions, then reduce duplicates by key
    r = jnp.concatenate([coo.rows, coo.cols])
    c = jnp.concatenate([coo.cols, coo.rows])
    v = jnp.concatenate([coo.vals, coo.vals])
    by_col = jnp.argsort(c, stable=True)
    order = by_col[jnp.argsort(r[by_col], stable=True)]
    r, c, v = r[order], c[order], v[order]
    first = jnp.concatenate([jnp.ones((1,), bool),
                             (r[1:] != r[:-1]) | (c[1:] != c[:-1])])
    seg = jnp.cumsum(first) - 1
    n_seg = coo.nnz * 2
    if op == "max":
        red = jnp.full((n_seg,), -jnp.inf, v.dtype).at[seg].max(v)
    elif op == "add":
        red = jnp.zeros((n_seg,), v.dtype).at[seg].add(v)
    elif op == "mean":
        s = jnp.zeros((n_seg,), v.dtype).at[seg].add(v)
        cnt = jnp.zeros((n_seg,), v.dtype).at[seg].add(1.0)
        red = s / jnp.maximum(cnt, 1.0)
    else:
        raise ValueError(f"unknown op {op!r}")
    keep = first
    return COO(r[keep], c[keep],
               red[seg[keep]], (n, n))
