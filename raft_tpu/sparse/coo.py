"""COO container (raft/core/coo_matrix.hpp + sparse/convert/coo.cuh)."""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects

__all__ = ["COO"]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class COO:
    """Coordinate-format sparse matrix (rows, cols, vals) + shape."""

    rows: jax.Array      # (nnz,) i32
    cols: jax.Array      # (nnz,) i32
    vals: jax.Array      # (nnz,) f32
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return self.vals.shape[0]

    def tree_flatten(self):
        return (self.rows, self.cols, self.vals), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, aux[0])

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_dense(cls, dense) -> "COO":
        d = np.asarray(dense)
        r, c = np.nonzero(d)
        return cls(jnp.asarray(r, jnp.int32), jnp.asarray(c, jnp.int32),
                   jnp.asarray(d[r, c], jnp.float32), d.shape)

    @classmethod
    def from_scipy(cls, m) -> "COO":
        m = m.tocoo()
        return cls(jnp.asarray(m.row, jnp.int32),
                   jnp.asarray(m.col, jnp.int32),
                   jnp.asarray(m.data, jnp.float32), m.shape)

    # -- conversions -------------------------------------------------------
    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.shape, self.vals.dtype)
        return out.at[self.rows, self.cols].add(self.vals)

    def _row_major_order(self) -> jax.Array:
        """Stable (row, col) ordering without an n*r+c key (which overflows
        int32 past ~46k rows): two stable argsorts."""
        by_col = jnp.argsort(self.cols, stable=True)
        return by_col[jnp.argsort(self.rows[by_col], stable=True)]

    def to_csr(self):
        from .csr import CSR

        order = self._row_major_order()
        counts = jnp.zeros((self.shape[0] + 1,), jnp.int32).at[
            self.rows[order] + 1].add(1)
        return CSR(jnp.cumsum(counts).astype(jnp.int32),
                   self.cols[order], self.vals[order], self.shape)

    def to_bcoo(self):
        from jax.experimental import sparse as jsparse

        idx = jnp.stack([self.rows, self.cols], axis=1)
        return jsparse.BCOO((self.vals, idx), shape=self.shape)

    def sorted_by_row(self) -> "COO":
        order = self._row_major_order()
        return COO(self.rows[order], self.cols[order], self.vals[order],
                   self.shape)
