"""Sparse solvers: Boruvka MST (sparse/solver/mst_solver.cuh) and a
Lanczos eigensolver (sparse/solver/lanczos.cuh)."""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects
from .coo import COO
from .csr import CSR
from .linalg import spmm

__all__ = ["mst", "lanczos_smallest"]


def mst(graph, symmetrize_input: bool = True
        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Minimum spanning forest of a weighted undirected graph →
    (src, dst, weight) edge arrays, |V|-components edges.

    Boruvka rounds (mst_solver.cuh): every component claims its minimum
    outgoing edge, claimed edges merge components; O(log V) rounds. Runs
    host-side in vectorized numpy — the union-find is pointer-chasing the
    TPU has no business doing, exactly why the reference keeps MST in its
    own solver.
    """
    coo = graph.to_coo() if isinstance(graph, CSR) else graph
    if symmetrize_input:
        from .linalg import symmetrize

        coo = symmetrize(coo, op="max")
    src = np.asarray(coo.rows, np.int64)
    dst = np.asarray(coo.cols, np.int64)
    w = np.asarray(coo.vals, np.float64)
    keep = src != dst
    src, dst, w = src[keep], dst[keep], w[keep]
    n = coo.shape[0]

    comp = np.arange(n)
    out_s, out_d, out_w = [], [], []

    def find_root(comp):
        # full path compression by repeated pointer jumping
        while True:
            nxt = comp[comp]
            if (nxt == comp).all():
                return comp
            comp = nxt

    for _ in range(64):  # ≥ log2(n) rounds always suffice
        cs, cd = comp[src], comp[dst]
        live = cs != cd
        if not live.any():
            break
        ls, ld, lw = cs[live], cd[live], w[live]
        eid = np.nonzero(live)[0]
        # min outgoing edge per component (consider both endpoints); weight
        # ties break on global edge id — the standard Boruvka tie-break that
        # keeps the union of picks acyclic
        allc = np.concatenate([ls, ld])
        alle = np.concatenate([eid, eid])
        allw = np.concatenate([lw, lw])
        order = np.lexsort((alle, allw, allc))
        first = np.concatenate([[True], allc[order][1:] != allc[order][:-1]])
        pick = np.unique(alle[order][first])
        # merge: point the larger root at the smaller for each picked edge;
        # several merges may hit one root — min-scatter then re-root
        a, b = comp[src[pick]], comp[dst[pick]]
        lo, hi = np.minimum(a, b), np.maximum(a, b)
        parent = np.arange(n)
        np.minimum.at(parent, hi, lo)
        comp = find_root(parent[comp])
        out_s.append(src[pick])
        out_d.append(dst[pick])
        out_w.append(w[pick])
    s = np.concatenate(out_s) if out_s else np.empty(0, np.int64)
    d = np.concatenate(out_d) if out_d else np.empty(0, np.int64)
    ww = np.concatenate(out_w) if out_w else np.empty(0, np.float64)
    # Kruskal filter over the O(n log n) candidates: simultaneous scatter
    # merges above can drop a merge, so the raw picks may contain a cycle —
    # a final union-find pass guarantees a forest with the same min weight
    order = np.argsort(ww, kind="stable")
    parent = np.arange(n)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = int(parent[x])
        return x

    ks, kd, kw = [], [], []
    for e in order:
        ra, rb = find(int(s[e])), find(int(d[e]))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
            ks.append(int(s[e]))
            kd.append(int(d[e]))
            kw.append(float(ww[e]))
    return (np.asarray(ks, np.int32), np.asarray(kd, np.int32),
            np.asarray(kw, np.float32))


def lanczos_smallest(a, k: int, n_iter: int = 0, seed: int = 0
                     ) -> Tuple[jax.Array, jax.Array]:
    """k smallest eigenpairs of a symmetric sparse matrix →
    (eigenvalues (k,), eigenvectors (n, k)).

    The lanczos.cuh solver role. A single-vector Lanczos chain cannot
    separate a degenerate eigenvalue (e.g. the q zero modes of a
    q-component graph Laplacian reach the chain through one direction of
    its start vector), so the solver is a *block* Krylov method — LOBPCG,
    whose block inner products are batched matmats (the MXU-friendly
    shape) — with a dense fallback for small problems.
    """
    coo = a.to_coo() if isinstance(a, CSR) else a
    n = coo.shape[0]
    expects(0 < k < n, "bad k=%d for n=%d", k, n)

    if n <= 512:
        dense = np.asarray(coo.to_dense(), np.float64)
        evals, evecs = np.linalg.eigh(dense)
        return (jnp.asarray(evals[:k].astype(np.float32)),
                jnp.asarray(evecs[:, :k].astype(np.float32)))

    import scipy.sparse as sp
    from scipy.sparse.linalg import lobpcg

    mat = sp.coo_matrix(
        (np.asarray(coo.vals, np.float64),
         (np.asarray(coo.rows), np.asarray(coo.cols))),
        shape=coo.shape).tocsr()
    rng = np.random.default_rng(seed)
    x0 = rng.standard_normal((n, k)).astype(np.float64)
    evals, evecs = lobpcg(mat, x0, largest=False, tol=1e-8,
                          maxiter=n_iter or 500)
    order = np.argsort(evals)
    return (jnp.asarray(evals[order].astype(np.float32)),
            jnp.asarray(evecs[:, order].astype(np.float32)))
