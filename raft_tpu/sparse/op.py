"""Sparse element/structure ops: analog of ``raft/sparse/op/``.

Reference surface: filter (drop entries, sparse/op/filter.cuh), reduce
(coalesce duplicate coordinates, sparse/op/reduce.cuh), row_op (per-row
transform, sparse/op/row_op.cuh), sort (canonical row-major entry order,
sparse/op/sort.cuh), slice (sparse/op/slice.cuh — lives as
``CSR.slice_rows``).

TPU design note: entry lists are dense 1-D arrays, so every op here is a
sort/segment/mask composition — no scalar loops. Ops that change nnz
(``filter_entries``, ``coalesce``) return host-sized results and are
host-eager (nnz is a *shape*, necessarily static under jit); callers
inside jit should filter by writing explicit zeros instead.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .coo import COO

__all__ = ["filter_entries", "remove_zeros", "coalesce", "row_op", "sort_coo"]


def filter_entries(m: COO, keep: Callable[[jax.Array, jax.Array, jax.Array],
                                          jax.Array]) -> COO:
    """Keep entries where ``keep(rows, cols, vals)`` is True
    (sparse/op/filter.cuh). Changes nnz → host-eager."""
    mask = np.asarray(keep(m.rows, m.cols, m.vals))
    rows = np.asarray(m.rows)[mask]
    cols = np.asarray(m.cols)[mask]
    vals = np.asarray(m.vals)[mask]
    return COO(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals),
               m.shape)


def remove_zeros(m: COO, eps: float = 0.0) -> COO:
    """Drop |val| <= eps entries (the reference's remove_zeros filter)."""
    return filter_entries(m, lambda r, c, v: jnp.abs(v) > eps)


def coalesce(m: COO, op: str = "add") -> COO:
    """Merge duplicate (row, col) entries (sparse/op/reduce.cuh
    max_duplicates): sort by coordinate, segment-reduce runs.
    op: "add" | "max" | "min"."""
    key = np.asarray(m.rows).astype(np.int64) * m.shape[1] + np.asarray(m.cols)
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    uniq, seg = np.unique(key_s, return_inverse=True)
    vals_s = jnp.take(m.vals, jnp.asarray(order))
    seg_j = jnp.asarray(seg)
    if op == "add":
        vals = jax.ops.segment_sum(vals_s, seg_j, num_segments=len(uniq))
    elif op == "max":
        vals = jax.ops.segment_max(vals_s, seg_j, num_segments=len(uniq))
    elif op == "min":
        vals = jax.ops.segment_min(vals_s, seg_j, num_segments=len(uniq))
    else:
        raise ValueError(f"unknown coalesce op {op!r}")
    rows = jnp.asarray((uniq // m.shape[1]).astype(np.int32))
    cols = jnp.asarray((uniq % m.shape[1]).astype(np.int32))
    return COO(rows, cols, vals, m.shape)


def row_op(m: COO, fn: Callable[[jax.Array, jax.Array], jax.Array]) -> COO:
    """Apply ``fn(vals, row_ids)`` per entry with its row id available
    (sparse/op/row_op.cuh — e.g. row scaling/softmax-style transforms).
    jit-safe: nnz unchanged."""
    return COO(m.rows, m.cols, fn(m.vals, m.rows), m.shape)


def sort_coo(m: COO) -> COO:
    """Canonical row-major entry order (sparse/op/sort.cuh). Two stable
    argsorts, not an n*r+c key — int64 keys truncate with x64 disabled."""
    return m.sorted_by_row()
