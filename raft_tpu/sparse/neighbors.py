"""Sparse brute-force kNN + kNN-graph (raft/sparse/neighbors/:
brute_force_knn, knn_graph construction for connectivities)."""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects
from ..distance.distance_types import canonical_metric, is_min_close
from ..matrix.select_k import select_k
from .coo import COO
from .csr import CSR
from .distance import pairwise_distance

__all__ = ["brute_force_knn", "knn_graph"]


def brute_force_knn(x: CSR, y: CSR, k: int, metric="sqeuclidean",
                    tile_rows: int = 2048) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN of each x row among y rows (sparse brute_force_knn.cuh):
    streaming row tiles of the sparse distance + per-tile select_k."""
    expects(0 < k <= y.shape[0], "bad k")
    mt = canonical_metric(metric)
    select_min = is_min_close(mt)
    outs_d, outs_i = [], []
    for r0 in range(0, x.shape[0], tile_rows):
        r1 = min(r0 + tile_rows, x.shape[0])
        d = pairwise_distance(x.slice_rows(r0, r1), y, mt)
        dv, di = select_k(d, k, select_min=select_min)
        outs_d.append(dv)
        outs_i.append(di)
    return jnp.concatenate(outs_d), jnp.concatenate(outs_i)


def knn_graph(x: CSR, k: int, metric="sqeuclidean") -> COO:
    """Symmetric kNN connectivity graph (sparse/neighbors/knn_graph.cuh):
    kNN per row (self removed) → COO with distance values, symmetrized."""
    from .linalg import symmetrize

    n = x.shape[0]
    d, i = brute_force_knn(x, x, min(k + 1, n), metric)
    d, i = np.asarray(d), np.asarray(i)
    rows = np.repeat(np.arange(n, dtype=np.int32), i.shape[1])
    cols = i.reshape(-1)
    vals = d.reshape(-1).astype(np.float32)
    keep = cols != rows          # drop self edges
    coo = COO(jnp.asarray(rows[keep]), jnp.asarray(cols[keep]),
              jnp.asarray(vals[keep]), (n, n))
    return symmetrize(coo, op="max")
