"""Sparse brute-force kNN + kNN-graph (raft/sparse/neighbors/:
brute_force_knn, knn_graph construction for connectivities)."""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects
from ..distance.distance_types import canonical_metric, is_min_close
from ..matrix.select_k import select_k
from .coo import COO
from .csr import CSR
from .distance import pairwise_distance

__all__ = ["brute_force_knn", "knn_graph", "cross_component_nn"]


def brute_force_knn(x: CSR, y: CSR, k: int, metric="sqeuclidean",
                    tile_rows: int = 2048) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN of each x row among y rows (sparse brute_force_knn.cuh):
    streaming row tiles of the sparse distance + per-tile select_k."""
    expects(0 < k <= y.shape[0], "bad k")
    mt = canonical_metric(metric)
    select_min = is_min_close(mt)
    outs_d, outs_i = [], []
    for r0 in range(0, x.shape[0], tile_rows):
        r1 = min(r0 + tile_rows, x.shape[0])
        d = pairwise_distance(x.slice_rows(r0, r1), y, mt)
        dv, di = select_k(d, k, select_min=select_min)
        outs_d.append(dv)
        outs_i.append(di)
    return jnp.concatenate(outs_d), jnp.concatenate(outs_i)


def cross_component_nn(x, labels, tile_rows: int = 4096):
    """Nearest neighbor of every point in a *different* component
    (role of sparse/neighbors/cross_component_nn.cuh, the
    FixConnectivitiesRedOp engine behind connect_components): one masked
    tiled L2 scan instead of a per-component search loop.

    ``x``: (n, d) dense rows or a CSR (densified up front — the left
    operand of every tile's matmul needs all rows; ``tile_rows`` bounds
    only the (n, tile) distance block);
    ``labels``: (n,) component id per point (any integer coloring).
    Returns (dists (n,) squared L2, idx (n,)) — idx = -1 when a point's
    component spans the whole set.
    """
    dense = x.to_dense() if isinstance(x, CSR) else jnp.asarray(x, jnp.float32)
    labels = jnp.asarray(labels)
    n = dense.shape[0]
    norms = jnp.sum(dense * dense, axis=1)
    n_pad = -(-n // tile_rows) * tile_rows
    xp = jnp.pad(dense, ((0, n_pad - n), (0, 0)))
    np_norms = jnp.pad(norms, (0, n_pad - n))
    lp = jnp.pad(labels, (0, n_pad - n), constant_values=-1)
    tiles = n_pad // tile_rows

    def step(carry, inp):
        best_d, best_i = carry
        xt, nt, lt, base = inp
        cross = jnp.matmul(dense, xt.T, precision="highest")
        d = jnp.maximum(norms[:, None] + nt[None, :] - 2.0 * cross, 0.0)
        bad = (labels[:, None] == lt[None, :]) | (lt[None, :] < 0)
        d = jnp.where(bad, jnp.inf, d)
        tmin = jnp.min(d, axis=1)
        targ = jnp.argmin(d, axis=1) + base
        better = tmin < best_d
        return (jnp.where(better, tmin, best_d),
                jnp.where(better, targ, best_i)), None

    init = (jnp.full((n,), jnp.inf, jnp.float32),
            jnp.full((n,), -1, jnp.int32))
    xs = (xp.reshape(tiles, tile_rows, -1),
          np_norms.reshape(tiles, tile_rows),
          lp.reshape(tiles, tile_rows),
          jnp.arange(tiles, dtype=jnp.int32) * tile_rows)
    (d, i), _ = jax.lax.scan(step, init, xs)
    return d, i


def knn_graph(x: CSR, k: int, metric="sqeuclidean") -> COO:
    """Symmetric kNN connectivity graph (sparse/neighbors/knn_graph.cuh):
    kNN per row (self removed) → COO with distance values, symmetrized."""
    from .linalg import symmetrize

    n = x.shape[0]
    d, i = brute_force_knn(x, x, min(k + 1, n), metric)
    d, i = np.asarray(d), np.asarray(i)
    rows = np.repeat(np.arange(n, dtype=np.int32), i.shape[1])
    cols = i.reshape(-1)
    vals = d.reshape(-1).astype(np.float32)
    keep = cols != rows          # drop self edges
    coo = COO(jnp.asarray(rows[keep]), jnp.asarray(cols[keep]),
              jnp.asarray(vals[keep]), (n, n))
    return symmetrize(coo, op="max")
