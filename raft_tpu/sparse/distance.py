"""Sparse pairwise distances (raft/sparse/distance/distance.cuh:38).

Supported metric set mirrors the reference's sparse list: L2
(expanded/sqrt), inner product, cosine, L1, Linf, Canberra, Hamming,
Jaccard, Hellinger, Jensen-Shannon, KL-divergence, Dice, Correlation,
Russel-Rao.

TPU design: the x side is densified in row tiles (the MXU wants dense
tiles; a CSR-by-CSR lane scan is the anti-pattern here) and the y side is
kept dense per tile too — sparse inputs buy *memory*, not FLOPs, on TPU.
Expanded metrics (L2/IP/cosine) use spmm cross-terms so the (m, n) block
is one GEMM; elementwise metrics map over densified tiles.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import expects
from ..distance.distance_types import DistanceType, canonical_metric
from ..distance.pairwise import pairwise_distance as dense_pairwise
from .csr import CSR

__all__ = ["pairwise_distance", "SUPPORTED_METRICS"]

SUPPORTED_METRICS = (
    DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
    DistanceType.InnerProduct, DistanceType.CosineExpanded,
    DistanceType.L1, DistanceType.Linf, DistanceType.Canberra,
    DistanceType.LpUnexpanded, DistanceType.BrayCurtis,
    DistanceType.HammingUnexpanded, DistanceType.JaccardExpanded,
    DistanceType.HellingerExpanded, DistanceType.JensenShannon,
    DistanceType.KLDivergence, DistanceType.DiceExpanded,
    DistanceType.CorrelationExpanded, DistanceType.RusselRaoExpanded,
)


def _jaccard(x, y):
    """Jaccard over nonzero supports (sparse semantics: set similarity)."""
    xb = (x != 0).astype(jnp.float32)
    yb = (y != 0).astype(jnp.float32)
    inter = xb @ yb.T
    union = jnp.sum(xb, 1)[:, None] + jnp.sum(yb, 1)[None, :] - inter
    return 1.0 - inter / jnp.maximum(union, 1.0)


def _dice(x, y):
    xb = (x != 0).astype(jnp.float32)
    yb = (y != 0).astype(jnp.float32)
    inter = xb @ yb.T
    denom = jnp.sum(xb, 1)[:, None] + jnp.sum(yb, 1)[None, :]
    return 1.0 - 2.0 * inter / jnp.maximum(denom, 1.0)


def pairwise_distance(x: CSR, y: CSR, metric="sqeuclidean",
                      tile_rows: int = 2048,
                      metric_arg: float = 2.0) -> jax.Array:
    """(m, n) distances between CSR row sets (distance.cuh:38 API).
    ``metric_arg`` is the Minkowski p for LpUnexpanded."""
    expects(isinstance(x, CSR) and isinstance(y, CSR),
            "sparse pairwise_distance takes CSR inputs")
    expects(x.shape[1] == y.shape[1], "dim mismatch %s vs %s",
            x.shape, y.shape)
    mt = canonical_metric(metric)
    expects(mt in SUPPORTED_METRICS,
            "metric %s unsupported for sparse inputs", mt.name)

    y_dense = y.to_dense()
    m = x.shape[0]
    outs = []
    for r0 in range(0, m, tile_rows):
        r1 = min(r0 + tile_rows, m)
        xt = x.slice_rows(r0, r1).to_dense()
        if mt is DistanceType.JaccardExpanded:
            outs.append(_jaccard(xt, y_dense))
        elif mt is DistanceType.DiceExpanded:
            outs.append(_dice(xt, y_dense))
        else:
            outs.append(dense_pairwise(xt, y_dense, mt, metric_arg))
    return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
