"""Pass 1: the Pallas kernel registry and its jaxpr-structural audits.

Every ``pl.pallas_call`` site in the library is registered here with one
or more *variants* — representative (storage dtype, mode, shape)
configurations traced through :func:`jax.make_jaxpr`. Tracing is
abstract evaluation: no compile, no device, CPU-cheap — but the traced
``pallas_call`` equation exposes exactly the structure Mosaic will see
(block mappings with memory spaces, scratch avals, the kernel jaxpr),
so the checks run against the real program, not a hand-maintained
shadow spec. An unregistered new kernel fails the registry drift guard
in ``tests/test_analysis.py``.

Checks per traced site (rules; docs/analysis.md has the incident log):

* ``vmem-budget`` — VMEM footprint derived from the VMEM block mappings
  (×2: the grid pipeline double-buffers streamed blocks) plus VMEM
  scratch, against the tightest per-generation budget × an occupancy
  cap that leaves headroom for the temporaries Mosaic keeps live.
* ``lane-misaligned`` / ``sublane-misaligned`` — last dim of a VMEM
  block/scratch must be a 128 multiple, second-to-last a dtype-dependent
  sublane multiple (f32 8 / bf16 16 / int8 32); size-1 dims are exempt
  (scalar rows/columns lower through broadcasts, not tiles).
* ``fragile-repeat`` — ``pltpu.repeat`` inside a kernel body: its
  interpret-mode semantics are ELEMENT-wise (``np.repeat``) on this jax
  while Mosaic tiles (``np.tile``) — the divergence behind the xfailed
  ivf_pq ``pq_bits=4`` int8-LUT test. Any use must be re-verified on
  real TPU before trust.
* ``fragile-reshape`` — an in-kernel reshape that changes the lane
  (minor) dim at sub-128 granularity: the relayout Mosaic handles least
  reliably (the reason graph_expand routes queries with a one-hot
  matmul instead).
* ``dma-unwaited`` — more ``dma_start`` than ``dma_wait`` equations: a
  started async (remote) copy some path never waits on.
* ``sem-unpaired`` — a REGULAR (non-DMA) semaphore that is signaled but
  never waited, or waited but never signaled, in the kernel body (the
  ring kernel's credit/barrier discipline).
"""
from __future__ import annotations

import dataclasses
import functools
import math
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

from . import Finding

# ---------------------------------------------------------------------------
# budgets and tiling constants (pallas guide: ~16 MB VMEM/core; min tile
# sublane×lane by dtype: f32 (8,128), bf16 (16,128), int8 (32,128))
# ---------------------------------------------------------------------------

VMEM_BUDGETS_BYTES: Dict[str, int] = {
    "v4": 16 << 20,
    "v5e": 16 << 20,
    "v5p": 16 << 20,
}
# fraction of the budget a single kernel's declared working set may
# claim: Mosaic keeps fold/concat temporaries live beyond the declared
# blocks (the reason cagra_fused budgets 8 MB of 16)
VMEM_OCCUPANCY = 0.75

_SUBLANE = {4: 8, 2: 16, 1: 32}
_LANE = 128

_CALL_RE = re.compile(r"pl\.pallas_call\(")

# primitives considered host-callback-free kernel internals; anything in
# this set inside a kernel body is a fragility finding
_REPEAT_PRIMS = {"repeat"}


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class KernelSite:
    """One literal ``pl.pallas_call`` site. ``occurrence`` is its 0-based
    index among the file's ``pl.pallas_call(`` matches (for line
    anchoring); ``variants`` maps variant name → zero-arg builder
    returning ``(fn, args)`` for :func:`jax.make_jaxpr`, or ``None``
    when the variant cannot trace in this process (reported as skipped,
    never silently dropped)."""

    name: str
    path: str
    occurrence: int
    variants: Tuple[Tuple[str, Callable], ...]


def _v_fused_knn(dtype: str):
    def build():
        import jax.numpy as jnp

        from ..ops.fused_knn import fused_knn

        m, n, d, k = 512, 2048, 128, 64
        q = jnp.zeros((m, d), jnp.float32)
        if dtype == "int4":
            data = jnp.zeros((n, d // 2), jnp.int8)
            return (functools.partial(fused_knn, k=k, interpret=True,
                                      int4_dim=d),
                    (q, data, ),
                    {"scales": jnp.ones((n,), jnp.float32)})
        if dtype == "int8":
            data = jnp.zeros((n, d), jnp.int8)
            return (functools.partial(fused_knn, k=k, interpret=True),
                    (q, data),
                    {"scales": jnp.ones((n,), jnp.float32)})
        kw = {}
        if dtype == "f32_pen":
            kw["penalty"] = jnp.zeros((n,), jnp.float32)
        data = jnp.zeros((n, d),
                         jnp.bfloat16 if dtype == "bf16" else jnp.float32)
        return (functools.partial(fused_knn, k=k, interpret=True, **kw),
                (q, data), {})
    return build


def _v_select_k():
    import jax.numpy as jnp

    from ..matrix.select_k import _kpass_2d

    vals = jnp.zeros((512, 4096), jnp.float32)
    return (lambda v: _kpass_2d(v, 64, True), (vals,), {})


def _v_ivf_flat(flavor: str):
    def build():
        import jax.numpy as jnp

        from ..ops.ivf_scan import ivf_flat_scan

        n, d, L, m, p, lmax, k = 1024, 128, 8, 128, 4, 256, 32
        data = jnp.zeros(
            (n, d), jnp.int8 if flavor == "int8_pen" else jnp.float32)
        norms = jnp.zeros((n,), jnp.float32)
        probed = jnp.zeros((m, p), jnp.int32)
        offsets = jnp.arange(L, dtype=jnp.int32) * (n // L)
        sizes = jnp.full((L,), n // L, jnp.int32)
        q = jnp.zeros((m, d), jnp.float32)
        kw = {"interpret": True}
        if flavor == "int8_pen":
            kw["penalty"] = jnp.zeros((n,), jnp.float32)
            kw["scales"] = jnp.ones((n,), jnp.float32)
        return (functools.partial(ivf_flat_scan, k=k, lmax=lmax, **kw),
                (data, norms, probed, offsets, sizes, q), {})
    return build


def _v_ivf_pq(lut: str):
    def build():
        import jax.numpy as jnp

        from ..ops.ivf_pq_scan import ivf_pq_scan, make_cb_matrix

        n, pq_dim, book, pq_len = 1024, 32, 256, 4
        L, m, p, lmax, k = 8, 128, 4, 256, 32
        rot_dim = pq_dim * pq_len
        codes = jnp.zeros((n, pq_dim), jnp.uint8)
        norms = jnp.zeros((n,), jnp.float32)
        centers = jnp.zeros((L, rot_dim), jnp.float32)
        cbm = make_cb_matrix(jnp.zeros((pq_dim, book, pq_len), jnp.float32))
        probed = jnp.zeros((m, p), jnp.int32)
        offsets = jnp.arange(L, dtype=jnp.int32) * (n // L)
        sizes = jnp.full((L,), n // L, jnp.int32)
        q = jnp.zeros((m, rot_dim), jnp.float32)
        kw = {}
        mode = lut
        if lut == "f32_pen":
            mode = "f32"
            kw["penalty"] = jnp.zeros((n,), jnp.float32)
        return (functools.partial(ivf_pq_scan, k=k, lmax=lmax,
                                  pq_dim=pq_dim, book=book, lut_mode=mode,
                                  interpret=True, **kw),
                (codes, norms, centers, cbm, probed, offsets, sizes, q), {})
    return build


def _v_graph_expand(mode: str):
    def build():
        import jax.numpy as jnp

        from ..ops.graph_expand import graph_expand

        m, width, n, deg_p, d, k_out = 64, 2, 1024, 64, 128, 32
        parents = jnp.zeros((m, width), jnp.int32)
        q = jnp.zeros((m, d), jnp.float32)
        aux = jnp.zeros((n, 2, deg_p), jnp.float32)
        kw: dict = {"mode": mode, "interpret": True}
        if mode == "int4":
            vecs = jnp.zeros((n, deg_p, d // 2), jnp.int8)
        elif mode == "pq":
            pq_dim, book = 16, 256
            vecs = jnp.zeros((n, deg_p, pq_dim), jnp.uint8)
            kw["cbm"] = jnp.zeros((pq_dim * book, d), jnp.int8)
            kw["cb_scale"] = jnp.ones((1, d), jnp.float32)
        else:
            vecs = jnp.zeros((n, deg_p, d), jnp.int8)
            if mode == "dense_pen":
                kw = {"mode": "dense", "interpret": True,
                      "pen": jnp.zeros((n, deg_p), jnp.float32)}
        return (functools.partial(graph_expand, k_out=k_out, **kw),
                (parents, q, vecs, aux), {})
    return build


def _v_cagra_fused(mode: str):
    def build():
        import jax.numpy as jnp

        from ..ops.cagra_fused import fused_traverse

        m, n, deg_p, d, itopk, width, kprime = 32, 1024, 64, 128, 64, 2, 32
        q = jnp.zeros((m, d), jnp.float32)
        bd = jnp.zeros((m, itopk), jnp.float32)
        bi = jnp.zeros((m, itopk), jnp.int32)
        aux = jnp.zeros((n, 2, deg_p), jnp.float32)
        gph = jnp.zeros((n, deg_p), jnp.int32)
        kw: dict = {"itopk": itopk, "width": width, "max_iter": 2,
                    "kprime": kprime, "degree": deg_p, "interpret": True}
        if mode == "int4":
            vecs = jnp.zeros((n, deg_p, d // 2), jnp.int8)
            kw["mode"] = "int4"
        else:
            vecs = jnp.zeros((n, deg_p, d), jnp.int8)
            if mode == "pen":
                kw["pen"] = jnp.zeros((n, deg_p), jnp.float32)
        return (functools.partial(fused_traverse, **kw),
                (q, bd, bi, vecs, aux, gph), {})
    return build


def _v_merge_step():
    import jax.numpy as jnp

    from ..ops.ring_topk import merge_step

    m, k = 64, 64
    args = (jnp.zeros((m, k), jnp.float32), jnp.zeros((m, k), jnp.int32),
            jnp.zeros((m, k), jnp.int32), jnp.zeros((m, k), jnp.float32),
            jnp.zeros((m, k), jnp.int32), jnp.zeros((m, k), jnp.int32))
    return (functools.partial(merge_step, k=k, engine="pallas",
                              interpret=True), args, {})


def _v_ring_pallas():
    """The remote-DMA ring kernel, traced (never run) under shard_map on
    the CPU mesh — remote DMA has no interpret emulation on this jax,
    but abstract tracing exposes the full DMA/semaphore structure."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..ops import ring_topk
    from ..utils import shard_map_compat

    devs = jax.devices()
    if len(devs) < 2:
        return None
    p = min(4, len(devs))
    mesh = Mesh(np.array(devs[:p]), ("shard",))
    m, k = 64, 128

    def body(d, g):
        return ring_topk._ring_pallas(d[0], g[0], k, True, "shard", p)

    fn = shard_map_compat(body, mesh=mesh,
                          in_specs=(P("shard", None, None),) * 2,
                          out_specs=(P(), P()), check=False)
    return (fn, (jnp.zeros((p, m, k), jnp.float32),
                 jnp.zeros((p, m, k), jnp.int32)), {})


SITES: Tuple[KernelSite, ...] = (
    KernelSite("fused_knn", "raft_tpu/ops/fused_knn.py", 0, (
        ("f32", _v_fused_knn("f32")),
        ("bf16", _v_fused_knn("bf16")),
        ("int8", _v_fused_knn("int8")),
        ("int4", _v_fused_knn("int4")),
        ("f32_pen", _v_fused_knn("f32_pen")),
    )),
    KernelSite("select_k.kpass", "raft_tpu/matrix/select_k.py", 0, (
        ("f32", _v_select_k),
    )),
    KernelSite("ivf_flat.scan", "raft_tpu/ops/ivf_scan.py", 0, (
        ("f32", _v_ivf_flat("f32")),
        ("int8_pen", _v_ivf_flat("int8_pen")),
    )),
    KernelSite("ivf_pq.scan", "raft_tpu/ops/ivf_pq_scan.py", 0, (
        ("f32", _v_ivf_pq("f32")),
        ("bf16", _v_ivf_pq("bf16")),
        ("int8", _v_ivf_pq("int8")),
        ("f32_pen", _v_ivf_pq("f32_pen")),
    )),
    KernelSite("cagra.graph_expand", "raft_tpu/ops/graph_expand.py", 0, (
        ("dense", _v_graph_expand("dense")),
        ("dense_pen", _v_graph_expand("dense_pen")),
        ("int4", _v_graph_expand("int4")),
        ("pq", _v_graph_expand("pq")),
    )),
    KernelSite("cagra.fused_search", "raft_tpu/ops/cagra_fused.py", 0, (
        ("dense", _v_cagra_fused("dense")),
        ("pen", _v_cagra_fused("pen")),
        ("int4", _v_cagra_fused("int4")),
    )),
    KernelSite("ring_topk.merge_step", "raft_tpu/ops/ring_topk.py", 0, (
        ("fold", _v_merge_step),
    )),
    KernelSite("ring_topk.ring_pallas", "raft_tpu/ops/ring_topk.py", 1, (
        ("remote_dma", _v_ring_pallas),
    )),
)


def registered_counts() -> Dict[str, int]:
    """path → number of registered literal ``pl.pallas_call`` sites (the
    drift guard compares this against the source grep)."""
    out: Dict[str, int] = {}
    for s in SITES:
        out[s.path] = max(out.get(s.path, 0), s.occurrence + 1)
    return out


def pallas_call_sites(root: str) -> Dict[str, int]:
    """Source grep: path → count of literal ``pl.pallas_call(`` call
    sites under ``raft_tpu/`` (comment/docstring mentions don't match
    the call regex)."""
    out: Dict[str, int] = {}
    pkg = os.path.join(root, "raft_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        if "analysis" in os.path.relpath(dirpath, pkg).split(os.sep):
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            with open(full) as f:
                n = len(_CALL_RE.findall(f.read()))
            if n:
                out[os.path.relpath(full, root)] = n
    return out


def site_line(root: str, site: KernelSite) -> int:
    """Line of the site's literal ``pl.pallas_call(`` (best effort)."""
    try:
        with open(os.path.join(root, site.path)) as f:
            lines = f.read().splitlines()
    except OSError:
        return 0
    hits = [i for i, t in enumerate(lines, 1) if _CALL_RE.search(t)]
    return hits[site.occurrence] if site.occurrence < len(hits) else 0


# ---------------------------------------------------------------------------
# jaxpr introspection
# ---------------------------------------------------------------------------

def _subjaxprs(params):
    import jax

    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if isinstance(x, jax.core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, jax.core.Jaxpr):
                yield x


def _walk_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _subjaxprs(eqn.params):
            yield from _walk_eqns(sub)


def pallas_eqns(closed_jaxpr) -> list:
    return [e for e in _walk_eqns(closed_jaxpr.jaxpr)
            if e.primitive.name == "pallas_call"]


def _aval_of(ref_aval):
    inner = getattr(ref_aval, "inner_aval", ref_aval)
    return inner


def _memspace(ref_aval) -> str:
    return str(getattr(ref_aval, "memory_space", "") or "")


def _is_vmem(ref_aval) -> bool:
    """A ref that lives in VMEM: explicit vmem, or the default (None)
    memory space — which lowers to VMEM on TPU. Excludes ANY (HBM),
    SMEM and semaphore refs."""
    ms = _memspace(ref_aval).lower()
    return ms in ("", "none") or "vmem" in ms


def _is_semaphore(ref_aval) -> bool:
    return "semaphore" in _memspace(ref_aval) or \
        "sem" in str(_aval_of(ref_aval).dtype)


def _bytes_of(aval) -> int:
    try:
        return math.prod(aval.shape) * aval.dtype.itemsize
    except (TypeError, AttributeError):
        return 0


@dataclasses.dataclass
class SiteReport:
    """Structural summary of one traced pallas_call (the CLI's --json
    payload and the check input)."""

    site: str
    variant: str
    grid: tuple
    vmem_block_bytes: int      # VMEM in/out blocks, single-buffered
    vmem_scratch_bytes: int
    vmem_total_bytes: int      # blocks ×2 (grid pipeline) + scratch
    misaligned: List[str]
    fragile: List[str]
    dma_starts: int
    dma_waits: int
    unpaired_sems: List[str]


def _alignment_issues(site: str, tag: str, aval) -> List[Tuple[str, str]]:
    """(rule, detail) for one VMEM-resident aval."""
    out = []
    shape = tuple(aval.shape)
    if not shape:
        return out
    itemsize = aval.dtype.itemsize
    lane = shape[-1]
    if lane > 1 and lane % _LANE:
        out.append(("lane-misaligned",
                    f"{tag} {aval.dtype}{list(shape)}: minor dim {lane} "
                    f"is not a {_LANE} multiple"))
    if len(shape) >= 2:
        sub = shape[-2]
        need = _SUBLANE.get(itemsize, 8)
        if sub > 1 and sub % need:
            out.append(("sublane-misaligned",
                        f"{tag} {aval.dtype}{list(shape)}: sublane dim "
                        f"{sub} is not a {need} multiple ({aval.dtype} "
                        f"tiles pad to {need})"))
    return out


def audit_eqn(site: str, variant: str, eqn) -> Tuple[SiteReport,
                                                     List[Tuple[str, str]]]:
    """Run every structural check on one traced pallas_call equation.
    Returns (report, [(rule, message)])."""
    gm = eqn.params["grid_mapping"]
    kjaxpr = eqn.params["jaxpr"]
    issues: List[Tuple[str, str]] = []

    block_bytes = 0
    mis: List[str] = []
    for bm in gm.block_mappings:
        ref_aval = bm.transformed_block_aval
        if not _is_vmem(ref_aval):
            continue
        aval = _aval_of(ref_aval)
        block_bytes += _bytes_of(aval)
        for rule, detail in _alignment_issues(site, f"block[{bm.origin}]",
                                              aval):
            issues.append((rule, detail))
            mis.append(detail)

    n_scratch = gm.num_scratch_operands
    scratch_avals = (list(kjaxpr.invars[-n_scratch:]) if n_scratch else [])
    scratch_bytes = 0
    sem_vars = []
    for i, var in enumerate(scratch_avals):
        ref_aval = var.aval
        if _is_semaphore(ref_aval):
            sem_vars.append((i, var))
            continue
        if not _is_vmem(ref_aval):
            continue
        aval = _aval_of(ref_aval)
        scratch_bytes += _bytes_of(aval)
        for rule, detail in _alignment_issues(site, f"scratch[{i}]", aval):
            issues.append((rule, detail))
            mis.append(detail)

    total = 2 * block_bytes + scratch_bytes
    budget = int(min(VMEM_BUDGETS_BYTES.values()) * VMEM_OCCUPANCY)
    if total > budget:
        worst = min(VMEM_BUDGETS_BYTES, key=VMEM_BUDGETS_BYTES.get)
        issues.append((
            "vmem-budget",
            f"declared VMEM working set {total / (1 << 20):.1f} MiB "
            f"(blocks ×2 + scratch) exceeds the {worst} budget "
            f"{VMEM_BUDGETS_BYTES[worst] / (1 << 20):.0f} MiB × "
            f"{VMEM_OCCUPANCY} occupancy"))

    # fragile primitives + DMA/semaphore pairing inside the kernel body
    fragile: List[str] = []
    dma_starts = dma_waits = 0
    signaled: set = set()
    waited: set = set()
    known_sem_ids = {id(var) for _i, var in sem_vars}
    unattributed_sem_ops = 0
    for keqn in _walk_eqns(kjaxpr):
        nm = keqn.primitive.name
        if nm in _REPEAT_PRIMS:
            fragile.append(
                "pltpu.repeat: interpret semantics are element-wise "
                "(np.repeat) on this jax while Mosaic tiles (np.tile) — "
                "re-verify on real TPU (the ivf_pq pq_bits=4 xfail)")
            issues.append(("fragile-repeat", fragile[-1]))
        elif nm == "reshape":
            in_shape = tuple(keqn.invars[0].aval.shape)
            out_shape = tuple(keqn.outvars[0].aval.shape)
            in_lane = in_shape[-1] if in_shape else 1
            out_lane = out_shape[-1] if out_shape else 1
            if (in_lane != out_lane
                    and any(d > 1 and d % _LANE for d in (in_lane,
                                                          out_lane))):
                detail = (f"sub-128-lane reshape {list(in_shape)} -> "
                          f"{list(out_shape)}: minor-dim relayout Mosaic "
                          "handles least reliably")
                fragile.append(detail)
                issues.append(("fragile-reshape", detail))
        elif nm == "dma_start":
            dma_starts += 1
        elif nm == "dma_wait":
            dma_waits += 1
        elif nm in ("semaphore_signal", "semaphore_wait"):
            ids = {id(v) for v in keqn.invars if not hasattr(v, "val")}
            (signaled if nm == "semaphore_signal" else waited).update(ids)
            # an op on a semaphore threaded through a control-flow
            # sub-jaxpr binds a DIFFERENT Var than the scratch invar —
            # id matching cannot attribute it (get_barrier_semaphore's
            # fresh var is the benign top-level case)
            sem_operands = {
                id(v) for v in keqn.invars
                if not hasattr(v, "val") and _is_semaphore(v.aval)}
            if sem_operands and not (sem_operands & known_sem_ids):
                in_top = any(keqn2 is keqn for keqn2 in kjaxpr.eqns)
                if not in_top:
                    unattributed_sem_ops += 1

    if dma_starts > dma_waits:
        issues.append((
            "dma-unwaited",
            f"{dma_starts} dma_start vs {dma_waits} dma_wait equations: "
            "a started async copy is never waited on some path"))

    unpaired: List[str] = []
    # regular (non-DMA) semaphores: every one must be both signaled and
    # waited somewhere in the body. DMA semaphores are consumed by
    # dma_wait and are covered by the count check above. LIMITATION:
    # signal/wait inside a control-flow sub-jaxpr (fori_loop/cond body)
    # binds inner Vars id-matching cannot attribute to the scratch
    # invar — when such ops exist the pairing verdict would be
    # unreliable in BOTH directions, so the check stands down rather
    # than emit a false finding (docs/analysis.md).
    for i, var in sem_vars:
        if unattributed_sem_ops:
            break
        if "dma" in str(_aval_of(var.aval).dtype):
            continue
        s, w = id(var) in signaled, id(var) in waited
        if s != w:
            what = "signaled but never waited" if s else \
                "waited but never signaled"
            unpaired.append(f"scratch[{i}] {what}")
            issues.append((
                "sem-unpaired",
                f"regular semaphore scratch[{i}] is {what} in the kernel "
                "body — a hung or leaking credit on hardware"))

    rep = SiteReport(site=site, variant=variant, grid=tuple(gm.grid),
                     vmem_block_bytes=block_bytes,
                     vmem_scratch_bytes=scratch_bytes,
                     vmem_total_bytes=total, misaligned=mis,
                     fragile=fragile, dma_starts=dma_starts,
                     dma_waits=dma_waits, unpaired_sems=unpaired)
    return rep, issues


def trace_variant(builder) -> Optional[list]:
    """Build and trace one variant → pallas_call eqns (None = variant
    skipped in this process, e.g. no multi-device mesh)."""
    import jax

    built = builder()
    if built is None:
        return None
    fn, args, kwargs = built
    closed = jax.make_jaxpr(functools.partial(fn, **kwargs))(*args)
    return pallas_eqns(closed)


def run(root: str, collect_reports: Optional[list] = None) -> List[Finding]:
    """Audit every registered site; returns findings (symbols are
    ``site:variant``-stable so the baseline survives line drift)."""
    findings: List[Finding] = []
    for site in SITES:
        line = site_line(root, site)
        for vname, builder in site.variants:
            try:
                eqns = trace_variant(builder)
            except Exception as e:  # noqa: BLE001 - a trace failure IS
                # a finding: the kernel cannot even shape-trace
                findings.append(Finding(
                    "trace-failed", site.path, f"{site.name}:{vname}",
                    f"variant failed to trace: {type(e).__name__}: {e}",
                    line))
                continue
            if eqns is None:
                continue
            for eqn in eqns:
                rep, issues = audit_eqn(site.name, vname, eqn)
                if collect_reports is not None:
                    collect_reports.append(rep)
                for rule, msg in issues:
                    # symbol carries the variant only for shape-dependent
                    # rules; structural rules dedupe across variants
                    structural = rule in ("fragile-repeat", "dma-unwaited",
                                          "sem-unpaired")
                    sym = site.name if structural else \
                        f"{site.name}:{vname}"
                    findings.append(Finding(rule, site.path, sym, msg,
                                            line))
    return findings
