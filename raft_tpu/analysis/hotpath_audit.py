"""Pass 2: serving hot-path audits — sync discipline and recompile
hazards.

The serving stack's latency contract rests on two invariants the last
six PRs kept re-litigating by hand:

* **No unconditional host sync on a dispatch path.** TPU dispatch is
  asynchronous; one stray ``jax.block_until_ready``/``jax.device_get``
  serializes the double-buffered batcher against device time (the PR 12
  dispatch-floor work existed to remove exactly these). Syncs are legal
  only on *sampled probes* (the batcher's ``if probe:`` device stage,
  mutable's pre-warm tick) or off the hot path (warmup, save/load,
  tune/bench). Rule ``hotpath-sync`` flags the rest.
* **No host callbacks inside a searcher program, and no continuous
  jit statics.** A callback primitive in a ``make_searcher`` closure's
  jaxpr round-trips every batch through Python; a float-valued (or
  signature-drifted) ``static_argnames`` entry bypasses the shape-bucket
  executable cache and recompiles per distinct value. Rules
  ``hotpath-callback`` (jaxpr, via :func:`audit_searcher`),
  ``jit-static-float`` and ``jit-static-missing`` (AST, whole tree).

:func:`jaxpr_stats` is the generalized form of
``cagra_fused.one_dispatch_stats`` (which now delegates here): it
counts kernel launches, device-side loops OUTSIDE kernel bodies (each
iteration of one is a dispatch round trip), and callback primitives in
any traced callable — the bench serving lane, the one-dispatch test and
the pod session all read the same counter set.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from . import Finding

__all__ = ["jaxpr_stats", "audit_searcher", "run", "HOTPATH_MODULES",
           "CALLBACK_PRIMS", "sync_lint", "sync_lint_source",
           "jit_static_lint", "jit_static_lint_source",
           "shardmap_lint", "shardmap_lint_source"]

# primitives that round-trip through the host per execution
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "python_callback",
    "callback", "host_callback", "outside_call", "infeed", "outfeed",
})

# the serving-reachable modules the sync lint scans: everything under
# serve/ — including tenancy.py (the fabric worker's dispatch/demux is
# a serving hot path) and qcache.py (a cache hit runs on the submit
# thread; test_analysis pins both into the scanned set) — plus every
# module that defines a make_searcher closure (or is dispatched from
# one)
HOTPATH_MODULES = (
    "raft_tpu/serve",
    "raft_tpu/neighbors/brute_force.py",
    "raft_tpu/neighbors/cagra.py",
    "raft_tpu/neighbors/ivf_flat.py",
    "raft_tpu/neighbors/ivf_pq.py",
    "raft_tpu/neighbors/mutable.py",
    "raft_tpu/neighbors/host_stream.py",
    "raft_tpu/parallel/sharded_ann.py",
    "raft_tpu/parallel/sharded_knn.py",
    "raft_tpu/parallel/fleet.py",
    "raft_tpu/parallel/dispatch_cache.py",
)

_SYNC_CALLS = {"block_until_ready", "device_get"}
# a sync inside a function whose name marks it off the hot path is fine
_OFFPATH_FN = re.compile(
    r"warm|prepare|tune|bench|save|load|export|__main__")
# ... as is one under a sampled-probe conditional
_PROBE_COND = re.compile(r"probe|sample|rate|tick|warm")

# -- rule hotpath-shardmap-rebuild ------------------------------------------
# constructing a shard_map per call re-traces (and usually recompiles)
# the WHOLE sharded program on every search — the dispatch tax the
# per-index compiled-program cache (parallel/dispatch_cache) exists to
# kill. Legal off the hot path (builds/training/warmup/tuning/dryruns,
# tier re-planning) ...
_SHARDMAP_CALLS = {"shard_map", "shard_map_compat"}
_SHARDMAP_OFFPATH = re.compile(
    r"warm|prepare|tune|bench|save|load|export|__main__|build|train"
    r"|dryrun|pack|plan|retier")
# ... or under a compiled-program-cache miss conditional (trace once,
# store, dispatch many)
_CACHE_MISS_COND = re.compile(r"cache|miss|compil|is None|not in")


# ---------------------------------------------------------------------------
# jaxpr-structural audit (the one_dispatch_stats generalization)
# ---------------------------------------------------------------------------

def jaxpr_stats(fn, *args) -> dict:
    """Trace ``fn(*args)`` (abstract — nothing executes) and report its
    dispatch structure: ``pallas_calls`` (kernel launch sites),
    ``while_loops``/``scans`` (device loops OUTSIDE kernel bodies — each
    ``while`` iteration is a separate kernel-launch round trip),
    ``callbacks`` (host round trips per execution, by primitive name),
    and ``one_dispatch`` (no device loop remains: the whole program is
    one straight-line executable per call).

    Plain python scalars (int/float/bool/str/None) among ``args`` are
    treated as static — a searcher closure's ``k`` is a shape/branch
    input, not a traced value (exactly as ``jax.jit`` statics would
    hold it on the serving path)."""
    import jax

    static = {i for i, a in enumerate(args)
              if a is None or isinstance(a, (int, float, bool, str))}
    traced = [a for i, a in enumerate(args) if i not in static]

    def call(*dyn):
        it = iter(dyn)
        full = [args[i] if i in static else next(it)
                for i in range(len(args))]
        return fn(*full)

    jaxpr = jax.make_jaxpr(call)(*traced)
    counts = {"pallas_calls": 0, "while_loops": 0, "scans": 0}
    callbacks: List[str] = []

    def _subjaxprs(params):
        for v in params.values():
            vals = v if isinstance(v, (tuple, list)) else (v,)
            for x in vals:
                if isinstance(x, jax.core.ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, jax.core.Jaxpr):
                    yield x

    def walk(jx):
        for eqn in jx.eqns:
            nm = eqn.primitive.name
            if nm == "pallas_call":
                counts["pallas_calls"] += 1
                continue           # hop loops INSIDE a kernel are free
            if nm == "while":
                counts["while_loops"] += 1
            elif nm == "scan":
                counts["scans"] += 1
            elif nm in CALLBACK_PRIMS:
                callbacks.append(nm)
            for sub in _subjaxprs(eqn.params):
                walk(sub)

    walk(jaxpr.jaxpr)
    counts["callbacks"] = callbacks
    counts["one_dispatch"] = counts["while_loops"] == 0
    return counts


def audit_searcher(name: str, fn, *args) -> Tuple[dict, List[Finding]]:
    """Audit one serving closure (a ``make_searcher`` product or any
    ``fn(queries, k)``-shaped callable): trace it and flag host-callback
    primitives. Returns ``(jaxpr_stats, findings)`` — dispatch-floor
    counts ride along for the caller (the pod session asserts
    ``one_dispatch`` for the fused engine; other engines legitimately
    loop)."""
    stats = jaxpr_stats(fn, *args)
    findings = [
        Finding("hotpath-callback", "<traced>", f"{name}:{prim}",
                f"searcher closure '{name}' reaches host-callback "
                f"primitive '{prim}': every batch round-trips through "
                "Python on the dispatch path")
        for prim in sorted(set(stats["callbacks"]))
    ]
    return stats, findings


# ---------------------------------------------------------------------------
# AST: unconditional-sync lint
# ---------------------------------------------------------------------------

def _is_sync_call(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _SYNC_CALLS:
        return f.attr
    return None


def _is_shardmap_call(node: ast.Call) -> Optional[str]:
    f = node.func
    name = (f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None)
    return name if name in _SHARDMAP_CALLS else None


class _CallSiteVisitor(ast.NodeVisitor):
    """Flag calls matched by ``matcher`` unless an enclosing function
    name matches ``offpath`` or an enclosing ``if`` condition matches
    ``cond_cover`` (the sampled-probe / cache-miss escape hatches)."""

    def __init__(self, matcher, offpath, cond_cover):
        self.matcher = matcher
        self.offpath = offpath
        self.cond_cover = cond_cover
        self.fn_stack: List[str] = []
        self.if_stack: List[str] = []
        self.hits: List[Tuple[int, str, str]] = []  # (line, call, fn)

    def visit_FunctionDef(self, node):
        self.fn_stack.append(node.name)
        # a nested def runs later, unconditionally — it must not inherit
        # an enclosing `if probe:` as sampled-probe cover
        saved, self.if_stack = self.if_stack, []
        self.generic_visit(node)
        self.if_stack = saved
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_If(self, node):
        # the test expression itself runs unconditionally: a sync call
        # INSIDE the condition must not inherit the condition as cover
        self.visit(node.test)
        try:
            cond = ast.unparse(node.test)
        except Exception:  # noqa: BLE001 - unparse is best-effort
            cond = ""
        self.if_stack.append(cond)
        for child in node.body:
            self.visit(child)
        self.if_stack.pop()
        for child in node.orelse:
            self.visit(child)

    def visit_Call(self, node):
        name = self.matcher(node)
        if name is not None:
            off_path = any(self.offpath.search(fn) for fn in self.fn_stack)
            covered = any(self.cond_cover.search(c) for c in self.if_stack)
            if not off_path and not covered:
                fn = ".".join(self.fn_stack) or "<module>"
                self.hits.append((node.lineno, name, fn))
        self.generic_visit(node)


def sync_lint_source(src: str, rel_path: str) -> List[Finding]:
    """Sync lint for one module's source (exposed for the fixture
    tests)."""
    visitor = _CallSiteVisitor(_is_sync_call, _OFFPATH_FN, _PROBE_COND)
    visitor.visit(ast.parse(src))
    return [Finding(
        "hotpath-sync", rel_path, f"{fn}:{call}",
        f"unconditional jax.{call} in serving-reachable "
        f"'{fn}' — syncs belong on sampled probes or off-path "
        "helpers (warmup/save/tune) only", line)
        for line, call, fn in visitor.hits]


def sync_lint(root: str) -> List[Finding]:
    from . import iter_module_paths

    findings = []
    for rel in iter_module_paths(root, HOTPATH_MODULES):
        with open(os.path.join(root, rel)) as f:
            src = f.read()
        findings += sync_lint_source(src, rel.replace(os.sep, "/"))
    return findings


def shardmap_lint_source(src: str, rel_path: str) -> List[Finding]:
    """Per-call shard_map-rebuild lint for one module's source (exposed
    for the fixture tests): any ``shard_map``/``shard_map_compat``
    construction in a serving-reachable module must sit off the hot
    path (build/train/warmup/tune/... function) or under a compiled-
    program-cache miss conditional (``if fn is None:`` — the
    trace-once/dispatch-many pattern of parallel/dispatch_cache)."""
    visitor = _CallSiteVisitor(_is_shardmap_call, _SHARDMAP_OFFPATH,
                               _CACHE_MISS_COND)
    visitor.visit(ast.parse(src))
    return [Finding(
        "hotpath-shardmap-rebuild", rel_path, f"{fn}:{call}",
        f"per-call {call} construction in serving-reachable "
        f"'{fn}': every search re-traces the whole sharded program "
        "(~hundreds of XLA programs per call at fleet scale) — route "
        "it through the per-index compiled-program cache "
        "(parallel/dispatch_cache)", line)
        for line, call, fn in visitor.hits]


def shardmap_lint(root: str) -> List[Finding]:
    from . import iter_module_paths

    findings = []
    for rel in iter_module_paths(root, HOTPATH_MODULES):
        with open(os.path.join(root, rel)) as f:
            src = f.read()
        findings += shardmap_lint_source(src, rel.replace(os.sep, "/"))
    return findings


# ---------------------------------------------------------------------------
# AST: recompile-hazard lint (jit statics)
# ---------------------------------------------------------------------------

def _static_argnames(call: ast.Call) -> Optional[List[Tuple[str, int]]]:
    """``static_argnames`` literals of a ``jax.jit`` /
    ``[functools.]partial(jax.jit, ...)`` call, with lines (both the
    attribute and the bare-imported ``partial`` spellings — cagra.py
    uses the bare form)."""
    f = call.func
    is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit"
              and isinstance(f.value, ast.Name) and f.value.id == "jax")
    is_partial = ((isinstance(f, ast.Attribute) and f.attr == "partial")
                  or (isinstance(f, ast.Name) and f.id == "partial"))
    is_partial_jit = (
        is_partial and bool(call.args)
        and isinstance(call.args[0], ast.Attribute)
        and call.args[0].attr == "jit")
    if not (is_jit or is_partial_jit):
        return None
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        names: List[Tuple[str, int]] = []
        vals = (kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value])
        for v in vals:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.append((v.value, v.lineno))
        return names
    return []


def _float_params(fn: ast.FunctionDef) -> Dict[str, str]:
    """Parameter name → evidence string for continuous-valued params
    (float annotation or float default)."""
    out: Dict[str, str] = {}
    args = fn.args
    params = args.posonlyargs + args.args + args.kwonlyargs
    for a in params:
        ann = a.annotation
        if isinstance(ann, ast.Name) and ann.id == "float":
            out[a.arg] = "annotated float"
    defaults = list(args.defaults)
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(defaults):], defaults):
        if isinstance(d, ast.Constant) and isinstance(d.value, float):
            out.setdefault(a.arg, f"float default {d.value}")
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None and isinstance(d, ast.Constant) \
                and isinstance(d.value, float):
            out.setdefault(a.arg, f"float default {d.value}")
    return out


def jit_static_lint_source(src: str, rel_path: str) -> List[Finding]:
    """Recompile-hazard lint for one module's source: every
    ``static_argnames`` entry must name a real parameter
    (``jit-static-missing`` — a typo silently turns the static into a
    traced arg or a TypeError) and must not be continuous-valued
    (``jit-static-float`` — each distinct float compiles a fresh
    executable, bypassing the shape buckets)."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return findings
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        statics: List[Tuple[str, int]] = []
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call):
                got = _static_argnames(dec)
                if got:
                    statics += got
        if not statics:
            continue
        params = {a.arg for a in (node.args.posonlyargs + node.args.args
                                  + node.args.kwonlyargs)}
        floaty = _float_params(node)
        for name, line in statics:
            if name not in params:
                findings.append(Finding(
                    "jit-static-missing", rel_path,
                    f"{node.name}:{name}",
                    f"static_argnames entry '{name}' is not a "
                    f"parameter of {node.name}() — signature "
                    "drift makes it a silently-traced arg", line))
            elif name in floaty:
                findings.append(Finding(
                    "jit-static-float", rel_path,
                    f"{node.name}:{name}",
                    f"static arg '{name}' of {node.name}() is "
                    f"continuous-valued ({floaty[name]}): every "
                    "distinct value compiles a fresh executable, "
                    "bypassing the shape-bucket cache", line))
    return findings


def jit_static_lint(root: str) -> List[Finding]:
    """Whole-tree recompile-hazard sweep (see
    :func:`jit_static_lint_source`)."""
    findings = []
    pkg = os.path.join(root, "raft_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        if "analysis" in os.path.relpath(dirpath, pkg).split(os.sep):
            continue
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            with open(full) as f:
                findings += jit_static_lint_source(f.read(), rel)
    return findings


def run(root: str) -> List[Finding]:
    return sync_lint(root) + jit_static_lint(root) + shardmap_lint(root)
