"""Pass 3: lock discipline over the threaded serving state.

Four of the last six PRs shipped review-caught races of exactly one
shape: a class serializes its mutations under ``self._lock``, then some
other method touches the same attribute lock-free (the SLOEngine
double-fire, the breaker probing flag, the mutable ``_merging`` clear,
``ops_snapshot`` copies). This pass mechanizes the reviewer: for every
class in the scanned modules it **infers the lock-guarded attribute
set** — attributes *written* under a ``with self.<lock>:`` hold (or
inside a ``*_locked``-suffixed method, the tree's caller-holds-the-lock
convention) in any method other than ``__init__`` — and flags any read
or write of those attributes outside a lock hold (rule
``unlocked-attr``).

Module-level state gets the same treatment: globals *mutated* under a
``with <module lock>:`` hold (assignment, subscript store, or a
mutating method call — ``append``/``clear``/``pop``/...) are guarded,
and any access outside a hold in the same module is flagged.

Scope and conventions:

* ``__init__`` is exempt (construction is single-threaded by contract),
  as are the lock attributes themselves.
* A ``*_locked``-suffixed method asserts "caller holds the lock": its
  body counts as locked. The flip side is NOT yet linted (calling a
  ``_locked`` helper without the lock) — keep the suffix honest.
* Nested ``def``/``lambda`` bodies reset to unlocked (they run later,
  when the ``with`` has exited).
* Deliberate lock-free reads (GIL-atomic scalar peeks on hot paths)
  carry an inline ``# lint: waive(unlocked-attr): <reason>`` with the
  justification — the waiver is the documentation.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import Finding

__all__ = ["run", "lint_source", "LOCK_MODULES"]

LOCK_MODULES = (
    # the whole serve/ tree — including tenancy.py (the fabric's
    # weighted drain + swap flip are exactly this lint's bug class)
    # and qcache.py (LRU map under one lock); test_analysis pins both
    # files into the scanned set so a future restructure can't
    # silently drop them
    "raft_tpu/serve",
    "raft_tpu/neighbors/mutable.py",
    "raft_tpu/ops/guarded.py",
)

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_MUTATORS = {"append", "appendleft", "add", "clear", "pop", "popleft",
             "remove", "discard", "update", "setdefault", "extend",
             "insert", "rotate"}


def _lock_ctor(call: ast.AST) -> bool:
    return (isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in _LOCK_CTORS
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "threading")


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


# access record: (attr, line, is_write, locked, method)
_Access = Tuple[str, int, bool, bool, str]


class _ClassScan:
    """Accesses to ``self.<attr>`` across one class, lock-hold aware."""

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.lock_attrs: Set[str] = set()
        self.accesses: List[_Access] = []
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _lock_ctor(node.value):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr:
                        self.lock_attrs.add(attr)
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_method(item)

    def _is_lock_with(self, item: ast.withitem) -> bool:
        attr = _self_attr(item.context_expr)
        return attr is not None and attr in self.lock_attrs

    def _scan_method(self, fn: ast.FunctionDef) -> None:
        base_locked = fn.name.endswith("_locked")
        self._scan_node(fn.body, base_locked, fn.name)

    def _scan_node(self, body, locked: bool, method: str) -> None:
        for node in body:
            self._scan_stmt(node, locked, method)

    def _scan_stmt(self, node: ast.AST, locked: bool,
                   method: str) -> None:
        if isinstance(node, ast.With):
            holds = any(self._is_lock_with(i) for i in node.items)
            for i in node.items:
                self._scan_expr(i.context_expr, locked, method)
            self._scan_node(node.body, locked or holds, method)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, outside the hold
            self._scan_node(node.body, False, f"{method}.{node.name}")
            return
        if isinstance(node, ast.Lambda):
            self._scan_expr(node.body, False, method)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                self._record_target(tgt, locked, method)
            value = node.value
            if value is not None:
                self._scan_expr(value, locked, method)
            if isinstance(node, ast.AugAssign):
                attr = _self_attr(node.target)
                if attr:
                    self.accesses.append((attr, node.lineno, True,
                                          locked, method))
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._record_target(tgt, locked, method)
            return
        # generic: recurse into child statements/expressions
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._scan_stmt(child, locked, method)
            elif isinstance(child, ast.expr):
                self._scan_expr(child, locked, method)

    def _record_target(self, tgt: ast.AST, locked: bool,
                       method: str) -> None:
        attr = _self_attr(tgt)
        if attr:
            self.accesses.append((attr, tgt.lineno, True, locked, method))
            return
        if isinstance(tgt, (ast.Subscript, ast.Attribute)):
            attr = _self_attr(tgt.value) if isinstance(
                tgt, ast.Subscript) else None
            if attr:
                # self._x[k] = v mutates self._x
                self.accesses.append((attr, tgt.lineno, True, locked,
                                      method))
                return
        for child in ast.iter_child_nodes(tgt):
            if isinstance(child, ast.expr):
                self._record_target(child, locked, method)

    def _scan_expr(self, node: ast.AST, locked: bool,
                   method: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._scan_node(node.body, False, method)
            return
        if isinstance(node, ast.Lambda):
            self._scan_expr(node.body, False, method)
            return
        if isinstance(node, ast.Call):
            # self._x.append(v): a mutation of self._x
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                attr = _self_attr(f.value)
                if attr:
                    self.accesses.append((attr, node.lineno, True,
                                          locked, method))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._scan_expr(child, locked, method)
            return
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self.accesses.append((attr, node.lineno, False, locked,
                                  method))
            # do not also record `self` below
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._scan_expr(child, locked, method)
            elif isinstance(child, ast.stmt):
                self._scan_stmt(child, locked, method)

    # ---- verdicts -------------------------------------------------------
    def guarded_attrs(self) -> Set[str]:
        return {a for a, _ln, w, locked, m in self.accesses
                if w and locked and m != "__init__"} - self.lock_attrs

    def violations(self, waived=None) -> List[Tuple[str, int, bool, str]]:
        """``waived``: optional ``line -> {rules}`` map (``waivers_in``)
        applied BEFORE deduplication — a waived first access must not
        suppress a later unwaived access to the same attribute."""
        guarded = self.guarded_attrs()
        out = []
        seen = set()
        for attr, line, write, locked, method in self.accesses:
            if attr not in guarded or locked:
                continue
            # a *_locked method's direct body is recorded locked=True
            # already; anything here with locked=False inside one is a
            # nested def/lambda that runs later, OFF the lock — flag it
            if method.split(".")[0] == "__init__":
                continue
            if waived is not None and _is_waived(waived, line):
                continue
            key = (method, attr, write)
            if key in seen:
                continue
            seen.add(key)
            out.append((attr, line, write, method))
        return out


class _ModuleScan:
    """Module-global form: locks at module scope, guarded globals."""

    def __init__(self, tree: ast.Module):
        self.lock_names: Set[str] = set()
        self.module_names: Set[str] = set()
        self.accesses: List[_Access] = []
        for node in tree.body:
            if isinstance(node, ast.Assign):
                if _lock_ctor(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.lock_names.add(tgt.id)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_names.add(tgt.id)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                self.module_names.add(node.target.id)
        if not self.lock_names:
            return
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_node(node.body,
                                node.name.endswith("_locked"), node.name)

    def _is_lock_with(self, item: ast.withitem) -> bool:
        c = item.context_expr
        return isinstance(c, ast.Name) and c.id in self.lock_names

    def _scan_node(self, body, locked: bool, fn: str) -> None:
        for node in body:
            self._scan_stmt(node, locked, fn)

    def _scan_stmt(self, node, locked: bool, fn: str) -> None:
        if isinstance(node, ast.With):
            holds = any(self._is_lock_with(i) for i in node.items)
            self._scan_node(node.body, locked or holds, fn)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._scan_node(node.body, False, f"{fn}.{node.name}")
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
                if isinstance(base, ast.Name) \
                        and base.id in self.module_names:
                    self.accesses.append((base.id, node.lineno, True,
                                          locked, fn))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._scan_stmt(child, locked, fn)
            elif isinstance(child, ast.expr):
                self._scan_expr(child, locked, fn)

    def _scan_expr(self, node, locked: bool, fn: str) -> None:
        if isinstance(node, ast.Lambda):
            self._scan_expr(node.body, False, fn)
            return
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                    and isinstance(f.value, ast.Name)
                    and f.value.id in self.module_names):
                self.accesses.append((f.value.id, node.lineno, True,
                                      locked, fn))
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in self.module_names:
            self.accesses.append((node.id, node.lineno, False, locked, fn))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._scan_expr(child, locked, fn)

    def guarded_names(self) -> Set[str]:
        return {a for a, _ln, w, locked, _f in self.accesses
                if w and locked} - self.lock_names

    def violations(self, waived=None) -> List[Tuple[str, int, bool, str]]:
        guarded = self.guarded_names()
        out, seen = [], set()
        for name, line, write, locked, fn in self.accesses:
            if name not in guarded or locked:
                continue
            if waived is not None and _is_waived(waived, line):
                continue
            key = (fn, name, write)
            if key in seen:
                continue
            seen.add(key)
            out.append((name, line, write, fn))
        return out


def _is_waived(waived: dict, line: int) -> bool:
    return ("unlocked-attr" in waived.get(line, ())
            or "unlocked-attr" in waived.get(line - 1, ()))


def lint_source(src: str, rel_path: str) -> List[Finding]:
    """Lint one module's source. Waivers are honoured access-by-access
    BEFORE the per-(method, attr) dedupe, so a waived peek cannot
    shadow a later unwaived access. Exposed for the injected-violation
    fixture tests."""
    from . import waivers_in

    waived = waivers_in(src)
    tree = ast.parse(src)
    findings: List[Finding] = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        scan = _ClassScan(node)
        if not scan.lock_attrs:
            continue
        for attr, line, write, method in scan.violations(waived):
            kind = "write" if write else "read"
            findings.append(Finding(
                "unlocked-attr", rel_path,
                f"{node.name}.{method}.{attr}",
                f"{kind} of lock-guarded attribute '{attr}' outside a "
                f"'with self._lock' hold in {node.name}.{method}() — "
                "the bug class behind the SLO double-fire / breaker "
                "probing / _merging races", line))
    mod = _ModuleScan(tree)
    if mod.lock_names:
        for name, line, write, fn in mod.violations(waived):
            kind = "write" if write else "read"
            findings.append(Finding(
                "unlocked-attr", rel_path, f"module.{fn}.{name}",
                f"{kind} of lock-guarded module global '{name}' outside "
                f"a lock hold in {fn}()", line))
    return findings


def run(root: str) -> List[Finding]:
    from . import iter_module_paths

    findings: List[Finding] = []
    for rel in iter_module_paths(root, LOCK_MODULES):
        with open(os.path.join(root, rel)) as f:
            src = f.read()
        findings += lint_source(src, rel.replace(os.sep, "/"))
    return findings
