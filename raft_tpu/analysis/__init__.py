"""Machine-checked TPU invariants: the static-analysis gate
(docs/analysis.md).

Every flagship kernel in this tree — the ring merge, the CAGRA
megakernel, the int4/pq edge stores, the host-stream overlap — is
interpret-validated only and has never compiled on a real TPU
(ROADMAP "Hardware-gated verdicts"), while the Mosaic rules that decide
whether they WILL compile lived only in code comments and reviewer
memory; meanwhile four of the last six PRs shipped review-caught lock
races in ``serve/``. This package encodes those invariants as three
static passes that fail the suite (``tests/test_analysis.py``):

* :mod:`~raft_tpu.analysis.kernel_audit` — a registry of every
  ``pallas_call`` site with jaxpr-structural checks: VMEM footprint vs
  a per-generation budget, tiling/lane alignment, fragile primitives
  (``pltpu.repeat``, sub-128-lane reshapes), DMA/semaphore pairing.
* :mod:`~raft_tpu.analysis.hotpath_audit` — serving hot-path audits:
  no host callbacks in a searcher jaxpr, no unconditional
  ``block_until_ready``/``device_get`` outside sampled probes, and a
  recompile-hazard lint over ``jax.jit`` statics.
* :mod:`~raft_tpu.analysis.lock_lint` — lock discipline over ``serve/``,
  ``neighbors/mutable.py`` and ``ops/guarded.py``: infer each class's
  lock-guarded attribute set and flag accesses outside a lock hold.

All passes are AST/jaxpr only — tracing, never compiling or running
device code — so the whole suite stays tier-1 cheap. Known findings
live in the checked-in ``baseline.json`` (zero-NEW-findings policy);
intentional patterns carry an inline escape hatch::

    some_racy_read  # lint: waive(unlocked-attr): GIL-atomic int, hot path

A waiver must name the rule and a reason; it covers its own line and
the line below (waiver-above-statement style).
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Finding", "run_all", "load_baseline", "compare",
           "baseline_path", "repo_root", "apply_waivers", "waivers_in",
           "KNOWN_RULES"]

_WAIVE_RE = re.compile(r"#\s*lint:\s*waive\(([\w.-]+)\)\s*:\s*\S")

# every rule id, by the pass that emits it — the waiver sweep in
# tests/test_analysis.py rejects waivers naming anything else (a typo'd
# waiver that never fires is worse than no waiver), and partial CLI runs
# compare only against the selected passes' slice of the baseline
PASS_RULES = {
    "kernel": frozenset({
        "vmem-budget", "lane-misaligned", "sublane-misaligned",
        "fragile-repeat", "fragile-reshape", "dma-unwaited",
        "sem-unpaired", "trace-failed"}),
    "hotpath": frozenset({
        "hotpath-sync", "hotpath-callback", "hotpath-shardmap-rebuild",
        "jit-static-float", "jit-static-missing"}),
    "lock": frozenset({"unlocked-attr"}),
}
KNOWN_RULES = frozenset().union(*PASS_RULES.values())


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation. ``key`` (rule::path::symbol) is the stable
    identity the baseline stores — line numbers drift, symbols don't."""

    rule: str       # e.g. "vmem-budget", "unlocked-attr"
    path: str       # repo-relative source path
    symbol: str     # stable anchor: site/variant, Class.attr, func name
    message: str    # human-facing: what is wrong and why it matters
    line: int = 0   # best-effort source line (0 = site-level)

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.symbol}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"[{self.rule}] {loc} ({self.symbol}): {self.message}"


def repo_root() -> str:
    """The directory holding the ``raft_tpu`` package."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def iter_module_paths(root: str, entries: Iterable[str]) -> List[str]:
    """Repo-relative ``.py`` paths for a tuple of module/directory
    entries; directories are scanned RECURSIVELY (a future subpackage
    under serve/ must not silently drop out of a pass)."""
    out: List[str] = []
    for entry in entries:
        full = os.path.join(root, entry)
        if os.path.isdir(full):
            for dirpath, _dirs, files in os.walk(full):
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.relpath(
                            os.path.join(dirpath, f), root))
        elif os.path.exists(full):
            out.append(entry)
    return out


def waivers_in(src: str) -> Dict[int, set]:
    """``# lint: waive(<rule>): <reason>`` comments → {line: {rules}}.
    A waiver covers its own line and the next line (comment-above)."""
    out: Dict[int, set] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        for m in _WAIVE_RE.finditer(text):
            out.setdefault(i, set()).add(m.group(1))
    return out


def apply_waivers(findings: Iterable[Finding],
                  root: Optional[str] = None) -> List[Finding]:
    """Drop findings whose (rule, line) is covered by an inline waiver
    in their source file. Site-level findings (line 0) cannot be waived
    inline — baseline them instead."""
    root = root or repo_root()
    cache: Dict[str, Dict[int, set]] = {}
    kept = []
    for f in findings:
        if f.line:
            if f.path not in cache:
                try:
                    with open(os.path.join(root, f.path)) as fh:
                        cache[f.path] = waivers_in(fh.read())
                except OSError:
                    cache[f.path] = {}
            w = cache[f.path]
            if (f.rule in w.get(f.line, ()) or
                    f.rule in w.get(f.line - 1, ())):
                continue
        kept.append(f)
    return kept


def _dedupe(findings: Iterable[Finding]) -> List[Finding]:
    seen, out = set(), []
    for f in findings:
        if f.key not in seen:
            seen.add(f.key)
            out.append(f)
    return out


def run_all(root: Optional[str] = None,
            passes: Tuple[str, ...] = ("kernel", "hotpath", "lock"),
            kernel_reports: Optional[list] = None) -> List[Finding]:
    """Run the selected passes and return the de-duplicated, waiver-
    filtered findings, sorted by key (the suite's input).
    ``kernel_reports``: optional list the kernel pass appends its
    per-variant :class:`~.kernel_audit.SiteReport` structures to (the
    CLI's --json payload)."""
    from . import hotpath_audit, kernel_audit, lock_lint

    root = root or repo_root()
    findings: List[Finding] = []
    if "kernel" in passes:
        findings += kernel_audit.run(root, collect_reports=kernel_reports)
    if "hotpath" in passes:
        findings += hotpath_audit.run(root)
    if "lock" in passes:
        findings += lock_lint.run(root)
    return sorted(_dedupe(apply_waivers(findings, root)),
                  key=lambda f: f.key)


def merged_baseline_keys(findings: Iterable[Finding],
                         passes: Optional[Tuple[str, ...]] = None
                         ) -> List[str]:
    """Baseline keys for a rebaseline: this run's findings, PLUS — when
    only a subset of passes ran — the existing baseline entries owned by
    the passes that did NOT run (a lock-only rebaseline must not wipe
    the kernel audit's entries)."""
    keys = {f.key for f in findings}
    if passes is not None:
        selected = frozenset().union(
            *(PASS_RULES[p] for p in passes if p in PASS_RULES))
        keys |= {k for k in load_baseline()
                 if k.split("::", 1)[0] not in selected}
    return sorted(keys)


def load_baseline(path: Optional[str] = None) -> List[str]:
    path = path or baseline_path()
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    return list(data.get("findings", []))


def compare(findings: Iterable[Finding],
            baseline: Optional[Iterable[str]] = None,
            passes: Optional[Tuple[str, ...]] = None) -> dict:
    """Zero-new-findings verdict: ``new`` must be empty for the suite to
    pass; ``stale`` (baselined but no longer firing) is the prune list —
    shrink the baseline whenever a fix lands. ``passes``: when only a
    subset ran, compare against that subset's slice of the baseline
    (other passes' entries are neither stale nor matched)."""
    base = set(load_baseline() if baseline is None else baseline)
    if passes is not None:
        rules = frozenset().union(
            *(PASS_RULES[p] for p in passes if p in PASS_RULES))
        base = {k for k in base if k.split("::", 1)[0] in rules}
    cur = {f.key: f for f in findings}
    return {
        "new": sorted(k for k in cur if k not in base),
        "stale": sorted(k for k in base if k not in cur),
        "baselined": sorted(k for k in cur if k in base),
        "count": len(cur),
    }
