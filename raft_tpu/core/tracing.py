"""Tracing/profiling ranges: TPU-native analog of the reference's NVTX layer.

Reference: raft/core/nvtx.hpp:84 (RAII ``nvtx::range`` pushed at every public
entry point, compiled out unless RAFT_NVTX). Here ranges map onto
``jax.profiler.TraceAnnotation`` so they show up in TPU profiler/Perfetto
traces; a module-level switch keeps them zero-cost when disabled.

A span *timer* can additionally be installed with :func:`set_timer`
(``raft_tpu.serve.metrics.enable_span_metrics`` does): every range and
annotated call then reports its wall duration under its span name,
giving the serving metrics per-stage latency histograms for free. The
timer is independent of the profiler switch — metrics collection must
not require Perfetto tracing to be on — and both default off, keeping
the probes one ``is None`` check on the hot path.
"""
from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Callable, Iterator, Optional

import jax

__all__ = ["enabled", "enable", "disable", "range", "annotate", "set_timer"]

_enabled = os.environ.get("RAFT_TPU_TRACE", "0") not in ("0", "", "false")

# (span_name, seconds) observer; None = timing off (the default)
_timer: Optional[Callable[[str, float], None]] = None


def set_timer(fn: Optional[Callable[[str, float], None]]) -> None:
    """Install (or clear with None) the span-duration observer. Spans
    report host wall time between entry and exit — for searches that is
    dispatch-to-value time, the serving-relevant quantity."""
    global _timer
    _timer = fn


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


@contextlib.contextmanager
def range(name: str) -> Iterator[None]:  # noqa: A001 - mirrors nvtx::range
    """Context-managed trace range (analog of ``raft::common::nvtx::range``)."""
    timer = _timer
    if timer is None and not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        if _enabled:
            with jax.profiler.TraceAnnotation(name):
                yield
        else:
            yield
    finally:
        if timer is not None:
            timer(name, time.perf_counter() - t0)


def annotate(name: str | None = None):
    """Decorator form: wrap a public API function in a trace range."""

    def deco(fn):
        label = name or f"raft_tpu::{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            timer = _timer
            if timer is None and not _enabled:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            try:
                if _enabled:
                    with jax.profiler.TraceAnnotation(label):
                        return fn(*args, **kwargs)
                return fn(*args, **kwargs)
            finally:
                if timer is not None:
                    timer(label, time.perf_counter() - t0)

        return wrapper

    return deco
