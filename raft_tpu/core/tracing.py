"""Tracing/profiling ranges: TPU-native analog of the reference's NVTX layer.

Reference: raft/core/nvtx.hpp:84 (RAII ``nvtx::range`` pushed at every public
entry point, compiled out unless RAFT_NVTX). Here ranges map onto
``jax.profiler.TraceAnnotation`` so they show up in TPU profiler/Perfetto
traces; a module-level switch keeps them zero-cost when disabled.

A span *timer* can additionally be installed with :func:`set_timer`
(``raft_tpu.serve.metrics.enable_span_metrics`` does): every range and
annotated call then reports its wall duration under its span name,
giving the serving metrics per-stage latency histograms for free. The
timer is independent of the profiler switch — metrics collection must
not require Perfetto tracing to be on — and both default off, keeping
the probes one ``is None`` check on the hot path.

Request-lifecycle layer (docs/observability.md): the serving runtime
stamps every request with a **trace ID** (:func:`new_trace_id`) and
binds the active IDs around dispatch (:func:`bind_trace`), so anything
that fires mid-dispatch — a guarded demotion, an injected fault, an XLA
recompile (all recorded in :mod:`raft_tpu.core.events`) — is stamped
with the requests it hit. :func:`child_span` times one stage of a
request (queue wait, pad, dispatch, ...); sampled requests additionally
log their full stage decomposition into a bounded in-process **span
log** (:func:`log_spans` / :func:`recent_spans`). Sampling is governed
by ``RAFT_TPU_TRACE_SAMPLE`` (:func:`sample_rate`, validated float in
[0, 1], default 0 = off): with it off and no timer installed, every
probe site is a single ``is None``/falsy check.
"""
from __future__ import annotations

import collections
import contextlib
import functools
import itertools
import math
import os
import threading
import time
import uuid
from typing import Callable, Dict, Iterator, List, Optional

import jax

__all__ = ["enabled", "enable", "disable", "range", "annotate", "set_timer",
           "new_trace_id", "bind_trace", "current_traces", "current_trace",
           "child_span", "sample_rate", "log_spans", "recent_spans",
           "clear_span_log", "set_span_log_capacity"]

_enabled = os.environ.get("RAFT_TPU_TRACE", "0") not in ("0", "", "false")

# (span_name, seconds) observer; None = timing off (the default)
_timer: Optional[Callable[[str, float], None]] = None


def set_timer(fn: Optional[Callable[[str, float], None]]) -> None:
    """Install (or clear with None) the span-duration observer. Spans
    report host wall time between entry and exit — for searches that is
    dispatch-to-value time, the serving-relevant quantity."""
    global _timer
    _timer = fn


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


@contextlib.contextmanager
def range(name: str) -> Iterator[None]:  # noqa: A001 - mirrors nvtx::range
    """Context-managed trace range (analog of ``raft::common::nvtx::range``)."""
    timer = _timer
    if timer is None and not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        if _enabled:
            with jax.profiler.TraceAnnotation(name):
                yield
        else:
            yield
    finally:
        if timer is not None:
            timer(name, time.perf_counter() - t0)


def annotate(name: str | None = None):
    """Decorator form: wrap a public API function in a trace range.

    The wrapper carries ``__raft_traced__ = True`` so the drift-guard
    test (tests/test_telemetry.py) can assert every public
    ``neighbors/*`` search/build entry point stays instrumented."""

    def deco(fn):
        label = name or f"raft_tpu::{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            timer = _timer
            if timer is None and not _enabled:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            try:
                if _enabled:
                    with jax.profiler.TraceAnnotation(label):
                        return fn(*args, **kwargs)
                return fn(*args, **kwargs)
            finally:
                if timer is not None:
                    timer(label, time.perf_counter() - t0)

        wrapper.__raft_traced__ = True
        return wrapper

    return deco


# -- trace IDs -------------------------------------------------------------
# Thread-local, not a contextvar: the serving worker is one daemon thread
# that binds per-batch, and probes (guarded_call, faults, the compile
# spy) run synchronously on that same thread.
_trace = threading.local()


# process-random prefix + atomic counter: unique without paying a
# per-request urandom syscall on the submit hot path (every Request
# gets an ID even with telemetry fully off — events stamp lazily)
_id_prefix = uuid.uuid4().hex[:8]
_id_counter = itertools.count(1)


def new_trace_id() -> str:
    """A fresh 16-hex trace ID (one per request entering the serving
    pipeline)."""
    return f"{_id_prefix}{next(_id_counter) & 0xFFFFFFFF:08x}"


@contextlib.contextmanager
def bind_trace(*trace_ids: str) -> Iterator[None]:
    """Bind the active trace IDs for the dynamic extent of the block (the
    requests currently being dispatched). Events recorded inside
    (:func:`raft_tpu.core.events.record` with ``trace_id=None``) are
    stamped with them. Nests: the previous binding is restored."""
    prev = getattr(_trace, "ids", ())
    _trace.ids = tuple(trace_ids)
    try:
        yield
    finally:
        _trace.ids = prev


def current_traces() -> tuple:
    """The trace IDs bound on this thread (empty tuple when none)."""
    return getattr(_trace, "ids", ())


def current_trace() -> Optional[str]:
    """First bound trace ID, or None."""
    ids = getattr(_trace, "ids", ())
    return ids[0] if ids else None


# -- child spans -----------------------------------------------------------
@contextlib.contextmanager
def child_span(name: str, out: Optional[Dict[str, float]] = None
               ) -> Iterator[None]:
    """Timed child span for one stage of a request.

    Unlike :func:`range`, the duration is ALWAYS measured — callers gate
    the call site themselves, opening child spans only on sampled work.
    The duration lands in ``out[name]`` (when given), feeds the
    installed span timer, and nests under the profiler range when
    tracing is on. (The serving batcher times its five stages with its
    own injectable clock for test determinism; this is the generic
    building block for instrumenting any other pipeline the same way.)
    """
    t0 = time.perf_counter()
    try:
        if _enabled:
            with jax.profiler.TraceAnnotation(name):
                yield
        else:
            yield
    finally:
        dt = time.perf_counter() - t0
        if out is not None:
            out[name] = dt
        timer = _timer
        if timer is not None:
            timer(name, dt)


# -- sampling knob ---------------------------------------------------------
def sample_rate(value=None, env: str = "RAFT_TPU_TRACE_SAMPLE",
                name: str = "trace_sample") -> float:
    """Resolve and validate a sampling-rate knob.

    ``value=None`` reads the ``env`` variable (default
    ``RAFT_TPU_TRACE_SAMPLE``; ``0`` = sampling off); an explicit value
    (float or string) bypasses the env. The rate must parse as a float
    in [0, 1] — anything else raises ValueError at construction time,
    not silently at the first sampled request. Other samplers (the
    recall sentinel's ``RAFT_TPU_RECALL_SAMPLE``) reuse this validation
    by passing their own ``env``/``name``."""
    # blame the actual source: the env var only on the env-read path
    src = env if value is None else name
    raw = os.environ.get(env, "0") if value is None else value
    try:
        r = float(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"{src} must be a float in [0, 1], got {raw!r}")
    if math.isnan(r) or not 0.0 <= r <= 1.0:
        raise ValueError(
            f"{src} must be in [0, 1], got {raw!r}")
    return r


# -- sampled per-request span log ------------------------------------------
_span_lock = threading.Lock()
_span_log: collections.deque = collections.deque(maxlen=256)


def log_spans(trace_id: str, stages: Dict[str, float], **meta) -> dict:
    """Append one sampled request's stage decomposition to the span log.

    ``stages`` maps stage name -> seconds (the serving batcher records
    queue_wait / bucket_pad / dispatch / device / demux); ``meta`` is
    free-form context (rows, k, dispatch bucket)."""
    entry = {"ts": time.time(), "trace_id": trace_id, "stages": dict(stages)}
    if meta:
        entry.update(meta)
    with _span_lock:
        _span_log.append(entry)
    return entry


def recent_spans(n: Optional[int] = None) -> List[dict]:
    """Most recent sampled span records, oldest first (``n=None`` = all,
    ``n=0`` = none)."""
    with _span_lock:
        items = list(_span_log)
    if n is None:
        return items
    return items[-n:] if n > 0 else []


def clear_span_log() -> None:
    with _span_lock:
        _span_log.clear()


def set_span_log_capacity(n: int) -> None:
    """Resize the span log (keeps the newest records)."""
    global _span_log
    with _span_lock:
        _span_log = collections.deque(_span_log, maxlen=int(n))
