"""Tracing/profiling ranges: TPU-native analog of the reference's NVTX layer.

Reference: raft/core/nvtx.hpp:84 (RAII ``nvtx::range`` pushed at every public
entry point, compiled out unless RAFT_NVTX). Here ranges map onto
``jax.profiler.TraceAnnotation`` so they show up in TPU profiler/Perfetto
traces; a module-level switch keeps them zero-cost when disabled.
"""
from __future__ import annotations

import contextlib
import functools
import os
from typing import Iterator

import jax

__all__ = ["enabled", "enable", "disable", "range", "annotate"]

_enabled = os.environ.get("RAFT_TPU_TRACE", "0") not in ("0", "", "false")


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


@contextlib.contextmanager
def range(name: str) -> Iterator[None]:  # noqa: A001 - mirrors nvtx::range
    """Context-managed trace range (analog of ``raft::common::nvtx::range``)."""
    if _enabled:
        with jax.profiler.TraceAnnotation(name):
            yield
    else:
        yield


def annotate(name: str | None = None):
    """Decorator form: wrap a public API function in a trace range."""

    def deco(fn):
        label = name or f"raft_tpu::{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return fn(*args, **kwargs)
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco
