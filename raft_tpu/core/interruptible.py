"""Cooperative cross-thread cancellation: analog of ``raft::interruptible``.

Reference: raft/core/interruptible.hpp:71-94 — a per-thread token whose
``cancel()`` makes the target thread's next ``synchronize()`` raise. The TPU
analog hooks the same token protocol into host-side checkpoints between
dispatched XLA computations (device work itself is not preemptible, exactly
as a single CUDA kernel is not).
"""
from __future__ import annotations

import threading
import weakref
from typing import Optional

__all__ = ["InterruptedException", "Token", "get_token", "cancel", "check", "synchronize"]


class InterruptedException(RuntimeError):
    """Raised at the next cancellation point after ``cancel()``."""


class Token:
    """Shared cancellation flag for one logical thread of work."""

    def __init__(self):
        self._flag = threading.Event()

    def cancel(self) -> None:
        self._flag.set()

    def cancelled(self) -> bool:
        return self._flag.is_set()

    def check(self) -> None:
        """Cancellation point: raise (and reset) if cancelled."""
        if self._flag.is_set():
            self._flag.clear()
            raise InterruptedException("raft_tpu: work interrupted")


# Token storage mirrors the reference's weak-pointer TLS design
# (interruptible.hpp:226-233): the thread-local holds the only strong
# reference, so a token dies with its thread and recycled thread idents
# can't inherit a stale cancellation.
_local = threading.local()
_registry: "weakref.WeakValueDictionary[int, Token]" = weakref.WeakValueDictionary()
_lock = threading.Lock()


def get_token(thread_id: Optional[int] = None) -> Token:
    """Get (creating if needed) the token for a thread (default: current).

    A token for another thread can only be *retrieved* while that thread is
    alive and has created one; otherwise a fresh detached token is returned
    (cancel on it is a no-op for everyone else).
    """
    if thread_id is None or thread_id == threading.get_ident():
        tok = getattr(_local, "token", None)
        if tok is None:
            tok = Token()
            _local.token = tok
            with _lock:
                _registry[threading.get_ident()] = tok
        return tok
    with _lock:
        tok = _registry.get(thread_id)
    return tok if tok is not None else Token()


def cancel(thread_id: Optional[int] = None) -> None:
    get_token(thread_id).cancel()


def check() -> None:
    """Cancellation point for the current thread."""
    get_token().check()


def synchronize(value=None):
    """Block on device work, honoring cancellation (analog of
    ``interruptible::synchronize(stream)``). If ``value`` is a jax array (or
    pytree), waits for it; otherwise waits for all dispatched work."""
    check()
    import jax

    if value is None:
        # effect tokens don't cover plain computations; piggyback on PJRT's
        # in-order execution by blocking on a freshly dispatched trivial op
        jax.effects_barrier()
        jax.device_put(0, jax.devices()[0]).block_until_ready()
    else:
        jax.block_until_ready(value)
    check()
    return value
