"""Deterministic fault injection at named sites.

The reference hardens its layers with contract macros and status checks
(raft/core/error.hpp, NCCL status checking in the comms layer) but has no
way to *exercise* the failure paths on demand; raft_tpu's resilience layer
(guarded kernel fallback, deadline propagation, degraded sharded search,
durable index I/O) is only trustworthy if every failure path is
deterministically testable. This module provides that: probes at named
sites that can be armed from the environment or from a context manager to
force kernel compile failure, shard death, byte corruption, I/O errors,
and slow dispatch.

Spec grammar (``RAFT_TPU_FAULTS``, comma-separated)::

    kind@pattern[:count][=value]

* ``kind`` — fault kind a probe asks about: ``kernel_compile`` (a
  per-call simulated kernel failure — never moves a circuit breaker),
  ``kernel_fault`` (a simulated *persistent* kernel failure: drives the
  ``ops/guarded`` breaker open and keeps its probes failing while
  armed), ``shard_dead``, ``shard_timeout``, ``corrupt_bytes``,
  ``io_error``, ``slow_dispatch``, ``crash_point`` (simulated process
  death at a named site: the probe raises :class:`InjectedCrash`, a
  ``BaseException`` that no containment layer may swallow — the
  crash-drill harness catches it, then exercises ``recover()`` on the
  on-disk state exactly as a restarted process would), ``wal_torn_tail``
  (a write cut mid-frame: :func:`cut` returns only a prefix of the
  bytes and the probing writer raises :class:`InjectedCrash`, leaving a
  torn frame on disk for recovery to truncate) — kinds are open
  strings; probes define meaning.
* ``pattern`` — fnmatch pattern over the site name (default ``*``).
* ``count`` — fire at most this many times (default unlimited).
* ``value`` — kind-specific argument (sleep seconds for
  ``slow_dispatch``, byte offset for ``corrupt_bytes``).

Examples::

    RAFT_TPU_FAULTS='kernel_compile@*'            # every gated kernel fails
    RAFT_TPU_FAULTS='shard_dead@*.shard1'         # shard 1 reported dead
    RAFT_TPU_FAULTS='io_error@core.serialize.*:1' # first save attempt dies
    RAFT_TPU_FAULTS='slow_dispatch@ivf_flat.*=0.05'

In-process, prefer the :func:`inject` context manager — it is scoped,
composable and needs no env round trip. Probes are cheap when nothing is
armed (one lock-free list check), so library sites stay probed in
production builds.

For multi-phase chaos drills, :class:`Scenario` sequences timed stages
(arm → hold → clear) against an injectable clock, so one deterministic
script can drive a whole failure-and-recovery arc — inject a kernel
fault and a dead shard, hold them while breakers open and the brownout
ladder engages, clear them and watch the probes restore baseline
(docs/robustness.md "Chaos drills").
"""
from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import os
import threading
import time
from typing import List, Optional

from .errors import RaftError

__all__ = ["InjectedFault", "InjectedCrash", "Fault", "inject", "fired",
           "check", "sleep_if", "corrupt", "crash", "cut", "active",
           "seen_sites", "reload_env", "reset_stats", "Scenario"]


class InjectedFault(RaftError):
    """Raised by a fault probe when an armed fault fires at its site."""

    def __init__(self, kind: str, site: str):
        self.kind = kind
        self.site = site
        super().__init__(f"injected fault {kind!r} at site {site!r}")


class InjectedCrash(BaseException):
    """Simulated process death at a named crash point.

    Deliberately NOT an :class:`Exception` (and not an
    :class:`InjectedFault`): every containment layer in the tree —
    ``guarded_call``'s broad ``except Exception``, telemetry guards,
    merge abandon handlers — must treat it like a kill signal and let
    it propagate, because the real event it simulates gives the process
    no chance to run any handler at all. The crash-drill harness arms
    ``crash_point@<site>:1``, catches this at the top, and then drives
    ``recover()`` against whatever reached disk."""

    def __init__(self, kind: str, site: str):
        self.kind = kind
        self.site = site
        super().__init__(f"injected crash {kind!r} at site {site!r}")


@dataclasses.dataclass
class Fault:
    """One armed fault: kind + site pattern + optional budget/argument."""

    kind: str
    pattern: str = "*"
    count: Optional[int] = None     # None → unlimited firings
    value: Optional[str] = None     # kind-specific argument
    fires: int = 0                  # times this fault has fired

    def matches(self, kind: str, site: str) -> bool:
        return (self.kind == kind
                and (self.count is None or self.fires < self.count)
                and fnmatch.fnmatch(site, self.pattern))


_lock = threading.Lock()
_injected: List[Fault] = []         # context-manager-armed
_env_faults: List[Fault] = []       # RAFT_TPU_FAULTS-armed
_env_loaded = False
_seen_sites: set = set()            # every site that ever probed


def _parse_spec(spec: str) -> Fault:
    kind, _, rest = spec.strip().partition("@")
    if not kind:
        raise ValueError(f"bad fault spec {spec!r}: empty kind")
    pattern = rest or "*"
    value = None
    if "=" in pattern:
        pattern, _, value = pattern.partition("=")
    count = None
    if ":" in pattern:
        pattern, _, c = pattern.partition(":")
        count = int(c)
    return Fault(kind, pattern or "*", count, value)


def _load_env() -> None:
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        spec = os.environ.get("RAFT_TPU_FAULTS", "")
        _env_faults.clear()
        for part in spec.split(","):
            if part.strip():
                _env_faults.append(_parse_spec(part))
        _env_loaded = True


def reload_env() -> None:
    """Re-read ``RAFT_TPU_FAULTS`` (tests that monkeypatch the env)."""
    global _env_loaded
    with _lock:
        _env_loaded = False
    _load_env()


@contextlib.contextmanager
def inject(kind: str, pattern: str = "*", count: Optional[int] = None,
           value=None):
    """Arm a fault for the dynamic extent of the block (thread-shared:
    probes on any thread see it, like an env-armed fault)."""
    f = Fault(kind, pattern, count, None if value is None else str(value))
    with _lock:
        _injected.append(f)
    try:
        yield f
    finally:
        with _lock:
            _injected.remove(f)


def fired(kind: str, site: str) -> Optional[Fault]:
    """Probe: does an armed fault of ``kind`` fire at ``site``? Consumes
    one firing from the first matching fault's budget. Lock-free when
    nothing is armed (the hot-path case: probes sit on per-chunk search
    dispatch); the race with a concurrently-arming context manager is
    benign — its window simply starts at the next probe."""
    _load_env()
    if not _injected and not _env_faults:
        return None
    hit = None
    first = False
    with _lock:
        _seen_sites.add(site)
        for f in _injected + _env_faults:
            if f.matches(kind, site):
                f.fires += 1
                first = f.fires == 1
                hit = f
                break
    if hit is not None:
        _emit_fire(kind, site, first)
    return hit


def _emit_fire(kind: str, site: str, first: bool) -> None:
    """Telemetry for a fired fault: a site-labeled counter on EVERY fire,
    but a flight-recorder event (stamped with the active trace IDs) only
    on the fault's FIRST — a per-batch drill firing 50x/s must not churn
    the bounded ring out of the demotion/shed events that reconstruct
    its blast radius; the counter carries the magnitude. Outside the
    probe lock; never raises (telemetry must not change fault
    semantics)."""
    try:
        if first:
            from . import events as _events

            _events.record("fault_injected", site, fault_kind=kind)
        from ..serve import metrics as _metrics

        _metrics.counter(f"faults.fired.{kind}.{site}").inc()
    except Exception:  # noqa: BLE001 - telemetry must not break injection
        pass


def check(kind: str, site: str) -> None:
    """Raise :class:`InjectedFault` when an armed fault fires here."""
    if fired(kind, site) is not None:
        raise InjectedFault(kind, site)


def sleep_if(site: str, default_s: float = 0.01) -> None:
    """``slow_dispatch`` probe: sleep the armed duration at this site."""
    f = fired("slow_dispatch", site)
    if f is not None:
        time.sleep(float(f.value) if f.value else default_s)


def corrupt(site: str, data):
    """``corrupt_bytes`` probe: flip one bit of ``data`` (any bytes-like;
    returned unchanged — not copied — when unarmed) at the armed byte
    offset, else the middle byte. No-op on empty data."""
    f = fired("corrupt_bytes", site)
    if f is None or not data:
        return data
    off = int(f.value) if f.value else len(data) // 2
    off = max(0, min(off, len(data) - 1))
    out = bytearray(data)
    out[off] ^= 0x01
    return bytes(out)


def crash(site: str) -> None:
    """``crash_point`` probe: simulate the process dying HERE.

    Raises :class:`InjectedCrash` when armed — a ``BaseException``, so
    no ``except Exception`` containment path can accidentally "survive"
    a crash the drill meant to be fatal. Durable-state writers place
    these probes at the instants whose on-disk state recovery must
    handle (mid-WAL-append, pre/post-manifest-flip, mid-merge)."""
    if fired("crash_point", site) is not None:
        raise InjectedCrash("crash_point", site)


def cut(site: str, data: bytes) -> bytes:
    """``wal_torn_tail`` probe: simulate a write torn mid-frame.

    When armed, returns only a prefix of ``data`` (the armed byte
    offset, else half) and the caller is expected to write that prefix
    and then die — :func:`WriteAheadLog.append
    <raft_tpu.core.wal.WriteAheadLog.append>` raises
    :class:`InjectedCrash` after flushing the torn prefix, so the file
    recovery sees is exactly what a power cut mid-``write(2)`` leaves.
    Unarmed: returns ``data`` unchanged (not copied)."""
    f = fired("wal_torn_tail", site)
    if f is None or not data:
        return data
    off = int(f.value) if f.value else len(data) // 2
    return bytes(data[: max(1, min(off, len(data) - 1))])


def active() -> List[Fault]:
    """Currently armed faults (context + env), for diagnostics."""
    _load_env()
    with _lock:
        return list(_injected + _env_faults)


def seen_sites() -> set:
    """Site names that probed while any fault was armed (the unarmed
    fast path skips the bookkeeping — see ``fired``)."""
    with _lock:
        return set(_seen_sites)


def reset_stats() -> None:
    with _lock:
        _seen_sites.clear()
        for f in _injected + _env_faults:
            f.fires = 0


@dataclasses.dataclass
class _Stage:
    """One timed stage of a :class:`Scenario`: a fault armed at
    ``at_s`` (relative to scenario start) and cleared at ``until_s``
    (None = held until :meth:`Scenario.stop`)."""

    fault: Fault
    at_s: float
    until_s: Optional[float]
    armed: bool = False
    done: bool = False


class Scenario:
    """A timed fault scenario: stages arm → hold → clear on a shared
    clock, applied by explicit :meth:`step` calls — deterministic under
    an injectable clock (no timer threads; tests step a fake clock, a
    serving loop calls ``step`` from its maintenance tick).

    ::

        sc = (faults.Scenario()
              .add("kernel_fault", "cagra.*", at_s=0.0, until_s=5.0)
              .add("shard_dead", "*.shard1", at_s=1.0, until_s=5.0)
              .start())
        ...
        sc.step()    # applies any due arms/clears; returns transitions

    Stages use the same :class:`Fault` machinery as :func:`inject`
    (thread-shared, composable with env-armed faults). Each transition
    is flight-recorded as a ``fault_scenario`` event, so the drill's
    timeline is readable next to the breaker/brownout events it
    provokes. Context-manager form clears everything on exit."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._stages: List[_Stage] = []
        self._t0: Optional[float] = None

    def add(self, kind: str, pattern: str = "*", *, at_s: float = 0.0,
            until_s: Optional[float] = None, count: Optional[int] = None,
            value=None) -> "Scenario":
        if until_s is not None and until_s < at_s:
            raise ValueError(
                f"stage {kind}@{pattern}: until_s {until_s} < at_s {at_s}")
        self._stages.append(_Stage(
            Fault(kind, pattern, count, None if value is None else
                  str(value)), float(at_s), until_s))
        return self

    def start(self) -> "Scenario":
        if self._t0 is not None:
            raise RuntimeError("scenario already started")
        self._t0 = self._clock()
        self.step()
        return self

    def elapsed_s(self) -> float:
        if self._t0 is None:
            return 0.0
        return self._clock() - self._t0

    def step(self) -> List[str]:
        """Apply every stage transition whose time has come; returns
        human-readable transition descriptions (empty when nothing was
        due)."""
        if self._t0 is None:
            raise RuntimeError("scenario not started")
        now_s = self._clock() - self._t0
        out: List[str] = []
        for st in self._stages:
            if not st.armed and not st.done and now_s >= st.at_s:
                with _lock:
                    _injected.append(st.fault)
                st.armed = True
                out.append(f"armed {st.fault.kind}@{st.fault.pattern}")
                self._emit("armed", st, now_s)
            if st.armed and st.until_s is not None and now_s >= st.until_s:
                self._clear(st, now_s, out)
        return out

    def _clear(self, st: _Stage, now_s: float, out: List[str]) -> None:
        with _lock:
            if st.fault in _injected:
                _injected.remove(st.fault)
        st.armed = False
        st.done = True
        out.append(f"cleared {st.fault.kind}@{st.fault.pattern}")
        self._emit("cleared", st, now_s)

    def _emit(self, action: str, st: _Stage, now_s: float) -> None:
        try:
            from . import events as _events

            _events.record("fault_scenario",
                           f"{st.fault.kind}@{st.fault.pattern}",
                           action=action, at_s=round(now_s, 3),
                           fires=st.fault.fires)
        except Exception:  # noqa: BLE001 - telemetry must not change
            pass           # fault semantics

    def finished(self) -> bool:
        """True once every stage has been armed and cleared."""
        return all(st.done for st in self._stages)

    def stages(self) -> List[dict]:
        """JSON-safe view of the schedule (one dict per stage, in add
        order) — a chaos plan's artifact records exactly what it armed
        and when, and two same-seed runs must produce identical lists."""
        return [{"kind": st.fault.kind, "pattern": st.fault.pattern,
                 "at_s": st.at_s, "until_s": st.until_s,
                 "count": st.fault.count, "value": st.fault.value,
                 "armed": st.armed, "done": st.done,
                 "fires": st.fault.fires}
                for st in self._stages]

    def stop(self) -> None:
        """Clear every still-armed stage (and mark pending ones done)."""
        if self._t0 is None:
            return
        now_s = self._clock() - self._t0
        out: List[str] = []
        for st in self._stages:
            if st.armed:
                self._clear(st, now_s, out)
            st.done = True

    def __enter__(self) -> "Scenario":
        return self.start() if self._t0 is None else self

    def __exit__(self, *exc) -> None:
        self.stop()
