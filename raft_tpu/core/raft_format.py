"""RAFT-native index file interop: load (and write) pylibraft-serialized
IVF-Flat / IVF-PQ / CAGRA index files.

The reference serializes indexes as a STREAM OF NUMPY FRAMES — each
scalar and mdspan is one complete ``.npy`` blob (magic + header + raw
bytes): core/detail/mdspan_numpy_serializer.hpp (``serialize_scalar``
writes a 0-d array, ``serialize_mdspan`` an n-d one). Python's
``np.lib.format.read_array`` consumes exactly one frame, so a file is a
sequence of ``read_array`` calls mirroring the C++ field order:

* IVF-PQ  — detail/ivf_pq_serialize.cuh:60-87 (version 3): version,
  size, dim, pq_bits, pq_dim, conservative_memory_allocation, metric,
  codebook_kind, n_lists; pq_centers, centers [n_lists, dim_ext],
  centers_rot, rotation_matrix; list_sizes u32; then per list: size
  scalar + interleaved code array + indices.
* IVF-Flat — detail/ivf_flat_serialize.cuh:54-92 (version 4): a 4-byte
  numpy dtype tag for T (``"<f4\\0"`` — serialize:54-57 writes
  ``dtype_string.resize(4)``), then version, size, dim, n_lists, metric,
  adaptive_centers, conservative, centers, has_norms(+norms),
  list_sizes; per list ``ivf::serialize_list`` (ivf_list.hpp:120-148)
  with ``size_override = Pow2<32>::roundUp(size)``: the ROUNDED size
  scalar, a 2-D ``(rounded, dim)`` data frame whose raw bytes are the
  interleaved in-memory layout (make_list_extents is flat —
  ivf_flat_types.hpp:114-117), and a ``rounded``-long indices frame
  (padding entries hold kInvalidRecord, ivf_list_types.hpp:33-35).
* CAGRA — detail/cagra/cagra_serialize.cuh:33-83 (version **3**): the
  same 4-byte dtype tag, then version, size, dim, graph_degree, metric,
  graph [n, degree], include_dataset (+dataset).

IVF-PQ files carry NO dtype tag (codes are always u8) and serialize
lists with the UNROUNDED size and the 4-D interleaved extent
(ivf_pq_serialize.cuh:85, ivf_pq_types.hpp:204-212).

List payloads use the reference's interleaved group layout
(ivf_pq_types.hpp:166-214 / ivf_flat_types.hpp:114-166): rows grouped by
``kIndexGroupSize``=32, components chunked by a 16-byte vector
(``kIndexGroupVecLen``; PQ codes are a little-endian bitfield inside
each 16-byte chunk — detail/ivf_pq_codepacking.cuh bitfield_view_t).
The decoders below invert that layout with vectorized numpy. The
loaders are pinned by byte-level goldens built with an independent
in-test reimplementation of the reference's write_header; the writers
are pinned frame-for-frame against those goldens — same field order,
scalar dtypes, shapes, and payload bytes (tests/test_raft_format.py::
TestReferenceWireFormat). Whole-file bytes may differ from C++ output
only in npy header whitespace (numpy emits a trailing ", " in the
header dict; RAFT's parser tolerates it and vice versa) — self-
round-trips alone cannot validate a wire format.
"""
from __future__ import annotations

import io
from typing import BinaryIO, Optional, Tuple

import numpy as np

from .errors import expects
from ..distance.distance_types import DistanceType

__all__ = [
    "load_raft_ivf_pq", "save_raft_ivf_pq",
    "load_raft_ivf_flat", "save_raft_ivf_flat",
    "load_raft_cagra", "save_raft_cagra",
]

_GROUP = 32          # kIndexGroupSize
_VEC = 16            # kIndexGroupVecLen (bytes)

# reference enum values (distance/distance_types.hpp:26-66); enums
# serialize via their underlying int -> i4 frames
# (mdspan_numpy_serializer.hpp:147-151)
_METRIC_BY_INT = {
    0: DistanceType.L2Expanded,
    1: DistanceType.L2SqrtExpanded,
    2: DistanceType.CosineExpanded,
    3: DistanceType.L1,
    4: DistanceType.L2Unexpanded,
    5: DistanceType.L2SqrtUnexpanded,
    6: DistanceType.InnerProduct,
    7: DistanceType.Linf,
    8: DistanceType.Canberra,
    9: DistanceType.LpUnexpanded,
    10: DistanceType.CorrelationExpanded,
    11: DistanceType.JaccardExpanded,
    12: DistanceType.HellingerExpanded,
    13: DistanceType.Haversine,
    14: DistanceType.BrayCurtis,
    15: DistanceType.JensenShannon,
    16: DistanceType.HammingUnexpanded,
    17: DistanceType.KLDivergence,
    18: DistanceType.RusselRaoExpanded,
    19: DistanceType.DiceExpanded,
    100: DistanceType.Precomputed,
}
_INT_BY_METRIC = {m: i for i, m in _METRIC_BY_INT.items()}


def _read(f: BinaryIO):
    """One npy frame (scalar frames come back as python scalars)."""
    arr = np.lib.format.read_array(f, allow_pickle=False)
    if arr.ndim == 0:
        return arr[()]
    return arr


def _write(f: BinaryIO, value, dtype=None) -> None:
    """One npy frame, mirroring serialize_scalar/serialize_mdspan."""
    arr = np.asarray(value, dtype=dtype)
    np.lib.format.write_array(f, arr, allow_pickle=False)


def _open(path_or_file, mode: str):
    if hasattr(path_or_file, "read") or hasattr(path_or_file, "write"):
        return path_or_file, False
    return open(path_or_file, mode), True


def _read_dtype_tag(f: BinaryIO) -> np.dtype:
    """The 4-byte numpy dtype tag (``"%c%c%u"`` + NUL padding) RAFT puts
    before the first frame of IVF-Flat / CAGRA files
    (mdspan_numpy_serializer.hpp:89-94, ivf_flat_serialize.cuh:54-57)."""
    raw = f.read(4)
    expects(len(raw) == 4, "truncated dtype tag")
    try:
        return np.dtype(raw.rstrip(b"\0").decode("ascii"))
    except (TypeError, ValueError, UnicodeDecodeError):
        expects(False, "bad dtype tag %r — not a RAFT-native file (files "
                "written before the r5 wire-format fix carry no tag)", raw)


def _write_dtype_tag(f: BinaryIO, dtype: np.dtype) -> None:
    dt = np.dtype(dtype)
    expects(dt.kind in "fiu", "no RAFT dtype tag for %s", dt)
    byteorder = "|" if dt.itemsize == 1 else "<"
    tag = f"{byteorder}{dt.kind}{dt.itemsize}".encode("ascii")
    f.write(tag.ljust(4, b"\0"))


def _round_up(n: int, align: int) -> int:
    return -(-n // align) * align


# --------------------------------------------------------------------------
# interleaved list payload codecs
# --------------------------------------------------------------------------

def _unpack_interleaved_rows(data: np.ndarray, size: int) -> np.ndarray:
    """(ngroups, nchunks, 32, veclen) interleaved rows → (size, dim)."""
    ngroups, nchunks, g, veclen = data.shape
    rows = data.transpose(0, 2, 1, 3).reshape(ngroups * g, nchunks * veclen)
    return rows[:size]


def _pack_interleaved_rows(rows: np.ndarray, veclen: int) -> np.ndarray:
    """(size, dim) → (ngroups, dim//veclen, 32, veclen) interleaved."""
    size, dim = rows.shape
    expects(dim % veclen == 0, "dim %d not a multiple of veclen %d",
            dim, veclen)
    ngroups = -(-size // _GROUP)
    pad = np.zeros((ngroups * _GROUP, dim), rows.dtype)
    pad[:size] = rows
    return np.ascontiguousarray(
        pad.reshape(ngroups, _GROUP, dim // veclen, veclen)
        .transpose(0, 2, 1, 3))


def _unpack_interleaved_pq(data: np.ndarray, size: int, pq_dim: int,
                           pq_bits: int) -> np.ndarray:
    """Interleaved bitfield codes → (size, pq_dim) u8.

    ``data``: (ngroups, nchunks, 32, 16) u8; each 16-byte chunk holds
    ``(16*8)//pq_bits`` codes as a little-endian bitfield."""
    ngroups, nchunks, g, v = data.shape
    pq_chunk = (v * 8) // pq_bits
    rows = data.transpose(0, 2, 1, 3).reshape(ngroups * g, nchunks, v)
    rows = rows[:size]
    bits = np.unpackbits(rows, axis=2, bitorder="little")  # (size, nc, 128)
    weights = (1 << np.arange(pq_bits, dtype=np.uint16))
    codes = np.zeros((size, pq_dim), np.uint8)
    for j in range(pq_dim):
        c, within = divmod(j, pq_chunk)
        sl = bits[:, c, within * pq_bits : (within + 1) * pq_bits]
        codes[:, j] = (sl.astype(np.uint16) * weights).sum(1).astype(np.uint8)
    return codes


def _pack_interleaved_pq(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """(size, pq_dim) u8 → interleaved bitfield (inverse of the above)."""
    size, pq_dim = codes.shape
    pq_chunk = (_VEC * 8) // pq_bits
    nchunks = -(-pq_dim // pq_chunk)
    ngroups = -(-size // _GROUP)
    bits = np.zeros((ngroups * _GROUP, nchunks, _VEC * 8), np.uint8)
    for j in range(pq_dim):
        c, within = divmod(j, pq_chunk)
        for b in range(pq_bits):
            bits[:size, c, within * pq_bits + b] = (codes[:, j] >> b) & 1
    packed = np.packbits(bits, axis=2, bitorder="little")  # (rows, nc, 16)
    return np.ascontiguousarray(
        packed.reshape(ngroups, _GROUP, nchunks, _VEC).transpose(0, 2, 1, 3))


# --------------------------------------------------------------------------
# IVF-PQ
# --------------------------------------------------------------------------

def load_raft_ivf_pq(path_or_file):
    """pylibraft-serialized ``.ivf_pq`` file → :class:`ivf_pq.Index`."""
    import jax.numpy as jnp

    from ..neighbors import ivf_pq

    f, close = _open(path_or_file, "rb")
    try:
        ver = int(_read(f))
        expects(ver == 3, "unsupported RAFT ivf_pq serialization version "
                "%d (expected 3, RAFT 24.02)", ver)
        n = int(_read(f))
        dim = int(_read(f))
        pq_bits = int(_read(f))
        pq_dim = int(_read(f))
        _conservative = bool(_read(f))
        metric = _METRIC_BY_INT[int(_read(f))]
        kind = ivf_pq.CodebookGen(int(_read(f)))
        n_lists = int(_read(f))

        pq_centers = _read(f)           # PER_SUBSPACE: (pq_dim, len, book)
        _centers = _read(f)             # (n_lists, dim_ext) — unused here
        centers_rot = _read(f)          # (n_lists, rot_dim)
        rotation = _read(f)             # (rot_dim, dim)
        list_sizes = np.asarray(_read(f), np.int64)

        codes_parts, ids_parts = [], []
        for label in range(n_lists):
            sz = int(_read(f))
            expects(sz == int(list_sizes[label]),
                    "list %d size mismatch (%d vs %d)", label, sz,
                    int(list_sizes[label]))
            if sz == 0:
                continue
            data = _read(f)
            inds = _read(f)
            codes_parts.append(_unpack_interleaved_pq(data, sz, pq_dim,
                                                      pq_bits))
            ids_parts.append(np.asarray(inds[:sz], np.int64))
        codes = (np.concatenate(codes_parts) if codes_parts
                 else np.zeros((0, pq_dim), np.uint8))
        ids = (np.concatenate(ids_parts) if ids_parts
               else np.zeros((0,), np.int64))
        expects(len(codes) == n, "row count mismatch (%d vs %d)",
                len(codes), n)
        expects(ids.size == 0 or ids.max() < 2 ** 31,
                "source ids exceed int32 (raft_tpu stores int32 ids)")

        offsets = np.zeros(n_lists + 1, np.int64)
        np.cumsum(list_sizes, out=offsets[1:])
        # reference pq_centers: (pq_dim|n_lists, pq_len, book) — ours is
        # (pq_dim|n_lists, book, pq_len)
        codebooks = np.ascontiguousarray(pq_centers.transpose(0, 2, 1))
        return ivf_pq.Index(
            jnp.asarray(codes), jnp.asarray(ids, jnp.int32),
            jnp.asarray(centers_rot), jnp.asarray(codebooks),
            jnp.asarray(rotation), offsets, metric, pq_bits, kind,
            list_sizes_arr=list_sizes)
    finally:
        if close:
            f.close()


def save_raft_ivf_pq(index, path_or_file) -> None:
    """:class:`ivf_pq.Index` → a file pylibraft's deserializer accepts
    (version-3 layout above)."""
    from ..neighbors.ivf_pq import CodebookGen

    f, close = _open(path_or_file, "wb")
    try:
        sizes = index.list_sizes
        _write(f, np.int32(3))
        _write(f, np.int64(index.size))
        _write(f, np.uint32(index.dim))
        _write(f, np.uint32(index.pq_bits))
        _write(f, np.uint32(index.pq_dim))
        _write(f, np.uint8(0))          # conservative_memory_allocation
        _write(f, np.int32(_INT_BY_METRIC[index.metric]))
        _write(f, np.int32(index.codebook_kind.value))
        _write(f, np.uint32(index.n_lists))

        cb = np.asarray(index.codebooks, np.float32)      # (s|L, book, len)
        _write(f, np.ascontiguousarray(cb.transpose(0, 2, 1)))
        centers_rot = np.asarray(index.centers_rot, np.float32)
        # centers in the original space, extended layout [n_lists,
        # dim_ext]; raft_tpu keeps everything rotated, so back-project
        rot = np.asarray(index.rotation, np.float32)
        centers = centers_rot @ rot
        # reference dim_ext() = round_up(dim + 1, 8) (ivf_pq_types.hpp:280)
        dim_ext = -(-(index.dim + 1) // 8) * 8
        cent_ext = np.zeros((index.n_lists, dim_ext), np.float32)
        cent_ext[:, : index.dim] = centers
        cent_ext[:, index.dim] = (centers * centers).sum(1)
        _write(f, cent_ext)
        _write(f, centers_rot)
        _write(f, rot)
        _write(f, np.asarray(sizes, np.uint32))

        codes = np.asarray(index.codes, np.uint8)
        ids = np.asarray(index.source_ids, np.int64)
        offsets = np.asarray(index.list_offsets)
        for label in range(index.n_lists):
            sz = int(sizes[label])
            _write(f, np.uint32(sz))
            if sz == 0:
                continue
            lo = int(offsets[label])
            _write(f, _pack_interleaved_pq(codes[lo : lo + sz],
                                           index.pq_bits))
            _write(f, ids[lo : lo + sz])
    finally:
        if close:
            f.close()


# --------------------------------------------------------------------------
# IVF-Flat
# --------------------------------------------------------------------------

def load_raft_ivf_flat(path_or_file):
    """pylibraft-serialized ``.ivf_flat`` file → :class:`ivf_flat.Index`."""
    import jax.numpy as jnp

    from ..neighbors import ivf_flat

    f, close = _open(path_or_file, "rb")
    try:
        dtype = _read_dtype_tag(f)
        ver = int(_read(f))
        expects(ver == 4, "unsupported RAFT ivf_flat serialization "
                "version %d (expected 4, RAFT 24.02)", ver)
        n = int(_read(f))
        dim = int(_read(f))
        n_lists = int(_read(f))
        metric = _METRIC_BY_INT[int(_read(f))]
        _adaptive = bool(_read(f))
        _conservative = bool(_read(f))
        centers = _read(f)
        has_norms = bool(_read(f))
        center_norms = _read(f) if has_norms else None
        list_sizes = np.asarray(_read(f), np.int64)

        # calculate_veclen (ivf_flat_types.hpp:385-395)
        veclen = max(1, 16 // dtype.itemsize)
        if dim % veclen != 0:
            veclen = 1

        rows_parts, ids_parts = [], []
        for label in range(n_lists):
            rounded = int(_read(f))   # Pow2<32>::roundUp(list size)
            if rounded == 0:
                continue
            sz = int(list_sizes[label])
            expects(rounded == _round_up(sz, _GROUP),
                    "list %d rounded size %d inconsistent with "
                    "list_sizes %d", label, rounded, sz)
            data = _read(f)           # 2-D (rounded, dim) frame of T whose
            expects(data.shape == (rounded, dim),
                    "list %d data frame shape %s != (%d, %d)", label,
                    tuple(data.shape), rounded, dim)
            expects(data.dtype == dtype, "list %d frame dtype %s != tag %s",
                    label, data.dtype, dtype)
            inds = _read(f)           # rounded-long; tail = kInvalidRecord
            # raw bytes ARE the interleaved group layout; reinterpret
            interleaved = np.ascontiguousarray(data).reshape(
                rounded // _GROUP, dim // veclen, _GROUP, veclen)
            rows_parts.append(_unpack_interleaved_rows(interleaved, sz))
            ids_parts.append(np.asarray(inds[:sz], np.int64))
        rows = (np.concatenate(rows_parts) if rows_parts
                else np.zeros((0, dim), np.float32))
        ids = (np.concatenate(ids_parts) if ids_parts
               else np.zeros((0,), np.int64))
        expects(len(rows) == n, "row count mismatch (%d vs %d)",
                len(rows), n)
        expects(ids.size == 0 or ids.max() < 2 ** 31,
                "source ids exceed int32 (raft_tpu stores int32 ids)")

        offsets = np.zeros(n_lists + 1, np.int64)
        np.cumsum(list_sizes, out=offsets[1:])
        rows_f = np.asarray(rows, np.float32)
        cn = (np.asarray(center_norms, np.float32) if center_norms is
              not None else (centers * centers).sum(1).astype(np.float32))
        return ivf_flat.Index(
            jnp.asarray(rows), jnp.asarray((rows_f * rows_f).sum(1)),
            jnp.asarray(ids, jnp.int32), jnp.asarray(centers),
            jnp.asarray(cn), offsets, metric,
            list_sizes_arr=list_sizes)
    finally:
        if close:
            f.close()


def save_raft_ivf_flat(index, path_or_file) -> None:
    """:class:`ivf_flat.Index` → a version-4 reference-layout file.

    Only float32 storage round-trips (the reference's T is the original
    dtype; raft_tpu's bf16/int8 modes have no reference file analog)."""
    from ..neighbors._list_layout import gather_dense

    f, close = _open(path_or_file, "wb")
    try:
        (rows_j, ids_j), _ = gather_dense(
            (index.data, index.source_ids), index.list_offsets,
            index.list_sizes)
        rows = np.asarray(rows_j)
        ids = np.asarray(ids_j)
        expects(rows.dtype == np.float32,
                "only float32 ivf_flat indexes serialize to the RAFT "
                "format (got %s)", rows.dtype)
        dim = index.dim
        # reference calculate_veclen (ivf_flat_types.hpp:385-395): f32
        # veclen = 16/sizeof(T) = 4, falling straight to 1 when dim is
        # not a multiple of it
        veclen = 4 if dim % 4 == 0 else 1
        sizes = index.list_sizes
        _write_dtype_tag(f, np.float32)
        _write(f, np.int32(4))
        _write(f, np.int64(index.size))
        _write(f, np.uint32(dim))
        _write(f, np.uint32(index.n_lists))
        _write(f, np.int32(_INT_BY_METRIC[index.metric]))
        _write(f, np.uint8(0))          # adaptive_centers
        _write(f, np.uint8(int(index.conservative_memory)))
        _write(f, np.asarray(index.centers, np.float32))
        _write(f, np.uint8(1))
        _write(f, np.asarray(index.center_norms, np.float32))
        _write(f, np.asarray(sizes, np.uint32))
        off = 0
        for label in range(index.n_lists):
            sz = int(sizes[label])
            rounded = _round_up(sz, _GROUP)
            _write(f, np.uint32(rounded))
            if sz == 0:
                continue
            # interleave, then emit as the flat (rounded, dim) frame the
            # reference memcpys (make_list_extents, ivf_flat_types.hpp:114)
            packed = _pack_interleaved_rows(rows[off : off + sz], veclen)
            _write(f, packed.reshape(rounded, dim))
            # indices padded to rounded with kInvalidRecord (= -1 for
            # signed IdxT, ivf_list_types.hpp:33-35)
            inds = np.full(rounded, -1, np.int64)
            inds[:sz] = ids[off : off + sz]
            _write(f, inds)
            off += sz
    finally:
        if close:
            f.close()


# --------------------------------------------------------------------------
# CAGRA
# --------------------------------------------------------------------------

def load_raft_cagra(path_or_file, dataset: Optional[np.ndarray] = None):
    """pylibraft-serialized ``.cagra`` file → :class:`cagra.Index`.

    Files written with ``include_dataset=False`` need ``dataset``."""
    import jax.numpy as jnp

    from ..neighbors import cagra

    f, close = _open(path_or_file, "rb")
    try:
        _dtype = _read_dtype_tag(f)
        ver = int(_read(f))
        expects(ver == 3, "unsupported RAFT cagra serialization version "
                "%d (expected 3, RAFT 24.02)", ver)
        n = int(_read(f))
        dim = int(_read(f))
        _degree = int(_read(f))
        metric = _METRIC_BY_INT[int(_read(f))]
        graph = np.asarray(_read(f), np.int32)
        include_dataset = bool(_read(f))
        if include_dataset:
            dataset = _read(f)
        expects(dataset is not None,
                "file has no dataset (include_dataset=false); pass one")
        expects(dataset.shape == (n, dim), "dataset shape mismatch %s",
                tuple(dataset.shape))
        return cagra.Index(jnp.asarray(dataset, jnp.float32),
                           jnp.asarray(graph), metric, None)
    finally:
        if close:
            f.close()


def save_raft_cagra(index, path_or_file, include_dataset: bool = True
                    ) -> None:
    """:class:`cagra.Index` → a version-3 reference-layout file."""
    f, close = _open(path_or_file, "wb")
    try:
        n, degree = index.graph.shape
        _write_dtype_tag(f, np.float32)
        _write(f, np.int32(3))
        # pylibraft instantiates cagra::index<T, uint32_t> (c_cagra.pxd:117)
        # so size() serializes as a u4 scalar, unlike ivf_flat's int64
        _write(f, np.uint32(n))
        _write(f, np.uint32(index.dataset.shape[1]))
        _write(f, np.uint32(degree))
        _write(f, np.int32(_INT_BY_METRIC[index.metric]))
        _write(f, np.asarray(index.graph, np.uint32))
        _write(f, np.uint8(int(include_dataset)))
        if include_dataset:
            _write(f, np.asarray(index.dataset, np.float32))
    finally:
        if close:
            f.close()
