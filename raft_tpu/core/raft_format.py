"""RAFT-native index file interop: load (and write) pylibraft-serialized
IVF-Flat / IVF-PQ / CAGRA index files.

The reference serializes indexes as a STREAM OF NUMPY FRAMES — each
scalar and mdspan is one complete ``.npy`` blob (magic + header + raw
bytes): core/detail/mdspan_numpy_serializer.hpp (``serialize_scalar``
writes a 0-d array, ``serialize_mdspan`` an n-d one). Python's
``np.lib.format.read_array`` consumes exactly one frame, so a file is a
sequence of ``read_array`` calls mirroring the C++ field order:

* IVF-PQ  — detail/ivf_pq_serialize.cuh:60-87 (version 3): version,
  size, dim, pq_bits, pq_dim, conservative_memory_allocation, metric,
  codebook_kind, n_lists; pq_centers, centers [n_lists, dim_ext],
  centers_rot, rotation_matrix; list_sizes u32; then per list: size
  scalar + interleaved code array + indices.
* IVF-Flat — detail/ivf_flat_serialize.cuh:59-92 (version 4): version,
  size, dim, n_lists, metric, adaptive_centers, conservative, centers,
  has_norms(+norms), list_sizes; per-list interleaved rows + indices.
* CAGRA — detail/cagra/cagra_serialize.cuh:61-82 (version 4): version,
  size, dim, graph_degree, metric, graph [n, degree], include_dataset
  (+dataset).

List payloads use the reference's interleaved group layout
(ivf_pq_types.hpp:166-214 / ivf_flat_types.hpp:114-166): rows grouped by
``kIndexGroupSize``=32, components chunked by a 16-byte vector
(``kIndexGroupVecLen``; PQ codes are a little-endian bitfield inside
each 16-byte chunk — detail/ivf_pq_codepacking.cuh bitfield_view_t).
The decoders below invert that layout with vectorized numpy; the
writers produce files the reference can load, tested by round-trip.
"""
from __future__ import annotations

import io
from typing import BinaryIO, Optional, Tuple

import numpy as np

from .errors import expects
from ..distance.distance_types import DistanceType

__all__ = [
    "load_raft_ivf_pq", "save_raft_ivf_pq",
    "load_raft_ivf_flat", "save_raft_ivf_flat",
    "load_raft_cagra", "save_raft_cagra",
]

_GROUP = 32          # kIndexGroupSize
_VEC = 16            # kIndexGroupVecLen (bytes)

# reference enum values (distance/distance_types.hpp:26-66), stored as
# u2 scalars in the files
_METRIC_BY_INT = {
    0: DistanceType.L2Expanded,
    1: DistanceType.L2SqrtExpanded,
    2: DistanceType.CosineExpanded,
    3: DistanceType.L1,
    4: DistanceType.L2Unexpanded,
    5: DistanceType.L2SqrtUnexpanded,
    6: DistanceType.InnerProduct,
    7: DistanceType.Linf,
    8: DistanceType.Canberra,
    9: DistanceType.LpUnexpanded,
    10: DistanceType.CorrelationExpanded,
    11: DistanceType.JaccardExpanded,
    12: DistanceType.HellingerExpanded,
    13: DistanceType.Haversine,
    14: DistanceType.BrayCurtis,
    15: DistanceType.JensenShannon,
    16: DistanceType.HammingUnexpanded,
    17: DistanceType.KLDivergence,
    18: DistanceType.RusselRaoExpanded,
    19: DistanceType.DiceExpanded,
    100: DistanceType.Precomputed,
}
_INT_BY_METRIC = {m: i for i, m in _METRIC_BY_INT.items()}


def _read(f: BinaryIO):
    """One npy frame (scalar frames come back as python scalars)."""
    arr = np.lib.format.read_array(f, allow_pickle=False)
    if arr.ndim == 0:
        return arr[()]
    return arr


def _write(f: BinaryIO, value, dtype=None) -> None:
    """One npy frame, mirroring serialize_scalar/serialize_mdspan."""
    arr = np.asarray(value, dtype=dtype)
    np.lib.format.write_array(f, arr, allow_pickle=False)


def _open(path_or_file, mode: str):
    if hasattr(path_or_file, "read") or hasattr(path_or_file, "write"):
        return path_or_file, False
    return open(path_or_file, mode), True


# --------------------------------------------------------------------------
# interleaved list payload codecs
# --------------------------------------------------------------------------

def _unpack_interleaved_rows(data: np.ndarray, size: int) -> np.ndarray:
    """(ngroups, nchunks, 32, veclen) interleaved rows → (size, dim)."""
    ngroups, nchunks, g, veclen = data.shape
    rows = data.transpose(0, 2, 1, 3).reshape(ngroups * g, nchunks * veclen)
    return rows[:size]


def _pack_interleaved_rows(rows: np.ndarray, veclen: int) -> np.ndarray:
    """(size, dim) → (ngroups, dim//veclen, 32, veclen) interleaved."""
    size, dim = rows.shape
    expects(dim % veclen == 0, "dim %d not a multiple of veclen %d",
            dim, veclen)
    ngroups = -(-size // _GROUP)
    pad = np.zeros((ngroups * _GROUP, dim), rows.dtype)
    pad[:size] = rows
    return np.ascontiguousarray(
        pad.reshape(ngroups, _GROUP, dim // veclen, veclen)
        .transpose(0, 2, 1, 3))


def _unpack_interleaved_pq(data: np.ndarray, size: int, pq_dim: int,
                           pq_bits: int) -> np.ndarray:
    """Interleaved bitfield codes → (size, pq_dim) u8.

    ``data``: (ngroups, nchunks, 32, 16) u8; each 16-byte chunk holds
    ``(16*8)//pq_bits`` codes as a little-endian bitfield."""
    ngroups, nchunks, g, v = data.shape
    pq_chunk = (v * 8) // pq_bits
    rows = data.transpose(0, 2, 1, 3).reshape(ngroups * g, nchunks, v)
    rows = rows[:size]
    bits = np.unpackbits(rows, axis=2, bitorder="little")  # (size, nc, 128)
    weights = (1 << np.arange(pq_bits, dtype=np.uint16))
    codes = np.zeros((size, pq_dim), np.uint8)
    for j in range(pq_dim):
        c, within = divmod(j, pq_chunk)
        sl = bits[:, c, within * pq_bits : (within + 1) * pq_bits]
        codes[:, j] = (sl.astype(np.uint16) * weights).sum(1).astype(np.uint8)
    return codes


def _pack_interleaved_pq(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """(size, pq_dim) u8 → interleaved bitfield (inverse of the above)."""
    size, pq_dim = codes.shape
    pq_chunk = (_VEC * 8) // pq_bits
    nchunks = -(-pq_dim // pq_chunk)
    ngroups = -(-size // _GROUP)
    bits = np.zeros((ngroups * _GROUP, nchunks, _VEC * 8), np.uint8)
    for j in range(pq_dim):
        c, within = divmod(j, pq_chunk)
        for b in range(pq_bits):
            bits[:size, c, within * pq_bits + b] = (codes[:, j] >> b) & 1
    packed = np.packbits(bits, axis=2, bitorder="little")  # (rows, nc, 16)
    return np.ascontiguousarray(
        packed.reshape(ngroups, _GROUP, nchunks, _VEC).transpose(0, 2, 1, 3))


# --------------------------------------------------------------------------
# IVF-PQ
# --------------------------------------------------------------------------

def load_raft_ivf_pq(path_or_file):
    """pylibraft-serialized ``.ivf_pq`` file → :class:`ivf_pq.Index`."""
    import jax.numpy as jnp

    from ..neighbors import ivf_pq

    f, close = _open(path_or_file, "rb")
    try:
        ver = int(_read(f))
        expects(ver == 3, "unsupported RAFT ivf_pq serialization version "
                "%d (expected 3, RAFT 24.02)", ver)
        n = int(_read(f))
        dim = int(_read(f))
        pq_bits = int(_read(f))
        pq_dim = int(_read(f))
        _conservative = bool(_read(f))
        metric = _METRIC_BY_INT[int(_read(f))]
        kind = ivf_pq.CodebookGen(int(_read(f)))
        n_lists = int(_read(f))

        pq_centers = _read(f)           # PER_SUBSPACE: (pq_dim, len, book)
        _centers = _read(f)             # (n_lists, dim_ext) — unused here
        centers_rot = _read(f)          # (n_lists, rot_dim)
        rotation = _read(f)             # (rot_dim, dim)
        list_sizes = np.asarray(_read(f), np.int64)

        codes_parts, ids_parts = [], []
        for label in range(n_lists):
            sz = int(_read(f))
            expects(sz == int(list_sizes[label]),
                    "list %d size mismatch (%d vs %d)", label, sz,
                    int(list_sizes[label]))
            if sz == 0:
                continue
            data = _read(f)
            inds = _read(f)
            codes_parts.append(_unpack_interleaved_pq(data, sz, pq_dim,
                                                      pq_bits))
            ids_parts.append(np.asarray(inds[:sz], np.int64))
        codes = (np.concatenate(codes_parts) if codes_parts
                 else np.zeros((0, pq_dim), np.uint8))
        ids = (np.concatenate(ids_parts) if ids_parts
               else np.zeros((0,), np.int64))
        expects(len(codes) == n, "row count mismatch (%d vs %d)",
                len(codes), n)
        expects(ids.size == 0 or ids.max() < 2 ** 31,
                "source ids exceed int32 (raft_tpu stores int32 ids)")

        offsets = np.zeros(n_lists + 1, np.int64)
        np.cumsum(list_sizes, out=offsets[1:])
        # reference pq_centers: (pq_dim|n_lists, pq_len, book) — ours is
        # (pq_dim|n_lists, book, pq_len)
        codebooks = np.ascontiguousarray(pq_centers.transpose(0, 2, 1))
        return ivf_pq.Index(
            jnp.asarray(codes), jnp.asarray(ids, jnp.int32),
            jnp.asarray(centers_rot), jnp.asarray(codebooks),
            jnp.asarray(rotation), offsets, metric, pq_bits, kind,
            list_sizes_arr=list_sizes)
    finally:
        if close:
            f.close()


def save_raft_ivf_pq(index, path_or_file) -> None:
    """:class:`ivf_pq.Index` → a file pylibraft's deserializer accepts
    (version-3 layout above)."""
    from ..neighbors.ivf_pq import CodebookGen

    f, close = _open(path_or_file, "wb")
    try:
        sizes = index.list_sizes
        _write(f, np.int32(3))
        _write(f, np.int64(index.size))
        _write(f, np.uint32(index.dim))
        _write(f, np.uint32(index.pq_bits))
        _write(f, np.uint32(index.pq_dim))
        _write(f, np.bool_(False))      # conservative_memory_allocation
        _write(f, np.array(_INT_BY_METRIC[index.metric], np.uint16))
        _write(f, np.int32(index.codebook_kind.value))
        _write(f, np.uint32(index.n_lists))

        cb = np.asarray(index.codebooks, np.float32)      # (s|L, book, len)
        _write(f, np.ascontiguousarray(cb.transpose(0, 2, 1)))
        centers_rot = np.asarray(index.centers_rot, np.float32)
        # centers in the original space, extended layout [n_lists,
        # dim_ext]; raft_tpu keeps everything rotated, so back-project
        rot = np.asarray(index.rotation, np.float32)
        centers = centers_rot @ rot
        # reference dim_ext() = round_up(dim + 1, 8) (ivf_pq_types.hpp:280)
        dim_ext = -(-(index.dim + 1) // 8) * 8
        cent_ext = np.zeros((index.n_lists, dim_ext), np.float32)
        cent_ext[:, : index.dim] = centers
        cent_ext[:, index.dim] = (centers * centers).sum(1)
        _write(f, cent_ext)
        _write(f, centers_rot)
        _write(f, rot)
        _write(f, np.asarray(sizes, np.uint32))

        codes = np.asarray(index.codes, np.uint8)
        ids = np.asarray(index.source_ids, np.int64)
        offsets = np.asarray(index.list_offsets)
        for label in range(index.n_lists):
            sz = int(sizes[label])
            _write(f, np.uint32(sz))
            if sz == 0:
                continue
            lo = int(offsets[label])
            _write(f, _pack_interleaved_pq(codes[lo : lo + sz],
                                           index.pq_bits))
            _write(f, ids[lo : lo + sz])
    finally:
        if close:
            f.close()


# --------------------------------------------------------------------------
# IVF-Flat
# --------------------------------------------------------------------------

def load_raft_ivf_flat(path_or_file):
    """pylibraft-serialized ``.ivf_flat`` file → :class:`ivf_flat.Index`."""
    import jax.numpy as jnp

    from ..neighbors import ivf_flat

    f, close = _open(path_or_file, "rb")
    try:
        ver = int(_read(f))
        expects(ver == 4, "unsupported RAFT ivf_flat serialization "
                "version %d (expected 4, RAFT 24.02)", ver)
        n = int(_read(f))
        dim = int(_read(f))
        n_lists = int(_read(f))
        metric = _METRIC_BY_INT[int(_read(f))]
        _adaptive = bool(_read(f))
        _conservative = bool(_read(f))
        centers = _read(f)
        has_norms = bool(_read(f))
        center_norms = _read(f) if has_norms else None
        list_sizes = np.asarray(_read(f), np.int64)

        rows_parts, ids_parts = [], []
        for label in range(n_lists):
            sz = int(_read(f))
            if sz == 0:
                continue
            data = _read(f)
            inds = _read(f)
            rows_parts.append(_unpack_interleaved_rows(data, sz))
            ids_parts.append(np.asarray(inds[:sz], np.int64))
        rows = (np.concatenate(rows_parts) if rows_parts
                else np.zeros((0, dim), np.float32))
        ids = (np.concatenate(ids_parts) if ids_parts
               else np.zeros((0,), np.int64))
        expects(len(rows) == n, "row count mismatch (%d vs %d)",
                len(rows), n)
        expects(ids.size == 0 or ids.max() < 2 ** 31,
                "source ids exceed int32 (raft_tpu stores int32 ids)")

        offsets = np.zeros(n_lists + 1, np.int64)
        np.cumsum(list_sizes, out=offsets[1:])
        rows_f = np.asarray(rows, np.float32)
        cn = (np.asarray(center_norms, np.float32) if center_norms is
              not None else (centers * centers).sum(1).astype(np.float32))
        return ivf_flat.Index(
            jnp.asarray(rows), jnp.asarray((rows_f * rows_f).sum(1)),
            jnp.asarray(ids, jnp.int32), jnp.asarray(centers),
            jnp.asarray(cn), offsets, metric,
            list_sizes_arr=list_sizes)
    finally:
        if close:
            f.close()


def save_raft_ivf_flat(index, path_or_file) -> None:
    """:class:`ivf_flat.Index` → a version-4 reference-layout file.

    Only float32 storage round-trips (the reference's T is the original
    dtype; raft_tpu's bf16/int8 modes have no reference file analog)."""
    from ..neighbors._list_layout import gather_dense

    f, close = _open(path_or_file, "wb")
    try:
        (rows_j, ids_j), _ = gather_dense(
            (index.data, index.source_ids), index.list_offsets,
            index.list_sizes)
        rows = np.asarray(rows_j)
        ids = np.asarray(ids_j)
        expects(rows.dtype == np.float32,
                "only float32 ivf_flat indexes serialize to the RAFT "
                "format (got %s)", rows.dtype)
        dim = index.dim
        # reference calculate_veclen (ivf_flat_types.hpp:385-395): f32
        # veclen = 16/sizeof(T) = 4, falling straight to 1 when dim is
        # not a multiple of it
        veclen = 4 if dim % 4 == 0 else 1
        sizes = index.list_sizes
        _write(f, np.int32(4))
        _write(f, np.int64(index.size))
        _write(f, np.uint32(dim))
        _write(f, np.uint32(index.n_lists))
        _write(f, np.array(_INT_BY_METRIC[index.metric], np.uint16))
        _write(f, np.bool_(False))      # adaptive_centers
        _write(f, np.bool_(index.conservative_memory))
        _write(f, np.asarray(index.centers, np.float32))
        _write(f, np.bool_(True))
        _write(f, np.asarray(index.center_norms, np.float32))
        _write(f, np.asarray(sizes, np.uint32))
        off = 0
        for label in range(index.n_lists):
            sz = int(sizes[label])
            _write(f, np.uint32(sz))
            if sz == 0:
                continue
            _write(f, _pack_interleaved_rows(rows[off : off + sz], veclen))
            _write(f, np.asarray(ids[off : off + sz], np.int64))
            off += sz
    finally:
        if close:
            f.close()


# --------------------------------------------------------------------------
# CAGRA
# --------------------------------------------------------------------------

def load_raft_cagra(path_or_file, dataset: Optional[np.ndarray] = None):
    """pylibraft-serialized ``.cagra`` file → :class:`cagra.Index`.

    Files written with ``include_dataset=False`` need ``dataset``."""
    import jax.numpy as jnp

    from ..neighbors import cagra

    f, close = _open(path_or_file, "rb")
    try:
        ver = int(_read(f))
        expects(ver == 4, "unsupported RAFT cagra serialization version "
                "%d (expected 4, RAFT 24.02)", ver)
        n = int(_read(f))
        dim = int(_read(f))
        _degree = int(_read(f))
        metric = _METRIC_BY_INT[int(_read(f))]
        graph = np.asarray(_read(f), np.int32)
        include_dataset = bool(_read(f))
        if include_dataset:
            dataset = _read(f)
        expects(dataset is not None,
                "file has no dataset (include_dataset=false); pass one")
        expects(dataset.shape == (n, dim), "dataset shape mismatch %s",
                tuple(dataset.shape))
        return cagra.Index(jnp.asarray(dataset, jnp.float32),
                           jnp.asarray(graph), metric, None)
    finally:
        if close:
            f.close()


def save_raft_cagra(index, path_or_file, include_dataset: bool = True
                    ) -> None:
    """:class:`cagra.Index` → a version-4 reference-layout file."""
    f, close = _open(path_or_file, "wb")
    try:
        n, degree = index.graph.shape
        _write(f, np.int32(4))
        _write(f, np.int64(n))
        _write(f, np.uint32(index.dataset.shape[1]))
        _write(f, np.uint32(degree))
        _write(f, np.array(_INT_BY_METRIC[index.metric], np.uint16))
        _write(f, np.asarray(index.graph, np.uint32))
        _write(f, np.bool_(include_dataset))
        if include_dataset:
            _write(f, np.asarray(index.dataset, np.float32))
    finally:
        if close:
            f.close()
