"""Error handling: the TPU-native analog of the reference's contract macros.

Reference: raft/core/error.hpp (``raft::exception``, ``RAFT_EXPECTS``,
``RAFT_FAIL``). CUDA status macros have no TPU analog — XLA raises Python
exceptions directly — so only the contract-checking surface is kept.
"""
from __future__ import annotations

__all__ = ["RaftError", "expects", "fail"]


class RaftError(RuntimeError):
    """Base exception for raft_tpu (analog of ``raft::exception``)."""


def expects(cond: bool, msg: str, *args) -> None:
    """Contract check (analog of ``RAFT_EXPECTS``).

    Raises :class:`RaftError` with the formatted message when ``cond`` is
    falsy. Only for host-side (trace-time) checks; inside jitted code use
    ``checkify`` or masking instead.
    """
    if not cond:
        raise RaftError(msg % args if args else msg)


def fail(msg: str, *args) -> None:
    """Unconditional failure (analog of ``RAFT_FAIL``)."""
    raise RaftError(msg % args if args else msg)
