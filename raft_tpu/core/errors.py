"""Error handling: the TPU-native analog of the reference's contract macros.

Reference: raft/core/error.hpp (``raft::exception``, ``RAFT_EXPECTS``,
``RAFT_FAIL``). CUDA status macros have no TPU analog — XLA raises Python
exceptions directly — so only the contract-checking surface is kept.
"""
from __future__ import annotations

__all__ = ["RaftError", "CorruptIndexError", "ShardsDownError", "expects",
           "fail"]


class RaftError(RuntimeError):
    """Base exception for raft_tpu (analog of ``raft::exception``)."""


class CorruptIndexError(RaftError, ValueError):
    """A serialized index failed an integrity check (CRC mismatch,
    truncation, unparseable section). ``section`` names the file section
    that failed: ``"header"`` or an array name. Also a ValueError so
    pre-checksum callers catching ValueError on malformed files keep
    working."""

    def __init__(self, section: str, detail: str = ""):
        self.section = section
        msg = f"corrupt index file: section {section!r}"
        super().__init__(f"{msg} ({detail})" if detail else msg)


class ShardsDownError(RaftError):
    """A sharded search found dead shards and the caller did not opt into
    a degraded answer (``allow_partial=True``). ``shards_ok`` is the
    per-shard validity mask observed at search time."""

    def __init__(self, shards_ok):
        self.shards_ok = list(bool(x) for x in shards_ok)
        down = [i for i, ok in enumerate(self.shards_ok) if not ok]
        if not any(self.shards_ok):
            # total failure: no degraded answer exists, so don't steer
            # the operator toward a flag that cannot help
            msg = (f"sharded search: all {len(self.shards_ok)} shards "
                   f"unavailable — no surviving shard to degrade onto")
        else:
            msg = (f"sharded search: shard(s) {down} unavailable; pass "
                   f"allow_partial=True to accept a degraded merged result")
        super().__init__(msg)


def expects(cond: bool, msg: str, *args) -> None:
    """Contract check (analog of ``RAFT_EXPECTS``).

    Raises :class:`RaftError` with the formatted message when ``cond`` is
    falsy. Only for host-side (trace-time) checks; inside jitted code use
    ``checkify`` or masking instead.
    """
    if not cond:
        raise RaftError(msg % args if args else msg)


def fail(msg: str, *args) -> None:
    """Unconditional failure (analog of ``RAFT_FAIL``)."""
    raise RaftError(msg % args if args else msg)
