"""Composable scalar/elementwise functors: analog of ``raft/core/operators.hpp``.

The reference passes small functor structs (sq_op, add_op, ...) into its
kernel templates; in JAX the same role is played by plain functions composed
into jitted programs. Provided for API parity and for the distance/linalg
layers that take ``main_op``/``final_op`` hooks.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "identity_op", "cast_op", "key_op", "value_op", "sq_op", "abs_op",
    "sqrt_op", "nz_op", "add_op", "sub_op", "mul_op", "div_op",
    "div_checkzero_op", "pow_op", "min_op", "max_op", "argmin_op",
    "argmax_op", "const_op", "compose_op",
]


def identity_op(x, *_):
    return x


def cast_op(dtype):
    return lambda x, *_: x.astype(dtype)


def key_op(kvp, *_):
    return kvp[0]


def value_op(kvp, *_):
    return kvp[1]


def sq_op(x, *_):
    return x * x


def abs_op(x, *_):
    return jnp.abs(x)


def sqrt_op(x, *_):
    return jnp.sqrt(x)


def nz_op(x, *_):
    return (x != 0).astype(x.dtype)


def add_op(a, b):
    return a + b


def sub_op(a, b):
    return a - b


def mul_op(a, b):
    return a * b


def div_op(a, b):
    return a / b


def div_checkzero_op(a, b):
    return jnp.where(b == 0, 0, a / jnp.where(b == 0, 1, b))


def pow_op(a, b):
    return jnp.power(a, b)


def min_op(a, b):
    return jnp.minimum(a, b)


def max_op(a, b):
    return jnp.maximum(a, b)


def argmin_op(kvp_a, kvp_b):
    """Reduce two (key, value) pairs to the one with smaller value (ties →
    smaller key), matching the reference's KVP argmin semantics."""
    ka, va = kvp_a
    kb, vb = kvp_b
    take_b = (vb < va) | ((vb == va) & (kb < ka))
    return (jnp.where(take_b, kb, ka), jnp.where(take_b, vb, va))


def argmax_op(kvp_a, kvp_b):
    ka, va = kvp_a
    kb, vb = kvp_b
    take_b = (vb > va) | ((vb == va) & (kb < ka))
    return (jnp.where(take_b, kb, ka), jnp.where(take_b, vb, va))


def const_op(c):
    return lambda *_: c


def compose_op(*fns):
    """compose_op(f, g, h)(x) == f(g(h(x)))."""

    def composed(x, *args):
        for fn in reversed(fns):
            x = fn(x, *args)
        return x

    return composed
