"""Structured logging: analog of the reference's spdlog-backed ``raft::logger``.

Reference: raft/core/logger-inl.hpp:74-126 (singleton logger, runtime
``set_level``/``set_pattern``, callback sink) and logger-macros.hpp
(``RAFT_LOG_{TRACE..CRITICAL}``). Here the backend is the stdlib ``logging``
module with an extra TRACE level and an optional callback sink, mirroring the
reference's callback-sink feature used by pylibraft to route logs to Python.
"""
from __future__ import annotations

import logging
import sys
from typing import Callable, Optional

__all__ = [
    "TRACE",
    "logger",
    "set_level",
    "get_level",
    "set_pattern",
    "set_callback",
    "log_trace",
    "log_debug",
    "log_info",
    "log_warn",
    "log_error",
    "log_critical",
]

TRACE = 5
logging.addLevelName(TRACE, "TRACE")

_DEFAULT_PATTERN = "[%(levelname)s] [%(asctime)s] %(name)s: %(message)s"

logger = logging.getLogger("raft_tpu")
if not logger.handlers:
    _handler = logging.StreamHandler(sys.stderr)
    _handler.setFormatter(logging.Formatter(_DEFAULT_PATTERN))
    logger.addHandler(_handler)
    logger.setLevel(logging.INFO)


class _CallbackHandler(logging.Handler):
    """Callback sink: forwards formatted records to a user function."""

    def __init__(self, fn: Callable[[int, str], None]):
        super().__init__()
        self._fn = fn

    def emit(self, record: logging.LogRecord) -> None:
        self._fn(record.levelno, self.format(record))


_callback_handler: Optional[_CallbackHandler] = None
_current_pattern = _DEFAULT_PATTERN


def set_level(level: int) -> None:
    """Runtime log level (analog of ``logger::set_level``)."""
    logger.setLevel(level)


def get_level() -> int:
    return logger.level


def set_pattern(pattern: str) -> None:
    """Set the log format (analog of ``logger::set_pattern``)."""
    global _current_pattern
    _current_pattern = pattern
    for h in logger.handlers:
        h.setFormatter(logging.Formatter(pattern))


def set_callback(fn: Optional[Callable[[int, str], None]]) -> None:
    """Install/remove a callback sink (analog of the spdlog callback sink).
    The sink formats with the current pattern, like every other handler."""
    global _callback_handler
    if _callback_handler is not None:
        logger.removeHandler(_callback_handler)
        _callback_handler = None
    if fn is not None:
        _callback_handler = _CallbackHandler(fn)
        _callback_handler.setFormatter(logging.Formatter(_current_pattern))
        logger.addHandler(_callback_handler)


def log_trace(msg, *a):
    logger.log(TRACE, msg, *a)


def log_debug(msg, *a):
    logger.debug(msg, *a)


def log_info(msg, *a):
    logger.info(msg, *a)


def log_warn(msg, *a):
    logger.warning(msg, *a)


def log_error(msg, *a):
    logger.error(msg, *a)


def log_critical(msg, *a):
    logger.critical(msg, *a)
