"""Deadline propagation for chunked searches.

The reference's cooperative cancellation (raft/core/interruptible.hpp)
stops work when *someone else* decides to; a serving stack also needs work
to stop *itself* when its latency budget is spent. A :class:`Deadline`
rides a :class:`~raft_tpu.core.resources.Resources` (the same injection
channel as comms) and the chunked search loops (ivf_flat / ivf_pq /
cagra / brute_force) call :func:`checkpoint` between device dispatches:
each checkpoint is a full interruptible cancellation point (the existing
token protocol) plus a deadline probe that raises
:class:`DeadlineExceeded` with the completed chunks' partial results
attached — a timed-out query still gets the best answer computed so far.

Device work itself is not preemptible (exactly as a single CUDA kernel is
not): granularity is the query chunk, sized by the workspace budget or the
caller's explicit ``query_chunk``.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from .errors import RaftError
from . import interruptible

__all__ = ["Deadline", "DeadlineExceeded", "carried", "checkpoint",
           "partial_topk"]


class DeadlineExceeded(RaftError):
    """Raised at a checkpoint once the deadline has passed.

    ``partial`` holds the completed chunks' results — for top-k searches a
    ``(distances, indices)`` pair covering the queries whose chunks
    finished dispatching, ``None`` when nothing completed.
    """

    def __init__(self, msg: str, partial=None):
        self.partial = partial
        super().__init__(msg)


class Deadline:
    """Wall-clock budget carried by Resources (``res.set_deadline``).

    ``clock`` is injectable for deterministic tests; it defaults to
    ``time.monotonic``. The budget starts counting at construction.
    """

    def __init__(self, seconds: float,
                 clock: Callable[[], float] = time.monotonic):
        self.seconds = float(seconds)
        self._clock = clock
        self._t0 = clock()

    @classmethod
    def after(cls, seconds: float, **kw) -> "Deadline":
        return cls(seconds, **kw)

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def remaining(self) -> float:
        return self.seconds - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


def carried(res) -> Optional["Deadline"]:
    """The Deadline carried by ``res`` — ``res`` may be a Resources, a
    bare Deadline, or None. The one resolution rule shared by checkpoint
    and the search entry points' auto-chunk gates (so a bare Deadline is
    never a silent no-op)."""
    if res is None:
        return None
    return res if isinstance(res, Deadline) else getattr(res, "deadline",
                                                         None)


def checkpoint(res=None, partial=None) -> None:
    """Cancellation + deadline point between chunk dispatches.

    ``res``: a Resources carrying a deadline (or a bare :class:`Deadline`;
    None → cancellation check only). ``partial``: the partial results to
    attach on expiry — a value or a zero-arg callable (evaluated only when
    the deadline actually fires).
    """
    interruptible.check()
    dl = carried(res)
    if dl is None or not dl.expired():
        return
    p = partial() if callable(partial) else partial
    raise DeadlineExceeded(
        f"raft_tpu: deadline of {dl.seconds:.4g}s exceeded "
        f"({dl.elapsed():.4g}s elapsed); partial results "
        f"{'attached' if p is not None else 'empty'}", partial=p)


def partial_topk(outs_d: list, outs_i: list):
    """Completed top-k chunks → one (distances, indices) pair (None when
    no chunk finished). The standard ``partial`` thunk for search loops."""
    if not outs_d:
        return None
    import jax.numpy as jnp

    if len(outs_d) == 1:
        return outs_d[0], outs_i[0]
    return jnp.concatenate(outs_d), jnp.concatenate(outs_i)
