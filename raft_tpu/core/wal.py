"""CRC32-framed write-ahead log for mutable indexes.

The durability contract of :mod:`raft_tpu.neighbors.mutable`: every
mutation (upsert/delete) is appended here — framed, checksummed,
fsynced — BEFORE the caller is acked, so an acked write survives any
crash. The reference has no analog (RAFT indexes are build-once); the
design follows the standard WAL discipline (ARIES / FreshDiskANN's
delta-log) mapped onto the PR 1 durable-I/O idioms: CRC32 per frame,
``os.fsync`` before ack, parent-directory fsync on create
(:func:`raft_tpu.core.serialize.fsync_dir`).

Wire format::

    RAFTWAL1 <u32 version>                      -- file header
    [ <u32 payload_len> <payload> <u32 crc> ]*  -- frames, appended

``crc`` is CRC32 over the length prefix + payload, so a frame whose
length field itself was torn fails the check. Frame payloads are
records: a one-byte kind (``U`` upsert / ``D`` delete) followed by
``.npy``-framed arrays (ids; vectors for upserts) — the same numpy
framing the index serializer uses, so nothing here depends on pickle.

Recovery semantics (:func:`replay`):

* a frame that extends past EOF, or whose CRC fails **on the last
  frame**, is a *torn tail* — the in-flight append the crash
  interrupted. It was never acked, so recovery truncates it
  (``repair=True``) and the log is consistent;
* a CRC failure with more complete frames AFTER it is *mid-log
  corruption* — acked data is damaged, silence would serve wrong
  results — and raises :class:`~raft_tpu.core.errors.CorruptIndexError`
  naming the frame.

Crash drills: :meth:`WriteAheadLog.append` probes
``crash_point@core.wal.append`` between the write and the fsync, and
``wal_torn_tail@core.wal.append`` cuts the frame bytes mid-write (both
then raise :class:`~raft_tpu.core.faults.InjectedCrash`), so
tests/test_mutable.py can leave *exactly* the on-disk states a power
cut leaves and assert ``recover()`` handles each.
"""
from __future__ import annotations

import io
import os
import struct
import zlib
from typing import Iterator, Optional, Tuple

import numpy as np

from .errors import CorruptIndexError
from .serialize import fsync_dir
from . import faults

__all__ = ["WriteAheadLog", "replay", "APPEND_SITE"]

_MAGIC = b"RAFTWAL1"
_VERSION = 1
_HEADER_LEN = len(_MAGIC) + 4

# the named mid-append crash/torn-write site (docs/mutation.md)
APPEND_SITE = "core.wal.append"

_KINDS = (b"U", b"D")


def _encode_record(kind: str, ids, vectors=None) -> bytes:
    tag = {"upsert": b"U", "delete": b"D"}[kind]
    buf = io.BytesIO()
    buf.write(tag)
    np.save(buf, np.ascontiguousarray(ids, dtype=np.int64),
            allow_pickle=False)
    if tag == b"U":
        np.save(buf, np.ascontiguousarray(vectors, dtype=np.float32),
                allow_pickle=False)
    return buf.getvalue()


def _decode_record(payload: bytes, frame: str):
    tag = payload[:1]
    if tag not in _KINDS:
        raise CorruptIndexError(frame, f"unknown record kind {tag!r}")
    buf = io.BytesIO(payload[1:])
    try:
        ids = np.load(buf, allow_pickle=False)
        vectors = np.load(buf, allow_pickle=False) if tag == b"U" else None
    except (ValueError, OSError, EOFError) as e:
        raise CorruptIndexError(frame, f"bad record arrays: {e}") from e
    return ("upsert" if tag == b"U" else "delete"), ids, vectors


class WriteAheadLog:
    """Append-only mutation log. Single-writer (the owning
    :class:`~raft_tpu.neighbors.mutable.MutableIndex` serializes appends
    under its lock); readers use the module-level :func:`replay`."""

    def __init__(self, path: str, _f):
        self.path = path
        self._f = _f
        # offset after the last SUCCESSFUL append: a failed/partial
        # write leaves torn bytes past this point, and the next append
        # truncates back to it first (see append)
        self._good_end = _f.tell()

    # -- lifecycle --------------------------------------------------------
    @classmethod
    def create(cls, path: str) -> "WriteAheadLog":
        """Create a fresh log (header written, fsynced, parent dir
        fsynced — the file's existence itself must survive a crash
        before the manifest may reference it)."""
        with open(path, "wb") as f:
            f.write(_MAGIC + struct.pack("<I", _VERSION))
            f.flush()
            os.fsync(f.fileno())
        fsync_dir(path)
        return cls.open(path)

    @classmethod
    def open(cls, path: str) -> "WriteAheadLog":
        """Open an existing log for appending (header verified)."""
        f = open(path, "r+b")
        try:
            head = f.read(_HEADER_LEN)
            if head[: len(_MAGIC)] != _MAGIC:
                raise CorruptIndexError("wal header",
                                        "not a raft_tpu WAL (bad magic)")
            f.seek(0, os.SEEK_END)
        except BaseException:
            f.close()
            raise
        return cls(path, f)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def seal(self) -> None:
        """Drop any torn un-acked tail (a failed append's leftovers)
        and fsync. Called before the log is rotated out of the active
        slot: a rotated-out log is replayed with
        ``allow_torn_tail=False``, so it must be whole-frames-only."""
        f = self._f
        if f.tell() != self._good_end:
            f.truncate(self._good_end)
            f.seek(self._good_end)
            f.flush()
            os.fsync(f.fileno())

    # -- writes -----------------------------------------------------------
    def size_bytes(self) -> int:
        return self._f.tell()

    def append(self, kind: str, ids, vectors=None) -> None:
        """Durably append one mutation record; returns only after the
        frame is on disk (write + flush + fsync). The caller acks its
        client AFTER this returns — that ordering IS the durability
        contract. A failed append (ENOSPC mid-write, a raised fault)
        leaves the un-acked torn bytes on disk but the NEXT append
        truncates back to the last good frame first — a retried write
        must never land after garbage, where recovery would either
        truncate the acked retry away or read mid-log corruption."""
        payload = _encode_record(kind, ids, vectors)
        hdr = struct.pack("<I", len(payload))
        frame = hdr + payload + struct.pack(
            "<I", zlib.crc32(payload, zlib.crc32(hdr)))
        f = self._f
        if f.tell() != self._good_end:
            f.truncate(self._good_end)
            f.seek(self._good_end)
        torn = faults.cut(APPEND_SITE, frame)
        if len(torn) != len(frame):
            # simulated power cut mid-write(2): flush the prefix so the
            # torn frame is really on disk, then die
            f.write(torn)
            f.flush()
            os.fsync(f.fileno())
            raise faults.InjectedCrash("wal_torn_tail", APPEND_SITE)
        f.write(frame)
        f.flush()
        # simulated death between write and fsync: the frame may or may
        # not survive — either is a legal recovery outcome for an
        # UN-acked write, and the drill asserts recover() handles both
        faults.crash(APPEND_SITE)
        os.fsync(f.fileno())
        self._good_end = f.tell()


def replay(path: str, repair: bool = False,
           allow_torn_tail: bool = True) -> Tuple[list, int]:
    """Read every good frame of ``path`` → (records, truncated_bytes).

    ``records`` is a list of ``(kind, ids, vectors)`` tuples in append
    order. A torn tail (see module docstring) stops the replay; with
    ``repair=True`` the file is physically truncated at the last good
    frame (fsynced) so later appends extend a clean log.
    ``truncated_bytes`` reports how much tail was dropped (0 on a clean
    log). ``allow_torn_tail=False`` (non-last logs of a multi-log
    manifest, which were rotated closed and can have no in-flight
    append) turns ANY bad frame into mid-log corruption.

    Raises :class:`CorruptIndexError` on mid-log corruption — damaged
    *acked* data is never silently dropped.
    """
    records: list = []
    with open(path, "rb") as f:
        head = f.read(_HEADER_LEN)
        if len(head) < _HEADER_LEN or head[: len(_MAGIC)] != _MAGIC:
            raise CorruptIndexError("wal header",
                                    f"{path}: not a raft_tpu WAL")
        end = f.seek(0, os.SEEK_END)
        pos = _HEADER_LEN
        f.seek(pos)
        good_end = pos
        torn: Optional[str] = None
        n_frame = 0
        while pos < end:
            n_frame += 1
            frame_name = f"wal frame {n_frame}"
            hdr = f.read(4)
            if len(hdr) < 4:
                torn = f"{frame_name}: truncated length prefix"
                break
            (plen,) = struct.unpack("<I", hdr)
            if pos + 4 + plen + 4 > end:
                torn = (f"{frame_name}: frame wants {plen} payload bytes, "
                        f"{end - pos - 8} remain")
                break
            payload = f.read(plen)
            (want,) = struct.unpack("<I", f.read(4))
            got = zlib.crc32(payload, zlib.crc32(hdr))
            pos = pos + 4 + plen + 4
            if got != want:
                if pos >= end:
                    # bad CRC on the very last frame: torn mid-overwrite
                    torn = (f"{frame_name}: CRC mismatch "
                            f"({got:#010x} != {want:#010x}) at tail")
                    break
                raise CorruptIndexError(
                    frame_name,
                    f"{path}: CRC mismatch ({got:#010x} != {want:#010x}) "
                    "mid-log — acked data is damaged")
            records.append(_decode_record(payload, frame_name))
            good_end = pos
    truncated = 0
    if torn is not None:
        if not allow_torn_tail:
            raise CorruptIndexError(
                "wal tail", f"{path}: torn frame in a closed log ({torn})")
        truncated = end - good_end
        if repair:
            with open(path, "r+b") as f:
                f.truncate(good_end)
                f.flush()
                os.fsync(f.fileno())
    return records, truncated
