"""Array + index serialization in NumPy ``.npy`` framing.

Reference: raft/core/serialize.hpp:36-65 and
core/detail/mdspan_numpy_serializer.hpp — the reference serializes every
mdspan in numpy format so Python can read index files directly. We keep the
same wire idea: a stream of scalars (struct-packed) and arrays (``.npy``
frames), plus a small versioned header per index type. Index save/load for
each ANN type builds on these primitives (the analog of
neighbors/*_serialize.cuh).
"""
from __future__ import annotations

import io
import os
import struct
from typing import Any, BinaryIO, Dict, List, Tuple

import jax
import numpy as np

__all__ = [
    "serialize_scalar",
    "deserialize_scalar",
    "serialize_array",
    "deserialize_array",
    "serialize_header",
    "deserialize_header",
    "save_arrays",
    "load_arrays",
]

_MAGIC = b"RAFT_TPU"


def serialize_scalar(f: BinaryIO, value, fmt: str) -> None:
    """Write one struct-packed scalar (fmt is a struct format char, e.g. '<q')."""
    f.write(struct.pack(fmt, value))


def deserialize_scalar(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    (v,) = struct.unpack(fmt, f.read(size))
    return v


def serialize_array(f: BinaryIO, arr) -> None:
    """Write an array as a standard ``.npy`` frame (device arrays are pulled
    to host first)."""
    np.save(f, np.asarray(jax.device_get(arr)), allow_pickle=False)


def deserialize_array(f: BinaryIO) -> np.ndarray:
    return np.load(f, allow_pickle=False)


def serialize_header(f: BinaryIO, kind: str, version: int, meta: Dict[str, Any]) -> None:
    """Versioned header: magic, index kind, serialization version and a
    metadata dict of plain ints/floats/strings/bools (analog of the version
    constants in detail/ivf_pq_serialize.cuh)."""
    f.write(_MAGIC)
    kind_b = kind.encode()
    f.write(struct.pack("<HI", len(kind_b), version))
    f.write(kind_b)
    items: List[Tuple[str, Any]] = sorted(meta.items())
    f.write(struct.pack("<I", len(items)))
    for k, v in items:
        kb = k.encode()
        if isinstance(v, bool):
            tag, payload = b"b", struct.pack("<?", v)
        elif isinstance(v, int):
            tag, payload = b"i", struct.pack("<q", v)
        elif isinstance(v, float):
            tag, payload = b"f", struct.pack("<d", v)
        elif isinstance(v, str):
            vb = v.encode()
            tag, payload = b"s", struct.pack("<I", len(vb)) + vb
        else:
            raise TypeError(f"unsupported meta value for {k!r}: {type(v)}")
        f.write(struct.pack("<H", len(kb)) + kb + tag + payload)


def deserialize_header(f: BinaryIO, expect_kind: str | None = None):
    magic = f.read(len(_MAGIC))
    if magic != _MAGIC:
        raise ValueError("not a raft_tpu serialized file (bad magic)")
    kind_len, version = struct.unpack("<HI", f.read(6))
    kind = f.read(kind_len).decode()
    if expect_kind is not None and kind != expect_kind:
        raise ValueError(f"expected index kind {expect_kind!r}, found {kind!r}")
    (n_items,) = struct.unpack("<I", f.read(4))
    meta: Dict[str, Any] = {}
    for _ in range(n_items):
        (klen,) = struct.unpack("<H", f.read(2))
        k = f.read(klen).decode()
        tag = f.read(1)
        if tag == b"b":
            (v,) = struct.unpack("<?", f.read(1))
        elif tag == b"i":
            (v,) = struct.unpack("<q", f.read(8))
        elif tag == b"f":
            (v,) = struct.unpack("<d", f.read(8))
        elif tag == b"s":
            (slen,) = struct.unpack("<I", f.read(4))
            v = f.read(slen).decode()
        else:
            raise ValueError(f"bad meta tag {tag!r}")
        meta[k] = v
    return kind, version, meta


def save_arrays(path_or_file, kind: str, version: int, meta: Dict[str, Any],
                arrays: Dict[str, Any]) -> None:
    """Save a header plus named arrays (sorted order, name-prefixed frames)."""

    def _write(f: BinaryIO):
        serialize_header(f, kind, version, meta)
        items = sorted(arrays.items())
        f.write(struct.pack("<I", len(items)))
        for name, arr in items:
            nb = name.encode()
            f.write(struct.pack("<H", len(nb)) + nb)
            serialize_array(f, arr)

    if isinstance(path_or_file, (str, bytes, os.PathLike)):
        with open(path_or_file, "wb") as f:
            _write(f)
    else:
        _write(path_or_file)


def load_arrays(path_or_file, expect_kind: str | None = None):
    """Inverse of :func:`save_arrays` → (kind, version, meta, {name: ndarray})."""

    def _read(f: BinaryIO):
        kind, version, meta = deserialize_header(f, expect_kind)
        (n,) = struct.unpack("<I", f.read(4))
        arrays: Dict[str, np.ndarray] = {}
        for _ in range(n):
            (nlen,) = struct.unpack("<H", f.read(2))
            name = f.read(nlen).decode()
            arrays[name] = deserialize_array(f)
        return kind, version, meta, arrays

    if isinstance(path_or_file, (str, bytes, os.PathLike)):
        with open(path_or_file, "rb") as f:
            return _read(f)
    return _read(path_or_file)
