"""Array + index serialization in NumPy ``.npy`` framing.

Reference: raft/core/serialize.hpp:36-65 and
core/detail/mdspan_numpy_serializer.hpp — the reference serializes every
mdspan in numpy format so Python can read index files directly. We keep the
same wire idea: a stream of scalars (struct-packed) and arrays (``.npy``
frames), plus a small versioned header per index type. Index save/load for
each ANN type builds on these primitives (the analog of
neighbors/*_serialize.cuh).

Durability (the resilience layer):

* **Per-section CRC32.** New files carry a checksum after the header
  section and after every array section (array frames are additionally
  length-prefixed, so truncation is detected before a frame is parsed).
  A mismatch raises :class:`~raft_tpu.core.errors.CorruptIndexError`
  naming the failing section. Pre-checksum files (no ``__crc__`` header
  flag) still load through the legacy path.
* **Atomic writes.** Path saves write to a same-directory temp file and
  ``os.replace`` into place, so an interrupted save never leaves a
  partial file at the target path (and never clobbers a previous good
  file). The parent directory is fsynced after the rename
  (:func:`fsync_dir`) — without it the rename itself can be lost on
  power failure even though the file's *data* was fsynced, and "the
  save returned" must mean "the save survives a crash" (the WAL and
  manifest writers in :mod:`raft_tpu.core.wal` /
  :mod:`raft_tpu.neighbors.mutable` lean on the same helper).
"""
from __future__ import annotations

import io
import os
import struct
import uuid
import zlib
from typing import Any, BinaryIO, Dict, List, Tuple

import jax
import numpy as np

from .errors import CorruptIndexError
from . import faults

__all__ = [
    "serialize_scalar",
    "deserialize_scalar",
    "serialize_array",
    "deserialize_array",
    "serialize_header",
    "deserialize_header",
    "save_arrays",
    "load_arrays",
    "fsync_dir",
]


def fsync_dir(path) -> None:
    """fsync the directory containing ``path`` (or ``path`` itself when
    it IS a directory): durability for renames and creates. An
    ``os.replace`` only becomes crash-durable once the parent
    directory's entry table hits disk. Platforms whose directory
    handles reject fsync (some network filesystems) degrade silently —
    the rename still happened, we just can't strengthen it."""
    d = os.fspath(path)
    if not os.path.isdir(d):
        d = os.path.dirname(d) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)

_MAGIC = b"RAFT_TPU"      # legacy (pre-checksum) layout
# the checksummed layout gets its OWN magic: the layout discriminator
# must not be a flippable flag byte inside the file — a corrupted
# discriminator must fail loudly (bad magic), never silently route a
# checksummed file through the unverified legacy parse
_MAGIC_CRC = b"RAFTTPU2"


class _CrcWriter:
    """Pass-through writer accumulating a CRC32 of the current section."""

    def __init__(self, f: BinaryIO):
        self._f = f
        self.crc = 0

    def write(self, b: bytes) -> None:
        self.crc = zlib.crc32(b, self.crc)
        self._f.write(b)

    def take(self) -> int:
        """Finish the current section: return its CRC and reset."""
        c = self.crc
        self.crc = 0
        return c


class _TeeCrc:
    """Streams a frame to ``f``: CRCs the TRUE bytes, writes the
    (possibly fault-corrupted) bytes, and counts the frame length — so
    large arrays serialize without a full in-memory copy."""

    def __init__(self, f: BinaryIO, site: str, crc0: int):
        self._f = f
        self._site = site
        self.crc = crc0
        self.n = 0

    def write(self, b) -> None:
        self.crc = zlib.crc32(b, self.crc)
        self.n += len(b)
        self._f.write(faults.corrupt(self._site, b))


def _write_array_section(f: BinaryIO, name: str, arr) -> None:
    """One checksummed array section: name frame, length-prefixed npy
    frame, CRC32 trailer. The CRC covers name + payload with the length
    folded in LAST (it is only known after the frame streams — seekable
    targets, which include the atomic-save temp file and BytesIO, get a
    placeholder patched in place; the reader mirrors the fold order)."""
    nb = name.encode()
    name_frame = struct.pack("<H", len(nb)) + nb
    f.write(name_frame)
    crc = zlib.crc32(name_frame)
    site = f"core.serialize.array.{name}"
    if hasattr(f, "seekable") and f.seekable():
        len_pos = f.tell()
        f.write(struct.pack("<Q", 0))              # patched below
        tee = _TeeCrc(f, site, crc)
        serialize_array(tee, arr)
        plen, crc = tee.n, tee.crc
        end = f.tell()
        f.seek(len_pos)
        f.write(struct.pack("<Q", plen))
        f.seek(end)
    else:
        # non-seekable sink: buffer the frame to learn its length
        buf = io.BytesIO()
        serialize_array(buf, arr)
        payload = buf.getbuffer()
        plen = len(payload)
        f.write(struct.pack("<Q", plen))
        crc = zlib.crc32(payload, crc)
        f.write(faults.corrupt(site, payload))
    crc = zlib.crc32(struct.pack("<Q", plen), crc)
    f.write(struct.pack("<I", crc))


class _CrcReader:
    """Pass-through reader accumulating a CRC32 of the current section."""

    def __init__(self, f: BinaryIO):
        self._f = f
        self.crc = 0

    def read(self, n: int = -1) -> bytes:
        b = self._f.read(n)
        self.crc = zlib.crc32(b, self.crc)
        return b

    def take(self) -> int:
        c = self.crc
        self.crc = 0
        return c


def _read_exact(f, n: int, section: str) -> bytes:
    """Read exactly ``n`` bytes or raise CorruptIndexError (truncation).

    Reads in bounded chunks: ``n`` can come from a corrupt length prefix
    (a flipped high bit turns it into exabytes), and a single ``read(n)``
    could attempt that allocation before EOF reveals the truncation —
    chunking keeps memory bounded by the actual data."""
    if n < 0:
        raise CorruptIndexError(section, f"negative length {n}")
    chunks = []
    remaining = n
    while remaining > 0:
        b = f.read(min(remaining, 64 << 20))
        if not b:
            got = n - remaining
            raise CorruptIndexError(
                section, f"truncated: wanted {n} bytes, got {got}")
        chunks.append(b)
        remaining -= len(b)
    if len(chunks) == 1:
        return chunks[0]
    return b"".join(chunks)


def _read_payload(f, n: int, section: str):
    """Exact-length array-payload read.

    Seekable sources (path loads, BytesIO) validate the untrusted length
    prefix against the remaining file size FIRST — a flipped high bit
    must raise CorruptIndexError, not attempt an exabyte allocation —
    then fill one preallocated buffer (no chunk-list + join doubling).
    Non-seekable sources fall back to the chunked bounded read."""
    if n < 0:
        raise CorruptIndexError(section, f"negative length {n}")
    if not (hasattr(f, "seekable") and f.seekable()):
        return _read_exact(f, n, section)
    pos = f.tell()
    end = f.seek(0, 2)
    f.seek(pos)
    if n > end - pos:
        raise CorruptIndexError(
            section, f"length {n} exceeds the {end - pos} bytes remaining")
    buf = bytearray(n)
    mv = memoryview(buf)
    got = 0
    while got < n:
        if hasattr(f, "readinto"):
            r = f.readinto(mv[got:])
        else:
            b = f.read(n - got)
            r = len(b)
            mv[got : got + r] = b
        if not r:
            raise CorruptIndexError(
                section, f"truncated: wanted {n} bytes, got {got}")
        got += r
    return buf


def serialize_scalar(f: BinaryIO, value, fmt: str) -> None:
    """Write one struct-packed scalar (fmt is a struct format char, e.g. '<q')."""
    f.write(struct.pack(fmt, value))


def deserialize_scalar(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    (v,) = struct.unpack(fmt, f.read(size))
    return v


def serialize_array(f: BinaryIO, arr) -> None:
    """Write an array as a standard ``.npy`` frame (device arrays are pulled
    to host first)."""
    np.save(f, np.asarray(jax.device_get(arr)), allow_pickle=False)


def deserialize_array(f: BinaryIO) -> np.ndarray:
    return np.load(f, allow_pickle=False)


def serialize_header(f: BinaryIO, kind: str, version: int, meta: Dict[str, Any]) -> None:
    """Versioned header: magic, index kind, serialization version and a
    metadata dict of plain ints/floats/strings/bools (analog of the version
    constants in detail/ivf_pq_serialize.cuh). Writes the LEGACY magic;
    :func:`save_arrays` writes the checksummed layout's own magic."""
    f.write(_MAGIC)
    _serialize_header_body(f, kind, version, meta)


def _serialize_header_body(f: BinaryIO, kind: str, version: int,
                           meta: Dict[str, Any]) -> None:
    kind_b = kind.encode()
    f.write(struct.pack("<HI", len(kind_b), version))
    f.write(kind_b)
    items: List[Tuple[str, Any]] = sorted(meta.items())
    f.write(struct.pack("<I", len(items)))
    for k, v in items:
        kb = k.encode()
        if isinstance(v, bool):
            tag, payload = b"b", struct.pack("<?", v)
        elif isinstance(v, int):
            tag, payload = b"i", struct.pack("<q", v)
        elif isinstance(v, float):
            tag, payload = b"f", struct.pack("<d", v)
        elif isinstance(v, str):
            vb = v.encode()
            tag, payload = b"s", struct.pack("<I", len(vb)) + vb
        else:
            raise TypeError(f"unsupported meta value for {k!r}: {type(v)}")
        f.write(struct.pack("<H", len(kb)) + kb + tag + payload)


def deserialize_header(f: BinaryIO, expect_kind: str | None = None):
    magic = _read_exact(f, len(_MAGIC), "header")
    if magic != _MAGIC:
        raise CorruptIndexError(
            "header", "not a raft_tpu serialized file (bad magic)")
    kind, version, meta = _deserialize_header_body(f)
    if expect_kind is not None and kind != expect_kind:
        raise ValueError(f"expected index kind {expect_kind!r}, found {kind!r}")
    return kind, version, meta


def _deserialize_header_body(f: BinaryIO):
    kind_len, version = struct.unpack("<HI", _read_exact(f, 6, "header"))
    kind = _read_exact(f, kind_len, "header").decode()
    (n_items,) = struct.unpack("<I", _read_exact(f, 4, "header"))
    meta: Dict[str, Any] = {}
    for _ in range(n_items):
        (klen,) = struct.unpack("<H", _read_exact(f, 2, "header"))
        k = _read_exact(f, klen, "header").decode()
        tag = _read_exact(f, 1, "header")
        if tag == b"b":
            (v,) = struct.unpack("<?", _read_exact(f, 1, "header"))
        elif tag == b"i":
            (v,) = struct.unpack("<q", _read_exact(f, 8, "header"))
        elif tag == b"f":
            (v,) = struct.unpack("<d", _read_exact(f, 8, "header"))
        elif tag == b"s":
            (slen,) = struct.unpack("<I", _read_exact(f, 4, "header"))
            v = _read_exact(f, slen, "header").decode()
        else:
            raise CorruptIndexError("header", f"bad meta tag {tag!r}")
        meta[k] = v
    return kind, version, meta


def save_arrays(path_or_file, kind: str, version: int, meta: Dict[str, Any],
                arrays: Dict[str, Any]) -> None:
    """Save a header plus named arrays (sorted order, name-prefixed frames).

    Writes the per-section-CRC layout (see module docstring). Path saves
    are atomic: a temp file in the target directory is ``os.replace``-d
    into place only after a complete, flushed write.
    """

    def _write(f: BinaryIO):
        w = _CrcWriter(f)
        w.write(_MAGIC_CRC)
        _serialize_header_body(w, kind, version, meta)
        items = sorted(arrays.items())
        w.write(struct.pack("<I", len(items)))
        f.write(struct.pack("<I", w.take()))            # header section CRC
        faults.check("io_error", "core.serialize.save_arrays")
        for name, arr in items:
            # per-section CRC covers the TRUE bytes; an armed
            # corrupt_bytes fault mutates what lands on disk after
            # checksumming, like real storage corruption — so the
            # reader's CRC check catches it
            _write_array_section(f, name, arr)

    if isinstance(path_or_file, (str, bytes, os.PathLike)):
        path = os.fspath(path_or_file)
        # pid alone collides when two threads save the same path; the
        # uuid component makes every save's temp file its own
        suffix = f".tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        tmp = path + (suffix.encode() if isinstance(path, bytes) else suffix)
        try:
            with open(tmp, "wb") as f:
                _write(f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            # the rename is only crash-durable once the parent
            # directory entry hits disk too
            fsync_dir(path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    else:
        _write(path_or_file)


def load_arrays(path_or_file, expect_kind: str | None = None):
    """Inverse of :func:`save_arrays` → (kind, version, meta, {name: ndarray}).

    Verifies per-section CRCs on checksummed files, raising
    :class:`CorruptIndexError` naming the failing section; files written
    before the checksum layout load through the legacy path unchanged.
    """

    def _read(f: BinaryIO):
        r = _CrcReader(f)
        # the MAGIC discriminates the layout — never a flag byte inside
        # the file (a flipped flag would silently skip verification)
        magic = _read_exact(r, len(_MAGIC), "header")
        has_crc = magic == _MAGIC_CRC
        if not has_crc and magic != _MAGIC:
            raise CorruptIndexError(
                "header", "not a raft_tpu serialized file (bad magic)")
        try:
            # kind check deferred: a corrupt header must report corruption,
            # not a spurious kind mismatch from flipped kind bytes
            kind, version, meta = _deserialize_header_body(r)
        except (struct.error, UnicodeDecodeError, OverflowError,
                MemoryError) as e:
            raise CorruptIndexError("header", f"unparseable: {e}") from e
        arrays: Dict[str, np.ndarray] = {}
        if has_crc:
            (n,) = struct.unpack("<I", _read_exact(r, 4, "header"))
            got = r.take()
            (want,) = struct.unpack("<I", _read_exact(f, 4, "header"))
            if got != want:
                raise CorruptIndexError(
                    "header", f"CRC mismatch ({got:#010x} != {want:#010x})")
            if expect_kind is not None and kind != expect_kind:
                raise ValueError(
                    f"expected index kind {expect_kind!r}, found {kind!r}")
            for _ in range(n):
                (nlen,) = struct.unpack(
                    "<H", _read_exact(r, 2, "array table"))
                try:
                    name = _read_exact(r, nlen, "array table").decode()
                except UnicodeDecodeError as e:
                    # a flipped bit in the name bytes is corruption, not
                    # a crash — the contract is CorruptIndexError always
                    raise CorruptIndexError(
                        "array table", f"undecodable name: {e}") from e
                # the length folds into the CRC last, mirroring the
                # writer (which learns it only after streaming the frame)
                plen_b = _read_exact(f, 8, name)
                (plen,) = struct.unpack("<Q", plen_b)
                payload = _read_payload(f, plen, name)
                r.crc = zlib.crc32(payload, r.crc)
                r.crc = zlib.crc32(plen_b, r.crc)
                got = r.take()
                (want,) = struct.unpack("<I", _read_exact(f, 4, name))
                if got != want:
                    raise CorruptIndexError(
                        name, f"CRC mismatch ({got:#010x} != {want:#010x})")
                bio = io.BytesIO(payload)
                del payload   # BytesIO holds its own copy; free ours
                try:
                    arrays[name] = np.load(bio, allow_pickle=False)
                except ValueError as e:
                    raise CorruptIndexError(name, f"bad npy frame: {e}") \
                        from e
        else:
            # legacy (pre-checksum) layout: count + raw npy frames
            if expect_kind is not None and kind != expect_kind:
                raise ValueError(
                    f"expected index kind {expect_kind!r}, found {kind!r}")
            (n,) = struct.unpack("<I", _read_exact(f, 4, "array table"))
            for _ in range(n):
                (nlen,) = struct.unpack(
                    "<H", _read_exact(f, 2, "array table"))
                try:
                    name = _read_exact(f, nlen, "array table").decode()
                except UnicodeDecodeError as e:
                    raise CorruptIndexError(
                        "array table", f"undecodable name: {e}") from e
                arrays[name] = deserialize_array(f)
        return kind, version, meta, arrays

    try:
        if isinstance(path_or_file, (str, bytes, os.PathLike)):
            with open(path_or_file, "rb") as f:
                return _read(f)
        return _read(path_or_file)
    except CorruptIndexError as e:
        # a corrupt load is an operational event, not just an exception:
        # the caller may contain it (mark_shard_failed, retry a replica)
        # and the ops surface must still show it happened
        try:
            from . import events as _events

            _events.record("corrupt_index", e.section, error=str(e))
            from ..serve import metrics as _metrics

            _metrics.counter("serialize.corrupt_load").inc()
        except Exception:  # noqa: BLE001 - telemetry must not mask the error
            pass
        raise
