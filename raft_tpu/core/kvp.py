"""Key-value pair used by fused argmin reductions (analog of raft/core/kvp.hpp).

In JAX a KVP is just a (key, value) tuple of arrays; this module gives it a
named constructor and the reduction helpers used by fused_l2_nn.
"""
from __future__ import annotations

from typing import NamedTuple

import jax

__all__ = ["KeyValuePair"]


class KeyValuePair(NamedTuple):
    """Index/distance pair; `key` is the argmin index, `value` its distance."""

    key: jax.Array
    value: jax.Array
