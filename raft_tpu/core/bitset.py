"""Device bitset over uint32 words: analog of ``raft::core::bitset``.

Reference: raft/core/bitset.cuh:38-91 (view) and :263-380 (owning type with
``test/set/flip/count/any/all``). Backs ANN sample filtering (bitset_filter).
Implemented as pure jnp bit arithmetic so it fuses into surrounding XLA
programs; all ops are jit-safe and shapes are static.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import cdiv

__all__ = ["Bitset"]

_BITS = 32


@jax.tree_util.register_pytree_node_class
class Bitset:
    """Fixed-length bitset stored as packed uint32 words (a pytree leaf
    wrapper, so it can pass through jit boundaries)."""

    def __init__(self, words: jax.Array, n_bits: int):
        self.words = words
        self.n_bits = n_bits

    # -- pytree protocol --------------------------------------------------
    def tree_flatten(self):
        return (self.words,), self.n_bits

    @classmethod
    def tree_unflatten(cls, n_bits, children):
        return cls(children[0], n_bits)

    # -- constructors -----------------------------------------------------
    @classmethod
    def create(cls, n_bits: int, default: bool = True) -> "Bitset":
        """All-set (default, matching the reference's default_value=true used
        for 'nothing filtered') or all-clear bitset."""
        n_words = cdiv(n_bits, _BITS)
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        words = jnp.full((n_words,), fill, dtype=jnp.uint32)
        bs = cls(words, n_bits)
        if default:
            bs = cls(bs._masked_words(), n_bits)  # clear tail padding bits
        return bs

    @classmethod
    def from_mask(cls, mask: jax.Array) -> "Bitset":
        """Pack a boolean vector (n_bits,) into a bitset."""
        n_bits = mask.shape[0]
        n_words = cdiv(n_bits, _BITS)
        pad = n_words * _BITS - n_bits
        m = jnp.pad(mask.astype(jnp.uint32), (0, pad)).reshape(n_words, _BITS)
        shifts = jnp.arange(_BITS, dtype=jnp.uint32)
        words = jnp.sum(m << shifts, axis=1, dtype=jnp.uint32)
        return cls(words, n_bits)

    # -- ops --------------------------------------------------------------
    def _masked_words(self) -> jax.Array:
        """Words with bits past n_bits forced to zero."""
        tail = self.n_bits % _BITS
        if tail == 0:
            return self.words
        last_mask = jnp.uint32((1 << tail) - 1)
        return self.words.at[-1].set(self.words[-1] & last_mask)

    def test(self, idx: jax.Array) -> jax.Array:
        """Read bit(s) at ``idx`` (any integer array shape). Out-of-range
        indices read as False rather than aliasing another bit (JAX clamps
        OOB gathers, which would otherwise return garbage)."""
        idx = jnp.asarray(idx)
        word = self.words[idx // _BITS]
        bit = ((word >> (idx % _BITS).astype(jnp.uint32)) & 1).astype(bool)
        return bit & (idx >= 0) & (idx < self.n_bits)

    def set(self, idx: jax.Array, value: bool | jax.Array = True) -> "Bitset":
        """Functional bit set/clear; returns a new bitset (idx: scalar or 1-D).

        Goes through the unpacked boolean form so duplicate indices scatter
        correctly; repack cost is O(n_bits) which is fine for filter-building
        (the read path ``test``/``to_mask`` stays packed).
        """
        idx = jnp.atleast_1d(jnp.asarray(idx))
        val = jnp.broadcast_to(jnp.asarray(value, dtype=bool), idx.shape)
        mask = self.to_mask().at[idx].set(val)
        return Bitset.from_mask(mask)

    def flip(self) -> "Bitset":
        return Bitset((~self._masked_words()).astype(jnp.uint32), self.n_bits)

    def to_mask(self) -> jax.Array:
        """Unpack to a boolean vector of shape (n_bits,)."""
        shifts = jnp.arange(_BITS, dtype=jnp.uint32)
        bits = (self.words[:, None] >> shifts[None, :]) & 1
        return bits.reshape(-1)[: self.n_bits].astype(bool)

    def count(self) -> jax.Array:
        w = self._masked_words()
        # popcount via bit tricks (uint32)
        w = w - ((w >> 1) & jnp.uint32(0x55555555))
        w = (w & jnp.uint32(0x33333333)) + ((w >> 2) & jnp.uint32(0x33333333))
        w = (w + (w >> 4)) & jnp.uint32(0x0F0F0F0F)
        return jnp.sum((w * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)

    def count_by_segments(self, ids: jax.Array, segment_ids: jax.Array,
                          num_segments: int) -> jax.Array:
        """Survivor count per segment: ``out[s] = sum over j with
        segment_ids[j] == s of test(ids[j])`` — one O(len(ids)) pass
        yielding a grouped popcount (the per-IVF-list selectivity
        measurement: ``ids`` = the index's ``source_ids`` in storage
        order, ``segment_ids`` = the list label of each storage row).
        Out-of-range ids (capacity-slack rows carry source id -1) read
        as False via :meth:`test`, so they never count. jit-safe."""
        bits = self.test(ids).astype(jnp.int32)
        return jax.ops.segment_sum(bits, segment_ids,
                                   num_segments=num_segments)

    def fingerprint(self) -> str:
        """Stable content digest of the packed words + length (host
        read). Two bitsets share a fingerprint iff they select the same
        rows — the cache-key component serving stacks fold in so a
        filtered answer can never alias an unfiltered (or differently
        filtered) one. Eager-only: forces a device→host transfer."""
        import hashlib

        import numpy as np

        h = hashlib.blake2b(np.asarray(self._masked_words()).tobytes(),
                            digest_size=16)
        h.update(str(int(self.n_bits)).encode())
        return h.hexdigest()

    def any(self) -> jax.Array:
        return jnp.any(self._masked_words() != 0)

    def all(self) -> jax.Array:
        return self.count() == self.n_bits

    def none(self) -> jax.Array:
        return ~self.any()
