"""Array interop: input adoption + output-type hooks.

Role of the pylibraft ``common/`` adapter layer (SURVEY §2.10):
``cai_wrapper``/``ai_wrapper`` (pylibraft/common/cai_wrapper.py:21) adopt
any ``__cuda_array_interface__``/``__array_interface__`` producer
zero-copy, and ``config.py`` + ``auto_convert_output`` return outputs as
cupy/torch per a process-wide setting.

TPU analog: DLPack is the zero-copy lingua franca. ``as_device_array``
is the explicit adoption helper for jax/numpy/torch arrays and any
``__dlpack__`` producer (public entries themselves accept whatever
``jnp.asarray`` understands, which includes numpy and CPU torch tensors
via the array protocol — ``as_device_array`` adds the zero-copy DLPack
route and an explicit place to put a dtype cast).
``set_output_as``/``output_as`` select what public APIs hand back
("jax" — the default, zero-cost — or "numpy"/"torch"/any callable), and
``auto_convert_output`` is the decorator the public entries wear.
Conversion only touches bare ``jax.Array`` leaves in tuple/list/dict
results — index pytrees pass through untouched — and only at the
library boundary: calls made *from raft_tpu modules* (ivf search calling
``select_k``, ball_cover calling brute force, the bench harness) always
keep device arrays, and under a jax trace the caller gets tracers
regardless of the configured output type.

Covered entries (everything else returns ``jax.Array``, itself a numpy-
protocol array): neighbors ``brute_force.search/knn``,
``ivf_flat.search``, ``ivf_pq.search``, ``cagra.search``,
``ball_cover.knn/eps_nn/epsilon_neighborhood``, ``refine.refine``;
``distance.pairwise_distance`` + ``fused_l2_nn_argmin`` /
``masked_l2_nn_argmin``; ``matrix.select_k``; and the ``cluster.kmeans`` entries
(``init_plus_plus``, ``fit``, ``predict``, ``fit_predict``,
``transform``, ``cluster_cost``, ``compute_new_centroids``,
``fit_mini_batch``).
"""
from __future__ import annotations

import contextlib
import contextvars
import functools
import sys
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import in_jax_trace
from .errors import expects

__all__ = ["as_device_array", "set_output_as", "output_as",
           "convert_output", "auto_convert_output"]

# process-wide default (the pylibraft config contract) + a contextvar
# overlay so the scoped form is thread-/async-safe
_GLOBAL_OUTPUT: Union[str, Callable[[jax.Array], Any]] = "jax"
_SCOPED_OUTPUT: contextvars.ContextVar[Optional[Union[str, Callable]]] = \
    contextvars.ContextVar("raft_tpu_output_as", default=None)


def _current_output():
    scoped = _SCOPED_OUTPUT.get()
    return _GLOBAL_OUTPUT if scoped is None else scoped


def as_device_array(x, dtype=None) -> jax.Array:
    """Adopt ``x`` as a ``jax.Array`` (zero-copy where the producer
    allows). Accepts jax arrays, numpy arrays, torch tensors, any object
    with ``__dlpack__``, and array-likes (lists, scalars)."""
    if isinstance(x, jax.Array):
        return x if dtype is None else x.astype(dtype)
    # lazy torch detection (covers Tensor subclasses): a torch tensor
    # can only exist if torch is already imported
    torch = sys.modules.get("torch")
    if torch is not None and isinstance(x, torch.Tensor):
        t = x.detach().cpu().contiguous()
        try:
            # from_dlpack commits to the producer's device (CPU): re-place
            # on the default backend so the result composes with
            # TPU-resident arrays instead of raising a device mismatch
            out = jax.device_put(jnp.from_dlpack(t))
        except Exception:  # layout/dtype the dlpack route won't take
            if t.dtype == torch.bfloat16:
                # numpy can't represent bf16: round-trip f32, restate
                out = jnp.asarray(np.asarray(t.float()), jnp.bfloat16)
            else:
                out = jnp.asarray(np.asarray(t))
        return out if dtype is None else out.astype(dtype)
    if hasattr(x, "__dlpack__") and not isinstance(x, np.ndarray):
        out = jax.device_put(jnp.from_dlpack(x))
        return out if dtype is None else out.astype(dtype)
    return jnp.asarray(x, dtype)


def _check_kind(kind):
    expects(callable(kind) or kind in ("jax", "numpy", "torch"),
            "output kind must be jax|numpy|torch or a callable, got %r",
            kind)


def set_output_as(kind: Union[str, Callable[[jax.Array], Any]]):
    """Set the process-wide output type for public APIs: "jax" (default),
    "numpy", "torch", or a callable applied to each output array (the
    pylibraft ``config.set_output_as`` contract). Returns the previous
    setting. Process-wide by design; for thread-safe scoping use the
    :func:`output_as` context manager."""
    global _GLOBAL_OUTPUT
    _check_kind(kind)
    prev, _GLOBAL_OUTPUT = _GLOBAL_OUTPUT, kind
    return prev


@contextlib.contextmanager
def output_as(kind):
    """Scoped :func:`set_output_as`, isolated per thread/task (contextvar
    overlay — concurrent threads never see each other's scope)."""
    _check_kind(kind)
    token = _SCOPED_OUTPUT.set(kind)
    try:
        yield
    finally:
        _SCOPED_OUTPUT.reset(token)


def _convert_leaf(x, kind):
    if not isinstance(x, jax.Array):
        return x
    if callable(kind):
        return kind(x)
    if kind == "numpy":
        # np.array copies: np.asarray would alias the device buffer
        # read-only on CPU backends, breaking in-place user code
        return np.array(x)
    if kind == "torch":
        import torch

        if x.dtype == jnp.bfloat16:
            # torch can't ingest ml_dtypes bf16 numpy arrays; round-trip
            # through f32 (value-exact) and restate the dtype
            return torch.from_numpy(
                np.array(x.astype(jnp.float32))).to(torch.bfloat16)
        # np.array copies: jax device buffers surface as read-only numpy
        # views, which torch tensors must not alias
        return torch.from_numpy(np.array(x))
    return x


def convert_output(out):
    """Apply the configured output conversion to bare ``jax.Array``
    leaves of ``out`` (recursing through tuple/list/dict — NamedTuples
    rebuilt field-wise — so index dataclasses and other rich objects
    pass through unchanged)."""
    kind = _current_output()
    if kind == "jax" or in_jax_trace():
        return out
    return _convert_tree(out, kind)


def _convert_tree(out, kind):
    if isinstance(out, jax.Array):
        return _convert_leaf(out, kind)
    if isinstance(out, tuple):
        vals = (_convert_tree(v, kind) for v in out)
        return type(out)(*vals) if hasattr(out, "_fields") else \
            type(out)(vals)
    if isinstance(out, list):
        return [_convert_tree(v, kind) for v in out]
    if isinstance(out, dict):
        return {k: _convert_tree(v, kind) for k, v in out.items()}
    return out


def auto_convert_output(fn):
    """Decorator: convert ``fn``'s result per the configured output type
    (pylibraft ``auto_convert_output``). Conversion happens only when the
    *caller* is outside raft_tpu — library internals that route through
    public entries (ivf search → ``select_k``, ball_cover → brute force,
    stats → pairwise distances, the bench harness) always keep device
    arrays, whatever the user configured."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        caller = sys._getframe(1).f_globals.get("__name__", "")
        if caller == "raft_tpu" or caller.startswith("raft_tpu."):
            return out
        return convert_output(out)

    return wrapped
