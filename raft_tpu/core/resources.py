"""Execution context: TPU-native analog of ``raft::resources``.

Reference: raft/core/resources.hpp:47 (type-indexed registry of lazily created
resources — stream, BLAS handles, comms, workspace allocator) and
raft/core/device_resources.hpp:61 (``handle_t`` convenience subclass).

On TPU there are no streams or vendor-library handles: XLA owns scheduling and
fusion. What survives is the *registry* idea — a shallow-copyable context
carrying (a) the device or mesh work targets, (b) a PRNG key source,
(c) a workspace byte budget that sizes tiled algorithms, and (d) an injected
comms object for multi-chip paths (mirroring how the reference injects
``comms_t`` into resources under the COMMUNICATOR key,
core/resource/resource_types.hpp:38-39).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import jax

from .errors import expects

__all__ = ["Resources", "DeviceResources", "device_resources_manager",
           "workspace_chunk_bytes"]


def workspace_chunk_bytes(res) -> int:
    """Per-chunk byte bound for streaming searches: the Resources budget
    when *explicitly configured* (clamped to a sane range), else 256 MB.
    A default-constructed Resources (workspace untouched) keeps the tuned
    default — passing a vanilla Resources for comms/device injection must
    not silently inflate memory use. ``res`` may be any deadline/comms
    carrier (e.g. a bare Deadline): no workspace attribute → default."""
    ws = getattr(res, "workspace_bytes", None) if res is not None else None
    if ws is not None and ws != DEFAULT_WORKSPACE_BYTES:
        return max(16 << 20, min(ws, 4 << 30))
    return 256 << 20

# Default workspace budget used to size tiles in streaming algorithms (the
# analog of the reference's workspace memory_resource limit). 2 GiB leaves
# headroom on a 16 GiB-HBM chip for the dataset itself.
DEFAULT_WORKSPACE_BYTES = 2 * 1024**3


class Resources:
    """Shallow-copyable, lazily-populated resource registry.

    Resources are created on first access through a registered factory, like
    the reference's ``resources::get_resource`` (resources.hpp:126-146).
    Unknown keys can be registered by callers (analog of custom_resource).
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        seed: int = 0,
        workspace_bytes: int = DEFAULT_WORKSPACE_BYTES,
        deadline=None,
    ):
        self._factories: Dict[str, Callable[[], Any]] = {}
        self._store: Dict[str, Any] = {}
        if device is not None:
            self._store["device"] = device
        if mesh is not None:
            self._store["mesh"] = mesh
        self._store["workspace_bytes"] = workspace_bytes
        if deadline is not None:
            self._store["deadline"] = deadline
        # generic registry access resolves the device the same lazy way the
        # .device property does, so both paths agree
        self._factories.setdefault("device", lambda: jax.devices()[0])
        self._key = jax.random.key(seed)
        # device_resources_manager shares one instance across server threads;
        # key splitting is a read-modify-write and must be serialized.
        self._key_lock = threading.Lock()

    # -- registry ---------------------------------------------------------
    def register(self, name: str, factory: Callable[[], Any]) -> None:
        """Register a lazy factory for a named resource."""
        self._factories[name] = factory

    def has(self, name: str) -> bool:
        return name in self._store or name in self._factories

    def get(self, name: str) -> Any:
        if name not in self._store:
            expects(name in self._factories, "unknown resource %r", name)
            self._store[name] = self._factories[name]()
        return self._store[name]

    def set(self, name: str, value: Any) -> None:
        self._store[name] = value

    # -- convenience accessors -------------------------------------------
    @property
    def device(self) -> jax.Device:
        return self.get("device")

    @property
    def mesh(self) -> Optional[jax.sharding.Mesh]:
        return self._store.get("mesh")

    @property
    def workspace_bytes(self) -> int:
        return self._store["workspace_bytes"]

    def next_key(self) -> jax.Array:
        """Split and return a fresh PRNG key (the stateful RNG convenience;
        algorithms that take explicit seeds bypass this)."""
        with self._key_lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    # -- deadline (injected like comms; see core/deadline.py) -------------
    @property
    def deadline(self):
        """The carried :class:`~raft_tpu.core.deadline.Deadline`, or None.
        Chunked searches probe it between dispatches (deadline.checkpoint)."""
        return self._store.get("deadline")

    def set_deadline(self, deadline) -> None:
        """Attach (or clear with None) a Deadline for subsequent searches."""
        if deadline is None:
            self._store.pop("deadline", None)
        else:
            self._store["deadline"] = deadline

    # -- comms (injected like the reference's COMMUNICATOR resource) ------
    @property
    def comms(self):
        expects("comms" in self._store, "no comms injected into resources")
        return self._store["comms"]

    def set_comms(self, comms) -> None:
        self._store["comms"] = comms

    def has_comms(self) -> bool:
        return "comms" in self._store

    def sync(self, value=None) -> None:
        """Block until queued device work is done (analog of ``sync_stream``).

        Prefer passing the array/pytree to wait on. With no value, a trivial
        op is dispatched to this context's device and blocked on — PJRT
        executes computations on a device in dispatch order, so its
        completion implies everything queued earlier finished. (Effect tokens
        alone don't cover ordinary computations.)
        """
        if value is not None:
            jax.block_until_ready(value)
        else:
            jax.effects_barrier()
            jax.device_put(0, self.device).block_until_ready()


class DeviceResources(Resources):
    """Convenience subclass mirroring ``raft::device_resources``/``handle_t``.

    Accepts a device ordinal like the reference's device-id ctor.
    """

    def __init__(self, device_id: int = 0, **kw):
        devices = jax.devices()
        expects(0 <= device_id < len(devices), "device_id %d out of range", device_id)
        super().__init__(device=devices[device_id], **kw)
        self.device_id = device_id


class _DeviceResourcesManager:
    """Thread-safe per-device pool of :class:`DeviceResources`.

    Analog of raft/core/device_resources_manager.hpp:36-96, which hands
    multi-threaded servers a shared per-device handle pool.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pool: Dict[int, DeviceResources] = {}

    def get_device_resources(self, device_id: int = 0) -> DeviceResources:
        with self._lock:
            if device_id not in self._pool:
                self._pool[device_id] = DeviceResources(device_id)
            return self._pool[device_id]

    def clear(self) -> None:
        with self._lock:
            self._pool.clear()


device_resources_manager = _DeviceResourcesManager()
