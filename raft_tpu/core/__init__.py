"""Core runtime: context, errors, logging, tracing, bitset, serialization.

TPU-native analog of the reference's ``raft/core/`` layer (SURVEY.md §2.1).
mdspan/mdarray deliberately have no analog — a ``jax.Array`` already carries
shape/dtype/layout, and XLA owns memory placement; the helpers here are what
remains genuinely runtime-shaped.
"""
from .bitset import Bitset
from .deadline import Deadline, DeadlineExceeded
from .errors import (CorruptIndexError, RaftError, ShardsDownError, expects,
                     fail)
from .faults import InjectedFault
from .interruptible import InterruptedException, synchronize
from .kvp import KeyValuePair
from .resources import DeviceResources, Resources, device_resources_manager
from .interop import (as_device_array, auto_convert_output, convert_output,
                      output_as, set_output_as)
from . import (events, faults, logging, operators, raft_format, serialize,
               tracing)

__all__ = [
    "Bitset",
    "RaftError",
    "CorruptIndexError",
    "ShardsDownError",
    "Deadline",
    "DeadlineExceeded",
    "InjectedFault",
    "expects",
    "fail",
    "InterruptedException",
    "synchronize",
    "events",
    "faults",
    "KeyValuePair",
    "DeviceResources",
    "Resources",
    "device_resources_manager",
    "as_device_array",
    "auto_convert_output",
    "convert_output",
    "output_as",
    "set_output_as",
    "logging",
    "operators",
    "raft_format",
    "serialize",
    "tracing",
]
