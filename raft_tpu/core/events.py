"""Flight recorder: a bounded ring of structured operational events.

The resilience machinery (guarded kernel demotions, fault injections,
deadline sheds, dead shards, autotune race verdicts, post-warmup XLA
recompiles) degrades *gracefully* — which means silently, unless the
degradations are recorded somewhere an operator can replay. This module
is that somewhere: a process-local, dependency-free ring of structured
events, each stamped with the trace IDs active when it fired
(:func:`raft_tpu.core.tracing.bind_trace` — the serving batcher binds
the requests it is dispatching), so "which requests got slow, and why"
has an answer after the fact.

Design constraints (mirrors :mod:`raft_tpu.serve.metrics`):

* **bounded**: a deque ring (default 512 events) — recording never
  grows without bound no matter how noisy a degradation storm is;
* **cheap and dependency-free**: plain dicts under one lock, no jax
  import at module load — recordable from any layer without cycles;
* **exportable**: :func:`to_jsonl` / :func:`export_jsonl` dump the ring
  as JSON-lines for offline triage; :mod:`raft_tpu.serve.debugz` folds
  the tail into its ops snapshot.

Event shape: ``{"seq", "ts", "kind", "site", "trace_id", ...details}``.
``trace_id`` is a string when exactly one trace was bound, a list when
a multi-request batch was in flight, None outside any binding.

Well-known kinds (open set — emitters define meaning):
``guarded_demotion``, ``fault_injected``, ``deadline_shed``,
``deadline_exceeded``, ``dispatch_error``, ``shard_marked``,
``autotune_verdict``, ``xla_compile``, ``corrupt_index``.
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import List, Optional

__all__ = ["record", "recent", "counts", "to_jsonl", "export_jsonl",
           "set_capacity", "clear", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 512

_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=DEFAULT_CAPACITY)
_seq = 0


def record(kind: str, site: str, trace_id=None, **details) -> dict:
    """Append one event. ``trace_id=None`` stamps the trace IDs bound on
    this thread (see module docstring); pass an explicit ID when the
    originating request is known (e.g. a shed, which happens outside the
    dispatch binding)."""
    global _seq
    if trace_id is None:
        from . import tracing

        ids = tracing.current_traces()
        trace_id = ids[0] if len(ids) == 1 else (list(ids) if ids else None)
    e = {"ts": time.time(), "kind": kind, "site": site, "trace_id": trace_id}
    if details:
        e.update(details)
    with _lock:
        _seq += 1
        e["seq"] = _seq
        _ring.append(e)
    return e


def recent(n: Optional[int] = None, kind: Optional[str] = None) -> List[dict]:
    """Most recent events, oldest first; ``kind`` filters. ``n=None``
    returns everything in the ring, ``n=0`` returns nothing."""
    with _lock:
        items = list(_ring)
    if kind is not None:
        items = [e for e in items if e["kind"] == kind]
    if n is None:
        return items
    return items[-n:] if n > 0 else []


def counts() -> dict:
    """Events per kind currently in the ring (NOT lifetime totals — the
    ring is bounded; lifetime counts live in the metrics registry)."""
    out: dict = {}
    with _lock:
        for e in _ring:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
    return out


def to_jsonl(n: Optional[int] = None, kind: Optional[str] = None) -> str:
    """The ring (tail ``n``, optionally filtered) as JSON-lines."""
    items = recent(n, kind)
    return "\n".join(json.dumps(e, sort_keys=True) for e in items) \
        + ("\n" if items else "")


def export_jsonl(path: str, n: Optional[int] = None) -> int:
    """Write the ring to ``path`` as JSONL; returns the event count."""
    items = recent(n)
    with open(path, "w") as f:
        for e in items:
            f.write(json.dumps(e, sort_keys=True) + "\n")
    return len(items)


def set_capacity(n: int) -> None:
    """Resize the ring (keeps the newest events)."""
    global _ring
    with _lock:
        _ring = collections.deque(_ring, maxlen=int(n))


def clear() -> None:
    with _lock:
        _ring.clear()
