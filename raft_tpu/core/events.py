"""Flight recorder: a bounded ring of structured operational events.

The resilience machinery (guarded kernel demotions, fault injections,
deadline sheds, dead shards, autotune race verdicts, post-warmup XLA
recompiles) degrades *gracefully* — which means silently, unless the
degradations are recorded somewhere an operator can replay. This module
is that somewhere: a process-local, dependency-free ring of structured
events, each stamped with the trace IDs active when it fired
(:func:`raft_tpu.core.tracing.bind_trace` — the serving batcher binds
the requests it is dispatching), so "which requests got slow, and why"
has an answer after the fact.

Design constraints (mirrors :mod:`raft_tpu.serve.metrics`):

* **bounded**: a deque ring (default 512 events) — recording never
  grows without bound no matter how noisy a degradation storm is;
* **cheap and dependency-free**: plain dicts under one lock, no jax
  import at module load — recordable from any layer without cycles;
* **exportable**: :func:`to_jsonl` / :func:`export_jsonl` dump the ring
  as JSON-lines for offline triage; :mod:`raft_tpu.serve.debugz` folds
  the tail into its ops snapshot.

Event shape: ``{"seq", "ts", "kind", "site", "trace_id", ...details}``.
``trace_id`` is a string when exactly one trace was bound, a list when
a multi-request batch was in flight, None outside any binding.

Well-known kinds (the :data:`WELL_KNOWN_KINDS` registry below — an
open set, emitters define meaning; the telemetry drift guard in
tests/test_telemetry.py holds every literal ``record()`` call in the
tree to it): ``guarded_demotion``, ``fault_injected``,
``deadline_shed``, ``deadline_exceeded``, ``dispatch_error``,
``shard_marked``, ``autotune_verdict``, ``xla_compile``,
``corrupt_index``, ``recall_regression``, ``slo_breach`` — the
self-healing set (docs/robustness.md): ``breaker_open`` /
``breaker_probe`` / ``breaker_close`` (ops/guarded circuit breakers),
``shard_restored`` (sharded_ann.probe_shards), ``brownout``
(serve/degrade ladder moves), ``fault_scenario`` (timed chaos-drill
stage transitions) — the mutable-tier set (docs/mutation.md,
neighbors/mutable.py): ``upsert`` / ``delete`` (one per mutation call,
trace-stamped like every serving event), ``merge_started`` /
``merge_committed`` / ``merge_abandoned`` (the background-merge state
machine), ``wal_recovered`` (a ``recover()`` replay, with
record/truncation counts) — and the multi-tenant set
(docs/serving.md "Multi-tenant fabric", serve/tenancy.py):
``tenant_shed`` (a token-bucket self-shed at admission, stamped with
the rejected request's trace ID), ``tenant_swap`` (one per
zero-downtime index flip, with the new generation and warmed shapes),
``qcache_stale`` (the recall sentinel caught the query cache serving a
provably-degraded hit; stamped with the crossing sample's trace ID) —
and the multi-host fleet set (docs/mnmg.md, parallel/fleet.py):
``host_lost`` / ``host_restored`` (a whole host's ICI clique left or
rejoined the serving set — the host-granular transition above the
per-shard ``shard_marked``/``shard_restored`` pair, carrying the
per-host health map), ``fleet_build`` (one distributed IVF-PQ build
completed, with topology and wire-shape stats), ``host_tier_armed``
(a beyond-HBM budget actually armed a host tier — one per distinct
budget value), ``fleet_tier_step`` (a host stepped down or back up the
per-host budget ladder: the MEMORY degrade axis of ROADMAP item 3).

Details are scrubbed JSON-safe at record time: non-finite floats become
None, numpy scalars/arrays become python values/lists (large arrays a
shape summary), exceptions become ``"Type: message"`` strings, unknown
objects their repr — so ``to_jsonl`` and the debugz snapshot can never
be broken by a hostile payload (an exception object in a
``dispatch_error``, an inf distance in a ``recall_regression``).
"""
from __future__ import annotations

import collections
import json
import math
import threading
import time
from typing import List, Optional

__all__ = ["record", "recent", "counts", "to_jsonl", "export_jsonl",
           "drain_new", "attach_sink", "detach_sink",
           "set_capacity", "clear", "DEFAULT_CAPACITY",
           "WELL_KNOWN_KINDS"]

DEFAULT_CAPACITY = 512

# the registered event vocabulary (module docstring, same order). An
# open set at runtime — record() accepts any kind — but every LITERAL
# kind in the library itself must be registered here, or the telemetry
# drift guard fails the suite: an operator greps dashboards by kind,
# so a new emitter must announce its vocabulary.
WELL_KNOWN_KINDS = frozenset({
    "guarded_demotion", "fault_injected", "deadline_shed",
    "deadline_exceeded", "dispatch_error", "shard_marked",
    "autotune_verdict", "xla_compile", "corrupt_index",
    "recall_regression", "slo_breach",
    # self-healing (docs/robustness.md)
    "breaker_open", "breaker_probe", "breaker_close", "shard_restored",
    "brownout", "fault_scenario",
    # mutable tier (docs/mutation.md)
    "upsert", "delete", "merge_started", "merge_committed",
    "merge_abandoned", "wal_recovered",
    # multi-tenant fabric (docs/serving.md "Multi-tenant fabric")
    "tenant_shed", "tenant_swap", "qcache_stale",
    # soak harness (docs/soak.md): ``hook_error`` — a SnapshotWriter
    # maintenance hook started/stopped failing (one event per
    # transition, not per failure); ``soak_phase`` — a SoakHarness
    # phase boundary (warmup/steady/chaos/recovery/quiesce)
    "hook_error", "soak_phase",
    # multi-host fleet (docs/mnmg.md)
    "host_lost", "host_restored", "fleet_build",
    # per-host storage tiers (docs/mnmg.md "Per-host storage tiers"):
    # ``host_tier_armed`` — a beyond-HBM budget became live (one per
    # distinct value, so debugz shows whether a tier is armed at all);
    # ``fleet_tier_step`` — a host stepped down/up the budget ladder
    # (the MEMORY degrade axis), with levels and effective budget
    "host_tier_armed", "fleet_tier_step",
    # selectivity-adaptive filtered search (docs/perf.md "Filtered
    # search"): a search routed to the compacted survivor-brute path
    "filter_crossover",
})

# arrays above this many elements are summarized, not inlined — one
# stray (10k, 128) distance matrix must not bloat the ring
_ARRAY_INLINE_MAX = 32

_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=DEFAULT_CAPACITY)
_seq = 0
_sink = None          # open JSONL file object (attach_sink), or None


def _json_safe(v, depth: int = 0):
    """Best-effort JSON-safe scrub (duck-typed: this module must stay
    numpy/jax-free). Never raises — worst case is a repr string."""
    try:
        if v is None or isinstance(v, (bool, int, str)):
            return v
        if isinstance(v, float):
            return v if math.isfinite(v) else None
        if isinstance(v, BaseException):
            return f"{type(v).__name__}: {v}"
        if depth >= 6:
            return repr(v)
        if isinstance(v, dict):
            return {str(k): _json_safe(x, depth + 1) for k, x in v.items()}
        if isinstance(v, (list, tuple, set, frozenset)):
            return [_json_safe(x, depth + 1) for x in v]
        # numpy/jax arrays: small ones inline as (scrubbed) lists, large
        # ones as a shape summary
        if hasattr(v, "dtype") and hasattr(v, "shape"):
            if getattr(v, "size", _ARRAY_INLINE_MAX + 1) <= _ARRAY_INLINE_MAX:
                return _json_safe(v.tolist(), depth + 1)
            return f"array(shape={tuple(v.shape)}, dtype={v.dtype})"
        if hasattr(v, "item"):            # numpy scalar
            return _json_safe(v.item(), depth + 1)
        return repr(v)
    except Exception:  # noqa: BLE001 - scrub must never raise
        try:
            return repr(v)
        except Exception:  # noqa: BLE001
            return "<unprintable>"


def record(kind: str, site: str, trace_id=None, **details) -> dict:
    """Append one event. ``trace_id=None`` stamps the trace IDs bound on
    this thread (see module docstring); pass an explicit ID when the
    originating request is known (e.g. a shed, which happens outside the
    dispatch binding)."""
    global _seq
    if trace_id is None:
        from . import tracing

        ids = tracing.current_traces()
        trace_id = ids[0] if len(ids) == 1 else (list(ids) if ids else None)
    e = {"ts": time.time(), "kind": kind, "site": site,
         "trace_id": _json_safe(trace_id)}
    if details:
        e.update({k: _json_safe(v) for k, v in details.items()})
    with _lock:
        _seq += 1
        e["seq"] = _seq
        _ring.append(e)
        if _sink is not None:
            try:
                _sink.write(json.dumps(e, sort_keys=True, default=repr)
                            + "\n")
            except Exception:  # noqa: BLE001 - a dead sink must never
                pass           # break the recording path
    return e


def recent(n: Optional[int] = None, kind: Optional[str] = None) -> List[dict]:
    """Most recent events, oldest first; ``kind`` filters. ``n=None``
    returns everything in the ring, ``n=0`` returns nothing."""
    with _lock:
        items = list(_ring)
    if kind is not None:
        items = [e for e in items if e["kind"] == kind]
    if n is None:
        return items
    return items[-n:] if n > 0 else []


def counts() -> dict:
    """Events per kind currently in the ring (NOT lifetime totals — the
    ring is bounded; lifetime counts live in the metrics registry)."""
    out: dict = {}
    with _lock:
        for e in _ring:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
    return out


def to_jsonl(n: Optional[int] = None, kind: Optional[str] = None) -> str:
    """The ring (tail ``n``, optionally filtered) as JSON-lines."""
    items = recent(n, kind)
    return "\n".join(json.dumps(e, sort_keys=True, default=repr)
                     for e in items) + ("\n" if items else "")


def export_jsonl(path: str, n: Optional[int] = None) -> int:
    """Write the ring to ``path`` as JSONL; returns the event count."""
    items = recent(n)
    with open(path, "w") as f:
        for e in items:
            f.write(json.dumps(e, sort_keys=True, default=repr) + "\n")
    return len(items)


def drain_new(cursor: int = 0):
    """Incremental read: every event still in the ring with
    ``seq > cursor``, plus the new cursor to pass next time —
    ``events, cursor = drain_new(cursor)``. A long soak polls this
    every tick so the 512-ring's churn never loses history. Events
    that aged out of the ring between polls are simply gone (use
    :func:`attach_sink` when losing any is unacceptable); the caller
    can detect the gap because the first returned ``seq`` jumps past
    ``cursor + 1``."""
    cursor = int(cursor)
    with _lock:
        items = [e for e in _ring if e["seq"] > cursor]
        new_cursor = _seq
    return items, new_cursor


def attach_sink(path: str, include_ring: bool = False) -> str:
    """Stream every FUTURE event to ``path`` as JSON-lines (append
    mode), in addition to the ring — the durable half of the flight
    recorder for runs longer than the ring. ``include_ring=True`` also
    dumps the current ring contents first (a soak that attaches late
    keeps its prologue). Re-attaching closes the previous sink.
    Returns ``path``."""
    global _sink
    f = open(path, "a", buffering=1)     # line-buffered: crash-readable
    with _lock:
        old, _sink = _sink, f
        prologue = list(_ring) if include_ring else []
        for e in prologue:
            f.write(json.dumps(e, sort_keys=True, default=repr) + "\n")
    if old is not None:
        try:
            old.close()
        except Exception:  # noqa: BLE001
            pass
    return path


def detach_sink() -> None:
    """Stop streaming and close the sink file (no-op when detached)."""
    global _sink
    with _lock:
        old, _sink = _sink, None
    if old is not None:
        try:
            old.close()
        except Exception:  # noqa: BLE001
            pass


def set_capacity(n: int) -> None:
    """Resize the ring (keeps the newest events)."""
    global _ring
    with _lock:
        _ring = collections.deque(_ring, maxlen=int(n))


def clear() -> None:
    with _lock:
        _ring.clear()
