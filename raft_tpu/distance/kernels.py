"""Kernel gram matrices: analog of ``raft::distance::kernels``.

Reference: raft/distance/kernels.cuh + detail/kernels/ (GramMatrix classes
with KernelParams{type, degree, gamma, coef0}; dense and CSR inputs).
CSR inputs are densified in row tiles before the GEMM — on TPU sparse
inputs buy memory, not FLOPs (see sparse/distance.py), and the gram
output is dense regardless.
"""
from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..utils import hdot
from .pairwise import pairwise_distance

__all__ = ["KernelType", "KernelParams", "gram_matrix"]


class KernelType(enum.Enum):
    LINEAR = "linear"
    POLYNOMIAL = "polynomial"
    RBF = "rbf"
    TANH = "tanh"


@dataclasses.dataclass
class KernelParams:
    """Mirror of the reference KernelParams (detail/kernels/gram_matrix.cuh)."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def gram_matrix(x, y, params: KernelParams,
                tile_rows: int = 4096) -> jax.Array:
    """Gram matrix K (m, n) between rows of x and y for the given kernel.

    ``x``/``y`` may be dense arrays or ``sparse.CSR`` (the reference's
    CSR GramMatrix overloads, detail/kernels/gram_matrix.cuh); CSR x is
    densified ``tile_rows`` rows at a time.
    """
    from ..sparse.csr import CSR

    if isinstance(y, CSR):
        y = y.to_dense()
    if isinstance(x, CSR):
        m = x.shape[0]
        if m > tile_rows:
            return jnp.concatenate(
                [gram_matrix(x.slice_rows(r, min(r + tile_rows, m)), y,
                             params, tile_rows)
                 for r in range(0, m, tile_rows)], axis=0)
        x = x.to_dense()
    expects(x.shape[1] == y.shape[1], "dim mismatch %s %s", x.shape, y.shape)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    k = params.kernel if isinstance(params.kernel, KernelType) else KernelType(params.kernel)
    if k is KernelType.LINEAR:
        return hdot(x, y.T)
    if k is KernelType.POLYNOMIAL:
        return (params.gamma * hdot(x, y.T) + params.coef0) ** params.degree
    if k is KernelType.TANH:
        return jnp.tanh(params.gamma * hdot(x, y.T) + params.coef0)
    if k is KernelType.RBF:
        sq = pairwise_distance(x, y, "sqeuclidean")
        return jnp.exp(-params.gamma * sq)
    raise AssertionError(k)
