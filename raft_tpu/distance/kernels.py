"""Kernel gram matrices: analog of ``raft::distance::kernels``.

Reference: raft/distance/kernels.cuh + detail/kernels/ (GramMatrix classes
with KernelParams{type, degree, gamma, coef0}; dense and CSR inputs). Dense
path here; the CSR path lives in raft_tpu.sparse once sparse containers land.
All four kernels ride one MXU GEMM plus a fused epilogue.
"""
from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..utils import hdot
from .pairwise import pairwise_distance

__all__ = ["KernelType", "KernelParams", "gram_matrix"]


class KernelType(enum.Enum):
    LINEAR = "linear"
    POLYNOMIAL = "polynomial"
    RBF = "rbf"
    TANH = "tanh"


@dataclasses.dataclass
class KernelParams:
    """Mirror of the reference KernelParams (detail/kernels/gram_matrix.cuh)."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def gram_matrix(x: jax.Array, y: jax.Array, params: KernelParams) -> jax.Array:
    """Gram matrix K (m, n) between rows of x and y for the given kernel."""
    expects(x.shape[1] == y.shape[1], "dim mismatch %s %s", x.shape, y.shape)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    k = params.kernel if isinstance(params.kernel, KernelType) else KernelType(params.kernel)
    if k is KernelType.LINEAR:
        return hdot(x, y.T)
    if k is KernelType.POLYNOMIAL:
        return (params.gamma * hdot(x, y.T) + params.coef0) ** params.degree
    if k is KernelType.TANH:
        return jnp.tanh(params.gamma * hdot(x, y.T) + params.coef0)
    if k is KernelType.RBF:
        sq = pairwise_distance(x, y, "sqeuclidean")
        return jnp.exp(-params.gamma * sq)
    raise AssertionError(k)
