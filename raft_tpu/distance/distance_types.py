"""Distance metric enumeration: analog of ``raft::distance::DistanceType``.

Reference: raft/distance/distance_types.hpp:23-68 (20 metrics + Precomputed).
The dense pairwise engine supports the same metric set the reference's dense
engine does (the per-metric op functors listed in
raft/distance/detail/distance_ops/, SURVEY.md §2.4); set-based metrics
(Jaccard/Dice) live in the sparse subsystem, as in the reference.
"""
from __future__ import annotations

import enum

__all__ = ["DistanceType", "canonical_metric", "is_min_close"]


class DistanceType(enum.Enum):
    """Metric identifiers; values mirror the reference enum's names."""

    L2Expanded = "l2_expanded"              # squared L2 via GEMM expansion
    L2SqrtExpanded = "l2_sqrt_expanded"     # L2 via GEMM expansion
    CosineExpanded = "cosine"               # 1 - cos(x, y)
    L1 = "l1"                               # Manhattan
    L2Unexpanded = "l2_unexpanded"          # squared L2, diff-based
    L2SqrtUnexpanded = "l2_sqrt_unexpanded"
    InnerProduct = "inner_product"          # similarity (larger = closer)
    Linf = "linf"                           # Chebyshev
    Canberra = "canberra"
    LpUnexpanded = "lp"                     # Minkowski, p = metric_arg
    CorrelationExpanded = "correlation"
    JaccardExpanded = "jaccard"             # sparse subsystem
    HellingerExpanded = "hellinger"
    Haversine = "haversine"                 # 2-D lat/lon
    BrayCurtis = "braycurtis"
    JensenShannon = "jensenshannon"
    HammingUnexpanded = "hamming"
    KLDivergence = "kl_divergence"
    RusselRaoExpanded = "russelrao"
    DiceExpanded = "dice"                   # sparse subsystem
    Precomputed = "precomputed"


# Accepted spellings for the string API (pylibraft accepts similar aliases,
# python/pylibraft/pylibraft/distance/pairwise_distance.pyx DISTANCE_TYPES).
_ALIASES = {
    "euclidean": DistanceType.L2SqrtExpanded,
    "l2": DistanceType.L2SqrtExpanded,
    "sqeuclidean": DistanceType.L2Expanded,
    "l2_expanded": DistanceType.L2Expanded,
    "l2_sqrt_expanded": DistanceType.L2SqrtExpanded,
    "l2_unexpanded": DistanceType.L2Unexpanded,
    "l2_sqrt_unexpanded": DistanceType.L2SqrtUnexpanded,
    "cosine": DistanceType.CosineExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "taxicab": DistanceType.L1,
    "inner_product": DistanceType.InnerProduct,
    "linf": DistanceType.Linf,
    "chebyshev": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "lp": DistanceType.LpUnexpanded,
    "minkowski": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "kldivergence": DistanceType.KLDivergence,
    "russelrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
    "precomputed": DistanceType.Precomputed,
}


def canonical_metric(metric) -> DistanceType:
    """Resolve a string alias or enum to a :class:`DistanceType`."""
    if isinstance(metric, DistanceType):
        return metric
    try:
        return _ALIASES[metric.lower()]
    except (KeyError, AttributeError):
        raise ValueError(f"unknown distance metric: {metric!r}") from None


def is_min_close(metric) -> bool:
    """True when smaller distance means closer (everything except
    InnerProduct, which is a similarity — mirrors the select_min flag
    pylibraft passes to select_k)."""
    return canonical_metric(metric) is not DistanceType.InnerProduct
