"""Dense pairwise distances: analog of ``raft::distance::pairwise_distance``.

Reference: raft/distance/distance-inl.cuh:67,238,329 (public API) and the
pairwise-matrix tile engine (detail/pairwise_matrix/dispatch-inl.cuh:69).

TPU design: two engines instead of the reference's SM60/SM80 kernel pair.

- **GEMM-expanded engine** for metrics whose cross term is an inner product
  (L2 expanded, cosine, inner product, correlation, hellinger, russelrao).
  The NxM cross term rides the MXU as one matmul; norms/corrections are
  rank-1 updates XLA fuses into the epilogue. This is where the FLOPs are
  and is the path brute-force kNN uses.
- **Elementwise tile engine** for metrics needing |x-y|-style terms
  (L1, Linf, Canberra, Lp, hamming, JS, KL, braycurtis, unexpanded L2).
  Computes (tile_m, tile_n, d) broadcast terms on the VPU, reduced over d,
  tiled so the intermediate stays within the workspace budget.

Both produce identical results to a NumPy/SciPy oracle (see
tests/test_distance.py); the expanded L2 path clamps tiny negatives exactly
like the reference does.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..core import interop, tracing
from ..utils import cdiv, hdot
from .distance_types import DistanceType, canonical_metric

__all__ = ["pairwise_distance", "distance"]

# Bytes of intermediate the elementwise engine may materialize per tile.
_TILE_BUDGET_BYTES = 64 * 1024 * 1024


# ---------------------------------------------------------------------------
# GEMM-expanded metrics
# ---------------------------------------------------------------------------

def _l2_expanded(x, y, sqrt: bool):
    """||x-y||^2 = ||x||^2 + ||y||^2 - 2<x,y>; cross term on the MXU."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    y2 = jnp.sum(y * y, axis=1, keepdims=True)
    cross = hdot(x, y.T)
    d = x2 + y2.T - 2.0 * cross
    d = jnp.maximum(d, 0.0)  # clamp fp cancellation, as the reference does
    return jnp.sqrt(d) if sqrt else d


def _cosine(x, y):
    xn = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True))
    cross = hdot(x, y.T)
    denom = jnp.maximum(xn * yn.T, 1e-30)
    return 1.0 - cross / denom


def _correlation(x, y):
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    yc = y - jnp.mean(y, axis=1, keepdims=True)
    return _cosine(xc, yc)


def _hellinger(x, y):
    # d = sqrt(1 - sum_i sqrt(x_i y_i)); inputs are probability-like (>= 0).
    ip = hdot(jnp.sqrt(jnp.abs(x)), jnp.sqrt(jnp.abs(y)).T)
    return jnp.sqrt(jnp.maximum(0.0, 1.0 - jnp.minimum(ip, 1.0)))


def _russelrao(x, y):
    # (d - <x, y>) / d over binary-ish data (reference russel_rao.cuh).
    k = x.shape[1]
    return (k - hdot(x, y.T)) / k


# ---------------------------------------------------------------------------
# Elementwise tile engine
# ---------------------------------------------------------------------------

def _elementwise_tile(x_tile, y_tile, metric: DistanceType, p: float):
    """Distance of one (tm, d) x-tile against one (tn, d) y-tile via
    broadcast terms reduced over d: returns (tm, tn)."""
    xe = x_tile[:, None, :]
    ye = y_tile[None, :, :]
    if metric is DistanceType.L1:
        return jnp.sum(jnp.abs(xe - ye), axis=-1)
    if metric in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
        d = jnp.sum((xe - ye) ** 2, axis=-1)
        return jnp.sqrt(d) if metric is DistanceType.L2SqrtUnexpanded else d
    if metric is DistanceType.Linf:
        return jnp.max(jnp.abs(xe - ye), axis=-1)
    if metric is DistanceType.Canberra:
        num = jnp.abs(xe - ye)
        den = jnp.abs(xe) + jnp.abs(ye)
        return jnp.sum(jnp.where(den == 0, 0.0, num / jnp.where(den == 0, 1.0, den)), axis=-1)
    if metric is DistanceType.LpUnexpanded:
        return jnp.sum(jnp.abs(xe - ye) ** p, axis=-1) ** (1.0 / p)
    if metric is DistanceType.HammingUnexpanded:
        return jnp.mean((xe != ye).astype(x_tile.dtype), axis=-1)
    if metric is DistanceType.BrayCurtis:
        num = jnp.sum(jnp.abs(xe - ye), axis=-1)
        den = jnp.sum(jnp.abs(xe + ye), axis=-1)
        return jnp.where(den == 0, 0.0, num / jnp.where(den == 0, 1.0, den))
    if metric is DistanceType.KLDivergence:
        # sum x log(x/y), terms with x == 0 contribute 0 (reference
        # kl_divergence.cuh uses the same convention).
        ratio = jnp.where(xe > 0, xe / jnp.where(ye > 0, ye, 1.0), 1.0)
        return jnp.sum(jnp.where(xe > 0, xe * jnp.log(ratio), 0.0), axis=-1)
    if metric is DistanceType.JensenShannon:
        m = 0.5 * (xe + ye)
        def _kl_terms(a):
            r = jnp.where(a > 0, a / jnp.where(m > 0, m, 1.0), 1.0)
            return jnp.where(a > 0, a * jnp.log(r), 0.0)
        js = 0.5 * jnp.sum(_kl_terms(xe) + _kl_terms(ye), axis=-1)
        return jnp.sqrt(jnp.maximum(js, 0.0))
    raise AssertionError(f"not an elementwise metric: {metric}")


def _haversine(x, y):
    """Great-circle distance over (lat, lon) radian pairs
    (reference: spatial/knn/detail/haversine_distance.cuh)."""
    expects(x.shape[1] == 2, "haversine requires 2-D (lat, lon) inputs")
    lat1, lon1 = x[:, None, 0], x[:, None, 1]
    lat2, lon2 = y[None, :, 0], y[None, :, 1]
    sin_dlat = jnp.sin(0.5 * (lat2 - lat1))
    sin_dlon = jnp.sin(0.5 * (lon2 - lon1))
    a = sin_dlat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sin_dlon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


_EXPANDED = {
    DistanceType.L2Expanded: functools.partial(_l2_expanded, sqrt=False),
    DistanceType.L2SqrtExpanded: functools.partial(_l2_expanded, sqrt=True),
    DistanceType.CosineExpanded: _cosine,
    DistanceType.InnerProduct: lambda x, y: hdot(x, y.T),
    DistanceType.CorrelationExpanded: _correlation,
    DistanceType.HellingerExpanded: _hellinger,
    DistanceType.RusselRaoExpanded: _russelrao,
}

_ELEMENTWISE = {
    DistanceType.L1,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.Linf,
    DistanceType.Canberra,
    DistanceType.LpUnexpanded,
    DistanceType.HammingUnexpanded,
    DistanceType.BrayCurtis,
    DistanceType.KLDivergence,
    DistanceType.JensenShannon,
}


def _tile_sizes(m: int, n: int, d: int, itemsize: int,
                workspace_bytes: int | None = None):
    """Pick (tm, tn) so tm*tn*d*itemsize stays within the tile budget,
    favoring full-width n tiles (better VPU utilization)."""
    # the reference sizes its scratch from the resources workspace
    # allocator; a Resources budget plays the same role here. Only an
    # explicitly configured budget changes the tuned tiling — a vanilla
    # Resources (default workspace) passed for comms/device injection
    # keeps the default footprint.
    from ..core.resources import DEFAULT_WORKSPACE_BYTES
    if workspace_bytes is not None and \
            workspace_bytes != DEFAULT_WORKSPACE_BYTES:
        total = min(max(workspace_bytes // 8, 16 << 20), 256 << 20)
    else:
        total = _TILE_BUDGET_BYTES
    budget = total // max(1, d * itemsize)
    tn = min(n, max(128, budget // 128))
    tm = max(1, min(m, budget // max(1, tn)))
    return tm, tn


@interop.auto_convert_output
@tracing.annotate("raft_tpu::distance::pairwise_distance")
def pairwise_distance(
    x: jax.Array,
    y: jax.Array,
    metric="l2_expanded",
    metric_arg: float = 2.0,
    res=None,
) -> jax.Array:
    """All-pairs distances between rows of ``x`` (m, d) and ``y`` (n, d).

    Analog of ``raft::distance::pairwise_distance``
    (distance-inl.cuh:238-329). Returns an (m, n) array in f32.
    ``res``: optional Resources whose workspace budget sizes the
    element-wise tiling (the reference's workspace-allocator role).
    """
    mt = canonical_metric(metric)
    expects(x.ndim == 2 and y.ndim == 2, "inputs must be 2-D (got %dD/%dD)", x.ndim, y.ndim)
    expects(x.shape[1] == y.shape[1], "dimension mismatch: %d vs %d", x.shape[1], y.shape[1])
    expects(mt is not DistanceType.Precomputed, "Precomputed is not a computable metric")
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)

    if mt in _EXPANDED:
        return _EXPANDED[mt](x, y)
    if mt is DistanceType.Haversine:
        return _haversine(x, y)
    expects(mt in _ELEMENTWISE, "metric %s is not supported by the dense engine "
            "(set-based metrics live in raft_tpu.sparse.distance)", mt.name)

    m, n, d = x.shape[0], y.shape[0], x.shape[1]
    ws = res.workspace_bytes if res is not None else None
    tm, tn = _tile_sizes(m, n, d, x.dtype.itemsize, ws)
    if tm >= m and tn >= n:
        return _elementwise_tile(x, y, mt, metric_arg)

    rows = []
    for i in range(cdiv(m, tm)):
        x_t = x[i * tm : min((i + 1) * tm, m)]
        cols = [
            _elementwise_tile(x_t, y[j * tn : min((j + 1) * tn, n)], mt, metric_arg)
            for j in range(cdiv(n, tn))
        ]
        rows.append(jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0])
    return jnp.concatenate(rows, axis=0) if len(rows) > 1 else rows[0]


def distance(x, y, metric="l2_expanded", metric_arg: float = 2.0):
    """Alias matching the reference's ``raft::distance::distance`` entry."""
    return pairwise_distance(x, y, metric, metric_arg)
