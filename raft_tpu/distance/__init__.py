"""Pairwise distances, fused nearest-neighbor reductions, kernel gram.

TPU-native analog of the reference's ``raft/distance/`` layer (SURVEY.md §2.4).
"""
from .distance_types import DistanceType, canonical_metric, is_min_close
from .fused_l2_nn import fused_l2_nn_argmin, masked_l2_nn_argmin
from .kernels import KernelParams, KernelType, gram_matrix
from .pairwise import distance, pairwise_distance

__all__ = [
    "DistanceType",
    "canonical_metric",
    "is_min_close",
    "fused_l2_nn_argmin",
    "masked_l2_nn_argmin",
    "KernelParams",
    "KernelType",
    "gram_matrix",
    "distance",
    "pairwise_distance",
]
