"""Fused L2 distance + per-row argmin: analog of ``raft::distance::fused_l2_nn``.

Reference: raft/distance/detail/fused_l2_nn.cuh:36,142,283-337 — one kernel
computing min/argmin over the full NxM distance matrix without materializing
it; the hot loop of kmeans predict.

TPU design: a `lax.scan` over column tiles of ``y``. Each step is one
(m, tile) GEMM on the MXU plus a running KVP-min update on the VPU; XLA keeps
the running minimum in registers/VMEM between steps, so HBM traffic is just
x, y, and the (m,) outputs — the same asymptotic saving as the CUDA kernel.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.errors import expects
from ..core import interop, tracing
from ..utils import hdot, round_up_to

__all__ = ["fused_l2_nn_argmin", "masked_l2_nn_argmin"]


@interop.auto_convert_output
@tracing.annotate("raft_tpu::distance::fused_l2_nn_argmin")
def fused_l2_nn_argmin(
    x: jax.Array,
    y: jax.Array,
    sqrt: bool = False,
    tile_n: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of ``x`` (m, d): index and distance of the nearest row of
    ``y`` (n, d) under (squared) L2. Returns (indices i32 (m,), distances
    f32 (m,)). Ties resolve to the smaller index, matching the reference's
    KVP argmin semantics.
    """
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "bad shapes %s %s", x.shape, y.shape)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    m, d = x.shape
    n = y.shape[0]

    tile_n = min(tile_n, round_up_to(n, 8))
    n_pad = round_up_to(n, tile_n)
    y_p = jnp.pad(y, ((0, n_pad - n), (0, 0)))
    y_tiles = y_p.reshape(n_pad // tile_n, tile_n, d)

    x2 = jnp.sum(x * x, axis=1)  # (m,)
    col = jnp.arange(tile_n, dtype=jnp.int32)

    def step(carry, inp):
        best_val, best_idx = carry
        y_t, base = inp
        y2 = jnp.sum(y_t * y_t, axis=1)                      # (tile,)
        cross = x @ y_t.T                                    # (m, tile) MXU
        dist = jnp.maximum(x2[:, None] + y2[None, :] - 2.0 * cross, 0.0)
        valid = (base + col) < n
        dist = jnp.where(valid[None, :], dist, jnp.inf)
        t_val = jnp.min(dist, axis=1)
        t_idx = jnp.argmin(dist, axis=1).astype(jnp.int32) + base
        # strict '<' keeps the earlier (smaller) index on ties because the
        # scan walks tiles in increasing index order
        take = t_val < best_val
        return (jnp.where(take, t_val, best_val),
                jnp.where(take, t_idx, best_idx)), None

    init = (jnp.full((m,), jnp.inf, jnp.float32), jnp.zeros((m,), jnp.int32))
    bases = (jnp.arange(n_pad // tile_n, dtype=jnp.int32) * tile_n)
    (val, idx), _ = jax.lax.scan(step, init, (y_tiles, bases))
    if sqrt:
        val = jnp.sqrt(val)
    return idx, val


@interop.auto_convert_output
@tracing.annotate("raft_tpu::distance::masked_l2_nn_argmin")
def masked_l2_nn_argmin(
    x: jax.Array,
    y: jax.Array,
    adj: jax.Array,
    group_idxs: jax.Array | None = None,
    sqrt: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Masked nearest neighbor: argmin over only the allowed (i, j) pairs.

    Analog of ``raft::distance::masked_l2_nn`` (masked_nn.cuh). Two mask
    forms, mirroring the reference's compressed group adjacency:

    - ``adj`` (m, n) boolean: pair-level mask.
    - ``adj`` (m, num_groups) boolean + ``group_idxs`` (num_groups,) end
      offsets: group g covers columns [group_idxs[g-1], group_idxs[g]).

    Rows with no allowed neighbor return index -1 and distance +inf (the
    reference leaves the initial KVP untouched in that case).
    """
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    m, n = x.shape[0], y.shape[0]
    if group_idxs is not None:
        ends = jnp.asarray(group_idxs, jnp.int32)            # (g,)
        starts = jnp.concatenate([jnp.zeros((1,), jnp.int32), ends[:-1]])
        cols = jnp.arange(n, dtype=jnp.int32)
        # column j belongs to group g iff starts[g] <= j < ends[g]
        member = (cols[None, :] >= starts[:, None]) & (cols[None, :] < ends[:, None])
        adj = (jnp.asarray(adj, bool) @ member.astype(jnp.float32)) > 0  # (m, n)
    else:
        expects(adj.shape == (m, n), "adj must be (m, n), got %s", adj.shape)
        adj = jnp.asarray(adj, bool)

    dist = jnp.maximum(
        jnp.sum(x * x, axis=1)[:, None]
        + jnp.sum(y * y, axis=1)[None, :]
        - 2.0 * hdot(x, y.T),
        0.0,
    )
    dist = jnp.where(adj, dist, jnp.inf)
    val = jnp.min(dist, axis=1)
    idx = jnp.where(jnp.isfinite(val), jnp.argmin(dist, axis=1).astype(jnp.int32), -1)
    if sqrt:
        val = jnp.sqrt(val)
    return idx, val
