"""The Fleet: topology-aware MNMG composition over ICI + DCN.

ROADMAP item 1 ("build a sharded IVF-PQ index on DEEP-1B across
v5p-32") needs three things no single-mesh module provides: a build
protocol where every host's data shapes one shared coarse quantizer
without the corpus ever crossing DCN, a search merge that respects the
ICI/DCN bandwidth cliff, and host-granular failure semantics. This
module composes the existing single-mesh machinery into exactly that:

* **Fleet** owns a host-major mesh plus its
  :class:`~raft_tpu.parallel.topology.Topology`, built three ways:
  :meth:`Fleet.local` (one host, today's meshes), :meth:`Fleet.virtual`
  (CPU-emulation: one process's virtual devices reshaped hosts × devs —
  every cross-host code path runs in tier-1), and
  :meth:`Fleet.distributed` (real ``jax.distributed`` processes via
  :func:`raft_tpu.comms.init_distributed`).

* **Distributed IVF-PQ build** (:meth:`Fleet.build_ivf_pq`): ONE coarse
  quantizer trained data-parallel — each shard contributes its own
  sample's centroid accumulators, allreduced across the fleet per Lloyd
  iteration — so every host's rows shape the same list structure
  (the single-mesh ``build_ivf_pq`` instead trains p independent
  quantizers, one per shard). What crosses DCN per iteration is
  ``n_lists × (dim + 1)`` floats of accumulator, never rows; list
  packing (assign/encode/sort) runs host-local on each host's own row
  block, and the packed device arrays are assembled from process-local
  slabs. PQ codebooks are trained once and broadcast. The allreduce is
  an allgather + LOCAL ordered sum, so the trained index is
  BIT-IDENTICAL no matter how the same topology is laid out over
  processes — a 2-process 2×2 fleet builds the same index as a
  1-process virtual 2×2 fleet (the dryrun's acceptance gate).

* **Search** (:meth:`Fleet.search`) routes through the existing
  ``sharded_ann.search_ivf_pq`` — the index carries its topology, so
  the merge chokepoint resolves the hierarchical ICI/DCN engine — and
  auto-widens ``n_probes`` by ``1/served_frac`` while shards are down
  (the ROADMAP "re-probe at a bigger radius" contract: losing 1/H of
  the corpus costs ~1/H recall; probing proportionally more lists on
  the survivors buys most of it back).

* **Host-loss degradation**: :meth:`mark_host_failed` masks a whole
  host's shards (sentinel rows in whichever merge engine runs,
  ``host_lost`` flight-recorder event), :meth:`probe_hosts` canaries
  the dead shards and emits ``host_restored`` when a host's full ICI
  clique is healthy again. Per-host health is one
  :meth:`host_health` call and a ``fleet`` debugz section.

* **Per-host storage tiers** (docs/mnmg.md "Per-host storage tiers"):
  :meth:`Fleet.build_ivf_pq` composes the single-host storage ladder
  with the fleet — ``store_dtype`` picks the rung each host's lists are
  stored at (``"pq"`` today's compressed build; ``"float32"`` /
  ``"int8"`` / ``"int4"`` flat rungs packed host-local, codes never
  crossing DCN), and ``hbm_budget_gb`` pins each host's resident set
  under a per-host HBM budget: hot lists stay device-resident, cold
  lists stream through :mod:`raft_tpu.neighbors.host_stream` chunks.
  Hot/cold is planned ONCE, fleet-wide, from per-list probe counts —
  only the ``(n_lists,)`` int count tables cross DCN
  (``process_allgather``), never rows. Budget *enforcement* is
  :class:`FleetTierController`: a host measured over budget (the memz
  decomposition aggregated per host in :meth:`Fleet.host_memz`) steps
  DOWN the ladder — resident set re-planned at half the budget, more
  lists streamed — instead of OOMing (``fleet_tier_step`` event,
  recovery on sustained headroom; the MEMORY degrade axis of ROADMAP
  item 3, reusing the brownout state machine). Every step re-packs the
  stepping host's shards into the EXISTING stacked shapes, so serving
  sees new values in the same compiled executables: zero recompiles,
  zero stranded work.
"""
from __future__ import annotations

import dataclasses
import time
import types
import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..comms import AxisComms
from ..core.errors import expects
from ..distance.distance_types import (DistanceType, canonical_metric,
                                       is_min_close)
from ..neighbors import host_stream as hs
from ..neighbors import ivf_flat, ivf_pq
from ..utils import cdiv, hdot, shard_map_compat
from . import dispatch_cache, sharded_ann
from .sharded_ann import ShardedIvfFlat, ShardedIvfPq
from .topology import AXIS, Topology, detect, fleet_mesh, plan_merge, virtual

__all__ = ["Fleet", "FleetBuildParams", "FleetTierController",
           "FLEET_STORE_RUNGS", "store_row_bytes", "ops_snapshot"]

# the storage-ladder rungs a fleet build can land on, cheapest-recall
# first (bench lane + docs order): full-precision flat, int8 flat,
# nibble-packed int4 flat, PQ codes
FLEET_STORE_RUNGS = ("float32", "int8", "int4", "pq")

# live fleets (weak — dropping a fleet must not leak it through debugz)
_FLEETS = weakref.WeakSet()


def store_row_bytes(store: str, dim: int, pq_dim: Optional[int] = None
                    ) -> int:
    """Resident bytes one stored row costs at a ladder rung — row data
    plus its per-row norms (4)/ids (4)/scales (4 where quantized). This
    is the number ``plan_hot_cold`` budgets with and ``plan_merge``'s
    storage block reports, so budget math in docs, bench, and the
    planner can never drift apart."""
    from ..ops.quant import int4_half_width

    if store == "pq":
        expects(pq_dim is not None and pq_dim > 0,
                "pq rung needs pq_dim for row-byte math")
        return int(pq_dim) + 12          # codes + norms + ids (tier rows
        #                                  carry decoded norms)
    if store == "float32":
        return dim * 4 + 8
    if store == "int8":
        return dim + 12
    if store == "int4":
        return int4_half_width(dim) + 12
    raise ValueError(f"unknown store rung {store!r}; "
                     f"expected one of {FLEET_STORE_RUNGS}")


@dataclasses.dataclass
class FleetBuildParams:
    """Knobs of the distributed coarse trainer (the fleet analog of
    :class:`raft_tpu.cluster.kmeans_balanced.BalancedKMeansParams` —
    the Lloyd/balancing structure mirrored into a pure-SPMD program).

    ``balancing_rounds`` re-seeds of starved lists (count below
    mean/``balancing_pessimism``) onto perturbed copies of the heaviest
    lists' centers, each followed by a share of the Lloyd iterations —
    deterministic (count-driven, no RNG), so process layout can't change
    the result."""

    balancing_rounds: int = 2
    balancing_pessimism: float = 2.5


def _effective_nprobe(n_probes: int, served_frac: float, n_lists: int) -> int:
    """The degradation auto-widen: probe ``n_probes / served_frac``
    lists while part of the corpus is dark, capped at ``n_lists``. At
    full health this is exactly ``n_probes`` — the healthy path is
    untouched."""
    frac = min(max(float(served_frac), 1.0 / max(n_lists, 1)), 1.0)
    return min(int(n_lists), int(np.ceil(n_probes / frac)))


def _host_slab(topo: Topology, host: int):
    """Leading-axis slice of a (p, ...) stacked array owned by ``host``."""
    s = topo.shards_of(host)
    return slice(s.start, s.stop)


def _fleet_put(mesh: Mesh, topo: Topology, global_np: np.ndarray, spec):
    """Assemble a (p, ...)-stacked P(AXIS, ...) fleet array.

    Single-process: a plain sharded ``device_put``. Multi-process: each
    process provides ONLY its own host's shard slab
    (``jax.make_array_from_process_local_data``) — this is the seam
    that keeps packed codes off DCN: every byte of a shard's lists is
    produced and device_put by the process that owns the shard."""
    sh = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(jnp.asarray(global_np), sh)
    local = np.ascontiguousarray(global_np[_host_slab(topo,
                                                      jax.process_index())])
    return jax.make_array_from_process_local_data(sh, local,
                                                  global_np.shape)


class Fleet:
    """A host-major mesh + topology and the MNMG operations over it
    (module docstring). Construct via :meth:`local`, :meth:`virtual`,
    or :meth:`distributed`."""

    def __init__(self, mesh: Mesh, topology: Topology):
        expects(AXIS in mesh.shape, "fleet mesh must have a %r axis", AXIS)
        expects(mesh.shape[AXIS] == topology.n_shards,
                "mesh has %d shards, topology %dx%d wants %d",
                mesh.shape[AXIS], topology.n_hosts, topology.devs_per_host,
                topology.n_shards)
        self.mesh = mesh
        self.topology = topology
        # indexes built by (or adopted into) this fleet — host-loss and
        # probe operations apply to all of them
        self._indexes = weakref.WeakSet()
        # hosts currently considered lost (mark_host_failed ⇄ probe_hosts)
        self._hosts_down: set = set()
        self.last_probe: Optional[dict] = None
        _FLEETS.add(self)

    # -- construction ------------------------------------------------------
    @classmethod
    def local(cls, n_devices: Optional[int] = None) -> "Fleet":
        """Single-host fleet over the local devices (today's meshes:
        ``Topology(1, n)`` — resolve_engine keeps the flat engines
        byte-for-byte)."""
        devs = jax.devices()
        n = len(devs) if n_devices is None else int(n_devices)
        mesh, topo = fleet_mesh(Topology(1, n), devices=devs[:n])
        return cls(mesh, topo)

    @classmethod
    def virtual(cls, n_hosts: int, devs_per_host: int) -> "Fleet":
        """CPU-emulation fleet: one process's (virtual) devices reshaped
        ``hosts × devs`` so the cross-host paths run without a pod."""
        mesh, topo = fleet_mesh(virtual(n_hosts, devs_per_host))
        return cls(mesh, topo)

    @classmethod
    def distributed(cls, coordinator_address: Optional[str] = None,
                    num_processes: Optional[int] = None,
                    process_id: Optional[int] = None) -> "Fleet":
        """Real multi-process fleet: bootstrap ``jax.distributed``
        (:func:`raft_tpu.comms.init_distributed` — args or the
        ``RAFT_TPU_COORDINATOR``/``_NUM_PROCESSES``/``_PROCESS_ID``
        env), then detect the topology from the global device set."""
        from ..comms import init_distributed

        init_distributed(coordinator_address, num_processes, process_id)
        mesh, topo = fleet_mesh(None)
        return cls(mesh, topo)

    # -- introspection -----------------------------------------------------
    @property
    def n_hosts(self) -> int:
        return self.topology.n_hosts

    @property
    def n_shards(self) -> int:
        return self.topology.n_shards

    def merge_plan(self, m: int = 128, k: int = 10) -> dict:
        """The search-merge wire math for this topology
        (:func:`raft_tpu.parallel.topology.plan_merge`)."""
        return plan_merge(self.topology, m, k)

    def host_health(self) -> dict:
        """Per-HOST view of the fleet's index health: for each host,
        whether every shard of every registered index is ok; plus the
        worst ``served_frac`` across indexes (the number the auto-widen
        uses). Healthy-with-no-indexes reads as all-up."""
        per_host = [True] * self.n_hosts
        served = 1.0
        for idx in list(self._indexes):
            ok = np.asarray(idx.shards_ok, bool)
            for h in range(self.n_hosts):
                if not ok[_host_slab(self.topology, h)].all():
                    per_host[h] = False
            served = min(served, sharded_ann.health(idx)["served_frac"])
        return {"topology": f"{self.n_hosts}x{self.topology.devs_per_host}",
                "hosts_ok": per_host,
                "hosts_down": sorted(self._hosts_down),
                "served_frac": round(served, 4)}

    # -- host-loss degradation --------------------------------------------
    def mark_host_failed(self, host: int, ok: bool = False) -> None:
        """Mark every shard of ``host`` across every registered index
        (host-granular ``shards_ok``): its ICI clique contributes
        sentinel rows to every merge until re-marked or re-probed.
        ``ok=True`` is the manual re-admit."""
        expects(0 <= host < self.n_hosts, "host %d out of range", host)
        for idx in list(self._indexes):
            for s in self.topology.shards_of(host):
                idx.mark_shard_failed(s, ok=ok)
        was_down = host in self._hosts_down
        if ok:
            self._hosts_down.discard(host)
        else:
            self._hosts_down.add(host)
        if ok == was_down:      # an actual host-level transition
            try:
                from ..core import events as _events

                _events.record("host_restored" if ok else "host_lost",
                               f"fleet.host{host}",
                               shards=list(self.topology.shards_of(host)),
                               **self.host_health())
            except Exception:  # noqa: BLE001 - telemetry must not fail ops
                pass

    def probe_hosts(self, **kw) -> dict:
        """Canary-probe dead shards of every registered index
        (:func:`raft_tpu.parallel.sharded_ann.probe_shards`) and
        re-admit hosts whose whole ICI clique recovered — the
        host-granular ``shard_restored`` loop, emitting one
        ``host_restored`` per recovered host. Returns
        ``{"shards": {family: {shard: ok}}, "hosts_restored": [...]}``."""
        shard_results: dict = {}
        for idx in list(self._indexes):
            if not np.asarray(idx.shards_ok, bool).all():
                shard_results.setdefault(idx.family, {}).update(
                    sharded_ann.probe_shards(idx, **kw))
        restored = []
        for h in sorted(self._hosts_down):
            up = all(np.asarray(idx.shards_ok,
                                bool)[_host_slab(self.topology, h)].all()
                     for idx in list(self._indexes))
            if up:
                self._hosts_down.discard(h)
                restored.append(h)
                try:
                    from ..core import events as _events

                    _events.record("host_restored", f"fleet.host{h}",
                                   shards=list(self.topology.shards_of(h)),
                                   **self.host_health())
                except Exception:  # noqa: BLE001
                    pass
        self.last_probe = {"ts": time.time(), "shards": shard_results,
                           "hosts_restored": restored}
        return {"shards": shard_results, "hosts_restored": restored}

    def adopt(self, index) -> None:
        """Register an externally built sharded index (its mesh must be
        this fleet's) for host-loss/probe management."""
        expects(getattr(index, "mesh", None) is self.mesh,
                "index was not built on this fleet's mesh")
        index.topology = self.topology
        self._indexes.add(index)

    # -- distributed build -------------------------------------------------
    def build_ivf_pq(self, dataset,
                     params: ivf_pq.IndexParams | None = None,
                     build_params: FleetBuildParams | None = None, *,
                     store_dtype: str = "pq",
                     hbm_budget_gb: Optional[float] = None,
                     sample_queries=None, chunk_mb: float = 4.0):
        """Distributed IVF-PQ build (module docstring): one allreduced
        coarse quantizer, broadcast codebooks, host-local list packing.

        ``dataset``: the (n, dim) corpus, visible to every process (from
        shared storage — NOT shipped over DCN; each process touches only
        its own hosts' row blocks for packing, and only the training
        sample feeds the allreduce). Returns a
        :class:`~raft_tpu.parallel.sharded_ann.ShardedIvfPq` whose
        searches resolve the topology-aware merge. PER_SUBSPACE
        codebooks only (PER_CLUSTER's trainer is host-driven and cannot
        run SPMD).

        ``store_dtype`` picks the storage rung (``FLEET_STORE_RUNGS``):
        the default ``"pq"`` is today's compressed build, byte-for-byte;
        ``"float32"``/``"int8"``/``"int4"`` store each host's lists as
        flat rows at that rung (the PR 13 ladder pushed through
        ``parallel/``), returning a
        :class:`~raft_tpu.parallel.sharded_ann.ShardedIvfFlat` over the
        SAME shared coarse quantizer. The rung is fleet-wide (one stacked
        dtype per index); per-host enforcement happens by shrinking the
        resident set, not by mixing dtypes.

        ``hbm_budget_gb`` (per HOST; ``RAFT_TPU_HBM_BUDGET_GB`` when
        None) arms the beyond-HBM rung: hot lists — planned fleet-wide
        from probe counts over ``sample_queries`` (list sizes standing
        in without a sample) — stay resident, cold lists stream from
        host RAM in ``chunk_mb`` chunks at search time, scored by the
        same XLA math as the resident path. Exact rungs stay BITWISE
        equal to the unbudgeted build's results (same probed lists, same
        per-candidate dot products — batch composition cancels out of
        both)."""
        p0 = params or ivf_pq.IndexParams()
        bp = build_params or FleetBuildParams()
        expects(store_dtype in FLEET_STORE_RUNGS,
                "store_dtype %r not in %s", store_dtype, FLEET_STORE_RUNGS)
        expects(p0.codebook_kind is ivf_pq.CodebookGen.PER_SUBSPACE,
                "fleet build supports PER_SUBSPACE codebooks only")
        mt = canonical_metric(p0.metric)
        dataset = np.asarray(dataset, np.float32)
        n, dim = dataset.shape
        p = self.n_shards
        L = p0.n_lists
        expects(4 <= p0.pq_bits <= 8, "pq_bits must be in [4,8], got %d",
                p0.pq_bits)
        expects(L <= n, "n_lists %d > n %d", L, n)
        pq_dim = p0.pq_dim or ivf_pq._default_pq_dim(dim)
        pq_len = cdiv(dim, pq_dim)
        rot_dim = pq_dim * pq_len
        book_size = 1 << p0.pq_bits
        budget = hs.budget_bytes(hbm_budget_gb)
        t0 = time.perf_counter()

        parts = sharded_ann._split_rows(n, p)
        # equal per-shard sample size: every shard must contribute the
        # same accumulator shapes, and the trainer needs enough rows per
        # shard to seed its slice of the centers and fill a codebook
        n_train = max(L, min(n, int(n * p0.kmeans_trainset_fraction)))
        t = max(book_size, cdiv(L, p), cdiv(n_train, p))
        samples = np.empty((p, t, dim), np.float32)
        for s in range(p):
            block = dataset[parts[s]]
            stride = max(1, len(block) // t)
            samples[s] = block[(np.arange(t) * stride) % len(block)]

        key = jax.random.key(p0.seed)
        k_rot, k_book = jax.random.split(key)
        rotation = np.asarray(ivf_pq.make_rotation_matrix(
            k_rot, rot_dim, dim, p0.force_random_rotation))

        if store_dtype == "pq":
            centers_rot, books = self._train(samples, rotation, L, pq_dim,
                                             pq_len, book_size, p0, bp,
                                             k_book)
            index, ctx = self._pack(dataset, parts, centers_rot, books,
                                    rotation, mt, p0, pq_dim,
                                    keep_host=budget > 0)
        else:
            # flat rungs share the SAME trainer program plus one extra
            # traced output (the input-space centers the flat searches
            # probe against); the pq path's program is untouched
            centers_rot, books, centers = self._train(
                samples, rotation, L, pq_dim, pq_len, book_size, p0, bp,
                k_book, want_centers=True)
            index, ctx = self._pack_flat(dataset, parts, centers, mt,
                                         store_dtype,
                                         keep_host=budget > 0)
        self.adopt(index)
        if budget > 0:
            ctx.update(store=store_dtype, dim=dim, metric=mt,
                       pq_dim=pq_dim if store_dtype == "pq" else None,
                       rotation=rotation if store_dtype == "pq" else None,
                       books=books if store_dtype == "pq" else None,
                       centers_rot=(centers_rot if store_dtype == "pq"
                                    else None))
            self._plan_budget(index, ctx, budget, sample_queries, chunk_mb)
        try:
            from ..core import events as _events

            _events.record(
                "fleet_build", "fleet.build_ivf_pq",
                topology=f"{self.n_hosts}x{self.topology.devs_per_host}",
                n=n, dim=dim, n_lists=L, pq_dim=pq_dim, pq_bits=p0.pq_bits,
                sample_rows_per_shard=t, store=store_dtype,
                hbm_budget_bytes=int(budget),
                wall_s=round(time.perf_counter() - t0, 3))
        except Exception:  # noqa: BLE001
            pass
        return index

    def _train(self, samples: np.ndarray, rotation: np.ndarray, L: int,
               pq_dim: int, pq_len: int, book_size: int, p0, bp, k_book,
               want_centers: bool = False):
        """The SPMD trainer: one shard_map program over the fleet mesh.

        Determinism contract: the cross-fleet allreduce is an allgather
        (pure data movement, axis-ordered) + a LOCAL ``jnp.sum`` over
        the gathered axis — the reduction order is fixed by the program,
        not the wire, so 1-process virtual and N-process real layouts of
        the same topology produce bitwise-equal centers. ``psum`` would
        be the hardware-efficient choice on a pod, at the cost of this
        guarantee. Codebooks are shard 0's, broadcast (masked psum:
        ``x + 0`` — exact).

        ``want_centers=True`` (the flat storage rungs) additionally
        returns the INPUT-space centers — a python-level flag, so the
        default traced program (the pq path, whose bitwise dryrun digest
        is pinned) is byte-identical to before."""
        p = self.n_shards
        t, dim = samples.shape[1:]
        iters = max(1, int(p0.kmeans_n_iters))
        rounds = max(0, int(bp.balancing_rounds))
        per = max(1, iters // (rounds + 1))
        ell = cdiv(L, p)
        stride = max(1, t // ell)

        def body(smp, rot):
            x = smp[0]                                   # (t, dim) local
            comms = AxisComms(AXIS, size=p)

            def allreduce_sum(v):
                # ordered: gather in axis order, reduce locally
                return jnp.sum(comms.allgather(v), axis=0)

            def lloyd(carry, n_it):
                def step(c, _):
                    d2 = (jnp.sum(x * x, axis=1, keepdims=True)
                          - 2.0 * hdot(x, c.T)
                          + jnp.sum(c * c, axis=1)[None, :])
                    lb = jnp.argmin(d2, axis=1)
                    sums = allreduce_sum(
                        jax.ops.segment_sum(x, lb, num_segments=L))
                    cnt = allreduce_sum(jax.ops.segment_sum(
                        jnp.ones((t,), jnp.float32), lb, num_segments=L))
                    new = jnp.where(cnt[:, None] > 0,
                                    sums / jnp.maximum(cnt, 1.0)[:, None], c)
                    return new, cnt
                c, cnts = jax.lax.scan(step, carry, None, length=n_it)
                return c, cnts[-1]

            # init: every shard seeds ceil(L/p) strided sample rows;
            # gathered in shard order, first L rows are the shared seed
            init = x[::stride][:ell]
            centers = comms.allgather(init).reshape(p * ell, dim)[:L]
            centers, cnt = lloyd(centers, per)
            for _ in range(rounds):
                # deterministic balancing: starved lists re-seed onto
                # perturbed copies of the heaviest lists' centers
                order = jnp.argsort(-cnt)
                starved = cnt < (jnp.mean(cnt) / bp.balancing_pessimism)
                rank = jnp.cumsum(starved.astype(jnp.int32)) - 1
                donor = centers[order[jnp.mod(rank, L)]]
                eps = 1e-4 * (1.0 + jnp.arange(L, dtype=jnp.float32)
                              )[:, None]
                centers = jnp.where(starved[:, None], donor * (1.0 + eps),
                                    centers)
                centers, cnt = lloyd(centers, per)

            c_rot = hdot(centers, rot.T)
            # codebooks: every shard trains on its OWN sample's residuals
            # (replicated compute), shard 0's result is broadcast — "one
            # trainer", SPMD-uniform
            x_rot = hdot(x, rot.T)
            d2 = (jnp.sum(x_rot * x_rot, axis=1, keepdims=True)
                  - 2.0 * hdot(x_rot, c_rot.T)
                  + jnp.sum(c_rot * c_rot, axis=1)[None, :])
            resid = x_rot - c_rot[jnp.argmin(d2, axis=1)]
            slices = jnp.transpose(resid.reshape(t, pq_dim, pq_len),
                                   (1, 0, 2))
            books = ivf_pq._train_per_subspace(slices, book_size, iters,
                                               k_book)
            if want_centers:
                return c_rot, comms.bcast(books, root=0), centers
            return c_rot, comms.bcast(books, root=0)

        out_specs = (P(), P(), P()) if want_centers else (P(), P())
        prog = jax.jit(shard_map_compat(
            body, mesh=self.mesh, in_specs=(P(AXIS, None, None), P()),
            out_specs=out_specs, check=False))
        smp = _fleet_put(self.mesh, self.topology, samples,
                         P(AXIS, None, None))
        if want_centers:
            c_rot, books, centers = prog(smp, jnp.asarray(rotation))
            return (np.asarray(c_rot), np.asarray(books),
                    np.asarray(centers))
        c_rot, books = prog(smp, jnp.asarray(rotation))
        return np.asarray(c_rot), np.asarray(books)

    def _pack(self, dataset, parts, centers_rot, books, rotation, mt, p0,
              pq_dim, keep_host: bool = False):
        """Host-local list packing: each process assigns/encodes/sorts
        ONLY its own hosts' row blocks against the replicated quantizer,
        then the (p, ...)-stacked device arrays are assembled from
        process-local slabs (:func:`_fleet_put`). The tiny per-shard
        list-size tables — the only cross-host metadata — travel via
        ``process_allgather``.

        Returns ``(index, ctx)``; with ``keep_host=True`` (a budgeted
        build) ``ctx`` keeps each LOCAL shard's cluster-sorted host
        arrays plus the full size/offset tables so the tier planner can
        (re)split hot/cold without fetching device arrays."""
        topo = self.topology
        p = self.n_shards
        L = centers_rot.shape[0]
        multi = jax.process_count() > 1
        my_shards = (list(topo.shards_of(jax.process_index())) if multi
                     else list(range(p)))
        R = max(len(part) for part in parts)          # common padded rows

        c_rot_j = jnp.asarray(centers_rot)
        books_j = jnp.asarray(books)
        rot_j = jnp.asarray(rotation)

        @jax.jit
        def assign_encode(xb):
            xb_rot = hdot(xb, rot_j.T)
            d2 = (jnp.sum(xb_rot * xb_rot, axis=1, keepdims=True)
                  - 2.0 * hdot(xb_rot, c_rot_j.T)
                  + jnp.sum(c_rot_j * c_rot_j, axis=1)[None, :])
            lb = jnp.argmin(d2, axis=1)
            resid = xb_rot - c_rot_j[lb]
            return lb.astype(jnp.int32), ivf_pq._encode(resid, books_j, lb,
                                                        False)

        codes = np.zeros((p, R, pq_dim), np.uint8)
        gids = np.full((p, R), -1, np.int32)
        sizes = np.zeros((p, L), np.int32)
        host_arrays: dict = {}
        for s in my_shards:
            rows = parts[s]
            lb, cd = assign_encode(jnp.asarray(dataset[rows], jnp.float32))
            lb, cd = np.asarray(lb), np.asarray(cd)
            order = np.argsort(lb, kind="stable")     # cluster-sorted lists
            codes[s, : len(rows)] = cd[order]
            gids[s, : len(rows)] = rows[order]        # GLOBAL row ids
            sizes[s] = np.bincount(lb, minlength=L)
            if keep_host:
                host_arrays[s] = {
                    "codes": codes[s, : len(rows)].copy(),
                    "ids": gids[s, : len(rows)].copy(),
                }
        if multi:
            from jax.experimental import multihost_utils

            # every process's local (D, L) size block, host-major — the
            # only packing metadata that crosses DCN
            local = sizes[_host_slab(topo, jax.process_index())]
            sizes = np.asarray(multihost_utils.process_allgather(
                jnp.asarray(local))).reshape(p, L).astype(np.int32)
        offsets = np.concatenate(
            [np.zeros((p, 1), np.int64), np.cumsum(sizes, axis=1)[:, :-1]],
            axis=1).astype(np.int32)

        put = lambda a, spec: _fleet_put(self.mesh, topo, a, spec)
        stack = lambda a: np.broadcast_to(a, (p,) + a.shape).copy()
        idx = ShardedIvfPq(
            self.mesh,
            put(codes, P(AXIS, None, None)),
            put(gids, P(AXIS, None)),
            put(stack(centers_rot), P(AXIS, None, None)),
            put(stack(books), P(AXIS, None, None, None)),
            put(stack(rotation), P(AXIS, None, None)),
            put(offsets, P(AXIS, None)),
            put(sizes, P(AXIS, None)),
            len(dataset), mt, p0.pq_bits, p0.codebook_kind,
            [sizes[s] for s in range(p)])
        ctx = {"sizes_full": sizes.copy(), "arrays": host_arrays,
               "fills": {"ids": -1, "labels": 0},
               "resident_names": ("codes", "ids"),
               "attr_of": {"codes": "codes", "ids": "source_ids"}}
        return idx, ctx

    def _pack_flat(self, dataset, parts, centers, mt, store,
                   keep_host: bool = False):
        """Flat-rung packing (the storage-ladder analog of :meth:`_pack`):
        each process assigns its own hosts' rows to the SHARED coarse
        quantizer, quantizes them at the rung
        (:mod:`raft_tpu.ops.quant`), cluster-sorts, and assembles the
        stacked :class:`ShardedIvfFlat` from process-local slabs — rows
        never cross the DCN, same contract as the pq pack. Stored norms
        are the DEQUANTIZED rows' (what the search math scores against),
        so a quantized rung is self-consistent, not mixed-precision."""
        from ..ops import quant

        topo = self.topology
        p = self.n_shards
        n, dim = dataset.shape
        L = centers.shape[0]
        multi = jax.process_count() > 1
        my_shards = (list(topo.shards_of(jax.process_index())) if multi
                     else list(range(p)))
        R = max(len(part) for part in parts)

        c_j = jnp.asarray(centers)

        @jax.jit
        def assign(xb):
            d2 = (jnp.sum(xb * xb, axis=1, keepdims=True)
                  - 2.0 * hdot(xb, c_j.T)
                  + jnp.sum(c_j * c_j, axis=1)[None, :])
            return jnp.argmin(d2, axis=1).astype(jnp.int32)

        @jax.jit
        def quantize(xb):
            if store == "float32":
                return xb, None, jnp.sum(xb * xb, axis=1)
            rows, scales = quant.quantize_rows(
                xb, "int4" if store == "int4" else jnp.int8)
            deq = (quant.dequantize_int4(rows, scales, dim)
                   if store == "int4"
                   else quant.dequantize_rows(rows, scales))
            return rows, scales, jnp.sum(deq * deq, axis=1)

        has_scales = store in ("int8", "int4")
        width = (quant.int4_half_width(dim) if store == "int4" else dim)
        data = np.zeros((p, R, width),
                        np.int8 if has_scales else np.float32)
        norms = np.zeros((p, R), np.float32)
        scales_np = np.ones((p, R), np.float32) if has_scales else None
        gids = np.full((p, R), -1, np.int32)
        sizes = np.zeros((p, L), np.int32)
        host_arrays: dict = {}
        for s in my_shards:
            rows_idx = parts[s]
            xb = jnp.asarray(dataset[rows_idx], jnp.float32)
            lb = np.asarray(assign(xb))
            rq, sc, nr = quantize(xb)
            order = np.argsort(lb, kind="stable")     # cluster-sorted
            m = len(rows_idx)
            data[s, :m] = np.asarray(rq)[order]
            norms[s, :m] = np.asarray(nr)[order]
            if has_scales:
                scales_np[s, :m] = np.asarray(sc)[order]
            gids[s, :m] = rows_idx[order]             # GLOBAL row ids
            sizes[s] = np.bincount(lb, minlength=L)
            if keep_host:
                host_arrays[s] = {
                    "data": data[s, :m].copy(),
                    "norms": norms[s, :m].copy(),
                    "ids": gids[s, :m].copy(),
                }
                if has_scales:
                    host_arrays[s]["scales"] = scales_np[s, :m].copy()
        if multi:
            from jax.experimental import multihost_utils

            local = sizes[_host_slab(topo, jax.process_index())]
            sizes = np.asarray(multihost_utils.process_allgather(
                jnp.asarray(local))).reshape(p, L).astype(np.int32)
        offsets = np.concatenate(
            [np.zeros((p, 1), np.int64), np.cumsum(sizes, axis=1)[:, :-1]],
            axis=1).astype(np.int32)
        cnorms = np.sum(centers * centers, axis=1).astype(np.float32)

        put = lambda a, spec: _fleet_put(self.mesh, topo, a, spec)
        stack = lambda a: np.broadcast_to(a, (p,) + a.shape).copy()
        idx = ShardedIvfFlat(
            self.mesh,
            put(data, P(AXIS, None, None)),
            put(norms, P(AXIS, None)),
            put(gids, P(AXIS, None)),
            put(stack(centers.astype(np.float32)), P(AXIS, None, None)),
            put(stack(cnorms), P(AXIS, None)),
            put(offsets, P(AXIS, None)),
            put(sizes, P(AXIS, None)),
            n, mt, [sizes[s] for s in range(p)],
            scales=(put(scales_np, P(AXIS, None)) if has_scales else None),
            store=store, logical_dim=dim)
        idx.topology = self.topology
        ctx = {"sizes_full": sizes.copy(), "arrays": host_arrays,
               "fills": {"ids": -1, "scales": 1.0},
               "resident_names": (("data", "norms", "ids", "scales")
                                  if has_scales
                                  else ("data", "norms", "ids")),
               "attr_of": {"data": "data", "norms": "data_norms",
                           "ids": "source_ids", "scales": "scales"},
               "centers": centers.astype(np.float32),
               "cnorms": cnorms}
        return idx, ctx

    # -- per-host HBM-budget tiers ----------------------------------------
    def _plan_budget(self, index, ctx, budget: int, sample_queries,
                     chunk_mb: float, n_probes_plan: int = 20) -> None:
        """Arm the beyond-HBM rung fleet-wide: one hot/cold plan per
        host from fleet-aggregated probe counts, each shard's cold
        lists cut into host-RAM chunks, resident arrays re-packed to
        the hot rows. Only ``(n_lists,)`` int count tables cross DCN
        (:meth:`_probe_counts`); every process computes every host's
        mask from the global size table, so the plans cannot diverge."""
        topo = self.topology
        p = self.n_shards
        sizes = ctx["sizes_full"]
        row_bytes = store_row_bytes(ctx["store"], ctx["dim"],
                                    ctx.get("pq_dim"))
        ctx["row_bytes"] = row_bytes
        ctx["budget_bytes"] = int(budget)
        ctx["chunk_rows"] = max(1, int(float(chunk_mb) * (1 << 20))
                                // max(int(row_bytes), 1))
        # level-invariant chunk geometry: every budget-ladder level's
        # cold chunks share ONE padded shape (row pin covers the largest
        # list ANY level could shed; list pin covers all-cold), so a
        # FleetTierController re-tier lands in the already-compiled
        # cold-scan executables — zero recompiles, the same discipline
        # _swap_resident applies to the resident slabs
        lmax_g = int(sizes.max()) if sizes.size else 0
        ctx["chunk_shape"] = (max(ctx["chunk_rows"], lmax_g, 1),
                              int(sizes.shape[1]) + 1, lmax_g)
        # full cluster-sorted row offsets per shard (L+1), the tier
        # splitter's view of the pre-tier layout
        ctx["offsets_full"] = {
            s: np.concatenate([[0], np.cumsum(sizes[s].astype(np.int64))])
            for s in range(p)}
        ctx["counts"] = (None if sample_queries is None
                         else self._probe_counts(ctx, sample_queries,
                                                 n_probes_plan))
        ctx["levels"] = {h: 0 for h in range(self.n_hosts)}
        ctx["hot"] = {}
        ctx["hot_sizes"] = {}
        ctx["hot_offsets"] = {}
        index._fleet_ctx = ctx
        index._fleet_tiers = {}
        # health() must keep reporting the FULL corpus as served: cold
        # rows stream, they are not lost (the auto-widen keys off this)
        index._rows_tbl_full = [sizes[s] for s in range(p)]
        # R_hot: the padded resident row count every shard shares — the
        # compiled row shape every later tier step must fit back into
        masks = {h: hs.plan_hot_cold(
            sizes[_host_slab(topo, h)].sum(axis=0).astype(np.int64),
            row_bytes, budget, ctx["counts"]) for h in range(self.n_hosts)}
        ctx["R_hot"] = max(1, max(
            int(sizes[s][masks[topo.host_of(s)]].sum()) for s in range(p)))
        ctx["resident"] = self._blank_resident(ctx)
        for h in range(self.n_hosts):
            self._retier_host(index, h, masks[h])
        self._swap_resident(index)

    def _blank_resident(self, ctx) -> dict:
        """Fill-initialized (p, R_hot, ...) host copies of the resident
        arrays — the buffers :meth:`_retier_host` packs hot rows into
        and :meth:`_swap_resident` device_puts whole."""
        p = self.n_shards
        R_hot = ctx["R_hot"]
        out = {}
        for name in ctx["resident_names"]:
            # any local shard's host array gives the trailing shape/dtype
            proto = next(iter(ctx["arrays"].values()))[name]
            out[name] = np.full((p, R_hot) + proto.shape[1:],
                                ctx["fills"].get(name, 0), proto.dtype)
        return out

    def _probe_counts(self, ctx, sample_queries,
                      n_probes: int) -> np.ndarray:
        """Fleet-wide per-list probe counts over a query sample: each
        process probes ITS slice against the replicated quantizer, then
        the ``(n_lists,)`` int tables are allgathered and summed — the
        only planning signal that crosses DCN."""
        from ..ops.ivf_scan import coarse_probe

        L = ctx["sizes_full"].shape[1]
        q = np.asarray(sample_queries, np.float32)
        nproc = jax.process_count()
        if nproc > 1:
            q = q[jax.process_index()::nproc]
        if q.shape[0] == 0:
            local = np.zeros(L, np.int64)
        elif ctx["store"] == "pq":
            q_rot = hdot(jnp.asarray(q), jnp.asarray(ctx["rotation"]).T)
            probed = np.asarray(coarse_probe(
                q_rot, jnp.asarray(ctx["centers_rot"]),
                min(n_probes, L),
                metric="ip" if ctx["metric"] is DistanceType.InnerProduct
                else "l2"))
            local = hs.probe_frequency(probed, L)
        else:
            mt = ctx["metric"]
            cmetric = ("ip" if mt is DistanceType.InnerProduct
                       else "cos" if mt is DistanceType.CosineExpanded
                       else "l2")
            probed = np.asarray(coarse_probe(
                jnp.asarray(q), jnp.asarray(ctx["centers"]),
                min(n_probes, L), metric=cmetric,
                center_norms=jnp.asarray(ctx["cnorms"])))
            local = hs.probe_frequency(probed, L)
        if nproc > 1:
            from jax.experimental import multihost_utils

            g = np.asarray(multihost_utils.process_allgather(
                jnp.asarray(local)))
            return g.reshape(nproc, L).sum(axis=0).astype(np.int64)
        return local.astype(np.int64)

    def _retier_host(self, index, host: int, hot_mask) -> None:
        """(Re)build one host's tiers + resident slabs for a hot mask,
        clamped into the index's existing padded row shape ``R_hot`` —
        a tier step must never grow device arrays or fork compiled
        signatures. Resident size/offset tables are global knowledge
        (sizes × mask) computed on every process; row arrays only on
        the owning process."""
        ctx = index._fleet_ctx
        topo = self.topology
        sizes = ctx["sizes_full"]
        R_hot = ctx["R_hot"]
        hot = np.asarray(hot_mask, bool).copy()
        host_sizes = sizes[_host_slab(topo, host)].sum(axis=0
                                                       ).astype(np.int64)
        freq = (host_sizes.astype(np.float64) if ctx["counts"] is None
                else np.asarray(ctx["counts"], np.float64))
        dens = freq / np.maximum(host_sizes * ctx["row_bytes"], 1.0)
        while max(int(sizes[s][hot].sum())
                  for s in topo.shards_of(host)) > R_hot:
            cands = np.flatnonzero(hot & (host_sizes > 0))
            hot[cands[np.argmin(dens[cands])]] = False
        ctx["hot"][host] = hot
        multi = jax.process_count() > 1
        local = (not multi) or (jax.process_index() == host)
        for s in topo.shards_of(host):
            hsz = np.where(hot, sizes[s], 0).astype(np.int64)
            ctx["hot_sizes"][s] = hsz
            ctx["hot_offsets"][s] = np.concatenate(
                [[0], np.cumsum(hsz)[:-1]])
            if not local:
                continue
            arrays = dict(ctx["arrays"][s])
            if ctx["store"] == "pq":
                arrays["labels"] = np.repeat(
                    np.arange(sizes.shape[1]), sizes[s]).astype(np.int32)
                arrays["norms"] = self._pq_row_norms(ctx, s)
            tier, hot_arrays, _, _ = hs.build_tier(
                arrays, ctx["offsets_full"][s], sizes[s], hot,
                ctx["chunk_rows"], pad_tail=0, fills=ctx["fills"],
                chunk_shape=ctx.get("chunk_shape"))
            if ctx["store"] == "pq":
                self._pq_chunk_extras(ctx, tier)
            index._fleet_tiers[s] = tier
            for name, res in ctx["resident"].items():
                res[s] = ctx["fills"].get(name, 0)
                rows = hot_arrays[name]
                res[s][: rows.shape[0]] = rows

    def _pq_row_norms(self, ctx, s: int) -> np.ndarray:
        """Exact decoded ||row||² for shard ``s``'s cluster-sorted codes
        (what the XLA cold rescore scores with), cached per shard."""
        cache = ctx.setdefault("_row_norms", {})
        if s not in cache:
            from ..ops.ivf_pq_scan import decoded_row_norms

            cache[s] = np.asarray(decoded_row_norms(
                jnp.asarray(ctx["arrays"][s]["codes"]),
                jnp.asarray(ctx["centers_rot"]),
                jnp.asarray(ctx["books"]),
                ctx["offsets_full"][s]), np.float32)
        return cache[s]

    def _pq_chunk_extras(self, ctx, tier) -> None:
        """Chunk-local label remap + per-chunk rotated centers (the
        ivf_pq.prepare_host_stream pattern) so the XLA cold rescore can
        reconstruct ``center + decode`` without global tables."""
        cent = ctx["centers_rot"]
        L = cent.shape[0]
        for ci, ch in enumerate(tier.chunks):
            lab = np.clip(ch.arrays["labels"], 0, L - 1)
            ch.arrays["labels"] = np.where(
                tier.chunk_of[lab] == ci, tier.local_of[lab],
                0).astype(np.int32)
            loc = np.zeros((tier.chunk_lists, cent.shape[1]), np.float32)
            loc[: len(ch.lists)] = cent[ch.lists]
            tier.extras[ci]["centers"] = loc

    def _swap_resident(self, index) -> None:
        """Re-put the stacked resident arrays and list tables from the
        ctx host copies. Shapes never change across tier steps, so the
        compiled search executables are reused — a step swaps VALUES,
        not signatures (the zero-recompile contract of the drill)."""
        ctx = index._fleet_ctx
        p = self.n_shards
        put = lambda a, spec: _fleet_put(self.mesh, self.topology, a, spec)
        for name, arr in ctx["resident"].items():
            spec = P(AXIS, *([None] * (arr.ndim - 1)))
            setattr(index, ctx["attr_of"][name], put(arr, spec))
        sizes = np.stack([ctx["hot_sizes"][s]
                          for s in range(p)]).astype(np.int32)
        offsets = np.stack([ctx["hot_offsets"][s]
                            for s in range(p)]).astype(np.int32)
        index.offsets = put(offsets, P(AXIS, None))
        index.sizes = put(sizes, P(AXIS, None))
        if index.family == "ivf_pq":
            index._sizes_host = [sizes[s] for s in range(p)]
        else:
            index._max_rows_tbl = [sizes[s] for s in range(p)]

    def _apply_tier_level(self, index, host: int, level: int,
                          old_level: int, reason: str) -> None:
        """Move one host to budget-ladder ``level``: re-plan its hot set
        at ``budget / 2**level``, rebuild its shards' tiers and resident
        slabs in place, and flight-record the transition. Called by
        :class:`FleetTierController` on a verdict edge."""
        ctx = index._fleet_ctx
        budget = int(ctx["budget_bytes"])
        eff = max(1, budget >> int(level))
        sizes = ctx["sizes_full"]
        host_sizes = sizes[_host_slab(self.topology, host)].sum(
            axis=0).astype(np.int64)
        hot = hs.plan_hot_cold(host_sizes, ctx["row_bytes"], eff,
                               ctx["counts"])
        for s in self.topology.shards_of(host):
            index._fleet_tiers.pop(s, None)
        self._retier_host(index, host, hot)
        self._swap_resident(index)
        ctx["levels"][host] = int(level)
        try:
            from ..core import events as _events

            _events.record(
                "fleet_tier_step", f"fleet.host{host}", host=host,
                level_from=int(old_level), level_to=int(level),
                direction="down" if level > old_level else "up",
                reason=reason, store=ctx["store"],
                budget_bytes=budget, effective_budget_bytes=int(eff),
                cold_lists=int((~ctx["hot"][host]).sum()))
        except Exception:  # noqa: BLE001 - telemetry must not fail a step
            pass

    # -- search ------------------------------------------------------------
    def search(self, index, queries, k: int,
               params=None,
               allow_partial: bool = True, merge_engine=None, res=None):
        """Topology-aware merged search with degradation auto-widen.

        While ``served_frac < 1`` (a lost host), ``n_probes`` widens to
        ``n_probes / served_frac`` (capped at ``n_lists``) so the
        surviving shards probe proportionally more lists — recall under
        a host loss recovers most of the way to healthy instead of
        dropping by the dead fraction. Returns ``(d, i, shards_ok)``
        with the default ``allow_partial=True`` (a fleet exists to keep
        serving through a host loss), ``(d, i)`` when ``False``.

        Dispatches on the index family (a flat-rung build returns a
        ``ShardedIvfFlat``). When the build armed an HBM budget, the
        resident half above is merged with every live host's streamed
        cold lists (:meth:`_merge_cold`) — a DEAD host's cold lists are
        never streamed (its resident results are already masked; its
        host tier must degrade with it, not resurrect through the side
        door)."""
        fam = getattr(index, "family", "ivf_pq")
        if fam == "ivf_flat":
            sp = params or ivf_flat.SearchParams()
            n_lists = int(index.centers.shape[1])
            fn = sharded_ann.search_ivf_flat
        else:
            sp = params or ivf_pq.SearchParams()
            n_lists = int(index.centers_rot.shape[1])
            fn = sharded_ann.search_ivf_pq
        frac = sharded_ann.health(index)["served_frac"]
        eff = _effective_nprobe(sp.n_probes, frac, n_lists)
        if eff != sp.n_probes:
            sp = dataclasses.replace(sp, n_probes=eff)
        out = fn(index, queries, k, sp, res=res,
                 allow_partial=allow_partial, merge_engine=merge_engine)
        ctx = getattr(index, "_fleet_ctx", None)
        # collective-safe skip: every process computes the same
        # any-cold answer from the GLOBAL hot masks
        if ctx is None or not any((~np.asarray(m)).any()
                                  for m in ctx["hot"].values()):
            return out
        if allow_partial:
            d, i, ok = out
        else:
            d, i = out
            ok = np.asarray(index.shards_ok, bool)
        d, i = self._merge_cold(index, queries, k, sp, d, i, ok)
        return (d, i, ok) if allow_partial else (d, i)

    def _merge_cold(self, index, queries, k: int, sp, d, i, ok):
        """Stream every LIVE shard's probed cold lists and fold them
        into the resident merge (the host_stream pattern lifted
        fleet-wide). Single-process: plain ``knn_merge_parts`` over
        local parts. Multi-process: local parts fold to ONE ``(m, k)``
        block per process (sentinel block when a process has nothing
        cold to add), the blocks allgather over DCN, and one final merge
        lands the global answer — every process participates in the
        collective regardless of its local cold traffic."""
        from ..neighbors.brute_force import knn_merge_parts

        ctx = index._fleet_ctx
        mt = ctx["metric"]
        select_min = is_min_close(mt)
        q = jnp.asarray(queries, jnp.float32)
        n_probes = min(int(sp.n_probes), ctx["sizes_full"].shape[1])
        probed = self._coarse_probed(index, q, n_probes)
        okv = np.asarray(ok, bool)
        parts_d, parts_i = [], []
        for s in sorted(index._fleet_tiers):
            if not okv[s] or self.topology.host_of(s) in self._hosts_down:
                continue    # dead host: no cold resurrection (see search)
            tier = index._fleet_tiers[s]
            run = self._cold_runner(index, ctx, tier, q, k)
            for cd, ci_ in tier.stream(probed, run):
                parts_d.append(ivf_flat._postprocess(mt, cd))
                parts_i.append(ci_)
        # fold chunk results in PAIRWISE (arity-2) merges: how many
        # chunks a batch touches varies per batch AND per tier level,
        # and a stacked (1+n_parts, m, k) merge forks one executable
        # per arity — the fold keeps the cold merge on a single
        # compiled shape regardless. Equal-output: select_k is stable,
        # so a left fold preserves the multi-way merge's part-order tie
        # priority.
        if jax.process_count() == 1:
            for pd, pi in zip(parts_d, parts_i):
                d, i = knn_merge_parts(jnp.stack([d, pd]),
                                       jnp.stack([i, pi]), select_min)
            return d, i
        bad = jnp.inf if select_min else -jnp.inf
        ld = jnp.full((q.shape[0], k), bad, jnp.float32)
        li = jnp.full((q.shape[0], k), -1, jnp.int32)
        for pd, pi in zip(parts_d, parts_i):
            ld, li = knn_merge_parts(jnp.stack([ld, pd]),
                                     jnp.stack([li, pi]), select_min)
        from jax.experimental import multihost_utils

        gd = jnp.asarray(multihost_utils.process_allgather(ld))
        gi = jnp.asarray(multihost_utils.process_allgather(li))
        return knn_merge_parts(
            jnp.concatenate([d[None], gd.reshape(-1, *ld.shape)]),
            jnp.concatenate([i[None], gi.reshape(-1, *li.shape)]),
            select_min)

    def _coarse_probed(self, index, q, n_probes: int) -> np.ndarray:
        """Probed list ids for the cold half — the SAME probe arguments
        as the resident executables (shared quantizer, shared center
        norms), so hot and cold scan the same lists and exact rungs stay
        bitwise equal to the unbudgeted build."""
        from ..ops.ivf_scan import coarse_probe

        ctx = index._fleet_ctx
        mt = ctx["metric"]
        # the quantizer arrays are host copies in ctx: device_put them
        # ONCE per index (dispatch_cache), not per search call — the
        # cold merge runs on the serving path
        cache = dispatch_cache.cache_of(index)
        if ctx["store"] == "pq":
            dev = cache.get("cold:probe")
            if dev is None:
                dev = (jnp.asarray(ctx["rotation"]).T,
                       jnp.asarray(ctx["centers_rot"]))
                cache["cold:probe"] = dev
            q_rot = hdot(q, dev[0])
            return np.asarray(coarse_probe(
                q_rot, dev[1], n_probes,
                metric="ip" if mt is DistanceType.InnerProduct else "l2"))
        cmetric = ("ip" if mt is DistanceType.InnerProduct
                   else "cos" if mt is DistanceType.CosineExpanded
                   else "l2")
        dev = cache.get("cold:probe")
        if dev is None:
            dev = (jnp.asarray(ctx["centers"]), jnp.asarray(ctx["cnorms"]))
            cache["cold:probe"] = dev
        return np.asarray(coarse_probe(
            q, dev[0], n_probes, metric=cmetric, center_norms=dev[1]))

    def _cold_runner(self, index, ctx, tier, q, k: int):
        """One chunk-scan closure for :meth:`HostTier.stream`: the XLA
        cold scorers from the single-host tiers, fed through a shim
        carrying only the fields they read (the fleet's stacked index
        has no single-shard attribute layout to hand them). The heavy
        codebook/rotation device transfers are cached per index
        (dispatch_cache) — only the thin shim is rebuilt per call; the
        cold scorers themselves are eager jnp programs whose primitives
        hit XLA's global executable cache (0 steady-state compiles)."""
        mt = ctx["metric"]
        if ctx["store"] == "pq":
            cache = dispatch_cache.cache_of(index)
            heavy = cache.get("cold:pq")
            if heavy is None:
                heavy = (jnp.asarray(ctx["books"]),
                         jnp.asarray(ctx["rotation"]))
                cache["cold:pq"] = heavy
            shim = types.SimpleNamespace(
                pq_dim=int(ctx["pq_dim"]),
                codebooks=heavy[0], rotation=heavy[1],
                metric=mt, _host_tier=tier)
            return lambda ci, dev, pl: ivf_pq._cold_chunk_xla_pq(
                shim, dev, pl, q, k, None)
        args = ivf_flat._ColdScanArgs(
            k=k, lmax=tier.lmax, metric="l2", precision="highest",
            int4_dim=(ctx["dim"] if ctx["store"] == "int4" else None))
        shim = types.SimpleNamespace(dim=int(ctx["dim"]), metric=mt)
        return lambda ci, dev, pl: ivf_flat._cold_chunk_xla_flat(
            shim, dev, pl, q, args, None)

    def warmup_searchers(self, index, params=None, **opts) -> dict:
        """``{rung_name: closure}`` mapping for
        :func:`raft_tpu.serve.warmup.warmup`'s ``engines=`` sweep: the
        base params plus one closure per host-loss auto-widen rung
        (:func:`sharded_ann.widen_rungs`), each dispatched through
        :meth:`search` so a budgeted index's cold-list merge warms
        together with the resident executables. At full health
        ``search`` leaves an explicit ``n_probes`` untouched
        (``_effective_nprobe`` with ``served_frac=1`` is the identity),
        so every rung compiles under EXACTLY the cache key the degraded
        path will later hit — ``mark_host_failed`` → widened search
        lands on a warmed bucket with zero compiles."""
        fam = getattr(index, "family", "ivf_pq")
        if fam == "ivf_flat":
            sp = params or ivf_flat.SearchParams()
            n_lists = int(index.centers.shape[1])
        else:
            sp = params or ivf_pq.SearchParams()
            n_lists = int(index.centers_rot.shape[1])
        base_np = min(int(sp.n_probes), n_lists)
        engs = {"base": lambda q, kk, _sp=sp: self.search(
            index, q, kk, params=_sp, **opts)}
        for eff in sharded_ann.widen_rungs(index, sp.n_probes):
            if eff == base_np:
                continue               # already covered by "base"
            spr = dataclasses.replace(sp, n_probes=eff)
            engs[f"np{eff}"] = lambda q, kk, _sp=spr: self.search(
                index, q, kk, params=_sp, **opts)
        return engs

    # -- per-host memory accounting ---------------------------------------
    def host_memz(self) -> list:
        """Per-HOST memory decomposition over every registered index:
        the stacked device arrays split evenly across shards (stacked
        layouts are uniform by construction) and summed per host, plus
        each host's tier bytes parked in host RAM. This is the
        measurement :class:`FleetTierController` compares against the
        budget — in a real multi-process fleet each process sees its own
        hosts' tier bytes only (tiers are process-local by design)."""
        from ..serve import quality

        topo = self.topology
        hosts = [{"host": h, "indexes": 0, "device_bytes": 0,
                  "host_tier_bytes": 0, "rows": 0}
                 for h in range(self.n_hosts)]
        for idx in list(self._indexes):
            try:
                rep = quality.device_bytes(idx)
            except TypeError:       # a family memz can't decompose yet
                continue
            per_host = (int(rep["total_device_bytes"]) // self.n_shards
                        * topo.devs_per_host)
            n = int(getattr(idx, "n_total", 0) or 0)
            for e in hosts:
                e["indexes"] += 1
                e["device_bytes"] += per_host
                e["rows"] += n // self.n_hosts
            for s, tier in getattr(idx, "_fleet_tiers", {}).items():
                hosts[topo.host_of(s)]["host_tier_bytes"] += int(
                    tier.host_bytes)
        for e in hosts:
            e["bytes_per_vector"] = (round(e["device_bytes"] / e["rows"], 2)
                                     if e["rows"] else 0.0)
        return hosts


class FleetTierController:
    """Budget brownout, per host (the MEMORY degrade axis of ROADMAP
    item 3): one :class:`~raft_tpu.serve.degrade.BrownoutController`
    state machine per host walks a ladder of HALVING effective budgets.
    A host measured over its HBM budget (:meth:`Fleet.host_memz`, or
    injected measurements in tests/drills) steps DOWN — its resident set
    re-planned at ``budget / 2**level``, more lists streamed — instead
    of OOMing; sustained headroom steps it back up. Every transition
    re-packs into the index's existing compiled shapes
    (:meth:`Fleet._swap_resident`): zero recompiles, zero stranded
    futures, one ``fleet_tier_step`` event.

    Levels are budget halvings, not search-param overrides, so the
    brownout ladder is constructed as empty dicts — the controller
    reuses ONLY the verdict/hysteresis state machine (dwell,
    sustained-green recovery, urgent memory step)."""

    def __init__(self, fleet: Fleet, index, *, levels: int = 3,
                 min_dwell_s: float = 0.0, up_after_s: float = 30.0,
                 clock=time.monotonic):
        from ..serve.degrade import BrownoutController

        ctx = getattr(index, "_fleet_ctx", None)
        expects(ctx is not None,
                "index has no armed HBM budget (build with hbm_budget_gb "
                "or RAFT_TPU_HBM_BUDGET_GB)")
        self.fleet = fleet
        self.index = index
        self.budget_bytes = int(ctx["budget_bytes"])
        self._ctls = [
            BrownoutController([{} for _ in range(int(levels))],
                               min_dwell_s=min_dwell_s,
                               up_after_s=up_after_s,
                               name=f"fleet.host{h}.tier", clock=clock)
            for h in range(fleet.n_hosts)]

    def observe(self, host_bytes: Optional[dict] = None) -> dict:
        """Feed one per-host measurement (``{host: device_bytes}``;
        default: live :meth:`Fleet.host_memz`) through each host's state
        machine and apply any tier step it decides. Returns
        ``{host: {level, measured_bytes, verdict}}``."""
        if host_bytes is None:
            host_bytes = {e["host"]: e["device_bytes"]
                          for e in self.fleet.host_memz()}
        out = {}
        for h, ctl in enumerate(self._ctls):
            b = int(host_bytes.get(h, 0))
            v = "breach" if b > self.budget_bytes else "ok"
            old = ctl.level
            lv = ctl.on_report(
                {"targets": {"device_bytes": {"verdict": v}}})
            if lv != old:
                self.fleet._apply_tier_level(
                    self.index, h, lv, old,
                    reason="memory" if lv > old else "headroom")
            out[h] = {"level": lv, "measured_bytes": b, "verdict": v}
        return out

    def snapshot(self) -> dict:
        """Strict-JSON controller state for debugz/bench artifacts."""
        return {"budget_bytes": self.budget_bytes,
                "hosts": [ctl.snapshot() for ctl in self._ctls]}


def ops_snapshot() -> dict:
    """The fleet ops surface (read by serve/debugz.py): per-fleet
    topology, per-host health, served_frac, the merge plan a search
    resolves, and the last probe result."""
    fleets = []
    for _ in range(4):
        try:
            live = list(_FLEETS)
            break
        except RuntimeError:       # registration race (see sharded_ann)
            continue
    else:
        live = []
    for f in live:
        ent = f.host_health()
        ent["n_indexes"] = len(list(f._indexes))
        ent["merge"] = {
            "engine": "hier" if f.topology.multi_host else "flat",
            "dcn_reduction": f.topology.devs_per_host
            if f.topology.multi_host else 1}
        ent["last_probe"] = f.last_probe
        ent["hosts"] = f.host_memz()
        fleets.append(ent)
    return {"fleets": fleets, "n_fleets": len(fleets)}
