"""The Fleet: topology-aware MNMG composition over ICI + DCN.

ROADMAP item 1 ("build a sharded IVF-PQ index on DEEP-1B across
v5p-32") needs three things no single-mesh module provides: a build
protocol where every host's data shapes one shared coarse quantizer
without the corpus ever crossing DCN, a search merge that respects the
ICI/DCN bandwidth cliff, and host-granular failure semantics. This
module composes the existing single-mesh machinery into exactly that:

* **Fleet** owns a host-major mesh plus its
  :class:`~raft_tpu.parallel.topology.Topology`, built three ways:
  :meth:`Fleet.local` (one host, today's meshes), :meth:`Fleet.virtual`
  (CPU-emulation: one process's virtual devices reshaped hosts × devs —
  every cross-host code path runs in tier-1), and
  :meth:`Fleet.distributed` (real ``jax.distributed`` processes via
  :func:`raft_tpu.comms.init_distributed`).

* **Distributed IVF-PQ build** (:meth:`Fleet.build_ivf_pq`): ONE coarse
  quantizer trained data-parallel — each shard contributes its own
  sample's centroid accumulators, allreduced across the fleet per Lloyd
  iteration — so every host's rows shape the same list structure
  (the single-mesh ``build_ivf_pq`` instead trains p independent
  quantizers, one per shard). What crosses DCN per iteration is
  ``n_lists × (dim + 1)`` floats of accumulator, never rows; list
  packing (assign/encode/sort) runs host-local on each host's own row
  block, and the packed device arrays are assembled from process-local
  slabs. PQ codebooks are trained once and broadcast. The allreduce is
  an allgather + LOCAL ordered sum, so the trained index is
  BIT-IDENTICAL no matter how the same topology is laid out over
  processes — a 2-process 2×2 fleet builds the same index as a
  1-process virtual 2×2 fleet (the dryrun's acceptance gate).

* **Search** (:meth:`Fleet.search`) routes through the existing
  ``sharded_ann.search_ivf_pq`` — the index carries its topology, so
  the merge chokepoint resolves the hierarchical ICI/DCN engine — and
  auto-widens ``n_probes`` by ``1/served_frac`` while shards are down
  (the ROADMAP "re-probe at a bigger radius" contract: losing 1/H of
  the corpus costs ~1/H recall; probing proportionally more lists on
  the survivors buys most of it back).

* **Host-loss degradation**: :meth:`mark_host_failed` masks a whole
  host's shards (sentinel rows in whichever merge engine runs,
  ``host_lost`` flight-recorder event), :meth:`probe_hosts` canaries
  the dead shards and emits ``host_restored`` when a host's full ICI
  clique is healthy again. Per-host health is one
  :meth:`host_health` call and a ``fleet`` debugz section.
"""
from __future__ import annotations

import dataclasses
import time
import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..comms import AxisComms
from ..core.errors import expects
from ..distance.distance_types import canonical_metric
from ..neighbors import ivf_pq
from ..utils import cdiv, hdot, shard_map_compat
from . import sharded_ann
from .sharded_ann import ShardedIvfPq
from .topology import AXIS, Topology, detect, fleet_mesh, plan_merge, virtual

__all__ = ["Fleet", "FleetBuildParams", "ops_snapshot"]

# live fleets (weak — dropping a fleet must not leak it through debugz)
_FLEETS = weakref.WeakSet()


@dataclasses.dataclass
class FleetBuildParams:
    """Knobs of the distributed coarse trainer (the fleet analog of
    :class:`raft_tpu.cluster.kmeans_balanced.BalancedKMeansParams` —
    the Lloyd/balancing structure mirrored into a pure-SPMD program).

    ``balancing_rounds`` re-seeds of starved lists (count below
    mean/``balancing_pessimism``) onto perturbed copies of the heaviest
    lists' centers, each followed by a share of the Lloyd iterations —
    deterministic (count-driven, no RNG), so process layout can't change
    the result."""

    balancing_rounds: int = 2
    balancing_pessimism: float = 2.5


def _effective_nprobe(n_probes: int, served_frac: float, n_lists: int) -> int:
    """The degradation auto-widen: probe ``n_probes / served_frac``
    lists while part of the corpus is dark, capped at ``n_lists``. At
    full health this is exactly ``n_probes`` — the healthy path is
    untouched."""
    frac = min(max(float(served_frac), 1.0 / max(n_lists, 1)), 1.0)
    return min(int(n_lists), int(np.ceil(n_probes / frac)))


def _host_slab(topo: Topology, host: int):
    """Leading-axis slice of a (p, ...) stacked array owned by ``host``."""
    s = topo.shards_of(host)
    return slice(s.start, s.stop)


def _fleet_put(mesh: Mesh, topo: Topology, global_np: np.ndarray, spec):
    """Assemble a (p, ...)-stacked P(AXIS, ...) fleet array.

    Single-process: a plain sharded ``device_put``. Multi-process: each
    process provides ONLY its own host's shard slab
    (``jax.make_array_from_process_local_data``) — this is the seam
    that keeps packed codes off DCN: every byte of a shard's lists is
    produced and device_put by the process that owns the shard."""
    sh = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(jnp.asarray(global_np), sh)
    local = np.ascontiguousarray(global_np[_host_slab(topo,
                                                      jax.process_index())])
    return jax.make_array_from_process_local_data(sh, local,
                                                  global_np.shape)


class Fleet:
    """A host-major mesh + topology and the MNMG operations over it
    (module docstring). Construct via :meth:`local`, :meth:`virtual`,
    or :meth:`distributed`."""

    def __init__(self, mesh: Mesh, topology: Topology):
        expects(AXIS in mesh.shape, "fleet mesh must have a %r axis", AXIS)
        expects(mesh.shape[AXIS] == topology.n_shards,
                "mesh has %d shards, topology %dx%d wants %d",
                mesh.shape[AXIS], topology.n_hosts, topology.devs_per_host,
                topology.n_shards)
        self.mesh = mesh
        self.topology = topology
        # indexes built by (or adopted into) this fleet — host-loss and
        # probe operations apply to all of them
        self._indexes = weakref.WeakSet()
        # hosts currently considered lost (mark_host_failed ⇄ probe_hosts)
        self._hosts_down: set = set()
        self.last_probe: Optional[dict] = None
        _FLEETS.add(self)

    # -- construction ------------------------------------------------------
    @classmethod
    def local(cls, n_devices: Optional[int] = None) -> "Fleet":
        """Single-host fleet over the local devices (today's meshes:
        ``Topology(1, n)`` — resolve_engine keeps the flat engines
        byte-for-byte)."""
        devs = jax.devices()
        n = len(devs) if n_devices is None else int(n_devices)
        mesh, topo = fleet_mesh(Topology(1, n), devices=devs[:n])
        return cls(mesh, topo)

    @classmethod
    def virtual(cls, n_hosts: int, devs_per_host: int) -> "Fleet":
        """CPU-emulation fleet: one process's (virtual) devices reshaped
        ``hosts × devs`` so the cross-host paths run without a pod."""
        mesh, topo = fleet_mesh(virtual(n_hosts, devs_per_host))
        return cls(mesh, topo)

    @classmethod
    def distributed(cls, coordinator_address: Optional[str] = None,
                    num_processes: Optional[int] = None,
                    process_id: Optional[int] = None) -> "Fleet":
        """Real multi-process fleet: bootstrap ``jax.distributed``
        (:func:`raft_tpu.comms.init_distributed` — args or the
        ``RAFT_TPU_COORDINATOR``/``_NUM_PROCESSES``/``_PROCESS_ID``
        env), then detect the topology from the global device set."""
        from ..comms import init_distributed

        init_distributed(coordinator_address, num_processes, process_id)
        mesh, topo = fleet_mesh(None)
        return cls(mesh, topo)

    # -- introspection -----------------------------------------------------
    @property
    def n_hosts(self) -> int:
        return self.topology.n_hosts

    @property
    def n_shards(self) -> int:
        return self.topology.n_shards

    def merge_plan(self, m: int = 128, k: int = 10) -> dict:
        """The search-merge wire math for this topology
        (:func:`raft_tpu.parallel.topology.plan_merge`)."""
        return plan_merge(self.topology, m, k)

    def host_health(self) -> dict:
        """Per-HOST view of the fleet's index health: for each host,
        whether every shard of every registered index is ok; plus the
        worst ``served_frac`` across indexes (the number the auto-widen
        uses). Healthy-with-no-indexes reads as all-up."""
        per_host = [True] * self.n_hosts
        served = 1.0
        for idx in list(self._indexes):
            ok = np.asarray(idx.shards_ok, bool)
            for h in range(self.n_hosts):
                if not ok[_host_slab(self.topology, h)].all():
                    per_host[h] = False
            served = min(served, sharded_ann.health(idx)["served_frac"])
        return {"topology": f"{self.n_hosts}x{self.topology.devs_per_host}",
                "hosts_ok": per_host,
                "hosts_down": sorted(self._hosts_down),
                "served_frac": round(served, 4)}

    # -- host-loss degradation --------------------------------------------
    def mark_host_failed(self, host: int, ok: bool = False) -> None:
        """Mark every shard of ``host`` across every registered index
        (host-granular ``shards_ok``): its ICI clique contributes
        sentinel rows to every merge until re-marked or re-probed.
        ``ok=True`` is the manual re-admit."""
        expects(0 <= host < self.n_hosts, "host %d out of range", host)
        for idx in list(self._indexes):
            for s in self.topology.shards_of(host):
                idx.mark_shard_failed(s, ok=ok)
        was_down = host in self._hosts_down
        if ok:
            self._hosts_down.discard(host)
        else:
            self._hosts_down.add(host)
        if ok == was_down:      # an actual host-level transition
            try:
                from ..core import events as _events

                _events.record("host_restored" if ok else "host_lost",
                               f"fleet.host{host}",
                               shards=list(self.topology.shards_of(host)),
                               **self.host_health())
            except Exception:  # noqa: BLE001 - telemetry must not fail ops
                pass

    def probe_hosts(self, **kw) -> dict:
        """Canary-probe dead shards of every registered index
        (:func:`raft_tpu.parallel.sharded_ann.probe_shards`) and
        re-admit hosts whose whole ICI clique recovered — the
        host-granular ``shard_restored`` loop, emitting one
        ``host_restored`` per recovered host. Returns
        ``{"shards": {family: {shard: ok}}, "hosts_restored": [...]}``."""
        shard_results: dict = {}
        for idx in list(self._indexes):
            if not np.asarray(idx.shards_ok, bool).all():
                shard_results.setdefault(idx.family, {}).update(
                    sharded_ann.probe_shards(idx, **kw))
        restored = []
        for h in sorted(self._hosts_down):
            up = all(np.asarray(idx.shards_ok,
                                bool)[_host_slab(self.topology, h)].all()
                     for idx in list(self._indexes))
            if up:
                self._hosts_down.discard(h)
                restored.append(h)
                try:
                    from ..core import events as _events

                    _events.record("host_restored", f"fleet.host{h}",
                                   shards=list(self.topology.shards_of(h)),
                                   **self.host_health())
                except Exception:  # noqa: BLE001
                    pass
        self.last_probe = {"ts": time.time(), "shards": shard_results,
                           "hosts_restored": restored}
        return {"shards": shard_results, "hosts_restored": restored}

    def adopt(self, index) -> None:
        """Register an externally built sharded index (its mesh must be
        this fleet's) for host-loss/probe management."""
        expects(getattr(index, "mesh", None) is self.mesh,
                "index was not built on this fleet's mesh")
        index.topology = self.topology
        self._indexes.add(index)

    # -- distributed build -------------------------------------------------
    def build_ivf_pq(self, dataset,
                     params: ivf_pq.IndexParams | None = None,
                     build_params: FleetBuildParams | None = None
                     ) -> ShardedIvfPq:
        """Distributed IVF-PQ build (module docstring): one allreduced
        coarse quantizer, broadcast codebooks, host-local list packing.

        ``dataset``: the (n, dim) corpus, visible to every process (from
        shared storage — NOT shipped over DCN; each process touches only
        its own hosts' row blocks for packing, and only the training
        sample feeds the allreduce). Returns a
        :class:`~raft_tpu.parallel.sharded_ann.ShardedIvfPq` whose
        searches resolve the topology-aware merge. PER_SUBSPACE
        codebooks only (PER_CLUSTER's trainer is host-driven and cannot
        run SPMD)."""
        p0 = params or ivf_pq.IndexParams()
        bp = build_params or FleetBuildParams()
        expects(p0.codebook_kind is ivf_pq.CodebookGen.PER_SUBSPACE,
                "fleet build supports PER_SUBSPACE codebooks only")
        mt = canonical_metric(p0.metric)
        dataset = np.asarray(dataset, np.float32)
        n, dim = dataset.shape
        p = self.n_shards
        L = p0.n_lists
        expects(4 <= p0.pq_bits <= 8, "pq_bits must be in [4,8], got %d",
                p0.pq_bits)
        expects(L <= n, "n_lists %d > n %d", L, n)
        pq_dim = p0.pq_dim or ivf_pq._default_pq_dim(dim)
        pq_len = cdiv(dim, pq_dim)
        rot_dim = pq_dim * pq_len
        book_size = 1 << p0.pq_bits
        t0 = time.perf_counter()

        parts = sharded_ann._split_rows(n, p)
        # equal per-shard sample size: every shard must contribute the
        # same accumulator shapes, and the trainer needs enough rows per
        # shard to seed its slice of the centers and fill a codebook
        n_train = max(L, min(n, int(n * p0.kmeans_trainset_fraction)))
        t = max(book_size, cdiv(L, p), cdiv(n_train, p))
        samples = np.empty((p, t, dim), np.float32)
        for s in range(p):
            block = dataset[parts[s]]
            stride = max(1, len(block) // t)
            samples[s] = block[(np.arange(t) * stride) % len(block)]

        key = jax.random.key(p0.seed)
        k_rot, k_book = jax.random.split(key)
        rotation = np.asarray(ivf_pq.make_rotation_matrix(
            k_rot, rot_dim, dim, p0.force_random_rotation))

        centers_rot, books = self._train(samples, rotation, L, pq_dim,
                                         pq_len, book_size, p0, bp, k_book)

        index = self._pack(dataset, parts, centers_rot, books, rotation,
                           mt, p0, pq_dim)
        self.adopt(index)
        try:
            from ..core import events as _events

            _events.record(
                "fleet_build", "fleet.build_ivf_pq",
                topology=f"{self.n_hosts}x{self.topology.devs_per_host}",
                n=n, dim=dim, n_lists=L, pq_dim=pq_dim, pq_bits=p0.pq_bits,
                sample_rows_per_shard=t,
                wall_s=round(time.perf_counter() - t0, 3))
        except Exception:  # noqa: BLE001
            pass
        return index

    def _train(self, samples: np.ndarray, rotation: np.ndarray, L: int,
               pq_dim: int, pq_len: int, book_size: int, p0, bp, k_book):
        """The SPMD trainer: one shard_map program over the fleet mesh.

        Determinism contract: the cross-fleet allreduce is an allgather
        (pure data movement, axis-ordered) + a LOCAL ``jnp.sum`` over
        the gathered axis — the reduction order is fixed by the program,
        not the wire, so 1-process virtual and N-process real layouts of
        the same topology produce bitwise-equal centers. ``psum`` would
        be the hardware-efficient choice on a pod, at the cost of this
        guarantee. Codebooks are shard 0's, broadcast (masked psum:
        ``x + 0`` — exact)."""
        p = self.n_shards
        t, dim = samples.shape[1:]
        iters = max(1, int(p0.kmeans_n_iters))
        rounds = max(0, int(bp.balancing_rounds))
        per = max(1, iters // (rounds + 1))
        ell = cdiv(L, p)
        stride = max(1, t // ell)

        def body(smp, rot):
            x = smp[0]                                   # (t, dim) local
            comms = AxisComms(AXIS, size=p)

            def allreduce_sum(v):
                # ordered: gather in axis order, reduce locally
                return jnp.sum(comms.allgather(v), axis=0)

            def lloyd(carry, n_it):
                def step(c, _):
                    d2 = (jnp.sum(x * x, axis=1, keepdims=True)
                          - 2.0 * hdot(x, c.T)
                          + jnp.sum(c * c, axis=1)[None, :])
                    lb = jnp.argmin(d2, axis=1)
                    sums = allreduce_sum(
                        jax.ops.segment_sum(x, lb, num_segments=L))
                    cnt = allreduce_sum(jax.ops.segment_sum(
                        jnp.ones((t,), jnp.float32), lb, num_segments=L))
                    new = jnp.where(cnt[:, None] > 0,
                                    sums / jnp.maximum(cnt, 1.0)[:, None], c)
                    return new, cnt
                c, cnts = jax.lax.scan(step, carry, None, length=n_it)
                return c, cnts[-1]

            # init: every shard seeds ceil(L/p) strided sample rows;
            # gathered in shard order, first L rows are the shared seed
            init = x[::stride][:ell]
            centers = comms.allgather(init).reshape(p * ell, dim)[:L]
            centers, cnt = lloyd(centers, per)
            for _ in range(rounds):
                # deterministic balancing: starved lists re-seed onto
                # perturbed copies of the heaviest lists' centers
                order = jnp.argsort(-cnt)
                starved = cnt < (jnp.mean(cnt) / bp.balancing_pessimism)
                rank = jnp.cumsum(starved.astype(jnp.int32)) - 1
                donor = centers[order[jnp.mod(rank, L)]]
                eps = 1e-4 * (1.0 + jnp.arange(L, dtype=jnp.float32)
                              )[:, None]
                centers = jnp.where(starved[:, None], donor * (1.0 + eps),
                                    centers)
                centers, cnt = lloyd(centers, per)

            c_rot = hdot(centers, rot.T)
            # codebooks: every shard trains on its OWN sample's residuals
            # (replicated compute), shard 0's result is broadcast — "one
            # trainer", SPMD-uniform
            x_rot = hdot(x, rot.T)
            d2 = (jnp.sum(x_rot * x_rot, axis=1, keepdims=True)
                  - 2.0 * hdot(x_rot, c_rot.T)
                  + jnp.sum(c_rot * c_rot, axis=1)[None, :])
            resid = x_rot - c_rot[jnp.argmin(d2, axis=1)]
            slices = jnp.transpose(resid.reshape(t, pq_dim, pq_len),
                                   (1, 0, 2))
            books = ivf_pq._train_per_subspace(slices, book_size, iters,
                                               k_book)
            return c_rot, comms.bcast(books, root=0)

        prog = jax.jit(shard_map_compat(
            body, mesh=self.mesh, in_specs=(P(AXIS, None, None), P()),
            out_specs=(P(), P()), check=False))
        smp = _fleet_put(self.mesh, self.topology, samples,
                         P(AXIS, None, None))
        c_rot, books = prog(smp, jnp.asarray(rotation))
        return np.asarray(c_rot), np.asarray(books)

    def _pack(self, dataset, parts, centers_rot, books, rotation, mt, p0,
              pq_dim) -> ShardedIvfPq:
        """Host-local list packing: each process assigns/encodes/sorts
        ONLY its own hosts' row blocks against the replicated quantizer,
        then the (p, ...)-stacked device arrays are assembled from
        process-local slabs (:func:`_fleet_put`). The tiny per-shard
        list-size tables — the only cross-host metadata — travel via
        ``process_allgather``."""
        topo = self.topology
        p = self.n_shards
        L = centers_rot.shape[0]
        multi = jax.process_count() > 1
        my_shards = (list(topo.shards_of(jax.process_index())) if multi
                     else list(range(p)))
        R = max(len(part) for part in parts)          # common padded rows

        c_rot_j = jnp.asarray(centers_rot)
        books_j = jnp.asarray(books)
        rot_j = jnp.asarray(rotation)

        @jax.jit
        def assign_encode(xb):
            xb_rot = hdot(xb, rot_j.T)
            d2 = (jnp.sum(xb_rot * xb_rot, axis=1, keepdims=True)
                  - 2.0 * hdot(xb_rot, c_rot_j.T)
                  + jnp.sum(c_rot_j * c_rot_j, axis=1)[None, :])
            lb = jnp.argmin(d2, axis=1)
            resid = xb_rot - c_rot_j[lb]
            return lb.astype(jnp.int32), ivf_pq._encode(resid, books_j, lb,
                                                        False)

        codes = np.zeros((p, R, pq_dim), np.uint8)
        gids = np.full((p, R), -1, np.int32)
        sizes = np.zeros((p, L), np.int32)
        for s in my_shards:
            rows = parts[s]
            lb, cd = assign_encode(jnp.asarray(dataset[rows], jnp.float32))
            lb, cd = np.asarray(lb), np.asarray(cd)
            order = np.argsort(lb, kind="stable")     # cluster-sorted lists
            codes[s, : len(rows)] = cd[order]
            gids[s, : len(rows)] = rows[order]        # GLOBAL row ids
            sizes[s] = np.bincount(lb, minlength=L)
        if multi:
            from jax.experimental import multihost_utils

            # every process's local (D, L) size block, host-major — the
            # only packing metadata that crosses DCN
            local = sizes[_host_slab(topo, jax.process_index())]
            sizes = np.asarray(multihost_utils.process_allgather(
                jnp.asarray(local))).reshape(p, L).astype(np.int32)
        offsets = np.concatenate(
            [np.zeros((p, 1), np.int64), np.cumsum(sizes, axis=1)[:, :-1]],
            axis=1).astype(np.int32)

        put = lambda a, spec: _fleet_put(self.mesh, topo, a, spec)
        stack = lambda a: np.broadcast_to(a, (p,) + a.shape).copy()
        idx = ShardedIvfPq(
            self.mesh,
            put(codes, P(AXIS, None, None)),
            put(gids, P(AXIS, None)),
            put(stack(centers_rot), P(AXIS, None, None)),
            put(stack(books), P(AXIS, None, None, None)),
            put(stack(rotation), P(AXIS, None, None)),
            put(offsets, P(AXIS, None)),
            put(sizes, P(AXIS, None)),
            len(dataset), mt, p0.pq_bits, p0.codebook_kind,
            [sizes[s] for s in range(p)])
        return idx

    # -- search ------------------------------------------------------------
    def search(self, index, queries, k: int,
               params: ivf_pq.SearchParams | None = None,
               allow_partial: bool = True, merge_engine=None, res=None):
        """Topology-aware merged search with degradation auto-widen.

        While ``served_frac < 1`` (a lost host), ``n_probes`` widens to
        ``n_probes / served_frac`` (capped at ``n_lists``) so the
        surviving shards probe proportionally more lists — recall under
        a host loss recovers most of the way to healthy instead of
        dropping by the dead fraction. Returns ``(d, i, shards_ok)``
        with the default ``allow_partial=True`` (a fleet exists to keep
        serving through a host loss), ``(d, i)`` when ``False``."""
        sp = params or ivf_pq.SearchParams()
        frac = sharded_ann.health(index)["served_frac"]
        n_lists = int(index.centers_rot.shape[1])
        eff = _effective_nprobe(sp.n_probes, frac, n_lists)
        if eff != sp.n_probes:
            sp = dataclasses.replace(sp, n_probes=eff)
        return sharded_ann.search_ivf_pq(
            index, queries, k, sp, res=res, allow_partial=allow_partial,
            merge_engine=merge_engine)


def ops_snapshot() -> dict:
    """The fleet ops surface (read by serve/debugz.py): per-fleet
    topology, per-host health, served_frac, the merge plan a search
    resolves, and the last probe result."""
    fleets = []
    for _ in range(4):
        try:
            live = list(_FLEETS)
            break
        except RuntimeError:       # registration race (see sharded_ann)
            continue
    else:
        live = []
    for f in live:
        ent = f.host_health()
        ent["n_indexes"] = len(list(f._indexes))
        ent["merge"] = {
            "engine": "hier" if f.topology.multi_host else "flat",
            "dcn_reduction": f.topology.devs_per_host
            if f.topology.multi_host else 1}
        ent["last_probe"] = f.last_probe
        fleets.append(ent)
    return {"fleets": fleets, "n_fleets": len(fleets)}
