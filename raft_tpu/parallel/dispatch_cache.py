"""Per-index compiled-program cache for sharded/fleet dispatch.

Every sharded family used to rebuild and re-trace its whole
``shard_map`` closure per search call — ~224 XLA programs *per call* on
an unbudgeted fleet index, the dispatch tax the r05 roofline blames for
``vs_baseline`` sitting at 0.08-0.11 (docs/perf.md "Sharded dispatch").
This module holds the mechanics that make sharded dispatch
trace-once/dispatch-many:

* :func:`cache_of` — the per-index ``{key: jitted shard_map program}``
  dict, lazily attached to the index object. The cache lives ON the
  index (not a module global) so dropping an index drops its
  executables, and two indexes with identical statics never share a
  program that closes over different comms/topology objects.
* :func:`program_key` — the ``shape_bucket``-style string key: family,
  resolved merge engine, mesh platform/device-kind tag, topology tag,
  comms fingerprint, then the family's closure-baked statics
  (``n_probes``, ``max_rows``, metric, filter arity, ...). The query
  count ``m`` is deliberately EXCLUDED: ``jax.jit`` keys executables by
  argument shape, so one cached wrapper serves every batch bucket —
  only values baked into the trace belong in the Python-level key.
* :func:`enabled` — ``RAFT_TPU_SHARDED_DISPATCH=uncached`` restores
  per-call dispatch: a FRESH jit wrapper per search, so every call
  re-traces and re-compiles the identical program. That is the bitwise
  comparison hook (same trace, same XLA program, same bits as the
  cached path) and the dryrun's before/after ``programs_per_call``
  measurement. It is deliberately NOT the historical eager
  ``shard_map`` dispatch: eager op-by-op execution and the fused jit
  program may differ in float low bits (FMA contraction), which would
  make bitwise pins vacuous.
* :func:`dispatch_label` — wraps a sharded dispatch in the serve
  recompile-watch's :func:`~raft_tpu.serve.warmup.compile_context`
  label (``sharded.<family>:<m>x<k>``), so a post-warmup sharded
  recompile lands in ``serve.recompiles`` + the ``xla_compile`` ring
  exactly like a batcher-path recompile. An enclosing warmup context
  is respected: the warmup sweep's first compiles stay exempt.
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["enabled", "cache_of", "program_key", "comms_tag", "mesh_tag",
           "topology_tag", "dispatch_label", "stats"]

_ENV = "RAFT_TPU_SHARDED_DISPATCH"
_ATTR = "_dispatch_cache"


def enabled() -> bool:
    """False when ``RAFT_TPU_SHARDED_DISPATCH=uncached`` pins per-call
    re-trace/re-compile dispatch (bitwise-comparison/measurement
    hook; module docstring)."""
    return os.environ.get(_ENV, "").lower() != "uncached"


def cache_of(index) -> dict:
    """The index's program cache, created on first use. Index types
    that reject attribute writes get a throwaway dict (every call a
    miss — correct, just uncached)."""
    cache = getattr(index, _ATTR, None)
    if cache is None:
        cache = {}
        try:
            setattr(index, _ATTR, cache)
        except (AttributeError, TypeError):
            pass
    return cache


def mesh_tag(mesh) -> str:
    """Platform/device-kind/axis-extent tag (the ``shape_bucket`` mesh
    discipline of ``ops.ring_topk._bucket``)."""
    dev = mesh.devices.flat[0]
    kind = getattr(dev, "device_kind", dev.platform).replace(" ", "_")
    axes = "x".join(f"{n}{s}" for n, s in mesh.shape.items())
    return f"{dev.platform}-{kind}-{axes}"


def topology_tag(topology) -> str:
    """``<hosts>x<devs_per_host>`` for a fleet topology, ``flat``
    otherwise — the hier merge bakes the host grouping into its trace."""
    if topology is None:
        return "flat"
    return f"{int(topology.n_hosts)}x{int(topology.devs_per_host)}"


def comms_tag(comms) -> str:
    """Fingerprint of the communicator a merge closure bakes in: an
    AxisComms is fully determined by (type, axis, size, groups). A
    foreign comm type without those fields falls back to object
    identity — correctness over sharing."""
    if comms is None:
        return "none"
    name = type(comms).__name__
    axis = getattr(comms, "axis", None)
    size = getattr(comms, "_size", None)
    groups = getattr(comms, "groups", None)
    if axis is None and size is None and groups is None:
        return f"{name}@{id(comms):x}"
    return f"{name}/{axis}/{size}/{groups}"


def program_key(family: str, engine, mesh, topology, comms,
                statics) -> str:
    """One cache key per distinct compiled program: everything the
    closure bakes into its trace, and nothing jit already shape-keys."""
    parts = [family, str(engine), mesh_tag(mesh), topology_tag(topology),
             comms_tag(comms)]
    parts += [f"{n}={v}" for n, v in statics]
    return ":".join(parts)


@contextlib.contextmanager
def dispatch_label(family: str, m: int, k: int):
    """Label this dispatch for the serve recompile watch (module
    docstring). No-op inside a warmup sweep (the outer warmup context
    must keep its exemption) or when serve is unimportable."""
    try:
        from ..serve import warmup as _wu
    except Exception:  # noqa: BLE001 - telemetry must not fail a search
        yield
        return
    if getattr(_wu._ctx, "warmup", False):
        yield
        return
    with _wu.compile_context(f"sharded.{family}:{m}x{k}"):
        yield


def stats(index) -> dict:
    """Cache introspection (debugz/tests): program count + keys."""
    cache = getattr(index, _ATTR, None) or {}
    return {"programs": len(cache), "keys": sorted(map(str, cache))}
