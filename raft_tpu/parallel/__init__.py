"""Multi-chip (MNMG-analog) sharded algorithms over jax.sharding meshes."""
from . import sharded_knn

__all__ = ["sharded_knn"]
