"""Multi-chip (MNMG-analog) sharded algorithms over jax.sharding meshes."""
from . import sharded_ann, sharded_knn

__all__ = ["sharded_ann", "sharded_knn"]
