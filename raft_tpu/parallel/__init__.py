"""Multi-chip (MNMG-analog) sharded algorithms over jax.sharding meshes.

Single-mesh layers: :mod:`sharded_ann` / :mod:`sharded_knn` (per-shard
local search + cross-shard merge). The multi-host fleet layer composes
them across the ICI/DCN hierarchy: :mod:`topology` (hosts × devices
model + hierarchical merge planning) and :mod:`fleet` (distributed
IVF-PQ build, topology-aware search, host-loss degradation).
"""
from . import fleet, sharded_ann, sharded_knn, topology
from .fleet import Fleet
from .topology import Topology

__all__ = ["sharded_ann", "sharded_knn", "topology", "fleet", "Fleet",
           "Topology"]
