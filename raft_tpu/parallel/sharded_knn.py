"""Multi-chip sharded exact kNN: the MNMG brute-force analog.

Reference pattern (SURVEY.md §2.11.3): each rank holds an index shard,
queries are broadcast, each rank computes its local top-k, and the per-shard
results are merged (detail/knn_merge_parts.cuh:172, orchestrated by
raft-dask + cuML kneighbors).

TPU design: the dataset is sharded along a mesh axis with `jax.sharding`;
`jax.shard_map` runs the single-chip tiled search per shard, local indices
are rebased to global ids from the shard's axis index, and the (k)-sized
candidate lists merge across ICI (:mod:`raft_tpu.ops.ring_topk`:
allgather + ``knn_merge_parts``, or the bit-identical ring engines with
O(k) traffic per hop) — cross-chip traffic is candidate lists only,
never raw vectors. Results come back device-resident: nothing on this
path blocks on readiness, callers sync when they consume.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.errors import expects
from ..distance.distance_types import is_min_close
from ..neighbors import brute_force
from ..ops import ring_topk
from ..utils import cdiv, shard_map_compat
from . import dispatch_cache

__all__ = ["ShardedIndex", "build", "search", "dryrun"]

AXIS = "shard"


class ShardedIndex:
    """Brute-force index sharded over a 1-D mesh axis.

    The dataset is padded to a multiple of the axis size and placed with
    rows sharded; padding rows are masked out at search time by the
    per-shard row-count carried in ``shard_sizes``.
    """

    def __init__(self, mesh: Mesh, dataset_sharded: jax.Array, n_total: int,
                 metric, metric_arg: float = 2.0):
        self.mesh = mesh
        self.dataset = dataset_sharded  # (n_pad, d), sharded over AXIS
        self.n_total = n_total
        self.metric = metric
        self.metric_arg = metric_arg

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[AXIS]

    @property
    def shard_rows(self) -> int:
        return self.dataset.shape[0] // self.n_shards


def build(dataset, mesh: Mesh, metric="sqeuclidean", metric_arg: float = 2.0) -> ShardedIndex:
    """Distribute the dataset row-sharded over ``mesh`` axis "shard"."""
    expects(AXIS in mesh.shape, "mesh must have a %r axis", AXIS)
    n, d = dataset.shape
    p = mesh.shape[AXIS]
    shard_rows = cdiv(n, p)
    n_pad = shard_rows * p
    data = np.zeros((n_pad, d), np.float32)
    data[:n] = np.asarray(dataset, np.float32)
    sharding = NamedSharding(mesh, P(AXIS, None))
    dataset_sharded = jax.device_put(jnp.asarray(data), sharding)
    return ShardedIndex(mesh, dataset_sharded, n, metric, metric_arg)


def search(index: ShardedIndex, queries, k: int, tile_size: int = 8192,
           algo: str | None = None, merge_engine: str | None = None
           ) -> Tuple[jax.Array, jax.Array]:
    """Sharded search: per-shard top-k then cross-shard merge.

    Queries are replicated; the result is replicated (every chip holds
    the merged answer) and DEVICE-RESIDENT — this path never blocks on
    readiness; callers sync when they consume the arrays.

    ``merge_engine``: force one of ``ops.ring_topk.ENGINES`` (ring or
    allgather merge — bit-identical); default resolves via
    ``RAFT_TPU_SHARDED_MERGE`` / the autotune verdict / backend.

    The compiled ``shard_map`` program is cached on the index per
    (engine, k, tile, algo) bucket (:mod:`.dispatch_cache`): repeat
    calls at a warmed shape dispatch a cached executable instead of
    re-tracing the whole sharded program.
    """
    select_min = is_min_close(index.metric)
    shard_rows = index.shard_rows
    n_total = index.n_total
    p = index.n_shards
    metric, metric_arg = index.metric, index.metric_arg
    # the per-shard compute runs on the mesh's devices, not the default
    # backend: only use the fused Pallas path when the mesh is TPU
    if algo is None:
        mesh_platform = index.mesh.devices.flat[0].platform
        algo = "auto" if mesh_platform == "tpu" else "scan"
    q = jnp.asarray(queries, jnp.float32)
    eng = ring_topk.resolve_engine(q.shape[0], k, p, override=merge_engine,
                                   mesh=index.mesh)
    cache = dispatch_cache.cache_of(index)

    def prog(merge_eng):
        key = dispatch_cache.program_key(
            "knn", merge_eng, index.mesh, None, None,
            (("k", k), ("tile", int(tile_size)), ("algo", algo),
             ("mt", metric), ("ma", metric_arg), ("n", int(n_total))))
        fn = cache.get(key) if dispatch_cache.enabled() else None
        if fn is None:
            def local_search(data_shard, qq):
                rank = jax.lax.axis_index(AXIS)
                base = rank * shard_rows
                # local exact search on this shard's rows; padding rows
                # (only the tail shard has them) are masked inside the
                # tiled scan so they can never displace true candidates
                # from the local top-k
                n_valid_local = jnp.clip(n_total - base, 0, shard_rows)
                local = brute_force.build(data_shard, metric, metric_arg)
                dist, idx = brute_force.search(local, qq, k,
                                               tile_size=tile_size,
                                               valid_rows=n_valid_local,
                                               algo=algo)
                gidx = jnp.where(idx >= 0, idx + base, -1)
                bad = jnp.inf if select_min else -jnp.inf
                dist = jnp.where(gidx >= 0, dist, bad)
                # only candidate lists cross ICI; vectors never move
                return ring_topk.merge(dist, gidx, k, select_min,
                                       axis=AXIS, axis_size=p,
                                       engine=merge_eng)

            sm = shard_map_compat(
                local_search,
                mesh=index.mesh,
                in_specs=(P(AXIS, None), P()),
                out_specs=(P(), P()),
                check=False,
            )
            fn = jax.jit(sm)
            if dispatch_cache.enabled():
                cache[key] = fn
            # else: fresh wrapper per call — re-trace/re-compile the
            # identical (bitwise) program; the measurement baseline
        return fn

    def run(e):
        with dispatch_cache.dispatch_label("knn", int(q.shape[0]), k):
            return prog(e)(index.dataset, q)

    return ring_topk.guarded_dispatch("knn", eng, run)


def dryrun(n_devices: int, ring_check: bool = True) -> None:
    """Driver hook: build an n-device mesh on whatever devices exist and run
    one full sharded search step on tiny shapes, verifying against the
    single-chip answer. ``ring_check=False`` skips the ring-engine
    cross-check (a second full search compile, ~4 s on the CPU mesh):
    the driver artifact keeps it; tier-1 covers the same path in
    tests/test_ring_topk.py."""
    devices = jax.devices()
    if len(devices) < n_devices:
        # single real TPU chip under the driver: fall back to the virtual
        # CPU devices provided by --xla_force_host_platform_device_count
        devices = jax.devices("cpu")
    devices = devices[:n_devices]
    expects(len(devices) == n_devices,
            "need %d devices, have %d", n_devices, len(devices))
    mesh = Mesh(np.array(devices), (AXIS,))
    rng = np.random.default_rng(0)
    # >=10k rows per device: big enough that a cross-shard merge bug
    # (rank mixing, id rebasing, padding leaks) actually surfaces
    data = rng.standard_normal((10_000 * n_devices - 17, 64)
                               ).astype(np.float32)
    q = rng.standard_normal((32, 64)).astype(np.float32)
    index = build(data, mesh)
    # pin both sides to the scan engine: the check below is exact-equality
    # on indices, which different engines may break on fp ties. Results
    # stay device-resident (no block_until_ready on the search path —
    # the np.asarray reads below are the sync point).
    dist, idx = jax.jit(
        lambda qq: search(index, qq, k=5, tile_size=128, algo="scan"))(q)
    # verify against single-device exact search (scan path: the comparison
    # is exact-equality on indices, so both sides must use the same engine)
    local = brute_force.build(data)
    ref_d, ref_i = brute_force.search(local, q, 5, tile_size=512, algo="scan")
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref_i))
    ring_note = ""
    if ring_check:
        # the ring merge engine must be BIT-identical to the allgather
        # merge (order included) — the driver artifact carries the
        # cross-engine check at the same scale as the single-chip one
        dist_r, idx_r = search(index, q, k=5, tile_size=128, algo="scan",
                               merge_engine="ring")
        np.testing.assert_array_equal(np.asarray(idx_r), np.asarray(idx))
        np.testing.assert_array_equal(np.asarray(dist_r), np.asarray(dist))
        ring_note = "; ring merge bit-identical"
    # report the engine that actually SERVED (fallbacks included), not a
    # fresh resolution
    eng = ring_topk.active_engines.get("knn", "-")
    print(f"dryrun_multichip ok: sharded brute force over {n_devices} "
          f"devices x {len(data) // n_devices + 1} rows, merged top-5 "
          f"matches single-chip exactly{ring_note} [engine={eng}]")
