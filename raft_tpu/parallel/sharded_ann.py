"""Multi-chip sharded ANN indexes (IVF-Flat and CAGRA): the MNMG analog for
approximate search.

Reference pattern (SURVEY.md §2.11.3, BASELINE north star "sharded IVF-PQ
DEEP-1B build on v5p-32"): each rank builds an index over its own rows;
queries are replicated; each rank searches locally and per-shard top-k
lists are merged (detail/knn_merge_parts.cuh:172). raft-dask bootstraps
this per worker; here one process drives the whole mesh.

TPU design: per-shard index arrays are **stacked along a leading axis and
sharded over the mesh** with `jax.sharding` (shape (p, ...) with spec
P(AXIS, ...)); the single-chip pure-array search cores
(ivf_flat.search_arrays, cagra._search_jit internals) run inside one
`shard_map`, then the per-shard (k)-wide result lists merge across ICI —
vectors never move between chips. Shard row counts are padded to a
common size; source ids carry GLOBAL row numbers so the merge is
trivial.

Shard health closes its own loop: ``mark_shard_failed`` masks a shard
out of every merge, and :func:`probe_shards` (periodic via
``SnapshotWriter(hooks=[probe_all])``) canary-probes dead shards and
flips ``shards_ok`` back once the fault clears — ``served_frac``
recovers without an operator (docs/robustness.md "Shard re-probe").

The cross-shard merge dispatches through :mod:`raft_tpu.ops.ring_topk`:
either the reference allgather + ``knn_merge_parts`` path or a ring
merge (``ppermute`` hops in XLA, or the Pallas ``make_async_remote_copy``
kernel on TPU) that keeps candidates device-resident with O(k) ICI
traffic per hop. All engines are bit-identical (order included), so the
ring engines are gated behind ``guarded_call("sharded.ring_topk")`` with
the allgather path as containment. Dead shards contribute (±inf, −1)
sentinel rows inside whichever engine runs, so the ``allow_partial``
degraded-merge contract survives unchanged.
"""
from __future__ import annotations

import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..comms import AxisComms
from ..core import faults
from ..core.errors import ShardsDownError, expects
from ..distance.distance_types import DistanceType, canonical_metric, is_min_close
from ..neighbors import cagra, ivf_flat, ivf_pq
from ..ops import ring_topk
from ..utils import cdiv, shard_map_compat
from . import dispatch_cache

__all__ = ["ShardedIvfFlat", "build_ivf_flat", "search_ivf_flat",
           "ShardedCagra", "build_cagra", "search_cagra",
           "ShardedIvfPq", "build_ivf_pq", "search_ivf_pq",
           "make_searcher", "warmup_searchers", "widen_rungs",
           "searcher_dim", "ops_snapshot", "health",
           "probe_shards", "probe_all"]

AXIS = "shard"

# guarded site for the ring merge engines (ops/guarded.py): a ring
# compile/execution failure demotes this process to the bit-identical
# allgather merge
MERGE_SITE = ring_topk.MERGE_SITE

# family -> merge engine that actually served the most recent search
# (the ops surface: serve/debugz.py reports which engine is serving).
# Shared with ops.ring_topk so sharded_knn's chokepoint reports here too.
_ACTIVE_ENGINE = ring_topk.active_engines

# live sharded indexes (weak: an operator dropping an index must not leak
# it through the ops surface) — debugz reads per-family shards_ok here
_LIVE = weakref.WeakSet()

# shard-MTTR bookkeeping: down-transition timestamps per shard site
# (``sharded_ann.<family>.shard<i>``), observed into the ``shard.mttr``
# histogram on the up-transition. The clock is module-injectable so a
# compressed-time soak (raft_tpu/soak) measures simulated MTTR.
_clock = time.monotonic
_downed_at: dict = {}


def _merged_shard_search(index, family: str, make_local, in_specs, arrays,
                         m: int, k: int, select_min: bool, comms, statics,
                         merge_engine=None, topology=None, donate_q=None):
    """One chokepoint for every sharded family's cross-shard merge:
    resolve the engine (param/env override → autotune verdict → backend
    default; a multi-host ``topology`` adds the hierarchical ICI/DCN
    tier), fetch the cached jitted ``shard_map`` program for this
    (engine, statics) bucket — tracing it ONCE on a miss from
    ``make_local()``'s per-shard closure (dead shards already masked to
    sentinel rows) — and gate every non-allgather engine behind
    ``guarded_call(MERGE_SITE)`` falling back to the bit-identical
    allgather program (which caches under its own key, so the fallback
    is also trace-once). Returns replica-identical (distances, ids).

    ``statics`` is the family's closure-baked (name, value) tuple — see
    docs/perf.md "Sharded dispatch" for the key anatomy. ``donate_q``:
    position of the replicated query array in ``arrays`` to donate to
    the compiled program (make_searcher(donate=True)); None keeps the
    caller's buffer. ``RAFT_TPU_SHARDED_DISPATCH=uncached`` restores
    the eager per-call trace (the bitwise comparison hook)."""
    mesh = index.mesh
    p = mesh.shape[AXIS]
    # ring engines permute over the raw mesh axis: an injected
    # communicator restricted to subgroups keeps the allgather path
    plain_axis = getattr(comms, "groups", True) is None
    eng = ring_topk.resolve_engine(m, k, p, override=merge_engine,
                                   plain_axis=plain_axis, mesh=mesh,
                                   topology=topology)
    cache = dispatch_cache.cache_of(index)

    def prog(e):
        key = dispatch_cache.program_key(
            family, e, mesh, topology, comms,
            (("k", k), ("dq", donate_q is not None)) + tuple(statics))
        fn = cache.get(key) if dispatch_cache.enabled() else None
        if fn is None:
            local_fn = make_local()

            def body(*xs):
                d, gi = local_fn(*xs)
                return ring_topk.merge(d, gi, k, select_min, comms=comms,
                                       axis=AXIS, axis_size=p, engine=e,
                                       topology=topology)

            sm = shard_map_compat(body, mesh=mesh,
                                  in_specs=tuple(in_specs),
                                  out_specs=(P(), P()), check=False)
            fn = jax.jit(sm, donate_argnums=(
                () if donate_q is None else (int(donate_q),)))
            if dispatch_cache.enabled():
                cache[key] = fn
            # else: fresh wrapper per call — re-trace/re-compile the
            # identical (bitwise) program; the measurement baseline
        return fn

    def run(e):
        with dispatch_cache.dispatch_label(family, m, k):
            return prog(e)(*arrays)

    return ring_topk.guarded_dispatch(family, eng, run)


def ops_snapshot() -> dict:
    """The sharded-serving ops surface (read by serve/debugz.py):
    per-family shard health of every live index, the merge engine each
    family's latest search actually resolved, and how many ring-merge
    calls this process served through the allgather fallback."""
    fams: dict = {}
    # WeakSet iteration is python-level and raises RuntimeError if a
    # build thread registers an index mid-snapshot (the background
    # SnapshotWriter case); retry rather than lose the whole section
    for _ in range(4):
        try:
            live = list(_LIVE)
            break
        except RuntimeError:
            continue
    else:
        live = []
    for idx in live:
        ent = fams.setdefault(idx.family, {"indexes": 0, "shards_ok": []})
        ent["indexes"] += 1
        ent["shards_ok"].append(
            [bool(b) for b in np.asarray(idx.shards_ok, bool)])
        # per-shard re-probe results (probe_shards), one entry per index
        # aligned with the shards_ok list: the operator's answer to "is
        # the dead shard coming back, and if not why". Copied under
        # retry: a background probe loop inserts here concurrently, and
        # losing the whole sharded section during an incident is exactly
        # when the operator is reading it
        for _ in range(4):
            try:
                probes = {str(i): dict(r)
                          for i, r in list(idx.last_probe.items())}
                break
            except RuntimeError:
                continue
        else:
            probes = {}
        ent.setdefault("last_probe", []).append(probes)
    for fam, eng in dict(_ACTIVE_ENGINE).items():
        fams.setdefault(fam, {"indexes": 0, "shards_ok": []})
        fams[fam]["merge_engine"] = eng
    demotions = 0.0
    try:
        from ..serve import metrics as _metrics

        demotions = _metrics.counter("sharded.ring.demotions").value
    except Exception:  # noqa: BLE001
        pass
    from ..ops import guarded

    return {"families": fams,
            "ring_demotions": int(demotions),
            "ring_demoted": MERGE_SITE in guarded.demoted_sites()}


def health(index) -> dict:
    """Sharded-index health report (docs/observability.md "Quality"):
    per-shard real row counts + the sticky ``shards_ok`` flags — the
    numbers that say how much of the corpus a degraded merge is actually
    serving, and whether the row split is balanced enough that one
    shard's loss costs ~1/p recall rather than a hot partition."""
    if isinstance(index, ShardedCagra):
        counts = np.asarray(index.counts, np.int64)
    elif isinstance(index, (ShardedIvfFlat, ShardedIvfPq)):
        # count from the host-side size tables, NOT the device arrays: a
        # multi-process fleet index's ``sizes`` spans non-addressable
        # devices and cannot be fetched host-side. A budget-tiered fleet
        # index's live tables hold HOT sizes only — its full counts live
        # in ``_rows_tbl_full`` (cold rows are still served, streamed;
        # they must not read as lost corpus and trigger the auto-widen)
        tbl = getattr(index, "_rows_tbl_full", None)
        if tbl is None:
            tbl = (index._sizes_host if isinstance(index, ShardedIvfPq)
                   else index._max_rows_tbl)
        counts = np.asarray([int(np.sum(s)) for s in tbl], np.int64)
    else:
        raise TypeError(
            f"no health report for sharded type {type(index).__name__}")
    ok = [bool(b) for b in np.asarray(index.shards_ok, bool)]
    served = int(counts[np.asarray(ok, bool)].sum())
    return {
        "family": f"sharded_{index.family}",
        "n_shards": int(index.n_shards),
        "shards_ok": ok,
        "healthy_shards": int(sum(ok)),
        "n_total": int(index.n_total),
        "shard_rows": [int(c) for c in counts],
        "served_rows": served,
        "served_frac": round(served / max(int(index.n_total), 1), 4),
        "row_skew": round(float(counts.max() / max(counts.min(), 1)), 3),
    }


def _shard_health(index, family: str) -> np.ndarray:
    """Effective per-shard validity for one search call: the index's
    sticky ``shards_ok`` flags (set by ``mark_shard_failed`` — e.g. after
    a failed build, corrupt shard load, or repeated timeouts) AND'd with
    any armed ``shard_dead``/``shard_timeout`` fault probes, so every
    degraded-merge path is deterministically testable."""
    ok = np.asarray(index.shards_ok, bool).copy()
    for i in range(ok.size):
        site = f"sharded_ann.{family}.shard{i}"
        if ok[i] and (faults.fired("shard_dead", site) is not None
                      or faults.fired("shard_timeout", site) is not None):
            ok[i] = False
    return ok


def _health_gate(ok: np.ndarray, allow_partial: bool,
                 family: str = "") -> None:
    """Dead shards without ``allow_partial=True`` are an error, not a
    silently-degraded answer — and ZERO surviving shards is total
    failure, not a degraded answer: an all-(+inf, -1) result piped
    downstream would silently wrap-index with -1.

    A tolerated degraded merge (``allow_partial=True`` with dead shards)
    is counted under ``sharded.degraded_searches.<family>`` — the signal
    previously surfaced only through the serve batcher's per-response
    bookkeeping, invisible to direct callers."""
    if not ok.all():
        if not allow_partial or not ok.any():
            raise ShardsDownError(ok)
        try:
            from ..serve import metrics as _metrics

            _metrics.counter(f"sharded.degraded_searches.{family}").inc()
        except Exception:  # noqa: BLE001 - telemetry must not fail a search
            pass


def _mark_shard(shards_ok: np.ndarray, family: str, i: int, ok: bool) -> None:
    """Set the sticky health flag; flight-record only an actual state
    TRANSITION — a health-check loop re-asserting the same state every
    second must not fill the bounded ring (per-search degradation is the
    counter above)."""
    changed = bool(shards_ok[i]) != bool(ok)
    shards_ok[i] = ok
    if not changed:
        return
    site = f"sharded_ann.{family}.shard{i}"
    try:
        from ..core import events as _events

        _events.record("shard_marked", site, ok=bool(ok))
    except Exception:  # noqa: BLE001
        pass
    # MTTR verdict (docs/soak.md): marked-dead → restored wall
    try:
        if not ok:
            _downed_at[site] = _clock()
        else:
            t0 = _downed_at.pop(site, None)
            if t0 is not None:
                from ..serve import metrics as _metrics

                _metrics.histogram(
                    "shard.mttr",
                    _metrics.MTTR_BUCKETS_S).observe(_clock() - t0)
    except Exception:  # noqa: BLE001 - telemetry must not undo a mark
        pass


def _canary_search(index, i: int, rows: int = 8) -> None:
    """Cheap per-shard canary: slice a few rows of the shard's float
    source arrays off the mesh, run an exact micro-search (rows vs
    themselves) on device, and require finite results. This exercises
    the shard's device round-trip and arithmetic without a ``shard_map``
    dispatch — even with the dispatch cache the first probe at an
    unwarmed shape would pay a whole-program trace, and a canary must
    stay cheap on a cold process. Raises on any failure."""
    site = f"sharded_ann.{index.family}.shard{i}"
    # armed shard faults keep the shard dead, so the recovery arc is
    # deterministically drillable: the probe fails while the fault
    # holds and succeeds the tick after it clears. Checked WITHOUT
    # consuming a firing (matches, not fired): a background probe tick
    # must not drain a count-limited fault budget armed for the search
    # path
    if any(f.matches(k, site) for f in faults.active()
           for k in ("shard_dead", "shard_timeout")):
        raise RuntimeError(f"shard fault armed at {site}")
    src = index._canary_source()
    # never ask for more rows than the source has (a 1-list/1-row shard
    # must still be probeable — a shape clamp that rounds UP would fail
    # its canary forever)
    rows = max(1, min(int(rows), int(src.shape[1])))
    x = jnp.asarray(src[i, :rows], jnp.float32)
    d = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    dd = np.asarray(d)
    if dd.shape != (rows, rows) or not np.isfinite(dd).all():
        raise RuntimeError(
            f"canary produced non-finite distances on shard {i}")


def probe_shards(index, *, rows: int = 8, probe_fn=None) -> dict:
    """Re-probe every shard currently marked failed; flip ``shards_ok``
    back on success (docs/robustness.md "Shard re-probe").

    ``mark_shard_failed`` has always been a one-way street in practice:
    nothing re-marked a shard after a transient ICI/driver fault, so
    ``served_frac`` never recovered. This closes the loop: each dead
    shard runs a cheap canary (:func:`_canary_search`, or ``probe_fn(
    index, shard)`` when injected); success re-marks the shard healthy
    (a ``shard_marked ok=True`` transition plus an explicit
    ``shard_restored`` flight-recorder event), failure records why and
    leaves the sticky flag alone. Healthy shards are never probed.

    Returns ``{shard: ok}`` for the shards probed. Per-shard last-probe
    results are kept on the index (``index.last_probe``) and surfaced in
    the debugz ``sharded`` section. Call on an interval from serving —
    e.g. ``SnapshotWriter(..., hooks=[sharded_ann.probe_all])``.
    """
    ok = np.asarray(index.shards_ok, bool)
    results: dict = {}
    for i in np.flatnonzero(~ok):
        i = int(i)
        site = f"sharded_ann.{index.family}.shard{i}"
        rec = {"ok": False, "ts": time.time(), "error": None}
        try:
            if probe_fn is not None:
                probe_fn(index, i)
            else:
                _canary_search(index, i, rows=rows)
            rec["ok"] = True
            index.mark_shard_failed(i, ok=True)
            try:
                from ..core import events as _events

                _events.record("shard_restored", site,
                               served_frac=health(index)["served_frac"])
            except Exception:  # noqa: BLE001 - telemetry must not undo
                pass           # the restore
        except Exception as e:  # noqa: BLE001 - a failed probe is a result
            rec["error"] = f"{type(e).__name__}: {e}"
            try:
                from ..serve import metrics as _metrics

                _metrics.counter(
                    f"sharded.probe_failures.{index.family}").inc()
            except Exception:  # noqa: BLE001
                pass
        index.last_probe[i] = rec
        results[i] = rec["ok"]
    return results


def probe_all(**kw) -> dict:
    """Probe every live sharded index with dead shards (the
    SnapshotWriter-hook form of :func:`probe_shards`); returns
    ``{family: {shard: ok}}`` merged across live indexes."""
    out: dict = {}
    for _ in range(4):
        try:
            live = list(_LIVE)
            break
        except RuntimeError:     # registration race (see ops_snapshot)
            continue
    else:
        live = []
    for idx in live:
        if not np.asarray(idx.shards_ok, bool).all():
            out.setdefault(idx.family, {}).update(probe_shards(idx, **kw))
    return out


def _shard_mask(mesh, ok: np.ndarray) -> jax.Array:
    """(p, 1) bool validity mask sharded over the mesh axis (rides into
    shard_map so each shard masks its own contribution pre-merge)."""
    return jax.device_put(jnp.asarray(ok.reshape(-1, 1)),
                          NamedSharding(mesh, P(AXIS, None)))


def _comms_of(mesh, res=None) -> AxisComms:
    """Communicator for the shard axis: the injected one when a Resources
    carries it (the reference's resource::get_comms path), else a fresh
    AxisComms over the mesh's axis."""
    if res is not None and res.has_comms():
        return res.comms
    return AxisComms(AXIS, size=mesh.shape[AXIS])


def _split_rows(n: int, p: int) -> list[np.ndarray]:
    """Balanced contiguous row ranges per shard (the reference shards row
    blocks); no shard is ever empty for n >= p."""
    expects(n >= p, "cannot shard %d rows over %d shards", n, p)
    return np.array_split(np.arange(n), p)


def _stack_pad(arrs: list[np.ndarray], pad_value=0,
               min_rows: int = 0) -> np.ndarray:
    """Stack along a new leading axis, padding dim 0 to the common max
    (or ``min_rows`` if larger — build_cagra uses it to guarantee every
    shard has at least one padding row for seed-padding sentinels)."""
    m = max(min_rows, max(a.shape[0] for a in arrs))
    out = np.full((len(arrs), m) + arrs[0].shape[1:], pad_value,
                  arrs[0].dtype)
    for i, a in enumerate(arrs):
        out[i, : a.shape[0]] = a
    return out


class ShardedIvfFlat:
    """Stacked per-shard IVF-Flat arrays, leading axis sharded over AXIS."""

    family = "ivf_flat"

    def __init__(self, mesh, data, data_norms, source_ids, centers,
                 center_norms, offsets, sizes, n_total, metric, max_rows_tbl,
                 scales=None, store=None, logical_dim=None):
        self.mesh = mesh
        self.data = data                    # (p, R, d) f32|bf16|int8|uint8
        self.data_norms = data_norms        # (p, R)
        self.source_ids = source_ids        # (p, R) global ids, -1 pad
        self.centers = centers              # (p, L, d)
        self.center_norms = center_norms    # (p, L)
        self.offsets = offsets              # (p, L) row offsets (per shard)
        self.sizes = sizes                  # (p, L) list sizes
        self.n_total = n_total
        self.metric = metric
        self._max_rows_tbl = max_rows_tbl   # host: n_probes → max_rows bound
        self.scales = scales                # (p, R) f32, int8/int4 modes
        # storage rung of the stacked rows ("float32"/"int8"/"int4"/...)
        # — "int4" means nibble-packed data whose last axis is the
        # packed half-width, so searches must decode via logical_dim
        self.store = store if store is not None else str(data.dtype)
        self.logical_dim = int(data.shape[-1] if logical_dim is None
                               else logical_dim)
        # sticky per-shard health flags (see mark_shard_failed)
        self.shards_ok = np.ones(mesh.shape[AXIS], bool)
        # shard -> last probe_shards result (debugz sharded section)
        self.last_probe: dict = {}
        _LIVE.add(self)

    def mark_shard_failed(self, i: int, ok: bool = False) -> None:
        """Flag shard ``i`` unhealthy: its results are masked out of every
        merge until re-marked ok (search then needs allow_partial=True)
        or a :func:`probe_shards` canary succeeds."""
        _mark_shard(self.shards_ok, "ivf_flat", i, ok)

    def _canary_source(self):
        """Small float per-shard array for :func:`probe_shards`."""
        return self.centers

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[AXIS]

    def max_rows(self, n_probes: int) -> int:
        """Static probe budget: max over shards of the n_probes largest
        lists summed. A budget-tiered fleet index computes the bound
        from the FULL size table (``_rows_tbl_full``): the live table
        holds hot sizes that change across tier steps, and this static
        is baked into the cached dispatch executables — the bound must
        not move on a re-tier (the zero-recompile tier-step contract).
        The full-table bound is a superset of any hot bound and the
        extra gather slots are masked sentinel rows, so results are
        bitwise unchanged."""
        tbl = getattr(self, "_rows_tbl_full", None)
        if tbl is None:
            tbl = self._max_rows_tbl
        return int(max(ivf_flat._probe_budget(s, n_probes) for s in tbl))


def build_ivf_flat(dataset, mesh: Mesh,
                   params: ivf_flat.IndexParams | None = None
                   ) -> ShardedIvfFlat:
    """Build one IVF-Flat index per shard over its contiguous row block
    (the raft-dask pattern: each worker indexes its own partition)."""
    expects(AXIS in mesh.shape, "mesh must have a %r axis", AXIS)
    p0 = params or ivf_flat.IndexParams()
    dataset = np.asarray(dataset, np.float32)
    n = len(dataset)
    p = mesh.shape[AXIS]
    parts = _split_rows(n, p)
    expects(p0.n_lists <= min(len(r) for r in parts),
            "n_lists %d > smallest shard %d", p0.n_lists,
            min(len(r) for r in parts))

    shards = [ivf_flat.build(dataset[rows], p0) for rows in parts]
    mt = shards[0].metric

    data = _stack_pad([np.asarray(s.data) for s in shards])
    norms = _stack_pad([np.asarray(s.data_norms) for s in shards])
    # rebase local ids to global row numbers
    gids = _stack_pad(
        [np.where(np.asarray(s.source_ids) >= 0,
                   np.asarray(s.source_ids) + parts[i][0], -1)
         for i, s in enumerate(shards)],
        pad_value=-1)
    centers = np.stack([np.asarray(s.centers) for s in shards])
    cnorms = np.stack([np.asarray(s.center_norms) for s in shards])
    offsets = np.stack([s.list_offsets[:-1] for s in shards]).astype(np.int32)
    sizes = np.stack([s.list_sizes for s in shards]).astype(np.int32)

    def put(x, spec):
        return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

    scales = None
    if shards[0].scales is not None:   # int8: per-row dequant factors
        scales = put(_stack_pad([np.asarray(s.scales) for s in shards]),
                     P(AXIS, None))
    return ShardedIvfFlat(
        mesh,
        put(data, P(AXIS, None, None)), put(norms, P(AXIS, None)),
        put(gids, P(AXIS, None)),
        put(centers, P(AXIS, None, None)), put(cnorms, P(AXIS, None)),
        put(offsets, P(AXIS, None)), put(sizes, P(AXIS, None)),
        n, mt, [s.list_sizes for s in shards], scales)


def search_ivf_flat(index: ShardedIvfFlat, queries, k: int,
                    params: ivf_flat.SearchParams | None = None,
                    res=None, allow_partial: bool = False,
                    merge_engine: str | None = None, filter=None,  # noqa: A002
                    donate: bool = False):
    """Replicated queries → per-shard local search → cross-shard merge
    (ring or allgather engine; see :func:`_merged_shard_search` — the
    compiled program is cached per index, so repeat calls at a warmed
    shape compile nothing).

    ``allow_partial=True`` accepts dead shards (``index.shards_ok`` or an
    armed ``shard_dead``/``shard_timeout`` fault): their contributions
    are masked out of the merge and the return becomes
    ``(distances, indices, shards_ok)`` reporting the loss. Default
    (False) raises :class:`ShardsDownError` when any shard is dead.
    The health mask rides into the program as a TRACED argument, so
    marking/restoring shards reuses the cached executable.
    ``merge_engine``: force one of ``ops.ring_topk.ENGINES`` (or
    ``"auto"``); default consults ``RAFT_TPU_SHARDED_MERGE`` and the
    autotune verdict for this shape bucket.
    ``filter``: optional GLOBAL-id sample bitset (n_total bits); the
    replicated mask rides into every shard's local search (shard
    source ids are global, so the gather indexes it directly). A
    filtered row yields the same (+inf, -1) sentinel the dead-shard
    path emits, so the merge needs no new semantics.
    ``donate=True`` donates the replicated query buffer to the compiled
    program (docs/perf.md "Sharded dispatch" donation caveats: only
    safe when the caller does not reuse ``queries``).
    """
    sp = params or ivf_flat.SearchParams()
    q = jnp.asarray(queries, jnp.float32)
    n_probes = min(sp.n_probes, index.centers.shape[1])
    max_rows = index.max_rows(n_probes)
    mt = index.metric
    select_min = is_min_close(mt)
    comms = _comms_of(index.mesh, res)
    ok = _shard_health(index, "ivf_flat")
    _health_gate(ok, allow_partial, "ivf_flat")

    has_scales = index.scales is not None
    mask = filter.to_mask() if filter is not None else None
    has_filter = mask is not None
    int4_dim = (index.logical_dim
                if getattr(index, "store", None) == "int4" else None)

    def make_local():
        def local(data, norms, gids, centers, cnorms, offsets, sizes, okf,
                  qq, *rest):
            args = [a[0] for a in (data, norms, gids, centers, cnorms,
                                   offsets, sizes)]
            sc = rest[0][0] if has_scales else None
            mb = rest[int(has_scales)] if has_filter else None
            d, i = ivf_flat.search_arrays(
                args[0], args[1], args[2], args[3], args[4], args[5],
                args[6], qq, k, n_probes, max_rows, mt, mask_bits=mb,
                scales=sc, int4_dim=int4_dim)
            # dead-shard containment: an invalid shard's list is all
            # (+inf, -1) sentinel rows, so the merge is over survivors
            bad = jnp.inf if select_min else -jnp.inf
            d = jnp.where(okf[0, 0], d, bad)
            i = jnp.where(okf[0, 0], i, -1)
            return d, i
        return local

    in_specs = [P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                P(AXIS, None), P(AXIS, None), P()]
    arrays = [index.data, index.data_norms, index.source_ids,
              index.centers, index.center_norms, index.offsets,
              index.sizes, _shard_mask(index.mesh, ok), q]
    q_pos = 8                          # q's slot, for donation
    if has_scales:
        in_specs.append(P(AXIS, None))
        arrays.append(index.scales)
    if has_filter:
        in_specs.append(P())           # replicated: gids are global
        arrays.append(mask)
    statics = (("np", n_probes), ("mr", max_rows), ("mt", mt.name),
               ("sc", has_scales), ("f", has_filter), ("i4", int4_dim))
    d, i = _merged_shard_search(index, "ivf_flat", make_local, in_specs,
                                arrays, q.shape[0], k, select_min, comms,
                                statics, merge_engine,
                                topology=getattr(index, "topology", None),
                                donate_q=q_pos if donate else None)
    return (d, i, ok) if allow_partial else (d, i)


class ShardedCagra:
    """Stacked per-shard CAGRA graphs, leading axis sharded over AXIS."""

    family = "cagra"

    def __init__(self, mesh, data, graphs, bases, counts, n_total, metric,
                 seeds=None):
        self.mesh = mesh
        self.data = data        # (p, R, d) padded rows
        self.graphs = graphs    # (p, R, deg) LOCAL neighbor ids
        self.bases = bases      # (p,) global row base per shard
        self.counts = counts    # (p,) real (unpadded) rows per shard
        self.n_total = n_total
        self.metric = metric
        self.seeds = seeds      # (p, s) per-shard covering seed rows
                                # (sorted unique; invalid-id padded)
        self.shards_ok = np.ones(mesh.shape[AXIS], bool)
        self.last_probe: dict = {}
        _LIVE.add(self)

    def mark_shard_failed(self, i: int, ok: bool = False) -> None:
        """Flag shard ``i`` unhealthy (see ShardedIvfFlat.mark_shard_failed)."""
        _mark_shard(self.shards_ok, "cagra", i, ok)

    def _canary_source(self):
        return self.data

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[AXIS]


def build_cagra(dataset, mesh: Mesh,
                params: cagra.IndexParams | None = None) -> ShardedCagra:
    """Build one CAGRA graph per shard row block."""
    expects(AXIS in mesh.shape, "mesh must have a %r axis", AXIS)
    p0 = params or cagra.IndexParams()
    dataset = np.asarray(dataset, np.float32)
    n = len(dataset)
    p = mesh.shape[AXIS]
    parts = _split_rows(n, p)
    # per-shard COVERING seed sets ride along (stacked + padded): random
    # seeding alone collapses recall once shards hold >~1k rows — 32
    # random seeds cover 0.3% of a 10k-row shard and the traversal
    # strands in the wrong cluster (r5 dryrun: recall 0.27 vs 0.97)
    shards = [cagra.build(dataset[rows], p0) for rows in parts]
    mt = shards[0].metric

    counts = np.array([len(r) for r in parts], np.int32)
    seed_sets = [np.asarray(s.seed_nodes)
                 if s.seed_nodes is not None else np.zeros((0,), np.int32)
                 for s in shards]
    n_seed = max(ss.shape[0] for ss in seed_sets)
    # every shard's seed padding (count_i + pad_i sentinel ids, below)
    # must land on a real-but-invalid padded row: per-shard seed counts
    # are data-dependent (np.unique in _covering_seeds), so size the row
    # capacity to the worst pad, not a fixed slack
    max_pad = max((n_seed - ss.shape[0] for ss in seed_sets), default=0)
    cap = int(counts.max()) + max(8, max_pad + 1)
    data = _stack_pad([np.asarray(s.dataset) for s in shards],
                      min_rows=cap)
    graphs = _stack_pad([np.asarray(s.graph) for s in shards],
                        min_rows=cap)
    bases = np.array([r[0] for r in parts], np.int32)

    seeds = None
    if n_seed > 0:
        # pad each shard's sorted-unique seed list with ascending
        # INVALID row ids (count_i + j < cap): stays sorted unique, and
        # the search-time mask (valid rows only) scores them +inf
        padded = []
        for i, ss in enumerate(seed_sets):
            pad = n_seed - ss.shape[0]
            padded.append(np.concatenate(
                [ss, counts[i] + np.arange(pad, dtype=np.int32)]))
        seeds = np.stack(padded).astype(np.int32)

    def put(x, spec):
        return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

    return ShardedCagra(mesh, put(data, P(AXIS, None, None)),
                        put(graphs, P(AXIS, None, None)),
                        put(bases, P(AXIS)), put(counts, P(AXIS)), n, mt,
                        seeds=None if seeds is None
                        else put(seeds, P(AXIS, None)))


def search_cagra(index: ShardedCagra, queries, k: int,
                 params: cagra.SearchParams | None = None,
                 res=None, allow_partial: bool = False,
                 merge_engine: str | None = None, filter=None,  # noqa: A002
                 donate: bool = False):
    """Replicated queries → per-shard graph traversal → cross-shard merge.

    ``allow_partial``/``merge_engine``/``filter``/``donate``: contract
    of :func:`search_ivf_flat`. CAGRA shard rows are LOCAL (row = global
    id - base), so each shard slices its window out of the replicated
    global mask and folds it into the padding-row validity mask that
    already rides ``_search_jit``'s filter slot.
    """
    sp = params or cagra.SearchParams()
    q = jnp.asarray(queries, jnp.float32)
    itopk = max(sp.itopk_size, k)
    width = max(1, sp.search_width)
    max_iter = sp.max_iterations or (itopk // width + 16)
    degree = index.graphs.shape[2]
    n_seeds = min(itopk, max(width * degree // 2,
                             16 * sp.num_random_samplings))
    mt = index.metric
    select_min = mt is not DistanceType.InnerProduct
    comms = _comms_of(index.mesh, res)
    ok = _shard_health(index, "cagra")
    _health_gate(ok, allow_partial, "cagra")

    has_seeds = index.seeds is not None
    mask = None
    if filter is not None:
        R = int(index.data.shape[1])
        mask = filter.to_mask()
        # pad the global mask with False so every shard's (base, base+R)
        # window is in range: lax.dynamic_slice CLAMPS an out-of-range
        # start, which would silently shift the last shard's window
        need = int(np.asarray(index.bases).max()) + R
        if mask.shape[0] < need:
            mask = jnp.pad(mask, (0, need - mask.shape[0]))
    has_filter = mask is not None

    def make_local():
        def local(data, graph, base, count, okf, qq, *rest):
            # padding rows (beyond this shard's real count) are masked
            # out so neither random nor covering seeding surfaces them
            valid = jnp.arange(data.shape[1], dtype=jnp.int32) < count[0]
            seed_rows = rest[0][0] if has_seeds else None
            if has_filter:
                gm = rest[int(has_seeds)]
                valid = valid & jax.lax.dynamic_slice(gm, (base[0],),
                                                      (data.shape[1],))
            # gather engine explicitly: shard-local data lives only
            # inside this trace, so an edge-resident store can never be
            # attached
            d, i = cagra._search_jit(
                data[0], data[0], None, graph[0], qq, valid,
                jax.random.key(sp.seed), seed_rows, None, None, None,
                itopk, width, int(max_iter), k, n_seeds, mt.value)
            gi = jnp.where(i >= 0, i + base[0], -1)
            gi = jnp.where(okf[0, 0], gi, -1)   # dead-shard containment
            bad = jnp.inf if select_min else -jnp.inf
            d = jnp.where(gi >= 0, d, bad)
            return d, gi
        return local

    in_specs = [P(AXIS, None, None), P(AXIS, None, None), P(AXIS), P(AXIS),
                P(AXIS, None), P()]
    arrays = [index.data, index.graphs, index.bases, index.counts,
              _shard_mask(index.mesh, ok), q]
    q_pos = 5                          # q's slot, for donation
    if has_seeds:
        in_specs.append(P(AXIS, None))
        arrays.append(index.seeds)
    if has_filter:
        in_specs.append(P())           # replicated; sliced per shard
        arrays.append(mask)
    statics = (("itopk", itopk), ("w", width), ("it", int(max_iter)),
               ("ns", n_seeds), ("rs", sp.seed), ("sd", has_seeds),
               ("f", has_filter), ("mt", mt.name))
    d, i = _merged_shard_search(index, "cagra", make_local, in_specs,
                                arrays, q.shape[0], k, select_min, comms,
                                statics, merge_engine,
                                topology=getattr(index, "topology", None),
                                donate_q=q_pos if donate else None)
    return (d, i, ok) if allow_partial else (d, i)


class ShardedIvfPq:
    """Stacked per-shard IVF-PQ arrays, leading axis sharded over AXIS.

    The BASELINE north-star layout (sharded IVF-PQ over a worker mesh): one
    compressed index per shard row block, merged per-query at search time.
    """

    family = "ivf_pq"

    def __init__(self, mesh, codes, source_ids, centers_rot, codebooks,
                 rotations, offsets, sizes, n_total, metric, pq_bits,
                 codebook_kind, sizes_host):
        self.mesh = mesh
        self.codes = codes              # (p, R, pq_dim) u8, cluster-sorted
        self.source_ids = source_ids    # (p, R) GLOBAL ids, -1 pad
        self.centers_rot = centers_rot  # (p, L, rot_dim)
        self.codebooks = codebooks      # (p, ...) per-shard codebooks
        self.rotations = rotations      # (p, rot_dim, dim)
        self.offsets = offsets          # (p, L) i32
        self.sizes = sizes              # (p, L) i32
        self.n_total = n_total
        self.metric = metric
        self.pq_bits = pq_bits
        self.codebook_kind = codebook_kind
        self._sizes_host = sizes_host   # list of per-shard np size arrays
        self.shards_ok = np.ones(mesh.shape[AXIS], bool)
        self.last_probe: dict = {}
        _LIVE.add(self)

    def mark_shard_failed(self, i: int, ok: bool = False) -> None:
        """Flag shard ``i`` unhealthy (see ShardedIvfFlat.mark_shard_failed)."""
        _mark_shard(self.shards_ok, "ivf_pq", i, ok)

    def _canary_source(self):
        return self.centers_rot

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[AXIS]

    def max_rows(self, n_probes: int) -> int:
        # full-table bound when budget-tiered (see
        # ShardedIvfFlat.max_rows: tier steps must not move this static)
        tbl = getattr(self, "_rows_tbl_full", None)
        if tbl is None:
            tbl = self._sizes_host
        return int(max(ivf_pq._probe_budget(s, n_probes) for s in tbl))


def build_ivf_pq(dataset, mesh: Mesh,
                 params: ivf_pq.IndexParams | None = None) -> ShardedIvfPq:
    """Build one IVF-PQ index per contiguous shard row block (the raft-dask
    per-worker build of BASELINE config 5)."""
    expects(AXIS in mesh.shape, "mesh must have a %r axis", AXIS)
    p0 = params or ivf_pq.IndexParams()
    dataset = np.asarray(dataset, np.float32)
    n = len(dataset)
    p = mesh.shape[AXIS]
    parts = _split_rows(n, p)

    shards = [ivf_pq.build(dataset[rows], p0) for rows in parts]
    mt = shards[0].metric

    codes = _stack_pad([np.asarray(s.codes) for s in shards])
    gids = _stack_pad(
        [np.where(np.asarray(s.source_ids) >= 0,
                   np.asarray(s.source_ids) + parts[i][0], -1)
         for i, s in enumerate(shards)],
        pad_value=-1)
    centers = np.stack([np.asarray(s.centers_rot) for s in shards])
    books = np.stack([np.asarray(s.codebooks) for s in shards])
    rots = np.stack([np.asarray(s.rotation) for s in shards])
    offsets = np.stack([s.list_offsets[:-1] for s in shards]).astype(np.int32)
    sizes = np.stack([s.list_sizes for s in shards]).astype(np.int32)

    def put(x, spec):
        return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

    ndim_spec = lambda a: P(AXIS, *([None] * (a.ndim - 1)))
    return ShardedIvfPq(
        mesh, put(codes, ndim_spec(codes)), put(gids, ndim_spec(gids)),
        put(centers, ndim_spec(centers)), put(books, ndim_spec(books)),
        put(rots, ndim_spec(rots)), put(offsets, ndim_spec(offsets)),
        put(sizes, ndim_spec(sizes)), n, mt, shards[0].pq_bits,
        shards[0].codebook_kind, [s.list_sizes for s in shards])


def search_ivf_pq(index: ShardedIvfPq, queries, k: int,
                  params: ivf_pq.SearchParams | None = None,
                  res=None, allow_partial: bool = False,
                  merge_engine: str | None = None, filter=None,  # noqa: A002
                  donate: bool = False):
    """Replicated queries → per-shard LUT search → cross-shard merge
    (knn_merge_parts.cuh:172 role, ring or allgather engine).

    ``allow_partial``/``merge_engine``/``filter``/``donate``: contract
    of :func:`search_ivf_flat` (PQ shard source ids are global, so the
    replicated mask indexes directly).
    """
    sp = params or ivf_pq.SearchParams()
    q = jnp.asarray(queries, jnp.float32)
    n_probes = min(sp.n_probes, index.centers_rot.shape[1])
    max_rows = index.max_rows(n_probes)
    mt = index.metric
    select_min = is_min_close(mt)
    comms = _comms_of(index.mesh, res)
    ok = _shard_health(index, "ivf_pq")
    _health_gate(ok, allow_partial, "ivf_pq")
    # dummy host offsets: _search_chunk reads offsets/sizes from the traced
    # args, never from the Index (search() does, but we bypass it)
    dummy_off = np.zeros(index.centers_rot.shape[1] + 1, np.int64)

    mask = filter.to_mask() if filter is not None else None
    has_filter = mask is not None

    def make_local():
        def local(codes, gids, centers, books, rots, offsets, sizes, okf,
                  qq, *rest):
            mb = rest[0] if has_filter else None
            shard = ivf_pq.Index(
                codes[0], gids[0], centers[0], books[0], rots[0],
                dummy_off, mt, index.pq_bits, index.codebook_kind)
            d, i = ivf_pq._search_chunk(shard, qq, k, n_probes, max_rows,
                                        offsets[0], sizes[0], mb,
                                        sp.lut_dtype)
            i = jnp.where(okf[0, 0], i, -1)     # dead-shard containment
            bad = jnp.inf if select_min else -jnp.inf
            d = jnp.where(i >= 0, d, bad)       # padded rows carry id -1
            return d, i
        return local

    in_specs = [P(AXIS, None, None), P(AXIS, None), P(AXIS, None, None),
                P(AXIS, *([None] * (index.codebooks.ndim - 1))),
                P(AXIS, None, None), P(AXIS, None), P(AXIS, None),
                P(AXIS, None), P()]
    arrays = [index.codes, index.source_ids, index.centers_rot,
              index.codebooks, index.rotations, index.offsets,
              index.sizes, _shard_mask(index.mesh, ok), q]
    q_pos = 8                          # q's slot, for donation
    if has_filter:
        in_specs.append(P())           # replicated: gids are global
        arrays.append(mask)
    statics = (("np", n_probes), ("mr", max_rows), ("mt", mt.name),
               ("lut", np.dtype(sp.lut_dtype).name), ("f", has_filter),
               ("b", index.pq_bits),
               ("ck", getattr(index.codebook_kind, "name",
                              index.codebook_kind)))
    d, i = _merged_shard_search(index, "ivf_pq", make_local, in_specs,
                                arrays, q.shape[0], k, select_min, comms,
                                statics, merge_engine,
                                topology=getattr(index, "topology", None),
                                donate_q=q_pos if donate else None)
    return (d, i, ok) if allow_partial else (d, i)


def make_searcher(index, params=None, *, allow_partial: bool = False,
                  donate: bool = False, **opts):
    """Stable batchable signature for the serving runtime
    (:mod:`raft_tpu.serve`), dispatching on the sharded index type:
    returns ``fn(queries, k, res=None) -> (distances, indices)`` — or,
    with ``allow_partial=True``, ``(distances, indices, shards_ok)`` so
    the batcher can serve degraded answers through dead shards and
    surface the loss in its metrics and per-request responses.

    The closure hits the index's compiled-program cache: after a
    :func:`~raft_tpu.serve.warmup.warmup` sweep (or one cold call per
    shape bucket), repeat dispatches compile nothing. ``donate=True``
    donates the replicated query buffer to the cached program (the
    batcher's double-buffered closures pass freshly-built batches that
    are never reused); leave False when callers keep their query
    arrays — see docs/perf.md "Sharded dispatch" donation caveats."""
    fns = {ShardedIvfFlat: search_ivf_flat,
           ShardedCagra: search_cagra,
           ShardedIvfPq: search_ivf_pq}
    fn = fns.get(type(index))
    expects(fn is not None, "unsupported sharded index type %s",
            type(index).__name__)

    def _fn(queries, k, res=None):
        return fn(index, queries, k, params, res=res,
                  allow_partial=allow_partial, donate=donate, **opts)

    return _fn


def searcher_dim(index) -> int:
    """Query dimensionality a sharded/fleet index expects — what a
    warmup sweep should size its dummy batches to."""
    if hasattr(index, "logical_dim"):          # ShardedIvfFlat
        return int(index.logical_dim)
    if hasattr(index, "rotations"):            # ShardedIvfPq
        return int(index.rotations.shape[-1])
    if hasattr(index, "dataset"):              # sharded_knn.ShardedIndex
        return int(index.dataset.shape[1])
    return int(index.data.shape[-1])           # ShardedCagra


def widen_rungs(index, n_probes: int | None = None) -> list:
    """Every effective ``n_probes`` the degradation auto-widen
    (``fleet._effective_nprobe``) can reach from ``n_probes`` on this
    index — the ladder a warmup sweep must pre-compile so a host loss
    lands on an already-cached executable instead of a fresh trace.

    Loss granularity follows the index: host-granular when a multi-host
    topology is adopted (a DCN partition takes whole hosts), shard-
    granular otherwise. Survivor subsets are enumerated exactly up to
    10 units (handles row skew); larger fleets warm the uniform
    ``j/u`` fractions. CAGRA has no probe ladder — returns ``[]``."""
    from . import fleet as _fleet    # lazy: fleet imports this module

    if isinstance(index, ShardedCagra):
        return []
    centers = (index.centers if isinstance(index, ShardedIvfFlat)
               else index.centers_rot)
    n_lists = int(centers.shape[1])
    if n_probes is None:
        n_probes = (ivf_flat.SearchParams().n_probes
                    if isinstance(index, ShardedIvfFlat)
                    else ivf_pq.SearchParams().n_probes)
    npb = min(int(n_probes), n_lists)
    h = health(index)
    rows = np.asarray(h["shard_rows"], np.int64)
    total = max(int(h["n_total"]), 1)
    topo = getattr(index, "topology", None)
    if topo is not None and getattr(topo, "n_hosts", 1) > 1:
        dph = int(topo.devs_per_host)
        units = [int(rows[i * dph:(i + 1) * dph].sum())
                 for i in range(int(topo.n_hosts))]
    else:
        units = [int(r) for r in rows]
    u = len(units)
    fracs = set()
    if u <= 10:
        for bits in range(1, 2 ** u):    # every non-empty survivor set
            served = sum(r for j, r in enumerate(units) if bits >> j & 1)
            fracs.add(served / total)
    else:
        fracs.update(j / u for j in range(1, u + 1))
    rungs = {npb}
    for f in fracs:
        rungs.add(_fleet._effective_nprobe(npb, f, n_lists))
    return sorted(rungs)


def warmup_searchers(index, params=None, **opts) -> dict:
    """``{rung_name: closure}`` mapping for
    :func:`raft_tpu.serve.warmup.warmup`'s ``engines=`` sweep: the base
    params plus one cache-hitting closure per :func:`widen_rungs` rung,
    so the warmup pass pre-compiles the whole degraded ``n_probes``
    ladder. Each closure searches with ``n_probes`` REPLACED by the
    rung value — exactly the params the fleet's auto-widen will
    produce, so a later host loss lands on the warmed key. (The health
    mask itself is a traced argument: no rung needs a dead shard to
    compile.) Budget-tiered fleet indexes should warm through
    :meth:`~raft_tpu.parallel.fleet.Fleet.warmup_searchers` instead,
    which also drives the cold-list merge."""
    import dataclasses

    engs = {"base": make_searcher(index, params, **opts)}
    if isinstance(index, ShardedCagra):
        return engs
    sp = params or (ivf_flat.SearchParams()
                    if isinstance(index, ShardedIvfFlat)
                    else ivf_pq.SearchParams())
    centers = (index.centers if isinstance(index, ShardedIvfFlat)
               else index.centers_rot)
    base_np = min(int(sp.n_probes), int(centers.shape[1]))
    for eff in widen_rungs(index, sp.n_probes):
        if eff == base_np:
            continue                   # already covered by "base"
        engs[f"np{eff}"] = make_searcher(
            index, dataclasses.replace(sp, n_probes=eff), **opts)
    return engs
