"""Two-level fleet topology: hosts × local devices over ICI + DCN.

The reference's MNMG layer treats the fabric as flat NCCL ranks; a TPU
pod is not flat — devices within a host (really: within an ICI domain)
see each other over the high-bandwidth interconnect, while hosts see
each other over DCN at roughly an order of magnitude less bandwidth.
This module is the one place that asymmetry is modeled:

* :class:`Topology` — ``n_hosts × devs_per_host`` with the host-major
  shard numbering every fleet mesh uses (shard ``s`` lives on host
  ``s // devs_per_host``), plus the two group decompositions the
  hierarchical merge needs: ``host_groups()`` (the ICI cliques) and
  ``cross_groups()`` (one representative per host at each local slot —
  the DCN planes).
* :func:`detect` — derive the topology from ``jax.distributed``
  process/device metadata (each jax process is one "host"; its
  addressable devices are the ICI domain).
* :func:`virtual` / :func:`fleet_mesh` — the CPU-emulation mode (the
  ``multichip`` fixture precedent): a single process's virtual devices
  reshaped ``hosts × devs``, so every cross-host code path (grouped
  collectives, the DCN fold, host-loss masking) runs machine-checked in
  tier-1 without a pod.
* :func:`plan_merge` — the wire math for one merged search: what
  crosses ICI, what crosses DCN, and the reduction factor vs. the flat
  allgather merge (the number an operator sizes DCN by).

Shard numbering is HOST-MAJOR everywhere: mesh position ``h * D + l``
is host ``h``'s local device ``l``. ``detect`` validates that the
device order actually satisfies this (jax orders ``jax.devices()`` by
id, which groups by process for the CPU/gloo and TPU backends; a
backend that interleaved processes would silently break the grouped
collectives, so it is checked, not assumed).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

from ..core.errors import expects

__all__ = ["Topology", "detect", "virtual", "fleet_mesh", "plan_merge"]

AXIS = "shard"


@dataclasses.dataclass(frozen=True)
class Topology:
    """``n_hosts`` ICI domains of ``devs_per_host`` devices each.

    A frozen value: resolve_engine keys behavior off it, so it must be
    hashable and comparison-stable across processes.
    """

    n_hosts: int
    devs_per_host: int

    def __post_init__(self):
        expects(self.n_hosts >= 1 and self.devs_per_host >= 1,
                "bad topology %dx%d", self.n_hosts, self.devs_per_host)

    @property
    def n_shards(self) -> int:
        return self.n_hosts * self.devs_per_host

    @property
    def multi_host(self) -> bool:
        return self.n_hosts > 1

    def host_of(self, shard: int) -> int:
        """Host owning mesh position ``shard`` (host-major numbering)."""
        expects(0 <= shard < self.n_shards, "shard %d out of range", shard)
        return shard // self.devs_per_host

    def shards_of(self, host: int) -> range:
        """Mesh positions of ``host``'s local devices."""
        expects(0 <= host < self.n_hosts, "host %d out of range", host)
        return range(host * self.devs_per_host,
                     (host + 1) * self.devs_per_host)

    def host_groups(self) -> Tuple[Tuple[int, ...], ...]:
        """ICI cliques: one group per host, its local shards in order —
        the ``axis_index_groups`` of every within-host collective."""
        return tuple(tuple(self.shards_of(h)) for h in range(self.n_hosts))

    def cross_groups(self) -> Tuple[Tuple[int, ...], ...]:
        """DCN planes: group ``l`` holds local slot ``l`` of every host,
        in host order — the ``axis_index_groups`` of the cross-host fold
        (group row order IS host order, which the hierarchical merge's
        position stamping depends on)."""
        return tuple(
            tuple(h * self.devs_per_host + l for h in range(self.n_hosts))
            for l in range(self.devs_per_host))


def detect(devices=None) -> Topology:
    """Topology from ``jax.distributed`` metadata: each process is one
    host, its addressable devices the ICI domain. Single-process (no
    ``jax.distributed``) collapses to ``Topology(1, n_devices)``.

    Validates host-major device order and equal per-host device counts —
    the two invariants every grouped collective below assumes.
    """
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    expects(len(devs) > 0, "no devices to build a topology over")
    procs = [d.process_index for d in devs]
    uniq = sorted(set(procs))
    per_host = [sum(1 for p in procs if p == u) for u in uniq]
    expects(len(set(per_host)) == 1,
            "unequal devices per host: %s (fleet meshes need a uniform "
            "hosts x devs grid)", dict(zip(uniq, per_host)))
    topo = Topology(len(uniq), per_host[0])
    # host-major order check: position h*D+l must belong to host h
    for s, p in enumerate(procs):
        expects(uniq[topo.host_of(s)] == p,
                "device order is not host-major at position %d (process "
                "%d where host %d was expected); reorder the mesh devices "
                "by (process_index, id)", s, p, topo.host_of(s))
    return topo


def virtual(n_hosts: int, devs_per_host: int) -> Topology:
    """CPU-emulation topology: a single process's virtual devices
    RESHAPED ``hosts × devs`` (the ``multichip`` fixture precedent) so
    the hierarchical-merge and host-loss paths run in tier-1. The grouped
    collectives behave identically; only the wire underneath differs."""
    return Topology(n_hosts, devs_per_host)


def fleet_mesh(topology: Optional[Topology] = None, devices=None,
               axis: str = AXIS):
    """1-D host-major mesh for a topology → ``(Mesh, Topology)``.

    ``topology=None`` detects it from the (global) device set. Devices
    are ordered ``(process_index, id)`` — host-major by construction —
    and trimmed to ``topology.n_shards`` (virtual mode: a 2x4 topology
    over the first 8 virtual CPU devices).
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    if topology is None:
        topo = detect(devs)
    else:
        topo = topology
        expects(len(devs) >= topo.n_shards,
                "topology %dx%d needs %d devices, have %d", topo.n_hosts,
                topo.devs_per_host, topo.n_shards, len(devs))
        devs = devs[: topo.n_shards]
        # real multi-process sets must still be host-major w.r.t. topo
        if len({d.process_index for d in devs}) > 1:
            expects(detect(devs) == topo,
                    "device processes do not match topology %dx%d",
                    topo.n_hosts, topo.devs_per_host)
    return Mesh(np.array(devs), (axis,)), topo


def plan_merge(topology: Topology, m: int, k: int, *,
               n_rows: Optional[int] = None,
               row_bytes: Optional[float] = None,
               hbm_budget_gb: Optional[float] = None) -> dict:
    """The wire math of one hierarchically merged search over ``m``
    queries × ``k`` results (f32 distances + i32 ids = 8 bytes/cell).

    Stage 1 (ICI, per host): a ``(D-1)``-hop ring over the host's local
    shards — each device moves ``per_hop_bytes`` per hop, all within the
    ICI domain. Stage 2 (DCN): an allgather fold of the per-host winner
    blocks — each device receives ``H-1`` foreign ``(m, k)`` blocks over
    DCN. The flat allgather merge instead moves ``(H-1)·D`` blocks per
    device over DCN: the hierarchy's DCN reduction factor is exactly
    ``D`` (the whole point of merging within the ICI domain first).

    ``n_rows`` + ``row_bytes`` add the per-host STORAGE math alongside
    the wire math (docs/mnmg.md "Per-host storage tiers"): how many
    rows and bytes each host carries at a given ladder rung, and — with
    ``hbm_budget_gb`` — how the corpus splits between the HBM-resident
    set and the host-streamed cold tier. Row bytes never cross either
    fabric (codes stay host-local); this block is what an operator
    sizes per-host HBM and the budget knob by.
    """
    from ..ops import ring_topk

    H, D = topology.n_hosts, topology.devs_per_host
    blk = m * k * (4 + 4)
    plan = {
        "topology": f"{H}x{D}",
        "n_shards": topology.n_shards,
        "engine": "hier" if topology.multi_host else "flat",
        "stages": [],
        "ici_bytes_per_device": 0,
        "dcn_bytes_per_device": 0,
    }
    if D > 1:
        plan["stages"].append(
            {"stage": "ici_ring", "hops": D - 1,
             "bytes_per_device": (D - 1) * ring_topk.per_hop_bytes(m, k)})
        plan["ici_bytes_per_device"] = (D - 1) * ring_topk.per_hop_bytes(m, k)
    if H > 1:
        plan["stages"].append(
            {"stage": "dcn_allgather_fold", "peers": H - 1,
             "bytes_per_device": (H - 1) * blk})
        plan["dcn_bytes_per_device"] = (H - 1) * blk
        plan["flat_dcn_bytes_per_device"] = (H - 1) * D * blk
        plan["dcn_reduction"] = D
    if n_rows is not None and row_bytes is not None:
        expects(n_rows >= 0 and row_bytes > 0,
                "bad storage shape: n_rows=%s row_bytes=%s",
                n_rows, row_bytes)
        rows_host = -(-int(n_rows) // H)          # ceil: worst host
        bytes_host = int(round(rows_host * float(row_bytes)))
        storage = {
            "row_bytes": float(row_bytes),
            "rows_per_host": rows_host,
            "bytes_per_host": bytes_host,
        }
        if hbm_budget_gb is not None and hbm_budget_gb > 0:
            budget = int(float(hbm_budget_gb) * (1 << 30))
            storage["hbm_budget_bytes_per_host"] = budget
            storage["resident_bytes_per_host"] = min(bytes_host, budget)
            storage["host_stream_bytes_per_host"] = max(
                0, bytes_host - budget)
            storage["fits_resident"] = bytes_host <= budget
        plan["storage"] = storage
    return plan
