"""On-device select_k cost sweep across (rows, n, k).

Produces the recorded measurement behind ``select_k``'s single-engine
design note (the measured analog of the reference's per-arch
``choose_select_k_algorithm`` table, matrix/detail/select_k-inl.cuh:48-72):
every point runs ``tune_select_k`` — per-call-blocked medians — purely as
a calibration record (nothing dispatches on it; every algo name maps to
the same engine). The historical sweep (bench_select_k_sweep.json at the
repo root) measured a masked-input "radix" pre-filter tying plain top_k
within dispatch noise at every point, which is why select_k ships a
single sort-based engine (see matrix/select_k.py).

Run: ``python -m raft_tpu.bench.select_k_sweep [out.json]`` on the target
device.
"""
from __future__ import annotations

import json
import sys

GRID = [
    # (rows, n, k): brute-force merge shapes, IVF coarse shapes, wide rows
    (128, 1024, 10),
    (1024, 1024, 64),
    (128, 16384, 10),
    (1024, 16384, 32),
    (128, 65536, 10),
    (512, 65536, 32),
    (64, 262144, 10),
    (64, 262144, 128),
]


def run(out_path: str | None = None) -> dict:
    import jax

    from ..matrix.select_k import tune_select_k

    results = []
    for rows, n, k in GRID:
        winner, timings = tune_select_k(rows, n, k, reps=5)
        entry = {"rows": rows, "n": n, "k": k, "winner": winner,
                 "ms": {name: round(t * 1e3, 2)
                        for name, t in timings.items()}}
        results.append(entry)
        print(f"# rows={rows} n={n} k={k}: {winner} {entry['ms']}",
              file=sys.stderr, flush=True)
    dev = jax.devices()[0]
    doc = {
        "device": f"{dev.platform}:{getattr(dev, 'device_kind', '?')}",
        "methodology": ("tune_select_k: per-call-blocked median of 5, "
                        "per-rep input perturb + output chain "
                        "(anti replay-cache)"),
        "results": results,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "bench_select_k_sweep.json"
    doc = run(out)
    print(json.dumps(doc))
