"""ANN benchmark harness: analog of ``python/raft-ann-bench`` +
``cpp/bench/ann``.

Reference: the CLI pipeline get_dataset → generate_groundtruth → run →
data_export → plot (raft-ann-bench/run/__main__.py:141-256) driving
executables that emit Google-Benchmark JSON with Recall/QPS counters
(cpp/bench/ann/src/common/benchmark.hpp:320-371).

TPU design: one in-process harness — datasets (synthetic generators +
big-ann fbin/ibin + ann-benchmarks HDF5 readers), brute-force ground
truth on-chip, param-sweep runner producing the same JSON counter schema
(so the reference's export/plot tooling carries over), CSV export with
pareto-frontier marking, and QPS-vs-recall plots.

CLI: ``python -m raft_tpu.bench run --dataset blobs-100000x128 ...``
"""
from .datasets import (generate_groundtruth, load_dataset, read_fbin,
                       read_ibin, write_fbin, write_ibin)
from .runner import BenchResult, default_configs, run_benchmarks

__all__ = [
    "read_fbin", "write_fbin", "read_ibin", "write_ibin", "load_dataset",
    "generate_groundtruth", "run_benchmarks", "default_configs",
    "BenchResult",
]
