"""On-device roofline probe: measured peaks, not assumed ones.

The bench reports kernel throughput as a fraction of the *measured* peak of
the device actually in use (matmul TFLOP/s, HBM stream GB/s), because
assumed per-generation limits (e.g. v5e datasheet numbers) can be off by
orders of magnitude under remote/tunneled or simulated backends.

Methodology: ``ops.autotune.measure`` — one blocking ``block_until_ready``
per call (backends can elide never-awaited dispatches, making
block-once-after-N timing meaningless), median of ``reps`` calls. Inputs
are generated on device — host↔device transfer never enters the timing.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..ops.autotune import measure as _median_time

__all__ = ["probe", "matmul_tflops", "hbm_stream_gbps", "dispatch_us"]


def matmul_tflops(n: int = 8192, dtype=jnp.bfloat16, reps: int = 7) -> float:
    """Sustained TFLOP/s of one n×n×n matmul (result consumed on device)."""
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.float32).astype(dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32).astype(dtype)

    @jax.jit
    def f(a, b):
        return jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dt = _median_time(f, a, b, reps=reps)
    return 2.0 * n ** 3 / dt / 1e12


def hbm_stream_gbps(mbytes: int = 1024, reps: int = 7) -> float:
    """Sustained HBM read GB/s on a streaming f32 sum reduction."""
    n = (mbytes << 20) // 4
    x = jax.random.normal(jax.random.PRNGKey(2), (n,), jnp.float32)

    @jax.jit
    def f(x):
        return jnp.sum(x)

    dt = _median_time(f, x, reps=reps)
    return 4.0 * n / dt / 1e9


def dispatch_us(reps: int = 11) -> float:
    """Median round-trip of a trivial dispatch (1-element add + sync)."""
    x = jnp.zeros((8, 128), jnp.float32)

    @jax.jit
    def f(x):
        return x + 1.0

    return _median_time(f, x, reps=reps) * 1e6


def probe(quick: bool = False) -> Dict[str, float]:
    """Measure this device's effective peaks. ~4 compiles, a few seconds
    of runtime (plus compile time) on a healthy backend."""
    reps = 3 if quick else 7
    return {
        "matmul_bf16_tflops": round(matmul_tflops(dtype=jnp.bfloat16,
                                                  reps=reps), 1),
        "matmul_f32_tflops": round(matmul_tflops(dtype=jnp.float32,
                                                 reps=reps), 1),
        "hbm_stream_gbps": round(hbm_stream_gbps(
            mbytes=256 if quick else 1024, reps=reps), 1),
        "dispatch_us": round(dispatch_us(), 1),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(probe()))
