"""On-device roofline probe: measured peaks, not assumed ones.

The bench reports kernel throughput as a fraction of the *measured* peak
of the device actually in use (matmul TFLOP/s, HBM stream GB/s, random-
row gather GB/s), because assumed per-generation limits can be off by
orders of magnitude under remote/tunneled or simulated backends.

Methodology (r5, replacing the r4 single-point probes): every probe runs
the SAME one-dispatch ``lax.fori_loop`` program at TWO iteration counts
``(i1, i2)`` and fits the slope

    per_iter_s = (t(i2) - t(i1)) / (i2 - i1)

so every per-dispatch constant — the tunnel's ~100 ms round trip, infeed,
program setup, clock ramp-up at the window edge — cancels exactly instead
of polluting the rate. Timing is ``autotune.measure_value_read_wall``
(content-distinct inputs; the window closes with a host ``float()`` of a
scalar folded from every output — the repo's strongest anti-replay
timing). Loop carries feed each iteration from the previous one, so no
iteration can be elided or hoisted.

This rewrite exists because the r4 probe read 74 GB/s HBM against an
819 GB/s v5e datasheet: with only 8-64 GB of traffic behind a ~0.15 s
per-dispatch constant, the constant dominated the division. The slope
method on the same device reads ~657 GB/s stream / ~175 TFLOP/s bf16 /
~48 GB/s random-row gather (scratch/exp_hbm_probe_r5.json) — numbers at
80-89% of datasheet that re-rate every "bandwidth-bound" analysis in the
repo. The matmul slope must use iteration counts ≥64: below that the
per-iteration time itself is nonlinear (ramp effects) and a small-iters
pair over-reads by ~3x.

Reference analog: the tiled brute-force design is sized against real
measured HBM (detail/knn_brute_force.cuh:61).
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..ops.autotune import measure_value_read_wall

__all__ = ["probe", "matmul_tflops", "hbm_stream_gbps", "gather_gbps",
           "dispatch_us", "dispatch_split"]


def _slope(make_fn, make_inputs, i1: int, i2: int) -> float:
    """Per-iteration seconds from a two-point fit of t(iters)."""
    times = {}
    for iters in (i1, i2):
        fn = make_fn(iters)
        ins = make_inputs(3)      # warm + 2 timed, all content-distinct
        times[iters] = measure_value_read_wall(fn, ins[1:],
                                               warm_input=ins[0])
    return (times[i2] - times[i1]) / (i2 - i1)


def matmul_tflops(n: int = 8192, dtype=jnp.bfloat16,
                  i1: int = 64, i2: int = 192) -> float:
    """Sustained TFLOP/s of chained n×n×n matmuls, slope-fitted.

    The chain c ← c @ (b/√n) keeps magnitudes stable and makes every
    matmul depend on the previous one — XLA cannot drop iterations."""
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
    bs = (b / jnp.sqrt(float(n))).astype(dtype)

    def make(iters):
        # bs rides as an ARGUMENT: closing over it would bake a 128-256 MB
        # HLO constant into the program and trip the tunnel's request-size
        # limit (HTTP 413)
        @jax.jit
        def f(a, bs):
            def body(_, c):
                return jax.lax.dot_general(
                    c, bs, (((1,), (0,)), ((), ())),
                    preferred_element_type=dtype)
            return jax.lax.fori_loop(0, iters, body, a)
        return lambda a: f(a, bs)

    def inputs(m):
        return [jax.random.normal(jax.random.PRNGKey(20 + j), (n, n),
                                  jnp.float32).astype(dtype)
                for j in range(m)]

    return 2.0 * n ** 3 / _slope(make, inputs, i1, i2) / 1e12


def hbm_stream_gbps(mbytes: int = 1024, i1: int = 64, i2: int = 256
                    ) -> float:
    """Sustained HBM GB/s on a chained read+write f32 stream.

    Each iteration rescales the full buffer with an iteration-dependent
    factor large enough to change every f32 value (not elidable)."""
    rows = (mbytes << 20) // 4 // 1024
    traffic = 2.0 * 4 * rows * 1024      # read + write per iteration

    def make(iters):
        @jax.jit
        def f(x):
            def body(i, c):
                s = 1.0 + (2.0 ** -6) * (i % 3 + 1).astype(jnp.float32)
                return c * s
            return jax.lax.fori_loop(0, iters, body, x)
        return f

    def inputs(m):
        return [jax.random.normal(jax.random.PRNGKey(10 + j),
                                  (rows, 1024), jnp.float32)
                for j in range(m)]

    return traffic / _slope(make, inputs, i1, i2) / 1e9


def gather_gbps(tbl_rows: int = 1 << 20, row_d: int = 128,
                g_rows: int = 1 << 18, i1: int = 16, i2: int = 64
                ) -> float:
    """Effective GB/s of iteration-dependent random-row gathers (the
    traffic shape of CAGRA hops and IVF-PQ refine)."""
    tbl = jax.random.normal(jax.random.PRNGKey(3), (tbl_rows, row_d),
                            jnp.float32)

    def make(iters):
        @jax.jit
        def f(x, t):
            def body(i, c):
                # the carry folds into the index base so the gather chain
                # is INPUT-dependent — an index stream derived from the
                # loop counter alone is value-identical across calls and
                # a replaying backend could serve it from cache
                iu = i.astype(jnp.uint32) + c[0].astype(jnp.uint32)
                base = iu * jnp.uint32(1315423911) + jnp.uint32(2654435761)
                idx = (base + jnp.arange(g_rows, dtype=jnp.uint32)
                       * jnp.uint32(2654435761)) % jnp.uint32(tbl_rows)
                g = jnp.take(t, idx.astype(jnp.int32), axis=0)
                return c + g.sum(axis=0)
            return jax.lax.fori_loop(0, iters, body, x)

        return lambda x: f(x, tbl)

    def inputs(m):
        return [jnp.zeros((row_d,), jnp.float32) + j for j in range(m)]

    return g_rows * row_d * 4 / _slope(make, inputs, i1, i2) / 1e9


def dispatch_us(reps: int = 11) -> float:
    """Median round-trip of a trivial dispatch (1-element add + sync).

    Deliberately NOT amortized: this is the per-call constant the slope
    probes cancel, reported so readers can judge how much of any
    per-call latency is transport."""
    from ..ops.autotune import measure as _median_time

    x = jnp.zeros((8, 128), jnp.float32)

    @jax.jit
    def f(x):
        return x + 1.0

    return _median_time(f, x, reps=reps) * 1e6


def dispatch_split(reps: int = 32) -> dict:
    """The ISSUE 12 decomposition of the dispatch constant: first-call
    vs amortized.

    ``dispatch_once_us`` is the round trip of the FIRST post-compile
    dispatch of a fresh executable (program upload + the full
    dispatch+sync transport) — what an un-warmed serving bucket or a
    per-hop kernel-launch loop pays. ``dispatch_steady_us`` is the
    amortized per-dispatch cost of ``reps`` back-to-back asynchronous
    dispatches closed by ONE sync — what a pipelined (double-buffered)
    serving loop or the one-dispatch megakernel actually pays per call.
    The gap between the two is the attribution the megakernel's win
    needs: a big once/steady ratio says the fixed per-launch cost, not
    the kernel math, bounded the old per-hop path."""
    import time as _time

    x = jnp.zeros((8, 128), jnp.float32)

    def f(x):
        return x + 1.0

    # fresh executable per probe run (a lambda is a distinct jit cache
    # key per call of dispatch_split, so re-probes stay honest)
    g = jax.jit(lambda a: f(a) * 1.0)
    compiled = g.lower(x).compile()
    t0 = _time.perf_counter()
    jax.block_until_ready(compiled(x))
    once = _time.perf_counter() - t0
    # steady: back-to-back async dispatches, one closing sync; each
    # call feeds the next so the chain cannot be collapsed
    y = x
    t0 = _time.perf_counter()
    for _ in range(reps):
        y = compiled(y)
    jax.block_until_ready(y)
    steady = (_time.perf_counter() - t0) / reps
    return {"dispatch_once_us": round(once * 1e6, 1),
            "dispatch_steady_us": round(steady * 1e6, 1)}


def probe(quick: bool = False) -> Dict[str, float]:
    """Measure this device's effective peaks via slope fits. ~8 compiles;
    each probe streams seconds of device work so the fit is stable.

    ``quick`` trims the large-iters points (shorter windows, same
    method); the matmul pair stays ≥64 — see the module docstring."""
    mm = (64, 128) if quick else (64, 192)
    st = (64, 160) if quick else (64, 256)
    ga = (16, 48) if quick else (16, 64)
    return {
        "matmul_bf16_tflops": round(matmul_tflops(dtype=jnp.bfloat16,
                                                  i1=mm[0], i2=mm[1]), 1),
        "matmul_f32_tflops": round(matmul_tflops(dtype=jnp.float32,
                                                 i1=mm[0], i2=mm[1]), 1),
        "hbm_stream_gbps": round(hbm_stream_gbps(
            mbytes=512 if quick else 1024, i1=st[0], i2=st[1]), 1),
        "gather_gbps": round(gather_gbps(i1=ga[0], i2=ga[1]), 1),
        "dispatch_us": round(dispatch_us(), 1),
        # first-call vs amortized split (ISSUE 12): attributes how much
        # of dispatch_us is per-launch fixed cost a pipelined/one-shot
        # dispatch path amortizes away
        **dispatch_split(),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(probe()))
