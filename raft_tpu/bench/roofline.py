"""On-device roofline probe: measured peaks, not assumed ones.

The bench reports kernel throughput as a fraction of the *measured* peak of
the device actually in use (matmul TFLOP/s, HBM stream GB/s), because
assumed per-generation limits (e.g. v5e datasheet numbers) can be off by
orders of magnitude under remote/tunneled or simulated backends.

Methodology: every probe runs its hot op ``iters`` times INSIDE one
compiled program (``lax.fori_loop`` with an iteration-dependent,
non-foldable carry), so the device window is hundreds of milliseconds and
the tunnel's ~90 ms dispatch round trip (see ``dispatch_us``) amortizes
away — a single 8192³ matmul is ~6 ms of MXU time and would otherwise
read as ~12 TFLOP/s on a chip whose true bf16 peak is an order of
magnitude higher. Timing is ``ops.autotune.measure`` (per-call blocked,
median); inputs are generated on device — host↔device transfer never
enters the timing. The loop carry feeds every iteration from the previous
one, so no iteration can be elided or hoisted.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..ops.autotune import measure as _median_time

__all__ = ["probe", "matmul_tflops", "hbm_stream_gbps", "dispatch_us"]


def matmul_tflops(n: int = 8192, dtype=jnp.bfloat16, reps: int = 5,
                  iters: int = 32) -> float:
    """Sustained TFLOP/s of ``iters`` chained n×n×n matmuls in one program.

    The chain c ← c @ (b/√n) keeps magnitudes stable (b ~ N(0,1), so
    b/√n has unit spectral scale in expectation) and makes every matmul
    depend on the previous one — XLA cannot drop or reorder iterations.
    """
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.float32).astype(dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
    bs = (b / jnp.sqrt(float(n))).astype(dtype)

    @jax.jit
    def f(a, bs):
        def body(_, c):
            return jax.lax.dot_general(
                c, bs, (((1,), (0,)), ((), ())),
                preferred_element_type=dtype)
        return jax.lax.fori_loop(0, iters, body, a)

    dt = _median_time(f, a, bs, reps=reps)
    return 2.0 * n ** 3 * iters / dt / 1e12


def hbm_stream_gbps(mbytes: int = 1024, reps: int = 5,
                    iters: int = 32) -> float:
    """Sustained HBM GB/s on a chained read+write stream.

    Each iteration reads and rewrites the full buffer with an
    iteration-dependent scale (not constant-foldable across the loop), so
    traffic per iteration is 2 × buffer bytes.
    """
    # (rows, 1024) rather than flat (n,): 1-D buffers lane-tile poorly
    # and understate streaming bandwidth
    n = (mbytes << 20) // 4
    x = jax.random.normal(jax.random.PRNGKey(2), (n // 1024, 1024),
                          jnp.float32)

    @jax.jit
    def f(x):
        def body(i, c):
            # one-ulp-scale, i-dependent factor: must exceed f32's
            # 2^-24 so the multiply actually changes values (1 + 1e-9
            # rounds to exactly 1.0f and the loop would be a bitwise
            # identity a value-analyzing backend could elide)
            return c * (1.0 + (2.0 ** -23) * (i + 1).astype(jnp.float32))
        return jax.lax.fori_loop(0, iters, body, x)

    dt = _median_time(f, x, reps=reps)
    return 2.0 * 4.0 * (n // 1024) * 1024 * iters / dt / 1e9


def dispatch_us(reps: int = 11) -> float:
    """Median round-trip of a trivial dispatch (1-element add + sync).

    Deliberately NOT amortized: this is the per-call overhead number the
    amortized probes are defending against, reported so readers can judge
    how much of any per-call latency is transport."""
    x = jnp.zeros((8, 128), jnp.float32)

    @jax.jit
    def f(x):
        return x + 1.0

    return _median_time(f, x, reps=reps) * 1e6


def probe(quick: bool = False) -> Dict[str, float]:
    """Measure this device's effective peaks. ~4 compiles; the amortized
    loops put a few hundred ms of device work behind each dispatch."""
    reps = 3 if quick else 5
    iters = 16 if quick else 32
    return {
        "matmul_bf16_tflops": round(matmul_tflops(dtype=jnp.bfloat16,
                                                  reps=reps, iters=iters), 1),
        "matmul_f32_tflops": round(matmul_tflops(dtype=jnp.float32,
                                                 reps=reps, iters=iters), 1),
        "hbm_stream_gbps": round(hbm_stream_gbps(
            mbytes=256 if quick else 1024, reps=reps, iters=iters), 1),
        "dispatch_us": round(dispatch_us(), 1),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(probe()))
