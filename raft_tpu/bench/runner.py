"""Param-sweep benchmark runner emitting Google-Benchmark-schema JSON.

Reference: cpp/bench/ann/src/common/benchmark.hpp:320-371 — per-case
counters {Recall, Latency, QPS=items_per_second, end_to_end}; algo/param
sweeps from raft-ann-bench YAML configs
(raft-ann-bench/run/conf/*.json); the same schema here so the
reference's data_export/plot tooling (and ours in plot.py) applies.
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.errors import expects

__all__ = ["BenchResult", "default_configs", "run_benchmarks"]


@dataclasses.dataclass
class BenchResult:
    name: str                      # e.g. "raft_ivf_flat.nlist1024.nprobe20"
    algo: str
    build_time: float
    search_params: Dict[str, Any]
    qps: float
    latency_s: float
    recall: float
    k: int
    batch_size: int

    def to_gbench(self) -> Dict[str, Any]:
        """One Google-Benchmark `benchmarks[]` entry (benchmark.hpp:337)."""
        return {
            "name": f"{self.name}/search",
            "run_type": "iteration",
            "real_time": self.latency_s,
            "time_unit": "s",
            "items_per_second": self.qps,
            "Recall": self.recall,
            "Latency": self.latency_s,
            "end_to_end": self.latency_s,
            "k": self.k,
            "n_queries": self.batch_size,
            "GPU": 0.0,
            "build_time": self.build_time,
        }


def _bf_case(base, metric, dtype="float32"):
    from ..neighbors import brute_force

    def build():
        return brute_force.build(base, metric, dtype=dtype)

    def make_search(index, k):
        def fn(q):
            return brute_force.search(index, q, k)
        return fn

    return build, make_search, [{}]


def _ivf_flat_case(base, metric, n_lists, probe_sweep, dtype="float32"):
    from ..neighbors import ivf_flat

    def build():
        return ivf_flat.build(base, ivf_flat.IndexParams(
            n_lists=n_lists, metric=metric, dtype=dtype))

    def make_search(index, k, n_probes=20):
        sp = ivf_flat.SearchParams(n_probes=n_probes)

        def fn(q):
            return ivf_flat.search(index, q, k, sp)
        return fn

    return build, make_search, [{"n_probes": p} for p in probe_sweep]


def _ivf_pq_case(base, metric, n_lists, pq_dim, probe_sweep):
    from ..neighbors import ivf_pq

    def build():
        return ivf_pq.build(base, ivf_pq.IndexParams(
            n_lists=n_lists, pq_dim=pq_dim, metric=metric))

    def make_search(index, k, n_probes=20):
        sp = ivf_pq.SearchParams(n_probes=n_probes)

        def fn(q):
            return ivf_pq.search(index, q, k, sp)
        return fn

    return build, make_search, [{"n_probes": p} for p in probe_sweep]


def _cagra_case(base, metric, graph_degree, itopk_sweep):
    from ..neighbors import cagra

    def build():
        return cagra.build(base, cagra.IndexParams(
            graph_degree=graph_degree,
            intermediate_graph_degree=graph_degree * 2, metric=metric))

    def make_search(index, k, itopk=64):
        sp = cagra.SearchParams(itopk_size=itopk)

        def fn(q):
            return cagra.search(index, q, k, sp)
        return fn

    return build, make_search, [{"itopk": t} for t in itopk_sweep]


def default_configs(base, metric, algos: Sequence[str],
                    n_lists: Optional[int] = None,
                    pq_dim: Optional[int] = None,
                    probe_sweep: Optional[Sequence[int]] = None,
                    cagra_degree: int = 32,
                    itopk_sweep: Optional[Sequence[int]] = None,
                    dtype: str = "float32"):
    """The raft-ann-bench default tuning envelopes
    (docs/ann_benchmarks_param_tuning.md:10-96) scaled to dataset size;
    every envelope overridable to pin a BASELINE.md config exactly."""
    n = len(base)
    if n_lists is None:
        n_lists = max(64, min(4096, int(np.sqrt(n) * 2)))
    if pq_dim is None:
        pq_dim = max(8, (base.shape[1] // 2 // 8) * 8 or 8)
    if probe_sweep is None:
        probe_sweep = [1, 2, 5, 10, 20, 50, 100]
    if itopk_sweep is None:
        itopk_sweep = [32, 64, 128, 256]
    cases = {}
    for a in algos:
        dtag = "" if dtype == "float32" else f".{dtype}"
        if a == "raft_brute_force":
            cases[a] = (_bf_case(base, metric, dtype), dtag.lstrip("."))
        elif a == "raft_ivf_flat":
            cases[a] = (_ivf_flat_case(base, metric, n_lists,
                                       list(probe_sweep), dtype),
                        f"nlist{n_lists}{dtag}")
        elif a == "raft_ivf_pq":
            cases[a] = (_ivf_pq_case(base, metric, n_lists, pq_dim,
                                     list(probe_sweep)),
                        f"nlist{n_lists}.pq{pq_dim}")
        elif a == "raft_cagra":
            cases[a] = (_cagra_case(base, metric, cagra_degree,
                                    list(itopk_sweep)),
                        f"degree{cagra_degree}")
        else:
            expects(False, "unknown algo %r", a)
    return cases


def run_benchmarks(
    base: np.ndarray,
    queries: np.ndarray,
    gt_indices: np.ndarray,
    k: int = 10,
    metric: str = "sqeuclidean",
    algos: Sequence[str] = ("raft_brute_force", "raft_ivf_flat",
                            "raft_ivf_pq", "raft_cagra"),
    batch_size: Optional[int] = None,
    reps: int = 5,
    verbose: bool = True,
    dtype: str = "float32",
) -> List[BenchResult]:
    """Build + sweep search params per algo; measure QPS and recall@k."""
    import jax
    import jax.numpy as jnp

    from .. import stats
    from ..ops import autotune

    base = np.asarray(base, np.float32)
    queries = np.asarray(queries, np.float32)
    if dtype == "uint8":
        mn, mx = float(base.min()), float(base.max())
        sample = base[:: max(1, len(base) // 4096)]
        maybe_bytes = (mn >= 0 and mx <= 255
                       and np.all(sample == np.round(sample)))
        # full integrality scan only when the sample says bytes (float
        # corpora — the remap path — never pay it); chunked with
        # early-exit so no full-corpus temporary is materialized. Without
        # it a corpus with sparse fractional rows would skip the remap
        # and crash in the builder's byte validation mid-bench
        def _all_integral(a, rows=1 << 16):
            return all(np.array_equal(c, np.round(c))
                       for c in (a[i : i + rows]
                                 for i in range(0, len(a), rows)))

        if not (maybe_bytes and _all_integral(base)):
            # uint8 storage is exact bytes only: discretize float corpora
            # to the byte grid via an affine map applied to base AND
            # queries. The shared shift preserves L2 distance ordering
            # only — and only the dtype-consuming algos may be in the run
            # (ivf_pq/cagra would otherwise silently benchmark remapped
            # data vs original gt).
            from ..distance.distance_types import (DistanceType,
                                                   canonical_metric)

            expects(canonical_metric(metric) in (
                        DistanceType.L2Expanded, DistanceType.L2SqrtExpanded),
                    "uint8 on a float corpus requires an L2 metric (the "
                    "byte-grid shift reorders cosine/IP neighbors); got %r",
                    metric)
            expects(set(algos) <= {"raft_brute_force", "raft_ivf_flat"},
                    "uint8 on a float corpus: restrict --algorithms to "
                    "raft_brute_force/raft_ivf_flat (other algos ignore "
                    "dtype and would run on remapped data vs original gt)")
            scale = 255.0 / max(mx - mn, 1e-30)
            base = np.round((base - mn) * scale).astype(np.float32)
            queries = ((queries - mn) * scale).astype(np.float32)
    gt = np.asarray(gt_indices)[:, :k]
    if batch_size:
        queries = queries[:batch_size]
        gt = gt[:batch_size]
    expects(len(gt) == len(queries), "gt/queries length mismatch")

    results: List[BenchResult] = []
    for algo, ((build, make_search, sweep), tag) in default_configs(
            base, metric, algos, dtype=dtype).items():
        t0 = time.perf_counter()
        index = build()
        jax.block_until_ready(jax.tree.leaves(index))
        build_time = time.perf_counter() - t0
        if verbose:
            print(f"# {algo}: built in {build_time:.2f}s")
        for params in sweep:
            fn = make_search(index, k, **params)
            d, i = fn(queries)                      # warmup + compile
            jax.block_until_ready((d, i))
            # per-call-blocked median with per-rep input perturbation —
            # value-identical replays have been observed served from a
            # tunnel-side result cache (autotune.measure docstring);
            # out0 reuses the warmup above instead of re-warming
            qj = jnp.asarray(queries, jnp.float32)
            dt = autotune.measure(fn, qj, reps=reps, out0=(d, i))
            recall = float(stats.neighborhood_recall(np.asarray(i)[:, :k], gt))
            ptag = ".".join(f"{kk}{vv}" for kk, vv in params.items())
            name = ".".join(x for x in (algo, tag, ptag) if x)
            results.append(BenchResult(
                name=name, algo=algo, build_time=build_time,
                search_params=dict(params), qps=len(queries) / dt,
                latency_s=dt, recall=recall, k=k, batch_size=len(queries)))
            if verbose:
                r = results[-1]
                print(f"#   {name}: qps={r.qps:,.0f} recall@{k}={r.recall:.4f}")
    return results


def to_gbench_json(results: List[BenchResult], context: Dict[str, Any]
                   ) -> str:
    """Full Google-Benchmark JSON document (context + benchmarks[])."""
    return json.dumps({
        "context": context,
        "benchmarks": [r.to_gbench() for r in results],
    }, indent=2)
