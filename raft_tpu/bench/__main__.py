"""CLI: ``python -m raft_tpu.bench <subcommand>``.

Mirrors the raft-ann-bench subcommands (run/__main__.py:141-256):
``groundtruth`` (generate_groundtruth), ``run``, ``export``
(data_export: GBench JSON → CSV with pareto marking), ``plot``
(QPS-vs-recall curves).
"""
from __future__ import annotations

import argparse
import csv
import json
import platform
import sys
import time
from pathlib import Path

import numpy as np


def _cmd_groundtruth(args):
    from .datasets import generate_groundtruth, load_dataset, write_fbin, write_ibin

    base, queries, _, metric = load_dataset(args.dataset, args.dataset_dir)
    d, i = generate_groundtruth(base, queries, args.k, metric)
    out = Path(args.output or f"{args.dataset}.gt")
    out.mkdir(parents=True, exist_ok=True)
    write_ibin(out / "groundtruth.neighbors.ibin", i)
    write_fbin(out / "groundtruth.distances.fbin", d)
    print(f"wrote ground truth (k={args.k}) to {out}/")


def _cmd_run(args):
    import jax

    from .datasets import generate_groundtruth, load_dataset
    from .runner import run_benchmarks, to_gbench_json

    base, queries, gt, metric = load_dataset(args.dataset, args.dataset_dir)
    if args.metric:
        metric = args.metric
    if gt is None or gt.shape[1] < args.k:
        print("# generating ground truth (exact brute force)...")
        _, gt = generate_groundtruth(base, queries, max(args.k, 100), metric)
    results = run_benchmarks(
        base, queries, gt, k=args.k, metric=metric,
        algos=args.algorithms.split(","), batch_size=args.batch_size,
        reps=args.reps, dtype=args.dtype)
    context = {
        "date": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "dataset": args.dataset,
        "host_name": platform.node(),
        "executable": "raft_tpu.bench",
        "num_cpus": 0,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }
    doc = to_gbench_json(results, context)
    out = Path(args.output or f"{args.dataset}.bench.json")
    out.write_text(doc)
    print(f"wrote {len(results)} benchmark cases to {out}")


def _cmd_lane(args):
    from .datasets import resolve_lane_dataset

    name, kind = resolve_lane_dataset(args.dataset_dir, args.budget_rows)
    if kind == "synthetic-fallback":
        print(f"# lane: SIFT-1M absent from dataset dir -> {name} "
              "(synthetic fallback; NOT a comparable number)")
    else:
        print(f"# lane: {name} ({kind})")
    args.dataset = name
    args.output = args.output or f"lane.{name}.bench.json"
    _cmd_run(args)
    # stamp how the lane resolved into the artifact, so a fallback run
    # can never be mistaken for a real SIFT-1M number downstream
    out = Path(args.output)
    doc = json.loads(out.read_text())
    doc.setdefault("context", {})["lane"] = {"dataset": name, "kind": kind}
    out.write_text(json.dumps(doc, indent=2))


def _pareto(points):
    """Mark pareto-optimal (recall, qps) points (data_export analog)."""
    best = []
    for idx, (r, q) in enumerate(points):
        dominated = any(r2 >= r and q2 > q or r2 > r and q2 >= q
                        for r2, q2 in points)
        best.append(not dominated)
    return best


def _cmd_export(args):
    doc = json.loads(Path(args.input).read_text())
    rows = doc["benchmarks"]
    by_algo = {}
    for r in rows:
        algo = r["name"].split(".")[0]
        by_algo.setdefault(algo, []).append(r)
    out = Path(args.output or Path(args.input).with_suffix(".csv"))
    with open(out, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["algo", "name", "recall", "qps", "latency_s",
                    "build_time", "pareto"])
        for algo, rs in by_algo.items():
            flags = _pareto([(r["Recall"], r["items_per_second"])
                             for r in rs])
            for r, p in zip(rs, flags):
                w.writerow([algo, r["name"], r["Recall"],
                            r["items_per_second"], r["Latency"],
                            r.get("build_time", ""), int(p)])
    print(f"wrote {out}")


def _cmd_plot(args):
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    doc = json.loads(Path(args.input).read_text())
    by_algo = {}
    for r in doc["benchmarks"]:
        algo = r["name"].split(".")[0]
        by_algo.setdefault(algo, []).append((r["Recall"],
                                             r["items_per_second"]))
    fig, ax = plt.subplots(figsize=(8, 6))
    for algo, pts in sorted(by_algo.items()):
        pts.sort()
        ax.plot([p[0] for p in pts], [p[1] for p in pts], "o-", label=algo)
    ax.set_xlabel(f"recall@k")
    ax.set_ylabel("QPS")
    ax.set_yscale("log")
    ax.set_title(doc.get("context", {}).get("dataset", ""))
    ax.grid(True, alpha=0.3)
    ax.legend()
    out = Path(args.output or Path(args.input).with_suffix(".png"))
    fig.savefig(out, dpi=120, bbox_inches="tight")
    print(f"wrote {out}")


def main(argv=None):
    p = argparse.ArgumentParser(prog="python -m raft_tpu.bench")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("groundtruth", help="exact GT via brute force")
    g.add_argument("--dataset", required=True)
    g.add_argument("--dataset-dir", default=None)
    g.add_argument("-k", type=int, default=100)
    g.add_argument("--output", default=None)
    g.set_defaults(fn=_cmd_groundtruth)

    r = sub.add_parser("run", help="run QPS@recall sweeps")
    r.add_argument("--dataset", required=True)
    r.add_argument("--dataset-dir", default=None)
    r.add_argument("--algorithms",
                   default="raft_brute_force,raft_ivf_flat,raft_ivf_pq,"
                           "raft_cagra")
    r.add_argument("-k", type=int, default=10)
    r.add_argument("--batch-size", type=int, default=None)
    r.add_argument("--reps", type=int, default=5)
    r.add_argument("--metric", default=None)
    r.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16", "int8", "uint8"],
                   help="dataset storage dtype (brute force / ivf_flat)")
    r.add_argument("--output", default=None)
    r.set_defaults(fn=_cmd_run)

    ln = sub.add_parser(
        "lane", help="standing SIFT-1M Pareto lane (synthetic fallback)")
    ln.add_argument("--dataset-dir", default=None)
    ln.add_argument("--budget-rows", type=int, default=100_000,
                    help="synthetic fallback corpus rows when SIFT absent")
    ln.add_argument("--algorithms",
                    default="raft_brute_force,raft_ivf_flat,raft_ivf_pq,"
                            "raft_cagra")
    ln.add_argument("-k", type=int, default=10)
    ln.add_argument("--batch-size", type=int, default=None)
    ln.add_argument("--reps", type=int, default=5)
    ln.add_argument("--metric", default=None)
    ln.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "int8", "uint8"])
    ln.add_argument("--output", default=None)
    ln.set_defaults(fn=_cmd_lane)

    e = sub.add_parser("export", help="GBench JSON → CSV + pareto")
    e.add_argument("--input", required=True)
    e.add_argument("--output", default=None)
    e.set_defaults(fn=_cmd_export)

    pl = sub.add_parser("plot", help="QPS-vs-recall curves")
    pl.add_argument("--input", required=True)
    pl.add_argument("--output", default=None)
    pl.set_defaults(fn=_cmd_plot)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
