"""Dataset IO + ground truth for the bench harness.

Formats (raft-ann-bench get_dataset/split_groundtruth):
- ``.fbin``/``.ibin``: big-ann-benchmarks binary — int32 (n, d) header then
  row-major f32/i32 payload.
- ann-benchmarks ``.hdf5``: train/test/neighbors/distances datasets.
- synthetic specs: ``blobs-{n}x{d}``, ``uniform-{n}x{d}`` generated with
  raft_tpu.random (no network in the TPU environment; real corpora can be
  dropped into the dataset dir as fbin/hdf5).
"""
from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..core.errors import expects

__all__ = ["read_fbin", "write_fbin", "read_ibin", "write_ibin",
           "iter_fbin", "load_dataset", "resolve_lane_dataset",
           "generate_groundtruth"]


def _read_bin(path, dtype) -> np.ndarray:
    with open(path, "rb") as f:
        n, d = np.fromfile(f, np.int32, 2)
        return np.fromfile(f, dtype, int(n) * int(d)).reshape(int(n), int(d))


def _write_bin(path, arr, dtype) -> None:
    arr = np.ascontiguousarray(arr, dtype)
    with open(path, "wb") as f:
        np.asarray(arr.shape, np.int32).tofile(f)
        arr.tofile(f)


def read_fbin(path) -> np.ndarray:
    return _read_bin(path, np.float32)


def write_fbin(path, arr) -> None:
    _write_bin(path, arr, np.float32)


def iter_fbin(path, batch_rows: int = 1 << 17):
    """Stream an fbin file in bounded row batches via mmap — the
    out-of-core reader for corpora larger than host memory (DEEP-1B /
    wiki-all class; feeds ivf_*.build_from_batches). Host memory stays
    O(batch_rows * d)."""
    with open(path, "rb") as f:
        n, d = np.fromfile(f, np.int32, 2)
    n, d = int(n), int(d)
    mm = np.memmap(path, np.float32, mode="r", offset=8, shape=(n, d))
    for b0 in range(0, n, batch_rows):
        yield np.asarray(mm[b0 : b0 + batch_rows])


def read_ibin(path) -> np.ndarray:
    return _read_bin(path, np.int32)


def write_ibin(path, arr) -> None:
    _write_bin(path, arr, np.int32)


_SYNTH = re.compile(r"^(blobs|uniform)-(\d+)x(\d+)$")


def load_dataset(
    name: str,
    dataset_dir: Optional[str] = None,
    n_queries: int = 10_000,
    seed: int = 1234,
) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], str]:
    """→ (base, queries, gt_indices or None, metric).

    ``name`` is a synthetic spec (``blobs-1000000x128``), an
    ann-benchmarks HDF5 basename (``sift-128-euclidean`` with
    ``{name}.hdf5`` in ``dataset_dir``), or a big-ann layout directory
    (``{name}/base.fbin``, ``query.fbin``, optional
    ``groundtruth.neighbors.ibin``). Metric is inferred: "-angular"/"-dot"
    → inner-product family, else sqeuclidean (the raft-ann-bench mapping).
    """
    dataset_dir = dataset_dir or os.environ.get(
        "RAFT_TPU_DATASET_DIR", "datasets")
    m = _SYNTH.match(name)
    if m:
        kind, n, d = m.group(1), int(m.group(2)), int(m.group(3))
        from .. import random as rrnd
        rng = rrnd.RngState(seed)
        if kind == "blobs":
            base, _ = rrnd.make_blobs(n + n_queries, d,
                                      n_clusters=max(16, d // 2),
                                      cluster_std=3.0, rng=rng)
            base = np.asarray(base)
        else:
            base = np.asarray(rrnd.uniform(rng, (n + n_queries, d)))
        return base[:n], base[n:], None, "sqeuclidean"

    h5 = Path(dataset_dir) / f"{name}.hdf5"
    if h5.exists():
        import h5py

        with h5py.File(h5, "r") as f:
            base = np.asarray(f["train"], np.float32)
            queries = np.asarray(f["test"], np.float32)
            gt = (np.asarray(f["neighbors"], np.int32)
                  if "neighbors" in f else None)
        # ann-benchmarks conventions: -angular ground truth is cosine
        # distance (NOT raw dot product — unnormalized vectors rank
        # differently); -dot is inner product
        if name.endswith("-angular"):
            metric = "cosine"
        elif name.endswith("-dot"):
            metric = "inner_product"
        else:
            metric = "sqeuclidean"
        return base, queries, gt, metric

    d = Path(dataset_dir) / name
    if (d / "base.fbin").exists():
        base = read_fbin(d / "base.fbin")
        queries = read_fbin(d / "query.fbin")
        gtp = d / "groundtruth.neighbors.ibin"
        gt = read_ibin(gtp) if gtp.exists() else None
        return base, queries, gt, "sqeuclidean"

    expects(False, "dataset %r not found (no synthetic match, %s, or %s)",
            name, str(h5), str(d / "base.fbin"))


# big-ann dataset-dir names accepted as "the" SIFT-1M corpus, in
# preference order (get_dataset drops it as sift-1m; older mirrors use
# sift1m/sift)
_LANE_FBIN_NAMES = ("sift-1m", "sift1m", "sift")
_LANE_HDF5_NAME = "sift-128-euclidean"


def resolve_lane_dataset(
    dataset_dir: Optional[str] = None,
    budget_rows: int = 100_000,
) -> Tuple[str, str]:
    """→ (dataset name for :func:`load_dataset`, kind).

    The *standing Pareto lane* (ROADMAP item 2a) runs on SIFT-1M so
    every perf PR moves a number the community recognizes. Resolution
    order: a big-ann fbin dir (``sift-1m/base.fbin``, the
    raft-ann-bench ``get_dataset`` layout), then the ann-benchmarks
    HDF5 (``sift-128-euclidean.hdf5``), else a small-budget synthetic
    fallback (``blobs-{budget_rows}x128`` — SIFT's dim, bounded rows)
    so zero-egress environments still exercise the full pipeline.
    ``kind`` is ``"fbin"`` / ``"hdf5"`` / ``"synthetic-fallback"`` —
    lane artifacts record it so a fallback run can never be mistaken
    for a real SIFT number.
    """
    dataset_dir = dataset_dir or os.environ.get(
        "RAFT_TPU_DATASET_DIR", "datasets")
    root = Path(dataset_dir)
    for cand in _LANE_FBIN_NAMES:
        if (root / cand / "base.fbin").exists():
            return cand, "fbin"
    if (root / f"{_LANE_HDF5_NAME}.hdf5").exists():
        return _LANE_HDF5_NAME, "hdf5"
    return f"blobs-{int(budget_rows)}x128", "synthetic-fallback"


def generate_groundtruth(base, queries, k: int = 100,
                         metric: str = "sqeuclidean",
                         batch: int = 10_000) -> Tuple[np.ndarray, np.ndarray]:
    """Exact kNN ground truth on-device (generate_groundtruth CLI analog;
    the reference also uses its own brute force for this)."""
    import jax

    from ..neighbors import brute_force

    index = brute_force.build(np.asarray(base, np.float32), metric)
    outs_d, outs_i = [], []
    for b0 in range(0, len(queries), batch):
        d, i = brute_force.search(index, queries[b0 : b0 + batch], k)
        jax.block_until_ready((d, i))
        outs_d.append(np.asarray(d))
        outs_i.append(np.asarray(i))
    return np.concatenate(outs_d), np.concatenate(outs_i)
