"""Honest (value-varying) decomposition of the prune-batch cost.
Every rep uses a different nodes window so the tunnel replay cache
cannot serve it."""
import os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import cagra

n, d0, B, deg = 100_000, 96, 7281, 64
k1 = jax.random.PRNGKey(0)
graph = jax.random.randint(k1, (n, d0), 0, n, jnp.int32)
graph_sorted = jnp.sort(graph, axis=1)
jax.block_until_ready((graph, graph_sorted))
print("chip:", jax.devices()[0].device_kind, flush=True)

def t(label, f, nargs=1):
    # warm/compile on window 0, then time distinct windows
    jax.block_until_ready(f(jnp.arange(B, dtype=jnp.int32)))
    ts = []
    for r in range(1, 4):
        nd = jnp.arange(r, B + r, dtype=jnp.int32)
        jax.block_until_ready(nd)
        t0 = time.perf_counter()
        jax.block_until_ready(f(nd))
        ts.append(time.perf_counter() - t0)
    print(f"{label}: {min(ts)*1e3:.0f} ms  (all: "
          f"{[round(x*1e3) for x in ts]})", flush=True)

# full prune batch
t("full _prune_batch", lambda nd: cagra._prune_batch(graph, nd, deg))

# gather stage only
@jax.jit
def gather_only(gs, g, nd):
    nbrs = g[nd]
    nbr_rows = gs[nbrs]
    return jnp.sum(nbr_rows, dtype=jnp.int32) + jnp.sum(nbrs)
t("gather only", lambda nd: gather_only(graph_sorted, graph, nd))

# gather + searchsorted, no detour/argsort tail
@jax.jit
def gather_ss(gs, g, nd):
    nbrs = g[nd]
    nbr_rows = gs[nbrs]
    rows2 = nbr_rows.reshape(B * d0, d0)
    tgts2 = jnp.broadcast_to(nbrs[:, None, :], (B, d0, d0)).reshape(
        B * d0, d0)
    rows2, tgts2 = jax.lax.optimization_barrier((rows2, tgts2))
    pos = jax.vmap(jnp.searchsorted)(rows2, tgts2)
    return jnp.sum(pos, dtype=jnp.int32)
t("gather+barrier+searchsorted", lambda nd: gather_ss(
    graph_sorted, graph, nd))

# same but unrolled binary search
@jax.jit
def gather_bin(gs, g, nd):
    nbrs = g[nd]
    nbr_rows = gs[nbrs]
    rows2 = nbr_rows.reshape(B * d0, d0)
    tgts2 = jnp.broadcast_to(nbrs[:, None, :], (B, d0, d0)).reshape(
        B * d0, d0)
    rows2, tgts2 = jax.lax.optimization_barrier((rows2, tgts2))
    lo = jnp.zeros(tgts2.shape, jnp.int32)
    hi = jnp.full(tgts2.shape, d0, jnp.int32)
    for _ in range(8):
        mid = jnp.minimum((lo + hi) // 2, d0 - 1)
        vals = jnp.take_along_axis(rows2, mid, axis=1)
        go = vals < tgts2
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(go, hi, mid)
    return jnp.sum(lo, dtype=jnp.int32)
t("gather+barrier+unrolled-bin", lambda nd: gather_bin(
    graph_sorted, graph, nd))

# detour-count tail alone (on device-created random inputs, varying)
@jax.jit
def tail_only(hit, det_seed):
    adj = hit.reshape(B, d0, d0)
    tri = jnp.tril(jnp.ones((d0, d0), bool), k=-1).T
    det = jnp.sum(adj & tri[None], axis=1) + det_seed
    key = det * d0 + jnp.arange(d0, dtype=jnp.int32)[None, :]
    order = jnp.argsort(key, axis=1, stable=True)[:, :deg]
    return jnp.sum(order, dtype=jnp.int32)
hit0 = jax.random.bernoulli(k1, 0.1, (B * d0, d0))
jax.block_until_ready(hit0)
t("detour+argsort tail", lambda nd: tail_only(hit0, nd[0]))
