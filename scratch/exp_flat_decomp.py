"""Round-5 prep: honest decomposition of the warm flat np20 wall at the
500k part shape (~50 ms). Each stage is timed with the value-read wall
on content-distinct inputs; stages are cut at the real function
boundaries (coarse probe, full search, search-minus-merge isn't directly
separable, so the kernel+grouping block is inferred)."""
import os, sys
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.ops import fused_knn
from raft_tpu.ops.ivf_scan import _ivf_flat_scan_jit, pack_pairs
from raft_tpu.ops.autotune import measure_value_read_wall

def log(m): print(m, file=sys.stderr, flush=True)

n, d, nq, k, di = 500_000, 128, 10_000, 10, 16
kw, kc, kx, ka, kq, kp, ke, kf = jax.random.split(jax.random.PRNGKey(0), 8)
w = jax.random.normal(kw, (di, d)); w = w / jnp.linalg.norm(w, axis=1, keepdims=True)
cz = jax.random.normal(kc, (200, di))
z = cz[jax.random.randint(ka, (n,), 0, 200)] + jax.random.normal(kx, (n, di))
data = z @ w + 0.1 * jax.random.normal(ke, (n, d))
qz = cz[jax.random.randint(kq, (nq,), 0, 200)] + jax.random.normal(kp, (nq, di))
queries = qz @ w + 0.1 * jax.random.normal(kf, (nq, d))
jax.block_until_ready((data, queries))

fi = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=1024, seed=0))
jax.block_until_ready(jax.tree.leaves(fi))
ivf_flat.prepare_scan(fi)
log("# built")

def wall(tp, calls=8, rounds=2):
    best = None
    for r in range(rounds):
        perms = [jnp.take(queries, jax.random.permutation(
            jax.random.PRNGKey(100 + 50 * r + i), nq), axis=0)
            for i in range(calls + 1)]
        jax.block_until_ready(perms)
        dt = measure_value_read_wall(tp, perms[:-1], warm_input=perms[-1])
        best = dt if best is None else min(best, dt)
    return best

# stage A: coarse probe only (fused_knn over 1024 centers)
coarse = jax.jit(lambda q, c, cn: fused_knn(q, c, 20, metric="l2",
                                            data_norms=cn)[1])
dt = wall(lambda p: coarse(p, fi.centers, fi.center_norms))
log(f"# A coarse probe: {dt*1e3:.1f}ms")

# stage B: full search (coarse + grouping + kernel + merge)
fn = jax.jit(lambda q, idx: ivf_flat.search(
    idx, q, k, ivf_flat.SearchParams(n_probes=20)))
dt = wall(lambda p: fn(p, fi))
log(f"# B full search: {dt*1e3:.1f}ms")

# stage C: grouping chain alone (pack_pairs on a fixed probed set,
# content varied via the probe ids derived from permuted queries)
probed_fn = jax.jit(lambda q, c, cn: fused_knn(q, c, 20, metric="l2",
                                               data_norms=cn)[1])
group = jax.jit(lambda pr: pack_pairs(pr, 1024)[0])
dt = wall(lambda p: group(probed_fn(p, fi.centers, fi.center_norms)))
log(f"# C coarse+grouping: {dt*1e3:.1f}ms")
