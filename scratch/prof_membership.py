"""Microbench: sorted-membership test formulations for _detour_counts.

Candidates at the real shape (B*d0, d0) = (~700k, 96):
  a) current vmap(jnp.searchsorted)            (10.7 s measured)
  b) manual unrolled binary search (log2 d0 take_along_axis steps)
  c) double lax.sort_key_val (concat + sort, tag sort back)
"""
import os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np

R, d0 = 699_000, 96
k1, k2 = jax.random.split(jax.random.PRNGKey(0))
rows = jnp.sort(jax.random.randint(k1, (R, d0), 0, 100_000, jnp.int32), axis=1)
tgts = jax.random.randint(k2, (R, d0), 0, 100_000, jnp.int32)
jax.block_until_ready((rows, tgts))
print("chip:", jax.devices()[0].device_kind, flush=True)

def t(label, fn, *a):
    f = jax.jit(fn)
    r = jax.block_until_ready(f(*a))   # compile
    t0 = time.perf_counter()
    r = jax.block_until_ready(f(*a))
    dt1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    r = jax.block_until_ready(f(*a))
    dt2 = time.perf_counter() - t0
    print(f"{label}: {min(dt1, dt2)*1e3:.0f} ms", flush=True)
    return r

def hit_a(rows, tgts):
    pos = jax.vmap(jnp.searchsorted)(rows, tgts)
    return jnp.take_along_axis(rows, jnp.minimum(pos, d0 - 1), axis=1) == tgts

def hit_b(rows, tgts):
    lo = jnp.zeros(tgts.shape, jnp.int32)
    hi = jnp.full(tgts.shape, d0, jnp.int32)
    for _ in range(8):  # 2^8 > 96
        mid = jnp.minimum((lo + hi) // 2, d0 - 1)
        vals = jnp.take_along_axis(rows, mid, axis=1)
        go = vals < tgts
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(go, hi, mid)
    return jnp.take_along_axis(rows, jnp.minimum(lo, d0 - 1), axis=1) == tgts

def hit_c(rows, tgts):
    keys = jnp.concatenate([rows, tgts], axis=1)
    tags = jnp.concatenate(
        [jnp.zeros((1, d0), jnp.int32),
         jnp.arange(1, d0 + 1, dtype=jnp.int32)[None, :]], axis=1)
    tags = jnp.broadcast_to(tags, keys.shape)
    sk, st = jax.lax.sort_key_val(keys, tags, dimension=1)
    left = jnp.pad(sk[:, :-1], ((0, 0), (1, 0)), constant_values=-1)
    lt = jnp.pad(st[:, :-1], ((0, 0), (1, 0)), constant_values=-1)
    right = jnp.pad(sk[:, 1:], ((0, 0), (0, 1)), constant_values=-1)
    rt = jnp.pad(st[:, 1:], ((0, 0), (0, 1)), constant_values=-1)
    # a tagged (target) entry is a member iff an adjacent equal key is a
    # rows entry (tag 0) or an adjacent equal target that is a member...
    # equal runs: a run containing ANY tag-0 entry makes all targets in
    # the run members. Use segmented max of (tag == 0) over equal runs.
    is_rows = (st == 0).astype(jnp.int32)
    new_run = sk != jnp.pad(sk[:, :-1], ((0, 0), (1, 0)),
                            constant_values=-(2**31))
    run_id = jnp.cumsum(new_run.astype(jnp.int32), axis=1)
    # segmented max via two cummax passes (forward suffices with runs
    # ordered): member if any rows entry in same run seen forward or
    # backward — do forward cummax on run boundaries then backward
    def seg_or(flags, run_id):
        fwd = jax.lax.associative_scan(
            lambda a, b: (jnp.where(b[1] == a[1], jnp.maximum(a[0], b[0]),
                                    b[0]), b[1]),
            (flags, run_id), axis=1)
        rev = jax.lax.associative_scan(
            lambda a, b: (jnp.where(b[1] == a[1], jnp.maximum(a[0], b[0]),
                                    b[0]), b[1]),
            (flags[:, ::-1], run_id[:, ::-1]), axis=1)
        return jnp.maximum(fwd[0], rev[0][:, ::-1])
    member = seg_or(is_rows, run_id)
    # scatter back by tag order: sort (tag, member) by tag
    st2, m2 = jax.lax.sort_key_val(st, member, dimension=1)
    return (m2[:, d0:] > 0)

ra = t("a) vmap searchsorted", hit_a, rows, tgts)
rb = t("b) unrolled binsearch", hit_b, rows, tgts)
rc = t("c) double sort", hit_c, rows, tgts)
print("b == a:", bool(jnp.all(ra == rb)))
print("c == a:", bool(jnp.all(ra == rc)))
