#!/usr/bin/env python
"""Standalone runner for the raft_tpu static-analysis gate.

The same three passes ``tests/test_analysis.py`` gates on —
kernel_audit / hotpath_audit / lock_lint (docs/analysis.md) — runnable
outside pytest so the pod session and CI can export findings and
rebaseline without a test run (the scratch/check_tier1_durations.py
pattern).

Usage::

    python scratch/run_analysis.py                    # human report
    python scratch/run_analysis.py --json out.jsonl   # findings JSONL
    python scratch/run_analysis.py --update-baseline  # rebaseline
    python scratch/run_analysis.py --passes kernel    # one pass only

Exit codes: 0 clean vs baseline, 1 new (or stale-baselined) findings,
2 usage/environment error.

``--json`` writes one JSON object per line: every finding (rule, path,
symbol, line, message, baselined flag) followed by one ``kind:
"kernel_report"`` line per audited kernel variant (VMEM footprint,
grid, DMA counts) — the pod session diffs these against the
interpret-trace expectations after the first real-TPU compile.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

# mirror tests/conftest.py: the ring-kernel variant traces under
# shard_map on the virtual multi-device CPU mesh
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", metavar="PATH",
                    help="export findings + kernel reports as JSONL")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite analysis/baseline.json from this run")
    ap.add_argument("--passes", default="kernel,hotpath,lock",
                    help="comma-separated subset of kernel,hotpath,lock")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")   # trace-only; never TPU

    from raft_tpu import analysis

    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    bad = set(passes) - {"kernel", "hotpath", "lock"}
    if bad:
        print(f"unknown passes: {sorted(bad)}", file=sys.stderr)
        return 2

    reports: list = []
    findings = analysis.run_all(passes=passes, kernel_reports=reports)
    verdict = analysis.compare(findings, passes=passes)
    base = set(analysis.load_baseline())

    if args.json:
        with open(args.json, "w") as f:
            for fd in findings:
                f.write(json.dumps({
                    "kind": "finding", "rule": fd.rule, "path": fd.path,
                    "symbol": fd.symbol, "line": fd.line,
                    "message": fd.message,
                    "baselined": fd.key in base}) + "\n")
            for r in reports:
                f.write(json.dumps(
                    {"kind": "kernel_report",
                     **dataclasses.asdict(r)}) + "\n")
        print(f"wrote {len(findings)} findings + {len(reports)} kernel "
              f"reports -> {args.json}")

    if args.update_baseline:
        # partial runs merge into (never wipe) the other passes' slice
        keys = analysis.merged_baseline_keys(findings, passes)
        with open(analysis.baseline_path(), "w") as f:
            json.dump({
                "findings": keys,
                "policy": "zero NEW findings; prune stale keys when "
                          "fixes land",
                "note": "kernel-audit entries are pre-hardware warnings "
                        "on interpret-only kernels; re-judge each on the "
                        "first real-TPU session (ROADMAP 'Hardware-gated "
                        "verdicts')"}, f, indent=1)
            f.write("\n")
        print(f"baseline updated: {len(keys)} findings -> "
              f"{analysis.baseline_path()}")
        return 0

    by_key = {fd.key: fd for fd in findings}
    for key in verdict["baselined"]:
        print(f"BASELINED {by_key[key].render()}")
    for key in verdict["new"]:
        print(f"NEW       {by_key[key].render()}")
    for key in verdict["stale"]:
        print(f"STALE     {key}")
    print(f"# {verdict['count']} findings over {len(reports)} audited "
          f"kernel configs: {len(verdict['new'])} new, "
          f"{len(verdict['baselined'])} baselined, "
          f"{len(verdict['stale'])} stale baseline entries")
    if verdict["new"] or verdict["stale"]:
        print("FAIL: fix, waive with '# lint: waive(<rule>): <reason>', "
              "or rerun with --update-baseline (see docs/analysis.md)",
              file=sys.stderr)
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
