"""Round-4 experiment: PQ scan cost vs pq_bits / lut mode on the real chip.

Latency (per-call-blocked median) AND pipelined throughput (the tunnel's
~90-110 ms dispatch floor dominates per-call numbers at these corpus
sizes) for:
  - flat np5 (the bar: PQ must beat this)
  - pq64  b8  bf16/int8 (current bench config + fp8-LUT role)
  - pq128 b4  bf16/int8 (same 512 bits/row, 8x narrower one-hot)
all with refine r2 at nprobe=20, plus scan-only variants.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, refine
from raft_tpu.ops.autotune import measure, measure_throughput

def log(m):
    print(m, file=sys.stderr, flush=True)

n, d, nq, k = 200_000, 128, 10_000, 10
kc, kx, ka, kq, kp = jax.random.split(jax.random.PRNGKey(0), 5)
centers = jax.random.normal(kc, (2000, d), jnp.float32) * 4.0
assign = jax.random.randint(ka, (n,), 0, 2000)
data = centers[assign] + jax.random.normal(kx, (n, d), jnp.float32)
# fresh mixture queries (NOT corpus perturbations): real recall frontier
qassign = jax.random.randint(kq, (nq,), 0, 2000)
queries = centers[qassign] + jax.random.normal(kp, (nq, d), jnp.float32)
jax.block_until_ready((data, queries))
log("# corpus ready")

bfi = brute_force.build(data, metric="sqeuclidean")
gt_fn = jax.jit(lambda q, idx: brute_force.search(idx, q, k, algo="matmul")[1])
gt = jax.block_until_ready(gt_fn(queries, bfi))
log("# gt done")

def recall(ids):
    hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
    return float(jnp.sum(hit) / jnp.sum(gt >= 0))

out = {}

def bench_fn(tag, fn, *args):
    try:
        lat = measure(fn, *args, reps=5, suspect_floor_s=0.002)
        thr = measure_throughput(fn, *args, depth=6, reps=3,
                                 suspect_floor_s=0.002)
        rec = recall(fn(*args)[1])
    except Exception as e:
        log(f"# {tag} failed: {type(e).__name__}: {e}")
        return
    out[tag] = dict(lat_ms=lat*1e3, thr_ms=thr*1e3, lat_qps=nq/lat,
                    thr_qps=nq/thr, recall=rec)
    log(f"# {tag}: lat {lat*1e3:.1f}ms ({nq/lat:,.0f}qps) "
        f"thr {thr*1e3:.1f}ms ({nq/thr:,.0f}qps) r={rec:.4f}")

# --- ivf_flat np5: the bar ---
t0 = time.perf_counter()
fi = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=1024, seed=0))
jax.block_until_ready(jax.tree.leaves(fi))
ivf_flat.prepare_scan(fi)
log(f"# flat built {time.perf_counter()-t0:.0f}s")
for probes in (5, 20):
    fn = jax.jit(lambda q, idx, p=probes: ivf_flat.search(
        idx, q, k, ivf_flat.SearchParams(n_probes=p)))
    bench_fn(f"flat_np{probes}", fn, queries, fi)

# --- ivf_pq configs ---
for name, pqd, bits in (("pq64b8", 64, 8), ("pq128b4", 128, 4)):
    t0 = time.perf_counter()
    pi = ivf_pq.build(data, ivf_pq.IndexParams(
        n_lists=1024, pq_dim=pqd, pq_bits=bits, seed=0))
    jax.block_until_ready(jax.tree.leaves(pi))
    build_s = time.perf_counter() - t0
    ivf_pq.prepare_scan(pi)
    log(f"# {name} built {build_s:.0f}s")
    for lut in ("bf16", "int8"):
        def fn_body(q, idx, dd, lu=lut):
            _, cand = ivf_pq.search(
                idx, q, 2 * k, ivf_pq.SearchParams(n_probes=20, lut_dtype=lu))
            return refine.refine(dd, q, cand, k)
        bench_fn(f"{name}_{lut}_np20_r2", jax.jit(fn_body), queries, pi, data)
    # scan-only int8 to isolate kernel cost
    sfn = jax.jit(lambda q, idx: ivf_pq.search(
        idx, q, k, ivf_pq.SearchParams(n_probes=20, lut_dtype="int8")))
    bench_fn(f"{name}_int8_scanonly_np20", sfn, queries, pi)

print(json.dumps(out, indent=1))
