#!/usr/bin/env python
"""Bench-lane artifact validator: machine-check ``bench_*.json`` files.

``bench.py`` lanes that write acceptance artifacts (currently the
``fleet_ladder`` lane behind ``RAFT_TPU_BENCH_FLEET_LADDER``) self-check
while they run, but the ARTIFACT is what lands in review — this script
re-derives the acceptance criteria from the file alone, so a stale,
truncated, or hand-edited artifact fails loudly.

All lanes:

* the whole file is strict JSON (``allow_nan=False`` round-trip) with
  schema ``raft_tpu_bench_v1`` and a recognised ``lane``.

``fleet_ladder`` lane (ISSUE 19, docs/mnmg.md "Per-host storage tiers"):

* one entry per storage rung, in ladder order
  (float32 -> int8 -> int4 -> pq), named ``fleet_ladder.<topo>.<rung>``;
* per-host device bytes are monotone non-increasing down the ladder and
  every narrower rung's ``bytes_vs_float32`` is < 1;
* exact rungs (float32/int8/int4) carry ``bitwise_vs_unbudgeted`` true
  and identical budgeted/unbudgeted recall — a capacity number from a
  build that changed the answers is worthless;
* the pq rung holds >= 0.95x its unbudgeted refined recall AND serves
  from <= 1/4 the per-host device bytes of the fully-resident float32
  build (``bytes_vs_float32_resident``) — the headline capacity claim;
* every rung that spilled lists cold stays near the per-host budget
  (resident bytes <= 1.25x budget: quantizer/offset overhead rides on
  top of the row budget, a 2x overshoot means the planner is broken).

``sharded_dispatch`` lane (ISSUE 20, docs/perf.md "Sharded dispatch",
written by ``scratch/run_fleet_dryrun.py``):

* steady-state repeat calls compile ZERO XLA programs
  (``programs_per_call_steady == 0``) — the one-trace acceptance;
* the uncached baseline (``programs_per_call_before``) compiles at
  least one program per call, or the comparison is vacuous;
* results are bitwise-equal between the cached and uncached dispatch
  and the steady-state dispatch p50 is present and positive.

Usage::

    python scratch/check_bench_artifact.py artifacts/bench_fleet_ladder.json

Exit status: 0 = valid, 1 = acceptance failure, 2 = unreadable/schema.
"""
from __future__ import annotations

import argparse
import json
import sys

RUNGS = ("float32", "int8", "int4", "pq")
ENTRY_KEYS = ("algo", "name", "qps", "recall", "recall_unbudgeted",
              "store", "topology", "rows_per_host",
              "device_bytes_per_host",
              "device_bytes_per_host_unbudgeted",
              "host_tier_bytes_per_host", "bytes_per_vector",
              "hbm_budget_bytes_per_host", "cold_lists_per_host",
              "bitwise_vs_unbudgeted")


def check_fleet_ladder(art: dict, errs: list) -> str:
    entries = art.get("entries", [])
    by_store = {e.get("store"): e for e in entries}
    if [e.get("store") for e in entries] != list(RUNGS):
        errs.append(f"expected one entry per rung {RUNGS}, got "
                    f"{[e.get('store') for e in entries]}")
        return ""
    topo = art.get("topology")
    budget = art.get("hbm_budget_bytes_per_host")
    for e in entries:
        missing = [k for k in ENTRY_KEYS if k not in e]
        if missing:
            errs.append(f"{e.get('name')}: missing keys {missing}")
            continue
        if e["name"] != f"fleet_ladder.{topo}.{e['store']}":
            errs.append(f"entry name {e['name']!r} does not match "
                        f"lane topology {topo!r}")
        if e["hbm_budget_bytes_per_host"] != budget:
            errs.append(f"{e['name']}: per-entry budget "
                        f"{e['hbm_budget_bytes_per_host']} != lane "
                        f"budget {budget}")
        if not (isinstance(e["qps"], (int, float)) and e["qps"] > 0):
            errs.append(f"{e['name']}: qps not positive: {e['qps']!r}")

    # -- ladder monotonicity ----------------------------------------------
    for a, b in zip(RUNGS, RUNGS[1:]):
        ba = by_store[a]["device_bytes_per_host"]
        bb = by_store[b]["device_bytes_per_host"]
        if bb > ba:
            errs.append(f"ladder not monotone: {b} uses {bb:,} B/host "
                        f"> {a} {ba:,}")
    for rung in RUNGS[1:]:
        r = by_store[rung].get("bytes_vs_float32")
        if not (isinstance(r, (int, float)) and r < 1.0):
            errs.append(f"{rung}: bytes_vs_float32 {r!r} not < 1")

    # -- exact rungs: budgeting must not change the answers ----------------
    for rung in ("float32", "int8", "int4"):
        e = by_store[rung]
        if e["bitwise_vs_unbudgeted"] is not True:
            errs.append(f"{rung}: bitwise_vs_unbudgeted is "
                        f"{e['bitwise_vs_unbudgeted']!r}")
        if e["recall"] != e["recall_unbudgeted"]:
            errs.append(f"{rung}: budgeted recall {e['recall']} != "
                        f"unbudgeted {e['recall_unbudgeted']}")

    # -- pq rung: refined recall floor + the 1/4-capacity claim ------------
    pq = by_store["pq"]
    if pq["recall_unbudgeted"] <= 0:
        errs.append("pq: unbudgeted recall is zero")
    elif pq["recall"] < 0.95 * pq["recall_unbudgeted"]:
        errs.append(f"pq: budgeted recall {pq['recall']} < 0.95x "
                    f"unbudgeted {pq['recall_unbudgeted']}")
    rr = pq.get("bytes_vs_float32_resident")
    if not (isinstance(rr, (int, float)) and rr <= 0.25):
        errs.append(f"pq: bytes_vs_float32_resident {rr!r} not <= 0.25 "
                    f"(the per-host capacity acceptance)")

    # -- budget respected wherever the planner spilled cold ----------------
    for e in entries:
        cold = sum(e["cold_lists_per_host"].values())
        if cold and e["device_bytes_per_host"] > 1.25 * budget:
            errs.append(f"{e['name']}: {cold} cold lists yet "
                        f"{e['device_bytes_per_host']:,} B/host > 1.25x "
                        f"budget {budget:,}")

    pq_r = by_store["pq"].get("bytes_vs_float32")
    return (f"{len(entries)} rungs on {topo}, budget {budget:,} B/host, "
            f"pq at {pq_r}x of f32 bytes with recall {pq['recall']} "
            f"({pq['recall_unbudgeted']} unbudgeted)")


def check_sharded_dispatch(art: dict, errs: list) -> str:
    for key in ("programs_per_call_before", "programs_per_call_steady",
                "dispatch_p50_ms", "bitwise_equal", "m", "k"):
        if key not in art:
            errs.append(f"missing key {key!r}")
    if errs:
        return ""
    steady = art["programs_per_call_steady"]
    before = art["programs_per_call_before"]
    p50 = art["dispatch_p50_ms"]
    if steady != 0:
        errs.append(f"steady-state repeat call compiled {steady!r} XLA "
                    "programs (must be exactly 0 — the one-trace "
                    "acceptance)")
    if not (isinstance(before, int) and before > 0):
        errs.append(f"uncached baseline compiled {before!r} programs "
                    "per call; expected > 0, else the before/after "
                    "comparison is vacuous")
    if not (isinstance(p50, (int, float)) and p50 > 0):
        errs.append(f"dispatch_p50_ms not positive: {p50!r}")
    if art["bitwise_equal"] is not True:
        errs.append(f"bitwise_equal is {art['bitwise_equal']!r}: cached "
                    "dispatch changed the answers")
    return (f"programs/call {before} -> {steady} steady-state, "
            f"p50 {p50} ms at m={art['m']} k={art['k']}")


LANES = {"fleet_ladder": check_fleet_ladder,
         "sharded_dispatch": check_sharded_dispatch}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifact", help="path to a bench lane artifact")
    args = ap.parse_args()

    try:
        with open(args.artifact) as f:
            art = json.load(f)
        json.dumps(art, allow_nan=False)
    except (OSError, ValueError) as exc:
        print(f"SCHEMA: cannot load strict-JSON artifact: {exc}")
        return 2
    lane = art.get("lane")
    if art.get("schema") != "raft_tpu_bench_v1" or lane not in LANES:
        print(f"SCHEMA: schema={art.get('schema')!r} lane={lane!r} "
              f"(known: {sorted(LANES)})")
        return 2

    errs = []
    summary = LANES[lane](art, errs)
    if errs:
        for e in errs:
            print(f"FAIL: {e}")
        return 1
    print(f"OK: {args.artifact}: lane {lane}, {summary}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
