"""Does int8 IVF-Flat storage (quarter scan traffic, per-row scales)
hold recall >=0.95 on the hard corpus at 500k? Value-read walls."""
import json, os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import brute_force, ivf_flat

def log(m): print(m, file=sys.stderr, flush=True)

n, d, nq, k, di = 500_000, 128, 10_000, 10, 16
kw, kc, kx, ka, kq, kp, ke, kf = jax.random.split(jax.random.PRNGKey(0), 8)
w = jax.random.normal(kw, (di, d)); w = w / jnp.linalg.norm(w, axis=1, keepdims=True)
cz = jax.random.normal(kc, (200, di))
z = cz[jax.random.randint(ka, (n,), 0, 200)] + jax.random.normal(kx, (n, di))
data = z @ w + 0.1 * jax.random.normal(ke, (n, d))
qz = cz[jax.random.randint(kq, (nq,), 0, 200)] + jax.random.normal(kp, (nq, di))
queries = qz @ w + 0.1 * jax.random.normal(kf, (nq, d))
jax.block_until_ready((data, queries))
bfi = brute_force.build(data, metric="sqeuclidean")
gt_fn = jax.jit(lambda q, idx: brute_force.search(idx, q, k, algo="matmul")[1])
gt = jnp.concatenate([jax.block_until_ready(gt_fn(queries[c:c+1000], bfi))
                      for c in range(0, nq, 1000)])
log("# gt done")

def recall(ids):
    hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
    return float(jnp.sum(hit) / jnp.sum(gt >= 0))

def wall(tp, calls=6):
    """Shared value-read wall (see ops/autotune.measure_value_read_wall):
    content-distinct permutations, warm outside the window."""
    from raft_tpu.ops.autotune import measure_value_read_wall
    perms = [jnp.take(queries, jax.random.permutation(
        jax.random.PRNGKey(100 + i), nq), axis=0) for i in range(calls + 1)]
    jax.block_until_ready(perms)
    return measure_value_read_wall(tp, perms[:-1], warm_input=perms[-1])

out = {}
for dtype in ("int8", "bfloat16"):
    t0 = time.perf_counter()
    fi = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=1024, seed=0,
                                                   dtype=dtype))
    jax.block_until_ready(jax.tree.leaves(fi))
    bs = time.perf_counter() - t0
    ivf_flat.prepare_scan(fi)
    log(f"# {dtype} built {bs:.0f}s")
    for probes in (20, 30, 50):
        fn = jax.jit(lambda q, idx, p=probes: ivf_flat.search(
            idx, q, k, ivf_flat.SearchParams(n_probes=p)))
        dt = wall(lambda p, f=fn: f(p, fi))
        r = recall(fn(queries, fi)[1])
        out[f"flat_{dtype}_np{probes}"] = dict(ms=dt*1e3, qps=nq and nq/dt,
                                               recall=r, build_s=bs)
        log(f"# flat {dtype} np{probes}: {dt*1e3:.1f}ms ({nq/dt:,.0f} qps) "
            f"r={r:.4f}")

print(json.dumps(out, indent=1))
