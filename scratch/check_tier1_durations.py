#!/usr/bin/env python
"""Tier-1 test-duration guard: flag wall regressions BEFORE the 870s gate.

The tier-1 suite runs under a hard 870s cap with ~35s of margin
(ROADMAP.md), so a PR that slows pre-existing tests must surface that
cost in review — not be discovered as a gate timeout. This script
compares a pytest ``--durations`` tail (the tier-1 command already
emits one into ``/tmp/_t1.log``) against the checked-in per-test
baseline and fails on UNTOUCHED tests that grew more than the
threshold.

Usage::

    # after a tier-1 run (ROADMAP command tees /tmp/_t1.log):
    python scratch/check_tier1_durations.py              # compare
    python scratch/check_tier1_durations.py --update     # rebaseline

Only ``call`` phases are compared (setup/teardown are fixture noise).
Tests whose FILE is touched in the working tree / staged diff (``git
diff --name-only HEAD``) are exempt — a PR is allowed to make the tests
it edits slower on purpose; the guard exists for collateral damage
(import-time costs, fixture contention, accidental de-caching) to
everyone else's tests. Regressions must clear BOTH the relative
threshold (default +20%) and an absolute floor (default 1.0s growth) —
host noise on sub-second tests routinely exceeds 20% (CHANGES.md
records ±45% swings), and a flag that cries wolf gets ignored.
New tests (absent from the baseline) are reported informationally.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(HERE, "tier1_durations_baseline.json")
_DUR_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)")


def parse_durations(log_path: str) -> dict:
    """pytest ``--durations`` lines → {test_id: call seconds}."""
    out: dict = {}
    with open(log_path, errors="replace") as f:
        for line in f:
            m = _DUR_RE.match(line)
            if m and m.group(2) == "call":
                out[m.group(3)] = float(m.group(1))
    return out


def touched_files(git_base: str = "HEAD") -> set:
    """Files changed in the working tree + index vs ``git_base`` —
    their tests are exempt (the PR owns their cost)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", git_base],
            capture_output=True, text=True, cwd=os.path.dirname(HERE),
            timeout=30, check=False).stdout
        return {ln.strip() for ln in diff.splitlines() if ln.strip()}
    except Exception:  # noqa: BLE001 - no git → guard everything
        return set()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--log", default="/tmp/_t1.log",
                    help="pytest log carrying the --durations tail")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--update", action="store_true",
                    help="rewrite the baseline from --log and exit")
    ap.add_argument("--threshold", type=float, default=1.2,
                    help="relative growth bar on untouched tests")
    ap.add_argument("--min-growth-s", type=float, default=1.0,
                    help="absolute growth floor (noise gate)")
    ap.add_argument("--git-base", default="HEAD",
                    help="diff base for the touched-test exemption")
    ap.add_argument("--no-git", action="store_true",
                    help="treat every test as untouched")
    args = ap.parse_args(argv)

    cur = parse_durations(args.log)
    if not cur:
        print(f"no --durations entries found in {args.log}; run the "
              "ROADMAP tier-1 command first", file=sys.stderr)
        return 2

    if args.update:
        with open(args.baseline, "w") as f:
            json.dump(dict(sorted(cur.items())), f, indent=1)
            f.write("\n")
        print(f"baseline updated: {len(cur)} tests -> {args.baseline}")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; run with --update once",
              file=sys.stderr)
        return 2
    with open(args.baseline) as f:
        base = json.load(f)

    touched = set() if args.no_git else touched_files(args.git_base)

    def is_touched(test_id: str) -> bool:
        path = test_id.split("::", 1)[0]
        return any(t.endswith(path) or path.endswith(t) for t in touched)

    flagged, grew, fresh = [], [], []
    for tid, secs in sorted(cur.items()):
        if tid not in base:
            fresh.append((tid, secs))
            continue
        b = base[tid]
        if secs > b * args.threshold and secs - b >= args.min_growth_s:
            (grew if is_touched(tid) else flagged).append((tid, b, secs))

    for tid, secs in fresh:
        print(f"NEW       {secs:7.2f}s  {tid}")
    for tid, b, secs in grew:
        print(f"TOUCHED   {b:6.2f}s -> {secs:6.2f}s  {tid}")
    for tid, b, secs in flagged:
        print(f"REGRESSED {b:6.2f}s -> {secs:6.2f}s "
              f"(+{(secs / b - 1) * 100:.0f}%)  {tid}")
    tot_b = sum(base.values())
    tot_c = sum(v for t, v in cur.items() if t in base)
    print(f"# shared-test wall: baseline {tot_b:.1f}s vs current "
          f"{tot_c:.1f}s; {len(fresh)} new, {len(flagged)} regressed "
          f"(threshold x{args.threshold}, floor "
          f"+{args.min_growth_s:g}s)")
    if flagged:
        print("FAIL: untouched tests regressed — demote to the slow "
              "lane or pay for the growth (see the tier-1 wall policy "
              "in CHANGES.md)", file=sys.stderr)
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
