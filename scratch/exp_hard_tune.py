"""Round-4 experiment 3: harder corpus (real recall frontier) + PQ tuning.

1. Pick a center scale where the nprobe sweep shows a real frontier
   (flat np20 < 1.0).
2. On that corpus, tune flat np{5,10,20} and PQ configs (int8 LUT,
   pq_bits=4, bf16 refine) for the bench headline.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, refine
from raft_tpu.ops.autotune import measure, measure_throughput

def log(m):
    print(m, file=sys.stderr, flush=True)

n, d, nq, k = 200_000, 128, 10_000, 10

def make(scale):
    kc, kx, ka, kq, kp = jax.random.split(jax.random.PRNGKey(0), 5)
    centers = jax.random.normal(kc, (2000, d), jnp.float32) * scale
    assign = jax.random.randint(ka, (n,), 0, 2000)
    data = centers[assign] + jax.random.normal(kx, (n, d), jnp.float32)
    qa = jax.random.randint(kq, (nq,), 0, 2000)
    queries = centers[qa] + jax.random.normal(kp, (nq, d), jnp.float32)
    return jax.block_until_ready(data), jax.block_until_ready(queries)

out = {"corpus": {}}

gt_fn = jax.jit(lambda q, idx: brute_force.search(idx, q, k, algo="matmul")[1])
flat_fn = {}
for p in (5, 20):
    flat_fn[p] = jax.jit(lambda q, idx, pp=p: ivf_flat.search(
        idx, q, k, ivf_flat.SearchParams(n_probes=pp)))

def frontier(scale):
    data, queries = make(scale)
    bfi = brute_force.build(data, metric="sqeuclidean")
    gt = jax.block_until_ready(gt_fn(queries, bfi))
    fi = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=1024, seed=0))
    ivf_flat.prepare_scan(fi)
    def rec(ids):
        hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
        return float(jnp.sum(hit) / jnp.sum(gt >= 0))
    r5 = rec(flat_fn[5](queries, fi)[1])
    r20 = rec(flat_fn[20](queries, fi)[1])
    log(f"# scale={scale}: flat recall np5={r5:.4f} np20={r20:.4f}")
    out["corpus"][str(scale)] = {"np5": r5, "np20": r20}
    return data, queries, bfi, gt, fi, rec, r5, r20

chosen = None
first = None
for scale in (1.5, 2.0, 2.5):
    state = frontier(scale)
    if first is None:
        first = state
    data, queries, bfi, gt, fi, rec, r5, r20 = state
    if r20 < 0.998 and r20 >= 0.9:
        chosen = scale
        break
if chosen is None:
    # fall back to the first scale WITHOUT rebuilding corpus/GT/index —
    # the loop already computed it
    chosen = 1.5
    data, queries, bfi, gt, fi, rec, r5, r20 = first
log(f"# chosen corpus scale {chosen}")
out["chosen_scale"] = chosen

data_bf16 = jnp.asarray(data, jnp.bfloat16)
jax.block_until_ready(data_bf16)

def bench_fn(tag, fn, *args):
    try:
        lat = measure(fn, *args, reps=5, suspect_floor_s=0.002)
        thr = measure_throughput(fn, *args, depth=10, reps=3,
                                 suspect_floor_s=0.002)
        r = rec(fn(*args)[1])
    except Exception as e:
        log(f"# {tag} failed: {type(e).__name__}: {e}")
        return
    out[tag] = dict(lat_ms=lat*1e3, thr_ms=thr*1e3, thr_qps=nq/thr, recall=r)
    log(f"# {tag}: lat {lat*1e3:.1f}ms thr {thr*1e3:.1f}ms "
        f"({nq/thr:,.0f}qps) r={r:.4f}")

for p in (5, 10, 20):
    fn = jax.jit(lambda q, idx, pp=p: ivf_flat.search(
        idx, q, k, ivf_flat.SearchParams(n_probes=pp)))
    bench_fn(f"flat_np{p}", fn, queries, fi)

def pq_fns(pi, probes, ratio):
    def body(q, idx, dd):
        _, cand = ivf_pq.search(
            idx, q, ratio * k,
            ivf_pq.SearchParams(n_probes=probes, lut_dtype="int8"))
        return refine.refine(dd, q, cand, k)
    return jax.jit(body)

for name, pqd, bits in (("pq128b4", 128, 4), ("pq64b4", 64, 4),
                        ("pq64b8", 64, 8)):
    t0 = time.perf_counter()
    pi = ivf_pq.build(data, ivf_pq.IndexParams(
        n_lists=1024, pq_dim=pqd, pq_bits=bits, seed=0))
    jax.block_until_ready(jax.tree.leaves(pi))
    ivf_pq.prepare_scan(pi)
    log(f"# {name} built {time.perf_counter()-t0:.0f}s")
    combos = ((10, 2), (20, 2), (20, 4)) if name == "pq128b4" else ((20, 4),)
    for probes, ratio in combos:
        bench_fn(f"{name}_i8_np{probes}_r{ratio}",
                 pq_fns(pi, probes, ratio), queries, pi, data_bf16)

print(json.dumps(out, indent=1))
