#!/usr/bin/env python
"""Run the time-compressed chaos soak and emit its verdict artifact.

The deterministic core lives in :mod:`raft_tpu.soak` — this wrapper
only parses knobs, times the wall clock (OUTSIDE the artifact, which
must stay bit-identical per seed), and prints a human summary.

Usage::

    JAX_PLATFORMS=cpu python scratch/run_soak.py                 # full drill
    JAX_PLATFORMS=cpu python scratch/run_soak.py --profile smoke
    JAX_PLATFORMS=cpu python scratch/run_soak.py --seed 3 \
        --json artifacts/soak_r16.json

Exit status: 0 on a PASS verdict, 1 on FAIL (any invariant violation).
Validate a saved artifact with ``scratch/check_soak_artifact.py``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--profile", choices=("full", "smoke"), default="full",
                    help="full = 120 sim-s canonical drill; smoke = the "
                         "72 sim-s tier-1 composition")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=None,
                    help="override sim duration_s")
    ap.add_argument("--t0", type=float, default=None,
                    help="override chaos window start (sim s)")
    ap.add_argument("--window", type=float, default=None,
                    help="override chaos window length (sim s)")
    ap.add_argument("--json", dest="json_path", default=None,
                    help="write the verdict artifact here")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir for WAL/segments/events.jsonl "
                         "(default: a fresh temp dir)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from raft_tpu.soak import SoakConfig, run_soak

    if args.profile == "smoke":
        cfg = SoakConfig.smoke(seed=args.seed)
    else:
        cfg = SoakConfig(seed=args.seed)
    overrides = {}
    if args.duration is not None:
        overrides["duration_s"] = args.duration
    if args.t0 is not None:
        overrides["chaos_t0"] = args.t0
    if args.window is not None:
        overrides["chaos_window"] = args.window
    if overrides:
        import dataclasses
        cfg = dataclasses.replace(cfg, **overrides)

    workdir = args.workdir or tempfile.mkdtemp(prefix="soak_")
    t_wall = time.monotonic()
    art = run_soak(cfg, workdir=workdir, artifact_path=args.json_path)
    wall_s = time.monotonic() - t_wall

    print(f"verdict    {art['verdict']}  "
          f"({len(art['violations'])} violations)")
    print(f"sim        {art['sim_duration_s']}s in {art['ticks']} ticks; "
          f"wall {wall_s:.1f}s "
          f"(compression {art['sim_duration_s'] / max(wall_s, 1e-9):.1f}x)")
    print(f"phases     {' -> '.join(p['name'] for p in art['phases'])}")
    for kind, v in sorted(art["mttr"].items()):
        print(f"mttr       {kind:<14} n={v['count']} "
              f"mean={v['mean_s']}s  ({v['source']})")
    for t in sorted(art["tenants"]):
        s = art["tenants"][t]
        print(f"tenant     {t:<5} rows={s['rows']:<4} req={s['requests']:<5}"
              f" served={s['served']:<5} shed={s['shed']:<4}"
              f" gen={s['generation']} qcache_hits={s['qcache_hits']}")
    for v in art["violations"][:10]:
        print(f"VIOLATION  t={v['t_s']} {v['name']} {v['detail']}")
    if args.json_path:
        print(f"artifact   {args.json_path}")
    print(f"workdir    {workdir}")
    return 0 if art["verdict"] == "PASS" else 1


if __name__ == "__main__":
    sys.exit(main())
