"""Find mixture params where the nprobe sweep shows a REAL frontier:
flat recall@np20 in [0.90, 0.99) at 200k — fewer, bigger clusters make
true neighbors straddle IVF partition boundaries (the SIFT-like regime);
2000 tight clusters are trivially recoverable at any nprobe."""
import json, os, sys
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import brute_force, ivf_flat

def log(m): print(m, file=sys.stderr, flush=True)

n, d, nq, k = 200_000, 128, 10_000, 10
out = {}
gt_fn = jax.jit(lambda q, idx: brute_force.search(idx, q, k, algo="matmul")[1])
sfn = {p: jax.jit(lambda q, idx, pp=p: ivf_flat.search(
    idx, q, k, ivf_flat.SearchParams(n_probes=pp))[1]) for p in (5, 20)}

for n_clusters, scale in ((200, 1.5), (200, 1.0), (64, 1.0), (500, 1.0)):
    kc, kx, ka, kq, kp = jax.random.split(jax.random.PRNGKey(0), 5)
    centers = jax.random.normal(kc, (n_clusters, d), jnp.float32) * scale
    assign = jax.random.randint(ka, (n,), 0, n_clusters)
    data = centers[assign] + jax.random.normal(kx, (n, d), jnp.float32)
    qa = jax.random.randint(kq, (nq,), 0, n_clusters)
    queries = centers[qa] + jax.random.normal(kp, (nq, d), jnp.float32)
    jax.block_until_ready((data, queries))
    bfi = brute_force.build(data, metric="sqeuclidean")
    gt = gt_fn(queries, bfi)
    fi = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=1024, seed=0))
    ivf_flat.prepare_scan(fi)
    def rec(ids):
        hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
        return float(jnp.sum(hit) / jnp.sum(gt >= 0))
    r5, r20 = rec(sfn[5](queries, fi)), rec(sfn[20](queries, fi))
    out[f"c{n_clusters}_s{scale}"] = {"np5": r5, "np20": r20}
    log(f"# clusters={n_clusters} scale={scale}: np5={r5:.4f} np20={r20:.4f}")

print(json.dumps(out, indent=1))
