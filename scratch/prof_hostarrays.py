"""Does a host-origin (np.asarray -> jnp.asarray) array cost a re-upload
per executable call under the axon tunnel? And which materialization
idiom fixes it?"""
import os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import cagra

n, d0, B, deg = 100_000, 96, 7281, 64
rng = np.random.default_rng(0)
knn_host = rng.integers(0, n, size=(n, d0)).astype(np.int32)
nodes = jnp.arange(B, dtype=jnp.int32)
print("chip:", jax.devices()[0].device_kind, flush=True)

def t(label, f, *a):
    r = jax.block_until_ready(f(*a))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        r = jax.block_until_ready(f(*a))
        ts.append(time.perf_counter() - t0)
    print(f"{label}: {min(ts)*1e3:.0f} ms", flush=True)
    return r

f = jax.jit(lambda g, nd: cagra._prune_batch(g, nd, deg))

# variant 1: plain jnp.asarray of host data
g1 = jnp.asarray(knn_host)
gs1 = jnp.sort(g1, axis=1)
jax.block_until_ready((g1, gs1))
t("host-origin jnp.asarray", f, g1, nodes)

# variant 2: explicit device_put
g2 = jax.device_put(knn_host, jax.devices()[0])
gs2 = jnp.sort(g2, axis=1)
jax.block_until_ready((g2, gs2))
t("device_put", f, g2, nodes)

# variant 3: force a device-computed copy
g3 = jax.jit(lambda x: x + 0)(jnp.asarray(knn_host))
gs3 = jnp.sort(g3, axis=1)
jax.block_until_ready((g3, gs3))
t("device-computed copy", f, g3, nodes)
