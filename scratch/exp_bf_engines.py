"""Honest (value-read wall) brute-force engine race at the 500k part
shape: matmul vs pallas fused vs scan, plus matmul workspace variants.
The earlier autotune pick used readiness-lying timings."""
import os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import brute_force

def log(m): print(m, file=sys.stderr, flush=True)

n, d, nq, k = 500_000, 128, 10_000, 10
k1, k2 = jax.random.split(jax.random.PRNGKey(0))
data = jax.random.normal(k1, (n, d), jnp.float32)
queries = jax.random.normal(k2, (nq, d), jnp.float32)
jax.block_until_ready((data, queries))
bfi = brute_force.build(data, metric="sqeuclidean")
bfi16 = brute_force.build(data, dtype=jnp.bfloat16)
# tile-aligned corpus resident in HBM: without this the jitted pallas
# path pays a corpus pad copy inside every call
brute_force.prepare_fused(bfi)
brute_force.prepare_fused(bfi16)
log("# built")

def wall(tp, calls=4):
    """Shared value-read wall (see ops/autotune.measure_value_read_wall):
    content-distinct permutations, warm outside the window."""
    from raft_tpu.ops.autotune import measure_value_read_wall
    perms = [jnp.take(queries, jax.random.permutation(
        jax.random.PRNGKey(100 + i), nq), axis=0) for i in range(calls + 1)]
    jax.block_until_ready(perms)
    return measure_value_read_wall(tp, perms[:-1], warm_input=perms[-1])

for name, algo, idx, ws in (
        ("matmul", "matmul", bfi, None),
        ("matmul.ws4096", "matmul", bfi, 4096),
        ("pallas", "pallas", bfi, None),
        ("scan", "scan", bfi, None),
        ("matmul.bf16", "matmul", bfi16, None),
        ("pallas.bf16", "pallas", bfi16, None)):
    kw = {"workspace_mb": ws} if ws else {}
    fn = jax.jit(lambda q, ii, a=algo, kww=tuple(sorted(kw.items())):
                 brute_force.search(ii, q, k, algo=a, **dict(kww)))
    try:
        dt = wall(lambda p, f=fn, ii=idx: f(p, ii))
        log(f"# {name}: {dt*1e3:.1f}ms/call ({nq/dt:,.0f} qps)")
    except Exception as e:
        log(f"# {name}: FAIL {type(e).__name__}: {e}")
