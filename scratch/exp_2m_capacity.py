"""PQ capacity demo: 2M rows on one chip via 4x500k parts (the corpus
at which raw f32 storage pressures HBM and PQ's 8x compression is the
point — the reference's DEEP-1B positioning), plus a CAGRA mid-point
sweep at 500k for a better 0.95-recall anchor. Value-read walls."""
import json, os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import brute_force, cagra, ivf_pq, refine

def log(m): print(m, file=sys.stderr, flush=True)

n, d, nq, k, part_n = 2_000_000, 128, 10_000, 10, 500_000
di = 16
kw, kc, kx, ka, kq, kp, ke, kf = jax.random.split(jax.random.PRNGKey(5), 8)
w = jax.random.normal(kw, (di, d)); w = w / jnp.linalg.norm(w, axis=1, keepdims=True)
cz = jax.random.normal(kc, (200, di))
z = cz[jax.random.randint(ka, (n,), 0, 200)] + jax.random.normal(kx, (n, di))
data = z @ w + 0.1 * jax.random.normal(ke, (n, d))
qz = cz[jax.random.randint(kq, (nq,), 0, 200)] + jax.random.normal(kp, (nq, di))
queries = qz @ w + 0.1 * jax.random.normal(kf, (nq, d))
jax.block_until_ready((data, queries))
parts = [data[i*part_n:(i+1)*part_n] for i in range(4)]
offsets = [i * part_n for i in range(4)]
log("# 2M corpus ready")

out = {}

# ground truth: 4-part exact with one executable
bfs = [brute_force.build(p, metric="sqeuclidean") for p in parts]
gt_fn = jax.jit(lambda q, idx: brute_force.search(idx, q, k, algo="matmul"))
merge = jax.jit(lambda dv, iv: brute_force.knn_merge_parts(dv, iv, True))
def exact(qs):
    ds, is_ = [], []
    for bfi, off in zip(bfs, offsets):
        dd, ii = gt_fn(qs, bfi)
        ds.append(dd); is_.append(jnp.where(ii >= 0, ii + off, -1))
    return merge(jnp.stack(ds), jnp.stack(is_))
gt = jnp.concatenate([jax.block_until_ready(exact(queries[c:c+1000])[1])
                      for c in range(0, nq, 1000)])
log("# gt done")

def recall(ids):
    hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
    return float(jnp.sum(hit) / jnp.sum(gt >= 0))

# 4-part PQ build
t0 = time.perf_counter()
pis = [ivf_pq.build(p, ivf_pq.IndexParams(n_lists=1024, pq_dim=128,
                                          pq_bits=4, seed=0))
       for p in parts]
jax.block_until_ready(jax.tree.leaves(pis))
build_s = time.perf_counter() - t0
for pi in pis:
    ivf_pq.prepare_scan(pi)
parts_bf16 = [jnp.asarray(p, jnp.bfloat16) for p in parts]
jax.block_until_ready(parts_bf16)
log(f"# 4x500k pq built in {build_s:.0f}s")

code_bytes = sum(int(np.prod(pi.codes.shape)) for pi in pis)
raw_bytes = n * d * 4
log(f"# compression: {raw_bytes/1e9:.2f} GB raw f32 -> "
    f"{code_bytes/1e9:.2f} GB codes (+norms/books)")

def pq_tp(probes, ratio):
    sp = ivf_pq.SearchParams(n_probes=probes, lut_dtype="int8")
    def body(q, idx, dd):
        _, cand = ivf_pq.search(idx, q, ratio * k, sp)
        return refine.refine(dd, q, cand, k)
    fn = jax.jit(body)
    def tp(q, *_):
        ds, is_ = [], []
        for pi, pb, off in zip(pis, parts_bf16, offsets):
            dd, ii = fn(q, pi, pb)
            ds.append(dd); is_.append(jnp.where(ii >= 0, ii + off, -1))
        return merge(jnp.stack(ds), jnp.stack(is_))
    return tp

def wall(tp, calls=4):
    """Shared value-read wall (see ops/autotune.measure_value_read_wall):
    content-distinct permutations, warm outside the window."""
    from raft_tpu.ops.autotune import measure_value_read_wall
    perms = [jnp.take(queries, jax.random.permutation(
        jax.random.PRNGKey(100 + i), nq), axis=0) for i in range(calls + 1)]
    jax.block_until_ready(perms)
    return measure_value_read_wall(tp, perms[:-1], warm_input=perms[-1])

for probes, ratio in ((20, 2), (50, 2)):
    tp = pq_tp(probes, ratio)
    dt = wall(tp)
    r = recall(tp(queries)[1])
    out[f"pq2M_np{probes}_r{ratio}"] = dict(
        ms=dt*1e3, qps=nq/dt, recall=r, build_s=build_s,
        corpus_n=n, code_gb=code_bytes/1e9, raw_gb=raw_bytes/1e9)
    log(f"# pq 2M np{probes} r{ratio}: {dt*1e3:.1f}ms ({nq/dt:,.0f} qps) "
        f"r={r:.4f}")

# free 2M structures before cagra
del bfs, pis, parts_bf16, data, parts

# --- CAGRA mid-point sweep at 500k ---
cdata = np.asarray(z[:part_n] @ w + 0.0)   # rebuild part-A-like corpus
del z
cdata = jnp.asarray(cdata) + 0.1 * jax.random.normal(ke, (part_n, d))
jax.block_until_ready(cdata)
cgt_bfi = brute_force.build(cdata, metric="sqeuclidean")
cgt = jnp.concatenate([
    jax.block_until_ready(gt_fn(queries[c:c+1000], cgt_bfi)[1])
    for c in range(0, nq, 1000)])
def crecall(ids):
    hit = jnp.any(ids[:, :, None] == cgt[:, None, :], axis=2) & (cgt >= 0)
    return float(jnp.sum(hit) / jnp.sum(cgt >= 0))
t0 = time.perf_counter()
ci = cagra.build(np.asarray(cdata), cagra.IndexParams(
    graph_degree=64, intermediate_graph_degree=96, seed=0))
jax.block_until_ready(jax.tree.leaves(ci))
log(f"# cagra 500k built in {time.perf_counter()-t0:.0f}s")
cagra.prepare_search(ci)
for itopk, width, mi in ((32, 4, 4), (48, 4, 5), (24, 6, 4), (32, 6, 4)):
    sp = cagra.SearchParams(itopk_size=itopk, search_width=width,
                            max_iterations=mi)
    fn = jax.jit(lambda q, idx, s=sp: cagra.search(idx, q, k, s))
    dt = wall(lambda p, *_: fn(p, ci))
    r = crecall(fn(queries, ci)[1])
    out[f"cagra_itopk{itopk}_w{width}_mi{mi}"] = dict(
        ms=dt*1e3, qps=nq/dt, recall=r)
    log(f"# cagra itopk{itopk} w{width} mi{mi}: {dt*1e3:.1f}ms "
        f"({nq/dt:,.0f} qps) r={r:.4f}")

print(json.dumps(out, indent=1))
