#!/usr/bin/env python
"""Soak verdict-artifact validator: machine-check ``soak_r16.json``.

The soak harness self-validates while it runs (the invariant suite),
but the ARTIFACT is what lands in review — this script re-derives the
acceptance criteria from the file alone, so a stale, truncated, or
hand-edited artifact fails loudly:

* schema is ``soak/v1`` and the whole file is strict JSON
  (``allow_nan=False`` round-trip);
* the verdict is PASS and the violation list is empty (and the two
  agree);
* the phase timeline is contiguous (each phase ends where the next
  begins), covers [0, sim_duration_s), and includes a chaos AND a
  recovery phase;
* every fault kind the chaos plan armed has a finite, positive MTTR
  entry, and every armed stage actually fired;
* every tenant served traffic.

Usage::

    python scratch/check_soak_artifact.py artifacts/soak_r16.json

Exit status: 0 = valid, 1 = acceptance failure, 2 = unreadable/schema.
"""
from __future__ import annotations

import argparse
import json
import math
import sys

REQUIRED_KEYS = ("schema", "seed", "config", "sim_duration_s", "ticks",
                 "phases", "chaos", "tenants", "mttr", "violations",
                 "verdict")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifact", help="path to a soak verdict artifact")
    args = ap.parse_args()

    try:
        with open(args.artifact) as f:
            art = json.load(f)
        json.dumps(art, allow_nan=False)
    except (OSError, ValueError) as exc:
        print(f"SCHEMA: cannot load strict-JSON artifact: {exc}")
        return 2
    missing = [k for k in REQUIRED_KEYS if k not in art]
    if missing or art.get("schema") != "soak/v1":
        print(f"SCHEMA: schema={art.get('schema')!r} missing={missing}")
        return 2

    errs = []

    # -- verdict <-> violations agreement ---------------------------------
    if art["violations"]:
        errs.append(f"{len(art['violations'])} invariant violations "
                    f"(first: {art['violations'][0]})")
    if art["verdict"] != ("PASS" if not art["violations"] else "FAIL"):
        errs.append(f"verdict {art['verdict']!r} disagrees with the "
                    f"violation list")

    # -- phase timeline ----------------------------------------------------
    phases = art["phases"]
    names = [p["name"] for p in phases]
    if not phases:
        errs.append("empty phase timeline")
    else:
        if phases[0]["t0_s"] != 0.0:
            errs.append(f"timeline starts at {phases[0]['t0_s']}, not 0")
        for a, b in zip(phases, phases[1:]):
            if a["t1_s"] != b["t0_s"]:
                errs.append(f"phase gap: {a['name']} ends {a['t1_s']}, "
                            f"{b['name']} starts {b['t0_s']}")
        if phases[-1]["t1_s"] != art["sim_duration_s"]:
            errs.append(f"timeline ends {phases[-1]['t1_s']} != "
                        f"sim_duration_s {art['sim_duration_s']}")
        for need in ("chaos", "recovery"):
            if need not in names:
                errs.append(f"no {need!r} phase in timeline {names}")

    # -- chaos coverage and MTTR ------------------------------------------
    stages = art["chaos"].get("stages", [])
    armed = sorted({st["kind"] for st in stages})
    if not armed:
        errs.append("chaos plan armed no fault stages")
    for st in stages:
        if st.get("fires", 0) < 1:
            errs.append(f"armed stage never fired: {st['kind']}@"
                        f"{st.get('pattern')}")
    for kind in armed:
        m = art["mttr"].get(kind)
        if m is None:
            errs.append(f"no MTTR verdict for injected kind {kind!r}")
            continue
        if m.get("count", 0) < 1:
            errs.append(f"MTTR for {kind!r} has zero recoveries")
        mean = m.get("mean_s")
        if not (isinstance(mean, (int, float)) and math.isfinite(mean)
                and mean > 0):
            errs.append(f"MTTR for {kind!r} not finite/positive: {mean!r}")

    # -- traffic -----------------------------------------------------------
    for name, t in sorted(art["tenants"].items()):
        if t.get("served", 0) < 1:
            errs.append(f"tenant {name!r} served no traffic")

    if errs:
        for e in errs:
            print(f"FAIL: {e}")
        return 1
    print(f"OK: {args.artifact}: verdict PASS, "
          f"{len(phases)} phases over {art['sim_duration_s']} sim-s, "
          f"{len(armed)} fault kinds with finite MTTR "
          f"({', '.join(armed)}), seed {art['seed']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
