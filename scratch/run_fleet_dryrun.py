"""2-process loopback-DCN fleet dryrun: the MNMG acceptance harness.

Orchestrates three child processes of itself (the MULTICHIP-lane
pattern of tests/test_distributed.py):

- ``--ref``: ONE process, 4 virtual CPU devices, ``Fleet.virtual(2, 2)``
  — builds the distributed IVF-PQ index and searches it, printing a
  sha256 digest of the (distances, ids) bytes. The ref also measures the
  sharded-dispatch lane — XLA programs per repeat call with the
  per-index executable cache disabled (fresh jit per call) vs enabled
  (must be 0) plus the steady-state dispatch p50 — which ``main()``
  writes to ``artifacts/bench_sharded_dispatch.json``
  (checked by ``scratch/check_bench_artifact.py``).
- ``--worker`` x2: 2 virtual CPU devices each, joined over loopback DCN
  via gloo. Workers bootstrap through the ``RAFT_TPU_*`` env autodetect
  path (``bootstrap.init_distributed()`` with NO args, then
  ``Fleet.distributed()`` hitting the idempotent re-init guard), build
  the same index, and run the full degradation arc:

  1. healthy search — digest must equal the ref's (the determinism
     contract: a 2-process 2x2 fleet builds and searches BIT-IDENTICAL
     to a 1-process virtual 2x2 fleet);
  2. ``mark_host_failed(1)`` — partial results with host-granular
     ``shards_ok``, no dead-host row ids leak, and the auto-widened
     ``n_probes`` keeps recall (vs ground truth over SURVIVING rows —
     vs full GT the ceiling is served_frac, by construction) at
     >= 0.9x the healthy recall (vs full GT);
  3. ``probe_hosts()`` re-admits host 1; the post-restore search digest
     must equal the healthy one.

Exit 0 = every assertion passed on every child (or SKIPPED: the gloo
CPU-collectives clique can't form in this sandbox); exit 1 = failure.

Usage:  python scratch/run_fleet_dryrun.py
"""
import hashlib
import os
import socket
import subprocess
import sys

_HERE = os.path.abspath(__file__)
_ROOT = os.path.dirname(os.path.dirname(_HERE))

N, DIM, M, K = 2048, 16, 64, 10
N_LISTS, NPROBE = 8, 4
# per-host HBM budget for the budgeted leg: int8 rows are DIM+12=28 B,
# each host carries N/2=1024 rows = 28672 B — 16 kB forces roughly half
# of every host's lists cold (the budget arc is exercised, not skipped)
BUDGET_BYTES = 16_000


def _dataset():
    import numpy as np

    rng = np.random.default_rng(7)
    base = rng.standard_normal((N, DIM)).astype(np.float32)
    q = rng.standard_normal((M, DIM)).astype(np.float32)
    return base, q


def _params():
    from raft_tpu.neighbors import ivf_pq

    return ivf_pq.IndexParams(n_lists=N_LISTS, pq_dim=8, pq_bits=4,
                              kmeans_n_iters=6, seed=3)


def _sparams():
    from raft_tpu.neighbors import ivf_pq

    return ivf_pq.SearchParams(n_probes=NPROBE)


def _gt(base, q, k, rows=None):
    """Exact top-k ids over ``base[rows]`` (GLOBAL ids), host numpy."""
    import numpy as np

    rows = np.arange(len(base)) if rows is None else np.asarray(rows)
    sub = base[rows]
    d2 = ((q[:, None, :] - sub[None, :, :]) ** 2).sum(-1)
    return rows[np.argsort(d2, axis=1, kind="stable")[:, :k]]


def _recall(found, want):
    hits = sum(len(set(found[i].tolist()) & set(want[i].tolist()))
               for i in range(len(want)))
    return hits / want.size


def _digest(d, i):
    import numpy as np

    h = hashlib.sha256()
    h.update(np.ascontiguousarray(np.asarray(d)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(i)).tobytes())
    return h.hexdigest()


def run_ref() -> None:
    import numpy as np

    sys.path.insert(0, _ROOT)
    from raft_tpu.parallel import Fleet

    fleet = Fleet.virtual(2, 2)
    base, q = _dataset()
    idx = fleet.build_ivf_pq(base, _params())
    d, i, ok = fleet.search(idx, q, K, _sparams())
    assert list(ok) == [True] * 4, ok
    rec = _recall(np.asarray(i), _gt(base, q, K))
    print(f"REF_DIGEST {_digest(d, i)}", flush=True)

    # budgeted leg: an int8-rung build under a per-host HBM budget must
    # serve the same answers as the unbudgeted int8 build (exact rung:
    # same probed lists, same per-candidate dot products)
    from raft_tpu.neighbors import ivf_flat

    sp = ivf_flat.SearchParams(n_probes=NPROBE)
    i8 = fleet.build_ivf_pq(base, _params(), store_dtype="int8")
    d8, i8d, _ = fleet.search(i8, q, K, sp)
    bud = fleet.build_ivf_pq(base, _params(), store_dtype="int8",
                             hbm_budget_gb=BUDGET_BYTES / 2 ** 30,
                             sample_queries=q)
    assert all((~m).any() for m in bud._fleet_ctx["hot"].values()), \
        "budget did not force any cold lists"
    db, ib, okb = fleet.search(bud, q, K, sp)
    assert list(okb) == [True] * 4, okb
    # exact rung: the budgeted build must return the SAME neighbors as
    # the unbudgeted one. Ids compare bitwise; distances to a few ulp —
    # since the dispatch moved from eager per-op execution to cached
    # compiled programs (docs/perf.md "Sharded dispatch"), the hot-slab
    # and full-resident programs are differently-shaped XLA programs
    # whose fusion may associate the same f32 sums differently. The
    # cross-process digests below stay bitwise: both sides run the
    # same-shaped compiled programs.
    assert (np.asarray(ib) == np.asarray(i8d)).all(), \
        "budgeted int8 ids != unbudgeted int8 ids"
    np.testing.assert_allclose(np.asarray(db), np.asarray(d8), rtol=0,
                               atol=1e-4)
    print(f"REF_BUDGET_DIGEST {_digest(db, ib)}", flush=True)

    # sharded-dispatch lane: XLA programs per repeat call before/after
    # the per-index compiled-program cache (the PR's hard number:
    # fleet-many -> 0 steady-state) plus the steady-state dispatch p50.
    # Measured on the BUDGETED index so the cold host-streamed path is
    # in the loop, not just the resident shard_map. "before" forces the
    # uncached baseline — a fresh jit wrapper per call that re-traces
    # and re-compiles the identical (bitwise) program.
    import json
    import statistics
    import time

    import jax

    from raft_tpu.serve import warmup as wu

    os.environ["RAFT_TPU_SHARDED_DISPATCH"] = "uncached"
    try:
        fleet.search(bud, q, K, sp)      # one-time eager compiles primed
        with wu.count_compilations() as c_before:
            du, iu, _ = fleet.search(bud, q, K, sp)
        jax.block_until_ready((du, iu))
    finally:
        del os.environ["RAFT_TPU_SHARDED_DISPATCH"]
    assert _digest(du, iu) == _digest(db, ib), \
        "uncached dispatch != cached dispatch (bitwise)"
    with wu.count_compilations() as c_steady:
        ds, js, _ = fleet.search(bud, q, K, sp)
    jax.block_until_ready((ds, js))
    assert c_steady.count == 0, \
        f"steady-state repeat call compiled {c_steady.count} programs"
    assert _digest(ds, js) == _digest(db, ib), "steady != primed (bitwise)"
    lat = []
    for _ in range(20):
        t0 = time.perf_counter()
        dl, il, _ = fleet.search(bud, q, K, sp)
        jax.block_until_ready((dl, il))
        lat.append(time.perf_counter() - t0)
    payload = {
        "programs_per_call_before": int(c_before.count),
        "programs_per_call_steady": int(c_steady.count),
        "dispatch_p50_ms": round(statistics.median(lat) * 1e3, 3),
        "m": M, "k": K, "n_probes": NPROBE, "bitwise_equal": True,
    }
    # no spaces in the JSON: _extract() takes the second whitespace field
    print("REF_DISPATCH " + json.dumps(payload, separators=(",", ":")),
          flush=True)
    print(f"REF_OK recall={rec:.4f}", flush=True)


def run_worker() -> None:
    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    import numpy as np

    sys.path.insert(0, _ROOT)
    # bootstrap FIRST (before anything touches the XLA backend), through
    # the env-autodetect path — the parent set RAFT_TPU_COORDINATOR/
    # _NUM_PROCESSES/_PROCESS_ID, the worker passes nothing
    from raft_tpu.comms import bootstrap

    cfg = bootstrap.init_distributed()
    assert cfg["distributed"] and cfg["num_processes"] == 2, cfg

    from raft_tpu.core import events
    from raft_tpu.parallel import Fleet, sharded_ann

    fleet = Fleet.distributed()      # idempotent re-init guard path
    topo = fleet.topology
    assert (topo.n_hosts, topo.devs_per_host) == (2, 2), topo
    base, q = _dataset()
    idx = fleet.build_ivf_pq(base, _params())
    assert getattr(idx, "topology", None) is topo

    # 1. healthy: bit-identity digest vs the single-process reference
    d, i, ok = fleet.search(idx, q, K, _sparams())
    assert list(ok) == [True] * 4, ok
    healthy = _recall(np.asarray(i), _gt(base, q, K))
    print(f"WORKER_DIGEST {_digest(d, i)}", flush=True)

    # 2. host loss: host 1's shards go dark (both ranks mark — SPMD)
    fleet.mark_host_failed(1)
    hh = fleet.host_health()
    assert hh["hosts_ok"] == [True, False], hh
    assert abs(hh["served_frac"] - 0.5) < 0.05, hh
    d2, i2, ok2 = fleet.search(idx, q, K, _sparams())
    assert list(ok2) == [True, True, False, False], ok2
    parts = sharded_ann._split_rows(N, 4)
    surv = np.concatenate([parts[0], parts[1]])
    surv_set = set(surv.tolist())
    ii2 = np.asarray(i2)
    leaked = [x for x in ii2.ravel().tolist()
              if x != -1 and x not in surv_set]
    assert not leaked, f"dead-host rows leaked into results: {leaked[:8]}"
    degraded = _recall(ii2, _gt(base, q, K, rows=surv))
    assert degraded >= 0.9 * healthy, (degraded, healthy)

    # 3. recovery: canary re-admission restores bit-identical serving
    rep = fleet.probe_hosts()
    assert rep["hosts_restored"] == [1], rep
    assert fleet.host_health()["served_frac"] == 1.0
    d3, i3, ok3 = fleet.search(idx, q, K, _sparams())
    assert list(ok3) == [True] * 4, ok3
    assert _digest(d3, i3) == _digest(d, i), "post-restore != healthy"

    # 4. budgeted int8 build: every rank plans the same fleet-wide
    # hot/cold split (only count tables cross DCN), streams its OWN
    # hosts' cold chunks, and the folded result must be bit-identical
    # to the single-process budgeted reference
    from raft_tpu.neighbors import ivf_flat

    sp = ivf_flat.SearchParams(n_probes=NPROBE)
    bud = fleet.build_ivf_pq(base, _params(), store_dtype="int8",
                             hbm_budget_gb=BUDGET_BYTES / 2 ** 30,
                             sample_queries=q)
    assert all((~m).any() for m in bud._fleet_ctx["hot"].values()), \
        "budget did not force any cold lists"
    # this rank holds tiers only for its own hosts' shards
    my = set(topo.shards_of(jax.process_index()))
    assert set(bud._fleet_tiers) == my, (set(bud._fleet_tiers), my)
    db, ib, okb = fleet.search(bud, q, K, sp)
    assert list(okb) == [True] * 4, okb
    print(f"WORKER_BUDGET_DIGEST {_digest(db, ib)}", flush=True)

    kinds = [e["kind"] for e in events.recent()]
    for want in ("fleet_build", "host_lost", "host_restored",
                 "host_tier_armed"):
        assert want in kinds, (want, kinds)
    print(f"WORKER_OK rank={jax.process_index()} healthy={healthy:.4f} "
          f"degraded_vs_survivors={degraded:.4f}", flush=True)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _extract(out: str, tag: str):
    for line in out.splitlines():
        if line.startswith(tag + " "):
            return line.split()[1]
    return None


def main() -> int:
    base_env = dict(os.environ)
    base_env["JAX_PLATFORMS"] = "cpu"
    base_env.pop("XLA_FLAGS", None)
    for k in ("RAFT_TPU_COORDINATOR", "RAFT_TPU_NUM_PROCESSES",
              "RAFT_TPU_PROCESS_ID"):
        base_env.pop(k, None)

    env = dict(base_env,
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    ref = subprocess.run([sys.executable, _HERE, "--ref"], env=env,
                         capture_output=True, text=True, timeout=600)
    if ref.returncode != 0:
        print(ref.stdout + ref.stderr)
        print("FAIL: single-process reference errored")
        return 1
    ref_digest = _extract(ref.stdout, "REF_DIGEST")
    print(f"# ref: digest={ref_digest}")

    disp = _extract(ref.stdout, "REF_DISPATCH")
    if disp is None:
        print(ref.stdout)
        print("FAIL: reference did not report the dispatch measurement")
        return 1
    import json
    payload = json.loads(disp)
    art = {"schema": "raft_tpu_bench_v1", "lane": "sharded_dispatch",
           "mesh": "cpu-virtual-2x2", **payload}
    art_path = os.path.join(_ROOT, "artifacts",
                            "bench_sharded_dispatch.json")
    os.makedirs(os.path.dirname(art_path), exist_ok=True)
    with open(art_path, "w") as f:
        json.dump(art, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"# sharded_dispatch: programs_per_call "
          f"{payload['programs_per_call_before']} -> "
          f"{payload['programs_per_call_steady']} steady-state, "
          f"p50={payload['dispatch_p50_ms']}ms -> {art_path}")

    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(base_env,
                   XLA_FLAGS="--xla_force_host_platform_device_count=2",
                   RAFT_TPU_COORDINATOR=f"127.0.0.1:{port}",
                   RAFT_TPU_NUM_PROCESSES="2",
                   RAFT_TPU_PROCESS_ID=str(rank))
        procs.append(subprocess.Popen(
            [sys.executable, _HERE, "--worker"], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("\n".join(outs))
        print("FAIL: workers timed out")
        return 1
    joined = "\n---\n".join(outs)
    rcs = [p.returncode for p in procs]
    if any(rc != 0 for rc in rcs) and (
            "UNAVAILABLE" in joined
            or ("gloo" in joined.lower()
                and "unimplemented" in joined.lower())):
        print(joined[-1500:])
        print("SKIPPED: CPU collectives backend unavailable")
        return 0
    if any(rc != 0 for rc in rcs):
        print(joined[-4000:])
        print("FAIL: worker assertion failed")
        return 1
    digests = [_extract(o, "WORKER_DIGEST") for o in outs]
    if not all(dg == ref_digest for dg in digests):
        print(joined[-4000:])
        print(f"FAIL: bit-identity broken ref={ref_digest} "
              f"workers={digests}")
        return 1
    ref_bdigest = _extract(ref.stdout, "REF_BUDGET_DIGEST")
    bdigests = [_extract(o, "WORKER_BUDGET_DIGEST") for o in outs]
    if not all(dg == ref_bdigest for dg in bdigests):
        print(joined[-4000:])
        print(f"FAIL: budgeted bit-identity broken ref={ref_bdigest} "
              f"workers={bdigests}")
        return 1
    for rank in range(2):
        if f"WORKER_OK rank={rank}" not in joined:
            print(joined[-4000:])
            print(f"FAIL: rank {rank} did not report OK")
            return 1
    print(joined)
    print("FLEET_DRYRUN_OK: distributed build bit-identical to "
          "single-process reference; host-loss degradation + widened "
          "recall + probe re-admission verified; budgeted int8 build "
          "(cold lists host-streamed, DCN-folded) bit-identical across "
          "processes, same neighbors as unbudgeted; steady-state "
          "sharded dispatch compiles 0 XLA programs")
    return 0


if __name__ == "__main__":
    if "--ref" in sys.argv:
        run_ref()
    elif "--worker" in sys.argv:
        run_worker()
    else:
        sys.exit(main())
