"""Profile CAGRA *build* phases at 100k on the real chip: where do
optimize()'s 219 s and seeds' 125 s actually go?"""
import time, sys, os
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import cagra

n, d, d0, deg = 100_000, 128, 96, 64
k1, k2 = jax.random.split(jax.random.PRNGKey(0))
data = jax.random.normal(k1, (n, d), jnp.float32)
# realistic-ish knn graph: random but sorted-by-closeness shape is
# irrelevant for cost profiling
knn = np.asarray(jax.random.randint(k2, (n, d0), 0, n, jnp.int32))
jax.block_until_ready(data)
print("chip:", jax.devices()[0].device_kind, flush=True)

def t(label, fn):
    t0 = time.perf_counter()
    r = fn()
    jax.block_until_ready(r) if r is not None else None
    print(f"{label}: {time.perf_counter()-t0:.1f}s", flush=True)
    return r

graph_j = jnp.asarray(knn)
graph_sorted = t("sort graph", lambda: jnp.sort(graph_j, axis=1))

batch = max(256, min(2048 * 8, (1 << 30) // (d0 * d0 * 16)))
batch = min(batch, n)
print(f"batch={batch} n_batches={-(-n // batch)}", flush=True)

nodes0 = jnp.arange(batch, dtype=jnp.int32)
# compile
t("prune_batch compile+run", lambda: cagra._prune_batch(graph_j, nodes0, deg))
t("prune_batch steady", lambda: cagra._prune_batch(
    graph_j, nodes0 + 1, deg))
t("prune_batch steady2", lambda: cagra._prune_batch(
    graph_j, nodes0 + 2, deg))

# sub-pieces of _detour_counts
def piece_gather():
    nbrs = graph_j[nodes0]
    return graph_sorted[nbrs]
t("detour gather compile+run", piece_gather)
t("detour gather steady", piece_gather)

def piece_ss():
    nbrs = graph_j[nodes0]
    nbr_rows = graph_sorted[nbrs]
    rows2 = nbr_rows.reshape(batch * d0, d0)
    tgts2 = jnp.broadcast_to(nbrs[:, None, :], (batch, d0, d0)).reshape(
        batch * d0, d0)
    pos = jax.vmap(jnp.searchsorted)(rows2, tgts2)
    return pos
f_ss = jax.jit(piece_ss)
t("searchsorted compile+run", f_ss)
t("searchsorted steady", f_ss)

t("full optimize", lambda: cagra.optimize(knn, deg))

# seeds phase
t("covering_seeds s=1562", lambda: cagra._covering_seeds(
    np.asarray(data), 1562, cagra.DistanceType.L2Expanded, 0))
