"""Lead check: pq64b4 (half the decode FLOPs and half the code bytes of
pq128b4) at the 1M two-part bench shape. Recall is probe-limited on this
corpus, so the coarser codebook may cost nothing after refine."""
import json, os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import brute_force, ivf_pq, refine

def log(m): print(m, file=sys.stderr, flush=True)

n, d, nq, k, part_n, di = 1_000_000, 128, 10_000, 10, 500_000, 16
kw, kc, kx, ka, kq, kp, ke, kf = jax.random.split(jax.random.PRNGKey(0), 8)
w = jax.random.normal(kw, (di, d)); w = w / jnp.linalg.norm(w, axis=1, keepdims=True)
cz = jax.random.normal(kc, (200, di))
z = cz[jax.random.randint(ka, (n,), 0, 200)] + jax.random.normal(kx, (n, di))
data = z @ w + 0.1 * jax.random.normal(ke, (n, d))
qz = cz[jax.random.randint(kq, (nq,), 0, 200)] + jax.random.normal(kp, (nq, di))
queries = qz @ w + 0.1 * jax.random.normal(kf, (nq, d))
jax.block_until_ready((data, queries))
parts = [data[:part_n], data[part_n:]]
offsets = [0, part_n]

bfs = [brute_force.build(p, metric="sqeuclidean") for p in parts]
gt_fn = jax.jit(lambda q, idx: brute_force.search(idx, q, k, algo="matmul"))
merge = jax.jit(lambda dv, iv: brute_force.knn_merge_parts(dv, iv, True))
def exact(qs):
    ds, is_ = [], []
    for bfi, off in zip(bfs, offsets):
        dd, ii = gt_fn(qs, bfi)
        ds.append(dd); is_.append(jnp.where(ii >= 0, ii + off, -1))
    return merge(jnp.stack(ds), jnp.stack(is_))
gt = jnp.concatenate([jax.block_until_ready(exact(queries[c:c+1000])[1])
                      for c in range(0, nq, 1000)])
del bfs
log("# gt done")

def recall(ids):
    hit = jnp.any(ids[:, :, None] == gt[:, None, :], axis=2) & (gt >= 0)
    return float(jnp.sum(hit) / jnp.sum(gt >= 0))

def wall(tp, calls=6):
    from raft_tpu.ops.autotune import measure_value_read_wall
    perms = [jnp.take(queries, jax.random.permutation(
        jax.random.PRNGKey(100 + i), nq), axis=0) for i in range(calls + 1)]
    jax.block_until_ready(perms)
    return measure_value_read_wall(tp, perms[:-1], warm_input=perms[-1])

parts_bf16 = [jnp.asarray(p, jnp.bfloat16) for p in parts]
jax.block_until_ready(parts_bf16)
out = {}
for name, pqd in (("pq64b4", 64), ("pq128b4", 128)):
    t0 = time.perf_counter()
    pis = [ivf_pq.build(p, ivf_pq.IndexParams(n_lists=1024, pq_dim=pqd,
                                              pq_bits=4, seed=0))
           for p in parts]
    jax.block_until_ready(jax.tree.leaves(pis))
    bs = time.perf_counter() - t0
    for pi in pis:
        ivf_pq.prepare_scan(pi)
    log(f"# {name} built {bs:.0f}s")
    for probes, ratio in ((20, 2), (20, 4)):
        sp = ivf_pq.SearchParams(n_probes=probes, lut_dtype="int8")
        def body(q, idx, dd, s=sp, r=ratio):
            _, cand = ivf_pq.search(idx, q, r * k, s)
            return refine.refine(dd, q, cand, k)
        fn = jax.jit(body)
        def tp(q, *_):
            ds, is_ = [], []
            for pi, pb, off in zip(pis, parts_bf16, offsets):
                dd, ii = fn(q, pi, pb)
                ds.append(dd); is_.append(jnp.where(ii >= 0, ii + off, -1))
            return merge(jnp.stack(ds), jnp.stack(is_))
        dt = wall(tp)
        r = recall(tp(queries)[1])
        out[f"{name}_np{probes}_r{ratio}"] = dict(ms=dt*1e3, qps=nq/dt,
                                                  recall=r, build_s=bs)
        log(f"# {name} np{probes} r{ratio}: {dt*1e3:.1f}ms "
            f"({nq/dt:,.0f} qps) r={r:.4f}")
    del pis

print(json.dumps(out, indent=1))
