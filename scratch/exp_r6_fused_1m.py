"""r6 probe: the ungated fused engine at the BASELINE 1M scale.

Races matmul vs the rewritten fused kernel (two-level block-min select,
corpus-resident tiles) on two 500k parts sharing one executable — the
same TwoPart shape the bench headline uses — and prints the
decomposition the bench now records: gemm-only rate, matmul select
overhead, fused rate. Also sweeps RAFT_TPU_FUSED_TILES when given as a
comma-separated list in RAFT_TPU_FUSED_TILE_SWEEP (e.g.
"512,1024;256,2048").
"""
import json
import os
import sys

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/raft_tpu_xla_cache")
sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp

from raft_tpu.neighbors import brute_force
from raft_tpu.ops.autotune import measure_value_read_wall


def log(m):
    print(m, file=sys.stderr, flush=True)


n_part, parts, d, nq, k = 500_000, 2, 128, 10_000, 10
keys = jax.random.split(jax.random.PRNGKey(0), parts + 1)
data = [jax.random.normal(kk, (n_part, d), jnp.float32) for kk in keys[:-1]]
queries = jax.random.normal(keys[-1], (nq, d), jnp.float32)
jax.block_until_ready((data, queries))
idxs = [brute_force.build(p) for p in data]
for ix in idxs:
    brute_force.prepare_fused(ix)
log("# built + prepared")


def wall(fn, calls=4):
    perms = [jnp.take(queries, jax.random.permutation(
        jax.random.PRNGKey(100 + i), nq), axis=0)
        for i in range(calls + 1)]
    jax.block_until_ready(perms)

    def tp(q):
        acc = None
        for ix in idxs:
            s = fn(q, ix)
            acc = s if acc is None else acc + s
        return acc

    return measure_value_read_wall(tp, perms[:-1], warm_input=perms[-1])


out = {}
flops = 2.0 * nq * n_part * d * parts

gemm = jax.jit(lambda q, ix: jnp.sum(jnp.where(jnp.isfinite(
    jax.lax.dot_general(q, ix.dataset, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision("highest"))), 1.0, 0.0)))
t = wall(gemm)
out["gemm_only"] = {"s_per_call": t, "tflops": flops / t / 1e12}
log(f"# gemm-only {t*1e3:.1f} ms = {flops/t/1e12:.1f} TFLOP/s")

for algo in ("matmul", "pallas"):
    fn = jax.jit(lambda q, ix, a=algo: jnp.sum(jnp.where(jnp.isfinite(
        brute_force.search(ix, q, k, algo=a)[0]), 1.0, 0.0)))
    try:
        t = wall(fn)
    except Exception as e:  # noqa: BLE001
        out[algo] = {"error": f"{type(e).__name__}: {e}"}
        log(f"# {algo} failed: {e}")
        continue
    out[algo] = {"s_per_call": t, "qps": nq / t, "tflops": flops / t / 1e12}
    log(f"# {algo}: {nq/t:,.0f} QPS ({flops/t/1e12:.1f} TFLOP/s)")

if "matmul" in out and "s_per_call" in out["matmul"]:
    out["select_overhead_ms"] = (out["matmul"]["s_per_call"]
                                 - out["gemm_only"]["s_per_call"]) * 1e3

for cfg in [c for c in os.environ.get("RAFT_TPU_FUSED_TILE_SWEEP",
                                      "").split(";") if c]:
    os.environ["RAFT_TPU_FUSED_TILES"] = cfg
    for ix in idxs:
        brute_force.prepare_fused(ix)   # re-aligns to the new tn
    fn = jax.jit(lambda q, ix: jnp.sum(jnp.where(jnp.isfinite(
        brute_force.search(ix, q, k, algo="pallas")[0]), 1.0, 0.0)))
    try:
        t = wall(fn)
    except Exception as e:  # noqa: BLE001
        out[f"pallas@{cfg}"] = {"error": f"{type(e).__name__}: {e}"}
        log(f"# pallas@{cfg} failed: {e}")
        continue
    out[f"pallas@{cfg}"] = {"s_per_call": t, "qps": nq / t,
                            "tflops": flops / t / 1e12}
    log(f"# pallas@{cfg}: {nq/t:,.0f} QPS")

print(json.dumps(out, indent=1))
