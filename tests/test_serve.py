"""Query-serving runtime tests: metrics registry, admission control,
shape-bucketed micro-batching, warmup, fault/deadline integration.

Acceptance bar (ISSUE 2): after ``warmup()``, a stream of mixed-size
requests (1-200 queries, varying k) causes ZERO new XLA compilations
(asserted with compilation-count instrumentation) and micro-batched
throughput is >= 3x the one-request-per-dispatch baseline at equal
recall; the metrics snapshot reports non-zero batch fill ratio, latency
histogram and queue depth; fault-injected runs increment shed/degraded
counters.

Index builds dominate runtime on the 1-core CI box: every index is a
module-scoped fixture (the tests/test_faults.py discipline) and the
expensive ladder warmup is paid ONCE inside the combined load test.
"""
import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from ann_utils import naive_knn
from raft_tpu.core import faults, tracing
from raft_tpu.core.deadline import Deadline, DeadlineExceeded
from raft_tpu.serve import metrics
from raft_tpu.serve.admission import AdmissionQueue, QueueFullError, Request
from raft_tpu.serve.batcher import BucketLadder, MicroBatcher
from raft_tpu.serve.warmup import count_compilations

pytestmark = pytest.mark.serve

DIM = 16
# one ladder shared by the batcher tests so its shapes compile once per
# process (the 870s tier-1 budget is tight; ground truth is numpy
# naive_knn — n_probes == n_lists makes ivf_flat exact — precisely to
# avoid compiling per-request direct-dispatch shapes)
LADDER = BucketLadder((8, 32, 256), (8, 16))


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(42)
    data = rng.standard_normal((800, DIM)).astype(np.float32)
    q = rng.standard_normal((24, DIM)).astype(np.float32)
    return data, q


@pytest.fixture(scope="module")
def flat_index(corpus):
    from raft_tpu.neighbors import ivf_flat

    return ivf_flat.build(corpus[0], ivf_flat.IndexParams(n_lists=8, seed=0))


@pytest.fixture(scope="module")
def searcher(flat_index):
    """The steady-state serving closure: engine frozen to the exact XLA
    path so results are bit-reproducible across dispatch groupings."""
    from raft_tpu.neighbors import ivf_flat

    return ivf_flat.make_searcher(
        flat_index, ivf_flat.SearchParams(n_probes=8), algo="xla")


@pytest.fixture
def reg():
    return metrics.Registry()


class TestMetrics:
    def test_counter_gauge(self, reg):
        c = reg.counter("c")
        c.inc()
        c.inc(2)
        assert c.value == 3
        g = reg.gauge("g")
        g.set(7)
        g.set_max(3)        # lower: no change
        assert g.value == 7
        assert reg.counter("c") is c    # get-or-create
        with pytest.raises(TypeError):
            reg.gauge("c")              # type collision

    def test_histogram_percentiles(self, reg):
        h = reg.histogram("h", buckets=(1.0, 2.0, 4.0, 8.0))
        assert np.isnan(h.percentile(50))
        for v in (0.5, 1.5, 3.0, 3.5, 6.0, 20.0):
            h.observe(v)
        assert h.count == 6 and h.sum == pytest.approx(34.5)
        assert 0.5 <= h.percentile(10) <= 1.5
        assert 1.5 <= h.percentile(50) <= 4.0
        assert h.percentile(99) <= 20.0
        snap = h.snapshot()
        assert snap["buckets"]["+inf"] == 1 and snap["max"] == 20.0

    def test_snapshot_and_text(self, reg):
        reg.counter("serve.requests").inc(5)
        reg.gauge("serve.queue_depth").set(2)
        reg.histogram("serve.latency_s").observe(0.01)
        snap = reg.snapshot()
        assert snap["counters"]["serve.requests"] == 5
        assert snap["gauges"]["serve.queue_depth"] == 2
        assert snap["histograms"]["serve.latency_s"]["count"] == 1
        text = reg.render_text()
        assert "serve_requests 5" in text
        assert 'serve_latency_s_bucket{le="+Inf"}' in text

    def test_span_metrics_via_tracing(self, reg):
        metrics.enable_span_metrics(reg)
        try:
            @tracing.annotate("unit::span")
            def f():
                return 1

            f()
            f()
            with tracing.range("unit::block"):
                pass
        finally:
            metrics.disable_span_metrics()
        h = reg.snapshot()["histograms"]
        assert h["span.unit::span"]["count"] == 2
        assert h["span.unit::block"]["count"] == 1
        # observer removed: no further recording
        with tracing.range("unit::block"):
            pass
        assert reg.histogram("span.unit::block").count == 1

    def test_guarded_demotion_counter(self):
        from raft_tpu.ops import guarded

        if any(f.kind == "kernel_compile" for f in faults.active()):
            pytest.skip("ambient kernel faults are served as injected "
                        "(non-demoting) failures")
        before = metrics.counter("guarded.demotions").value

        def boom():
            raise RuntimeError("mosaic lowering died")

        try:
            assert guarded.guarded_call("serve.t", boom, lambda: "fb") == "fb"
        finally:
            guarded.reset()
        assert metrics.counter("guarded.demotions").value == before + 1


class TestLadder:
    def test_bucketing(self):
        lad = BucketLadder((8, 32, 128), (16, 64))
        assert lad.bucket_queries(1) == 8
        assert lad.bucket_queries(8) == 8
        assert lad.bucket_queries(9) == 32
        assert lad.bucket_k(16) == 16 and lad.bucket_k(17) == 64
        assert lad.max_queries == 128 and lad.max_k == 64
        assert len(lad.shapes()) == 6
        with pytest.raises(Exception):
            lad.bucket_queries(129)
        with pytest.raises(Exception):
            lad.bucket_k(65)
        with pytest.raises(Exception):
            BucketLadder((32, 8), (16,))    # not ascending


class TestAdmission:
    def test_backpressure(self, reg):
        q = AdmissionQueue(max_depth=2, registry=reg, prefix="t")
        r = [Request(np.zeros((1, DIM), np.float32), 5) for _ in range(3)]
        q.submit(r[0])
        q.submit(r[1])
        with pytest.raises(QueueFullError):
            q.submit(r[2])
        assert reg.counter("t.rejected").value == 1
        assert reg.gauge("t.queue_depth_peak").value == 2

    def test_pop_coalesces_and_sheds(self, reg):
        q = AdmissionQueue(max_depth=8, registry=reg, prefix="t")
        dead = Request(np.zeros((2, DIM), np.float32), 5,
                       deadline=Deadline(0.0))
        live = [Request(np.zeros((3, DIM), np.float32), 5) for _ in range(3)]
        q.submit(dead)
        for r in live:
            q.submit(r)
        batch = q.pop_batch(max_requests=8, max_wait_s=0.001, max_rows=6)
        # expired request shed, 2x3 rows fit the 6-row cap, 3rd stays
        assert batch == live[:2] and len(q) == 1
        assert reg.counter("t.shed").value == 1
        with pytest.raises(DeadlineExceeded):
            dead.result(1)
        q.close()
        assert q.pop_batch(8, 0.001) == [live[2]]
        assert q.pop_batch(8, 0.001) == []


class TestBatcher:
    def test_mixed_requests_match_ground_truth(self, corpus, reg, searcher):
        data, q = corpus
        with MicroBatcher(searcher, DIM, ladder=LADDER, registry=reg,
                          max_wait_s=0.001) as b:
            reqs = [b.submit(q[:m], k)
                    for m, k in ((1, 3), (5, 10), (24, 8))]
            outs = [r.result(60) for r in reqs]
        for (m, k), out in zip(((1, 3), (5, 10), (24, 8)), outs):
            want_d, want_i = naive_knn(data, q[:m], k)
            np.testing.assert_array_equal(np.asarray(out.indices), want_i)
            np.testing.assert_allclose(np.asarray(out.distances), want_d,
                                       rtol=1e-4, atol=1e-4)

    def test_single_vector_and_validation(self, corpus, searcher, reg):
        _, q = corpus
        lad = BucketLadder((8,), (8,))
        with MicroBatcher(searcher, DIM, ladder=lad, registry=reg,
                          max_wait_s=0.001) as b:
            out = b.search(q[0], 5, timeout=60)     # 1-D vector request
            assert np.asarray(out.indices).shape == (1, 5)
            with pytest.raises(Exception):
                b.submit(q[:9], 5)      # rows beyond the largest bucket
            with pytest.raises(Exception):
                b.submit(q[:2], 9)      # k beyond the largest k bucket
            with pytest.raises(Exception):
                b.submit(q[:2, :8], 5)  # wrong query width

    def test_double_buffer_demux_overlaps_next_dispatch(self, reg):
        """ISSUE 12 double buffering: with a backlog, batch N+1 must be
        DISPATCHED before batch N is demuxed (device computes N+1 while
        the host demuxes N), and an emptied queue demuxes immediately.
        Instrumented at the two host boundaries: the search call
        (dispatch) and the ``np.asarray`` device→host conversion
        (demux)."""
        log = []

        class _Arr:
            def __init__(self, tag, val):
                self.tag, self.val = tag, val

            def __array__(self, dtype=None):
                log.append(("demux", self.tag))
                return np.asarray(self.val, dtype)

        calls = [0]

        def fn(q, k, res=None):
            tag = calls[0]
            calls[0] += 1
            log.append(("dispatch", tag))
            m = q.shape[0]
            return (_Arr(tag, np.zeros((m, k), np.float32)),
                    _Arr(tag, np.zeros((m, k), np.int32)))

        b = MicroBatcher(fn, 4, ladder=BucketLadder((1,), (4,)),
                         registry=reg, autostart=False, max_wait_s=0.0,
                         max_batch_requests=1, trace_sample=0)
        try:
            rs = [b.submit(np.zeros((1, 4), np.float32), 4)
                  for _ in range(3)]
            b.start()           # worker sees a 3-deep backlog
            for r in rs:
                r.result(30)
        finally:
            b.close()
        assert calls[0] == 3
        # demux(N) strictly after dispatch(N+1) while the backlog lasts
        assert log.index(("dispatch", 1)) < log.index(("demux", 0)), log
        assert log.index(("dispatch", 2)) < log.index(("demux", 1)), log
        # the final batch (queue drained) is demuxed without waiting
        assert ("demux", 2) in log

    def test_codeadline_collateral_is_redispatched(self, reg):
        """A request with no deadline co-batched behind a tighter
        deadline must be re-dispatched when that deadline fires, never
        failed with someone else's DeadlineExceeded."""
        calls = []

        def flaky(queries, k, res=None):
            m = queries.shape[0]
            if not calls:
                calls.append(1)
                raise DeadlineExceeded("deadline", partial=(
                    np.zeros((4, k), np.float32),
                    np.zeros((4, k), np.int32)))
            return (np.ones((m, k), np.float32),
                    np.ones((m, k), np.int32))

        def ticking(ticks):
            it = iter(ticks)
            return lambda: next(it)

        b = MicroBatcher(flaky, DIM, ladder=BucketLadder((8,), (8,)),
                         registry=reg, autostart=False, max_wait_s=0.001)
        # ticks: ctor, pop shed-probe, dispatch shed-probe, tightest —
        # the deadline stays live on the host; the (stub) search raises
        tight = b.submit(np.zeros((4, DIM), np.float32), 5,
                         deadline=Deadline(1.0,
                                           clock=ticking([0., .1, .2, .3])))
        free = b.submit(np.zeros((2, DIM), np.float32), 5)
        b.start()
        # tight (rows 0-4) is fully covered by the partial: served
        out_t = tight.result(60)
        assert (np.asarray(out_t.indices) == 0).all()
        # free (rows 4-6) was collateral: re-dispatched, then served
        out_f = free.result(60)
        assert (np.asarray(out_f.indices) == 1).all()
        b.close()
        assert reg.counter("serve.redispatched").value == 1
        assert reg.counter("serve.deadline_exceeded").value == 0
        assert reg.counter("serve.served").value == 2

    def test_worker_survives_dispatch_error(self, reg):
        calls = []

        def flaky(queries, k, res=None):
            if not calls:
                calls.append(1)
                raise RuntimeError("transient engine failure")
            m = queries.shape[0]
            return (np.zeros((m, k), np.float32),
                    np.zeros((m, k), np.int32))

        lad = BucketLadder((8,), (8,))
        with MicroBatcher(flaky, DIM, ladder=lad, registry=reg,
                          max_wait_s=0.001) as b:
            r1 = b.submit(np.zeros((2, DIM), np.float32), 4)
            with pytest.raises(RuntimeError, match="transient"):
                r1.result(60)
            out = b.search(np.zeros((2, DIM), np.float32), 4, timeout=60)
        assert np.asarray(out.indices).shape == (2, 4)
        assert reg.counter("serve.errors").value == 1


class TestLoad:
    """The ISSUE 2 acceptance load test. One test pays the ladder warmup
    once and proves both headline properties plus the metrics contract."""

    def test_warmup_zero_recompiles_throughput_and_metrics(
            self, corpus, searcher, reg):
        data, _ = corpus
        rng = np.random.default_rng(7)
        b = MicroBatcher(searcher, DIM, ladder=LADDER, registry=reg,
                         autostart=False, max_wait_s=0.001,
                         max_batch_requests=64)
        b.warmup()
        assert reg.gauge("serve.warmup.shapes").value == len(LADDER.shapes())

        # mixed-size stream: 1-200 queries, k varying across both buckets
        sizes = [1, 3, 8, 17, 40, 200, 2, 33]
        ks = [5, 8, 12, 16, 3, 10, 8, 16]
        streams = [rng.standard_normal((m, DIM)).astype(np.float32)
                   for m in sizes]
        reqs = []
        with count_compilations() as cc:
            for qm, k in zip(streams, ks):
                reqs.append(b.submit(qm, k))
            depth_while_queued = len(b.queue)
            b.start()
            outs = [r.result(60) for r in reqs]
        assert cc.count == 0, (
            f"{cc.count} XLA recompiles in steady state — the bucket "
            "ladder failed its recompile-avoidance guarantee")
        assert depth_while_queued > 0

        # equal recall: batched answers == exact ground truth (n_probes
        # == n_lists makes ivf_flat exact; numpy oracle, no extra XLA)
        for qm, k, out in zip(streams, ks, outs):
            _, want = naive_knn(data, qm, k)
            np.testing.assert_array_equal(np.asarray(out.indices), want)

        # throughput: >= 3x over one-request-per-dispatch singles
        singles = [rng.standard_normal((1, DIM)).astype(np.float32)
                   for _ in range(48)]
        np.asarray(searcher(singles[0], 8)[0])     # warm the (1,) shape
        t0 = time.perf_counter()
        base = [np.asarray(searcher(qv, 8)[1]) for qv in singles]
        t_base = time.perf_counter() - t0
        sreqs = []
        t0 = time.perf_counter()
        for qv in singles:
            sreqs.append(b.submit(qv, 8))
        souts = [r.result(60) for r in sreqs]
        t_batched = time.perf_counter() - t0
        b.close()
        for w, out in zip(base, souts):
            np.testing.assert_array_equal(np.asarray(out.indices), w)
        speedup = t_base / max(t_batched, 1e-9)
        assert speedup >= 3.0, (
            f"micro-batching speedup {speedup:.2f}x < 3x "
            f"(baseline {t_base:.3f}s, batched {t_batched:.3f}s)")

        # metrics contract: non-zero fill ratio, latency histogram, depth
        snap = reg.snapshot()
        fill = snap["histograms"]["serve.batch_fill"]
        assert fill["count"] > 0 and fill["sum"] > 0
        lat = snap["histograms"]["serve.latency_s"]
        assert lat["count"] == len(reqs) + len(sreqs) and lat["p50"] > 0
        assert snap["gauges"]["serve.queue_depth_peak"] > 0
        assert snap["counters"]["serve.served"] == len(reqs) + len(sreqs)
        assert any(name.startswith("serve.dispatch.")
                   for name in snap["counters"])


@pytest.mark.faults
class TestServeFaults:
    """Batcher under RAFT_TPU_FAULTS-style injection: slow dispatch ->
    deadline shed / partial results; dead shard -> degraded serve with
    shards_ok surfaced in metrics and responses."""

    def test_slow_dispatch_deadline_returns_partial(self, corpus,
                                                    flat_index, reg):
        from raft_tpu.neighbors import ivf_flat

        if any(f.kind == "kernel_compile" for f in faults.active()):
            pytest.skip("ambient kernel faults reroute the guarded scan "
                        "site this test arms slow_dispatch on")
        _, q = corpus
        sp = ivf_flat.SearchParams(n_probes=8)
        # chunked pallas path: the guarded per-chunk dispatch is the
        # slow_dispatch probe site, checkpoints run between chunks
        searcher_p = ivf_flat.make_searcher(flat_index, sp, algo="pallas",
                                            query_chunk=8)
        _, iref = ivf_flat.search(flat_index, q, 8, sp, algo="pallas")
        b = MicroBatcher(searcher_p, DIM,
                         ladder=BucketLadder((8, 32), (8,)),
                         registry=reg, autostart=False, max_wait_s=0.001)
        with faults.inject("slow_dispatch", "ivf_flat.scan", value=0.15):
            req = b.submit(q, 8, deadline=Deadline(0.25))
            b.start()
            with pytest.raises(DeadlineExceeded) as ei:
                req.result(60)
        b.close()
        assert ei.value.partial is not None
        pd, pi = ei.value.partial
        done = pd.shape[0]
        assert done in (8, 16)      # whole chunks, not all 24 rows
        np.testing.assert_array_equal(np.asarray(pi),
                                      np.asarray(iref)[:done])
        assert reg.counter("serve.deadline_exceeded").value == 1

    def test_expired_in_queue_is_shed(self, corpus, searcher, reg):
        _, q = corpus
        b = MicroBatcher(searcher, DIM, ladder=BucketLadder((8,), (8,)),
                         registry=reg, autostart=False, max_wait_s=0.001)
        dead = b.submit(q[:4], 8, deadline=Deadline(0.0))
        live = b.submit(q[:2], 8)
        b.start()
        out = live.result(60)
        with pytest.raises(DeadlineExceeded) as ei:
            dead.result(60)
        b.close()
        assert ei.value.partial is None
        assert np.asarray(out.indices).shape == (2, 8)
        assert reg.counter("serve.shed").value == 1
        assert reg.counter("serve.served").value == 1

    def test_degraded_accounting_with_stub_shards(self, reg):
        """Batcher-side degraded contract without the ~20s shard_map
        compile: a searcher reporting a dead shard must surface
        shards_ok in the response, the healthy_shards gauge and the
        degraded_batches counter (the real sharded path is covered by
        the slow-lane test below and tests/test_faults.py)."""
        ok = np.array([True, False, True, True])

        def degraded(queries, k, res=None):
            m = queries.shape[0]
            return (np.zeros((m, k), np.float32),
                    np.zeros((m, k), np.int32), ok)

        with MicroBatcher(degraded, DIM, ladder=BucketLadder((8,), (8,)),
                          registry=reg, max_wait_s=0.001) as b:
            out = b.search(np.zeros((4, DIM), np.float32), 5, timeout=60)
        assert list(out.shards_ok) == [True, False, True, True]
        assert reg.gauge("serve.healthy_shards").value == 3
        assert reg.counter("serve.degraded_batches").value == 1

    @pytest.mark.slow
    def test_shard_dead_degraded_serve(self, corpus, reg):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.parallel import sharded_ann

        rng = np.random.default_rng(17)
        data = rng.standard_normal((600, DIM)).astype(np.float32)
        q = rng.standard_normal((8, DIM)).astype(np.float32)
        mesh = Mesh(np.array(jax.devices()[:4]), ("shard",))
        sidx = sharded_ann.build_ivf_flat(
            data, mesh, ivf_flat.IndexParams(n_lists=4, seed=0))
        searcher_s = sharded_ann.make_searcher(
            sidx, ivf_flat.SearchParams(n_probes=4), allow_partial=True)
        with MicroBatcher(searcher_s, DIM, ladder=BucketLadder((8,), (8,)),
                          registry=reg, max_wait_s=0.001) as b:
            with faults.inject("shard_dead",
                               "sharded_ann.ivf_flat.shard1"):
                out = b.search(q, 5, timeout=120)
            healthy = b.search(q, 5, timeout=120)
        assert list(out.shards_ok) == [True, False, True, True]
        got = np.asarray(out.indices)
        # shard 1 owns global rows [150, 300): none may appear
        assert not (((got >= 150) & (got < 300)).any())
        assert (got >= 0).all()
        snap = reg.snapshot()
        assert snap["counters"]["serve.degraded_batches"] == 1
        # gauge reflects the LAST batch: recovered to all-healthy
        assert list(healthy.shards_ok) == [True] * 4
        assert snap["gauges"]["serve.healthy_shards"] == 4
