"""IVF-Flat tests (analog of NEIGHBORS_ANN_IVF_TEST): recall vs brute-force
oracle over a param sweep, never exact equality (SURVEY.md §4)."""
import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import ivf_flat


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    return rng.standard_normal((20_000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(8)
    return rng.standard_normal((100, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def built_index(dataset):
    return ivf_flat.build(dataset, ivf_flat.IndexParams(n_lists=64, seed=0))


class TestIvfFlat:
    def test_structure(self, built_index, dataset):
        assert built_index.size == len(dataset)
        assert built_index.n_lists == 64
        sizes = built_index.list_sizes
        assert sizes.sum() == len(dataset)
        assert sizes.min() > 0
        # every source id appears exactly once on valid rows; capacity
        # slack rows carry the -1 sentinel
        ids = np.asarray(built_index.source_ids)
        valid = ids[ids >= 0]
        np.testing.assert_array_equal(np.sort(valid),
                                      np.arange(len(dataset)))
        caps = np.diff(built_index.list_offsets)
        assert (caps >= sizes).all()

    # NOTE: thresholds calibrated on unstructured gaussian data, where probing
    # 8/64 lists gives ~0.56 *upper-bound* recall (partition-limited, verified
    # against the probed-list membership oracle); real ANN datasets cluster
    # far better. 64/64 probes must be exact.
    @pytest.mark.parametrize("n_probes,min_recall", [(8, 0.50), (16, 0.68), (64, 0.9999)])
    def test_recall(self, built_index, dataset, queries, n_probes, min_recall):
        dist, idx = ivf_flat.search(built_index, queries, k=10,
                                    params=ivf_flat.SearchParams(n_probes))
        _, want = naive_knn(dataset, queries, 10)
        r = calc_recall(np.asarray(idx), want)
        assert r >= min_recall, f"recall {r} < {min_recall} at n_probes={n_probes}"

    def test_all_probes_is_exact(self, built_index, dataset, queries):
        dist, idx = ivf_flat.search(built_index, queries, k=5,
                                    params=ivf_flat.SearchParams(n_probes=64))
        want_d, want_i = naive_knn(dataset, queries, 5)
        np.testing.assert_allclose(np.asarray(dist), want_d, rtol=1e-2, atol=1e-2)

    def test_distances_match_l2(self, built_index, dataset, queries):
        dist, idx = ivf_flat.search(built_index, queries, k=3,
                                    params=ivf_flat.SearchParams(n_probes=32))
        d = np.asarray(dist)
        i = np.asarray(idx)
        # returned distances must equal true L2^2 to the returned ids
        for row in range(0, 100, 17):
            for col in range(3):
                true = ((queries[row] - dataset[i[row, col]]) ** 2).sum()
                assert abs(d[row, col] - true) < 1e-1

    def test_inner_product(self, dataset, queries):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=32, metric="inner_product", seed=0))
        _, idx = ivf_flat.search(index, queries, k=10,
                                 params=ivf_flat.SearchParams(n_probes=16))
        _, want = naive_knn(dataset, queries, 10, "inner_product")
        assert calc_recall(np.asarray(idx), want) > 0.85

    def test_extend(self, dataset, queries):
        index = ivf_flat.build(dataset[:10_000], ivf_flat.IndexParams(n_lists=32, seed=0))
        index = ivf_flat.extend(index, dataset[10_000:],
                                np.arange(10_000, 20_000, dtype=np.int32))
        assert index.size == 20_000
        _, idx = ivf_flat.search(index, queries, k=10,
                                 params=ivf_flat.SearchParams(n_probes=16))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) > 0.9

    def test_extend_in_place_with_growth_slack(self, dataset, queries):
        # growth=2: the second half fits in slack, so extend keeps the SAME
        # offsets (the O(batch) in-place scatter path)
        p = ivf_flat.IndexParams(n_lists=32, seed=0, list_growth=2.0)
        index = ivf_flat.build(dataset[:10_000], p)
        off0 = index.list_offsets.copy()
        index2 = ivf_flat.extend(index, dataset[10_000:13_000],
                                 np.arange(10_000, 13_000, dtype=np.int32))
        np.testing.assert_array_equal(index2.list_offsets, off0)
        assert index2.size == 13_000
        _, idx = ivf_flat.search(index2, queries, k=10,
                                 params=ivf_flat.SearchParams(n_probes=16))
        _, want = naive_knn(dataset[:13_000], queries, 10)
        assert calc_recall(np.asarray(idx), want) > 0.85

    def test_extend_overflow_repacks(self, dataset, queries):
        # growth=1: slack is only alignment, so a large extend overflows
        # and triggers the device-side repack; results stay correct
        index = ivf_flat.build(dataset[:10_000],
                               ivf_flat.IndexParams(n_lists=32, seed=0))
        index2 = ivf_flat.extend(index, dataset[10_000:],
                                 np.arange(10_000, 20_000, dtype=np.int32))
        assert index2.size == 20_000
        ids = np.asarray(index2.source_ids)
        np.testing.assert_array_equal(np.sort(ids[ids >= 0]),
                                      np.arange(20_000))
        _, idx = ivf_flat.search(index2, queries, k=10,
                                 params=ivf_flat.SearchParams(n_probes=16))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) > 0.9

    def test_save_strips_slack(self, dataset, tmp_path, queries):
        p = ivf_flat.IndexParams(n_lists=32, seed=0, list_growth=2.0)
        index = ivf_flat.build(dataset[:5000], p)
        ivf_flat.save(index, tmp_path / "slack.raft")
        loaded = ivf_flat.load(tmp_path / "slack.raft")
        assert loaded.size == 5000
        assert loaded.data.shape[0] == 5000    # dense file, no slack
        d1, i1 = ivf_flat.search(index, queries, 5,
                                 ivf_flat.SearchParams(n_probes=32))
        d2, i2 = ivf_flat.search(loaded, queries, 5,
                                 ivf_flat.SearchParams(n_probes=32))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    @pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
    def test_low_precision_storage(self, dataset, queries, dtype):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=64, seed=0, dtype=dtype))
        assert str(index.data.dtype) == dtype
        if dtype == "int8":
            assert index.scales is not None
        # full-probe search ≈ exact (quantization-limited)
        _, idx = ivf_flat.search(index, queries, k=10,
                                 params=ivf_flat.SearchParams(n_probes=64))
        _, want = naive_knn(dataset, queries, 10)
        r = calc_recall(np.asarray(idx), want)
        assert r > (0.95 if dtype == "bfloat16" else 0.9), r

    @pytest.mark.parametrize("dtype,rtol", [("float32", 0.0),
                                            ("bfloat16", 1e-2),
                                            ("int8", 2e-2)])
    def test_reconstruct(self, dataset, dtype, rtol):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=64, seed=0, dtype=dtype))
        ids = np.asarray(index.source_ids)
        rows = np.flatnonzero(ids >= 0)[::97][:64]  # valid physical rows
        got = np.asarray(ivf_flat.reconstruct(index, rows))
        want = dataset[ids[rows]]
        if dtype == "float32":
            np.testing.assert_array_equal(got, want)
        else:
            err = np.abs(got - want).max(axis=1)
            scale = np.abs(want).max(axis=1)
            assert (err <= rtol * scale + 1e-6).all(), err.max()

    def test_uint8_byte_corpus(self):
        rng = np.random.default_rng(11)
        data = rng.integers(0, 256, (8000, 32)).astype(np.float32)
        q = rng.integers(0, 256, (50, 32)).astype(np.float32)
        u8 = ivf_flat.build(data, ivf_flat.IndexParams(
            n_lists=32, seed=0, dtype="uint8"))
        assert str(u8.data.dtype) == "uint8" and u8.scales is None
        # full probe: lossless storage → exact vs brute-force oracle
        _, idx = ivf_flat.search(u8, q, k=10,
                                 params=ivf_flat.SearchParams(n_probes=32))
        _, want = naive_knn(data, q, 10)
        assert calc_recall(np.asarray(idx), want) > 0.9999
        # reconstruct round-trips bytes exactly
        ids = np.asarray(u8.source_ids)
        rows = np.flatnonzero(ids >= 0)[:16]
        np.testing.assert_array_equal(
            np.asarray(ivf_flat.reconstruct(u8, rows)), data[ids[rows]])

    def test_uint8_save_load(self, tmp_path):
        rng = np.random.default_rng(12)
        data = rng.integers(0, 256, (2000, 16)).astype(np.float32)
        q = rng.integers(0, 256, (20, 16)).astype(np.float32)
        u8 = ivf_flat.build(data, ivf_flat.IndexParams(
            n_lists=8, seed=0, dtype="uint8"))
        ivf_flat.save(u8, tmp_path / "u8.raft")
        loaded = ivf_flat.load(tmp_path / "u8.raft")
        assert str(loaded.data.dtype) == "uint8"
        sp = ivf_flat.SearchParams(n_probes=8)
        _, i1 = ivf_flat.search(u8, q, 5, sp)
        _, i2 = ivf_flat.search(loaded, q, 5, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_reconstruct_rejects_bad_rows(self, built_index):
        from raft_tpu.core.errors import RaftError
        cap = built_index.data.shape[0]
        with pytest.raises(RaftError):
            ivf_flat.reconstruct(built_index, [cap + 5])
        slack = np.flatnonzero(np.asarray(built_index.source_ids) < 0)
        if slack.size:
            with pytest.raises(RaftError):
                ivf_flat.reconstruct(built_index, [int(slack[0])])

    def test_bf16_pallas_scan_matches_xla(self, dataset, queries):
        index = ivf_flat.build(dataset, ivf_flat.IndexParams(
            n_lists=64, seed=0, dtype="bfloat16"))
        sp = ivf_flat.SearchParams(n_probes=16)
        dx, ix = ivf_flat.search(index, queries, 8, sp, algo="xla")
        dp, ip = ivf_flat.search(index, queries, 8, sp, algo="pallas")
        assert np.mean(np.asarray(ip) == np.asarray(ix)) > 0.97
        np.testing.assert_allclose(np.asarray(dp), np.asarray(dx),
                                   rtol=5e-2, atol=5e-2)

    def test_low_precision_save_load(self, dataset, queries, tmp_path):
        for dtype in ("bfloat16", "int8"):
            index = ivf_flat.build(dataset[:5000], ivf_flat.IndexParams(
                n_lists=32, seed=0, dtype=dtype))
            ivf_flat.save(index, tmp_path / f"ivf_{dtype}.raft")
            loaded = ivf_flat.load(tmp_path / f"ivf_{dtype}.raft")
            assert str(loaded.data.dtype) == dtype
            sp = ivf_flat.SearchParams(n_probes=32)
            _, i1 = ivf_flat.search(index, queries, 5, sp, algo="xla")
            _, i2 = ivf_flat.search(loaded, queries, 5, sp, algo="xla")
            np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_build_empty_then_extend(self, dataset, queries):
        p = ivf_flat.IndexParams(n_lists=32, add_data_on_build=False, seed=0)
        index = ivf_flat.build(dataset, p)
        assert index.size == 0
        index = ivf_flat.extend(index, dataset)
        assert index.size == len(dataset)
        _, idx = ivf_flat.search(index, queries, k=5,
                                 params=ivf_flat.SearchParams(n_probes=16))
        _, want = naive_knn(dataset, queries, 5)
        assert calc_recall(np.asarray(idx), want) > 0.9

    def test_filter(self, built_index, dataset, queries):
        _, base = naive_knn(dataset, queries, 2)
        mask = np.ones(len(dataset), bool)
        mask[base[:, 0]] = False
        filt = Bitset.from_mask(jnp.asarray(mask))
        _, idx = ivf_flat.search(built_index, queries, k=10,
                                 params=ivf_flat.SearchParams(n_probes=64),
                                 filter=filt)
        got = np.asarray(idx)
        assert not np.isin(base[:, 0], got.ravel()).any() or all(
            base[i, 0] not in got[i] for i in range(len(got)))

    def test_save_load(self, tmp_path, built_index, queries, dataset):
        ivf_flat.save(built_index, tmp_path / "ivf.raft")
        loaded = ivf_flat.load(tmp_path / "ivf.raft")
        d1, i1 = ivf_flat.search(built_index, queries, k=5,
                                 params=ivf_flat.SearchParams(16))
        d2, i2 = ivf_flat.search(loaded, queries, k=5,
                                 params=ivf_flat.SearchParams(16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_query_chunking_matches(self, built_index, queries):
        d1, i1 = ivf_flat.search(built_index, queries, k=5,
                                 params=ivf_flat.SearchParams(16), query_chunk=7)
        d2, i2 = ivf_flat.search(built_index, queries, k=5,
                                 params=ivf_flat.SearchParams(16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_index_as_jit_argument(self, built_index, queries):
        """The pytree carries the aligned-DMA pad cache byte-identical,
        so jitted functions can take the index as an ARGUMENT (baked
        closure constants exceed remote-compile limits at 500k rows)."""
        import jax

        ivf_flat.prepare_scan(built_index)
        leaves, td = jax.tree_util.tree_flatten(built_index)
        rebuilt = jax.tree_util.tree_unflatten(td, leaves)
        c0, c1 = built_index._scan_pad, rebuilt._scan_pad
        assert c1[0] == c0[0]
        for a, b in zip(c0[1:], c1[1:]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        fn = jax.jit(lambda q, idx: ivf_flat.search(
            idx, q, 5, ivf_flat.SearchParams(16)))
        d1, i1 = fn(queries, rebuilt)
        d2, i2 = ivf_flat.search(built_index, queries, k=5,
                                 params=ivf_flat.SearchParams(16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5, atol=1e-5)

    def test_k_larger_than_candidates(self, dataset, queries):
        index = ivf_flat.build(dataset[:500], ivf_flat.IndexParams(n_lists=64, seed=0))
        d, i = ivf_flat.search(index, queries, k=64,
                               params=ivf_flat.SearchParams(n_probes=1))
        assert d.shape == (100, 64)
        # padded tail rows marked -1
        assert (np.asarray(i) == -1).any()


class TestStreamingBuild:
    def test_build_from_batches_matches_bulk_recall(self, dataset, queries):
        batches = [dataset[i : i + 4096] for i in range(0, len(dataset), 4096)]
        p = ivf_flat.IndexParams(n_lists=32, seed=0)
        idx = ivf_flat.build_from_batches(iter(batches), p)
        assert idx.size == len(dataset)
        ids = np.asarray(idx.source_ids)
        np.testing.assert_array_equal(np.sort(ids[ids >= 0]),
                                      np.arange(len(dataset)))
        _, i = ivf_flat.search(idx, queries, 10,
                               ivf_flat.SearchParams(n_probes=16))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(i), want) > 0.85

    def test_iter_fbin_roundtrip(self, dataset, tmp_path):
        from raft_tpu.bench.datasets import iter_fbin, write_fbin

        write_fbin(tmp_path / "x.fbin", dataset[:5000])
        got = np.concatenate(list(iter_fbin(tmp_path / "x.fbin",
                                            batch_rows=1111)))
        np.testing.assert_array_equal(got, dataset[:5000])
