"""On-device smoke lane: jit-compile build+search for every index type on
the real TPU chip (VERDICT: the CPU suite can't catch TPU-only lowering
failures). Run with::

    RAFT_TPU_TEST_LANE=1 python -m pytest tests/test_tpu_lane.py -m tpu -q

Shapes are small — this lane is about compilation and numerical sanity on
hardware, not performance.
"""
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(0)
    return rng.standard_normal((6_000, 64)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(1)
    return rng.standard_normal((64, 64)).astype(np.float32)


@pytest.fixture(scope="module")
def oracle(dataset, queries):
    _, want = naive_knn(dataset, queries, 10)
    return want


def test_brute_force_pallas_on_device(dataset, queries, oracle):
    from raft_tpu.neighbors import brute_force

    index = brute_force.build(dataset)
    d, i = brute_force.search(index, queries, 10)   # auto → pallas on TPU
    assert calc_recall(np.asarray(i), oracle) == 1.0


def test_ivf_flat_on_device(dataset, queries, oracle):
    from raft_tpu.neighbors import ivf_flat

    index = ivf_flat.build(dataset, ivf_flat.IndexParams(n_lists=64, seed=0))
    d, i = ivf_flat.search(index, queries, 10,
                           ivf_flat.SearchParams(n_probes=64))
    assert calc_recall(np.asarray(i), oracle) == 1.0  # full probes = exact


def test_ivf_pq_on_device(dataset, queries, oracle):
    from raft_tpu.neighbors import ivf_pq

    index = ivf_pq.build(dataset, ivf_pq.IndexParams(
        n_lists=64, pq_dim=16, seed=0))
    d, i = ivf_pq.search(index, queries, 10, ivf_pq.SearchParams(n_probes=64))
    r = calc_recall(np.asarray(i), oracle)
    # PQ at 4 dims/subspace on gaussian data measures 0.545 on the XLA
    # path too — the bound checks the kernel, not PQ's information loss
    assert r >= 0.5, f"ivf_pq TPU recall {r}"


def test_cagra_on_device(dataset, queries, oracle):
    from raft_tpu.neighbors import cagra

    index = cagra.build(dataset, cagra.IndexParams(
        intermediate_graph_degree=48, graph_degree=24,
        build_algo=cagra.BuildAlgo.NN_DESCENT, seed=0))
    d, i = cagra.search(index, queries, 10,
                        cagra.SearchParams(itopk_size=96))
    r = calc_recall(np.asarray(i), oracle)
    assert r >= 0.9, f"cagra TPU recall {r}"


def test_kmeans_on_device(dataset):
    from raft_tpu.cluster import kmeans_balanced

    centers, labels = kmeans_balanced.fit_predict(dataset, 32)
    assert centers.shape == (32, 64)
    counts = np.bincount(np.asarray(labels), minlength=32)
    assert (counts > 0).all()


def test_low_precision_storage_on_device(dataset, queries, oracle):
    """bf16 and byte storage must compile and score correctly on the
    real chip (the dequant-fused GEMM and bf16 scan paths are
    TPU-lowering-sensitive)."""
    from raft_tpu.neighbors import brute_force

    bf16 = brute_force.build(dataset, dtype="bfloat16")
    _, i = brute_force.search(bf16, queries, 10)
    assert calc_recall(np.asarray(i), oracle) > 0.95

    bytes_data = np.round(np.clip(dataset * 40 + 128, 0, 255)
                          ).astype(np.float32)
    bytes_q = np.round(np.clip(queries * 40 + 128, 0, 255)
                       ).astype(np.float32)
    u8 = brute_force.build(bytes_data, dtype="uint8")
    _, iu = brute_force.search(u8, bytes_q, 10)
    _, want = naive_knn(bytes_data, bytes_q, 10)
    # >= 0.998 tolerates one k-boundary tie (integer distances on byte
    # vectors can tie exactly; tie order may differ from the oracle)
    assert calc_recall(np.asarray(iu), want) >= 0.998
