"""Sharded kNN over the 8-device virtual CPU mesh (the reference tests MNMG
logic on a LocalCUDACluster the same way — SURVEY.md §4)."""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from ann_utils import calc_recall, naive_knn
from raft_tpu.parallel import sharded_knn


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("shard",))


class TestShardedKnn:
    def test_matches_single_chip(self, mesh, rng):
        data = rng.standard_normal((4000, 32)).astype(np.float32)
        q = rng.standard_normal((32, 32)).astype(np.float32)
        index = sharded_knn.build(data, mesh)
        dist, idx = sharded_knn.search(index, q, k=10, tile_size=256)
        _, want = naive_knn(data, q, 10)
        assert calc_recall(np.asarray(idx), want) > 0.999

    def test_n_not_divisible_by_shards(self, mesh, rng):
        data = rng.standard_normal((1003, 16)).astype(np.float32)
        q = rng.standard_normal((8, 16)).astype(np.float32)
        index = sharded_knn.build(data, mesh)
        _, idx = sharded_knn.search(index, q, k=5, tile_size=128)
        _, want = naive_knn(data, q, 5)
        assert calc_recall(np.asarray(idx), want) > 0.999
        assert (np.asarray(idx) < 1003).all() and (np.asarray(idx) >= 0).all()

    def test_inner_product(self, mesh, rng):
        data = rng.standard_normal((2048, 16)).astype(np.float32)
        q = rng.standard_normal((8, 16)).astype(np.float32)
        index = sharded_knn.build(data, mesh, metric="inner_product")
        _, idx = sharded_knn.search(index, q, k=5, tile_size=256)
        _, want = naive_knn(data, q, 5, "inner_product")
        assert calc_recall(np.asarray(idx), want) > 0.999

    def test_dryrun(self):
        # ring_check=False: the cross-engine check costs a second full
        # search compile; tier-1 covers that path in test_ring_topk.py
        # (the driver's own dryrun subprocess keeps the check on)
        sharded_knn.dryrun(8, ring_check=False)

    def test_jit_compiles_once(self, mesh, rng):
        data = rng.standard_normal((1024, 16)).astype(np.float32)
        index = sharded_knn.build(data, mesh)
        fn = jax.jit(lambda q: sharded_knn.search(index, q, k=3, tile_size=128))
        q = rng.standard_normal((4, 16)).astype(np.float32)
        out1 = fn(q)
        out2 = fn(q + 1)
        jax.block_until_ready((out1, out2))
