"""Multi-tenant serving fabric tests (ISSUE 15): weighted-fair drain,
token-bucket isolation, the query cache (hit / generation invalidation
/ sentinel policing), zero-downtime swap under load, per-tenant
SLO/brownout independence, and the debugz tenants section.

Acceptance drills here are the ISSUE's:

* **isolation**: tenant A driven past its token bucket sheds/brownouts
  ITSELF while tenants B/C stay at SLO-ok verdicts with p99 within
  1.5x of their solo run, and no request is ever answered with another
  tenant's results (id-spot-checked via tagged stub searchers);
* **swap**: under sustained concurrent load, a swap drops zero
  requests, invalidates the cache, records exactly one ``tenant_swap``
  event, and the replacement is pre-warmed (zero steady-state
  recompiles after the flip, asserted via the recompile watch).

Everything except the swap drill runs on stub searchers (no XLA
compiles), so the file stays lean under the tier-1 wall; the swap
drill shares one module-scoped pair of tiny brute-force indexes.
"""
import json
import threading
import time

import numpy as np
import pytest

from raft_tpu.core import events
from raft_tpu.serve import debugz, metrics
from raft_tpu.serve.batcher import BucketLadder
from raft_tpu.serve.qcache import QueryCache
from raft_tpu.serve.quality import RecallSentinel
from raft_tpu.serve.slo import SLOEngine, Targets
from raft_tpu.serve.tenancy import (RateLimitedError, ServeFabric,
                                    TokenBucket, install, uninstall)
from raft_tpu.serve.warmup import count_compilations

pytestmark = pytest.mark.serve

DIM = 8
LADDER = BucketLadder((4, 16, 64), (4, 8))


@pytest.fixture(autouse=True)
def _clean_events():
    events.clear()
    yield


def tag_searcher(tag, calls=None, delay=0.0):
    """Stub searcher whose indices are all ``tag`` and whose distances
    echo each query row's first component — demux correctness AND
    cross-tenant leakage are both id-spot-checkable."""

    def fn(queries, k, res=None):
        if calls is not None:
            calls.append(queries.shape[0])
        if delay:
            time.sleep(delay)
        m = queries.shape[0]
        d = np.tile(np.asarray(queries)[:, :1], (1, k)).astype(np.float32)
        i = np.full((m, k), tag, np.int64)
        return d, i

    return fn


def make_fabric(**kw):
    kw.setdefault("ladder", LADDER)
    kw.setdefault("autostart", False)
    kw.setdefault("registry", metrics.Registry())
    return ServeFabric(DIM, **kw)


def q_of(v, rows=1):
    q = np.full((rows, DIM), float(v), np.float32)
    return q


class TestWeightedDrain:
    def test_weighted_fairness_one_round(self):
        """Deficit WRR: one round credits weight x quantum rows, so a
        3:1 weight split drains a 3:1 request split from equal
        backlogs."""
        fab = make_fabric(quantum_rows=8)
        a = fab.add_tenant("a", search_fn=tag_searcher(1), weight=3.0)
        b = fab.add_tenant("b", search_fn=tag_searcher(2), weight=1.0)
        ra = [fab.submit("a", q_of(i), 4) for i in range(64)]
        rb = [fab.submit("b", q_of(i), 4) for i in range(64)]
        fab.drain_once()
        done_a = sum(r.done() for r in ra)
        done_b = sum(r.done() for r in rb)
        assert done_a == 24 and done_b == 8, (done_a, done_b)
        assert a.weight == 3.0 and len(b.queue) == 56

    def test_empty_queue_forfeits_credit(self):
        """Classic DRR: a silent tenant must not bank burst rights."""
        fab = make_fabric(quantum_rows=8)
        fab.add_tenant("a", search_fn=tag_searcher(1), weight=1.0)
        t = fab.tenant("a")
        fab.drain_once()        # empty round: credit granted, forfeited
        fab.drain_once()
        assert t._deficit == 0
        for i in range(32):
            fab.submit("a", q_of(i), 4)
        fab.drain_once()
        # one round's credit only (8 rows), not three banked rounds
        assert len(t.queue) == 24

    def test_cobatch_shared_searcher_and_demux(self):
        """Tenants sharing one searcher closure co-batch into ONE
        dispatch; every request still gets exactly its own rows
        back."""
        calls = []
        shared = tag_searcher(7, calls=calls)
        fab = make_fabric()
        fab.add_tenant("a", search_fn=shared)
        fab.add_tenant("b", search_fn=shared)
        ra = [fab.submit("a", q_of(10 + i), 4) for i in range(2)]
        rb = [fab.submit("b", q_of(20 + i), 4) for i in range(2)]
        fab.drain_once()
        assert len(calls) == 1 and calls[0] == 4  # one padded dispatch
        for i, r in enumerate(ra):
            assert r.result(1.0).distances[0, 0] == 10 + i
        for i, r in enumerate(rb):
            assert r.result(1.0).distances[0, 0] == 20 + i
        assert fab.snapshot()["cobatched_dispatches"] == 1

    def test_no_cross_tenant_leakage(self):
        """Distinct searchers: every answer carries its own tenant's
        tag, across interleaved submits and shared drain rounds."""
        fab = make_fabric()
        tags = {"a": 101, "b": 202, "c": 303}
        for name, tag in tags.items():
            fab.add_tenant(name, search_fn=tag_searcher(tag))
        futs = []
        for i in range(12):
            name = ["a", "b", "c"][i % 3]
            futs.append((name, fab.submit(name, q_of(i), 4)))
        while any(not f.done() for _, f in futs):
            fab.drain_once()
        for name, f in futs:
            ids = f.result(1.0).indices
            assert (ids == tags[name]).all(), (name, ids)


class TestTokenBucket:
    def test_bucket_refill(self):
        now = [0.0]
        b = TokenBucket(rate=2.0, burst=4.0, clock=lambda: now[0])
        assert all(b.try_take() for _ in range(4))
        assert not b.try_take()
        now[0] += 1.0           # refills 2 tokens
        assert b.try_take() and b.try_take() and not b.try_take()

    def test_rate_limit_sheds_self_only(self):
        now = [0.0]
        fab = make_fabric(clock=lambda: now[0])
        a = fab.add_tenant("a", search_fn=tag_searcher(1), rate=1.0,
                           burst=3.0)
        fab.add_tenant("b", search_fn=tag_searcher(2))
        ok, shed = 0, 0
        for i in range(8):
            try:
                fab.submit("a", q_of(i), 4)
                ok += 1
            except RateLimitedError:
                shed += 1
        assert (ok, shed) == (3, 5)
        assert a.registry.counter("a.shed").value == 5
        assert a.registry.counter("a.requests").value == 8
        # b unaffected
        fab.submit("b", q_of(0), 4)
        ev = events.recent(kind="tenant_shed")
        assert len(ev) == 5 and all(e["site"] == "a.admission"
                                    and e["trace_id"] for e in ev)


class TestIsolationDrill:
    """The ISSUE acceptance drill: a hot tenant past its token bucket
    sheds and brownouts ITSELF; the cold tenants' SLOs stay ok and
    their p99 holds within 1.5x of a solo run."""

    N = 40
    COLD = ("b", "c")

    def _run_cold(self, fab, tags):
        futs = [(n, fab.submit(n, q_of(i), 4))
                for i in range(self.N) for n in self.COLD]
        for n, f in futs:
            res = f.result(5.0)
            assert (res.indices == tags[n]).all(), "cross-tenant leak"

    def _p99(self, tenant):
        return tenant.registry.histogram(
            f"{tenant.name}.latency_s").percentile(99)

    def test_hot_tenant_isolated(self):
        tags = {"a": 11, "b": 22, "c": 33}
        cold_targets = Targets(p99_latency_s=0.5, max_shed_rate=0.3)

        # ---- solo run: B and C alone ------------------------------------
        solo = make_fabric(autostart=True)
        for n in self.COLD:
            solo.add_tenant(n, search_fn=tag_searcher(tags[n],
                                                      delay=0.0002))
        self._run_cold(solo, tags)
        p99_solo = {n: self._p99(solo.tenant(n)) for n in self.COLD}
        solo.close()

        # ---- combined run: hot A floods past its bucket -----------------
        fab = make_fabric(autostart=True)
        hot = fab.add_tenant(
            "a", search_fn=tag_searcher(tags["a"], delay=0.0002),
            rate=50.0, burst=20.0,
            targets=Targets(max_shed_rate=0.3))
        cold = {n: fab.add_tenant(n,
                                  search_fn=tag_searcher(tags[n],
                                                         delay=0.0002),
                                  targets=cold_targets)
                for n in self.COLD}
        # window baselines BEFORE traffic (burn-rate diffs need one)
        hot.slo.tick()
        for t in cold.values():
            t.slo.tick()
        hot_futs, hot_shed = [], 0
        for i in range(400):
            try:
                hot_futs.append(fab.submit("a", q_of(1000 + i), 4))
            except RateLimitedError:
                hot_shed += 1
        self._run_cold(fab, tags)
        for f in hot_futs:
            assert (f.result(5.0).indices == tags["a"]).all()
        assert hot_shed > 300, "the drill must actually exceed the bucket"

        tick = fab.tick()       # SLO poll + brownout act
        # A browned out / breached on ITS OWN shed budget...
        assert tick["a"]["slo_verdict"] == "breach"
        assert tick["a"]["brownout_level"] >= 1
        # ...while B and C stayed green at level 0
        for n in self.COLD:
            assert tick[n]["slo_verdict"] == "ok", (n, tick[n])
            assert tick[n]["brownout_level"] == 0
        # and the cold tenants' p99 held (1.5x of solo, floored to 50ms
        # against 1-core CI scheduler noise on sub-ms absolute values)
        for n in self.COLD:
            p99 = self._p99(fab.tenant(n))
            bound = max(1.5 * p99_solo[n], 0.05)
            assert p99 <= bound, (n, p99, p99_solo[n])
        fab.close()


class TestQueryCache:
    def test_lru_eviction_and_limits(self):
        reg = metrics.Registry()
        c = QueryCache(capacity=2, max_rows=2, registry=reg, name="t")
        k1 = c.key("a", q_of(1), 4, "p")
        k2 = c.key("a", q_of(2), 4, "p")
        k3 = c.key("a", q_of(3), 4, "p")
        assert c.key("a", q_of(1, rows=3), 4, "p") is None  # oversize
        c.put(k1, np.zeros((1, 4)), np.zeros((1, 4)))
        c.put(k2, np.zeros((1, 4)), np.zeros((1, 4)))
        assert c.get(k1) is not None        # refreshes k1
        c.put(k3, np.zeros((1, 4)), np.zeros((1, 4)))   # evicts k2 (LRU)
        assert c.get(k2) is None and c.get(k1) is not None
        assert c.snapshot()["evictions"] == 1
        # same bytes, different k / params / tenant: distinct keys
        assert c.key("a", q_of(1), 8, "p") != k1
        assert c.key("a", q_of(1), 4, "q") != k1
        assert c.key("b", q_of(1), 4, "p") != k1
        assert c.invalidate_tenant("a") == 2
        assert len(c) == 0

    def test_hit_miss_bypass_counters(self):
        fab = make_fabric(cache=QueryCache(capacity=8, max_rows=2,
                                           registry=metrics.Registry()))
        fab.add_tenant("a", search_fn=tag_searcher(1))
        r1 = fab.submit("a", q_of(5), 4)
        fab.drain_once()
        r1.result(1.0)
        r2 = fab.submit("a", q_of(5), 4)        # byte-identical repeat
        assert r2.done(), "hit must complete without a dispatch"
        assert (r2.result(0.1).indices == r1.result(0.1).indices).all()
        fab.submit("a", q_of(5), 4, cache=False)         # bypass
        fab.submit("a", q_of(5, rows=3), 4)              # oversize bypass
        fab.drain_once()
        snap = fab.cache.snapshot()
        assert snap["hits"] == 1 and snap["bypass"] == 2
        assert fab.tenant("a").snapshot()["qcache"]["hits"] == 1

    def test_swap_invalidates_and_records_one_event(self):
        fab = make_fabric(cache=QueryCache(capacity=8,
                                           registry=metrics.Registry()))
        t = fab.add_tenant("a", search_fn=tag_searcher(1))
        r = fab.submit("a", q_of(5), 4)
        fab.drain_once()
        assert (r.result(1.0).indices == 1).all()
        assert fab.submit("a", q_of(5), 4).done()        # warm hit
        gen = t.swap(search_fn=tag_searcher(9), warm=False)
        assert gen == 1 and t.generation == 1
        r2 = fab.submit("a", q_of(5), 4)
        assert not r2.done(), "swap must defeat the cache"
        fab.drain_once()
        assert (r2.result(1.0).indices == 9).all()
        ev = events.recent(kind="tenant_swap")
        assert len(ev) == 1 and ev[0]["site"] == "a.swap"
        assert fab.cache.snapshot()["invalidated"] >= 1
        assert fab.tick()["a"]["retired"] == 1           # old pair released

    def test_degraded_sharded_result_never_cached(self):
        """A degraded sharded answer (shards_ok not all true) must not
        be cached: a replayed hit drops shards_ok, and the degradation
        would outlive the shard's recovery (no generation flip defeats
        the key)."""
        ok = [np.array([True, False])]   # one dead shard, mutable cell

        def sharded_fn(queries, k, res=None):
            m = queries.shape[0]
            return (np.zeros((m, k), np.float32),
                    np.full((m, k), 4, np.int64), ok[0])

        fab = make_fabric(cache=QueryCache(capacity=8,
                                           registry=metrics.Registry()))
        fab.add_tenant("a", search_fn=sharded_fn)
        r = fab.submit("a", q_of(5), 4)
        fab.drain_once()
        assert not r.result(1.0).shards_ok.all()
        r2 = fab.submit("a", q_of(5), 4)
        assert not r2.done(), "degraded answer must not have been cached"
        ok[0] = np.array([True, True])   # shard recovered
        fab.drain_once()
        assert r2.result(1.0).shards_ok.all()
        # healthy answers DO cache
        assert fab.submit("a", q_of(5), 4).done()

    def test_mutable_generation_flip_invalidates(self):
        """A background-merge generation flip (index.generation bump)
        orphans the tenant's entries via the key, no explicit call."""

        class FakeMutable:
            generation = 0

        idx = FakeMutable()
        fab = make_fabric(cache=QueryCache(capacity=8,
                                           registry=metrics.Registry()))
        fab.add_tenant("m", index=idx, search_fn=tag_searcher(3))
        r = fab.submit("m", q_of(7), 4)
        fab.drain_once()
        r.result(1.0)
        assert fab.submit("m", q_of(7), 4).done()        # hit at gen 0
        idx.generation = 1                               # merge flipped
        r2 = fab.submit("m", q_of(7), 4)
        assert not r2.done(), "generation flip must defeat the cache"
        fab.drain_once()
        r2.result(1.0)


class TestCacheSentinel:
    def test_sentinel_catches_poisoned_entry(self):
        """The police satellite: a poisoned cache entry served as a hit
        crosses the sentinel floor -> recall_regression (family
        qcache) + qcache_stale event + eager invalidation."""
        truth = tag_searcher(5)

        def ref(queries, k):
            return truth(queries, k)

        sreg = metrics.Registry()
        sent = RecallSentinel(ref, sample=1.0, floor=0.9, min_samples=1,
                              window=4, registry=sreg, name="a")
        fab = make_fabric(cache=QueryCache(capacity=8,
                                           registry=metrics.Registry()))
        t = fab.add_tenant("a", search_fn=truth, sentinel=sent)
        r = fab.submit("a", q_of(5), 4)
        fab.drain_once()
        r.result(1.0)
        sent.drain(10.0)
        # poison the cached entry in place (a bug, bit-rot, or a swap
        # that forgot to invalidate — the sentinel must catch all of
        # them the same way)
        (key, (d, i)), = list(fab.cache._map.items())
        fab.cache._map[key] = (np.full_like(d, 1e6),
                               np.full_like(i, 777))
        r2 = fab.submit("a", q_of(5), 4)
        assert r2.done() and (r2.result(0.1).indices == 777).all()
        assert sent.drain(10.0)
        reg_ev = events.recent(kind="recall_regression")
        assert reg_ev and reg_ev[-1]["site"] == "a.recall.qcache"
        stale = events.recent(kind="qcache_stale")
        assert len(stale) == 1 and stale[0]["site"] == "a.qcache"
        assert stale[0]["trace_id"] == r2.trace_id
        assert t.registry.counter("a.qcache.stale").value == 1
        assert len(fab.cache) == 0, "stale tenant entries must be dropped"
        sent.close()


@pytest.fixture(scope="module")
def bf_pair():
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force

    rng = np.random.default_rng(3)
    d1 = rng.standard_normal((64, DIM)).astype(np.float32)
    d2 = rng.standard_normal((64, DIM)).astype(np.float32)
    return (brute_force.build(jnp.asarray(d1)),
            brute_force.build(jnp.asarray(d2)))


class TestSwapUnderLoad:
    def test_zero_downtime_swap(self, bf_pair):
        """The ISSUE swap drill: sustained concurrent load across the
        flip, zero dropped/failed requests, one tenant_swap event,
        cache invalidated, and zero steady-state recompiles after the
        flip (the replacement was pre-warmed at the served shapes)."""
        idx1, idx2 = bf_pair
        fab = ServeFabric(DIM, ladder=BucketLadder((4, 16), (4,)),
                          cache=QueryCache(capacity=64,
                                           registry=metrics.Registry()),
                          registry=metrics.Registry(), name="swapfab")
        t = fab.add_tenant("s", index=idx1, warm=True)
        rng = np.random.default_rng(0)
        futs, errs = [], []

        def client():
            for _ in range(80):
                q = rng.standard_normal(
                    (int(rng.integers(1, 4)), DIM)).astype(np.float32)
                try:
                    futs.append(fab.submit("s", q, 4, cache=False))
                except Exception as e:  # noqa: BLE001 - drill bookkeeping
                    errs.append(e)
                time.sleep(0.0005)

        th = threading.Thread(target=client)
        th.start()
        time.sleep(0.01)
        gen = t.swap(idx2)              # warm=True: off the hot path
        th.join()
        assert not errs and gen == 1
        for f in futs:
            res = f.result(10.0)        # zero dropped futures
            assert res.indices.shape[1] == 4
        ev = events.recent(kind="tenant_swap")
        assert len(ev) == 1 and ev[0]["generation"] == 1
        assert fab.cache.snapshot()["invalidated"] >= 0
        # post-flip steady state never recompiles: every served shape
        # was pre-warmed through the replacement before the flip
        with count_compilations() as cc:
            for _ in range(4):
                res = fab.search("s", np.ones((2, DIM), np.float32), 4,
                                 timeout=10.0)
                assert res.indices.shape == (2, 4)
        assert cc.count == 0, "post-swap dispatch recompiled"
        fab.close()


class TestPerTenantSLOIndependence:
    def test_one_tenant_breaches_alone(self):
        """Per-tenant SLO engines + brownout controllers over private
        registries: tenant A's latency breach steps A's ladder; B
        (same fabric, same process) stays green at level 0 — the
        generalization of the process-global install() slots."""
        now = [0.0]
        fab = make_fabric(clock=lambda: now[0])
        regs = {}
        tenants = {}
        for n in ("a", "b"):
            reg = metrics.Registry()
            slo = SLOEngine(Targets(p99_latency_s=0.01), registry=reg,
                            name=n, clock=lambda: now[0])
            from raft_tpu.serve.degrade import BrownoutController

            ctl = BrownoutController(slo=slo, registry=reg, name=n,
                                     min_dwell_s=0.0,
                                     clock=lambda: now[0])
            tenants[n] = fab.add_tenant(n, search_fn=tag_searcher(1),
                                        slo=slo, brownout=ctl,
                                        registry=reg)
            regs[n] = reg
            slo.tick()
        now[0] += 1.0
        for _ in range(20):
            regs["a"].histogram("a.latency_s").observe(0.2)   # breach
            regs["b"].histogram("b.latency_s").observe(0.001)  # fine
        now[0] += 400.0         # both windows cover the bad minute
        tick = fab.tick()
        assert tick["a"]["slo_verdict"] == "breach"
        assert tick["a"]["brownout_level"] == 1
        assert tick["b"]["slo_verdict"] == "ok"
        assert tick["b"]["brownout_level"] == 0
        # params degradation is scoped to A too
        assert tenants["a"].brownout.max_wait_scale() > 1.0
        assert tenants["b"].brownout.max_wait_scale() == 1.0


class TestDebugz:
    def test_tenants_section_strict_json_and_text(self, tmp_path):
        fab = make_fabric(cache=QueryCache(capacity=8,
                                           registry=metrics.Registry()))
        fab.add_tenant("acme", search_fn=tag_searcher(1), weight=2.0,
                       rate=100.0, targets=Targets(max_shed_rate=0.5))
        r = fab.submit("acme", q_of(1), 4)
        fab.drain_once()
        r.result(1.0)
        install(fab)
        try:
            s = debugz.snapshot(registry=metrics.Registry())
            json.dumps(s, allow_nan=False)      # strict-JSON preserved
            te = s["tenants"]["tenants"]["acme"]
            for field in ("weight", "generation", "queue_depth", "shed",
                          "served", "qcache", "slo", "tokens"):
                assert field in te, field
            assert s["tenants"]["qcache"]["capacity"] == 8
            txt = debugz.render_text(registry=metrics.Registry())
            assert "-- tenants" in txt and "acme:" in txt
        finally:
            uninstall()
        # SnapshotWriter(fabric=...) wires the maintenance tick
        w = debugz.SnapshotWriter(str(tmp_path / "z.json"), fabric=fab)
        w.tick()                # runs fabric.tick via the hook slot
        disk = w.write_once()
        assert "tenants" in disk
        json.dumps(disk, allow_nan=False)

    def test_warmup_shapes_subset(self):
        """warmup(shapes=...) sweeps exactly the named shapes — the
        swap warm set."""
        from raft_tpu.serve import warmup as w

        calls = []

        def fn(q, k, res=None):
            calls.append((q.shape[0], k))
            return (np.zeros((q.shape[0], k), np.float32),
                    np.zeros((q.shape[0], k), np.int64))

        reg = metrics.Registry()
        w.warmup(fn, LADDER, DIM, registry=reg, name="sub",
                 shapes=[(4, 4), (16, 8)])
        assert calls == [(4, 4), (16, 8)]
        assert reg.gauge("sub.warmup.shapes").value == 2
