"""IVF-PQ + refine tests (analog of NEIGHBORS_ANN_IVF_TEST pq cases +
cpp/test/neighbors/refine.cu): recall vs brute-force oracle, never exact
equality (SURVEY.md §4)."""
import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import ivf_pq, refine


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    return rng.standard_normal((20_000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(8)
    return rng.standard_normal((100, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def built_index(dataset):
    return ivf_pq.build(dataset, ivf_pq.IndexParams(
        n_lists=64, pq_dim=8, pq_bits=8, seed=0))


class TestIvfPq:
    def test_structure(self, built_index, dataset):
        assert built_index.size == len(dataset)
        assert built_index.n_lists == 64
        assert built_index.pq_dim == 8
        assert built_index.pq_len == 4
        assert built_index.rot_dim == 32
        assert built_index.list_sizes.sum() == len(dataset)
        ids = np.asarray(built_index.source_ids)
        np.testing.assert_array_equal(np.sort(ids[ids >= 0]),
                                      np.arange(len(dataset)))
        # rotation has orthonormal columns
        r = np.asarray(built_index.rotation)
        np.testing.assert_allclose(r.T @ r, np.eye(32), atol=1e-5)

    # thresholds calibrated on unstructured gaussian data — the PQ worst
    # case: the full-scan ADC oracle (exact search over reconstructions)
    # itself only reaches 0.552 recall@10 here, and n_probes=64 matches it
    # exactly; real datasets cluster far better.
    @pytest.mark.parametrize("n_probes,min_recall", [(16, 0.45), (64, 0.52)])
    def test_recall(self, built_index, dataset, queries, n_probes, min_recall):
        _, idx = ivf_pq.search(built_index, queries, k=10,
                               params=ivf_pq.SearchParams(n_probes))
        _, want = naive_knn(dataset, queries, 10)
        r = calc_recall(np.asarray(idx), want)
        assert r >= min_recall, f"recall {r} < {min_recall} at n_probes={n_probes}"

    def test_refine_lifts_recall(self, built_index, dataset, queries):
        _, cand = ivf_pq.search(built_index, queries, k=100,
                                params=ivf_pq.SearchParams(64))
        dist, idx = refine.refine(dataset, queries, cand, k=10)
        _, want = naive_knn(dataset, queries, 10)
        raw = calc_recall(np.asarray(cand[:, :10]), want)
        refined = calc_recall(np.asarray(idx), want)
        assert refined > raw
        assert refined >= 0.9
        # refined distances are exact L2^2
        d = np.asarray(dist)
        i = np.asarray(idx)
        for row in range(0, 100, 23):
            true = ((queries[row] - dataset[i[row, 0]]) ** 2).sum()
            assert abs(d[row, 0] - true) < 1e-1

    @pytest.mark.slow  # 23s single-core: variant-recall check; the
    # PER_SUBSPACE path keeps tier-1 coverage of the shared machinery
    def test_per_cluster_codebooks(self, dataset, queries):
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=32, pq_dim=8, codebook_kind=ivf_pq.CodebookGen.PER_CLUSTER,
            seed=0))
        assert index.codebooks.shape[0] == 32
        _, idx = ivf_pq.search(index, queries, k=10,
                               params=ivf_pq.SearchParams(32))
        _, want = naive_knn(dataset, queries, 10)
        # full-probe search matches the per-cluster ADC oracle (0.541) exactly
        assert calc_recall(np.asarray(idx), want) >= 0.5

    def test_inner_product(self, dataset, queries):
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=32, pq_dim=8, metric="inner_product", seed=0))
        dist, idx = ivf_pq.search(index, queries, k=10,
                                  params=ivf_pq.SearchParams(16))
        want_d, want = naive_knn(dataset, queries, 10, "inner_product")
        assert calc_recall(np.asarray(idx), want) >= 0.5
        # reported distances are (approximate) true inner products, descending
        d = np.asarray(dist)
        assert (np.diff(d, axis=1) <= 1e-3).all()

    def test_pq_bits_4(self, dataset, queries):
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=32, pq_dim=16, pq_bits=4, seed=0))
        assert index.pq_book_size == 16
        assert int(np.asarray(index.codes).max()) < 16
        _, idx = ivf_pq.search(index, queries, k=10,
                               params=ivf_pq.SearchParams(32))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.4

    @pytest.mark.slow  # 20s single-core for a relative recall-delta
    # check between two lut_dtype rungs of the same scan (cf. the
    # tier-1 budget note on test_int8_lut_pq_bits_4 below)
    def test_int8_lut_mode(self, dataset, queries):
        """fp8-LUT role (ivf_pq_types.hpp:110-146): the int8-quantized
        codebook scan must track the bf16 scan's recall closely."""
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=32, pq_dim=16, seed=0))
        _, want = naive_knn(dataset, queries, 10)
        _, idx_bf = ivf_pq.search(index, queries, k=10, algo="pallas",
                                  params=ivf_pq.SearchParams(16))
        _, idx_i8 = ivf_pq.search(
            index, queries, k=10, algo="pallas",
            params=ivf_pq.SearchParams(16, lut_dtype="int8"))
        r_bf = calc_recall(np.asarray(idx_bf), want)
        r_i8 = calc_recall(np.asarray(idx_i8), want)
        assert r_i8 >= r_bf - 0.03, (r_i8, r_bf)

    @pytest.mark.xfail(
        strict=False, run=False,
        reason="known jax-0.4.37 interpret divergence: pltpu.repeat is "
               "ELEMENT-wise (np.repeat) under the CPU interpreter while "
               "the ivf_pq one-hot decode requires tiling semantics "
               "(see ivf_pq_scan.make_cb_matrix) — recall collapses for "
               "every interpret lut_mode, most visibly here; expected to "
               "pass on the Mosaic lowering (tiling), pending first "
               "real-TPU validation. run=False: environment-pinned and "
               "the ~20s run only burns the tight tier-1 budget")
    def test_int8_lut_pq_bits_4(self, dataset, queries):
        """int8 LUT composes with the 16-entry (pq_bits=4) codebooks."""
        index = ivf_pq.build(dataset, ivf_pq.IndexParams(
            n_lists=32, pq_dim=32, pq_bits=4, seed=0))
        _, idx = ivf_pq.search(
            index, queries, k=10, algo="pallas",
            params=ivf_pq.SearchParams(32, lut_dtype="int8"))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.5

    def test_non_divisible_dim_pads(self, queries):
        rng = np.random.default_rng(3)
        data = rng.standard_normal((5000, 30)).astype(np.float32)
        index = ivf_pq.build(data, ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, seed=0))
        assert index.rot_dim == 32 and index.dim == 30
        _, idx = ivf_pq.search(index, queries[:, :30], k=10,
                               params=ivf_pq.SearchParams(16))
        _, want = naive_knn(data, queries[:, :30], 10)
        assert calc_recall(np.asarray(idx), want) >= 0.5

    def test_reconstruct(self, built_index, dataset):
        rows = np.arange(0, 200)
        approx = np.asarray(ivf_pq.reconstruct(built_index, rows))
        orig = dataset[np.asarray(built_index.source_ids)[rows]]
        rel = np.linalg.norm(approx - orig) / np.linalg.norm(orig)
        assert rel < 0.5  # lossy but meaningful

    def test_extend(self, dataset, queries):
        p = ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=0)
        index = ivf_pq.build(dataset[:10_000], p)
        index = ivf_pq.extend(index, dataset[10_000:],
                              np.arange(10_000, 20_000, dtype=np.int32))
        assert index.size == 20_000
        _, idx = ivf_pq.search(index, queries, k=10,
                               params=ivf_pq.SearchParams(32))
        _, want = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.45

    def test_extend_in_place_with_growth_slack(self, dataset, queries):
        p = ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=0,
                               list_growth=2.0)
        index = ivf_pq.build(dataset[:10_000], p)
        off0 = index.list_offsets.copy()
        index2 = ivf_pq.extend(index, dataset[10_000:13_000],
                               np.arange(10_000, 13_000, dtype=np.int32))
        # fits in slack: same offsets, O(batch) in-place scatter
        np.testing.assert_array_equal(index2.list_offsets, off0)
        assert index2.size == 13_000
        _, idx = ivf_pq.search(index2, queries, k=10,
                               params=ivf_pq.SearchParams(32))
        _, want = naive_knn(dataset[:13_000], queries, 10)
        assert calc_recall(np.asarray(idx), want) >= 0.45

    def test_filter(self, built_index, dataset, queries):
        _, base = naive_knn(dataset, queries, 1)
        mask = np.ones(len(dataset), bool)
        mask[base[:, 0]] = False
        filt = Bitset.from_mask(jnp.asarray(mask))
        _, idx = ivf_pq.search(built_index, queries, k=10,
                               params=ivf_pq.SearchParams(64), filter=filt)
        got = np.asarray(idx)
        assert all(base[i, 0] not in got[i] for i in range(len(got)))

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(0)
        for bits in (4, 5, 8):
            codes = rng.integers(0, 1 << bits, (100, 12)).astype(np.uint8)
            packed = ivf_pq.pack_codes(codes, bits)
            assert packed.shape[1] < 12 or bits == 8
            np.testing.assert_array_equal(
                ivf_pq.unpack_codes(packed, 12, bits), codes)

    def test_save_load(self, tmp_path, built_index, queries):
        ivf_pq.save(built_index, tmp_path / "pq.raft")
        loaded = ivf_pq.load(tmp_path / "pq.raft")
        d1, i1 = ivf_pq.search(built_index, queries, k=5,
                               params=ivf_pq.SearchParams(16))
        d2, i2 = ivf_pq.search(loaded, queries, k=5,
                               params=ivf_pq.SearchParams(16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_query_chunking_matches(self, built_index, queries):
        d1, i1 = ivf_pq.search(built_index, queries, k=5,
                               params=ivf_pq.SearchParams(16), query_chunk=7)
        d2, i2 = ivf_pq.search(built_index, queries, k=5,
                               params=ivf_pq.SearchParams(16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_index_as_jit_argument(self, built_index, queries,
                                   monkeypatch):
        """The Index pytree carries its scan-prep cache, so a jitted
        function may take the index as an ARGUMENT (arrays become
        program parameters, not closure-baked HLO constants — at 500k
        rows baked constants exceed remote-compile request limits) and
        must match the eager path WITHOUT re-deriving the cache (the
        in-trace _scan_prep fallback would silently mask a broken
        flatten/unflatten round-trip, so it is forbidden here)."""
        import jax

        ivf_pq.prepare_scan(built_index)
        leaves, td = jax.tree_util.tree_flatten(built_index)
        rebuilt = jax.tree_util.tree_unflatten(td, leaves)
        cache0, cache1 = built_index._scan_cache, rebuilt._scan_cache
        assert cache1 is not None
        # the cache must survive BYTE-IDENTICAL: off-TPU the search path
        # below doesn't consume it (pallas is TPU-only), so leaf mixups
        # must be caught here, not by the recall check
        assert cache1["n"] == cache0["n"] and cache1["lmax"] == cache0["lmax"]
        for key in ("codes_p", "norms_p", "cbm"):
            np.testing.assert_array_equal(np.asarray(cache0[key]),
                                          np.asarray(cache1[key]))

        def no_prep(*a, **k):  # noqa: ARG001
            raise AssertionError(
                "scan cache was re-derived under the trace: the pytree "
                "dropped it")

        monkeypatch.setattr(ivf_pq, "_scan_prep", no_prep)
        fn = jax.jit(lambda q, idx: ivf_pq.search(
            idx, q, 5, ivf_pq.SearchParams(16)))
        d1, i1 = fn(queries, rebuilt)
        d2, i2 = ivf_pq.search(built_index, queries, k=5,
                               params=ivf_pq.SearchParams(16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5, atol=1e-5)


class TestRefine:
    def test_refine_exact_when_candidates_cover(self, dataset, queries):
        # candidates = true top-30 → refine top-10 must equal naive top-10
        _, cand = naive_knn(dataset, queries, 30)
        dist, idx = refine.refine(dataset, queries, cand, k=10)
        want_d, want_i = naive_knn(dataset, queries, 10)
        np.testing.assert_allclose(np.asarray(dist), want_d, rtol=1e-2, atol=1e-2)
        assert calc_recall(np.asarray(idx), want_i) == 1.0

    def test_refine_handles_negative_ids(self, dataset, queries):
        _, cand = naive_knn(dataset, queries, 20)
        cand = np.asarray(cand)
        cand[:, 15:] = -1
        dist, idx = refine.refine(dataset, queries, cand, k=18)
        assert (np.asarray(idx)[:, -1] == -1).all()
        assert np.isinf(np.asarray(dist)[:, -1]).all()

    def test_refine_bf16_dataset(self, dataset, queries):
        """A bf16 corpus copy (half the gather traffic) must re-rank to
        near-identical top-k."""
        import jax.numpy as jnp

        _, cand = naive_knn(dataset, queries, 30)
        _, idx = refine.refine(jnp.asarray(dataset, jnp.bfloat16),
                               queries, cand, k=10)
        _, want_i = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(idx), want_i) >= 0.98

    def test_refine_uint8_dataset(self):
        """Byte corpora re-rank exactly through the uint8 gather path:
        quarter-traffic gather, widened to f32 AFTER the gather so the
        exact f32 contraction still runs."""
        import jax.numpy as jnp

        rng = np.random.default_rng(9)
        bdata = rng.integers(0, 256, size=(3000, 32)).astype(np.float32)
        bq = rng.integers(0, 256, size=(30, 32)).astype(np.float32)
        _, cand = naive_knn(bdata, bq, 30)
        _, idx = refine.refine(jnp.asarray(bdata, jnp.uint8), bq, cand,
                               k=10)
        _, want_i = naive_knn(bdata, bq, 10)
        assert calc_recall(np.asarray(idx), want_i) >= 0.98

    def test_refine_inner_product(self, dataset, queries):
        _, cand = naive_knn(dataset, queries, 30, "inner_product")
        dist, idx = refine.refine(dataset, queries, cand, k=10,
                                  metric="inner_product")
        want_d, want_i = naive_knn(dataset, queries, 10, "inner_product")
        assert calc_recall(np.asarray(idx), want_i) == 1.0
        np.testing.assert_allclose(np.asarray(dist), want_d, rtol=1e-2, atol=1e-2)


def test_pq_build_from_batches(dataset, queries):
    batches = [dataset[i : i + 4096] for i in range(0, len(dataset), 4096)]
    p = ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=0)
    idx = ivf_pq.build_from_batches(iter(batches), p)
    assert idx.size == len(dataset)
    _, i = ivf_pq.search(idx, queries, 10, ivf_pq.SearchParams(32))
    _, want = naive_knn(dataset, queries, 10)
    assert calc_recall(np.asarray(i), want) >= 0.45
