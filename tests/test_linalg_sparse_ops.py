"""Tests for the linalg façade (SURVEY §2.6), sparse op module, sparse
cross-component NN (§2.8) and the random long tail (§2.9)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg
from raft_tpu.sparse import (COO, CSR, coalesce, cross_component_nn,
                             filter_entries, remove_zeros, row_op, sort_coo)


class TestLinalg:
    def test_gemm_gemv_axpy(self, rng):
        a = rng.standard_normal((16, 8)).astype(np.float32)
        b = rng.standard_normal((8, 4)).astype(np.float32)
        c = rng.standard_normal((16, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.gemm(a, b, 2.0, 3.0, c)),
                                   2.0 * a @ b + 3.0 * c, rtol=1e-5)
        x = rng.standard_normal(8).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.gemv(a, x)), a @ x,
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(linalg.axpy(2.0, x, x)), 3 * x,
                                   rtol=1e-6)

    def test_factorizations(self, rng):
        a = rng.standard_normal((12, 12)).astype(np.float32)
        sym = a @ a.T + 12 * np.eye(12, dtype=np.float32)
        w, v = linalg.eig(sym)
        np.testing.assert_allclose(np.asarray(v @ jnp.diag(w) @ v.T), sym,
                                   rtol=1e-3, atol=1e-3)
        q, r = linalg.qr(a)
        np.testing.assert_allclose(np.asarray(q @ r), a, rtol=1e-4, atol=1e-4)
        u, s, vt = linalg.svd(a)
        np.testing.assert_allclose(np.asarray(u * s @ vt), a, rtol=1e-3,
                                   atol=1e-3)

    def test_rsvd_matches_svd_spectrum(self, rng):
        # low-rank + noise: rsvd top-k singular values track exact SVD
        u = rng.standard_normal((100, 5)).astype(np.float32)
        v = rng.standard_normal((5, 60)).astype(np.float32)
        a = u @ v + 0.01 * rng.standard_normal((100, 60)).astype(np.float32)
        _, s_exact, _ = np.linalg.svd(a, full_matrices=False)
        ur, sr, vtr = linalg.rsvd(jax.random.PRNGKey(0), jnp.asarray(a), k=5)
        np.testing.assert_allclose(np.asarray(sr), s_exact[:5], rtol=1e-2)
        approx = np.asarray(ur * sr @ vtr)
        assert np.linalg.norm(approx - a) / np.linalg.norm(a) < 0.05

    def test_lstsq(self, rng):
        a = rng.standard_normal((50, 8)).astype(np.float32)
        x_true = rng.standard_normal(8).astype(np.float32)
        b = a @ x_true
        x = np.asarray(linalg.lstsq(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_allclose(x, x_true, rtol=1e-3, atol=1e-3)

    def test_cholesky_rank_one_update(self, rng):
        a = rng.standard_normal((6, 6)).astype(np.float32)
        sym = a @ a.T + 6 * np.eye(6, dtype=np.float32)
        x = rng.standard_normal(6).astype(np.float32)
        l = np.linalg.cholesky(sym)
        l2 = np.asarray(linalg.cholesky_rank_one_update(
            jnp.asarray(l), jnp.asarray(x), alpha=0.5))
        np.testing.assert_allclose(l2 @ l2.T, sym + 0.5 * np.outer(x, x),
                                   rtol=1e-3, atol=1e-3)

    def test_norms_and_reductions(self, rng):
        a = rng.standard_normal((10, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.norm(a, -2, axis=1)),
                                   (a * a).sum(1), rtol=1e-5)
        nrm = np.asarray(linalg.normalize(a))
        np.testing.assert_allclose(np.linalg.norm(nrm, axis=1),
                                   np.ones(10), rtol=1e-5)
        keys = jnp.asarray([0, 1, 0, 1, 2, 2, 0, 1, 2, 0])
        out = np.asarray(linalg.reduce_rows_by_key(jnp.asarray(a), keys, 3))
        want = np.stack([a[np.asarray(keys) == i].sum(0) for i in range(3)])
        np.testing.assert_allclose(out, want, rtol=1e-5)


class TestSparseOps:
    def _coo(self, rng, shape=(20, 30), nnz=80):
        r = rng.integers(0, shape[0], nnz).astype(np.int32)
        c = rng.integers(0, shape[1], nnz).astype(np.int32)
        v = rng.standard_normal(nnz).astype(np.float32)
        return COO(jnp.asarray(r), jnp.asarray(c), jnp.asarray(v), shape)

    def test_filter_and_remove_zeros(self, rng):
        m = self._coo(rng)
        vals = np.asarray(m.vals).copy()
        vals[::3] = 0.0
        m = COO(m.rows, m.cols, jnp.asarray(vals), m.shape)
        out = remove_zeros(m)
        assert out.nnz == int((vals != 0).sum())
        pos = filter_entries(m, lambda r, c, v: v > 0)
        assert (np.asarray(pos.vals) > 0).all()

    def test_coalesce_matches_scipy(self, rng):
        import scipy.sparse as sps

        m = self._coo(rng, nnz=200)  # dense dupes at 20x30
        ref = sps.coo_matrix(
            (np.asarray(m.vals), (np.asarray(m.rows), np.asarray(m.cols))),
            shape=m.shape)
        out = coalesce(m, op="add")
        np.testing.assert_allclose(np.asarray(out.to_dense()),
                                   ref.toarray(), rtol=1e-5, atol=1e-6)

    def test_row_op_and_sort(self, rng):
        m = self._coo(rng)
        doubled = row_op(m, lambda v, r: v * 2.0)
        np.testing.assert_allclose(np.asarray(doubled.vals),
                                   np.asarray(m.vals) * 2, rtol=1e-6)
        s = sort_coo(m)
        key = np.asarray(s.rows).astype(np.int64) * m.shape[1] + np.asarray(s.cols)
        assert (np.diff(key) >= 0).all()


class TestCrossComponentNN:
    def test_nearest_other_component(self, rng):
        # two well-separated blobs: every point's cross-component NN must be
        # in the other blob, and the component-min edge bridges the gap
        a = rng.standard_normal((40, 8)).astype(np.float32)
        b = rng.standard_normal((30, 8)).astype(np.float32) + 50.0
        x = np.concatenate([a, b])
        labels = np.array([0] * 40 + [1] * 30)
        d, i = cross_component_nn(jnp.asarray(x), jnp.asarray(labels))
        i = np.asarray(i)
        assert (i[:40] >= 40).all() and (i[40:] < 40).all()
        # distances are true squared L2 to the reported neighbor
        d = np.asarray(d)
        row = 3
        np.testing.assert_allclose(d[row], ((x[row] - x[i[row]]) ** 2).sum(),
                                   rtol=1e-3)

    def test_csr_input(self, rng):
        x = rng.standard_normal((30, 6)).astype(np.float32)
        labels = np.arange(30) % 3
        d1, i1 = cross_component_nn(jnp.asarray(x), jnp.asarray(labels))
        d2, i2 = cross_component_nn(CSR.from_dense(x), jnp.asarray(labels))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_single_component_returns_sentinel(self, rng):
        x = rng.standard_normal((10, 4)).astype(np.float32)
        d, i = cross_component_nn(jnp.asarray(x), jnp.zeros(10, np.int32))
        assert (np.asarray(i) == -1).all()
        assert np.isinf(np.asarray(d)).all()


class TestKernelGramCSR:
    def test_csr_matches_dense(self, rng):
        from raft_tpu.distance.kernels import KernelParams, KernelType, gram_matrix

        x = rng.standard_normal((25, 10)).astype(np.float32)
        x[rng.random((25, 10)) < 0.6] = 0.0
        y = rng.standard_normal((15, 10)).astype(np.float32)
        for kt in KernelType:
            p = KernelParams(kernel=kt, gamma=0.3, coef0=0.5, degree=2)
            kd = np.asarray(gram_matrix(jnp.asarray(x), jnp.asarray(y), p))
            ks = np.asarray(gram_matrix(CSR.from_dense(x), jnp.asarray(y), p))
            np.testing.assert_allclose(ks, kd, rtol=1e-4, atol=1e-5)
        # tiled CSR path
        p = KernelParams(kernel=KernelType.RBF, gamma=0.3)
        kt_ = np.asarray(gram_matrix(CSR.from_dense(x), jnp.asarray(y), p,
                                     tile_rows=8))
        kd = np.asarray(gram_matrix(jnp.asarray(x), jnp.asarray(y), p))
        np.testing.assert_allclose(kt_, kd, rtol=1e-4, atol=1e-5)


class TestMultivariableGaussian:
    def test_moments(self):
        from raft_tpu.random import RngState, multivariable_gaussian

        mean = np.array([1.0, -2.0, 0.5], np.float32)
        a = np.array([[2.0, 0.3, 0.0], [0.3, 1.0, 0.2], [0.0, 0.2, 0.5]],
                     np.float32)
        draws = np.asarray(multivariable_gaussian(RngState(0), 20000, mean, a))
        np.testing.assert_allclose(draws.mean(0), mean, atol=0.05)
        np.testing.assert_allclose(np.cov(draws.T), a, atol=0.1)


class TestSparseMetricBreadth:
    def test_wider_metric_set_matches_dense(self, rng):
        from scipy.spatial import distance as sp

        from raft_tpu.sparse import CSR, sparse_pairwise_distance

        x = rng.standard_normal((20, 12)).astype(np.float32)
        x[rng.random((20, 12)) < 0.5] = 0.0
        y = rng.standard_normal((15, 12)).astype(np.float32)
        y[rng.random((15, 12)) < 0.5] = 0.0
        xc, yc = CSR.from_dense(x), CSR.from_dense(y)
        for metric, ref in [
            ("l2_unexpanded", sp.cdist(x, y, "sqeuclidean")),
            ("braycurtis", sp.cdist(x, y, "braycurtis")),
            ("lp", sp.cdist(x, y, "minkowski", p=3.0)),
        ]:
            got = np.asarray(sparse_pairwise_distance(
                xc, yc, metric, metric_arg=3.0))
            np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)
