"""Distance tests vs SciPy oracle (analog of DISTANCE_TEST, which compares
CUDA kernels against a simple reference kernel — cpp/test/distance/distance_base.cuh)."""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial import distance as sp

from raft_tpu.distance import (
    DistanceType,
    KernelParams,
    KernelType,
    canonical_metric,
    fused_l2_nn_argmin,
    gram_matrix,
    is_min_close,
    masked_l2_nn_argmin,
    pairwise_distance,
)

SCIPY_METRICS = [
    ("sqeuclidean", "sqeuclidean", {}),
    ("euclidean", "euclidean", {}),
    ("l2_unexpanded", "sqeuclidean", {}),
    ("l2_sqrt_unexpanded", "euclidean", {}),
    ("cosine", "cosine", {}),
    ("l1", "cityblock", {}),
    ("linf", "chebyshev", {}),
    ("canberra", "canberra", {}),
    ("minkowski", "minkowski", {"p": 3.0}),
    ("correlation", "correlation", {}),
    ("braycurtis", "braycurtis", {}),
    ("jensenshannon", "jensenshannon", {}),
]


def _data(rng, m=33, n=47, d=24, positive=False):
    x = rng.standard_normal((m, d)).astype(np.float32)
    y = rng.standard_normal((n, d)).astype(np.float32)
    if positive:
        x, y = np.abs(x) + 0.01, np.abs(y) + 0.01
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
    return x, y


@pytest.mark.parametrize("ours,scipy_name,kw", SCIPY_METRICS)
def test_pairwise_vs_scipy(rng, ours, scipy_name, kw):
    positive = scipy_name in ("jensenshannon",)
    x, y = _data(rng, positive=positive)
    arg = kw.get("p", 2.0)
    got = np.asarray(pairwise_distance(x, y, ours, metric_arg=arg))
    want = sp.cdist(x, y, scipy_name, **kw)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_inner_product(rng):
    x, y = _data(rng)
    got = np.asarray(pairwise_distance(x, y, "inner_product"))
    np.testing.assert_allclose(got, x @ y.T, rtol=1e-4, atol=1e-4)
    assert not is_min_close("inner_product")
    assert is_min_close("euclidean")


def test_hamming(rng):
    x = (rng.random((20, 32)) < 0.5).astype(np.float32)
    y = (rng.random((15, 32)) < 0.5).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, "hamming"))
    np.testing.assert_allclose(got, sp.cdist(x, y, "hamming"), rtol=1e-5, atol=1e-5)


def test_russelrao(rng):
    x = (rng.random((20, 32)) < 0.5).astype(np.float32)
    y = (rng.random((15, 32)) < 0.5).astype(np.float32)
    got = np.asarray(pairwise_distance(x, y, "russelrao"))
    # (d - <x,y>) / d — computed directly (scipy dropped boolean metrics)
    want = (x.shape[1] - x @ y.T) / x.shape[1]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_kl_divergence(rng):
    x, y = _data(rng, positive=True)
    got = np.asarray(pairwise_distance(x, y, "kl_divergence"))
    want = np.array([[np.sum(xi * np.log(xi / yj)) for yj in y] for xi in x])
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_hellinger(rng):
    x, y = _data(rng, positive=True)
    got = np.asarray(pairwise_distance(x, y, "hellinger"))
    want = np.sqrt(np.maximum(0, 1 - np.sqrt(x) @ np.sqrt(y).T))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_haversine(rng):
    x = (rng.random((10, 2)) - 0.5) * np.array([np.pi, 2 * np.pi])
    y = (rng.random((8, 2)) - 0.5) * np.array([np.pi, 2 * np.pi])
    got = np.asarray(pairwise_distance(x.astype(np.float32), y.astype(np.float32), "haversine"))
    lat1, lon1 = x[:, None, 0], x[:, None, 1]
    lat2, lon2 = y[None, :, 0], y[None, :, 1]
    a = np.sin((lat2 - lat1) / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin((lon2 - lon1) / 2) ** 2
    want = 2 * np.arcsin(np.sqrt(a))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_tiled_path_matches_single_tile(rng):
    # Force the elementwise engine to tile by using a big-ish input
    from raft_tpu.distance import pairwise as pw
    x, y = _data(rng, m=257, n=129, d=8)
    old = pw._TILE_BUDGET_BYTES
    pw._TILE_BUDGET_BYTES = 64 * 1024  # force multi-tile
    try:
        got = np.asarray(pairwise_distance(x, y, "l1"))
    finally:
        pw._TILE_BUDGET_BYTES = old
    np.testing.assert_allclose(got, sp.cdist(x, y, "cityblock"), rtol=1e-3, atol=1e-3)


def test_unknown_metric():
    with pytest.raises(ValueError, match="unknown distance metric"):
        pairwise_distance(np.ones((2, 2)), np.ones((2, 2)), "nope")


def test_canonical_enum_passthrough():
    assert canonical_metric(DistanceType.L1) is DistanceType.L1


class TestFusedL2NN:
    def test_matches_bruteforce(self, rng):
        x, y = _data(rng, m=100, n=3000, d=16)
        idx, val = fused_l2_nn_argmin(x, y, tile_n=256)
        d = sp.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(idx), d.argmin(1))
        np.testing.assert_allclose(np.asarray(val), d.min(1), rtol=1e-3, atol=1e-3)

    def test_sqrt(self, rng):
        x, y = _data(rng, m=10, n=50, d=4)
        _, val = fused_l2_nn_argmin(x, y, sqrt=True)
        d = sp.cdist(x, y, "euclidean")
        np.testing.assert_allclose(np.asarray(val), d.min(1), rtol=1e-3, atol=1e-3)

    def test_n_not_multiple_of_tile(self, rng):
        x, y = _data(rng, m=7, n=1001, d=8)
        idx, _ = fused_l2_nn_argmin(x, y, tile_n=128)
        d = sp.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(idx), d.argmin(1))

    def test_duplicate_points_tie_break(self):
        x = np.zeros((3, 4), np.float32)
        y = np.zeros((10, 4), np.float32)  # all equidistant
        idx, val = fused_l2_nn_argmin(x, y)
        np.testing.assert_array_equal(np.asarray(idx), 0)


class TestMaskedNN:
    def test_pair_mask(self, rng):
        x, y = _data(rng, m=20, n=30, d=8)
        adj = rng.random((20, 30)) < 0.3
        adj[5] = False  # row with no neighbors
        idx, val = masked_l2_nn_argmin(x, y, jnp.asarray(adj))
        d = sp.cdist(x, y, "sqeuclidean")
        d_masked = np.where(adj, d, np.inf)
        want_idx = np.where(np.isfinite(d_masked.min(1)), d_masked.argmin(1), -1)
        np.testing.assert_array_equal(np.asarray(idx), want_idx)
        assert np.asarray(idx)[5] == -1 and np.isinf(np.asarray(val)[5])

    def test_group_mask(self, rng):
        x, y = _data(rng, m=10, n=30, d=8)
        group_idxs = np.array([10, 20, 30])  # 3 groups of 10 columns
        adj = rng.random((10, 3)) < 0.5
        idx, val = masked_l2_nn_argmin(x, y, jnp.asarray(adj), jnp.asarray(group_idxs))
        full = np.zeros((10, 30), bool)
        starts = [0, 10, 20]
        for g in range(3):
            full[:, starts[g]:group_idxs[g]] = adj[:, g][:, None]
        d = np.where(full, sp.cdist(x, y, "sqeuclidean"), np.inf)
        want_idx = np.where(np.isfinite(d.min(1)), d.argmin(1), -1)
        np.testing.assert_array_equal(np.asarray(idx), want_idx)


class TestGram:
    def test_linear(self, rng):
        x, y = _data(rng)
        got = np.asarray(gram_matrix(x, y, KernelParams(KernelType.LINEAR)))
        np.testing.assert_allclose(got, x @ y.T, rtol=1e-4, atol=1e-4)

    def test_poly(self, rng):
        x, y = _data(rng, d=8)
        p = KernelParams(KernelType.POLYNOMIAL, degree=2, gamma=0.5, coef0=1.0)
        got = np.asarray(gram_matrix(x, y, p))
        np.testing.assert_allclose(got, (0.5 * x @ y.T + 1.0) ** 2, rtol=1e-3, atol=1e-3)

    def test_rbf(self, rng):
        x, y = _data(rng, d=8)
        p = KernelParams(KernelType.RBF, gamma=0.1)
        got = np.asarray(gram_matrix(x, y, p))
        want = np.exp(-0.1 * sp.cdist(x, y, "sqeuclidean"))
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_tanh(self, rng):
        x, y = _data(rng, d=8)
        p = KernelParams(KernelType.TANH, gamma=0.01, coef0=0.5)
        got = np.asarray(gram_matrix(x, y, p))
        np.testing.assert_allclose(got, np.tanh(0.01 * x @ y.T + 0.5), rtol=1e-3, atol=1e-3)
