"""Core layer tests (analog of the reference's CORE_TEST suite)."""
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import (
    Bitset,
    KeyValuePair,
    RaftError,
    Resources,
    device_resources_manager,
    expects,
    fail,
    operators,
    serialize,
)
from raft_tpu.core import interruptible
from raft_tpu.utils import cdiv, next_pow2, round_up_to


class TestUtils:
    def test_cdiv(self):
        assert cdiv(10, 3) == 4
        assert cdiv(9, 3) == 3
        assert cdiv(1, 128) == 1

    def test_round_up(self):
        assert round_up_to(100, 128) == 128
        assert round_up_to(128, 128) == 128

    def test_next_pow2(self):
        assert next_pow2(1) == 1
        assert next_pow2(3) == 4
        assert next_pow2(64) == 64
        assert next_pow2(65) == 128


class TestErrors:
    def test_expects_pass(self):
        expects(True, "fine")

    def test_expects_fail(self):
        with pytest.raises(RaftError, match="bad value 3"):
            expects(False, "bad value %d", 3)

    def test_fail(self):
        with pytest.raises(RaftError):
            fail("boom")


class TestResources:
    def test_lazy_registry(self):
        r = Resources()
        calls = []
        r.register("thing", lambda: calls.append(1) or "made")
        assert r.has("thing")
        assert not calls
        assert r.get("thing") == "made"
        assert r.get("thing") == "made"
        assert len(calls) == 1

    def test_unknown_resource(self):
        with pytest.raises(RaftError):
            Resources().get("nope")

    def test_keys_differ(self):
        r = Resources(seed=7)
        k1, k2 = r.next_key(), r.next_key()
        assert not np.array_equal(jax.random.key_data(k1), jax.random.key_data(k2))

    def test_manager_pools(self):
        a = device_resources_manager.get_device_resources(0)
        b = device_resources_manager.get_device_resources(0)
        assert a is b

    def test_comms_injection(self):
        r = Resources()
        assert not r.has_comms()
        r.set_comms("fake")
        assert r.comms == "fake"


class TestBitset:
    def test_create_default_all_set(self):
        bs = Bitset.create(70, default=True)
        assert int(bs.count()) == 70
        assert bool(bs.all())

    def test_from_mask_roundtrip(self, rng):
        mask = rng.random(1000) < 0.3
        bs = Bitset.from_mask(jnp.asarray(mask))
        np.testing.assert_array_equal(np.asarray(bs.to_mask()), mask)
        assert int(bs.count()) == int(mask.sum())

    def test_test_and_set(self):
        bs = Bitset.create(100, default=False)
        bs = bs.set(jnp.array([3, 64, 99]))
        assert bool(bs.test(3)) and bool(bs.test(64)) and bool(bs.test(99))
        assert not bool(bs.test(4))
        bs = bs.set(jnp.array([64]), False)
        assert not bool(bs.test(64))
        assert int(bs.count()) == 2

    def test_flip(self):
        bs = Bitset.create(33, default=False).flip()
        assert int(bs.count()) == 33

    def test_jit_through(self):
        bs = Bitset.from_mask(jnp.arange(64) % 2 == 0)

        @jax.jit
        def f(b: Bitset):
            return b.count()

        assert int(f(bs)) == 32


class TestOperators:
    def test_argmin_op_tie_break(self):
        k, v = operators.argmin_op(
            (jnp.array(5), jnp.array(1.0)), (jnp.array(2), jnp.array(1.0))
        )
        assert int(k) == 2

    def test_compose(self):
        f = operators.compose_op(operators.sqrt_op, operators.sq_op)
        assert float(f(jnp.float32(3.0))) == pytest.approx(3.0)


class TestSerialize:
    def test_scalar_roundtrip(self):
        buf = io.BytesIO()
        serialize.serialize_scalar(buf, 42, "<q")
        serialize.serialize_scalar(buf, 2.5, "<d")
        buf.seek(0)
        assert serialize.deserialize_scalar(buf, "<q") == 42
        assert serialize.deserialize_scalar(buf, "<d") == 2.5

    def test_array_roundtrip(self, rng):
        x = rng.standard_normal((17, 5)).astype(np.float32)
        buf = io.BytesIO()
        serialize.serialize_array(buf, jnp.asarray(x))
        buf.seek(0)
        np.testing.assert_array_equal(serialize.deserialize_array(buf), x)

    def test_save_load_arrays(self, tmp_path, rng):
        path = str(tmp_path / "index.raft")
        arrays = {
            "data": rng.standard_normal((8, 4)).astype(np.float32),
            "ids": np.arange(8, dtype=np.int64),
        }
        meta = {"metric": "l2", "n": 8, "frac": 0.5, "trained": True}
        serialize.save_arrays(path, "test_index", 3, meta, arrays)
        kind, version, meta2, arrays2 = serialize.load_arrays(path, "test_index")
        assert kind == "test_index" and version == 3
        assert meta2 == meta
        np.testing.assert_array_equal(arrays2["data"], arrays["data"])
        np.testing.assert_array_equal(arrays2["ids"], arrays["ids"])

    def test_kind_mismatch(self, tmp_path):
        path = str(tmp_path / "x.raft")
        serialize.save_arrays(path, "a", 1, {}, {})
        with pytest.raises(ValueError):
            serialize.load_arrays(path, "b")


class TestInterruptible:
    def test_cancel_then_check(self):
        interruptible.cancel()
        with pytest.raises(interruptible.InterruptedException):
            interruptible.check()
        interruptible.check()  # token reset after raise

    def test_synchronize_value(self):
        x = jnp.ones((4,))
        out = interruptible.synchronize(x * 2)
        np.testing.assert_array_equal(np.asarray(out), 2.0)


class TestKvp:
    def test_named_tuple(self):
        p = KeyValuePair(jnp.array(1), jnp.array(0.5))
        assert int(p.key) == 1


class TestResourcesWiring:
    """VERDICT item: workspace budgets must actually drive the tiled
    algorithms rather than being decoration."""

    def test_pairwise_respects_workspace(self):
        from raft_tpu.core.resources import Resources
        from raft_tpu.distance import pairwise

        rng = np.random.default_rng(0)
        x = rng.standard_normal((600, 16)).astype(np.float32)
        res = Resources(workspace_bytes=1 << 20)   # 1 MB: forces tiling
        d_small = pairwise.pairwise_distance(x, x, "l1", res=res)
        d_default = pairwise.pairwise_distance(x, x, "l1")
        np.testing.assert_allclose(np.asarray(d_small),
                                   np.asarray(d_default), rtol=1e-5)
        # the budget really changes the tiling decision
        tm_small, _ = pairwise._tile_sizes(600, 600, 16, 4, 1 << 20)
        tm_big, _ = pairwise._tile_sizes(600, 600, 16, 4, None)
        assert tm_small < tm_big

    def test_ivf_search_accepts_res(self):
        from raft_tpu.core.resources import Resources
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(1)
        data = rng.standard_normal((500, 16)).astype(np.float32)
        q = rng.standard_normal((10, 16)).astype(np.float32)
        index = ivf_flat.build(data, ivf_flat.IndexParams(n_lists=8, seed=0))
        res = Resources(workspace_bytes=32 << 20)
        d1, i1 = ivf_flat.search(index, q, 5,
                                 ivf_flat.SearchParams(n_probes=8), res=res)
        d2, i2 = ivf_flat.search(index, q, 5,
                                 ivf_flat.SearchParams(n_probes=8))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestInterop:
    """pylibraft common/ analog: cai_wrapper-style input adoption +
    config.set_output_as / auto_convert_output output hooks."""

    def test_as_device_array_sources(self):
        from raft_tpu.core import as_device_array
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        for src in (a, a.tolist(), jnp.asarray(a)):
            out = as_device_array(src)
            assert isinstance(out, jax.Array)
            np.testing.assert_array_equal(np.asarray(out), a)
        torch = pytest.importorskip("torch")
        t = as_device_array(torch.from_numpy(a.copy()))
        assert isinstance(t, jax.Array)
        np.testing.assert_array_equal(np.asarray(t), a)
        assert as_device_array(a, jnp.bfloat16).dtype == jnp.bfloat16

    def test_output_as_numpy_and_torch(self):
        from raft_tpu.core import output_as
        from raft_tpu.matrix import select_k
        x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 32)),
                        jnp.float32)
        with output_as("numpy"):
            d, i = select_k(x, k=3)
            assert isinstance(d, np.ndarray) and isinstance(i, np.ndarray)
        torch = pytest.importorskip("torch")
        with output_as("torch"):
            d, i = select_k(x, k=3)
            assert isinstance(d, torch.Tensor) and isinstance(i, torch.Tensor)
        d, i = select_k(x, k=3)  # default restored
        assert isinstance(d, jax.Array) and isinstance(i, jax.Array)

    def test_output_as_callable_and_nesting(self):
        from raft_tpu.core import output_as
        from raft_tpu.neighbors import brute_force
        rng = np.random.default_rng(1)
        ds = rng.standard_normal((500, 16)).astype(np.float32)
        q = rng.standard_normal((8, 16)).astype(np.float32)
        index = brute_force.build(ds)
        seen = []
        with output_as(lambda a: (seen.append(type(a)), np.asarray(a))[1]):
            d, i = brute_force.search(index, q, k=5)
        # outermost entry converted; internal select_k calls stayed jax
        assert isinstance(d, np.ndarray) and isinstance(i, np.ndarray)
        assert len(seen) == 2
        _, want = brute_force.search(index, q, k=5)
        np.testing.assert_array_equal(i, np.asarray(want))

    def test_output_as_skipped_under_jit(self):
        from raft_tpu.core import output_as
        from raft_tpu.matrix import select_k
        x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 32)),
                        jnp.float32)
        with output_as("numpy"):
            d, i = jax.jit(lambda v: select_k(v, k=3))(x)
        assert isinstance(d, jax.Array) and isinstance(i, jax.Array)

    def test_output_as_bf16_to_torch(self):
        from raft_tpu.core import output_as
        from raft_tpu.matrix import select_k
        torch = pytest.importorskip("torch")
        x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 16)),
                        jnp.bfloat16)
        with output_as("torch"):
            d, i = select_k(x, k=2)
        assert d.dtype == torch.bfloat16
        assert i.dtype == torch.int32

    def test_convert_output_namedtuple(self):
        from raft_tpu.core import convert_output, output_as
        from raft_tpu.core.kvp import KeyValuePair
        kv = KeyValuePair(jnp.zeros((3,)), jnp.ones((3,)))
        with output_as("numpy"):
            out = convert_output(kv)
        assert isinstance(out, KeyValuePair)
        assert isinstance(out.key, np.ndarray) and isinstance(out.value, np.ndarray)

    def test_internal_callers_keep_device_arrays(self):
        # an undecorated library path (ball_cover) routes through decorated
        # entries internally; a user-set output type must not leak inside
        from raft_tpu.core import output_as
        from raft_tpu.neighbors import ball_cover
        rng = np.random.default_rng(4)
        ds = rng.standard_normal((300, 8)).astype(np.float32)
        q = rng.standard_normal((4, 8)).astype(np.float32)
        index = ball_cover.build(ds)
        # knn internally routes through decorated brute_force.search and
        # jnp-post-processes its result: if the user's converter leaked
        # into that internal call, the jnp ops would crash on "poison".
        # The outer knn entry is itself decorated, so the final result IS
        # converted — exactly once, at the library boundary.
        with output_as(lambda a: "poison"):
            d, i = ball_cover.knn(index, q, k=3, n_probes=0)
        assert d == "poison" and i == "poison"
        d, i = ball_cover.knn(index, q, k=3, n_probes=0)
        assert isinstance(d, jax.Array) and isinstance(i, jax.Array)
