"""Multi-process (DCN-bootstrap) smoke test: the raft-dask
test_comms.py:69-338 analog without a real cluster.

Two localhost CPU processes join through ``bootstrap.init_comms``
(jax.distributed.initialize under the hood — the ncclUniqueId-broadcast
role), run the collective self-tests over the *global* 4-device mesh, and
execute a sharded brute-force search. Skips cleanly where the gloo CPU
collectives backend can't form a clique (sandboxed CI without
localhost sockets).
"""
import os
import socket
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.distributed
def test_two_process_bootstrap_collectives_and_search():
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)   # workers set their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "_dist_worker.py"),
             coordinator, "2", str(rank)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers timed out:\n"
                    + "\n".join(o or "" for o in outs))
    rcs = [p.returncode for p in procs]
    joined = "\n---\n".join(outs)
    if any(rc != 0 for rc in rcs) and (
            "UNAVAILABLE" in joined or "gloo" in joined.lower()
            and "unimplemented" in joined.lower()):
        pytest.skip(f"CPU collectives backend unavailable:\n{joined[-1500:]}")
    assert all(rc == 0 for rc in rcs), joined[-3000:]
    for rank in range(2):
        assert f"DIST_WORKER_OK rank={rank}" in joined
