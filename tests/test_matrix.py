"""Matrix + select_k tests (analog of MATRIX_TEST / MATRIX_SELECT_TEST)."""
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import matrix
from raft_tpu.matrix import SelectAlgo, select_k


class TestSelectK:
    @pytest.mark.parametrize("algo", ["topk", "radix"])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_vs_numpy(self, rng, algo, select_min):
        v = rng.standard_normal((13, 300)).astype(np.float32)
        k = 7
        vals, idxs = select_k(jnp.asarray(v), k, select_min=select_min, algo=algo)
        vals, idxs = np.asarray(vals), np.asarray(idxs)
        order = np.sort(v, axis=1)
        want = order[:, :k] if select_min else order[:, ::-1][:, :k]
        np.testing.assert_allclose(vals, want, rtol=1e-5, atol=1e-6)
        # indices recover the values
        np.testing.assert_allclose(np.take_along_axis(v, idxs, axis=1), vals,
                                   rtol=1e-5, atol=1e-6)

    def test_index_passthrough(self, rng):
        v = rng.standard_normal((4, 50)).astype(np.float32)
        base = jnp.arange(1000, 1050, dtype=jnp.int32)
        ids = jnp.broadcast_to(base, (4, 50))
        _, idxs = select_k(jnp.asarray(v), 3, indices=ids)
        want = np.argsort(v, axis=1)[:, :3] + 1000
        np.testing.assert_array_equal(np.asarray(idxs), want)

    def test_k_equals_n(self, rng):
        v = rng.standard_normal((2, 16)).astype(np.float32)
        vals, _ = select_k(jnp.asarray(v), 16)
        np.testing.assert_allclose(np.asarray(vals), np.sort(v, 1), rtol=1e-6)

    def test_k_out_of_range(self):
        from raft_tpu.core import RaftError
        with pytest.raises(RaftError):
            select_k(jnp.ones((2, 4)), 5)

    def test_radix_all_equal_rows(self):
        v = jnp.ones((3, 100))
        vals, idxs = select_k(v, 5, algo=SelectAlgo.RADIX)
        np.testing.assert_allclose(np.asarray(vals), 1.0)

    def test_radix_large_row(self, rng):
        v = rng.standard_normal((2, 50_000)).astype(np.float32)
        vals, _ = select_k(jnp.asarray(v), 10, algo="radix")
        np.testing.assert_allclose(np.asarray(vals), np.sort(v, 1)[:, :10],
                                   rtol=1e-5, atol=1e-6)


class TestOps:
    def test_argmax_argmin(self, rng):
        m = rng.standard_normal((5, 9)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.argmax(jnp.asarray(m))), m.argmax(1))
        np.testing.assert_array_equal(np.asarray(matrix.argmin(jnp.asarray(m))), m.argmin(1))

    def test_sort_cols(self, rng):
        m = rng.standard_normal((4, 6)).astype(np.float32)
        s, idx = matrix.sort_cols_per_row(jnp.asarray(m))
        np.testing.assert_allclose(np.asarray(s), np.sort(m, 1), rtol=1e-6)
        np.testing.assert_allclose(np.take_along_axis(m, np.asarray(idx), 1), np.sort(m, 1), rtol=1e-6)

    def test_gather_scatter(self, rng):
        m = rng.standard_normal((6, 3)).astype(np.float32)
        ids = np.array([4, 0, 2])
        g = matrix.gather(jnp.asarray(m), jnp.asarray(ids))
        np.testing.assert_array_equal(np.asarray(g), m[ids])
        s = matrix.scatter(jnp.asarray(m), jnp.asarray(ids), jnp.zeros((3, 3)))
        assert np.asarray(s)[ids].sum() == 0

    def test_gather_if(self, rng):
        m = rng.standard_normal((6, 3)).astype(np.float32)
        ids = jnp.array([0, 1, 2])
        mask = jnp.array([True, False, True])
        g = np.asarray(matrix.gather_if(jnp.asarray(m), ids, mask, fill_value=-1.0))
        np.testing.assert_array_equal(g[1], -1.0)
        np.testing.assert_array_equal(g[0], m[0])

    def test_linewise(self, rng):
        m = jnp.asarray(rng.standard_normal((4, 6)).astype(np.float32))
        v = jnp.arange(6, dtype=jnp.float32)
        out = matrix.linewise_op(m, v, along_rows=True, op=lambda a, b: a + b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(m) + np.arange(6), rtol=1e-6)

    def test_diag_and_triangles(self):
        m = jnp.asarray(np.arange(16, dtype=np.float32).reshape(4, 4))
        d = matrix.get_diagonal(m)
        np.testing.assert_array_equal(np.asarray(d), [0, 5, 10, 15])
        m2 = matrix.set_diagonal(m, jnp.zeros(4))
        assert np.trace(np.asarray(m2)) == 0
        assert np.allclose(np.asarray(matrix.upper_triangular(m)), np.triu(np.asarray(m)))

    def test_reverse_slice_norm(self, rng):
        m = rng.standard_normal((5, 7)).astype(np.float32)
        jm = jnp.asarray(m)
        np.testing.assert_array_equal(np.asarray(matrix.col_reverse(jm)), m[:, ::-1])
        np.testing.assert_array_equal(np.asarray(matrix.row_reverse(jm)), m[::-1])
        np.testing.assert_array_equal(np.asarray(matrix.slice_matrix(jm, 1, 2, 4, 6)), m[1:4, 2:6])
        assert float(matrix.l2_norm(jm)) == pytest.approx(np.linalg.norm(m), rel=1e-5)

    def test_weighted_means(self, rng):
        m = rng.standard_normal((4, 6)).astype(np.float32)
        w = rng.random(6).astype(np.float32)
        got = np.asarray(matrix.row_weighted_mean(jnp.asarray(m), jnp.asarray(w)))
        np.testing.assert_allclose(got, (m * w).sum(1) / w.sum(), rtol=1e-5)


class TestSelectKAutoDispatch:
    def test_tune_select_k_records_winner(self, tmp_path, monkeypatch):
        from raft_tpu.matrix.select_k import tune_select_k
        from raft_tpu.ops import autotune

        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "t.json"))
        monkeypatch.setattr(autotune, "_MEM_CACHE", {})
        monkeypatch.setattr(autotune, "_DISK_LOADED", False)
        winner, timings = tune_select_k(rows=32, n=4096, k=8, reps=2)
        # on TPU both engines race; off-TPU the Pallas k-pass extractor
        # only exists in interpret mode, so the tuner must not measure
        # (and could then mis-cache) it
        import jax as _jax
        want = ({"topk", "kpass"} if _jax.default_backend() == "tpu"
                else {"topk"})
        assert set(timings) == want
        assert winner in timings
        key = autotune.shape_bucket("select_k", n=4096, k=8)
        assert autotune.lookup(key) == winner

    def test_auto_matches_topk_results(self, rng):
        # auto (whatever it dispatches) must agree with explicit topk
        from raft_tpu.matrix.select_k import select_k

        x = jnp.asarray(rng.standard_normal((16, 1 << 16)).astype(np.float32))
        v1, i1 = select_k(x, 10, algo="auto")
        v2, i2 = select_k(x, 10, algo="topk")
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    @pytest.mark.parametrize("m,n,k", [(130, 1024, 20), (64, 515, 10),
                                       (700, 2048, 33)])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_kpass_matches_topk(self, rng, m, n, k, select_min):
        """The Pallas k-pass engine is exact and breaks ties like top_k
        (lowest index first), including on ragged (padded) shapes."""
        from raft_tpu.matrix.select_k import select_k

        x = rng.standard_normal((m, n)).astype(np.float32)
        # force value ties so tie-breaking is actually exercised
        x = np.round(x * 8) / 8
        xj = jnp.asarray(x)
        v1, i1 = select_k(xj, k, select_min=select_min, algo="kpass")
        v2, i2 = select_k(xj, k, select_min=select_min, algo="topk")
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_kpass_inf_rows_distinct_indices(self):
        """+inf is a legal value (filter penalties, padding): when infs
        enter the top-k the engine must still return DISTINCT ascending
        indices, exactly like top_k — not repeat column 0."""
        from raft_tpu.matrix.select_k import select_k

        x = np.full((520, 1024), np.inf, np.float32)
        x[:, 0], x[:, 1], x[:, 2] = 1.0, 2.0, 3.0
        v1, i1 = select_k(jnp.asarray(x), 6, algo="kpass")
        v2, i2 = select_k(jnp.asarray(x), 6, algo="topk")
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_kpass_preserves_dtype(self, rng):
        from raft_tpu.matrix.select_k import select_k

        x = jnp.asarray(rng.standard_normal((520, 1024)),
                        jnp.bfloat16)
        v, _ = select_k(x, 4, algo="kpass")
        assert v.dtype == jnp.bfloat16

    def test_kpass_vmem_column_cap(self, rng):
        """Rows wider than 4096 must never dispatch to KPASS: the kernel
        keeps ~5 live (128, n) f32/i32 planes on the scoped-VMEM stack,
        and measured compile-OOMs on v5e put (128, 15744) at 24.8 MB and
        even (128, 8192) at 21.3 MB against the 16 MB scoped limit —
        4096 (~10.5 MB) is the rehearsed-safe width. AUTO falls back to
        TOPK; the chunked wide path stays exact. 4224 sits just past the
        cap, exercising the excluded-range boundary."""
        from raft_tpu.matrix.select_k import _kpass_eligible, _kpass_safe
        from raft_tpu.neighbors.brute_force import _wide_select_k

        for n in (4224, 8192, 15744):
            x = jnp.zeros((520, n), jnp.float32)
            assert not _kpass_safe(x, 10) and not _kpass_eligible(x, 10)

        x = rng.standard_normal((64, 15744)).astype(np.float32)
        v1, i1 = _wide_select_k(jnp.asarray(x), 10)
        v2, i2 = select_k(jnp.asarray(x), 10, algo="topk")
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_kpass_indices_passthrough(self, rng):
        from raft_tpu.matrix.select_k import select_k

        x = jnp.asarray(rng.standard_normal((130, 640)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, 1 << 20, (130, 640)), jnp.int32)
        v, i = select_k(x, 5, indices=ids, algo="kpass")
        v2, i2 = select_k(x, 5, indices=ids, algo="topk")
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i2))
