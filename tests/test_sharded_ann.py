"""Sharded ANN tests on the 8-device virtual CPU mesh (the raft-dask
LocalCUDACluster analog, SURVEY.md §4: distributed tests without a real
cluster exercise the real collective code paths)."""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from ann_utils import calc_recall, naive_knn
from raft_tpu.neighbors import cagra, ivf_flat
from raft_tpu.parallel import sharded_ann


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:4]), ("shard",))


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    return rng.standard_normal((8_000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(8)
    return rng.standard_normal((50, 32)).astype(np.float32)


# builds dominate this module's wall on the 1-core CI box (the 870s
# tier-1 timeout is tight): tests that search the same configuration
# share one module-scoped build — searches never mutate the index
@pytest.fixture(scope="module")
def flat_index16(mesh, dataset):
    return sharded_ann.build_ivf_flat(
        dataset, mesh, ivf_flat.IndexParams(n_lists=16, seed=0))


@pytest.fixture(scope="module")
def pq_index16(mesh, dataset):
    from raft_tpu.neighbors import ivf_pq

    return sharded_ann.build_ivf_pq(
        dataset, mesh, ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0))


class TestShardedIvfFlat:
    def test_recall_and_merge(self, mesh, dataset, queries, flat_index16):
        index = flat_index16
        assert index.n_shards == 4
        # full probes per shard → exact: merged result must match global knn
        d, i = sharded_ann.search_ivf_flat(
            index, queries, k=10, params=ivf_flat.SearchParams(n_probes=16))
        want_d, want_i = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(i), want_i) == 1.0
        np.testing.assert_allclose(np.asarray(d), want_d, rtol=1e-2, atol=1e-2)

    # tier-1 wall: one low-precision param suffices for the sharded path
    # (storage dtype never reaches the cross-shard merge); the full dtype
    # matrix is single-chip coverage (test_ivf_flat) + the slow lane
    @pytest.mark.parametrize("dtype", [
        "bfloat16",
        pytest.param("int8", marks=pytest.mark.slow),
        pytest.param("uint8", marks=pytest.mark.slow)])
    def test_low_precision_storage(self, mesh, dataset, queries, dtype):
        data, q = dataset, queries
        if dtype == "uint8":  # byte-valued corpus for exact uint8 storage
            data = np.round(np.clip(data * 40 + 128, 0, 255)
                            ).astype(np.float32)
            q = np.round(np.clip(q * 40 + 128, 0, 255)).astype(np.float32)
        index = sharded_ann.build_ivf_flat(
            data, mesh, ivf_flat.IndexParams(n_lists=16, seed=0,
                                             dtype=dtype))
        d, i = sharded_ann.search_ivf_flat(
            index, q, k=10, params=ivf_flat.SearchParams(n_probes=16))
        _, want_i = naive_knn(data, q, 10)
        r = calc_recall(np.asarray(i), want_i)
        floor = {"bfloat16": 0.95, "int8": 0.9, "uint8": 0.9999}[dtype]
        assert r > floor, r

    # tier-1 wall: a recall-only variant of test_recall_and_merge (the
    # partial-probe mechanics are single-chip coverage in test_ivf_flat)
    @pytest.mark.slow
    def test_partial_probes(self, mesh, dataset, queries, flat_index16):
        index = flat_index16
        _, i = sharded_ann.search_ivf_flat(
            index, queries, k=10, params=ivf_flat.SearchParams(n_probes=8))
        _, want_i = naive_knn(dataset, queries, 10)
        assert calc_recall(np.asarray(i), want_i) >= 0.7

    def test_uneven_rows(self, mesh, queries):
        rng = np.random.default_rng(3)
        data = rng.standard_normal((8_000 - 37, 32)).astype(np.float32)
        index = sharded_ann.build_ivf_flat(
            data, mesh, ivf_flat.IndexParams(n_lists=8, seed=0))
        d, i = sharded_ann.search_ivf_flat(
            index, queries, k=5, params=ivf_flat.SearchParams(n_probes=8))
        got = np.asarray(i)
        assert got.max() < len(data)
        _, want_i = naive_knn(data, queries, 5)
        assert calc_recall(got, want_i) == 1.0


class TestShardedCagra:
    def test_recall(self, mesh, dataset, queries):
        index = sharded_ann.build_cagra(
            dataset, mesh, cagra.IndexParams(
                intermediate_graph_degree=48, graph_degree=24, seed=0))
        d, i = sharded_ann.search_cagra(
            index, queries, k=10, params=cagra.SearchParams(itopk_size=64))
        _, want_i = naive_knn(dataset, queries, 10)
        got = np.asarray(i)
        assert got.max() < len(dataset)
        assert (got >= 0).all()
        r = calc_recall(got, want_i)
        assert r >= 0.9, f"sharded cagra recall {r}"

    def test_uneven_rows_no_padding_leak(self, mesh, queries):
        rng = np.random.default_rng(4)
        data = rng.standard_normal((4_000 - 13, 32)).astype(np.float32)
        index = sharded_ann.build_cagra(
            data, mesh, cagra.IndexParams(
                intermediate_graph_degree=32, graph_degree=16, seed=0))
        _, i = sharded_ann.search_cagra(
            index, queries, k=10, params=cagra.SearchParams(itopk_size=64))
        got = np.asarray(i)
        assert got.max() < len(data)  # no padded-row global ids


class TestShardedIvfPq:
    def test_recall_vs_single_shard(self, mesh, dataset, queries,
                                    pq_index16):
        from raft_tpu.neighbors import ivf_pq

        index = pq_index16
        assert index.n_shards == 4
        d, i = sharded_ann.search_ivf_pq(
            index, queries, k=10, params=ivf_pq.SearchParams(n_probes=16))
        got = np.asarray(i)
        assert got.max() < len(dataset) and (got >= -1).all()
        _, want_i = naive_knn(dataset, queries, 10)
        r = calc_recall(got, want_i)
        # PQ is lossy and random gaussian data is its worst case: the
        # single-index build at these params measures 0.586 on this data —
        # the sharded merge must stay at that quality level
        assert r >= 0.5, f"sharded ivf_pq recall {r}"

    # tier-1 wall (PR 8 pays for the quality-observability suite):
    # uneven-row stacking/rebasing stays tier-1 via the ivf_flat and
    # cagra uneven tests through the same merge chokepoint, and the
    # MULTICHIP dryrun gates ivf_pq global-id ranges + recall at 10k
    # rows/device every PR; this fresh-shape ivf_pq build (~14s of
    # compiles) moves to the slow lane
    @pytest.mark.slow
    def test_uneven_rows_no_padding_leak(self, mesh, queries):
        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(5)
        data = rng.standard_normal((4_000 - 21, 32)).astype(np.float32)
        index = sharded_ann.build_ivf_pq(
            data, mesh, ivf_pq.IndexParams(n_lists=8, pq_dim=8, seed=0))
        d, i = sharded_ann.search_ivf_pq(
            index, queries, k=10, params=ivf_pq.SearchParams(n_probes=8))
        got = np.asarray(i)
        assert got.max() < len(data)
        assert (got >= 0).all()

    # tier-1 wall: the fast comms-injection equivalent lives in
    # test_core.py; this full sharded-search form moves to the slow lane
    @pytest.mark.slow
    def test_comms_injection(self, mesh, dataset, queries, pq_index16):
        """search via a Resources-injected communicator (comms_t pattern)."""
        from raft_tpu.comms import AxisComms
        from raft_tpu.core.resources import Resources
        from raft_tpu.neighbors import ivf_pq

        res = Resources(mesh=mesh)
        res.set_comms(AxisComms("shard", size=4))
        index = pq_index16
        d1, i1 = sharded_ann.search_ivf_pq(
            index, queries, k=5, params=ivf_pq.SearchParams(n_probes=16),
            res=res)
        d2, i2 = sharded_ann.search_ivf_pq(
            index, queries, k=5, params=ivf_pq.SearchParams(n_probes=16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
