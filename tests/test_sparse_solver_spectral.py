"""Sparse subsystem + solver (LAP, MST, Lanczos) + single_linkage + label
+ spectral tests. Oracles: scipy/sklearn."""
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from raft_tpu.sparse import (COO, CSR, degree, knn_graph, lanczos_smallest,
                             mst, row_norm, sddmm,
                             sparse_brute_force_knn,
                             sparse_pairwise_distance, spmm, symmetrize,
                             transpose)


@pytest.fixture(scope="module")
def rand_sparse():
    rng = np.random.default_rng(0)
    d = rng.random((60, 40)).astype(np.float32)
    d[d < 0.7] = 0
    return d


class TestContainers:
    def test_roundtrips(self, rand_sparse):
        c = COO.from_dense(rand_sparse)
        np.testing.assert_allclose(np.asarray(c.to_dense()), rand_sparse)
        csr = c.to_csr()
        np.testing.assert_allclose(np.asarray(csr.to_dense()), rand_sparse)
        back = csr.to_coo().to_dense()
        np.testing.assert_allclose(np.asarray(back), rand_sparse)

    def test_from_scipy(self, rand_sparse):
        m = sp.csr_matrix(rand_sparse)
        csr = CSR.from_scipy(m)
        np.testing.assert_allclose(np.asarray(csr.to_dense()), rand_sparse)

    def test_slice_rows(self, rand_sparse):
        csr = CSR.from_dense(rand_sparse)
        s = csr.slice_rows(10, 30)
        np.testing.assert_allclose(np.asarray(s.to_dense()),
                                   rand_sparse[10:30])


class TestLinalg:
    def test_degree_norm(self, rand_sparse):
        csr = CSR.from_dense(rand_sparse)
        np.testing.assert_array_equal(np.asarray(degree(csr)),
                                      (rand_sparse != 0).sum(1))
        np.testing.assert_allclose(np.asarray(row_norm(csr, "l2")),
                                   (rand_sparse ** 2).sum(1), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(row_norm(csr, "l1")),
                                   np.abs(rand_sparse).sum(1), rtol=1e-5)

    def test_spmm_transpose(self, rand_sparse):
        csr = CSR.from_dense(rand_sparse)
        b = np.random.default_rng(1).random((40, 8)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(spmm(csr, b)),
                                   rand_sparse @ b, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(transpose(csr).to_dense()),
                                   rand_sparse.T)

    def test_sddmm(self):
        rng = np.random.default_rng(2)
        a = rng.random((10, 6)).astype(np.float32)
        b = rng.random((6, 12)).astype(np.float32)
        mask = (rng.random((10, 12)) < 0.3).astype(np.float32)
        out = sddmm(a, b, COO.from_dense(mask))
        want = (a @ b) * (mask != 0)
        np.testing.assert_allclose(np.asarray(out.to_dense()), want,
                                   rtol=1e-4, atol=1e-5)

    def test_symmetrize(self):
        d = np.array([[0, 3, 0], [1, 0, 0], [0, 5, 0]], np.float32)
        s = symmetrize(COO.from_dense(d), op="max")
        want = np.maximum(d, d.T)
        np.testing.assert_allclose(np.asarray(s.to_dense()), want)


class TestSparseDistance:
    @pytest.mark.parametrize("metric", ["sqeuclidean", "cosine",
                                        "inner_product", "l1"])
    def test_matches_dense(self, rand_sparse, metric):
        from raft_tpu.distance.pairwise import pairwise_distance as dense_pd

        x = CSR.from_dense(rand_sparse[:20])
        y = CSR.from_dense(rand_sparse[20:])
        got = sparse_pairwise_distance(x, y, metric)
        want = dense_pd(rand_sparse[:20], rand_sparse[20:], metric)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_jaccard(self):
        x = np.array([[1, 1, 0, 0], [1, 0, 1, 0]], np.float32)
        got = np.asarray(sparse_pairwise_distance(
            CSR.from_dense(x), CSR.from_dense(x), "jaccard"))
        assert got[0, 0] == 0
        np.testing.assert_allclose(got[0, 1], 1 - 1 / 3, rtol=1e-6)

    def test_sparse_knn(self, rand_sparse):
        x = CSR.from_dense(rand_sparse)
        d, i = sparse_brute_force_knn(x, x, 5)
        # self is nearest with distance 0
        np.testing.assert_array_equal(np.asarray(i)[:, 0],
                                      np.arange(rand_sparse.shape[0]))

    def test_knn_graph_symmetric(self, rand_sparse):
        g = knn_graph(CSR.from_dense(rand_sparse), 4)
        dense = np.asarray(g.to_dense())
        np.testing.assert_allclose(dense, dense.T)


class TestMst:
    def test_matches_scipy(self):
        from scipy.sparse.csgraph import minimum_spanning_tree

        rng = np.random.default_rng(3)
        g = rng.random((50, 50))
        g = (g + g.T) / 2
        g[g > 0.4] = 0
        np.fill_diagonal(g, 0)
        s, d, w = mst(COO.from_dense(g))
        want = minimum_spanning_tree(sp.csr_matrix(g)).sum()
        np.testing.assert_allclose(w.sum(), want, rtol=1e-5)

    def test_forest_on_disconnected(self):
        g = np.zeros((6, 6), np.float32)
        g[0, 1] = g[1, 0] = 1.0
        g[2, 3] = g[3, 2] = 2.0
        g[4, 5] = g[5, 4] = 3.0
        s, d, w = mst(COO.from_dense(g))
        assert len(w) == 3


class TestLanczos:
    def test_smallest_eigs(self):
        rng = np.random.default_rng(4)
        a = rng.random((40, 40))
        a = (a + a.T) / 2
        a[np.abs(a) < 0.4] = 0
        np.fill_diagonal(a, np.abs(a).sum(1) + 1)   # make it PD-ish sparse
        vals, vecs = lanczos_smallest(COO.from_dense(a), 3)
        want = np.sort(np.linalg.eigvalsh(a))[:3]
        np.testing.assert_allclose(np.asarray(vals), want, rtol=1e-3,
                                   atol=1e-3)
        # residuals ||Av - λv|| small
        for j in range(3):
            v = np.asarray(vecs)[:, j]
            r = a @ v - float(vals[j]) * v
            assert np.linalg.norm(r) < 1e-2


class TestSingleLinkage:
    def test_matches_scipy_labels(self):
        from scipy.cluster.hierarchy import fcluster, linkage

        from raft_tpu.cluster import single_linkage

        rng = np.random.default_rng(5)
        x = np.concatenate([
            rng.standard_normal((30, 4)) + 8,
            rng.standard_normal((30, 4)) - 8,
            rng.standard_normal((30, 4)),
        ]).astype(np.float32)
        out = single_linkage(x, n_clusters=3, c=20)
        want = fcluster(linkage(x, method="single"), 3, criterion="maxclust")
        # same partition up to label permutation
        from raft_tpu import stats
        ari = float(stats.adjusted_rand_index(out.labels, want - 1, 90))
        assert ari == pytest.approx(1.0)

    def test_dendrogram_shape(self):
        from raft_tpu.cluster import single_linkage

        rng = np.random.default_rng(6)
        x = rng.standard_normal((25, 3)).astype(np.float32)
        out = single_linkage(x, n_clusters=4, c=24)
        assert out.children.shape == (24, 2)
        assert (np.diff(out.deltas) >= -1e-6).all()   # ascending merges
        assert len(np.unique(out.labels)) == 4


class TestLabel:
    def test_make_monotonic(self):
        from raft_tpu.label import get_unique_labels, make_monotonic

        l = np.array([10, 30, 10, 20, 30])
        out, n = make_monotonic(l)
        np.testing.assert_array_equal(np.asarray(out), [0, 2, 0, 1, 2])
        assert n == 3
        np.testing.assert_array_equal(np.asarray(get_unique_labels(l)),
                                      [10, 20, 30])

    def test_merge_labels(self):
        from raft_tpu.label import merge_labels

        a = np.array([0, 0, 1, 1, 2])
        b = np.array([5, 6, 6, 7, 8])
        mask = np.array([True, True, True, False, False])
        # b connects label 0 and 1 through shared b-label 6
        out = np.asarray(merge_labels(a, b, mask))
        assert out[0] == out[1] == out[2] == out[3]
        assert out[4] != out[0]


class TestSpectral:
    @pytest.mark.xfail(
        strict=False, run=False,
        reason="known pre-existing jax-0.4.37 failure: the Lanczos "
               "eigensolver behind spectral partition converges to a "
               "degenerate Fiedler vector on this jax/CPU stack and the "
               "two blobs land in one part (tracked alongside the "
               "interpret-mode int8-LUT quirks as the 4 expected tier-1 "
               "failures; run=False to spare the tight tier-1 budget)")
    def test_partition_two_blobs(self):
        from raft_tpu.spectral import analyze_partition, partition
        from raft_tpu.sparse import CSR, knn_graph

        rng = np.random.default_rng(7)
        x = np.concatenate([rng.standard_normal((40, 5)) + 10,
                            rng.standard_normal((40, 5)) - 10])
        g = knn_graph(CSR.from_dense(x.astype(np.float32)), 6)
        # similarity weights (spectral wants affinity, not distance)
        from raft_tpu.sparse import COO
        aff = COO(g.rows, g.cols,
                  jnp.exp(-jnp.asarray(g.vals) / 10.0), g.shape)
        labels, vals, emb = partition(aff, 2)
        want = np.array([0] * 40 + [1] * 40)
        from raft_tpu import stats
        ari = float(stats.adjusted_rand_index(labels, want, 2))
        assert ari == pytest.approx(1.0)
        cut, cost = analyze_partition(aff, labels)
        assert cut < 1.0  # blobs are far apart → near-zero cut
