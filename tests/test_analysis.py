"""Machine-checked TPU invariants (ISSUE 14): the static-analysis gate.

Runs the three passes of :mod:`raft_tpu.analysis` against the live tree
under a zero-NEW-findings policy (``analysis/baseline.json``), keeps the
kernel registry honest with a source-grep drift guard (the
``guarded_call``/``POLICIES`` sweep pattern from test_quality.py), and
proves each rule class actually fires by injecting a violation of it
into a fixture kernel/module.

Everything here is AST- and trace-level only — no device work, no XLA
compiles — so the whole file stays tier-1 cheap (<5s; the one traced
fixture kernel runs in interpret shape-tracing only).
"""
import pathlib
import re

import pytest

from raft_tpu import analysis
from raft_tpu.analysis import hotpath_audit, kernel_audit, lock_lint

pytestmark = pytest.mark.analysis

ROOT = analysis.repo_root()


@pytest.fixture(scope="module")
def tree_run():
    """ONE full-tree run shared by the gate tests (the expensive part —
    ~19 kernel variant traces — happens once per module)."""
    reports = []
    findings = analysis.run_all(ROOT, kernel_reports=reports)
    return findings, reports


class TestBaselineGate:
    def test_zero_new_findings(self, tree_run):
        """THE gate: a new kernel, serving path, or thread that violates
        an invariant cannot merge without either fixing it, waiving it
        inline with a reason, or consciously baselining it."""
        findings, _ = tree_run
        verdict = analysis.compare(findings)
        by_key = {f.key: f for f in findings}
        new = "\n".join(f"  {by_key[k].render()}" for k in verdict["new"])
        assert not verdict["new"], (
            f"{len(verdict['new'])} NEW analysis findings (fix, waive "
            f"with '# lint: waive(<rule>): <reason>', or rebaseline via "
            f"scratch/run_analysis.py --update-baseline):\n{new}")

    def test_no_stale_baseline_entries(self, tree_run):
        """A fixed finding must leave the baseline (run
        ``scratch/run_analysis.py --update-baseline``) — a stale entry
        would silently re-admit a regression of the same key."""
        findings, _ = tree_run
        verdict = analysis.compare(findings)
        assert not verdict["stale"], (
            f"baseline entries no longer firing: {verdict['stale']}")

    def test_waivers_name_known_rules(self):
        """A typo'd waiver never fires; reject waivers naming unknown
        rules anywhere in the package."""
        bad = []
        waive_re = re.compile(r"#\s*lint:\s*waive\(([\w.-]+)\)")
        for p in (pathlib.Path(ROOT) / "raft_tpu").rglob("*.py"):
            for i, line in enumerate(p.read_text().splitlines(), 1):
                for m in waive_re.finditer(line):
                    if m.group(1) not in analysis.KNOWN_RULES:
                        bad.append(f"{p}:{i}: waive({m.group(1)})")
        assert not bad, f"waivers naming unknown rules: {bad}"

    def test_partial_rebaseline_preserves_other_passes(self):
        """`--update-baseline --passes lock` must merge into, never
        wipe, the kernel audit's baseline slice."""
        lock_only = [analysis.Finding("unlocked-attr", "a.py", "X.m.a",
                                      "msg", 3)]
        merged = analysis.merged_baseline_keys(lock_only,
                                               passes=("lock",))
        kernel_entries = [k for k in analysis.load_baseline()
                          if k.split("::")[0] not in
                          analysis.PASS_RULES["lock"]]
        assert set(kernel_entries) <= set(merged)
        assert "unlocked-attr::a.py::X.m.a" in merged
        # a full-pass rebaseline is exactly this run's findings
        assert analysis.merged_baseline_keys(lock_only) == \
            ["unlocked-attr::a.py::X.m.a"]

    def test_waiver_applies_to_own_and_next_line(self):
        f1 = analysis.Finding("unlocked-attr", "x.py", "s", "m", line=3)
        f2 = analysis.Finding("unlocked-attr", "x.py", "s2", "m", line=9)
        src = "a\nb\n# lint: waive(unlocked-attr): reason\nc\n"
        w = analysis.waivers_in(src)
        assert w == {3: {"unlocked-attr"}}
        # covered: finding ON the waiver line or the line after
        assert "unlocked-attr" in w.get(f1.line, set()) | w.get(
            f1.line - 1, set())
        assert not (w.get(f2.line, set()) | w.get(f2.line - 1, set()))


class TestKernelRegistry:
    def test_pallas_call_drift_guard(self):
        """The test_quality.py POLICIES-sweep pattern for kernels: the
        source grep for literal ``pl.pallas_call(`` sites must equal the
        registry's per-file counts — an unregistered new kernel (or a
        registry entry for a removed one) fails the suite."""
        grepped = kernel_audit.pallas_call_sites(ROOT)
        registered = kernel_audit.registered_counts()
        assert grepped == registered, (
            f"pallas_call sites drifted from the analysis registry.\n"
            f"unregistered: "
            f"{ {k: v for k, v in grepped.items() if registered.get(k) != v} }\n"
            f"stale registry: "
            f"{ {k: v for k, v in registered.items() if grepped.get(k) != v} }\n"
            "— register the site (with at least one traced variant) in "
            "raft_tpu/analysis/kernel_audit.SITES")

    def test_every_site_traced_and_audited(self, tree_run):
        """Every registered site must produce at least one audited
        pallas_call report, and the audited variant surface must cover
        the ISSUE 14 floor (~14 registered+audited configurations)."""
        findings, reports = tree_run
        audited_sites = {r.site for r in reports}
        registered = {s.name for s in kernel_audit.SITES}
        assert audited_sites == registered, (
            f"sites without an audited trace: "
            f"{registered - audited_sites}")
        assert len(reports) >= 14, (
            f"only {len(reports)} audited kernel configurations — the "
            "registry lost variant coverage")
        # no variant silently failed to trace (a trace failure IS a
        # finding, so it is caught by the baseline gate too — this
        # asserts the stronger property that none is even baselined)
        assert not [f for f in findings if f.rule == "trace-failed"]

    def test_vmem_reports_are_sane(self, tree_run):
        """Footprints must be positive and inside the budget for every
        current variant (the budget rule fires above it)."""
        _, reports = tree_run
        budget = int(min(kernel_audit.VMEM_BUDGETS_BYTES.values())
                     * kernel_audit.VMEM_OCCUPANCY)
        for r in reports:
            assert r.vmem_total_bytes > 0, r.site
            assert r.vmem_total_bytes <= budget, (r.site, r.variant)
            assert r.dma_waits >= r.dma_starts, (r.site, r.variant)


def _toy_kernel_eqn(scratch_mib: int = 0, unwaited_dma: bool = False,
                    unpaired_sem: bool = False, misaligned: bool = False,
                    use_repeat: bool = False):
    """Trace a tiny fixture kernel with the requested violation injected
    and return its pallas_call equation (shape-trace only, never run)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(x_ref, o_ref, *scratch):
        refs = list(scratch)
        if unwaited_dma or unpaired_sem:
            sem = refs.pop()
        if scratch_mib or misaligned:
            scr = refs.pop(0)
            scr[:] = jnp.zeros_like(scr)
        if unwaited_dma:
            c = pltpu.make_async_copy(x_ref, o_ref, sem)
            c.start()            # deliberately never waited
        if unpaired_sem:
            pltpu.semaphore_signal(sem, 1)   # deliberately never waited
        x = x_ref[...]
        if use_repeat:
            r = pltpu.repeat(x.astype(jnp.int32), 2, axis=1)
            o_ref[...] = x + r[:, :x.shape[1]].astype(jnp.float32)
        else:
            o_ref[...] = x * 2.0

    scratch_shapes = []
    if scratch_mib:
        rows = (scratch_mib << 20) // (128 * 4)
        scratch_shapes.append(pltpu.VMEM((rows, 128), jnp.float32))
    if misaligned:
        scratch_shapes.append(pltpu.VMEM((3, 96), jnp.float32))
    if unwaited_dma:
        scratch_shapes.append(pltpu.SemaphoreType.DMA)
    elif unpaired_sem:
        scratch_shapes.append(pltpu.SemaphoreType.REGULAR)

    def f(x):
        return pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=scratch_shapes,
            interpret=True,
        )(x)

    closed = jax.make_jaxpr(f)(jnp.zeros((8, 128), jnp.float32))
    (eqn,) = kernel_audit.pallas_eqns(closed)
    return eqn


class TestInjectedKernelViolations:
    """Each kernel rule class must actually catch its bug when one is
    deliberately injected (the ISSUE 14 acceptance fixtures)."""

    def _rules(self, eqn):
        _rep, issues = kernel_audit.audit_eqn("toy", "v", eqn)
        return {r for r, _m in issues}

    def test_clean_kernel_has_no_findings(self):
        assert self._rules(_toy_kernel_eqn()) == set()

    def test_vmem_overflow_caught(self):
        assert "vmem-budget" in self._rules(_toy_kernel_eqn(scratch_mib=13))

    def test_unwaited_dma_caught(self):
        assert "dma-unwaited" in self._rules(
            _toy_kernel_eqn(unwaited_dma=True))

    def test_unpaired_regular_semaphore_caught(self):
        assert "sem-unpaired" in self._rules(
            _toy_kernel_eqn(unpaired_sem=True))

    def test_misalignment_caught(self):
        rules = self._rules(_toy_kernel_eqn(misaligned=True))
        assert "lane-misaligned" in rules
        assert "sublane-misaligned" in rules

    def test_fragile_repeat_caught(self):
        assert "fragile-repeat" in self._rules(
            _toy_kernel_eqn(use_repeat=True))


class TestInjectedHotpathViolations:
    def test_unconditional_sync_caught_and_probe_exempt(self):
        src = (
            "import jax\n"
            "class S:\n"
            "    def _demux(self, out, probe):\n"
            "        jax.block_until_ready(out)\n"       # unconditional
            "        if probe:\n"
            "            jax.block_until_ready(out)\n"   # sampled: fine
            "    def warmup_all(self, out):\n"
            "        jax.block_until_ready(out)\n"       # off-path: fine
        )
        fs = hotpath_audit.sync_lint_source(src, "fixture.py")
        assert len(fs) == 1
        assert fs[0].rule == "hotpath-sync" and fs[0].line == 4

    def test_sync_inside_if_condition_caught(self):
        """The condition expression runs unconditionally — a sync there
        must not inherit its own `if` as probe cover."""
        src = ("import jax\n"
               "def serve(flag):\n"
               "    if jax.device_get(flag):\n"
               "        pass\n")
        fs = hotpath_audit.sync_lint_source(src, "fixture.py")
        assert [f.rule for f in fs] == ["hotpath-sync"]

    def test_callback_in_searcher_closure_caught(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        def bad_searcher(q):
            return jax.pure_callback(
                lambda x: np.asarray(x), jax.ShapeDtypeStruct(
                    q.shape, q.dtype), q)

        stats, fs = hotpath_audit.audit_searcher(
            "bad", bad_searcher, jnp.zeros((4, 8)))
        assert [f.rule for f in fs] == ["hotpath-callback"]
        # and a clean closure audits clean + one-dispatch
        stats, fs = hotpath_audit.audit_searcher(
            "good", lambda q: q * 2.0, jnp.zeros((4, 8)))
        assert not fs and stats["one_dispatch"]

    def test_jit_static_hazards_caught(self):
        src = (
            "import functools, jax\n"
            "@functools.partial(jax.jit,\n"
            "                   static_argnames=('k', 'rate', 'typo'))\n"
            "def f(x, k: int, rate: float = 0.5):\n"
            "    return x\n"
        )
        fs = hotpath_audit.jit_static_lint_source(src, "fixture.py")
        rules = {f.symbol: f.rule for f in fs}
        assert rules == {"f:rate": "jit-static-float",
                         "f:typo": "jit-static-missing"}

    def test_bare_partial_jit_form_also_linted(self):
        """cagra.py spells it `@partial(jax.jit, ...)` — the bare
        imported-name form must not be a blind spot."""
        src = (
            "from functools import partial\n"
            "import jax\n"
            "@partial(jax.jit, static_argnames=('typo',))\n"
            "def g(x):\n"
            "    return x\n"
        )
        fs = hotpath_audit.jit_static_lint_source(src, "fixture.py")
        assert [f.rule for f in fs] == ["jit-static-missing"]

    def test_sync_in_nested_def_not_covered_by_outer_probe_if(self):
        """A closure defined under `if probe:` runs later,
        unconditionally — the outer condition is not probe cover."""
        src = (
            "import jax\n"
            "def serve(out, probe):\n"
            "    if probe:\n"
            "        def cb():\n"
            "            jax.block_until_ready(out)\n"
            "        return cb\n"
        )
        fs = hotpath_audit.sync_lint_source(src, "fixture.py")
        assert [f.rule for f in fs] == ["hotpath-sync"]


_LOCK_FIXTURE = """
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}
        self._count = 0
        self._free = 0

    def update(self, k, v):
        with self._lock:
            self._state[k] = v
            self._count += 1

    def racy_read(self):
        return self._count            # VIOLATION: guarded, no hold

    def racy_write(self):
        self._state["x"] = 1          # VIOLATION: guarded, no hold

    def snapshot_locked(self):
        return dict(self._state)      # caller-holds-lock convention

    def waived_read(self):
        # lint: waive(unlocked-attr): fixture-documented atomic peek
        return self._count

    def free_read(self):
        return self._free             # never written under lock: clean
"""


class TestInjectedLockViolations:
    def test_unlocked_guarded_attr_caught(self):
        fs = lock_lint.lint_source(_LOCK_FIXTURE, "fixture.py")
        got = {f.symbol for f in fs}
        assert "Engine.racy_read._count" in got
        assert "Engine.racy_write._state" in got
        assert all(f.line > 0 for f in fs)
        # the *_locked convention and the never-guarded attr stay clean
        assert not [f for f in fs if "snapshot_locked" in f.symbol]
        assert not [f for f in fs if "_free" in f.symbol]
        # the waiver is honoured inside lint_source (access-level,
        # BEFORE dedupe)
        assert not [f for f in fs if "waived_read" in f.symbol]
        assert len(fs) == 2

    def test_waived_access_does_not_shadow_later_unwaived(self):
        """A waived first peek must not dedupe away a later UNWAIVED
        access to the same attribute in the same method."""
        src = (
            "import threading\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = []\n"
            "    def put(self, v):\n"
            "        with self._lock:\n"
            "            self._q.append(v)\n"
            "    def peek_then_race(self):\n"
            "        # lint: waive(unlocked-attr): cheap saturation peek\n"
            "        n = len(self._q)\n"
            "        return n, list(self._q)\n"     # racy, NOT waived
        )
        fs = lock_lint.lint_source(src, "fixture.py")
        assert [f.symbol for f in fs] == ["E.peek_then_race._q"]
        assert fs[0].line == 12

    def test_nested_def_in_locked_method_still_flagged(self):
        """A `*_locked` method's DIRECT body holds the lock; a closure it
        defines runs later, off the lock — that access must still
        fire."""
        src = (
            "import threading\n"
            "class E:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
            "    def read_locked(self):\n"
            "        direct = self._n\n"          # caller holds: fine
            "        def later():\n"
            "            return self._n\n"        # runs off-lock: flag
            "        return later\n"
        )
        fs = lock_lint.lint_source(src, "fixture.py")
        assert [f.symbol for f in fs] == ["E.read_locked.later._n"]

    def test_module_global_discipline(self):
        src = (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_REG = {}\n"
            "def put(k, v):\n"
            "    with _lock:\n"
            "        _REG[k] = v\n"
            "def racy():\n"
            "    return list(_REG)\n"      # VIOLATION
            "def local_ok():\n"
            "    out = {}\n"               # locals never flagged
            "    out['a'] = 1\n"
            "    return out\n"
        )
        fs = lock_lint.lint_source(src, "fixture.py")
        assert [f.symbol for f in fs] == ["module.racy._REG"]


class TestServeTreeVerdicts:
    """The satellite fixes hold: the serving tree itself is clean —
    every surviving kernel finding is a pre-hardware warning, not a
    lock/hot-path violation."""

    def test_serve_and_mutable_lock_clean(self, tree_run):
        findings, _ = tree_run
        assert not [f for f in findings if f.rule == "unlocked-attr"], (
            [f.render() for f in findings if f.rule == "unlocked-attr"])

    def test_hotpath_clean(self, tree_run):
        findings, _ = tree_run
        hot = [f for f in findings
               if f.rule in ("hotpath-sync", "hotpath-shardmap-rebuild",
                             "jit-static-float", "jit-static-missing")]
        assert not hot, [f.render() for f in hot]

    def test_tenancy_modules_in_scan_lists(self):
        """The multi-tenant fabric (ISSUE 15) stays under the gate:
        serve/tenancy.py (weighted drain + swap flip — exactly the
        lock-discipline bug class) and serve/qcache.py must resolve
        into BOTH scan lists; a future restructure that moves them out
        of serve/ must update LOCK_MODULES/HOTPATH_MODULES too."""
        import os

        import raft_tpu
        from raft_tpu.analysis import iter_module_paths
        from raft_tpu.analysis.hotpath_audit import HOTPATH_MODULES
        from raft_tpu.analysis.lock_lint import LOCK_MODULES

        root = os.path.dirname(os.path.dirname(raft_tpu.__file__))
        for entries in (LOCK_MODULES, HOTPATH_MODULES):
            rels = set(iter_module_paths(root, entries))
            for mod in ("raft_tpu/serve/tenancy.py",
                        "raft_tpu/serve/qcache.py"):
                assert mod in rels, f"{mod} fell out of the scan list"

    def test_fragile_repeat_is_baselined_not_new(self, tree_run):
        """The documented ivf_pq pltpu.repeat quirk is visible to the
        gate (it must not silently disappear while the kernel still
        calls repeat) and is baselined, pending real-TPU adjudication."""
        findings, _ = tree_run
        rep = [f for f in findings if f.rule == "fragile-repeat"]
        assert len(rep) == 1
        assert rep[0].path == "raft_tpu/ops/ivf_pq_scan.py"
        assert rep[0].key in set(analysis.load_baseline())
