"""Ring-top-k sharded merge tests (the `multichip` CPU emulation lane).

The acceptance bar (ISSUE 8): the ring merge is BIT-identical — order
included — to ``knn_merge_parts`` on the emulated 8-device mesh, with
exact-tie candidates, with dead shards under ``allow_partial=True``, and
under ``guarded_call`` fault injection (which must serve the allgather
path with identical results and record no demotion). The Pallas VMEM
fold is pinned against the XLA fold in interpret mode; the full remote-
DMA ring kernel compiles only on a real TPU (`tpu` lane test).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.ops import ring_topk
from raft_tpu.parallel import sharded_ann, sharded_knn
from raft_tpu.utils import shard_map_compat

pytestmark = pytest.mark.multichip


@pytest.fixture(autouse=True)
def _no_disk_autotune(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE", "")


def _sharded_parts(mesh, d, gid):
    spec = NamedSharding(mesh, P("shard", None, None))
    return (jax.device_put(jnp.asarray(d), spec),
            jax.device_put(jnp.asarray(gid), spec))


def _merge_on_mesh(mesh, dd, gg, k, select_min, engine):
    p = mesh.shape["shard"]

    def body(ds, gs):
        return ring_topk.merge(ds[0], gs[0], k, select_min, axis="shard",
                               axis_size=p, engine=engine)

    f = shard_map_compat(body, mesh=mesh,
                         in_specs=(P("shard", None, None),) * 2,
                         out_specs=(P(), P()), check=False)
    return f(dd, gg)


@pytest.fixture(scope="module")
def parts():
    """(p=8, m, k) candidate blocks with cross-shard exact ties and one
    dead shard's (+inf, -1) sentinel block."""
    rng = np.random.default_rng(0)
    p, m, k = 8, 16, 7
    d = np.sort(rng.standard_normal((p, m, k)).astype(np.float32), axis=-1)
    d[3] = d[1]                      # bit-exact ties across shards
    gid = rng.integers(0, 100_000, size=(p, m, k)).astype(np.int32)
    d[5], gid[5] = np.inf, -1        # dead shard sentinels
    return d, gid


class TestMergeBitIdentity:
    @pytest.mark.parametrize("select_min", [True, False])
    def test_ring_matches_knn_merge_parts(self, multichip_mesh, parts,
                                          select_min):
        d, gid = parts
        d = d if select_min else -d
        k = d.shape[-1]
        ref = brute_force.knn_merge_parts(jnp.asarray(d), jnp.asarray(gid),
                                          select_min)
        dd, gg = _sharded_parts(multichip_mesh, d, gid)
        od, og = _merge_on_mesh(multichip_mesh, dd, gg, k, select_min,
                                "ring")
        np.testing.assert_array_equal(np.asarray(od), np.asarray(ref[0]))
        np.testing.assert_array_equal(np.asarray(og), np.asarray(ref[1]))

    def test_allgather_engine_matches_reference(self, multichip_mesh, parts):
        # the fallback engine must BE the reference path
        d, gid = parts
        k = d.shape[-1]
        ref = brute_force.knn_merge_parts(jnp.asarray(d), jnp.asarray(gid),
                                          True)
        dd, gg = _sharded_parts(multichip_mesh, d, gid)
        od, og = _merge_on_mesh(multichip_mesh, dd, gg, k, True, "allgather")
        np.testing.assert_array_equal(np.asarray(od), np.asarray(ref[0]))
        np.testing.assert_array_equal(np.asarray(og), np.asarray(ref[1]))


class TestVmemFoldKernel:
    @pytest.mark.parametrize("select_min", [True, False])
    def test_interpret_matches_xla_fold(self, select_min):
        """The merge step the TPU ring kernel runs per hop, through the
        real Pallas kernel in interpret mode, vs the lax.sort fold —
        ties (equal value, position decides) included."""
        rng = np.random.default_rng(1)
        m, w, k = 6, 9, 6
        rd = np.sort(rng.standard_normal((m, w)).astype(np.float32), -1)
        bd = np.sort(rng.standard_normal((m, w)).astype(np.float32), -1)
        bd[2] = rd[2]               # tie rows: position must decide
        rp = np.tile(np.arange(w, dtype=np.int32), (m, 1))
        bp = rp + 7 * w
        rg = rng.integers(0, 999, (m, w)).astype(np.int32)
        bg = rng.integers(0, 999, (m, w)).astype(np.int32)
        if not select_min:
            rd, bd = -rd, -bd
        args = tuple(map(jnp.asarray, (rd, rp, rg, bd, bp, bg)))
        want = ring_topk.merge_step(*args, k, select_min=select_min,
                                    engine="xla")
        got = ring_topk.merge_step(*args, k, select_min=select_min,
                                   engine="pallas", interpret=True)
        for a, b in zip(want, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(scope="module")
def mesh4():
    """4-device mesh for the family-level flow: the ring program unrolls
    p−1 hops, so family compile cost halves at p=4 while the 8-device
    bit-identity acceptance stays with TestMergeBitIdentity above."""
    from jax.sharding import Mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    return Mesh(np.array(jax.devices()[:4]), ("shard",))


@pytest.fixture(scope="module")
def flat4(mesh4):
    rng = np.random.default_rng(2)
    data = rng.standard_normal((1_200, 16)).astype(np.float32)
    q = rng.standard_normal((12, 16)).astype(np.float32)
    index = sharded_ann.build_ivf_flat(
        data, mesh4, ivf_flat.IndexParams(n_lists=4, seed=0))
    return index, data, q


class TestShardedFamilies:
    """Every eager sharded search recompiles its shard_map program (~5 s
    on the CPU mesh), so the family-level acceptance flow — healthy
    bit-identity, ≥1 dead shard under allow_partial, fault-injected
    demotion to allgather, make_searcher/debugz pick-up — runs as ONE
    consolidated test with the minimum number of search dispatches."""

    @pytest.mark.slow  # ~30s single-core (5 eager shard_map compiles);
    # tier-1 keeps the per-family sharded coverage in test_sharded_ann
    # and the breaker arc drills in test_faults
    def test_ring_acceptance_flow(self, flat4):
        from raft_tpu.core import faults
        from raft_tpu.ops import guarded
        from raft_tpu.serve import debugz, metrics

        index, _, q = flat4
        sp = ivf_flat.SearchParams(n_probes=4)
        # 1-2) healthy: ring bit-identical to the allgather reference
        #      (2-tuple legacy API preserved)
        da, ia = sharded_ann.search_ivf_flat(index, q, 5, params=sp)
        dr, ir = sharded_ann.search_ivf_flat(index, q, 5, params=sp,
                                             merge_engine="ring")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ir))
        np.testing.assert_array_equal(np.asarray(da), np.asarray(dr))
        assert sharded_ann._ACTIVE_ENGINE["ivf_flat"] == "ring"

        # ops surface: the engine tag and shard health are live
        snap = debugz.snapshot()
        fams = snap["sharded"]["families"]
        assert fams["ivf_flat"]["merge_engine"] == "ring"
        assert all(all(ok) for ok in fams["ivf_flat"]["shards_ok"])
        assert isinstance(snap["sharded"]["ring_demotions"], int)
        assert "engine=ring" in debugz.render_text()

        # 3) dead shard under allow_partial through the RING engine: the
        #    loss reported, full answer from survivors, no dead-shard row
        #    surfaces (ring-vs-allgather identity for sentinel blocks is
        #    pinned against knn_merge_parts in TestMergeBitIdentity)
        index.mark_shard_failed(3)
        try:
            dpr, ipr, okr = sharded_ann.search_ivf_flat(
                index, q, 5, params=sp, allow_partial=True,
                merge_engine="ring")
        finally:
            index.mark_shard_failed(3, ok=True)
        assert list(okr) == [True, True, True, False]
        got = np.asarray(ipr)       # shard 3 = rows [900, 1200)
        assert not ((got >= 900) & (got < 1200)).any()
        assert (got >= 0).all() and np.isfinite(np.asarray(dpr)).all()
        hs = debugz.snapshot()["sharded"]["families"]["ivf_flat"]
        assert all(all(ok) for ok in hs["shards_ok"])  # re-marked healthy

        # 4) fault injection: the guarded site serves the allgather path
        #    with identical results, demotion NOT sticky, counter ticks
        before = metrics.counter("sharded.ring.demotions").value
        with faults.inject("kernel_compile", "sharded.ring_topk"):
            df, if_ = sharded_ann.search_ivf_flat(
                index, q, 5, params=sp, merge_engine="ring")
        np.testing.assert_array_equal(np.asarray(if_), np.asarray(ia))
        np.testing.assert_array_equal(np.asarray(df), np.asarray(da))
        assert "sharded.ring_topk" not in guarded.demoted_sites()
        assert metrics.counter("sharded.ring.demotions").value == before + 1
        assert sharded_ann._ACTIVE_ENGINE["ivf_flat"] == "allgather"

        # 5) healthy allow_partial (ring, post-fault: the path is live
        #    again): all-ok reported, ids identical to the reference
        d3, i3, ok3 = sharded_ann.search_ivf_flat(
            index, q, 5, params=sp, allow_partial=True,
            merge_engine="ring")
        assert ok3.all()
        np.testing.assert_array_equal(np.asarray(i3), np.asarray(ia))
        assert sharded_ann._ACTIVE_ENGINE["ivf_flat"] == "ring"

        # the serving closure threads merge_engine through to resolution
        # (raises in resolve_engine, before any compile)
        fn = sharded_ann.make_searcher(index, sp, merge_engine="bogus")
        with pytest.raises(Exception, match="merge engine"):
            fn(q, 5)

    def test_sharded_knn_ring_bit_identical(self, mesh4):
        rng = np.random.default_rng(3)
        data = rng.standard_normal((1_600 - 9, 16)).astype(np.float32)
        q = rng.standard_normal((8, 16)).astype(np.float32)
        index = sharded_knn.build(data, mesh4)
        d1, i1 = sharded_knn.search(index, q, 5, algo="scan")
        d2, i2 = sharded_knn.search(index, q, 5, algo="scan",
                                    merge_engine="ring")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


class TestEngineResolution:
    def test_override_and_env(self, monkeypatch):
        assert ring_topk.resolve_engine(8, 5, 4, override="ring") == "ring"
        monkeypatch.setenv("RAFT_TPU_SHARDED_MERGE", "ring")
        assert ring_topk.resolve_engine(8, 5, 4) == "ring"
        monkeypatch.setenv("RAFT_TPU_SHARDED_MERGE", "allgather")
        assert ring_topk.resolve_engine(8, 5, 4) == "allgather"
        # param override beats env
        assert ring_topk.resolve_engine(8, 5, 4, override="ring") == "ring"
        with pytest.raises(Exception):
            ring_topk.resolve_engine(8, 5, 4, override="bogus")

    def test_subgroups_and_trivial_mesh_force_allgather(self):
        assert ring_topk.resolve_engine(8, 5, 4,
                                        plain_axis=False) == "allgather"
        assert ring_topk.resolve_engine(8, 5, 1,
                                        override="ring") == "allgather"

    def test_cpu_default_is_allgather_and_pallas_gated(self):
        # no TPU in tier-1: the remote-DMA kernel must never be resolved,
        # and asking for it degrades to the XLA ring, not an error
        assert not ring_topk.ring_capable(8, 5, backend="cpu")
        assert ring_topk.resolve_engine(8, 5, 4) == "allgather"
        assert ring_topk.resolve_engine(
            8, 5, 4, override="ring_pallas") == "ring"

    def test_note_fallback_reports_to_ops_surface(self):
        from raft_tpu.serve import metrics

        before = metrics.counter("sharded.ring.demotions").value
        ring_topk.note_engine("knn", "ring")
        ring_topk.note_fallback("knn")
        assert ring_topk.active_engines["knn"] == "allgather"
        assert metrics.counter("sharded.ring.demotions").value == before + 1
        # shared dict: sharded_ann's ops surface sees the same state
        assert sharded_ann._ACTIVE_ENGINE is ring_topk.active_engines
        assert sharded_ann.ops_snapshot()["families"]["knn"][
            "merge_engine"] == "allgather"

    def test_mesh_aware_resolution(self, mesh4):
        # a CPU mesh must never resolve to the TPU-only remote-DMA
        # kernel, whatever the process default backend is
        assert ring_topk.resolve_engine(32, 5, 4, mesh=mesh4) == "allgather"
        assert "meshcpu" in ring_topk._bucket(8, 5, 4, jnp.float32, mesh4)

    def test_autotune_verdict_steers(self, multichip_mesh):
        from raft_tpu.ops import autotune

        winner, timings = ring_topk.tune_merge(multichip_mesh, m=8, k=5)
        assert winner in ring_topk.ENGINES
        assert set(timings) >= {"allgather", "ring"}
        assert ring_topk.resolve_engine(8, 5, 8) == winner
        autotune.forget(ring_topk._bucket(8, 5, 8, jnp.float32))


@pytest.mark.tpu
class TestTpuRingKernel:
    def test_ring_pallas_bit_identical(self):
        """The remote-DMA ring kernel vs the allgather merge on a real
        TPU mesh (RAFT_TPU_TEST_LANE=1; remote DMA has no CPU interpret
        emulation on this jax)."""
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs a multi-chip TPU mesh")
        mesh = Mesh(np.array(devs), ("shard",))
        p = len(devs)
        rng = np.random.default_rng(0)
        m, k = 32, 10
        d = np.sort(rng.standard_normal((p, m, k)).astype(np.float32), -1)
        gid = rng.integers(0, 1 << 20, size=(p, m, k)).astype(np.int32)
        ref = brute_force.knn_merge_parts(jnp.asarray(d),
                                          jnp.asarray(gid), True)
        dd, gg = _sharded_parts(mesh, d, gid)
        od, og = _merge_on_mesh(mesh, dd, gg, k, True, "ring_pallas")
        np.testing.assert_array_equal(np.asarray(od), np.asarray(ref[0]))
        np.testing.assert_array_equal(np.asarray(og), np.asarray(ref[1]))
