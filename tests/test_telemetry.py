"""Request-lifecycle telemetry tests: trace-ID propagation + stage
decomposition through the serving pipeline, the flight recorder, the
recompile watch, the debugz ops surface, and the zero-overhead-when-off
guarantee (docs/observability.md).

Everything except the recompile-watch test runs on STUB searchers (no
XLA compiles) so the whole file stays well under the tier-1 budget.
"""
import io
import json
import time

import jax
import numpy as np
import pytest

from raft_tpu.core import events, faults, serialize, tracing
from raft_tpu.core.deadline import Deadline, DeadlineExceeded
from raft_tpu.core.errors import CorruptIndexError
from raft_tpu.serve import debugz, metrics
from raft_tpu.serve.batcher import STAGES, BucketLadder, MicroBatcher

pytestmark = pytest.mark.serve

DIM = 16


def stub_search(queries, k, res=None):
    m = queries.shape[0]
    return (np.zeros((m, k), np.float32),
            np.tile(np.arange(k, dtype=np.int32), (m, 1)))


@pytest.fixture
def reg():
    return metrics.Registry()


@pytest.fixture(autouse=True)
def _clean_rings():
    events.clear()
    tracing.clear_span_log()
    yield


class TestTracingPrimitives:
    def test_trace_ids_and_binding(self):
        a, b = tracing.new_trace_id(), tracing.new_trace_id()
        assert a != b and len(a) == 16
        assert tracing.current_traces() == ()
        with tracing.bind_trace(a):
            assert tracing.current_trace() == a
            with tracing.bind_trace(a, b):
                assert tracing.current_traces() == (a, b)
            assert tracing.current_traces() == (a,)
        assert tracing.current_trace() is None

    def test_child_span_collects(self):
        out = {}
        with tracing.child_span("unit::stage", out):
            pass
        assert out["unit::stage"] >= 0.0

    def test_sample_rate_validation(self, monkeypatch):
        monkeypatch.delenv("RAFT_TPU_TRACE_SAMPLE", raising=False)
        assert tracing.sample_rate() == 0.0
        monkeypatch.setenv("RAFT_TPU_TRACE_SAMPLE", "0.25")
        assert tracing.sample_rate() == 0.25
        assert tracing.sample_rate(1.0) == 1.0     # explicit beats env
        for bad in ("nope", "-0.1", "1.5", "nan"):
            monkeypatch.setenv("RAFT_TPU_TRACE_SAMPLE", bad)
            with pytest.raises(ValueError):
                tracing.sample_rate()
        # the knob is validated at batcher construction, not first sample
        with pytest.raises(ValueError):
            MicroBatcher(stub_search, DIM, trace_sample=2.0,
                         autostart=False)

    def test_span_log_ring(self):
        for i in range(5):
            tracing.log_spans(f"t{i}", {"dispatch": 0.001 * i}, rows=1)
        spans = tracing.recent_spans(3)
        assert [s["trace_id"] for s in spans] == ["t2", "t3", "t4"]
        tracing.set_span_log_capacity(2)
        try:
            assert len(tracing.recent_spans()) == 2
        finally:
            tracing.set_span_log_capacity(256)


class TestEventsRing:
    def test_record_recent_export(self, tmp_path):
        events.record("unit_kind", "unit.site", detail=7)
        with tracing.bind_trace("abc123"):
            events.record("unit_kind", "unit.site2")
        evs = events.recent(kind="unit_kind")
        assert len(evs) == 2
        assert evs[0]["trace_id"] is None and evs[0]["detail"] == 7
        assert evs[1]["trace_id"] == "abc123"
        assert evs[1]["seq"] > evs[0]["seq"]
        assert events.counts()["unit_kind"] == 2
        lines = events.to_jsonl(kind="unit_kind").strip().splitlines()
        assert len(lines) == 2 and json.loads(lines[0])["site"] == "unit.site"
        path = tmp_path / "events.jsonl"
        assert events.export_jsonl(str(path)) == 2
        assert len(path.read_text().strip().splitlines()) == 2

    def test_bounded_ring(self):
        events.set_capacity(8)
        try:
            for i in range(20):
                events.record("flood", f"s{i}")
            evs = events.recent()
            assert len(evs) == 8 and evs[-1]["site"] == "s19"
        finally:
            events.set_capacity(events.DEFAULT_CAPACITY)
            events.clear()


class TestTracePropagation:
    def test_cobatched_pair_distinct_decompositions(self, reg):
        """Two requests coalesced into ONE batch: each yields its own
        five-stage decomposition (own trace ID, own queue_wait, shared
        batch stages) in the span log AND the stage histograms."""
        b = MicroBatcher(stub_search, DIM, ladder=BucketLadder((8,), (8,)),
                         registry=reg, autostart=False, trace_sample=1.0,
                         max_wait_s=0.001)
        r1 = b.submit(np.zeros((3, DIM), np.float32), 5)
        time.sleep(0.002)      # make the two queue waits distinguishable
        r2 = b.submit(np.zeros((2, DIM), np.float32), 5)
        assert r1.trace_id != r2.trace_id
        b.start()
        r1.result(60)
        r2.result(60)
        b.close()
        assert reg.counter("serve.batches").value == 1   # truly co-batched
        spans = {s["trace_id"]: s for s in tracing.recent_spans()}
        assert set(spans) == {r1.trace_id, r2.trace_id}
        s1, s2 = spans[r1.trace_id], spans[r2.trace_id]
        for s in (s1, s2):
            assert set(s["stages"]) == set(STAGES)
            assert s["bucket"] == "8x8"
        # distinct decompositions: r1 waited ~2ms longer than r2; the
        # shared batch stages agree exactly
        assert s1["stages"]["queue_wait"] > s2["stages"]["queue_wait"]
        assert s1["stages"]["dispatch"] == s2["stages"]["dispatch"]
        assert s1["rows"] == 3 and s2["rows"] == 2
        # metrics snapshot carries the five-stage latency decomposition
        snap = reg.snapshot()["histograms"]
        for s in STAGES:
            assert snap[f"serve.stage.{s}_s"]["count"] == 2

    def test_sampling_interval(self, reg):
        """trace_sample=0.5 decomposes every 2nd batch (deterministic
        counter, not a coin flip)."""
        b = MicroBatcher(stub_search, DIM, ladder=BucketLadder((8,), (8,)),
                         registry=reg, autostart=False, trace_sample=0.5,
                         max_wait_s=0.0)
        reqs = []
        b.start()
        for _ in range(4):     # serial singles: 4 batches
            r = b.submit(np.zeros((1, DIM), np.float32), 5)
            r.result(60)
            reqs.append(r)
        b.close()
        assert reg.counter("serve.batches").value == 4
        sampled = {s["trace_id"] for s in tracing.recent_spans()}
        assert sampled == {reqs[0].trace_id, reqs[2].trace_id}

    def test_sampling_rate_never_exceeded(self):
        """ceil(1/rate), not round: 0.7 must probe every 2nd batch, never
        100% (the knob bounds telemetry's latency cost from above)."""
        b = MicroBatcher(stub_search, DIM, ladder=BucketLadder((8,), (8,)),
                         registry=metrics.Registry(), autostart=False,
                         trace_sample=0.7)
        assert b._probe_every == 2


class TestFlightRecorder:
    def test_demotion_and_sheds_stamped_with_trace_id(self, reg):
        """The acceptance drill: an injected guarded demotion and a
        deadline shed both land in the recorder stamped with the
        originating request's trace ID."""
        from raft_tpu.ops import guarded

        if any(f.kind == "kernel_compile" for f in faults.active()):
            pytest.skip("ambient kernel faults are served as injected "
                        "(non-demoting) failures")

        def demoting_search(queries, k, res=None):
            def boom():
                raise RuntimeError("mosaic lowering died")

            guarded.guarded_call("telemetry.kernel", boom,
                                 lambda: None)
            return stub_search(queries, k)

        b = MicroBatcher(demoting_search, DIM,
                         ladder=BucketLadder((8,), (8,)), registry=reg,
                         autostart=False, max_wait_s=0.001)
        req = b.submit(np.zeros((2, DIM), np.float32), 4)
        dead = b.submit(np.zeros((2, DIM), np.float32), 4,
                        deadline=Deadline(0.0))
        b.start()
        try:
            req.result(60)
            with pytest.raises(DeadlineExceeded):
                dead.result(60)
        finally:
            b.close()
            guarded.reset()
        demo = events.recent(kind="guarded_demotion")
        assert len(demo) == 1 and demo[0]["site"] == "telemetry.kernel"
        assert demo[0]["trace_id"] == req.trace_id
        shed = events.recent(kind="deadline_shed")
        assert len(shed) == 1 and shed[0]["trace_id"] == dead.trace_id
        assert shed[0]["site"] == "serve.shed"

    def test_mid_batch_deadline_event(self, reg):
        def ticking(ticks):
            it = iter(ticks)
            return lambda: next(it)

        def expiring(queries, k, res=None):
            raise DeadlineExceeded("deadline", partial=None)

        b = MicroBatcher(expiring, DIM, ladder=BucketLadder((8,), (8,)),
                         registry=reg, autostart=False, max_wait_s=0.001)
        # live through ctor/pop/dispatch/tightest probes, expired at the
        # partial-delivery check
        req = b.submit(np.zeros((2, DIM), np.float32), 4,
                       deadline=Deadline(1.0, clock=ticking(
                           [0., .1, .2, .3, 2.0, 2.1])))
        b.start()
        with pytest.raises(DeadlineExceeded):
            req.result(60)
        b.close()
        evs = events.recent(kind="deadline_exceeded")
        assert len(evs) == 1 and evs[0]["trace_id"] == req.trace_id

    def test_fault_fire_metric_and_event(self):
        before = metrics.counter(
            "faults.fired.slow_dispatch.telemetry.drill").value
        ev_before = len(events.recent(kind="fault_injected"))
        with faults.inject("slow_dispatch", "telemetry.drill", value=0.0):
            faults.sleep_if("telemetry.drill")
            faults.sleep_if("telemetry.drill")   # per-batch drill re-fire
        # counter carries the magnitude (every fire) ...
        assert metrics.counter(
            "faults.fired.slow_dispatch.telemetry.drill").value \
            == before + 2
        # ... but the bounded ring records only the fault's FIRST fire
        evs = events.recent(kind="fault_injected")
        assert len(evs) == ev_before + 1
        assert evs[-1]["site"] == "telemetry.drill"
        assert evs[-1]["kind"] == "fault_injected"

    def test_shard_mark_records_only_transitions(self):
        """Re-asserting an unchanged shard health state (a health-check
        loop) must not churn the bounded ring — only transitions land."""
        from raft_tpu.parallel.sharded_ann import _mark_shard

        ok = np.ones(4, bool)
        before = len(events.recent(kind="shard_marked"))
        _mark_shard(ok, "unit", 2, False)      # transition: healthy->dead
        _mark_shard(ok, "unit", 2, False)      # re-assert: no new event
        _mark_shard(ok, "unit", 2, True)       # transition: dead->healthy
        _mark_shard(ok, "unit", 2, True)       # re-assert: no new event
        evs = events.recent(kind="shard_marked")
        assert len(evs) == before + 2
        assert evs[-1]["ok"] is True and not evs[-2]["ok"]

    def test_corrupt_load_metric_and_event(self):
        before = metrics.counter("serialize.corrupt_load").value
        with pytest.raises(CorruptIndexError):
            serialize.load_arrays(io.BytesIO(b"not a raft_tpu file at all"))
        assert metrics.counter("serialize.corrupt_load").value == before + 1
        evs = events.recent(kind="corrupt_index")
        assert evs and evs[-1]["site"] == "header"

    def test_autotune_verdict_event(self):
        from raft_tpu.ops import autotune

        key = "cpu:test:telemetry_family:n1"
        try:
            autotune.record(key, "stub_engine", persist=False)
            evs = events.recent(kind="autotune_verdict")
            assert evs and evs[-1]["site"] == key
            assert evs[-1]["choice"] == "stub_engine"
            assert key in autotune.entries()
        finally:
            autotune.forget(key)


class TestRecompileWatch:
    def test_stream_counter_and_labels(self):
        from raft_tpu.serve import warmup as wu

        wu.install_recompile_watch()
        before = metrics.counter("serve.recompiles").value
        total_before = metrics.counter("serve.compiles").value
        with wu.compile_context("telemetry:16x8"):
            jax.block_until_ready(
                jax.jit(lambda x: x * 3.7 + 1)(np.arange(33, dtype=np.float32)))
        assert metrics.counter("serve.recompiles").value >= before + 1
        evs = events.recent(kind="xla_compile")
        assert any(e["site"] == "telemetry:16x8" and not e["warmup"]
                   for e in evs)
        # warmup-context compiles are counted in the totals but exempt
        # from the post-warmup counter AND from the bounded ring (a
        # ~100-compile warmup sweep must not churn out demotion events)
        before = metrics.counter("serve.recompiles").value
        with wu.compile_context("telemetry:warm", warmup=True):
            jax.block_until_ready(
                jax.jit(lambda x: x * 2.5 - 3)(np.arange(34, dtype=np.float32)))
        assert metrics.counter("serve.recompiles").value == before
        assert metrics.counter("serve.compiles").value >= total_before + 2
        assert not any(e["site"] == "telemetry:warm"
                       for e in events.recent(kind="xla_compile"))
        # count_compilations subscribes to the same persistent stream
        with wu.count_compilations() as cc:
            jax.block_until_ready(
                jax.jit(lambda x: x - 0.125)(np.arange(35, dtype=np.float32)))
        assert cc.count >= 1


class TestDebugz:
    def test_snapshot_and_render(self, reg, tmp_path):
        with MicroBatcher(stub_search, DIM, ladder=BucketLadder((8,), (8,)),
                          registry=reg, max_wait_s=0.001,
                          trace_sample=1.0) as b:
            b.search(np.zeros((2, DIM), np.float32), 5, timeout=60)
            events.record("unit_kind", "debugz.site")
            reg.histogram("unit.empty_h")     # NaN min/max must scrub
            snap = debugz.snapshot(batcher=b, registry=reg)
            # registry omitted -> the batcher's OWN registry, not the
            # default one (where its dispatch counters never land)
            assert debugz.snapshot(batcher=b)["ladder"]["dispatches"][
                "8x8"] == 1
            text = debugz.render_text(batcher=b, registry=reg)
            w = debugz.SnapshotWriter(str(tmp_path / "debugz.json"),
                                      interval_s=60.0, batcher=b,
                                      registry=reg)
            w.write_once()
        assert snap["ladder"]["dispatches"]["8x8"] == 1
        assert snap["ladder"]["queue_depth"] == 0
        assert snap["metrics"]["counters"]["serve.served"] == 1
        assert isinstance(snap["autotune"], dict)
        assert any(e["kind"] == "unit_kind" for e in snap["events"])
        assert snap["spans"]            # trace_sample=1.0 logged the request
        # strict-JSON-safe end to end: empty histograms must not leak
        # bare NaN tokens into on-disk post-mortem snapshots
        json.dumps(snap, allow_nan=False)
        # tail size 0 means "omit", not "everything in the ring"
        empty = debugz.snapshot(batcher=b, registry=reg, events_n=0,
                                spans_n=0)
        assert empty["events"] == [] and empty["spans"] == []
        assert "bucket ladder" in text and "8x8: 1 dispatches" in text
        assert "flight recorder" in text
        disk = json.loads((tmp_path / "debugz.json").read_text())
        assert disk["metrics"]["counters"]["serve.served"] == 1

    def test_snapshot_writer_background(self, reg, tmp_path):
        path = tmp_path / "bg.json"
        w = debugz.SnapshotWriter(str(path), interval_s=0.01, registry=reg)
        with w:
            deadline = time.monotonic() + 5.0
            while not path.exists() and time.monotonic() < deadline:
                time.sleep(0.01)
        assert path.exists() and "metrics" in json.loads(path.read_text())


class TestDriftGuard:
    # the public search/build verbs every neighbors family must trace
    VERBS = {"build", "search", "extend", "build_from_batches",
             "build_knn_graph", "knn", "eps_nn", "refine", "optimize"}

    def test_every_entry_point_is_annotated(self):
        import raft_tpu.neighbors as nb

        missing = []
        for mod_name in nb.__all__:
            mod = getattr(nb, mod_name)
            if mod_name == "ann_types":
                continue
            for fn_name in getattr(mod, "__all__", ()):
                if fn_name not in self.VERBS:
                    continue
                fn = getattr(mod, fn_name)
                if not getattr(fn, "__raft_traced__", False):
                    missing.append(f"{mod_name}.{fn_name}")
        assert not missing, (
            f"public neighbors entry points missing tracing.annotate: "
            f"{missing} — wrap them (docs/observability.md drift guard)")

    def test_every_literal_event_kind_is_registered(self):
        """Every literal flight-recorder kind emitted anywhere in the
        library must be in events.WELL_KNOWN_KINDS (operators grep
        dashboards by kind — a new emitter must announce its
        vocabulary), and every registered kind the docstring promises
        must actually be registered."""
        import os
        import re

        import raft_tpu

        root = os.path.dirname(raft_tpu.__file__)
        # events.record / _events.record / mutable's self._event helper,
        # with a literal first argument (possibly on the next line)
        pat = re.compile(
            r"(?:\bevents\.record|\b_events\.record|self\._event)"
            r"\(\s*\n?\s*\"([a-z_]+)\"")
        found = {}
        for dirpath, _dirs, files in os.walk(root):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path) as f:
                    for kind in pat.findall(f.read()):
                        found.setdefault(kind, []).append(
                            os.path.relpath(path, root))
        assert found, "the kind scan found nothing — pattern rot?"
        unregistered = {k: v for k, v in found.items()
                        if k not in events.WELL_KNOWN_KINDS}
        assert not unregistered, (
            f"flight-recorder kinds not in events.WELL_KNOWN_KINDS: "
            f"{unregistered} — register them (core/events.py docstring)")
        # the multi-tenant vocabulary this PR registered is present
        assert {"tenant_shed", "tenant_swap",
                "qcache_stale"} <= events.WELL_KNOWN_KINDS


class TestZeroOverheadWhenOff:
    def test_disabled_path_runs_no_device_probe(self, reg, monkeypatch):
        """With sampling off, the serving hot path must never sync the
        device (the accidental-always-on-probe regression guard)."""
        from raft_tpu.serve import batcher as batcher_mod

        calls = []
        real = jax.block_until_ready
        monkeypatch.setattr(batcher_mod.jax, "block_until_ready",
                            lambda x: (calls.append(1), real(x))[1])
        monkeypatch.delenv("RAFT_TPU_TRACE_SAMPLE", raising=False)
        spans_before = len(tracing.recent_spans())
        with MicroBatcher(stub_search, DIM, ladder=BucketLadder((8,), (8,)),
                          registry=reg, max_wait_s=0.001) as b:
            for _ in range(4):
                b.search(np.zeros((2, DIM), np.float32), 5, timeout=60)
        assert calls == [], "sampling disabled but the batcher synced " \
                            "the device (always-on probe regression)"
        assert len(tracing.recent_spans()) == spans_before
        assert not any(name.startswith("serve.stage.")
                       for name in reg.snapshot()["histograms"])

    def test_disabled_annotate_overhead_within_noise(self):
        """Disabled tracing probes must stay branch-cheap: the annotate
        wrapper with timer off + tracing off is bounded by an absolute
        per-call overhead far below any real probe (a stray histogram
        observe or block_until_ready per call would blow it by orders
        of magnitude). Generous bound: timing on the 1-core CI box is
        noisy."""
        tracing.set_timer(None)
        was_enabled = tracing.enabled()
        tracing.disable()
        try:
            def raw(x):
                return x + 1

            wrapped = tracing.annotate("unit::overhead")(raw)

            def bench(fn, n=20000):
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    for i in range(n):
                        fn(i)
                    best = min(best, (time.perf_counter() - t0) / n)
                return best

            base = bench(raw)
            cost = bench(wrapped)
            assert cost - base < 20e-6, (
                f"disabled annotate overhead {cost - base:.2e}s/call — "
                "a probe is running on the disabled path")
        finally:
            if was_enabled:
                tracing.enable()
