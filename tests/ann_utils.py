"""Shared ANN test helpers: NumPy brute-force oracle + recall evaluation.

Analog of the reference's cpp/test/neighbors/ann_utils.cuh
(calc_recall/eval_neighbours) and the pure-NumPy oracle in
python/pylibraft/pylibraft/test/ann_utils.py.
"""
import numpy as np


def naive_knn(dataset: np.ndarray, queries: np.ndarray, k: int,
              metric: str = "sqeuclidean"):
    """Exact reference kNN on host; returns (distances, indices)."""
    if metric in ("sqeuclidean", "euclidean", "l2_expanded"):
        d = (
            (queries**2).sum(1)[:, None]
            + (dataset**2).sum(1)[None, :]
            - 2.0 * queries @ dataset.T
        )
        d = np.maximum(d, 0)
        if metric == "euclidean":
            d = np.sqrt(d)
    elif metric == "inner_product":
        d = -(queries @ dataset.T)  # negate: sort ascending = best first
    elif metric == "cosine":
        qn = np.linalg.norm(queries, axis=1, keepdims=True)
        dn = np.linalg.norm(dataset, axis=1, keepdims=True)
        d = 1 - (queries @ dataset.T) / np.maximum(qn * dn.T, 1e-30)
    else:
        raise ValueError(metric)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    dist = np.take_along_axis(d, idx, axis=1)
    if metric == "inner_product":
        dist = -dist
    return dist, idx


def calc_recall(found: np.ndarray, expected: np.ndarray) -> float:
    """Fraction of expected neighbors present in found (per row, averaged) —
    the eval_recall metric from ann_utils.cuh:129."""
    assert found.shape == expected.shape
    hits = sum(
        len(set(found[i]) & set(expected[i])) for i in range(found.shape[0])
    )
    return hits / found.size
