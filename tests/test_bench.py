"""Bench harness tests: IO formats, dataset loading, runner schema, CLI
export/plot — on tiny shapes (CPU)."""
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from raft_tpu import bench


class TestIO:
    def test_fbin_ibin_roundtrip(self, tmp_path):
        a = np.random.default_rng(0).standard_normal((13, 7)).astype(np.float32)
        bench.write_fbin(tmp_path / "a.fbin", a)
        np.testing.assert_array_equal(bench.read_fbin(tmp_path / "a.fbin"), a)
        b = np.arange(12, dtype=np.int32).reshape(4, 3)
        bench.write_ibin(tmp_path / "b.ibin", b)
        np.testing.assert_array_equal(bench.read_ibin(tmp_path / "b.ibin"), b)

    def test_load_synthetic(self):
        base, q, gt, metric = bench.load_dataset("blobs-1000x16",
                                                 n_queries=100)
        assert base.shape == (1000, 16) and q.shape == (100, 16)
        assert gt is None and metric == "sqeuclidean"

    def test_load_bigann_dir(self, tmp_path):
        rng = np.random.default_rng(1)
        base = rng.standard_normal((200, 8)).astype(np.float32)
        qs = rng.standard_normal((20, 8)).astype(np.float32)
        d = tmp_path / "toy"
        d.mkdir()
        bench.write_fbin(d / "base.fbin", base)
        bench.write_fbin(d / "query.fbin", qs)
        got_b, got_q, gt, metric = bench.load_dataset(
            "toy", dataset_dir=str(tmp_path))
        np.testing.assert_array_equal(got_b, base)
        np.testing.assert_array_equal(got_q, qs)
        assert gt is None

    def test_load_hdf5(self, tmp_path):
        import h5py

        rng = np.random.default_rng(2)
        with h5py.File(tmp_path / "toy-8-angular.hdf5", "w") as f:
            f["train"] = rng.standard_normal((100, 8)).astype(np.float32)
            f["test"] = rng.standard_normal((10, 8)).astype(np.float32)
            f["neighbors"] = rng.integers(0, 100, (10, 5)).astype(np.int32)
        base, q, gt, metric = bench.load_dataset("toy-8-angular",
                                                 dataset_dir=str(tmp_path))
        assert base.shape == (100, 8) and gt.shape == (10, 5)
        # ann-benchmarks "-angular" ground truth is cosine distance
        assert metric == "cosine"


class TestLaneResolution:
    """The standing Pareto lane resolves SIFT-1M, falling back to a
    bounded synthetic so the pipeline still runs without the dataset
    (ROADMAP item 2a)."""

    def test_fbin_dir_preferred(self, tmp_path):
        from raft_tpu.bench.datasets import resolve_lane_dataset

        d = tmp_path / "sift-1m"
        d.mkdir()
        bench.write_fbin(d / "base.fbin", np.zeros((4, 8), np.float32))
        assert resolve_lane_dataset(str(tmp_path)) == ("sift-1m", "fbin")

    def test_hdf5_second(self, tmp_path):
        import h5py

        from raft_tpu.bench.datasets import resolve_lane_dataset

        with h5py.File(tmp_path / "sift-128-euclidean.hdf5", "w") as f:
            f["train"] = np.zeros((4, 8), np.float32)
        assert resolve_lane_dataset(str(tmp_path)) == (
            "sift-128-euclidean", "hdf5")
        # an fbin dir outranks the hdf5
        d = tmp_path / "sift-1m"
        d.mkdir()
        bench.write_fbin(d / "base.fbin", np.zeros((4, 8), np.float32))
        assert resolve_lane_dataset(str(tmp_path))[1] == "fbin"

    def test_synthetic_fallback(self, tmp_path):
        from raft_tpu.bench.datasets import resolve_lane_dataset

        name, kind = resolve_lane_dataset(str(tmp_path), budget_rows=5000)
        assert (name, kind) == ("blobs-5000x128", "synthetic-fallback")
        # the fallback name must load through the normal dataset path
        base, q, gt, metric = bench.load_dataset(name, n_queries=16)
        assert base.shape == (5000, 128) and metric == "sqeuclidean"

    def test_lane_cli_stamps_kind(self, tmp_path, monkeypatch):
        """`bench lane` on an empty dataset dir runs the fallback sweep
        and stamps how the lane resolved into the artifact, so a
        synthetic run can never be mistaken for a SIFT number."""
        from raft_tpu.bench.__main__ import main

        monkeypatch.chdir(tmp_path)
        out = tmp_path / "lane.json"
        main(["lane", "--dataset-dir", str(tmp_path / "nothing"),
              "--budget-rows", "2000", "--algorithms", "raft_brute_force",
              "-k", "5", "--reps", "1", "--output", str(out)])
        doc = json.loads(out.read_text())
        assert doc["context"]["lane"] == {"dataset": "blobs-2000x128",
                                          "kind": "synthetic-fallback"}
        assert doc["benchmarks"]


class TestGroundTruth:
    def test_matches_naive(self):
        from ann_utils import naive_knn

        rng = np.random.default_rng(3)
        base = rng.standard_normal((500, 16)).astype(np.float32)
        qs = rng.standard_normal((30, 16)).astype(np.float32)
        d, i = bench.generate_groundtruth(base, qs, k=5)
        _, want = naive_knn(base, qs, 5)
        assert np.mean([len(set(i[r]) & set(want[r])) / 5
                        for r in range(30)]) == 1.0


class TestRunner:
    def test_runner_schema_and_recall(self):
        base, q, _, metric = bench.load_dataset("blobs-2000x16",
                                                n_queries=64)
        _, gt = bench.generate_groundtruth(base, q, k=10, metric=metric)
        results = bench.run_benchmarks(
            base, q, gt, k=10, metric=metric,
            algos=("raft_brute_force", "raft_ivf_flat"), reps=1,
            verbose=False)
        assert len(results) > 1
        bf = [r for r in results if r.algo == "raft_brute_force"][0]
        assert bf.recall == 1.0          # exact search must be perfect
        assert bf.qps > 0
        g = bf.to_gbench()
        for key in ("name", "items_per_second", "Recall", "Latency"):
            assert key in g
        # wider probes → recall must not decrease (allow fp jitter)
        ivf = sorted((r for r in results if r.algo == "raft_ivf_flat"),
                     key=lambda r: r.search_params["n_probes"])
        assert ivf[-1].recall >= ivf[0].recall - 0.02


class TestCli:
    def test_export_and_plot(self, tmp_path):
        from raft_tpu.bench.__main__ import main

        doc = {
            "context": {"dataset": "toy"},
            "benchmarks": [
                {"name": "algoA.p1/search", "Recall": 0.8,
                 "items_per_second": 1000.0, "Latency": 0.01},
                {"name": "algoA.p2/search", "Recall": 0.9,
                 "items_per_second": 500.0, "Latency": 0.02},
                {"name": "algoA.p3/search", "Recall": 0.7,
                 "items_per_second": 400.0, "Latency": 0.02},  # dominated
            ],
        }
        src = tmp_path / "r.json"
        src.write_text(json.dumps(doc))
        main(["export", "--input", str(src)])
        csv_text = (tmp_path / "r.csv").read_text()
        rows = [l.split(",") for l in csv_text.strip().splitlines()[1:]]
        pareto = {r[1]: r[-1] for r in rows}
        assert pareto["algoA.p1/search"] == "1"
        assert pareto["algoA.p2/search"] == "1"
        assert pareto["algoA.p3/search"] == "0"
        main(["plot", "--input", str(src)])
        assert (tmp_path / "r.png").exists()
