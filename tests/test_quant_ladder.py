"""Storage-ladder rung tests (ISSUE 13): int4 + PQ coding, the cagra
edge-store rungs' recall contract, host-streamed cold IVF lists, the
memz ops surface, and the tier-1 durations guard.

The acceptance bar lives in TestRungRecall.test_low_rungs_track_int8:
int4 and PQ edge-store searches hit >= 0.95 of the int8 rung's recall
at fixed k after the exact refine pass, with the guarded fallbacks
serving the resident paths bit-identically (TestGuardedFallbacks).
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.core import faults
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.neighbors import refine as refine_mod
from raft_tpu.ops import guarded, quant

from ann_utils import calc_recall, naive_knn


# ---------------------------------------------------------------- quant --
class TestInt4Coding:
    def test_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(300, 100)).astype(np.float32)
        packed, scales = quant.quantize_int4(jnp.asarray(x))
        assert packed.shape == (300, quant.int4_half_width(100))
        assert packed.dtype == jnp.int8
        deq = np.asarray(quant.dequantize_int4(packed, scales, 100))
        # symmetric rounding: per-component error <= the row's step/2
        bound = np.asarray(scales)[:, None] / 2 + 1e-6
        assert (np.abs(deq - x) <= bound).all()

    def test_nibbles_exact_for_representable(self):
        # integer values in [-7, 7] survive the pack/unpack bit-exactly
        rng = np.random.default_rng(1)
        v = rng.integers(-7, 8, size=(64, 96)).astype(np.float32)
        packed, scales = quant.quantize_int4(jnp.asarray(v * 0.5))
        deq = np.asarray(quant.dequantize_int4(packed, scales, 96))
        np.testing.assert_allclose(deq, v * 0.5, rtol=0, atol=1e-6)

    def test_int4_brute_force_engines_agree(self):
        """Fused-kernel int4 (in-kernel nibble unpack) vs the XLA
        split-dot fallback: same ids, matching values."""
        rng = np.random.default_rng(2)
        x = rng.normal(size=(600, 100)).astype(np.float32)
        q = rng.normal(size=(9, 100)).astype(np.float32)
        ix = brute_force.build(x, "sqeuclidean", dtype="int4")
        assert ix.store_name == "int4" and ix.dim == 100
        dm, im = brute_force.search(ix, q, 7, algo="matmul")
        dp, ip_ = brute_force.search(ix, q, 7, algo="pallas")
        np.testing.assert_array_equal(np.asarray(im), np.asarray(ip_))
        np.testing.assert_allclose(np.asarray(dm), np.asarray(dp),
                                   rtol=1e-5, atol=1e-4)
        rep = brute_force.health(ix)
        assert rep["store_dtype"] == "int4" and "int4" in rep["quant"]

    def test_int4_save_load(self, tmp_path):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(200, 40)).astype(np.float32)
        ix = brute_force.build(x, "sqeuclidean", dtype="int4")
        brute_force.save(ix, tmp_path / "i4.bin")
        ld = brute_force.load(tmp_path / "i4.bin")
        assert ld.dim == 40 and ld.store_name == "int4"
        np.testing.assert_array_equal(np.asarray(ld.dataset),
                                      np.asarray(ix.dataset))


class TestPqCoding:
    def test_exact_when_book_covers_corpus(self):
        """book >= n: every row gets its own codeword chain — decode is
        exact, so the coding pipeline itself adds no error."""
        rng = np.random.default_rng(4)
        x = rng.normal(size=(100, 64)).astype(np.float32)
        cb = quant.train_pq_rows(x, 8)
        codes = quant.encode_pq_rows(x, cb)
        cbn, cn = np.asarray(cb), np.asarray(codes)
        dec = np.concatenate([cbn[s][cn[:, s]] for s in range(8)],
                             axis=1)[:, :64]
        assert np.abs(dec - x).max() < 1e-4
        en = np.asarray(quant.pq_decoded_norms(codes, cb))
        want = (np.concatenate([cbn[s][cn[:, s]] for s in range(8)],
                               axis=1) ** 2).sum(1)
        np.testing.assert_allclose(en, want, rtol=1e-4)

    def test_decode_table_int8_roundtrip(self):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(200, 64)).astype(np.float32)
        cb = quant.train_pq_rows(x, 8)
        tbl = quant.pq_decode_table(cb)
        t8, srow = quant.pq_int8_cb(tbl, 8, cb.shape[1])
        back = np.asarray(t8, np.float32) * np.asarray(srow)
        # per-subspace symmetric quantization: table error bounded by
        # half a step per element
        step = np.asarray(srow)[0]
        assert (np.abs(back - np.asarray(tbl)) <= step / 2 + 1e-7).all()


# ------------------------------------------------- cagra edge-store rungs
@pytest.fixture(scope="module")
def rung_setup():
    rng = np.random.default_rng(7)
    cent = rng.normal(size=(12, 64)).astype(np.float32) * 3
    x = (cent[rng.integers(0, 12, 1600)]
         + rng.normal(size=(1600, 64))).astype(np.float32)
    q = (cent[rng.integers(0, 12, 32)]
         + rng.normal(size=(32, 64))).astype(np.float32)
    k = 8
    _, gt = naive_knn(x, q, k)
    ix = cagra.build(x, cagra.IndexParams(graph_degree=24,
                                          intermediate_graph_degree=36))
    return ix, x, q, k, gt


def _refined_recall(ix, x, q, k, gt, engine, kc=96,
                    sp=None) -> float:
    """The ladder's serving recipe: traverse at the rung's precision,
    exact-refine the WHOLE itopk candidate buffer down to k — the
    wider-refine operating point the low-bit rungs want (docs/perf.md
    "Storage ladder"; ISSUE 13 acceptance shape)."""
    sp = sp or cagra.SearchParams(itopk_size=96, search_width=2,
                                  max_iterations=10)
    _, cand = cagra.search(ix, q, kc, sp, engine=engine)
    _, ids = refine_mod.refine(jnp.asarray(x), jnp.asarray(q), cand, k,
                               "sqeuclidean")
    return calc_recall(np.asarray(ids), gt)


class TestRungRecall:
    def test_low_rungs_track_int8(self, rung_setup):
        """ISSUE 13 acceptance: int4 and PQ edge-store searches >= 0.95
        of the int8 rung's recall at fixed k after exact refine."""
        ix, x, q, k, gt = rung_setup
        recalls = {}
        for rung in ("int8", "int4", "pq"):
            ix.__dict__.pop("_edge_store", None)
            cagra.prepare_traversal(ix, rung)
            assert ix._edge_store[0][0] == rung
            recalls[rung] = _refined_recall(ix, x, q, k, gt, "edge")
        assert recalls["int8"] >= 0.9, recalls
        assert recalls["int4"] >= 0.95 * recalls["int8"], recalls
        assert recalls["pq"] >= 0.95 * recalls["int8"], recalls

    def test_store_bytes_ladder(self, rung_setup):
        """Each rung's edge store shrinks as promised: bf16 > int8 >
        int4 >= pq codes (the pq rung's CODE store is >= 4x under
        int8's rows — the capacity claim the bench lane records)."""
        ix, *_ = rung_setup
        nbytes = {}
        for rung in ("bfloat16", "int8", "int4", "pq"):
            ix.__dict__.pop("_edge_store", None)
            cagra.prepare_traversal(ix, rung)
            ev = ix._edge_store[1]
            nbytes[rung] = ev.size * ev.dtype.itemsize
        assert nbytes["bfloat16"] == 2 * nbytes["int8"]
        # d=64 packs to the 64-byte sublane-pair floor (no win below
        # d128); the pq rung's cut is the load-bearing one
        assert nbytes["int4"] <= nbytes["int8"]
        assert nbytes["pq"] * 4 <= nbytes["int8"], nbytes

    @pytest.mark.slow
    def test_monotone_rung_chain(self, rung_setup):
        """f32(gather) >= bf16 >= int8 >= int4 >= pq refined recall
        (small tolerance: rung noise on a 48-query sample)."""
        ix, x, q, k, gt = rung_setup
        chain = [("f32", "gather", None)] + [
            (r, "edge", r) for r in ("bfloat16", "int8", "int4", "pq")]
        got = []
        for name, eng, rung in chain:
            if rung is not None:
                ix.__dict__.pop("_edge_store", None)
                cagra.prepare_traversal(ix, rung)
            got.append((name, _refined_recall(ix, x, q, k, gt, eng)))
        for (na, ra), (nb, rb) in zip(got, got[1:]):
            assert rb <= ra + 0.02, (f"rung {nb} above {na}", got)

    @pytest.mark.slow
    def test_int4_fused_megakernel_parity(self):
        """The one-dispatch megakernel scores int4 stores bit-identically
        to the per-hop edge engine (shared edge_tile_widen)."""
        rng = np.random.default_rng(8)
        x = rng.normal(size=(700, 64)).astype(np.float32)
        q = rng.normal(size=(16, 64)).astype(np.float32)
        ix = cagra.build(x, cagra.IndexParams(
            graph_degree=16, intermediate_graph_degree=24))
        cagra.prepare_traversal(ix, "int4")
        sp = cagra.SearchParams(itopk_size=16, search_width=1,
                                max_iterations=4)
        de, ie = cagra.search(ix, q, 8, sp, engine="edge")
        df, if_ = cagra.search(ix, q, 8, sp, engine="fused")
        np.testing.assert_array_equal(np.asarray(ie), np.asarray(if_))
        np.testing.assert_array_equal(np.asarray(de), np.asarray(df))


@pytest.mark.faults
class TestGuardedFallbacks:
    def test_pq_expand_demotes_to_gather(self, rung_setup):
        """A PQ-expand kernel failure serves the resident gather path
        bit-identically (the ISSUE 13 fallback contract) under its OWN
        breaker site."""
        if any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active()):
            pytest.skip("ambient kernel faults change demotion counts")
        ix, x, q, k, gt = rung_setup
        ix.__dict__.pop("_edge_store", None)
        cagra.prepare_traversal(ix, "pq")
        sp = cagra.SearchParams(itopk_size=32, search_width=1,
                                max_iterations=5)
        guarded.reset()
        try:
            with faults.inject("kernel_fault", "cagra.pq_expand"):
                dd, di = cagra.search(ix, q, k, sp, engine="edge")
            assert "cagra.pq_expand" in guarded.demoted_sites()
            assert "cagra.graph_expand" not in guarded.demoted_sites()
        finally:
            guarded.reset()
        dg, ig = cagra.search(ix, q, k, sp, engine="gather")
        np.testing.assert_array_equal(np.asarray(di), np.asarray(ig))
        np.testing.assert_array_equal(np.asarray(dd), np.asarray(dg))


# ------------------------------------------------ host-streamed IVF lists
class TestHostStream:
    def test_flat_bit_identity(self):
        """Host-streamed vs HBM-resident ivf_flat: bit-identical results
        on a distinct-valued corpus (same kernel, per-list row order
        preserved), across multiple double-buffered chunks."""
        rng = np.random.default_rng(10)
        x = rng.normal(size=(1500, 48)).astype(np.float32)
        q = rng.normal(size=(24, 48)).astype(np.float32)
        ix = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=16))
        sp = ivf_flat.SearchParams(n_probes=5)
        d0, i0 = ivf_flat.search(ix, q, 9, sp, algo="pallas")
        ivf_flat.prepare_host_stream(ix, budget_gb=90e3 / (1 << 30),
                                     sample_queries=q[:8], chunk_mb=0.06)
        tier = ix._host_tier
        assert tier.n_cold_lists > 0 and len(tier.chunks) >= 2
        d1, i1 = ivf_flat.search(ix, q, 9, sp, algo="pallas")
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        assert tier.streamed_chunks > 0

    @pytest.mark.slow
    def test_pq_bit_identity(self):
        # slow lane (tier-1 wall policy): the flat bit-identity test
        # pins the shared tier machinery (planner/chunking/merge) in
        # tier-1; this adds the pq-family kernel call on top
        rng = np.random.default_rng(11)
        x = rng.normal(size=(1500, 32)).astype(np.float32)
        q = rng.normal(size=(16, 32)).astype(np.float32)
        ix = ivf_pq.build(x, ivf_pq.IndexParams(n_lists=16, pq_dim=8))
        sp = ivf_pq.SearchParams(n_probes=5)
        d0, i0 = ivf_pq.search(ix, q, 9, sp, algo="pallas")
        ivf_pq.prepare_host_stream(ix, budget_gb=20e3 / (1 << 30),
                                   chunk_mb=0.02)
        assert ix._host_tier.n_cold_lists > 0
        d1, i1 = ivf_pq.search(ix, q, 9, sp, algo="pallas")
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_budget_fits_is_noop(self):
        rng = np.random.default_rng(12)
        x = rng.normal(size=(200, 32)).astype(np.float32)
        ix = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=4))
        ivf_flat.prepare_host_stream(ix, budget_gb=1.0)
        assert getattr(ix, "_host_tier", None) is None

    def test_streamed_index_refuses_save_and_jit(self, tmp_path):
        """A host-streamed index fails LOUDLY where it cannot serve the
        full corpus: save() would drop cold rows; a traced search would
        skip them."""
        from raft_tpu.core.errors import RaftError

        rng = np.random.default_rng(16)
        x = rng.normal(size=(400, 32)).astype(np.float32)
        ix = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=8))
        ivf_flat.prepare_host_stream(ix, budget_gb=20e3 / (1 << 30))
        assert ix._host_tier is not None
        with pytest.raises(RaftError, match="host-streamed"):
            ivf_flat.save(ix, tmp_path / "hs.bin")
        with pytest.raises(RaftError, match="eagerly"):
            jax.jit(lambda q: ivf_flat.search(ix, q, 5))(
                jnp.asarray(x[:4]))

    @pytest.mark.slow
    def test_flat_int8_filter_bit_identity(self):
        from raft_tpu.core.bitset import Bitset

        rng = np.random.default_rng(13)
        x = rng.normal(size=(2500, 48)).astype(np.float32)
        q = rng.normal(size=(16, 48)).astype(np.float32)
        mask = np.ones(2500, bool)
        mask[::3] = False
        bs = Bitset.from_mask(jnp.asarray(mask))
        ix = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=24,
                                                    dtype="int8"))
        sp = ivf_flat.SearchParams(n_probes=6)
        d0, i0 = ivf_flat.search(ix, q, 9, sp, algo="pallas", filter=bs)
        ivf_flat.prepare_host_stream(ix, budget_gb=100e3 / (1 << 30),
                                     chunk_mb=0.1)
        d1, i1 = ivf_flat.search(ix, q, 9, sp, algo="pallas", filter=bs)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    @pytest.mark.slow
    @pytest.mark.faults
    def test_guarded_fallback_serves(self):
        # slow lane: the generic drift-guard drill (tests/test_quality)
        # already exercises the ivf.host_stream breaker arc in tier-1;
        # this adds the end-to-end served-results check
        """An ivf.host_stream kernel failure falls back to the XLA
        rescore of the same streamed chunk: same neighbor sets."""
        if any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active()):
            pytest.skip("ambient kernel faults change demotion counts")
        rng = np.random.default_rng(14)
        x = rng.normal(size=(1500, 32)).astype(np.float32)
        q = rng.normal(size=(12, 32)).astype(np.float32)
        ix = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=16))
        sp = ivf_flat.SearchParams(n_probes=5)
        d0, i0 = ivf_flat.search(ix, q, 8, sp, algo="pallas")
        ivf_flat.prepare_host_stream(ix, budget_gb=80e3 / (1 << 30),
                                     chunk_mb=0.1)
        guarded.reset()
        try:
            with faults.inject("kernel_fault", "ivf.host_stream"):
                d1, i1 = ivf_flat.search(ix, q, 8, sp, algo="pallas")
            assert "ivf.host_stream" in guarded.demoted_sites()
        finally:
            guarded.reset()
        # the fallback's arithmetic differs from the kernel's; the
        # neighbor SETS must not (distinct-valued corpus)
        for a, b in zip(np.asarray(i0), np.asarray(i1)):
            assert set(a.tolist()) == set(b.tolist())


# ------------------------------------------------------------ ops surface
class TestMemz:
    def test_memz_components_and_strict_json(self):
        from raft_tpu.serve import debugz, quality

        rng = np.random.default_rng(15)
        x = rng.normal(size=(400, 64)).astype(np.float32)
        ci = cagra.build(x, cagra.IndexParams(
            graph_degree=12, intermediate_graph_degree=16))
        cagra.prepare_traversal(ci, "pq")
        fi = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=8))
        ivf_flat.prepare_host_stream(fi, budget_gb=30e3 / (1 << 30))
        quality.watch_index("memz_cagra", ci)
        quality.watch_index("memz_flat", fi)
        try:
            snap = debugz.snapshot()
            mz = snap["memz"]
            assert mz["memz_cagra"]["components"]["pq_codes"] > 0
            assert mz["memz_cagra"]["bytes_per_vector"] > 0
            assert mz["memz_flat"]["host_stream"]["cold_lists"] > 0
            # host-streamed bytes_per_vector divides by ALL answered
            # rows, cold included
            assert mz["memz_flat"]["n_total"] == 400
            json.loads(json.dumps(snap, allow_nan=False))
            text = debugz.render_text()
            assert "-- memz (device bytes) --" in text
            assert "host tier" in text
        finally:
            quality.unwatch_index("memz_cagra")
            quality.unwatch_index("memz_flat")


# -------------------------------------------------------- durations guard
class TestDurationsGuard:
    def _write_log(self, path, entries):
        lines = ["== slowest durations ==\n"]
        for secs, phase, tid in entries:
            lines.append(f"{secs:.2f}s {phase:<8} {tid}\n")
        path.write_text("".join(lines))

    def test_flags_untouched_regressions_only(self, tmp_path):
        import sys
        sys.path.insert(0, "scratch")
        try:
            import check_tier1_durations as guard
        finally:
            sys.path.pop(0)
        log = tmp_path / "t1.log"
        base = tmp_path / "base.json"
        self._write_log(log, [(10.0, "call", "tests/test_a.py::t1"),
                              (2.0, "call", "tests/test_b.py::t2"),
                              (5.0, "setup", "tests/test_a.py::t1")])
        assert guard.main(["--log", str(log), "--baseline", str(base),
                           "--update"]) == 0
        saved = json.loads(base.read_text())
        assert saved == {"tests/test_a.py::t1": 10.0,
                         "tests/test_b.py::t2": 2.0}   # call phases only
        # same durations: OK
        assert guard.main(["--log", str(log), "--baseline", str(base),
                           "--no-git"]) == 0
        # +30% and +3s on an untouched test: flagged
        self._write_log(log, [(13.0, "call", "tests/test_a.py::t1"),
                              (2.0, "call", "tests/test_b.py::t2")])
        assert guard.main(["--log", str(log), "--baseline", str(base),
                           "--no-git"]) == 1
        # +30% but under the absolute floor: noise, not a flag
        self._write_log(log, [(10.0, "call", "tests/test_a.py::t1"),
                              (2.6, "call", "tests/test_b.py::t2")])
        assert guard.main(["--log", str(log), "--baseline", str(base),
                           "--no-git"]) == 0
