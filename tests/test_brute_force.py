"""Brute-force kNN tests (analog of NEIGHBORS_ANN_BRUTE_FORCE_TEST)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force


def _data(rng, n=5000, d=32, m=64):
    return (
        rng.standard_normal((n, d)).astype(np.float32),
        rng.standard_normal((m, d)).astype(np.float32),
    )


class TestBruteForce:
    @pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "inner_product", "cosine"])
    def test_exact_vs_oracle(self, rng, metric):
        data, q = _data(rng)
        dist, idx = brute_force.knn(data, q, k=10, metric=metric, tile_size=1024)
        want_dist, want_idx = naive_knn(data, q, 10, metric)
        assert calc_recall(np.asarray(idx), want_idx) > 0.999
        np.testing.assert_allclose(np.asarray(dist), want_dist, rtol=1e-3, atol=1e-3)

    def test_single_tile(self, rng):
        data, q = _data(rng, n=500)
        dist, idx = brute_force.knn(data, q, k=5, tile_size=8192)
        _, want_idx = naive_knn(data, q, 5)
        np.testing.assert_array_equal(np.asarray(idx), want_idx)

    def test_n_not_multiple_of_tile(self, rng):
        data, q = _data(rng, n=1337)
        dist, idx = brute_force.knn(data, q, k=7, tile_size=512)
        _, want_idx = naive_knn(data, q, 7)
        assert calc_recall(np.asarray(idx), want_idx) > 0.999

    def test_k_larger_than_tile(self, rng):
        data, q = _data(rng, n=1000, m=8)
        dist, idx = brute_force.knn(data, q, k=200, tile_size=128)
        _, want_idx = naive_knn(data, q, 200)
        assert calc_recall(np.asarray(idx), want_idx) > 0.999

    def test_elementwise_metric(self, rng):
        from scipy.spatial import distance as sp
        data, q = _data(rng, n=800, m=16, d=8)
        dist, idx = brute_force.knn(data, q, k=5, metric="l1", tile_size=256)
        d = sp.cdist(q, data, "cityblock")
        want = np.argsort(d, 1)[:, :5]
        assert calc_recall(np.asarray(idx), want) > 0.99

    def test_filter(self, rng):
        data, q = _data(rng, n=1000, m=16)
        # exclude the true top-1 of each query, expect the former #2 as new #1
        _, base_idx = naive_knn(data, q, 2)
        mask = np.ones(1000, bool)
        mask[base_idx[:, 0]] = False
        filt = Bitset.from_mask(jnp.asarray(mask))
        _, idx = brute_force.search(brute_force.build(data), q, k=1,
                                    tile_size=256, filter=filt)
        got = np.asarray(idx)[:, 0]
        # each query's result must be its oracle #2 unless #2 was also excluded
        for i in range(16):
            if mask[base_idx[i, 1]]:
                assert got[i] == base_idx[i, 1]

    def test_jit_search(self, rng):
        data, q = _data(rng, n=512, m=8)
        index = brute_force.build(data)
        fn = jax.jit(lambda qq: brute_force.search(index, qq, 3, tile_size=256))
        dist, idx = fn(jnp.asarray(q))
        _, want_idx = naive_knn(data, q, 3)
        np.testing.assert_array_equal(np.asarray(idx), want_idx)

    def test_save_load(self, tmp_path, rng):
        data, q = _data(rng, n=300, m=4)
        index = brute_force.build(data, metric="cosine")
        brute_force.save(index, tmp_path / "bf.raft")
        loaded = brute_force.load(tmp_path / "bf.raft")
        assert loaded.metric == index.metric
        d1, i1 = brute_force.search(index, q, 5)
        d2, i2 = brute_force.search(loaded, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_merge_parts(self, rng):
        # two shards of one dataset must merge to the global answer
        data, q = _data(rng, n=1000, m=16)
        d0, i0 = brute_force.knn(data[:500], q, k=8, tile_size=256)
        d1, i1 = brute_force.knn(data[500:], q, k=8, tile_size=256)
        i1 = i1 + 500
        dist, idx = brute_force.knn_merge_parts(
            jnp.stack([d0, d1]), jnp.stack([i0, i1]))
        _, want_idx = naive_knn(data, q, 8)
        assert calc_recall(np.asarray(idx), want_idx) > 0.999

    @pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean",
                                        "inner_product", "cosine"])
    def test_matmul_engine_vs_oracle(self, rng, metric):
        data, q = _data(rng, n=3000, m=32)
        index = brute_force.build(data, metric=metric)
        dist, idx = brute_force.search(index, q, k=10, algo="matmul")
        want_dist, want_idx = naive_knn(data, q, 10, metric)
        assert calc_recall(np.asarray(idx), want_idx) > 0.999
        np.testing.assert_allclose(np.asarray(dist), want_dist,
                                   rtol=1e-3, atol=1e-3)

    def test_matmul_engine_blockmin_wide(self, rng):
        """n >= 8192 rides the block-min two-level select; must stay
        exact, including on value ties (quantized corpus forces them)."""
        data, q = _data(rng, n=9000, m=64)
        data = np.round(data * 4) / 4       # heavy ties
        index = brute_force.build(data)
        dist, idx = brute_force.search(index, q, k=10, algo="matmul")
        want_dist, want_idx = naive_knn(data, q, 10)
        np.testing.assert_allclose(np.asarray(dist), want_dist,
                                   rtol=1e-4, atol=1e-4)
        assert calc_recall(np.asarray(idx), want_idx) > 0.999

    def test_blockmin_topk_matches_topk_exactly(self, rng):
        from raft_tpu.neighbors.brute_force import _blockmin_topk

        s = rng.standard_normal((256, 8200)).astype(np.float32)
        s = np.round(s * 8) / 8             # ties
        v1, i1 = _blockmin_topk(jnp.asarray(s), 10)
        nv, i2 = jax.lax.top_k(-jnp.asarray(s), 10)
        np.testing.assert_array_equal(np.asarray(v1), -np.asarray(nv))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_matmul_engine_chunked(self, rng, monkeypatch):
        # budget forcing multiple query chunks through lax.map
        monkeypatch.setenv("RAFT_TPU_MATMUL_WORKSPACE_MB", "1")
        data, q = _data(rng, n=2000, m=400)
        dist, idx = brute_force.search(brute_force.build(data), q, k=5,
                                       algo="matmul")
        _, want_idx = naive_knn(data, q, 5)
        assert calc_recall(np.asarray(idx), want_idx) > 0.999

    def test_matmul_engine_filter_and_valid_rows(self, rng):
        data, q = _data(rng, n=1000, m=16)
        _, base_idx = naive_knn(data, q, 2)
        mask = np.ones(1000, bool)
        mask[base_idx[:, 0]] = False
        filt = Bitset.from_mask(jnp.asarray(mask))
        _, idx = brute_force.search(brute_force.build(data), q, k=1,
                                    algo="matmul", filter=filt)
        got = np.asarray(idx)[:, 0]
        for i in range(16):
            if mask[base_idx[i, 1]]:
                assert got[i] == base_idx[i, 1]
        # valid_rows: restrict to the first 100 rows
        d2, i2 = brute_force.search(brute_force.build(data), q, k=3,
                                    algo="matmul",
                                    valid_rows=jnp.asarray(100))
        _, want = naive_knn(data[:100], q, 3)
        assert calc_recall(np.asarray(i2), want) > 0.999

    def test_tune_search_records_winner(self, rng, tmp_path, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "tune.json"))
        from raft_tpu.ops import autotune
        monkeypatch.setattr(autotune, "_MEM_CACHE", {})
        monkeypatch.setattr(autotune, "_DISK_LOADED", False)
        data, q = _data(rng, n=600, m=16)
        index = brute_force.build(data)
        winner, timings = brute_force.tune_search(index, q, k=5, reps=2)
        assert winner in ("matmul", "scan")
        assert set(timings) >= {"matmul", "scan"}
        # the race key carries the storage dtype: bf16/int8 corpora
        # stream at different HBM widths and must tune separately
        key = autotune.shape_bucket("bf_search", n=600, m=16, d=32, k=5,
                                    store="float32")
        assert autotune.lookup(key) == winner
        # auto now dispatches the cached winner without error
        d, i = brute_force.search(index, q, k=5, algo="auto")
        _, want_idx = naive_knn(data, q, 5)
        assert calc_recall(np.asarray(i), want_idx) > 0.999

    @pytest.mark.parametrize("dtype,min_recall", [("bfloat16", 0.95),
                                                  ("int8", 0.9)])
    @pytest.mark.parametrize("algo", ["matmul", "scan"])
    def test_low_precision_storage(self, rng, dtype, min_recall, algo):
        data, q = _data(rng, n=4000, m=48)
        index = brute_force.build(data, dtype=dtype)
        assert str(index.dataset.dtype) == dtype
        dist, idx = brute_force.search(index, q, k=10, algo=algo)
        _, want = naive_knn(data, q, 10)
        assert calc_recall(np.asarray(idx), want) > min_recall
        # distances stay near the exact values (dequantized scoring)
        want_d, _ = naive_knn(data, q, 10)
        assert np.median(np.abs(np.asarray(dist) - want_d)) < 0.5

    def test_bf16_pallas_engine(self, rng):
        data, q = _data(rng, n=2000, m=32)
        index = brute_force.build(data, dtype="bfloat16")
        dist, idx = brute_force.search(index, q, k=10, algo="pallas")
        _, want = naive_knn(data, q, 10)
        assert calc_recall(np.asarray(idx), want) > 0.95

    def test_int8_pallas_in_kernel_matches_matmul(self, rng):
        # int8 rows stream through the fused kernel in their stored
        # width (per-row scales folded into the dot) and must reproduce
        # the GEMM engine's dequantized math exactly
        data, q = _data(rng, n=1000, m=8)
        index = brute_force.build(data, dtype="int8")
        d1, i1 = brute_force.search(index, q, k=5, algo="pallas")
        d2, i2 = brute_force.search(index, q, k=5, algo="matmul")
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_uint8_byte_corpus_exact(self, rng):
        # SIFT/DEEP-style byte vectors: uint8 storage is lossless, so
        # search must match the f32 index exactly (incl. save/load)
        data = rng.integers(0, 256, (3000, 32)).astype(np.float32)
        q = rng.integers(0, 256, (32, 32)).astype(np.float32)
        u8 = brute_force.build(data, dtype="uint8")
        assert str(u8.dataset.dtype) == "uint8" and u8.scales is None
        f32 = brute_force.build(data)
        for algo in ("matmul", "scan"):
            du, iu = brute_force.search(u8, q, k=10, algo=algo)
            df, jf = brute_force.search(f32, q, k=10, algo=algo)
            np.testing.assert_array_equal(np.asarray(iu), np.asarray(jf))
            np.testing.assert_allclose(np.asarray(du), np.asarray(df),
                                       rtol=1e-5)
        # the fused engine streams uint8 rows in-kernel and must agree
        dp, ip = brute_force.search(u8, q, k=10, algo="pallas")
        np.testing.assert_array_equal(np.asarray(ip),
                                      np.asarray(brute_force.search(
                                          u8, q, k=10, algo="matmul")[1]))

    def test_uint8_rejects_float_data(self, rng):
        from raft_tpu.core import RaftError
        data, _ = _data(rng, n=200, m=8)  # zero-centered floats
        with pytest.raises(RaftError, match="byte-valued"):
            brute_force.build(data, dtype="uint8")

    def test_low_precision_save_load(self, tmp_path, rng):
        for dtype in ("bfloat16", "int8", "uint8"):
            data, q = _data(rng, n=500, m=8)
            if dtype == "uint8":  # uint8 demands byte-valued corpora
                data = np.round(np.clip(data * 40 + 128, 0, 255)
                                ).astype(np.float32)
            index = brute_force.build(data, dtype=dtype)
            brute_force.save(index, tmp_path / f"bf_{dtype}.raft")
            loaded = brute_force.load(tmp_path / f"bf_{dtype}.raft")
            assert str(loaded.dataset.dtype) == dtype
            d1, i1 = brute_force.search(index, q, 5, algo="scan")
            d2, i2 = brute_force.search(loaded, q, 5, algo="scan")
            np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_bad_query_dim(self, rng):
        from raft_tpu.core import RaftError
        data, _ = _data(rng, n=100)
        with pytest.raises(RaftError):
            brute_force.search(brute_force.build(data), np.ones((4, 999), np.float32), 3)
