"""Selectivity-adaptive filtered search tests (ISSUE 18): survivor
counting, the widen-ladder decision, the survivor-brute crossover (pinned
bit-exact vs a filtered reference), the CAGRA filtered-seed regression,
k > survivors sentinel parity across every family, and the filter's
interaction with host streaming, the qcache key, sharding, and the
serving searcher/batcher path.

Ground truth is an exact NumPy oracle over the compacted survivor set
(``filtered_ref``) — the same construction the crossover claims to be
bit-equal to.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ann_utils import calc_recall, naive_knn
from raft_tpu.core import events, faults
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.ops import filter_policy, guarded

N, D, K = 3000, 32, 10


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    return rng.standard_normal((N, D)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(8)
    return rng.standard_normal((20, D)).astype(np.float32)


@pytest.fixture(scope="module")
def flat_index(dataset):
    return ivf_flat.build(dataset, ivf_flat.IndexParams(n_lists=16, seed=0))


@pytest.fixture(scope="module")
def pq_index(dataset):
    return ivf_pq.build(dataset, ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                                    seed=0))


@pytest.fixture(scope="module")
def cagra_index(dataset):
    return cagra.build(dataset, cagra.IndexParams(
        intermediate_graph_degree=32, graph_degree=16, seed=0))


def make_mask(n: int, survivors: int, seed: int = 3) -> np.ndarray:
    rng = np.random.default_rng(seed)
    mask = np.zeros(n, bool)
    if survivors:
        mask[rng.choice(n, size=survivors, replace=False)] = True
    return mask


def filtered_ref(dataset, queries, k, mask):
    """Exact filtered oracle: brute force over the compacted survivors,
    ids mapped back, padded to k with the (+inf, -1) sentinel."""
    ids = np.nonzero(mask)[0]
    m = queries.shape[0]
    d = np.full((m, k), np.inf, np.float32)
    i = np.full((m, k), -1, np.int64)
    if ids.size:
        kk = min(k, ids.size)
        dd, ii = naive_knn(dataset[ids], queries, kk)
        d[:, :kk] = dd
        i[:, :kk] = ids[ii]
    return d, i


def assert_in_survivors(indices, mask):
    i = np.asarray(indices)
    valid = i >= 0
    assert valid.any(), "no valid neighbors returned at all"
    assert mask[i[valid]].all(), "returned a filtered-out id"


class TestSurvivorCounting:
    def test_count_by_segments_matches_numpy(self):
        rng = np.random.default_rng(0)
        n_bits, rows, segs = 500, 2048, 12
        mask = rng.random(n_bits) < 0.3
        bs = Bitset.from_mask(jnp.asarray(mask))
        # ids include -1 (slack) and out-of-range rows: both count as 0
        ids = rng.integers(-1, n_bits + 50, size=rows)
        seg = rng.integers(0, segs, size=rows)
        got = np.asarray(bs.count_by_segments(
            jnp.asarray(ids, jnp.int32), jnp.asarray(seg, jnp.int32), segs))
        want = np.zeros(segs, np.int64)
        for i, s in zip(ids, seg):
            if 0 <= i < n_bits and mask[i]:
                want[s] += 1
        np.testing.assert_array_equal(got, want)

    def test_list_survivors_matches_per_list_reference(self, flat_index):
        mask = make_mask(N, 300)
        bs = Bitset.from_mask(jnp.asarray(mask))
        got = np.asarray(filter_policy.list_survivors(flat_index, bs))
        src = np.asarray(flat_index.source_ids)
        offs = np.asarray(flat_index.list_offsets)
        want = np.zeros(flat_index.n_lists, np.int64)
        for j in range(flat_index.n_lists):
            span = src[offs[j]:offs[j + 1]]
            # capacity-slack rows carry source id -1 and never count
            live = span[(span >= 0) & (span < N)]
            want[j] = mask[live].sum()
        np.testing.assert_array_equal(got, want)
        assert got.sum() == mask.sum()

    def test_fingerprint_content_equality(self):
        mask = make_mask(100_000, 50_000, seed=1)
        a = Bitset.from_mask(jnp.asarray(mask))
        b = Bitset.from_mask(jnp.asarray(mask.copy()))
        assert a.fingerprint() == b.fingerprint()
        mask2 = mask.copy()
        mask2[50_000] = not mask2[50_000]      # flip one mid-array bit
        c = Bitset.from_mask(jnp.asarray(mask2))
        assert a.fingerprint() != c.fingerprint()


class TestDecision:
    def test_all_pass_filter_stays_level_one(self, flat_index, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FILTER_BRUTE_MAX", "0")
        bs = Bitset.from_mask(jnp.ones(N, bool))
        fd = filter_policy.decide_ivf(flat_index, bs, 4, K, "ivf_flat")
        assert fd.level == 1 and fd.n_probes == 4
        assert not fd.use_brute
        assert fd.selectivity == 1.0 and fd.lists_pruned == 0

    def test_mild_filter_widens_at_most_once(self, flat_index, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FILTER_BRUTE_MAX", "0")
        bs = Bitset.from_mask(jnp.asarray(make_mask(N, int(N * 0.9))))
        fd = filter_policy.decide_ivf(flat_index, bs, 4, K, "ivf_flat")
        # one doubling restores the ~10% survivor-mass shortfall; the
        # mild end must never pay the widest rung
        assert fd.level <= 2
        assert not fd.use_brute
        assert abs(fd.selectivity - 0.9) < 0.01

    def test_extreme_filter_widens_and_prunes(self, flat_index, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FILTER_BRUTE_MAX", "0")
        bs = Bitset.from_mask(jnp.asarray(make_mask(N, 30)))
        fd = filter_policy.decide_ivf(flat_index, bs, 2, K, "ivf_flat")
        assert fd.level > 1, "30/3000 survivors must widen the probe set"
        assert fd.n_probes == min(2 * fd.level, flat_index.n_lists)
        assert fd.lists_pruned > 0, "some of 16 lists hold none of 30 ids"
        assert fd.survivors == 30

    def test_widen_cap_env(self, flat_index, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FILTER_BRUTE_MAX", "0")
        monkeypatch.setenv("RAFT_TPU_FILTER_WIDEN_MAX", "2")
        bs = Bitset.from_mask(jnp.asarray(make_mask(N, 30)))
        fd = filter_policy.decide_ivf(flat_index, bs, 2, K, "ivf_flat")
        assert fd.level <= 2

    def test_brute_threshold_env(self, flat_index):
        # default threshold (8192) >> N: tiny survivor sets route brute
        bs = Bitset.from_mask(jnp.asarray(make_mask(N, 30)))
        fd = filter_policy.decide_ivf(flat_index, bs, 4, K, "ivf_flat")
        assert fd.use_brute

    def test_decide_graph_ladder(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FILTER_BRUTE_MAX", "0")
        for frac, lv in ((0.6, 1), (0.2, 2), (0.05, 4), (0.001, 8)):
            bs = Bitset.from_mask(jnp.asarray(make_mask(N, int(N * frac))))
            fd = filter_policy.decide_graph(bs, N, D, K)
            assert fd.level == lv, (frac, fd.level)

    def test_selectivity_bucket(self):
        assert filter_policy.selectivity_bucket(0.0) == "none"
        assert filter_policy.selectivity_bucket(1.0) == "e0"
        assert filter_policy.selectivity_bucket(0.05) == "e1"
        assert filter_policy.selectivity_bucket(1e-3) == "e3"
        assert filter_policy.selectivity_bucket(1e-9) == "e6"

    def test_traced_filtered_search_prunes_free(self, flat_index, dataset,
                                                queries):
        """A jitted filtered search keeps the device-side prune (no host
        pulls) and still honors the filter."""
        mask = make_mask(N, 1500)
        bs = Bitset.from_mask(jnp.asarray(mask))
        sp = ivf_flat.SearchParams(n_probes=16)

        @jax.jit
        def go(q):
            return ivf_flat.search(flat_index, q, K, sp, filter=bs)

        d, i = go(jnp.asarray(queries))
        assert_in_survivors(i, mask)
        want_d, want_i = filtered_ref(dataset, queries, K, mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)


class TestCrossoverExact:
    """The survivor-brute crossover is exact by construction: ids must be
    bit-equal to the filtered oracle (the ISSUE 18 acceptance pin)."""

    def test_ivf_flat_bit_equal(self, flat_index, dataset, queries):
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = ivf_flat.search(flat_index, queries, K,
                               ivf_flat.SearchParams(n_probes=4), filter=bs)
        want_d, want_i = filtered_ref(dataset, queries, K, mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)
        np.testing.assert_allclose(np.asarray(d), want_d, rtol=1e-4,
                                   atol=1e-4)

    def test_cagra_bit_equal(self, cagra_index, dataset, queries):
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = cagra.search(cagra_index, queries, K,
                            cagra.SearchParams(itopk_size=32), filter=bs)
        _, want_i = filtered_ref(dataset, queries, K, mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)

    def test_brute_force_bit_equal(self, dataset, queries):
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        ix = brute_force.build(dataset)
        d, i = brute_force.search(ix, queries, K, filter=bs)
        _, want_i = filtered_ref(dataset, queries, K, mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)

    def test_ivf_pq_in_survivors_high_recall(self, pq_index, dataset,
                                             queries):
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = ivf_pq.search(pq_index, queries, K,
                             ivf_pq.SearchParams(n_probes=4), filter=bs)
        assert_in_survivors(i, mask)
        # pq decode reorders near-ties; the neighbor SET must still track
        # the exact filtered oracle closely over 50 survivors
        _, want_i = filtered_ref(dataset, queries, K, mask)
        assert calc_recall(np.asarray(i), want_i) >= 0.8

    def test_crossover_records_event(self, flat_index, queries):
        bs = Bitset.from_mask(jnp.asarray(make_mask(N, 20)))
        before = len(events.recent(kind="filter_crossover"))
        ivf_flat.search(flat_index, queries, K,
                        ivf_flat.SearchParams(n_probes=4), filter=bs)
        after = events.recent(kind="filter_crossover")
        assert len(after) > before
        assert after[-1]["survivors"] == 20

    def test_widened_path_when_disabled(self, flat_index, queries,
                                        monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FILTER_BRUTE_MAX", "0")
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = ivf_flat.search(flat_index, queries, K,
                               ivf_flat.SearchParams(n_probes=4), filter=bs)
        assert_in_survivors(i, mask)

    @pytest.mark.faults
    def test_breaker_falls_back_to_widened_scan(self, flat_index, dataset,
                                                queries):
        """A survivor-brute failure demotes the site and serves through
        the family's widened scan — results stay inside the survivor
        set, nothing raises."""
        if any(f.kind in ("kernel_compile", "kernel_fault")
               for f in faults.active()):
            pytest.skip("ambient kernel faults change demotion counts")
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        sp = ivf_flat.SearchParams(n_probes=4)
        guarded.reset()
        try:
            with faults.inject("kernel_fault", "filter.survivor_brute"):
                d, i = ivf_flat.search(flat_index, queries, K, sp, filter=bs)
            assert "filter.survivor_brute" in guarded.demoted_sites()
        finally:
            guarded.reset()
        assert_in_survivors(i, mask)


class TestCagraSeedRegression:
    def test_survivor_seeding_with_tiny_survivor_set(self, cagra_index,
                                                     dataset, queries,
                                                     monkeypatch):
        """Regression (ISSUE 18 S1): with 10 survivors in 3000 rows and
        the crossover disabled, uniform-random seeds are all filtered out
        with high probability and the old traversal returned nothing.
        Survivor-aware seeding must still find real neighbors."""
        monkeypatch.setenv("RAFT_TPU_FILTER_BRUTE_MAX", "0")
        mask = make_mask(N, 10)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = cagra.search(cagra_index, queries, 5,
                            cagra.SearchParams(itopk_size=32), filter=bs)
        i = np.asarray(i)
        valid = i >= 0
        assert mask[i[valid]].all()
        # every query must surface at least one survivor, most several
        assert (valid.any(axis=1)).all()
        assert valid.mean() >= 0.5


class TestKGreaterThanSurvivors:
    """S2: every family returns the same (+inf, -1) sentinel padding when
    fewer than k rows survive, and the real prefix is exactly the
    survivor set."""

    @pytest.mark.parametrize("survivors", [0, 1, K - 1])
    @pytest.mark.parametrize("family", ["brute", "ivf_flat", "ivf_pq",
                                        "cagra"])
    def test_sentinel_parity(self, family, survivors, dataset, queries,
                             flat_index, pq_index, cagra_index):
        mask = make_mask(N, survivors, seed=survivors + 5)
        bs = Bitset.from_mask(jnp.asarray(mask))
        q = queries[:4]
        if family == "brute":
            d, i = brute_force.search(brute_force.build(dataset), q, K,
                                      filter=bs)
        elif family == "ivf_flat":
            d, i = ivf_flat.search(flat_index, q, K,
                                   ivf_flat.SearchParams(n_probes=4),
                                   filter=bs)
        elif family == "ivf_pq":
            d, i = ivf_pq.search(pq_index, q, K,
                                 ivf_pq.SearchParams(n_probes=4), filter=bs)
        else:
            d, i = cagra.search(cagra_index, q, K,
                                cagra.SearchParams(itopk_size=32), filter=bs)
        d, i = np.asarray(d), np.asarray(i)
        assert (i[:, survivors:] == -1).all()
        assert np.isinf(d[:, survivors:]).all()
        surv_ids = set(np.nonzero(mask)[0].tolist())
        for row in i:
            assert set(row[:survivors].tolist()) == surv_ids

    @pytest.mark.parametrize("survivors", [0, 1, K - 1])
    def test_mutable_tombstones(self, survivors, tmp_path):
        from raft_tpu.neighbors import mutable

        rng = np.random.default_rng(11)
        x = rng.standard_normal((60, 8)).astype(np.float32)
        m = mutable.create(tmp_path / "i", x)
        keep = set(range(survivors))
        m.delete([i for i in range(60) if i not in keep])
        before = len(events.recent(kind="filter_crossover"))
        d, i = m.search(x[:3], K)
        # tombstone masks are shape-stable internal filters: the policy
        # runs suspended, so the crossover must never fire here (it
        # would recompile after every delete — the soak's steady-state
        # invariant catches exactly that storm)
        assert len(events.recent(kind="filter_crossover")) == before
        d, i = np.asarray(d), np.asarray(i)
        assert (i[:, survivors:] == -1).all()
        assert np.isinf(d[:, survivors:]).all()
        for row in i:
            assert set(row[:survivors].tolist()) == keep


class TestHostStreamFilter:
    def test_host_stream_filtered_exact(self, dataset, queries):
        """A host-streamed index keeps the classic masked path (the
        adaptive policy is device-resident-only): with full probes the
        filtered result is exact, and no crossover event fires."""
        ix = ivf_flat.build(dataset, ivf_flat.IndexParams(n_lists=16,
                                                          seed=0))
        ivf_flat.prepare_host_stream(ix, budget_gb=80e3 / (1 << 30),
                                     chunk_mb=0.1)
        assert ix._host_tier is not None
        mask = make_mask(N, 1500)
        bs = Bitset.from_mask(jnp.asarray(mask))
        before = len(events.recent(kind="filter_crossover"))
        d, i = ivf_flat.search(ix, queries, K,
                               ivf_flat.SearchParams(n_probes=16), filter=bs)
        assert len(events.recent(kind="filter_crossover")) == before
        _, want_i = filtered_ref(dataset, queries, K, mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)


class TestQCacheFilterKey:
    def test_params_sig_large_bitsets_never_collide(self):
        """Regression: jax array reprs truncate with '...', so two large
        bitsets differing only in the middle used to sign identically —
        a different filter could hit another filter's cached answer."""
        from raft_tpu.serve.tenancy import _params_sig

        mask = make_mask(100_000, 50_000, seed=1)
        mask2 = mask.copy()
        mask2[50_000] = not mask2[50_000]
        a = Bitset.from_mask(jnp.asarray(mask))
        b = Bitset.from_mask(jnp.asarray(mask2))
        same = Bitset.from_mask(jnp.asarray(mask.copy()))
        assert _params_sig(None, {"filter": a}) != \
            _params_sig(None, {"filter": b})
        assert _params_sig(None, {"filter": a}) == \
            _params_sig(None, {"filter": same})

    @pytest.mark.serve
    def test_fabric_filter_swap_never_serves_stale_hit(self, dataset):
        """End to end: a cached unfiltered answer must never be served
        after the tenant swaps in a filtered searcher."""
        from raft_tpu.serve import metrics
        from raft_tpu.serve.batcher import BucketLadder
        from raft_tpu.serve.qcache import QueryCache
        from raft_tpu.serve.tenancy import ServeFabric

        ix = brute_force.build(dataset)
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        fab = ServeFabric(D, ladder=BucketLadder((4,), (K,)),
                          autostart=False, registry=metrics.Registry(),
                          cache=QueryCache(capacity=8,
                                           registry=metrics.Registry()))
        q = dataset[:1].copy()
        fab.add_tenant("a", index=ix)
        r1 = fab.submit("a", q, K)
        fab.drain_once()
        out1 = r1.result(5.0)
        assert fab.submit("a", q, K).done(), "warm unfiltered hit expected"
        fab.tenant("a").swap(new_index=ix, warm=False, filter=bs)
        r2 = fab.submit("a", q, K)
        assert not r2.done(), "filtered tenant must not hit the stale entry"
        fab.drain_once()
        out2 = r2.result(5.0)
        assert_in_survivors(out2.indices, mask)
        assert not np.array_equal(np.asarray(out1.indices),
                                  np.asarray(out2.indices))


@pytest.mark.multichip
class TestShardedFilter:
    @pytest.fixture(scope="class")
    def mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:4]), ("shard",))

    @pytest.mark.slow
    def test_sharded_ivf_flat_filtered_exact(self, mesh, dataset, queries):
        from raft_tpu.parallel import sharded_ann

        ix = sharded_ann.build_ivf_flat(
            dataset, mesh, ivf_flat.IndexParams(n_lists=16, seed=0))
        mask = make_mask(N, 1500)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = sharded_ann.search_ivf_flat(
            ix, queries, k=K, params=ivf_flat.SearchParams(n_probes=16),
            filter=bs)
        _, want_i = filtered_ref(dataset, queries, K, mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)

    @pytest.mark.slow
    def test_sharded_ivf_pq_filtered(self, mesh, dataset, queries):
        from raft_tpu.parallel import sharded_ann

        ix = sharded_ann.build_ivf_pq(
            dataset, mesh, ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                              seed=0))
        mask = make_mask(N, 1500)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = sharded_ann.search_ivf_pq(
            ix, queries, k=K, params=ivf_pq.SearchParams(n_probes=16),
            filter=bs)
        assert_in_survivors(i, mask)
        _, want_i = filtered_ref(dataset, queries, K, mask)
        assert calc_recall(np.asarray(i), want_i) >= 0.8

    @pytest.mark.slow
    def test_sharded_cagra_filtered(self, mesh, dataset, queries):
        from raft_tpu.parallel import sharded_ann

        ix = sharded_ann.build_cagra(
            dataset, mesh, cagra.IndexParams(intermediate_graph_degree=32,
                                             graph_degree=16, seed=0))
        mask = make_mask(N, 1500)
        bs = Bitset.from_mask(jnp.asarray(mask))
        d, i = sharded_ann.search_cagra(
            ix, queries, k=K, params=cagra.SearchParams(itopk_size=64),
            filter=bs)
        assert_in_survivors(i, mask)
        _, want_i = filtered_ref(dataset, queries, K, mask)
        assert calc_recall(np.asarray(i), want_i) >= 0.8


class TestSearcherFlow:
    def test_make_searcher_filter_flows(self, flat_index, dataset, queries):
        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        fn = ivf_flat.make_searcher(flat_index,
                                    ivf_flat.SearchParams(n_probes=4),
                                    filter=bs)
        d, i = fn(queries[:4], K)
        _, want_i = filtered_ref(dataset, queries[:4], K, mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)

    @pytest.mark.serve
    def test_microbatcher_filtered(self, flat_index, dataset, queries):
        from raft_tpu.serve.batcher import BucketLadder, MicroBatcher

        mask = make_mask(N, 50)
        bs = Bitset.from_mask(jnp.asarray(mask))
        fn = ivf_flat.make_searcher(flat_index,
                                    ivf_flat.SearchParams(n_probes=4),
                                    filter=bs)
        with MicroBatcher(fn, D, ladder=BucketLadder((8,), (K,)),
                          max_wait_s=0.001) as b:
            out = b.submit(queries[:4], K).result(60)
        _, want_i = filtered_ref(dataset, queries[:4], K, mask)
        np.testing.assert_array_equal(np.asarray(out.indices), want_i)
