"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; per the project contract all
sharding/collective code is exercised on `--xla_force_host_platform_device_count=8`
CPU devices (the driver separately dry-run-compiles the multi-chip path).
Env vars must be set before jax is imported anywhere.
"""
import os

# NOTE: under the axon TPU tunnel the JAX_PLATFORMS *env var* is ignored;
# only the in-process config switch reliably selects CPU. XLA_FLAGS must
# still be set before jax initializes its backends.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# RAFT_TPU_TEST_LANE=1 keeps the real accelerator visible so `-m tpu`
# tests compile on device; the default lane pins everything to the
# 8-device virtual CPU mesh.
_TPU_LANE = os.environ.get("RAFT_TPU_TEST_LANE", "") == "1"
if not _TPU_LANE:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

if not _TPU_LANE:
    assert jax.device_count() == 8, "tests expect the 8-device virtual CPU mesh"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def res():
    from raft_tpu.core import Resources

    return Resources(seed=0)


@pytest.fixture(scope="session")
def multichip_mesh():
    """The CPU multi-device emulation lane (``multichip`` marker): an
    8-device mesh over the virtual CPU devices this conftest forces via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the driver's
    dryrun runs the same body in a subprocess with the same flag). Skips
    rather than fails when the interpreter was initialized without the
    flag, so ``multichip`` tests are runnable standalone too."""
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("multichip lane needs the 8-device virtual CPU mesh "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    return Mesh(np.array(devs[:8]), ("shard",))


# The CI box has ONE CPU core (nproc=1), so the smoke lane is a measured
# file subset, not parallelism:
#   python -m pytest -q -m "smoke and not slow"
# covers comms, matrix, distance, sharded brute-force, linalg/sparse,
# core, brute force and random/stats. Measured ~90-110 s serial on an
# idle box (per-file timings 2026-07-31) but 133-175 s under contention
# (judge 2026-08-01, rerun 2026-08-02): treat the promise as ~2-3 min,
# not <2. The full not-slow lane stays the depth lane (~13 min).
_SMOKE_FILES = {
    "test_comms.py", "test_matrix.py", "test_distance.py",
    "test_sharded_knn.py", "test_linalg_sparse_ops.py", "test_core.py",
    "test_brute_force.py", "test_random_stats.py",
}

# (file, test) pairs measured >=14 s on the 8-device CPU mesh (pytest
# --durations, 2026-07-31): excluded from the `not heavy` lane. Keyed by
# file because bare names collide (e.g. test_comms_injection exists fast
# in test_core.py and slow in test_sharded_ann.py).
_HEAVY = {
    ("test_sharded_ann.py", "test_uneven_rows_no_padding_leak"),
    ("test_ivf_pq.py", "test_per_cluster_codebooks"),
    ("test_sharded_ann.py", "test_comms_injection"),
    ("test_ops.py", "test_ivf_flat_pallas_matches_xla"),
    ("test_ivf_pq.py", "test_pq_build_from_batches"),
    ("test_ops.py", "test_ivf_pq_pallas_filter_excludes"),
    ("test_sharded_ann.py", "test_uneven_rows"),
    ("test_ops.py", "test_ivf_flat_pallas_filter_matches_xla"),
    ("test_ivf_pq.py", "test_int8_lut_mode"),
    ("test_ivf_pq.py", "test_non_divisible_dim_pads"),
    ("test_sharded_ann.py", "test_low_precision_storage"),
    ("test_sharded_ann.py", "test_recall_vs_single_shard"),
    ("test_ivf_flat.py", "test_uint8_byte_corpus"),
    ("test_sharded_ann.py", "test_recall_and_merge"),
    ("test_ivf_flat.py", "test_uint8_save_load"),
    ("test_ivf_flat.py", "test_k_larger_than_candidates"),
    ("test_ops.py", "test_ivf_flat_pallas_small_k_and_tail_lists"),
    ("test_ivf_flat.py", "test_build_from_batches_matches_bulk_recall"),
}


def pytest_collection_modifyitems(config, items):
    """Skip `tpu`-marked tests unless the TPU lane is active (and, in the
    TPU lane, skip everything else — collectives expect the CPU mesh);
    auto-mark the measured heavy tail for the smoke lane."""
    skip_tpu = pytest.mark.skip(reason="needs RAFT_TPU_TEST_LANE=1 + a TPU")
    skip_cpu = pytest.mark.skip(reason="TPU lane runs only -m tpu tests")
    on_tpu = _TPU_LANE and jax.default_backend() == "tpu"
    for item in items:
        fname = item.path.name
        if ((fname, item.originalname) in _HEAVY
                or (fname, item.name) in _HEAVY):
            item.add_marker(pytest.mark.heavy)
        if fname in _SMOKE_FILES:
            item.add_marker(pytest.mark.smoke)
        is_tpu_test = "tpu" in item.keywords
        if is_tpu_test and not on_tpu:
            item.add_marker(skip_tpu)
        elif not is_tpu_test and _TPU_LANE:
            item.add_marker(skip_cpu)
