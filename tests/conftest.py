"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; per the project contract all
sharding/collective code is exercised on `--xla_force_host_platform_device_count=8`
CPU devices (the driver separately dry-run-compiles the multi-chip path).
Env vars must be set before jax is imported anywhere.
"""
import os

# NOTE: under the axon TPU tunnel the JAX_PLATFORMS *env var* is ignored;
# only the in-process config switch reliably selects CPU. XLA_FLAGS must
# still be set before jax initializes its backends.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# RAFT_TPU_TEST_LANE=1 keeps the real accelerator visible so `-m tpu`
# tests compile on device; the default lane pins everything to the
# 8-device virtual CPU mesh.
_TPU_LANE = os.environ.get("RAFT_TPU_TEST_LANE", "") == "1"
if not _TPU_LANE:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

if not _TPU_LANE:
    assert jax.device_count() == 8, "tests expect the 8-device virtual CPU mesh"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def res():
    from raft_tpu.core import Resources

    return Resources(seed=0)


def pytest_collection_modifyitems(config, items):
    """Skip `tpu`-marked tests unless the TPU lane is active (and, in the
    TPU lane, skip everything else — collectives expect the CPU mesh)."""
    skip_tpu = pytest.mark.skip(reason="needs RAFT_TPU_TEST_LANE=1 + a TPU")
    skip_cpu = pytest.mark.skip(reason="TPU lane runs only -m tpu tests")
    on_tpu = _TPU_LANE and jax.default_backend() == "tpu"
    for item in items:
        is_tpu_test = "tpu" in item.keywords
        if is_tpu_test and not on_tpu:
            item.add_marker(skip_tpu)
        elif not is_tpu_test and _TPU_LANE:
            item.add_marker(skip_cpu)
