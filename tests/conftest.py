"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; per the project contract all
sharding/collective code is exercised on `--xla_force_host_platform_device_count=8`
CPU devices (the driver separately dry-run-compiles the multi-chip path).
Env vars must be set before jax is imported anywhere.
"""
import os

# NOTE: under the axon TPU tunnel the JAX_PLATFORMS *env var* is ignored;
# only the in-process config switch reliably selects CPU. XLA_FLAGS must
# still be set before jax initializes its backends.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

assert jax.device_count() == 8, "tests expect the 8-device virtual CPU mesh"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def res():
    from raft_tpu.core import Resources

    return Resources(seed=0)
