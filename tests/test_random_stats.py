"""Tests for raft_tpu.random and raft_tpu.stats (oracle: numpy/scipy/sklearn
formulas computed directly)."""
import numpy as np
import pytest

from raft_tpu import random as rrnd
from raft_tpu import stats


class TestRng:
    def test_rng_state_streams(self):
        a = rrnd.uniform(rrnd.RngState(1), (100,))
        b = rrnd.uniform(rrnd.RngState(1), (100,))
        c = rrnd.uniform(rrnd.RngState(1, stream=7), (100,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_rng_state_advances(self):
        st = rrnd.RngState(0)
        a, b = rrnd.uniform(st, (50,)), rrnd.uniform(st, (50,))
        assert not np.allclose(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("fn,kw,mean,std", [
        (rrnd.uniform, {}, 0.5, 0.2887),
        (rrnd.normal, dict(mu=2.0, sigma=3.0), 2.0, 3.0),
        (rrnd.exponential, dict(lam=2.0), 0.5, 0.5),
        (rrnd.laplace, dict(mu=1.0, scale=0.5), 1.0, 0.7071),
        (rrnd.rayleigh, dict(sigma=1.0), 1.2533, 0.6551),
    ])
    def test_distribution_moments(self, fn, kw, mean, std):
        x = np.asarray(fn(rrnd.RngState(3), (20000,), **kw))
        assert abs(x.mean() - mean) < 0.05 * max(1.0, abs(mean)) + 0.02
        assert abs(x.std() - std) < 0.06

    def test_bernoulli_and_scaled(self):
        st = rrnd.RngState(5)
        b = np.asarray(rrnd.bernoulli(st, (10000,), prob=0.3))
        assert abs(b.mean() - 0.3) < 0.02
        s = np.asarray(rrnd.scaled_bernoulli(st, (1000,), prob=0.5, scale=2.0))
        assert set(np.unique(s)) == {-2.0, 2.0}

    def test_sample_without_replacement(self):
        idx = np.asarray(rrnd.sample_without_replacement(
            rrnd.RngState(0), 50, n_population=100))
        assert len(np.unique(idx)) == 50
        assert idx.min() >= 0 and idx.max() < 100

    def test_sample_without_replacement_weighted(self):
        w = np.zeros(100); w[:60] = 1.0
        idx = np.asarray(rrnd.sample_without_replacement(
            rrnd.RngState(0), 50, n_population=100, weights=w))
        assert len(np.unique(idx)) == 50 and idx.max() < 60

    def test_permute(self):
        p = np.asarray(rrnd.permute(rrnd.RngState(0), 64))
        assert sorted(p.tolist()) == list(range(64))

    def test_discrete(self):
        d = np.asarray(rrnd.discrete(rrnd.RngState(1), (5000,),
                                     [0.1, 0.0, 0.9]))
        assert set(np.unique(d)) <= {0, 2}
        assert abs((d == 2).mean() - 0.9) < 0.03


class TestDatagen:
    def test_make_blobs_separable(self):
        x, y = rrnd.make_blobs(600, 8, n_clusters=3, cluster_std=0.1,
                               rng=0)
        x, y = np.asarray(x), np.asarray(y)
        assert x.shape == (600, 8) and set(np.unique(y)) == {0, 1, 2}
        # cluster members are tight around their mean vs global spread
        for c in range(3):
            assert x[y == c].std(0).mean() < 0.15
        assert x.std(0).mean() > 1.0

    def test_make_blobs_given_centers(self):
        centers = np.array([[0.0, 0.0], [100.0, 100.0]], np.float32)
        x, y = rrnd.make_blobs(100, 2, centers=centers, cluster_std=0.5,
                               shuffle=False, rng=1)
        x, y = np.asarray(x), np.asarray(y)
        np.testing.assert_allclose(x[y == 1].mean(0), [100, 100], atol=0.5)

    def test_make_regression_recoverable(self):
        x, y, coef = rrnd.make_regression(500, 10, noise=0.0, rng=2)
        x, y, coef = np.asarray(x), np.asarray(y), np.asarray(coef)
        est, *_ = np.linalg.lstsq(x, y, rcond=None)
        np.testing.assert_allclose(est, coef, atol=1e-2)

    def test_rmat_shapes_and_skew(self):
        theta = np.array([0.57, 0.19, 0.19, 0.05], np.float32)
        src, dst = rrnd.rmat_rectangular_generator(
            rrnd.RngState(0), theta, r_scale=8, c_scale=8, n_edges=20000)
        src, dst = np.asarray(src), np.asarray(dst)
        assert src.min() >= 0 and src.max() < 256
        assert dst.min() >= 0 and dst.max() < 256
        # power-law-ish: low-id nodes (quadrant a attractor) dominate
        assert (src < 128).mean() > 0.6


class TestBasicStats:
    def test_meanvar_cov(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((200, 5)).astype(np.float32)
        mu, var = stats.meanvar(x)
        np.testing.assert_allclose(np.asarray(mu), x.mean(0), atol=1e-5)
        np.testing.assert_allclose(np.asarray(var), x.var(0, ddof=1),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(stats.cov(x)),
                                   np.cov(x.T), rtol=1e-3, atol=1e-4)

    def test_histogram(self):
        x = np.array([0.0, 0.1, 0.5, 0.9, 1.0], np.float32)
        counts, edges = stats.histogram(x, 2, lo=0.0, hi=1.0)
        np.testing.assert_array_equal(np.asarray(counts), [2, 3])

    def test_weighted_mean(self):
        x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        w = np.array([1.0, 3.0], np.float32)
        np.testing.assert_allclose(np.asarray(stats.weighted_mean(x, w)),
                                   [2.5, 3.5])

    def test_minmax(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        lo, hi = stats.minmax(x)
        np.testing.assert_array_equal(np.asarray(lo), x.min(0))
        np.testing.assert_array_equal(np.asarray(hi), x.max(0))


class TestMetrics:
    def test_accuracy_r2(self):
        assert float(stats.accuracy([1, 2, 3, 4], [1, 2, 0, 4])) == 0.75
        y = np.array([1.0, 2.0, 3.0]); yh = np.array([1.1, 1.9, 3.2])
        from sklearn.metrics import r2_score as sk_r2
        np.testing.assert_allclose(float(stats.r2_score(y, yh)),
                                   sk_r2(y, yh), rtol=1e-5)

    def test_cluster_metrics_vs_sklearn(self):
        from sklearn import metrics as skm
        rng = np.random.default_rng(0)
        a = rng.integers(0, 4, 300)
        b = (a + (rng.random(300) < 0.2).astype(int)) % 4
        np.testing.assert_allclose(float(stats.adjusted_rand_index(a, b, 4)),
                                   skm.adjusted_rand_score(a, b), atol=1e-5)
        np.testing.assert_allclose(float(stats.mutual_info_score(a, b, 4)),
                                   skm.mutual_info_score(a, b), atol=1e-5)
        np.testing.assert_allclose(float(stats.homogeneity_score(a, b, 4)),
                                   skm.homogeneity_score(a, b), atol=1e-4)
        np.testing.assert_allclose(float(stats.completeness_score(a, b, 4)),
                                   skm.completeness_score(a, b), atol=1e-4)
        np.testing.assert_allclose(float(stats.v_measure(a, b, 4)),
                                   skm.v_measure_score(a, b), atol=1e-4)

    def test_rand_index(self):
        a = np.array([0, 0, 1, 1]); b = np.array([0, 0, 1, 2])
        # pairs: (0,1) agree, (2,3) split ref... compute directly
        from sklearn.metrics import rand_score
        np.testing.assert_allclose(float(stats.rand_index(a, b)),
                                   rand_score(a, b), atol=1e-6)

    def test_entropy_kl(self):
        labels = np.array([0, 0, 1, 1])
        np.testing.assert_allclose(float(stats.entropy(labels, 2)),
                                   np.log(2), rtol=1e-5)
        p = np.array([0.5, 0.5]); q = np.array([0.9, 0.1])
        ref = (0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1))
        np.testing.assert_allclose(float(stats.kl_divergence(p, q)), ref,
                                   rtol=1e-5)

    def test_silhouette_vs_sklearn(self):
        from sklearn.metrics import silhouette_score as sk_sil
        from raft_tpu import random as rrnd2
        x, y = rrnd2.make_blobs(120, 4, n_clusters=3, cluster_std=0.5, rng=5)
        x, y = np.asarray(x), np.asarray(y)
        ours = float(stats.silhouette_score(x, y, 3, metric="euclidean"))
        ref = sk_sil(x, y, metric="euclidean")
        np.testing.assert_allclose(ours, ref, atol=1e-3)

    def test_trustworthiness_identity(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((60, 5)).astype(np.float32)
        t = float(stats.trustworthiness(x, x, n_neighbors=5))
        np.testing.assert_allclose(t, 1.0, atol=1e-6)
        from sklearn.manifold import trustworthiness as sk_tw
        e = x[:, :2]
        np.testing.assert_allclose(
            float(stats.trustworthiness(x, e, n_neighbors=5)),
            sk_tw(x, e, n_neighbors=5), atol=1e-3)

    def test_neighborhood_recall(self):
        idx = np.array([[0, 1, 2], [3, 4, 5]])
        ref = np.array([[2, 1, 9], [3, 4, 5]])
        np.testing.assert_allclose(
            float(stats.neighborhood_recall(idx, ref)), 5 / 6, rtol=1e-6)

    def test_neighborhood_recall_distance_ties(self):
        idx = np.array([[0, 7]]); ref = np.array([[0, 1]])
        d = np.array([[1.0, 2.0]]); rd = np.array([[1.0, 2.0]])
        # id 7 != 1 but distance ties at 2.0 → counts
        np.testing.assert_allclose(
            float(stats.neighborhood_recall(idx, ref, d, rd)), 1.0)

    def test_information_criterion(self):
        ll = np.float32(-100.0)
        assert float(stats.information_criterion(ll, 3, 50, "aic")) == \
            pytest.approx(206.0)
        assert float(stats.information_criterion(ll, 3, 50, "bic")) == \
            pytest.approx(3 * np.log(50) + 200.0, rel=1e-6)
