"""Fleet storage-tier tests (ISSUE 19, docs/mnmg.md "Per-host storage
tiers"): the per-host HBM-budget ladder threaded through the fleet
layer — quant-ladder rung builds (``Fleet.build_ivf_pq(store_dtype=,
hbm_budget_gb=)``), fleet-wide hot/cold planning, host-streamed cold
lists, and the budget-brownout tier controller.

The acceptance pins, in test form:

* exact rungs (float32/int8/int4) built under a budget are BITWISE
  equal to the unbudgeted resident build — same probed lists, same
  per-candidate dot products, batch composition cancels out;
* the pq rung under a budget holds >= 0.95x its unbudgeted recall;
* a host measured over budget steps DOWN the ladder (more lists
  streamed) with zero extra compiles and steps back on sustained
  headroom, flight-recording ``fleet_tier_step`` both ways;
* a dead host's cold tier streams nothing and leaks no rows.

Cheap planner/row-math/controller slices run in tier-1; the
compile-heavy build+search arcs are ``slow`` (the same split as
test_fleet.py)."""
import json
import warnings

import numpy as np
import pytest

from raft_tpu.core import events
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import host_stream as hs
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.parallel import Fleet, Topology
from raft_tpu.parallel import fleet as fleet_mod
from raft_tpu.parallel import topology as topo_mod


# ---------------------------------------------------------------------
# tier-1-lean: row-byte math, env parsing, planner, controller wiring
# ---------------------------------------------------------------------

class TestStoreRowBytes:
    def test_ladder_values_at_docs_dim(self):
        """The numbers docs/mnmg.md budgets with, pinned: dim=96."""
        f = fleet_mod.store_row_bytes
        assert f("float32", 96) == 392
        assert f("int8", 96) == 108
        assert f("int4", 96) == 76
        assert f("pq", 96, pq_dim=48) == 60
        vals = [f("float32", 96), f("int8", 96), f("int4", 96),
                f("pq", 96, pq_dim=48)]
        assert vals == sorted(vals, reverse=True), \
            "ladder must be byte-monotone at the docs dim"

    def test_int4_sublane_padding_inverts_small_dims(self):
        """Below dim 64 the int4 rung's 64-byte sublane-pair padding
        dominates — the planner must budget with the REAL packed width,
        not dim/2 (this is why the bench lane runs at d >= 64)."""
        assert fleet_mod.store_row_bytes("int4", 32) == 76
        assert fleet_mod.store_row_bytes("int8", 32) == 44

    def test_pq_needs_pq_dim(self):
        with pytest.raises(RaftError):
            fleet_mod.store_row_bytes("pq", 96)

    def test_unknown_rung(self):
        with pytest.raises(ValueError):
            fleet_mod.store_row_bytes("bf16", 96)


class TestBudgetBytesEnv:
    def test_malformed_env_warns_and_disables(self, monkeypatch):
        """The operator-knob contract: a typo'd budget is a LOUD no-op
        (RuntimeWarning + budget 0), never a crash and never a silently
        armed tier."""
        monkeypatch.setenv("RAFT_TPU_HBM_BUDGET_GB", "2GB")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert hs.budget_bytes() == 0
        assert any("malformed RAFT_TPU_HBM_BUDGET_GB" in str(x.message)
                   for x in w), [str(x.message) for x in w]

    def test_unset_env_is_silent_zero(self, monkeypatch):
        monkeypatch.delenv("RAFT_TPU_HBM_BUDGET_GB", raising=False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert hs.budget_bytes() == 0
        assert not w

    def test_armed_event_once_per_value(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_HBM_BUDGET_GB", "0.125")
        hs._armed_seen.discard(int(0.125 * (1 << 30)))
        n0 = len(events.recent(kind="host_tier_armed"))
        b = hs.budget_bytes()
        assert b == int(0.125 * (1 << 30))
        armed = events.recent(kind="host_tier_armed")
        assert len(armed) == n0 + 1
        assert armed[-1]["budget_bytes"] == b
        assert armed[-1]["source"] == "env"
        hs.budget_bytes()              # same value: no second event
        assert len(events.recent(kind="host_tier_armed")) == n0 + 1

    def test_event_kinds_registered(self):
        assert {"host_tier_armed", "fleet_tier_step"} <= \
            events.WELL_KNOWN_KINDS


class TestPlanMergeStorage:
    def test_storage_block_budget_split(self):
        """plan_merge's storage block must use the same row-byte math as
        the planner, so docs/bench/planner can't drift apart."""
        rb = fleet_mod.store_row_bytes("int8", 96)
        plan = topo_mod.plan_merge(Topology(2, 4), m=128, k=10,
                                   n_rows=1000, row_bytes=rb,
                                   hbm_budget_gb=30_000 / (1 << 30))
        st = plan["storage"]
        assert st["rows_per_host"] == 500
        assert st["bytes_per_host"] == 500 * rb
        assert st["hbm_budget_bytes_per_host"] == 30_000
        assert st["resident_bytes_per_host"] == 30_000
        assert st["host_stream_bytes_per_host"] == 500 * rb - 30_000
        assert st["fits_resident"] is False
        json.dumps(plan, allow_nan=False)

    def test_storage_block_fits(self):
        plan = topo_mod.plan_merge(Topology(2, 2), m=16, k=4,
                                   n_rows=100, row_bytes=108.0,
                                   hbm_budget_gb=1.0)
        st = plan["storage"]
        assert st["fits_resident"] is True
        assert st["host_stream_bytes_per_host"] == 0

    def test_no_storage_without_shape(self):
        assert "storage" not in topo_mod.plan_merge(Topology(2, 2),
                                                    m=16, k=4)


class TestPlanHotCold:
    def test_probe_weighted_admission(self):
        sizes = np.array([100, 100, 100, 0])
        freq = np.array([1, 50, 10, 0])
        hot = hs.plan_hot_cold(sizes, 10.0, 2100, freq)
        # budget fits two non-empty lists: the hottest two win, the
        # empty list is free to keep
        assert hot.tolist() == [False, True, True, True]

    def test_size_prior_without_sample(self):
        sizes = np.array([10, 1000, 10])
        hot = hs.plan_hot_cold(sizes, 1.0, 25)
        # uniform-traffic prior ~ list size; equal density ->
        # stable-order admission until the budget is spent
        assert hot.sum() >= 1 and not hot[1]


class TestBrownoutMemoryAxis:
    def test_memory_breach_urgent_and_outranks_recall(self):
        from raft_tpu.serve.degrade import BrownoutController

        t = [0.0]
        ctl = BrownoutController([{}, {}], min_dwell_s=100.0,
                                 up_after_s=5.0, name="t.mem",
                                 clock=lambda: t[0])
        rep = {"targets": {
            "device_bytes": {"verdict": "breach"},
            "recall": {"verdict": "breach", "samples": 10}}}
        # memory skips the dwell AND outranks the recall floor: a floor
        # defended into an OOM serves nothing
        assert ctl.on_report(rep) == 1
        assert ctl.on_report(rep) == 2
        assert ctl.on_report(rep) == 2          # ladder top
        # sustained green steps back (min_dwell does not gate recovery
        # once up_after has accrued)
        ok = {"targets": {"device_bytes": {"verdict": "ok"}}}
        t[0] = 200.0
        ctl.on_report(ok)
        t[0] = 206.0
        assert ctl.on_report(ok) == 1


class TestBuildValidation:
    def test_invalid_store_dtype(self):
        fl = Fleet.virtual(1, 1)
        with pytest.raises(RaftError):
            fl.build_ivf_pq(np.zeros((64, 8), np.float32),
                            ivf_pq.IndexParams(n_lists=4),
                            store_dtype="bf16")

    def test_tier_controller_requires_budget(self):
        class Bare:
            pass

        fl = Fleet.virtual(1, 1)
        with pytest.raises(RaftError):
            fleet_mod.FleetTierController(fl, Bare())


# ---------------------------------------------------------------------
# slow: the build+search acceptance arcs on the virtual 2x2 fleet
# ---------------------------------------------------------------------

def _gt(base, q, k):
    d2 = ((q[:, None, :] - base[None, :, :]) ** 2).sum(-1)
    return np.argsort(d2, axis=1, kind="stable")[:, :k]


def _recall(found, want):
    k = want.shape[1]
    return float(np.mean([len(set(found[m].tolist())
                              & set(want[m].tolist())) / k
                          for m in range(len(want))]))


def _cold_counts(idx):
    return {h: int((~np.asarray(m)).sum())
            for h, m in idx._fleet_ctx["hot"].items()}


@pytest.mark.multichip
@pytest.mark.slow
class TestBudgetedBuildArc:
    N, DIM, M, K = 2048, 16, 32, 10

    def _corpus(self, rng, dim=None):
        base = rng.standard_normal((self.N, dim or self.DIM))
        base = base.astype(np.float32)
        q = rng.standard_normal((self.M, dim or self.DIM))
        return base, q.astype(np.float32)

    @pytest.mark.parametrize("rung", ["float32", "int8", "int4"])
    def test_exact_rung_bitwise_parity(self, multichip_mesh, rng, rung):
        """The headline pin: a budgeted exact-rung build must return
        results BITWISE equal to the unbudgeted resident build — cold
        lists go through the same probe selection and the same
        highest-precision dot products, so where a row is stored cannot
        change an answer."""
        fl = Fleet.virtual(2, 2)
        base, q = self._corpus(rng)
        p0 = ivf_pq.IndexParams(n_lists=8, seed=0)
        sp = ivf_flat.SearchParams(n_probes=4)
        idx0 = fl.build_ivf_pq(base, p0, store_dtype=rung)
        d0, i0, ok0 = fl.search(idx0, q, self.K, sp)
        idx1 = fl.build_ivf_pq(base, p0, store_dtype=rung,
                               hbm_budget_gb=20e3 / (1 << 30),
                               sample_queries=q)
        cold = _cold_counts(idx1)
        assert sum(cold.values()) > 0, \
            f"budget never spilled ({cold}) — the parity claim is vacuous"
        d1, i1, ok1 = fl.search(idx1, q, self.K, sp)
        assert list(ok0) == list(ok1) == [True] * 4
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_flat_bit_identity_multi_chunk(self, multichip_mesh, rng):
        """Parity must survive the chunk boundary: a chunk_mb small
        enough to cut every host's cold tier into several streamed
        chunks still merges to the identical result."""
        fl = Fleet.virtual(2, 2)
        base, q = self._corpus(rng)
        p0 = ivf_pq.IndexParams(n_lists=8, seed=0)
        sp = ivf_flat.SearchParams(n_probes=6)
        idx0 = fl.build_ivf_pq(base, p0, store_dtype="int8")
        d0, i0, _ = fl.search(idx0, q, self.K, sp)
        idx1 = fl.build_ivf_pq(base, p0, store_dtype="int8",
                               hbm_budget_gb=12e3 / (1 << 30),
                               sample_queries=q, chunk_mb=0.005)
        assert any(len(t.chunks) > 1
                   for t in idx1._fleet_tiers.values()), \
            "chunk_mb did not force a multi-chunk tier"
        d1, i1, _ = fl.search(idx1, q, self.K, sp)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_pq_rung_recall_and_bytes(self, multichip_mesh, rng):
        """The pq rung's acceptance: budgeted recall >= 0.95x the
        unbudgeted build, and the budgeted resident set respects the
        per-host budget (+ tolerance for the shared quantizer, which is
        outside the row budget)."""
        from raft_tpu.serve import quality

        fl = Fleet.virtual(2, 2)
        base, q = self._corpus(rng, dim=32)
        p0 = ivf_pq.IndexParams(n_lists=8, pq_dim=16, seed=0)
        sp = ivf_pq.SearchParams(n_probes=6)
        idx0 = fl.build_ivf_pq(base, p0, store_dtype="pq")
        _, i0, _ = fl.search(idx0, q, self.K, sp)
        budget_b = 3000
        idx1 = fl.build_ivf_pq(base, p0, store_dtype="pq",
                               hbm_budget_gb=budget_b / (1 << 30),
                               sample_queries=q)
        assert sum(_cold_counts(idx1).values()) > 0
        _, i1, _ = fl.search(idx1, q, self.K, sp)
        gt = _gt(base, q, self.K)
        r0, r1 = _recall(np.asarray(i0), gt), _recall(np.asarray(i1), gt)
        assert r1 >= 0.95 * r0, (r1, r0)
        # resident codes shrank to ~the budget: budgeted list-data bytes
        # are the unbudgeted build's minus what the tier parked on host
        rep0 = quality.device_bytes(idx0)["components"]["dataset"]
        rep1 = quality.device_bytes(idx1)["components"]["dataset"]
        saved = sum(t.device_bytes_saved
                    for t in idx1._fleet_tiers.values())
        assert rep1 < rep0 and saved > 0
        json.dumps(fl.host_memz(), allow_nan=False)

    def test_host_loss_cold_interaction(self, multichip_mesh, rng):
        """A dead host's shards drop out of BOTH paths: its resident
        results vanish and its cold tier streams nothing (no wasted
        host->device uploads for shards whose results are discarded),
        and no dead-host row leaks into the merged ids. Restore brings
        the rows back."""
        fl = Fleet.virtual(2, 2)
        base, q = self._corpus(rng)
        p0 = ivf_pq.IndexParams(n_lists=8, seed=0)
        sp = ivf_flat.SearchParams(n_probes=6)
        idx = fl.build_ivf_pq(base, p0, store_dtype="int8",
                              hbm_budget_gb=20e3 / (1 << 30),
                              sample_queries=q)
        assert sum(_cold_counts(idx).values()) > 0
        _, i_all, _ = fl.search(idx, q, self.K, sp)

        fl.mark_host_failed(1)
        for t in idx._fleet_tiers.values():
            t.streamed_chunks = 0
        try:
            _, ii, ok = fl.search(idx, q, self.K, sp)
            assert list(ok) == [True, True, False, False]
            # host 1 owns the upper half of the row split
            dead = {s for s in idx._fleet_tiers
                    if fl.topology.host_of(s) == 1}
            assert all(idx._fleet_tiers[s].streamed_chunks == 0
                       for s in dead)
            live_streams = sum(idx._fleet_tiers[s].streamed_chunks
                               for s in idx._fleet_tiers if s not in dead)
            assert live_streams > 0
            ids = np.asarray(ii).ravel()
            assert not ((ids >= self.N // 2) & (ids >= 0)).any(), \
                "dead host's rows leaked through the cold merge"
        finally:
            fl.mark_host_failed(1, ok=True)
        _, i_back, _ = fl.search(idx, q, self.K, sp)
        np.testing.assert_array_equal(np.asarray(i_back),
                                      np.asarray(i_all))

    def test_budget_keeps_health_green(self, multichip_mesh, rng):
        """Cold rows are SERVED (streamed), not lost: budgeting must not
        read as missing corpus and trip the auto-widen (served_frac
        stays 1.0, effective n_probes untouched)."""
        fl = Fleet.virtual(2, 2)
        base, q = self._corpus(rng)
        idx = fl.build_ivf_pq(base, ivf_pq.IndexParams(n_lists=8, seed=0),
                              store_dtype="int8",
                              hbm_budget_gb=20e3 / (1 << 30),
                              sample_queries=q)
        assert sum(_cold_counts(idx).values()) > 0
        assert fl.host_health()["served_frac"] == 1.0


@pytest.mark.multichip
@pytest.mark.slow
class TestTierStepDrill:
    def test_over_budget_steps_down_and_recovers(self, multichip_mesh,
                                                 rng):
        """The brownout drill: a host measured over budget steps DOWN
        the ladder (results still bitwise-stable, zero new compiles —
        every re-tier lands in the already-compiled shapes), sustained
        headroom steps it back, and both transitions flight-record
        ``fleet_tier_step``."""
        from raft_tpu.serve.warmup import count_compilations

        fl = Fleet.virtual(2, 2)
        base = rng.standard_normal((2048, 16)).astype(np.float32)
        q = rng.standard_normal((32, 16)).astype(np.float32)
        sp = ivf_flat.SearchParams(n_probes=6)
        budget_b = 40_000            # full int8 residency fits: no cold
        idx = fl.build_ivf_pq(base, ivf_pq.IndexParams(n_lists=8, seed=0),
                              store_dtype="int8",
                              hbm_budget_gb=budget_b / (1 << 30),
                              sample_queries=q)
        assert sum(_cold_counts(idx).values()) == 0
        d0, i0, _ = fl.search(idx, q, 10, sp)

        t = [0.0]
        ctl = fleet_mod.FleetTierController(fl, idx, levels=3,
                                            min_dwell_s=0.0,
                                            up_after_s=30.0,
                                            clock=lambda: t[0])
        n_steps0 = len(events.recent(kind="fleet_tier_step"))

        # host 0 measured at 2x budget -> down one level; host 1 green
        out = ctl.observe({0: budget_b * 2, 1: budget_b // 2})
        assert out[0]["level"] == 1 and out[1]["level"] == 0
        assert _cold_counts(idx)[0] > 0 and _cold_counts(idx)[1] == 0

        # the step must not invent new programs: warm the stepped state,
        # then a steady-state search compiles nothing beyond the
        # per-call baseline measured on the SAME warmed state
        d1, i1, _ = fl.search(idx, q, 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        with count_compilations() as base_c:
            fl.search(idx, q, 10, sp)
        t[0] = 1.0
        # green report: holds level 1 (recovery needs 30s sustained
        # green) without stepping further down — a repeated breach
        # report would, one urgent step per observation
        out = ctl.observe({0: budget_b // 2, 1: budget_b // 2})
        assert out[0]["level"] == 1
        with count_compilations() as post_c:
            fl.search(idx, q, 10, sp)
        assert post_c.count <= base_c.count, (post_c.count, base_c.count)

        ev = events.recent(kind="fleet_tier_step")[n_steps0:]
        assert [(e["host"], e["level_from"], e["level_to"],
                 e["direction"], e["reason"]) for e in ev] == \
            [(0, 0, 1, "down", "memory")]

        # sustained headroom: green observations past up_after_s
        for tt in (10.0, 31.0, 62.0):
            t[0] = tt
            out = ctl.observe({0: budget_b // 2, 1: budget_b // 2})
        assert out[0]["level"] == 0
        assert _cold_counts(idx)[0] == 0
        d2, i2, _ = fl.search(idx, q, 10, sp)
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d2), np.asarray(d0))
        ev = events.recent(kind="fleet_tier_step")[n_steps0:]
        assert ev[-1]["direction"] == "up"
        assert ev[-1]["reason"] == "headroom"
        json.dumps(ctl.snapshot(), allow_nan=False)

    def test_debugz_fleet_hosts_section(self, multichip_mesh, rng):
        """ops surface: the fleet section carries per-host memory rows
        (device bytes, tier bytes, bytes/vector), strict-JSON, and the
        text rendering includes them."""
        from raft_tpu.serve import debugz

        fl = Fleet.virtual(2, 2)
        base = rng.standard_normal((1024, 16)).astype(np.float32)
        idx = fl.build_ivf_pq(base, ivf_pq.IndexParams(n_lists=8, seed=0),
                              store_dtype="int8",
                              hbm_budget_gb=10e3 / (1 << 30))
        assert sum(_cold_counts(idx).values()) > 0
        snap = debugz.snapshot()
        ent = next(e for e in snap["fleet"]
                   if e["topology"] == "2x2" and e.get("hosts"))
        hosts = ent["hosts"]
        assert [h["host"] for h in hosts] == [0, 1]
        assert all(h["device_bytes"] > 0 for h in hosts)
        assert sum(h["host_tier_bytes"] for h in hosts) > 0
        assert all(h["bytes_per_vector"] > 0 for h in hosts)
        json.dumps(snap, allow_nan=False)
        txt = debugz.render_text()
        assert "host_tier_bytes" in txt or "tier_bytes" in txt
